"""Statistics + manipulations split-sweep tests (reference:
test_statistics.py, test_manipulations.py)."""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    return rng.standard_normal((6, 10)).astype(np.float32)


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_argminmax_var_std(data, split, axis):
    a = ht.array(data, split=split)
    np.testing.assert_array_equal(ht.argmax(a, axis=axis).numpy(), data.argmax(axis=axis))
    np.testing.assert_array_equal(ht.argmin(a, axis=axis).numpy(), data.argmin(axis=axis))
    np.testing.assert_allclose(ht.var(a, axis=axis).numpy(), data.var(axis=axis), rtol=1e-4)
    np.testing.assert_allclose(ht.std(a, axis=axis).numpy(), data.std(axis=axis), rtol=1e-4)
    np.testing.assert_allclose(
        ht.var(a, axis=axis, ddof=1).numpy(), data.var(axis=axis, ddof=1), rtol=1e-4
    )


@pytest.mark.parametrize("split", SPLITS)
def test_statistics_misc(data, split):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.median(a).numpy(), np.median(data), rtol=1e-5)
    np.testing.assert_allclose(
        ht.percentile(a, 30.0).numpy(), np.percentile(data, 30.0), rtol=1e-4
    )
    np.testing.assert_allclose(ht.average(a).numpy(), np.average(data), rtol=1e-5)
    w = np.arange(1.0, 11.0, dtype=np.float32)
    np.testing.assert_allclose(
        ht.average(a, axis=1, weights=ht.array(w)).numpy(), np.average(data, axis=1, weights=w), rtol=1e-5
    )
    np.testing.assert_allclose(ht.maximum(a, -a).numpy(), np.maximum(data, -data))
    np.testing.assert_allclose(ht.minimum(a, -a).numpy(), np.minimum(data, -data))


def test_bincount_digitize_histogram():
    x = np.array([0, 1, 1, 3, 2, 1, 7], dtype=np.int32)
    a = ht.array(x, split=0)
    np.testing.assert_array_equal(ht.bincount(a).numpy(), np.bincount(x))
    boundaries = np.array([1.0, 3.0, 5.0], dtype=np.float32)
    v = np.array([0.5, 1.0, 2.5, 4.0, 6.0], dtype=np.float32)
    b = ht.array(v, split=0)
    np.testing.assert_array_equal(
        ht.digitize(b, ht.array(boundaries)).numpy(), np.digitize(v, boundaries)
    )
    h, edges = ht.histogram(b, bins=4)
    h_np, e_np = np.histogram(v, bins=4)
    np.testing.assert_array_equal(h.numpy(), h_np)
    np.testing.assert_allclose(edges.numpy(), e_np, rtol=1e-6)


def test_skew_kurtosis_cov(data):
    from scipy import stats

    a = ht.array(data.ravel(), split=0)
    np.testing.assert_allclose(
        ht.skew(a, unbiased=False).numpy(), stats.skew(data.ravel(), bias=True), rtol=1e-3, atol=1e-4
    )
    np.testing.assert_allclose(
        ht.kurtosis(a, unbiased=False).numpy(),
        stats.kurtosis(data.ravel(), bias=True),
        rtol=1e-3,
        atol=1e-4,
    )
    c = ht.cov(ht.array(data, split=0))
    np.testing.assert_allclose(c.numpy(), np.cov(data), rtol=1e-4)


@pytest.mark.parametrize("split", SPLITS)
def test_concatenate_stack(data, split):
    a = ht.array(data, split=split)
    b = ht.array(data * 2, split=split)
    np.testing.assert_allclose(
        ht.concatenate([a, b], axis=0).numpy(), np.concatenate([data, data * 2], axis=0)
    )
    np.testing.assert_allclose(
        ht.concatenate([a, b], axis=1).numpy(), np.concatenate([data, data * 2], axis=1)
    )
    np.testing.assert_allclose(ht.stack([a, b]).numpy(), np.stack([data, data * 2]))
    np.testing.assert_allclose(ht.vstack([a, b]).numpy(), np.vstack([data, data * 2]))
    np.testing.assert_allclose(ht.hstack([a, b]).numpy(), np.hstack([data, data * 2]))


@pytest.mark.parametrize("split", SPLITS)
def test_reshape_flatten_squeeze(data, split):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.reshape(a, (10, 6)).numpy(), data.reshape(10, 6))
    np.testing.assert_allclose(ht.reshape(a, (-1,)).numpy(), data.reshape(-1))
    np.testing.assert_allclose(a.flatten().numpy(), data.flatten())
    b = ht.array(data[None], split=None)
    np.testing.assert_allclose(ht.squeeze(b, 0).numpy(), data)
    np.testing.assert_allclose(ht.expand_dims(a, 0).numpy(), data[None])


@pytest.mark.parametrize("split", SPLITS)
def test_flip_roll_rot90(data, split):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.flip(a, 0).numpy(), np.flip(data, 0))
    np.testing.assert_allclose(ht.fliplr(a).numpy(), np.fliplr(data))
    np.testing.assert_allclose(ht.roll(a, 3, axis=1).numpy(), np.roll(data, 3, axis=1))
    np.testing.assert_allclose(ht.roll(a, -2, axis=0).numpy(), np.roll(data, -2, axis=0))
    np.testing.assert_allclose(ht.rot90(a).numpy(), np.rot90(data))


@pytest.mark.parametrize("split", [None, 0])
def test_sort_unique_topk(split):
    rng = np.random.default_rng(3)
    x = rng.integers(0, 20, size=17).astype(np.int32)  # uneven over 8
    a = ht.array(x, split=split)
    v, i = ht.sort(a)
    np.testing.assert_array_equal(v.numpy(), np.sort(x))
    np.testing.assert_array_equal(i.numpy(), np.argsort(x, kind="stable"))
    u = ht.unique(a)
    np.testing.assert_array_equal(u.numpy(), np.unique(x))
    u2, inv = ht.unique(a, return_inverse=True)
    np.testing.assert_array_equal(u2.numpy()[inv.numpy()], x)
    tv, ti = ht.topk(a, 3)
    np.testing.assert_array_equal(tv.numpy(), np.sort(x)[-3:][::-1])


def test_pad_tile_repeat(data):
    a = ht.array(data, split=0)
    np.testing.assert_allclose(
        ht.pad(a, ((1, 1), (2, 0))).numpy(), np.pad(data, ((1, 1), (2, 0)))
    )
    np.testing.assert_allclose(ht.tile(a, (2, 1)).numpy(), np.tile(data, (2, 1)))
    np.testing.assert_allclose(ht.repeat(a, 2, axis=0).numpy(), np.repeat(data, 2, axis=0))


def test_split_funcs(data):
    a = ht.array(data, split=0)
    parts = ht.split(a, 2, axis=0)
    assert len(parts) == 2
    np.testing.assert_allclose(parts[0].numpy(), data[:3])
    h = ht.hsplit(a, 2)
    np.testing.assert_allclose(h[1].numpy(), data[:, 5:])
    v = ht.vsplit(a, 3)
    np.testing.assert_allclose(v[2].numpy(), data[4:])


def test_diag_unfold_nonzero():
    m = np.arange(16.0, dtype=np.float32).reshape(4, 4)
    a = ht.array(m, split=0)
    np.testing.assert_allclose(ht.diag(a).numpy(), np.diag(m))
    v = ht.array(np.arange(3.0, dtype=np.float32))
    np.testing.assert_allclose(ht.diag(v).numpy(), np.diag(np.arange(3.0)))
    x = np.array([0.0, 1.0, 0.0, 2.0], dtype=np.float32)
    nz = ht.nonzero(ht.array(x, split=0))
    np.testing.assert_array_equal(nz.numpy(), np.nonzero(x)[0])
    w = ht.where(ht.array(x, split=0) > 0, 1.0, -1.0)
    np.testing.assert_array_equal(w.numpy(), np.where(x > 0, 1.0, -1.0))


def test_unfold():
    x = np.arange(10.0, dtype=np.float32)
    a = ht.array(x, split=0)
    u = ht.unfold(a, 0, 3, 2)
    expected = np.stack([x[i : i + 3] for i in range(0, 8, 2)])
    np.testing.assert_allclose(u.numpy(), expected)


def test_broadcast_to_arrays(data):
    a = ht.array(data[0], split=0)
    b = ht.broadcast_to(a, (4, 10))
    np.testing.assert_allclose(b.numpy(), np.broadcast_to(data[0], (4, 10)))
    arrs = ht.broadcast_arrays(ht.array(data, split=0), a)
    assert arrs[1].shape == (6, 10)


def test_percentile_sketched(ht):
    # reference statistics.py:1490-1532 — estimate on a random subset
    ht.random.seed(0)
    x = ht.random.randn(50_000, split=0)
    exact = float(ht.percentile(x, 50.0))
    sk = float(ht.percentile(x, 50.0, sketched=True, sketch_size=8192))
    assert abs(sk - exact) < 0.1, (sk, exact)
    # tiny arrays: sketch covers everything, exact result
    y = ht.arange(10, dtype=ht.float32, split=0)
    np.testing.assert_allclose(
        float(ht.percentile(y, 30.0, sketched=True, sketch_size=100)),
        float(ht.percentile(y, 30.0)),
    )


def test_gaussian_nb_partial_fit_matches_fit(ht):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((120, 4)).astype(np.float32)
    y = rng.integers(0, 3, 120)
    full = ht.naive_bayes.GaussianNB()
    full.fit(ht.array(X, split=0), ht.array(y, split=0))
    inc = ht.naive_bayes.GaussianNB()
    inc.partial_fit(ht.array(X[:60], split=0), ht.array(y[:60], split=0), classes=ht.array([0, 1, 2]))
    inc.partial_fit(ht.array(X[60:], split=0), ht.array(y[60:], split=0))
    np.testing.assert_allclose(inc.theta_.numpy(), full.theta_.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(inc.var_.numpy(), full.var_.numpy(), rtol=1e-3, atol=1e-4)
    p1 = inc.predict(ht.array(X, split=0)).numpy()
    p2 = full.predict(ht.array(X, split=0)).numpy()
    assert (p1 == p2).mean() > 0.97


class TestSampleSort:
    """PSRS collective sort (reference manipulations.py:2497-2750)."""

    @pytest.fixture(autouse=True)
    def _force_path(self, monkeypatch):
        from heat_tpu.core import sample_sort

        monkeypatch.setattr(sample_sort, "SAMPLE_SORT_THRESHOLD", 1)

    @pytest.mark.parametrize("n", [64, 61, 1003])
    def test_matches_numpy_stable(self, n):
        rng = np.random.default_rng(0)
        for data in (
            rng.standard_normal(n).astype(np.float32),
            rng.integers(-50, 50, n).astype(np.int32),
            np.zeros(n, np.float32),  # all-equal: the tie storm that breaks
            # approximate-bucket sample sorts; distinct packed keys keep
            # the PSRS 2B bound exact
            np.repeat([3.0, 1.0], n // 2 + 1)[:n].astype(np.float32),
        ):
            v, i = ht.sort(ht.array(data, split=0))
            assert v.split == 0 and i.split == 0
            np.testing.assert_array_equal(v.numpy(), np.sort(data))
            np.testing.assert_array_equal(i.numpy(), np.argsort(data, kind="stable"))

    def test_compiles_to_all_to_all(self):
        from heat_tpu.core import sample_sort

        a = ht.array(np.arange(64, dtype=np.float32), split=0)
        fn = sample_sort._psrs_fn(
            a.comm, 64, a.larray_padded.shape[0] // a.comm.size, (), "float32", False
        )
        txt = fn.lower(a.larray_padded).compile().as_text()
        assert "all-to-all" in txt

    def test_gate(self):
        from heat_tpu.core.sample_sort import supports_sample_sort

        a = ht.array(np.arange(64, dtype=np.float32), split=0)
        assert supports_sample_sort(a, 0, False)
        assert supports_sample_sort(a, 0, True)  # descending now collective too
        b = ht.array(np.arange(64, dtype=np.float64), split=0)
        # f64 keys ride the u64 plane when x64 is on (tests enable it)
        assert supports_sample_sort(b, 0, False)
        c = ht.array(np.arange(64, dtype=np.float32), split=0).resplit(None)
        assert not supports_sample_sort(c, 0, False)  # replicated -> local sort

    @pytest.mark.parametrize(
        "dtype", [np.float64, np.int64, np.uint32, np.float16, np.int32]
    )
    def test_wide_dtype_matrix(self, dtype):
        rng = np.random.default_rng(4)
        if np.issubdtype(dtype, np.floating):
            data = rng.standard_normal(403).astype(dtype)
        else:
            data = rng.integers(0, 1000, 403).astype(dtype)
        v, i = ht.sort(ht.array(data, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(data))
        np.testing.assert_array_equal(i.numpy(), np.argsort(data, kind="stable"))

    def test_sentinel_key_collision_keeps_indices(self):
        # INT_MAX ascending / INT_MIN descending / NaN map onto the
        # scatter-fill sentinel key; the merge's rescue pass must keep
        # their true indices (r3 review finding)
        data = np.array(
            [5, np.iinfo(np.int32).max, -3, np.iinfo(np.int32).max, 7, 0, 2, 9],
            np.int32,
        )
        v, i = ht.sort(ht.array(data, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(data))
        np.testing.assert_array_equal(i.numpy(), np.argsort(data, kind="stable"))
        v, i = ht.sort(
            ht.array(np.array([1, np.iinfo(np.int32).min, 4, -9], np.int32), split=0),
            descending=True,
        )
        np.testing.assert_array_equal(v.numpy(), [4, 1, -9, np.iinfo(np.int32).min])
        fl = np.array([3.0, np.nan, 1.0, np.nan, -2.0, np.inf], np.float32)
        v, i = ht.sort(ht.array(fl, split=0))
        np.testing.assert_array_equal(i.numpy(), np.argsort(fl, kind="stable"))
        u = np.array([7, np.iinfo(np.uint32).max, 2, 1], np.uint32)
        v, i = ht.sort(ht.array(u, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(u))
        np.testing.assert_array_equal(i.numpy(), np.argsort(u, kind="stable"))

    def test_descending_collective(self):
        rng = np.random.default_rng(5)
        data = rng.integers(-40, 40, 517).astype(np.int32)
        v, i = ht.sort(ht.array(data, split=0), descending=True)
        np.testing.assert_array_equal(v.numpy(), np.sort(data)[::-1])
        # stable: ties keep ascending original index
        np.testing.assert_array_equal(
            i.numpy(), np.argsort(-data, kind="stable")
        )

    def test_nd_batched_sort_along_split(self):
        rng = np.random.default_rng(6)
        data = rng.standard_normal((203, 7)).astype(np.float32)
        v, i = ht.sort(ht.array(data, split=0), axis=0)
        assert v.split == 0
        np.testing.assert_array_equal(v.numpy(), np.sort(data, axis=0))
        np.testing.assert_array_equal(i.numpy(), np.argsort(data, axis=0, kind="stable"))

    def test_nans_sort_last(self):
        # the PSRS path must put every NaN bit pattern last, like numpy
        # (ADVICE r2: bit-pattern order diverged); the gather-path twin
        # lives in test_sort_nans_gather_path below
        data = np.array(
            [3.0, np.nan, -np.inf, 1.0, -np.float32(np.nan), np.inf, -2.0, np.nan],
            np.float32,
        )
        v, _ = ht.sort(ht.array(data, split=0))
        got = v.numpy()
        np.testing.assert_array_equal(got[:5], np.sort(data)[:5])
        assert np.isnan(got[5:]).all()

    def test_sort_out_param(self):
        data = np.random.default_rng(3).standard_normal(40).astype(np.float32)
        a = ht.array(data, split=0)
        out = ht.empty(40, dtype=ht.float32, split=0)
        res, idx = ht.sort(a, out=out)
        np.testing.assert_array_equal(out.numpy(), np.sort(data))


def test_topk_distributed_merge():
    """1-D split topk merges per-shard candidates instead of gathering
    (reference manipulations.py:4175 custom MPI merge op)."""
    rng = np.random.default_rng(11)
    for dtype in (np.float64, np.float32):
        x = rng.standard_normal(1003).astype(dtype)
        a = ht.array(x, split=0)
        for largest in (True, False):
            v, i = ht.topk(a, 17, largest=largest)
            want = np.sort(x)[::-1][:17] if largest else np.sort(x)[:17]
            np.testing.assert_allclose(np.asarray(v.numpy()), want, atol=0)
            np.testing.assert_allclose(x[np.asarray(i.numpy())], want, atol=0)
    xi = rng.integers(-(10**9), 10**9, 257)
    v, i = ht.topk(ht.array(xi, split=0), 9)
    np.testing.assert_array_equal(np.asarray(v.numpy()), np.sort(xi)[::-1][:9])

    import importlib

    man = importlib.import_module("heat_tpu.core.manipulations")
    a = ht.array(np.zeros(1 << 12), split=0)
    fn = man._topk_merge_fn(a.comm, 8, True, 1 << 12, a.larray_padded.shape[0] // a.comm.size)
    txt = fn.lower(a.larray_padded).compile().as_text()
    # only the tiny (p*k,) candidate gathers appear — never the full array
    assert "all-gather" in txt


def test_sort_nans_gather_path():
    """Below SAMPLE_SORT_THRESHOLD ht.sort takes the gather path — its NaN
    order must agree with PSRS and numpy (NaNs last)."""
    data = np.array(
        [3.0, np.nan, -np.inf, 1.0, -np.float32(np.nan), np.inf, -2.0, np.nan],
        np.float32,
    )
    v, _ = ht.sort(ht.array(data, split=0))
    got = v.numpy()
    np.testing.assert_array_equal(got[:5], np.sort(data)[:5])
    assert np.isnan(got[5:]).all()


def test_sort_out_param_different_split(monkeypatch):
    """PSRS out= must rebuild in OUT's layout, not swap split-0 padding in."""
    from heat_tpu.core import sample_sort

    monkeypatch.setattr(sample_sort, "SAMPLE_SORT_THRESHOLD", 1)
    data = np.random.default_rng(1).standard_normal(19)
    a = ht.array(data, split=0)
    out = ht.empty(19, dtype=ht.float64, split=None)
    ht.sort(a, out=out)
    assert out.split is None and out.shape == (19,)
    np.testing.assert_array_equal(out.numpy(), np.sort(data))


def test_topk_bool_takes_dense_path():
    b = ht.array(np.array([True, False, True, True, False, True, False, True]), split=0)
    v, i = ht.topk(b, 3)
    assert np.asarray(v.numpy()).all()


class TestSortedOrderStatistics:
    """percentile/median/unique on the PSRS sorted distribution instead of
    a dense gather (VERDICT r2 #4; reference statistics.py:1443)."""

    @pytest.fixture(autouse=True)
    def _force_path(self, monkeypatch):
        from heat_tpu.core import sample_sort

        monkeypatch.setattr(sample_sort, "SAMPLE_SORT_THRESHOLD", 1)

    def test_percentile_matches_numpy(self):
        rng = np.random.default_rng(0)
        data = rng.standard_normal(1003).astype(np.float32)
        a = ht.array(data, split=0)
        for q in (50.0, [10.0, 50.0, 93.5], 0.0, 100.0):
            for interp in ("linear", "lower", "higher", "midpoint", "nearest"):
                got = ht.percentile(a, q, interpolation=interp).numpy()
                want = np.percentile(data, q, method=interp)
                np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_median_and_int_input(self):
        rng = np.random.default_rng(1)
        data = rng.integers(-500, 500, 807).astype(np.int32)
        a = ht.array(data, split=0)
        np.testing.assert_allclose(
            ht.median(a).numpy(), np.median(data), rtol=1e-6
        )

    def test_unique_sorted_path(self):
        rng = np.random.default_rng(2)
        data = rng.integers(0, 60, 903).astype(np.int32)
        u = ht.unique(ht.array(data, split=0))
        np.testing.assert_array_equal(u.numpy(), np.unique(data))

    def test_selection_never_gathers(self):
        from heat_tpu.core import sample_sort

        a = ht.array(np.arange(64, dtype=np.float32), split=0)
        if a.comm.size == 1:
            pytest.skip("needs a mesh")
        fn = sample_sort._select_fn(a.comm, 64 // a.comm.size, 2, "float32")
        import jax.numpy as jnp

        txt = fn.lower(a.larray_padded, jnp.zeros(2, jnp.int64)).compile().as_text()
        assert "all-gather" not in txt or "f32[64]" not in txt  # no full-array gather


def test_sorted_orderstats_nan_propagation(monkeypatch):
    """r3 review: the PSRS fast paths must keep numpy's NaN semantics."""
    from heat_tpu.core import sample_sort

    monkeypatch.setattr(sample_sort, "SAMPLE_SORT_THRESHOLD", 1)
    data = np.array([5.0, 1.0, np.nan, 3.0, 2.0, 4.0, 8.0, 7.0], np.float32)
    a = ht.array(data, split=0)
    assert np.isnan(float(ht.percentile(a, 50.0)))
    assert np.isnan(float(ht.median(a)))
    dn = np.array([3.0, np.nan, 1.0, np.nan, -2.0, np.nan, 1.0, 3.0], np.float32)
    u = ht.unique(ht.array(dn, split=0)).numpy()
    want = np.unique(dn)
    assert u.shape == want.shape
    np.testing.assert_array_equal(u[:-1], want[:-1])
    assert np.isnan(u[-1])


def test_lstsq_pinv_rank_deficient_falls_back(monkeypatch):
    """r3 review: duplicated column -> fast path must defer to the SVD."""
    p = ht.get_comm().size
    rng = np.random.default_rng(9)
    A = rng.standard_normal((8 * p, 3))
    A[:, 2] = A[:, 0]  # rank 2
    b = rng.standard_normal(8 * p)
    x, _, _, _ = ht.linalg.lstsq(ht.array(A, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(
        x.numpy(), np.linalg.lstsq(A, b, rcond=None)[0], rtol=1e-5, atol=1e-6
    )
    pi = ht.linalg.pinv(ht.array(A, split=0))
    np.testing.assert_allclose(pi.numpy(), np.linalg.pinv(A), rtol=1e-5, atol=1e-6)


def test_lstsq_contract_full_rank():
    """resid is the residual sum of squares and sv the true spectrum."""
    p = ht.get_comm().size
    rng = np.random.default_rng(10)
    A = rng.standard_normal((8 * p, 3))
    b = rng.standard_normal(8 * p)
    x, resid, rank, sv = ht.linalg.lstsq(ht.array(A, split=0), ht.array(b, split=0))
    xn, rn, kn, svn = np.linalg.lstsq(A, b, rcond=None)
    np.testing.assert_allclose(x.numpy(), xn, rtol=1e-6)
    np.testing.assert_allclose(resid.numpy(), rn, rtol=1e-5)
    assert int(rank) == kn
    np.testing.assert_allclose(np.sort(sv.numpy())[::-1], svn, rtol=1e-5)
