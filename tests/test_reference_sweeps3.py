"""Third reference test-family batch (VERDICT r2 #8): arithmetics edge
cases (reference test_arithmetics.py, 4519 LoC), io partial/corrupt loads
(test_io.py:894), and random statistical-moment checks (test_random.py).
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


# ----------------------------------------------------------------------
# arithmetics edge cases (reference test_arithmetics.py)
# ----------------------------------------------------------------------
class TestArithmeticsEdges:
    @pytest.mark.parametrize("split", SPLITS)
    def test_div_by_zero(self, split):
        a = ht.array(np.array([1.0, -1.0, 0.0], np.float32), split=split)
        z = ht.zeros(3, dtype=ht.float32, split=split)
        out = (a / z).numpy()
        assert np.isposinf(out[0]) and np.isneginf(out[1]) and np.isnan(out[2])

    @pytest.mark.parametrize("split", SPLITS)
    def test_floordiv_mod_negative(self, split):
        x = np.array([7, -7, 5, -5], np.int32)
        y = np.array([3, 3, -3, -3], np.int32)
        a, b = ht.array(x, split=split), ht.array(y, split=split)
        np.testing.assert_array_equal((a // b).numpy(), x // y)
        np.testing.assert_array_equal((a % b).numpy(), x % y)

    @pytest.mark.parametrize("split", SPLITS)
    def test_pow_edge(self, split):
        x = np.array([0.0, 2.0, -2.0], np.float32)
        a = ht.array(x, split=split)
        np.testing.assert_allclose((a ** 0).numpy(), np.ones(3), rtol=1e-6)
        np.testing.assert_allclose((a ** 3).numpy(), x ** 3, rtol=1e-6)
        np.testing.assert_allclose(
            ht.pow(a, ht.array(np.array([1.0, 0.5, 2.0], np.float32), split=split)).numpy(),
            x ** np.array([1.0, 0.5, 2.0], np.float32),
            rtol=1e-6,
        )

    def test_scalar_broadcast_both_sides(self):
        a = ht.array(np.arange(5, dtype=np.float32), split=0)
        np.testing.assert_allclose((2.0 - a).numpy(), 2.0 - np.arange(5))
        np.testing.assert_allclose((2.0 / (a + 1)).numpy(), 2.0 / (np.arange(5) + 1))
        np.testing.assert_allclose((a + True).numpy(), np.arange(5) + 1)

    @pytest.mark.parametrize("split", SPLITS)
    def test_inplace_cast_guard(self, split):
        a = ht.array(np.arange(5, dtype=np.int32), split=split)
        with pytest.raises(TypeError):
            a += 0.5  # float into int in place must raise (reference idiom)
        a += 2
        np.testing.assert_array_equal(a.numpy(), np.arange(5) + 2)

    @pytest.mark.parametrize("split", SPLITS)
    def test_bitops_and_shifts(self, split):
        x = np.array([0b1010, 0b0110, 0b1111], np.int32)
        y = np.array([0b0011, 0b0101, 0b1000], np.int32)
        a, b = ht.array(x, split=split), ht.array(y, split=split)
        np.testing.assert_array_equal((a & b).numpy(), x & y)
        np.testing.assert_array_equal((a | b).numpy(), x | y)
        np.testing.assert_array_equal((a ^ b).numpy(), x ^ y)
        np.testing.assert_array_equal(ht.left_shift(a, 2).numpy(), x << 2)
        np.testing.assert_array_equal(ht.right_shift(a, 1).numpy(), x >> 1)
        np.testing.assert_array_equal(ht.invert(a).numpy(), ~x)

    def test_gcd_lcm_hypot(self):
        x = np.array([12, 18, 7], np.int32)
        y = np.array([8, 27, 14], np.int32)
        np.testing.assert_array_equal(
            ht.gcd(ht.array(x, split=0), ht.array(y, split=0)).numpy(), np.gcd(x, y)
        )
        np.testing.assert_array_equal(
            ht.lcm(ht.array(x, split=0), ht.array(y, split=0)).numpy(), np.lcm(x, y)
        )
        f = np.array([3.0, 5.0], np.float32)
        g = np.array([4.0, 12.0], np.float32)
        np.testing.assert_allclose(
            ht.hypot(ht.array(f, split=0), ht.array(g, split=0)).numpy(),
            np.hypot(f, g),
            rtol=1e-6,
        )

    @pytest.mark.parametrize("split", SPLITS)
    def test_nan_aware_reductions(self, split):
        x = np.array([1.0, np.nan, 3.0, np.nan, 5.0], np.float32)
        a = ht.array(x, split=split)
        np.testing.assert_allclose(float(ht.nansum(a)), np.nansum(x), rtol=1e-6)
        np.testing.assert_allclose(float(ht.nanprod(a)), np.nanprod(x), rtol=1e-6)
        np.testing.assert_allclose(
            ht.nan_to_num(a).numpy(), np.nan_to_num(x), rtol=1e-6
        )

    @pytest.mark.parametrize("split", SPLITS)
    def test_diff_and_cumops(self, split):
        x = np.array([1, 3, 0, 7, 2], np.int32)
        a = ht.array(x, split=split)
        np.testing.assert_array_equal(ht.diff(a).numpy(), np.diff(x))
        np.testing.assert_array_equal(ht.diff(a, n=2).numpy(), np.diff(x, n=2))
        np.testing.assert_array_equal(ht.cumsum(a, 0).numpy(), np.cumsum(x))
        np.testing.assert_array_equal(ht.cumprod(a, 0).numpy(), np.cumprod(x))

    def test_overflow_wraparound_int32(self):
        x = np.array([np.iinfo(np.int32).max], np.int32)
        a = ht.array(x, split=0)
        np.testing.assert_array_equal((a + 1).numpy(), x + np.int32(1))

    @pytest.mark.parametrize("split", SPLITS)
    def test_copysign_signbit_trunc(self, split):
        x = np.array([1.5, -2.5, 0.0, -0.0], np.float32)
        a = ht.array(x, split=split)
        np.testing.assert_array_equal(ht.signbit(a).numpy(), np.signbit(x))
        np.testing.assert_allclose(ht.trunc(a).numpy(), np.trunc(x))
        y = np.array([-1.0, 1.0, -1.0, 1.0], np.float32)
        np.testing.assert_allclose(
            ht.copysign(a, ht.array(y, split=split)).numpy(), np.copysign(x, y)
        )


# ----------------------------------------------------------------------
# io partial / corrupt loads (reference test_io.py)
# ----------------------------------------------------------------------
class TestIOPartialCorrupt:
    def test_csv_missing_file(self):
        with pytest.raises((FileNotFoundError, OSError)):
            ht.load_csv("/nonexistent/not_here.csv")

    def test_load_unknown_extension(self, tmp_path):
        p = tmp_path / "data.weird"
        p.write_text("junk")
        with pytest.raises(ValueError):
            ht.load(str(p))

    def test_hdf5_corrupt(self, tmp_path):
        pytest.importorskip("h5py")
        p = tmp_path / "bad.h5"
        p.write_bytes(b"this is not an hdf5 file at all" * 4)
        with pytest.raises(Exception):
            ht.load_hdf5(str(p), "data")

    def test_hdf5_missing_dataset(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        p = str(tmp_path / "x.h5")
        with h5py.File(p, "w") as f:
            f["present"] = np.arange(4.0)
        with pytest.raises(KeyError):
            ht.load_hdf5(p, "absent")

    def test_hdf5_load_fraction(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        p = str(tmp_path / "frac.h5")
        data = np.arange(40, dtype=np.float32).reshape(20, 2)
        with h5py.File(p, "w") as f:
            f["data"] = data
        part = ht.load_hdf5(p, "data", split=0, load_fraction=0.5)
        assert part.shape[0] == 10
        np.testing.assert_allclose(part.numpy(), data[:10])

    def test_npy_shard_dir_mismatched(self, tmp_path):
        np.save(tmp_path / "a.npy", np.ones((3, 2), np.float32))
        np.save(tmp_path / "b.npy", np.ones((4, 5), np.float32))  # wrong cols
        with pytest.raises(Exception):
            ht.load_npy_from_path(str(tmp_path), split=0)

    def test_csv_ragged_rows(self, tmp_path):
        p = tmp_path / "ragged.csv"
        p.write_text("1,2,3\n4,5\n")
        with pytest.raises(Exception):
            ht.load_csv(str(p))


# ----------------------------------------------------------------------
# random statistical moments (reference test_random.py)
# ----------------------------------------------------------------------
class TestRandomMoments:
    def test_uniform_moments(self):
        ht.random.seed(42)
        x = ht.random.rand(200_000, split=0)
        m = float(ht.mean(x))
        v = float(ht.var(x))
        assert abs(m - 0.5) < 5e-3
        assert abs(v - 1.0 / 12.0) < 5e-3
        mn, mx = float(x.min()), float(x.max())
        assert 0.0 <= mn < 0.001 and 0.999 < mx < 1.0

    def test_normal_moments(self):
        ht.random.seed(7)
        x = ht.random.randn(200_000, split=0)
        from scipy import stats

        xs = x.numpy()
        assert abs(xs.mean()) < 0.01
        assert abs(xs.std() - 1.0) < 0.01
        assert abs(stats.skew(xs)) < 0.03
        assert abs(stats.kurtosis(xs)) < 0.06

    def test_randint_uniformity(self):
        ht.random.seed(3)
        k = 16
        x = ht.random.randint(0, k, size=(160_000,), split=0)
        counts = np.bincount(x.numpy().astype(np.int64), minlength=k)
        expected = 160_000 / k
        chi2 = float(((counts - expected) ** 2 / expected).sum())
        # chi-square with 15 dof: P(chi2 > 37.7) ~ 0.001
        assert chi2 < 37.7, chi2

    def test_permutation_is_uniform_enough(self):
        ht.random.seed(11)
        n = 6
        first_pos = np.zeros(n)
        trials = 300
        for t in range(trials):
            p = ht.random.permutation(n).numpy()
            first_pos[p[0]] += 1
        # element appearing first ~ uniform over n
        expected = trials / n
        chi2 = ((first_pos - expected) ** 2 / expected).sum()
        assert chi2 < 20.5  # 5 dof, p ~ 0.001

    def test_standard_normal_split_invariance(self):
        ht.random.seed(123)
        a = ht.random.standard_normal((1000,), split=0).numpy()
        ht.random.seed(123)
        b = ht.random.standard_normal((1000,), split=None).numpy()
        np.testing.assert_allclose(a, b, rtol=1e-6)
