"""Padded COO-plane engine for distributed sparse matrices.

The reference stores one torch.sparse_csr chunk *per MPI rank* and re-syncs
nnz after every op (heat/sparse/dcsx_matrix.py:19-423,
heat/sparse/_operations.py:17-209).  The TPU-native re-design applies the
framework's pad-and-mask policy to the *nonzero* dimension: a matrix split
along its compressed axis is stored as three flat planes

    comp  : int32 (P*C,)  LOCAL compressed index within the shard
    other : int32 (P*C,)  GLOBAL uncompressed index
    val   : dtype (P*C,)  stored values

sharded over the mesh, where ``C`` is the max per-shard nnz (static, so
every kernel has fixed shapes for XLA) and padding entries carry
``comp == comp_pad`` (one past the last local row) with ``val == 0`` so
they sort to the back and contribute nothing to any segment-sum.  Per-shard
entries are kept sorted by (comp, other) with the real entries first; the
per-shard true counts live in a device-resident ``lnnz`` vector (P,) plus
a host tuple (the analog of the reference's nnz Allreduce re-sync).

Every op is a jitted program over these static shapes: elementwise union /
intersection are a concat + two-key ``lax.sort`` + neighbor merge, SpMM is
a gather + ``segment_sum`` (plus a ``psum``/``psum_scatter`` for the
column-compressed layout), and the CSR<->CSC transpose is pure metadata
(the planes of A in (row, col) order ARE the planes of A^T in (col, row)
order under the same chunking).
"""

from __future__ import annotations

import functools as _functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..core._compat import pcast as _pcast
from ..core._compat import shard_map as _shard_map

__all__ = []


def _shard_spec(ndim_specs):
    from jax.sharding import PartitionSpec as P

    return P(*ndim_specs)


def _smap(comm, body, in_specs, out_specs):
    return jax.jit(
        _shard_map(body, mesh=comm.mesh, in_specs=in_specs, out_specs=out_specs)
    )


def _plane_sharding(comm, dist: bool):
    return comm.sharding(0 if dist else None)


def fetch_host(arr) -> np.ndarray:
    """Device->host fetch that works when the array spans processes (the
    multi-host analog of ``DNDarray.numpy``): tiny metadata vectors only
    (lnnz re-sync), never O(nnz)."""
    if jax.process_count() > 1 and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)


# ----------------------------------------------------------------------
# construction
# ----------------------------------------------------------------------
def build_from_host_coo(rows, cols, vals, gshape, comp_axis, split, comm):
    """Build padded planes from host COO triplets (ingestion path — host
    work is allowed here, exactly like the dense factories).

    Returns (comp, other, val, lnnz_dev, lnnz_host, C, comp_pad).
    """
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals)
    comp_g, other = (rows, cols) if comp_axis == 0 else (cols, rows)
    order = np.lexsort((other, comp_g))
    comp_g, other, vals = comp_g[order], other[order], vals[order]
    # sum duplicates (the factories promise canonical form)
    if comp_g.size:
        key_same = np.zeros(comp_g.size, bool)
        key_same[1:] = (comp_g[1:] == comp_g[:-1]) & (other[1:] == other[:-1])
        if key_same.any():
            seg = np.cumsum(~key_same) - 1
            agg = np.zeros(seg[-1] + 1, vals.dtype)
            np.add.at(agg, seg, vals)
            keep = ~key_same
            comp_g, other, vals = comp_g[keep], other[keep], agg

    extent = gshape[comp_axis]
    dist = split is not None
    P = comm.size if dist else 1
    comp_pad = comm.padded_extent(extent) // P if dist else max(extent, 1)

    starts = np.minimum(np.arange(P) * comp_pad, extent)
    stops = np.minimum(starts + comp_pad, extent)
    bounds = np.searchsorted(comp_g, np.concatenate([starts, [extent]]))
    lnnz = (bounds[1:] - bounds[:-1]).astype(np.int32)
    # entries past the last true row cannot exist (comp_g < extent)
    C = max(int(lnnz.max()) if P else 0, 1)

    comp_p = np.full((P, C), comp_pad, np.int32)
    other_p = np.zeros((P, C), np.int32)
    val_p = np.zeros((P, C), vals.dtype)
    for s in range(P):
        lo, hi = bounds[s], bounds[s + 1]
        k = hi - lo
        comp_p[s, :k] = comp_g[lo:hi] - starts[s]
        other_p[s, :k] = other[lo:hi]
        val_p[s, :k] = vals[lo:hi]

    sh = _plane_sharding(comm, dist)
    comp = jax.device_put(comp_p.reshape(-1), sh)
    oth = jax.device_put(other_p.reshape(-1), sh)
    val = jax.device_put(val_p.reshape(-1), sh)
    lnnz_dev = jax.device_put(lnnz, sh)
    return comp, oth, val, lnnz_dev, tuple(int(x) for x in lnnz), C, comp_pad


@_functools.lru_cache(maxsize=128)
def _count_nonzero_prog(comm, P: int, rows_loc: int, ncols: int, dist: bool, fortran: bool):
    def body(x):
        return jnp.count_nonzero(x).astype(jnp.int32)[None]

    if not dist:
        return jax.jit(lambda x: jnp.count_nonzero(x).astype(jnp.int32)[None])
    spec = _shard_spec((comm.axis_name, None) if not fortran else (None, comm.axis_name))
    return _smap(comm, body, (spec,), _shard_spec((comm.axis_name,)))


@_functools.lru_cache(maxsize=128)
def _pack_from_dense_prog(
    comm, P: int, rows_loc: int, ncols: int, C: int, comp_pad: int, true_extent: int,
    dist: bool, fortran: bool,
):
    """Pack a dense padded block into sorted planes.

    ``fortran`` packs column-major (for the column-compressed layout, where
    the local block is (m, comp_pad) and entries sort by (col, row))."""

    def body(x):
        if fortran:
            flat = x.T.reshape(-1)  # (comp_pad * m): index f -> comp=f//m, other=f%m
            div = x.shape[0]
        else:
            flat = x.reshape(-1)  # (rows_loc * n): comp=f//n, other=f%n
            div = x.shape[1]
        n_el = flat.shape[0]
        mask = flat != 0
        big = jnp.asarray(n_el, jnp.int32)
        key = jnp.where(mask, jnp.arange(n_el, dtype=jnp.int32), big)
        order = jnp.argsort(key)[:C]
        valid = jnp.take(mask, order)
        comp = jnp.where(valid, (order // div).astype(jnp.int32), comp_pad)
        other = jnp.where(valid, (order % div).astype(jnp.int32), 0)
        val = jnp.where(valid, jnp.take(flat, order), jnp.zeros((), flat.dtype))
        ln = jnp.sum(mask).astype(jnp.int32)[None]
        return comp, other, val, ln

    if not dist:
        return jax.jit(body)
    name = comm.axis_name
    in_spec = _shard_spec((name, None) if not fortran else (None, name))
    pl = _shard_spec((name,))
    return _smap(comm, body, (in_spec,), (pl, pl, pl, pl))


def pack_from_dense(x_padded, gshape, comp_axis, split, comm):
    """Device-side dense -> planes (``to_sparse``): one tiny (P,) count
    pull to fix the static capacity, then a single packing program."""
    dist = split is not None
    P = comm.size if dist else 1
    extent = gshape[comp_axis]
    comp_pad = comm.padded_extent(extent) // P if dist else max(extent, 1)
    fortran = comp_axis == 1
    rows_loc = x_padded.shape[0] // (P if (dist and not fortran) else 1)
    counts = _count_nonzero_prog(
        comm, P, rows_loc, x_padded.shape[1], dist, fortran
    )(x_padded)
    lnnz_host = tuple(int(v) for v in fetch_host(counts))
    C = max(max(lnnz_host), 1)
    prog = _pack_from_dense_prog(
        comm, P, rows_loc, int(x_padded.shape[1]), C, comp_pad, extent, dist, fortran
    )
    comp, other, val, lnnz_dev = prog(x_padded)
    return comp, other, val, lnnz_dev, lnnz_host, C, comp_pad


# ----------------------------------------------------------------------
# accessors (all device-side)
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=256)
def _lindptr_prog(comm, P: int, C: int, comp_pad: int, dist: bool):
    def body(comp):
        return jnp.searchsorted(comp, jnp.arange(comp_pad + 1, dtype=comp.dtype)).astype(
            jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        )

    if not dist:
        return jax.jit(body)
    name = comm.axis_name
    return _smap(comm, body, (_shard_spec((name,)),), _shard_spec((name,)))


def lindptr_blocks(comp, P, C, comp_pad, dist, comm):
    """(P*(comp_pad+1),) concatenated per-shard local indptrs."""
    return _lindptr_prog(comm, P, C, comp_pad, dist)(comp)


@_functools.lru_cache(maxsize=256)
def _global_indptr_prog(comm, P: int, C: int, comp_pad: int, extent: int, dist: bool):
    lp = _lindptr_prog(comm, P, C, comp_pad, dist)

    def run(comp, lnnz):
        l = lp(comp).reshape(P, comp_pad + 1)
        base = jnp.cumsum(lnnz) - lnnz  # exclusive scan (tiny, (P,))
        flat = (l[:, :comp_pad] + base[:, None]).reshape(-1)
        total = jnp.sum(lnnz)[None]
        return jnp.concatenate([flat[:extent], total]).astype(l.dtype)

    return jax.jit(run)


def global_indptr(comp, lnnz_dev, P, C, comp_pad, extent, dist, comm):
    return _global_indptr_prog(comm, P, C, comp_pad, extent, dist)(comp, lnnz_dev)


@_functools.lru_cache(maxsize=256)
def _pack_triple_prog(comm, P: int, C: int, gnnz: int):
    """Global packed (other, val) of length gnnz, in global (comp, other)
    order — shard blocks are already sorted, shards are in comp order."""

    def run(other, val, lnnz):
        base = jnp.cumsum(lnnz) - lnnz
        idx = jnp.tile(jnp.arange(C, dtype=jnp.int32), (P, 1))
        pos = base[:, None].astype(jnp.int32) + idx
        pos = jnp.where(idx < lnnz[:, None], pos, gnnz).reshape(-1)
        out_other = jnp.zeros((gnnz,), other.dtype).at[pos].set(other, mode="drop")
        out_val = jnp.zeros((gnnz,), val.dtype).at[pos].set(val, mode="drop")
        return out_other, out_val

    return jax.jit(run)


def packed_indices_data(other, val, lnnz_dev, P, C, gnnz, comm):
    return _pack_triple_prog(comm, P, C, gnnz)(other, val, lnnz_dev)


# ----------------------------------------------------------------------
# device-side re-split (None <-> compressed axis): a layout change between
# mesh shardings, like the dense layer's resplit — no host COO round-trip
# (VERDICT r4 weak #6).  The only host traffic is the usual (P,)-int
# capacity re-sync.
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=128)
def _chunk_bounds_prog(comm, P: int, chunk: int, extent: int):
    """searchsorted of the target chunk starts into replicated global comp
    (pad sentinel == extent sorts past every real entry)."""
    starts = np.minimum(np.arange(P + 1) * chunk, extent).astype(np.int32)

    def run(comp_g):
        return jnp.searchsorted(comp_g, jnp.asarray(starts, comp_g.dtype)).astype(jnp.int32)

    return jax.jit(run)


@_functools.lru_cache(maxsize=128)
def _scatter_planes_prog(comm, P: int, size_in: int, chunk_new: int, C_new: int):
    """Replicated global planes -> per-shard chunked planes (None -> split)."""
    name = comm.axis_name

    def body(comp_g, other_g, val_g, bounds):
        s = jax.lax.axis_index(name)
        start, stop = bounds[s], bounds[s + 1]
        idx = start + jnp.arange(C_new, dtype=jnp.int32)
        valid = idx < stop
        idc = jnp.clip(idx, 0, max(size_in - 1, 0))
        comp = jnp.where(
            valid, jnp.take(comp_g, idc).astype(jnp.int32) - s * chunk_new, chunk_new
        )
        other = jnp.where(valid, jnp.take(other_g, idc), 0)
        val = jnp.where(valid, jnp.take(val_g, idc), jnp.zeros((), val_g.dtype))
        return comp, other, val

    rep = _shard_spec((None,))
    pl = _shard_spec((name,))
    return _smap(comm, body, (rep, rep, rep, rep), (pl, pl, pl))


@_functools.lru_cache(maxsize=128)
def _gather_planes_prog(comm, P: int, C: int, chunk_old: int, gnnz: int, extent: int):
    """Per-shard chunked planes -> replicated sorted global planes
    (split -> None): one on-device position scatter, like
    ``_pack_triple_prog`` but carrying the globalized comp plane too."""
    out_C = max(gnnz, 1)

    def run(comp, other, val, lnnz):
        base = jnp.cumsum(lnnz) - lnnz
        idx = jnp.tile(jnp.arange(C, dtype=jnp.int32), (P, 1))
        pos = base[:, None].astype(jnp.int32) + idx
        pos = jnp.where(idx < lnnz[:, None], pos, out_C).reshape(-1)
        shard_off = jnp.repeat(
            jnp.arange(P, dtype=comp.dtype) * chunk_old, C, total_repeat_length=P * C
        )
        comp_glob = comp + shard_off
        out_comp = jnp.full((out_C,), extent, comp.dtype).at[pos].set(comp_glob, mode="drop")
        out_other = jnp.zeros((out_C,), other.dtype).at[pos].set(other, mode="drop")
        out_val = jnp.zeros((out_C,), val.dtype).at[pos].set(val, mode="drop")
        rep = _plane_sharding(comm, False)
        return tuple(
            jax.lax.with_sharding_constraint(x, rep)
            for x in (out_comp, out_other, out_val)
        )

    return jax.jit(run)


def rechunk_planes(comp, other, val, lnnz_dev, lnnz_host, extent, to_dist, P, C, comp_pad, comm):
    """Re-split planes between replicated (split=None) and chunked
    (split=comp axis).  Returns (comp, other, val, lnnz_dev, lnnz_host,
    C_new, comp_pad_new) — everything device-resident except the standard
    (P,)-int re-sync."""
    if to_dist:
        Pn = comm.size
        chunk_new = comm.padded_extent(extent) // Pn
        bounds = _chunk_bounds_prog(comm, Pn, chunk_new, extent)(comp)
        bh = fetch_host(bounds)
        counts = tuple(int(bh[i + 1] - bh[i]) for i in range(Pn))
        C_new = max(max(counts), 1)
        prog = _scatter_planes_prog(comm, Pn, int(comp.shape[0]), chunk_new, C_new)
        nc, no, nv = prog(comp, other, val, jax.device_put(bounds, comm.sharding(None)))
        lnnz_new = jax.device_put(np.asarray(counts, np.int32), comm.sharding(0))
        return nc, no, nv, lnnz_new, counts, C_new, chunk_new
    gnnz = int(np.sum(lnnz_host))
    prog = _gather_planes_prog(comm, P, C, comp_pad, gnnz, extent)
    nc, no, nv = prog(comp, other, val, lnnz_dev)
    lnnz_new = jax.device_put(np.asarray([gnnz], np.int32), comm.sharding(None))
    return nc, no, nv, lnnz_new, (gnnz,), max(gnnz, 1), max(extent, 1)


# ----------------------------------------------------------------------
# elementwise union / intersection
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=256)
def _merge_prog(comm, kind: str, P: int, Ca: int, Cb: int, comp_pad: int, out_C: int, dist: bool):
    def body(ca, oa, va, cb, ob, vb):
        comp = jnp.concatenate([ca, cb])
        other = jnp.concatenate([oa, ob])
        val = jnp.concatenate([va, vb])
        comp, other, val = jax.lax.sort((comp, other, val), num_keys=2)
        real = comp < comp_pad
        same = (comp[1:] == comp[:-1]) & (other[1:] == other[:-1]) & real[1:]
        first = jnp.concatenate([same, jnp.zeros((1,), bool)])
        second = jnp.concatenate([jnp.zeros((1,), bool), same])
        nxt = jnp.concatenate([val[1:], jnp.zeros((1,), val.dtype)])
        if kind == "add":
            val = jnp.where(first, val + nxt, val)
            kill = second
        else:  # intersection: only duplicate pairs survive, as products
            val = jnp.where(first, val * nxt, jnp.zeros((), val.dtype))
            kill = ~first
        comp = jnp.where(kill, comp_pad, comp)
        other = jnp.where(kill, 0, other)
        val = jnp.where(kill, jnp.zeros((), val.dtype), val)
        comp, other, val = jax.lax.sort((comp, other, val), num_keys=2)
        comp, other, val = comp[:out_C], other[:out_C], val[:out_C]
        ln = jnp.searchsorted(comp, jnp.asarray(comp_pad, comp.dtype)).astype(jnp.int32)[None]
        return comp, other, val, ln

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((comm.axis_name,))
    return _smap(comm, body, (pl,) * 6, (pl, pl, pl, pl))


def merge_planes(kind, a_planes, b_planes, P, Ca, Cb, comp_pad, dist, comm):
    """Union-add or intersect-mul of two same-layout matrices.

    Returns (comp, other, val, lnnz_dev, lnnz_host, out_C) — the result is
    compacted to its true max shard occupancy with one (P,) host pull, the
    analog of the reference's post-op nnz re-sync
    (heat/sparse/_operations.py:151-170)."""
    out_C = (Ca + Cb) if kind == "add" else min(Ca, Cb)
    prog = _merge_prog(comm, kind, P, Ca, Cb, comp_pad, out_C, dist)
    comp, other, val, lnnz_dev = prog(*a_planes, *b_planes)
    lnnz_host = tuple(int(v) for v in fetch_host(lnnz_dev))
    tight = max(max(lnnz_host), 1)
    if tight < out_C:
        comp, other, val = _slice_planes_prog(comm, P, out_C, tight, dist)(comp, other, val)
        out_C = tight
    return comp, other, val, lnnz_dev, lnnz_host, out_C


@_functools.lru_cache(maxsize=256)
def _slice_planes_prog(comm, P: int, C: int, newC: int, dist: bool):
    out = _plane_sharding(comm, dist)

    def run(comp, other, val):
        res = tuple(
            x.reshape(P, C)[:, :newC].reshape(-1) for x in (comp, other, val)
        )
        return tuple(jax.lax.with_sharding_constraint(x, out) for x in res)

    return jax.jit(run)


# ----------------------------------------------------------------------
# dense conversion
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=256)
def _todense_prog(comm, comp_axis: int, P: int, C: int, comp_pad: int, other_extent: int, dist: bool):
    if comp_axis == 0:
        def body(comp, other, val):
            out = jnp.zeros((comp_pad, other_extent), val.dtype)
            return out.at[comp, other].add(val, mode="drop")
        out_spec = _shard_spec((comm.axis_name, None))
    else:
        def body(comp, other, val):
            out = jnp.zeros((other_extent, comp_pad), val.dtype)
            return out.at[other, comp].add(val, mode="drop")
        out_spec = _shard_spec((None, comm.axis_name))

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((comm.axis_name,))
    return _smap(comm, body, (pl,) * 3, out_spec)


def todense_padded(comp, other, val, comp_axis, P, C, comp_pad, other_extent, dist, comm):
    """Padded dense buffer in the canonical DNDarray layout for
    split = comp_axis (CSR -> rows sharded, CSC -> columns sharded)."""
    return _todense_prog(comm, comp_axis, P, C, comp_pad, other_extent, dist)(comp, other, val)


# ----------------------------------------------------------------------
# reductions
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=256)
def _sum_comp_prog(comm, P: int, C: int, comp_pad: int, dist: bool):
    """Per-compressed-index sums -> padded (P*comp_pad,) split-0 vector."""

    def body(comp, val):
        return jax.ops.segment_sum(val, comp, num_segments=comp_pad + 1)[:comp_pad]

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((comm.axis_name,))
    return _smap(comm, body, (pl, pl), pl)


@_functools.lru_cache(maxsize=256)
def _sum_other_prog(comm, P: int, C: int, other_pad: int, dist: bool):
    """Per-uncompressed-index sums; psum_scatter -> padded split-0 vector."""

    def body(comp, other, val):
        seg = jax.ops.segment_sum(val, other, num_segments=other_pad)
        return comm.psum_scatter(seg)

    if not dist:
        return jax.jit(
            lambda comp, other, val: jax.ops.segment_sum(val, other, num_segments=other_pad)
        )
    pl = _shard_spec((comm.axis_name,))
    return _smap(comm, body, (pl,) * 3, pl)


def sum_planes(comp, other, val, axis_is_comp: Optional[bool], P, C, comp_pad, other_extent, dist, comm):
    """axis_is_comp=None -> scalar total; True -> reduce over *other*
    (one value per compressed index); False -> reduce over comp."""
    if axis_is_comp is None:
        return jnp.sum(val)  # padding is zero; GSPMD sums the sharded plane
    if axis_is_comp:
        return _sum_comp_prog(comm, P, C, comp_pad, dist)(comp, val)
    other_pad = comm.padded_extent(other_extent) if dist else other_extent
    return _sum_other_prog(comm, P, C, other_pad, dist)(comp, other, val)


# ----------------------------------------------------------------------
# SpMM / SpMV
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=256)
def _spmm_comp_rows_prog(comm, P: int, C: int, comp_pad: int, k: int, n: int, dist: bool):
    """(compressed-axis = output rows) A @ X: every shard owns whole output
    rows, so one segment-sum per shard and no collective; X is needed in
    full per shard (the columns a shard touches are arbitrary)."""

    def body(comp, other, val, x):
        rows = val[:, None] * jnp.take(x, other, axis=0, mode="clip")
        return jax.ops.segment_sum(rows, comp, num_segments=comp_pad + 1)[:comp_pad]

    if not dist:
        return jax.jit(body)
    name = comm.axis_name
    pl = _shard_spec((name,))
    return _smap(
        comm, body, (pl, pl, pl, _shard_spec((None, None))), _shard_spec((name, None))
    )


@_functools.lru_cache(maxsize=256)
def _spmm_comp_rows_ring_prog(comm, P: int, C: int, comp_pad: int, k_pad: int, n: int):
    """(compressed-axis = output rows) A @ X with X *sharded* split-0:
    instead of replicating X per shard (O(k*n) device memory — VERDICT r4
    weak #5), X's row chunks ride a ppermute ring.  At step t shard s
    holds owner (s+t)%P's chunk; entries whose global column falls in
    that chunk contribute through a masked gather + segment-sum.  Peak
    per-device memory is O((k/P)*n + (m/P)*n) and the only collective is
    the ring's collective-permute (no all-gather, no broadcast)."""
    name = comm.axis_name
    chunk = k_pad // P
    perm = [(i, (i - 1) % P) for i in range(P)]

    def body(comp, other, val, x_loc):
        idx = jax.lax.axis_index(name)

        def step(carry, t):
            acc, xc = carry
            owner = (idx + t) % jnp.asarray(P, jnp.int32)
            rel = other - owner * chunk
            valid = (rel >= 0) & (rel < chunk)
            xr = jnp.take(xc, jnp.clip(rel, 0, chunk - 1), axis=0)
            v = jnp.where(valid, val, jnp.zeros((), val.dtype))
            acc = acc + jax.ops.segment_sum(
                v[:, None] * xr, comp, num_segments=comp_pad + 1
            )
            xc = jax.lax.ppermute(xc, name, perm)
            return (acc, xc), None

        acc0 = jnp.zeros((comp_pad + 1, n), jnp.result_type(val.dtype, x_loc.dtype))
        acc0 = _pcast(acc0, (name,), to="varying")  # scan carry vma
        (acc, _), _ = jax.lax.scan(step, (acc0, x_loc), jnp.arange(P, dtype=jnp.int32))
        return acc[:comp_pad]

    pl = _shard_spec((name,))
    return _smap(
        comm, body, (pl, pl, pl, _shard_spec((name, None))), _shard_spec((name, None))
    )


@_functools.lru_cache(maxsize=256)
def _spmm_comp_inner_prog(comm, P: int, C: int, comp_pad: int, m_pad: int, n: int, dist: bool):
    """(compressed-axis = contraction) A @ X with A column-compressed:
    the shard's columns align with X's split-0 row chunk, so X needs NO
    gather; partial outputs meet in a psum_scatter — the segment-sum +
    psum program (VERDICT r3 #1)."""

    def body(comp, other, val, x_loc):
        xr = jnp.take(x_loc, comp, axis=0, mode="fill", fill_value=0)
        contrib = val[:, None] * xr
        out = jax.ops.segment_sum(contrib, other, num_segments=m_pad)
        return comm.psum_scatter(out)

    if not dist:
        def run(comp, other, val, x_loc):
            xr = jnp.take(x_loc, comp, axis=0, mode="fill", fill_value=0)
            return jax.ops.segment_sum(val[:, None] * xr, other, num_segments=m_pad)
        return jax.jit(run)
    name = comm.axis_name
    pl = _shard_spec((name,))
    return _smap(
        comm, body, (pl, pl, pl, _shard_spec((name, None))), _shard_spec((name, None))
    )


# ----------------------------------------------------------------------
# SpGEMM: sparse @ sparse -> sparse, OUTPUT-SPARSE (ISSUE 16 tentpole 1).
#
# The GEMM-style route densified B per ring chunk and re-packed a dense
# (m/P, n) output block — which cannot even be allocated when the result
# is sparse but n is large.  Here each ring step contracts the local CSR
# chunk of A against the ARRIVING (comp, other, val) triplet chunk of B
# and merges the canonical partial products through ``merge_planes``:
# nothing dense ever materializes, and peak per-device memory is
# O(Ca * r_max) partial triplets (r_max = B's max nnz per row).
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=128)
def _row_occupancy_prog(comm, P: int, C: int, comp_pad: int, dist: bool):
    """Per-shard max nnz of any compressed index -> (P,) int32 (the
    static ELL width the SpGEMM step needs; padding rows count 0)."""

    def body(comp):
        bounds = jnp.searchsorted(comp, jnp.arange(comp_pad + 1, dtype=comp.dtype))
        return jnp.max(jnp.diff(bounds)).astype(jnp.int32)[None]

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((comm.axis_name,))
    return _smap(comm, body, (pl,), pl)


def max_row_occupancy(comp, P, C, comp_pad, dist, comm) -> int:
    """Global max nnz per compressed index — one (P,) host pull, like the
    standard nnz re-sync."""
    occ = fetch_host(_row_occupancy_prog(comm, P, C, comp_pad, dist)(comp))
    return max(1, int(np.max(occ)))


@_functools.lru_cache(maxsize=64)
def _spgemm_step_prog(
    comm, P: int, Ca: int, Cb: int, comp_pad_a: int, chunk_b: int, r_max: int,
    res_dt: str, dist: bool,
):
    """One ring step of the output-sparse SpGEMM.

    The resident B triplet chunk (rows of owner ``(s+t) % P``) is ELL-ized
    in registers — (chunk_b, r_max) col/val/mask planes via one scatter —
    then every A entry (i, j, v) with j in the owner's row range expands to
    the r_max partial products v * B[j, :].  The raw partials are
    CANONICALIZED here (two-key sort + run-head segment-sum: ``_merge_prog``
    only collapses duplicate runs of length <= 2, which canonical operands
    guarantee and raw partials do not), so the accumulator merge upstream
    is an ordinary ``merge_planes("add", ...)``.  Returns the canonical
    partial planes plus B's planes shifted one step around the ring."""
    Cp = Ca * r_max
    dt = jnp.dtype(res_dt)
    name = comm.axis_name
    perm = [(i, (i - 1) % P) for i in range(P)]

    def body(ac, ao, av, bc, bo, bv, t):
        if dist:
            owner = (jax.lax.axis_index(name) + t) % jnp.asarray(P, jnp.int32)
        else:
            owner = jnp.asarray(0, jnp.int32)
        # ELL-ize the resident B chunk (padding bc == chunk_b drops out)
        row_starts = jnp.searchsorted(
            bc, jnp.arange(chunk_b + 1, dtype=bc.dtype)
        ).astype(jnp.int32)
        pos = jnp.arange(Cb, dtype=jnp.int32) - jnp.take(
            row_starts, jnp.clip(bc, 0, chunk_b)
        )
        ell_col = jnp.zeros((chunk_b, r_max), bo.dtype).at[bc, pos].set(bo, mode="drop")
        ell_val = jnp.zeros((chunk_b, r_max), dt).at[bc, pos].set(
            bv.astype(dt), mode="drop"
        )
        ell_ok = jnp.zeros((chunk_b, r_max), bool).at[bc, pos].set(True, mode="drop")
        # expand A entries hitting the chunk to (Ca, r_max) partials
        rel = ao - owner * chunk_b
        hit = (rel >= 0) & (rel < chunk_b) & (ac < comp_pad_a)
        relc = jnp.clip(rel, 0, chunk_b - 1)
        ok = jnp.take(ell_ok, relc, axis=0) & hit[:, None]
        comp = jnp.where(ok, ac[:, None], comp_pad_a).reshape(-1)
        other = jnp.where(ok, jnp.take(ell_col, relc, axis=0), 0).reshape(-1)
        val = jnp.where(
            ok, av.astype(dt)[:, None] * jnp.take(ell_val, relc, axis=0),
            jnp.zeros((), dt),
        ).reshape(-1)
        # canonicalize: sort by (comp, other), collapse each duplicate run
        # into its head via a run-id segment-sum, push the rest to padding
        comp, other, val = jax.lax.sort((comp, other, val), num_keys=2)
        head = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (comp[1:] != comp[:-1]) | (other[1:] != other[:-1]),
            ]
        )
        seg = jnp.cumsum(head.astype(jnp.int32)) - 1
        summed = jax.ops.segment_sum(val, seg, num_segments=Cp)
        keep = head & (comp < comp_pad_a)
        val = jnp.where(keep, jnp.take(summed, seg), jnp.zeros((), dt))
        comp = jnp.where(keep, comp, comp_pad_a)
        other = jnp.where(keep, other, 0)
        comp, other, val = jax.lax.sort((comp, other, val), num_keys=2)
        ln = jnp.searchsorted(comp, jnp.asarray(comp_pad_a, comp.dtype)).astype(
            jnp.int32
        )[None]
        if dist:
            bc = jax.lax.ppermute(bc, name, perm)
            bo = jax.lax.ppermute(bo, name, perm)
            bv = jax.lax.ppermute(bv, name, perm)
        return comp, other, val, ln, bc, bo, bv

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((name,))
    rep = _shard_spec(())
    return _smap(comm, body, (pl,) * 6 + (rep,), (pl,) * 7)


def spgemm_planes(
    a_planes, b_planes, P, Ca, Cb, comp_pad_a, chunk_b, r_max, res_dt, dist, comm
):
    """Output-sparse SpGEMM driver: P ring steps, each producing canonical
    partial triplets that fold into the accumulator through
    ``merge_planes("add", ...)`` — the per-step compaction is the usual
    (P,)-int nnz re-sync, and no dense buffer exists at any point.

    Returns (comp, other, val, lnnz_dev, lnnz_host, C)."""
    from ..resilience.faults import inject

    prog = _spgemm_step_prog(
        comm, P, Ca, Cb, comp_pad_a, chunk_b, r_max, str(jnp.dtype(res_dt)), dist
    )
    Cp = Ca * r_max
    bc, bo, bv = b_planes
    acc = None
    for t in range(P):
        tj = jnp.asarray(t, jnp.int32)
        pc, po, pv, pln, bc, bo, bv = prog(*a_planes, bc, bo, bv, tj)
        # the per-step nnz re-sync is a host allgather — the ring's one
        # collective choke point, so the comm.collective fault site fires
        # here; the loop holds no mutable operand state, so a failed step
        # aborts the whole matmul cleanly and a retry recomputes it
        inject("comm.collective", op="spgemm.nnz_resync", step=t)
        pln_host = tuple(int(v) for v in fetch_host(pln))
        tight = max(max(pln_host), 1)
        if tight < Cp:
            pc, po, pv = _slice_planes_prog(comm, P, Cp, tight, dist)(pc, po, pv)
        if acc is None:
            acc = (pc, po, pv, pln, pln_host, tight)
            continue
        comp, other, val, lnnz_dev, lnnz_host, out_C = merge_planes(
            "add", acc[:3], (pc, po, pv), P, acc[5], tight, comp_pad_a, dist, comm
        )
        acc = (comp, other, val, lnnz_dev, lnnz_host, out_C)
    return acc


# ----------------------------------------------------------------------
# triplet-preserving re-compression (CSR <-> CSC without densifying):
# replicated global planes sorted by the OLD compressed axis are re-keyed
# and re-sorted by the OTHER axis — O(gnnz) plane traffic, never an
# (m, n) dense buffer (ISSUE 16 satellite: SpGEMM inputs keep triplets).
# ----------------------------------------------------------------------
@_functools.lru_cache(maxsize=128)
def _recompress_prog(comm, C: int, extent_old: int, extent_new: int):
    def run(comp_g, other, val):
        real = comp_g < extent_old
        nc = jnp.where(real, other, extent_new).astype(comp_g.dtype)
        no = jnp.where(real, comp_g, 0).astype(other.dtype)
        nv = jnp.where(real, val, jnp.zeros((), val.dtype))
        nc, no, nv = jax.lax.sort((nc, no, nv), num_keys=2)
        rep = _plane_sharding(comm, False)
        return tuple(
            jax.lax.with_sharding_constraint(x, rep) for x in (nc, no, nv)
        )

    return jax.jit(run)


def recompress_planes(comp_g, other, val, extent_old, extent_new, comm):
    """Swap compression axes of replicated global triplets (sorted by the
    old comp axis in, sorted by the new one out; pad sentinel re-keyed to
    ``extent_new``)."""
    return _recompress_prog(comm, int(comp_g.shape[0]), extent_old, extent_new)(
        comp_g, other, val
    )


@_functools.lru_cache(maxsize=256)
def _dense_times_comp_rows_prog(comm, P: int, C: int, comp_pad: int, q: int, n_out: int, dist: bool):
    """E @ A with A row-compressed: shard s owns A's row block, i.e. a
    column slice of E; partials meet in a psum."""

    def body(comp, other, val, e):
        off = (jax.lax.axis_index(comm.axis_name) * comp_pad) if dist else 0
        cols = jnp.take(e, off + comp, axis=1, mode="clip")  # (q, C)
        contrib = (cols * val[None, :]).T  # (C, q)
        out = jax.ops.segment_sum(contrib, other, num_segments=n_out).T  # (q, n_out)
        return jax.lax.psum(out, comm.axis_name) if dist else out

    if not dist:
        return jax.jit(body)
    pl = _shard_spec((comm.axis_name,))
    return _smap(
        comm, body, (pl, pl, pl, _shard_spec((None, None))), _shard_spec((None, None))
    )


@_functools.lru_cache(maxsize=256)
def _dense_times_comp_cols_prog(comm, P: int, C: int, comp_pad: int, q: int, dist: bool):
    """E @ A with A column-compressed: shard s owns whole output columns;
    no collective at all (each shard's comp indices are its own columns)."""

    def body(comp, other, val, e):
        cols = jnp.take(e, other, axis=1, mode="clip")  # (q, C) gather rows of A
        contrib = (cols * val[None, :]).T  # (C, q)
        out = jax.ops.segment_sum(contrib, comp, num_segments=comp_pad + 1)[:comp_pad]
        return out.T  # (q, comp_pad)

    if not dist:
        return jax.jit(body)
    name = comm.axis_name
    pl = _shard_spec((name,))
    return _smap(
        comm, body, (pl, pl, pl, _shard_spec((None, None))), _shard_spec((None, name))
    )
