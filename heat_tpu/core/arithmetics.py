"""Arithmetic operations, analog of heat/core/arithmetics.py (39 exports).

Every function is a thin shim over the generic wrappers in
core/_operations.py; the distributed behavior documented in the reference
(split matching, Allreduce on reduced split axes, Exscan for cumops) falls
out of the sharded-jnp execution model.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import __binary_op as _binary_op
from ._operations import __cum_op as _cum_op
from ._operations import __local_op as _local_op
from ._operations import __reduce_op as _reduce_op
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "copysign",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divide",
    "divmod",
    "floordiv",
    "floor_divide",
    "fmod",
    "gcd",
    "hypot",
    "invert",
    "lcm",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nan_to_num",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=True):
    """Element-wise addition (arithmetics.py:42)."""
    return _binary_op(jnp.add, t1, t2, out, where)


def _check_int_or_bool(t1, t2, name):
    for t in (t1, t2):
        if isinstance(t, DNDarray) and not types.heat_type_is_exact(t.dtype):
            raise TypeError(f"{name} is only supported for integer or boolean types, got {t.dtype.__name__}")
        if isinstance(t, float):
            raise TypeError(f"{name} is only supported for integer or boolean types, got float")


def bitwise_and(t1, t2, out=None, where=True):
    """Element-wise AND of bits (arithmetics.py:175)."""
    _check_int_or_bool(t1, t2, "bitwise_and")
    return _binary_op(jnp.bitwise_and, t1, t2, out, where)


def bitwise_or(t1, t2, out=None, where=True):
    """Element-wise OR of bits (arithmetics.py:252)."""
    _check_int_or_bool(t1, t2, "bitwise_or")
    return _binary_op(jnp.bitwise_or, t1, t2, out, where)


def bitwise_xor(t1, t2, out=None, where=True):
    """Element-wise XOR of bits (arithmetics.py:329)."""
    _check_int_or_bool(t1, t2, "bitwise_xor")
    return _binary_op(jnp.bitwise_xor, t1, t2, out, where)


def bitwise_not(t, out=None):
    """Element-wise bit inversion, alias invert (arithmetics.py:1369)."""
    return invert(t, out)


def copysign(t1, t2, out=None, where=True):
    """Magnitude of t1 with sign of t2 (arithmetics.py:406)."""
    return _binary_op(jnp.copysign, t1, t2, out, where)


def cumprod(t, axis, dtype=None, out=None):
    """Cumulative product along ``axis`` (arithmetics.py:468)."""
    return _cum_op(jnp.cumprod, t, axis, neutral=1, out=out, dtype=dtype)


cumproduct = cumprod


def cumsum(t, axis, dtype=None, out=None):
    """Cumulative sum along ``axis`` (arithmetics.py:526)."""
    return _cum_op(jnp.cumsum, t, axis, neutral=0, out=out, dtype=dtype)


def diff(a, n: int = 1, axis: int = -1, prepend=None, append=None):
    """n-th discrete difference along an axis (arithmetics.py:584)."""
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    if n == 0:
        return a
    from .stride_tricks import sanitize_axis

    axis = sanitize_axis(a.shape, axis)
    dense = a._dense()
    pre = prepend._dense() if isinstance(prepend, DNDarray) else prepend
    app = append._dense() if isinstance(append, DNDarray) else append
    kwargs = {}
    if pre is not None:
        kwargs["prepend"] = jnp.asarray(pre)
    if app is not None:
        kwargs["append"] = jnp.asarray(app)
    result = jnp.diff(dense, n=n, axis=axis, **kwargs)
    split = a.split if a.split is None or a.split < result.ndim else None
    return DNDarray.from_dense(result, split, a.device, a.comm)


def div(t1, t2, out=None, where=True):
    """Element-wise true division (arithmetics.py:717)."""
    return _binary_op(jnp.true_divide, t1, t2, out, where)


divide = div


def divmod(t1, t2, out1=None, out2=None, out=None, where=True):
    """Simultaneous floordiv and mod (arithmetics.py:794)."""
    if out is None:
        out = (out1, out2)
    if not isinstance(out, tuple) or len(out) != 2:
        raise ValueError("out must be a 2-tuple")
    d = floordiv(t1, t2, out[0], where)
    m = mod(t1, t2, out[1], where)
    return d, m


def floordiv(t1, t2, out=None, where=True):
    """Element-wise floor division (arithmetics.py:879)."""
    return _binary_op(jnp.floor_divide, t1, t2, out, where)


floor_divide = floordiv


def fmod(t1, t2, out=None, where=True):
    """C-style remainder (sign of dividend) (arithmetics.py:956)."""
    return _binary_op(jnp.fmod, t1, t2, out, where)


def gcd(t1, t2, out=None, where=True):
    """Greatest common divisor (arithmetics.py:1032)."""
    _check_int_or_bool(t1, t2, "gcd")
    return _binary_op(jnp.gcd, t1, t2, out, where)


def hypot(t1, t2, out=None, where=True):
    """sqrt(t1^2 + t2^2) (arithmetics.py:1102)."""
    for t in (t1, t2):
        if isinstance(t, DNDarray) and types.heat_type_is_exact(t.dtype) or isinstance(t, int):
            raise TypeError("hypot is only supported for floating point types")
    return _binary_op(jnp.hypot, t1, t2, out, where)


def invert(t, out=None):
    """Element-wise bitwise NOT (arithmetics.py:1369)."""
    if isinstance(t, DNDarray) and not types.heat_type_is_exact(t.dtype):
        raise TypeError(f"invert is only supported for integer or boolean types, got {t.dtype.__name__}")
    return _local_op(jnp.invert, t, out, no_cast=True)


def lcm(t1, t2, out=None, where=True):
    """Least common multiple (arithmetics.py:1444)."""
    _check_int_or_bool(t1, t2, "lcm")
    return _binary_op(jnp.lcm, t1, t2, out, where)


def left_shift(t1, t2, out=None, where=True):
    """Shift bits left (arithmetics.py:1512)."""
    _check_int_or_bool(t1, t2, "left_shift")
    return _binary_op(jnp.left_shift, t1, t2, out, where)


def mod(t1, t2, out=None, where=True):
    """Python-style modulo (sign of divisor), alias remainder
    (arithmetics.py:1582)."""
    return _binary_op(jnp.mod, t1, t2, out, where)


remainder = mod


def mul(t1, t2, out=None, where=True):
    """Element-wise multiplication (arithmetics.py:1660)."""
    return _binary_op(jnp.multiply, t1, t2, out, where)


multiply = mul


def nan_to_num(t, nan: float = 0.0, posinf=None, neginf=None, out=None):
    """Replace NaN/Inf with finite numbers (arithmetics.py:1738)."""
    return _local_op(jnp.nan_to_num, t, out, no_cast=True, nan=nan, posinf=posinf, neginf=neginf)


def nanprod(a, axis=None, out=None, keepdims=False):
    """Product treating NaN as 1 (arithmetics.py:1791)."""
    return _reduce_op(jnp.nanprod, a, axis, neutral=1, out=out, keepdims=keepdims)


def nansum(a, axis=None, out=None, keepdims=False):
    """Sum treating NaN as 0 (arithmetics.py:1836)."""
    return _reduce_op(jnp.nansum, a, axis, neutral=0, out=out, keepdims=keepdims)


def neg(a, out=None):
    """Element-wise negation (arithmetics.py:1880)."""
    return _local_op(jnp.negative, a, out, no_cast=True)


negative = neg


def pos(a, out=None):
    """Element-wise +a (copy) (arithmetics.py:1928)."""
    return _local_op(jnp.positive, a, out, no_cast=True)


positive = pos


def pow(t1, t2, out=None, where=True):
    """Element-wise power (arithmetics.py:1976)."""
    return _binary_op(jnp.power, t1, t2, out, where)


power = pow


def prod(a, axis=None, out=None, keepdims=False):
    """Product of elements over axes (arithmetics.py:2054)."""
    return _reduce_op(jnp.prod, a, axis, neutral=1, out=out, keepdims=keepdims)


def right_shift(t1, t2, out=None, where=True):
    """Shift bits right (arithmetics.py:2100)."""
    _check_int_or_bool(t1, t2, "right_shift")
    return _binary_op(jnp.right_shift, t1, t2, out, where)


def sub(t1, t2, out=None, where=True):
    """Element-wise subtraction (arithmetics.py:2170)."""
    return _binary_op(jnp.subtract, t1, t2, out, where)


subtract = sub


def sum(a, axis=None, out=None, keepdims=False):
    """Sum of elements over axes (arithmetics.py:2248)."""
    return _reduce_op(jnp.sum, a, axis, neutral=0, out=out, keepdims=keepdims)


# ----------------------------------------------------------------------
# in-place variants (reference: `_`-suffixed functions bound as DNDarray
# methods and `__i*__` dunders, e.g. add_ arithmetics.py:135,195-196).
# Functional substrate underneath: compute out-of-place, then swap the
# backing array with a cast-safety check (dndarray._iop).  Under the
# dispatch layer the out-of-place result is a PENDING chain, so `a += b`
# compiles as one cached executable whose output aliases a's donated
# backing buffer when it is provably unshared (core/dispatch.cast_store).
# ----------------------------------------------------------------------
from .dndarray import _iop as __iop  # noqa: E402


def _inplace(t1, result) -> DNDarray:
    if not isinstance(t1, DNDarray):
        raise TypeError(f"in-place operations require a DNDarray target, got {type(t1)}")
    return __iop(t1, result)


def add_(t1, t2):
    """In-place element-wise addition (arithmetics.py:135)."""
    return _inplace(t1, add(t1, t2))


def bitwise_and_(t1, t2):
    """In-place bitwise AND (arithmetics.py:265)."""
    return _inplace(t1, bitwise_and(t1, t2))


def bitwise_or_(t1, t2):
    """In-place bitwise OR (arithmetics.py:415)."""
    return _inplace(t1, bitwise_or(t1, t2))


def bitwise_xor_(t1, t2):
    """In-place bitwise XOR (arithmetics.py:556)."""
    return _inplace(t1, bitwise_xor(t1, t2))


def copysign_(t1, t2):
    """In-place copysign (arithmetics.py:676)."""
    return _inplace(t1, copysign(t1, t2))


def cumprod_(t, axis):
    """In-place cumulative product (arithmetics.py:~800)."""
    return _inplace(t, cumprod(t, axis))


cumproduct_ = cumprod_


def cumsum_(t, axis):
    """In-place cumulative sum (arithmetics.py:~870)."""
    return _inplace(t, cumsum(t, axis))


def div_(t1, t2):
    """In-place true division (arithmetics.py:~1100)."""
    return _inplace(t1, div(t1, t2))


divide_ = div_


def floordiv_(t1, t2):
    """In-place floor division (arithmetics.py:~1330)."""
    return _inplace(t1, floordiv(t1, t2))


floor_divide_ = floordiv_


def fmod_(t1, t2):
    """In-place C-style remainder (arithmetics.py:~1000)."""
    return _inplace(t1, fmod(t1, t2))


def gcd_(t1, t2):
    """In-place greatest common divisor (arithmetics.py:~1070)."""
    return _inplace(t1, gcd(t1, t2))


def hypot_(t1, t2):
    """In-place hypot (arithmetics.py:~1140)."""
    return _inplace(t1, hypot(t1, t2))


def invert_(t):
    """In-place bitwise NOT (arithmetics.py:~1410)."""
    return _inplace(t, invert(t))


bitwise_not_ = invert_


def lcm_(t1, t2):
    """In-place least common multiple (arithmetics.py:~1480)."""
    return _inplace(t1, lcm(t1, t2))


def left_shift_(t1, t2):
    """In-place left shift (arithmetics.py:~1550)."""
    return _inplace(t1, left_shift(t1, t2))


def mod_(t1, t2):
    """In-place modulo (arithmetics.py:~1620)."""
    return _inplace(t1, mod(t1, t2))


remainder_ = mod_


def mul_(t1, t2):
    """In-place multiplication (arithmetics.py:~1700)."""
    return _inplace(t1, mul(t1, t2))


multiply_ = mul_


def nan_to_num_(t, nan: float = 0.0, posinf=None, neginf=None):
    """In-place NaN/Inf replacement (arithmetics.py:~1780)."""
    return _inplace(t, nan_to_num(t, nan, posinf, neginf))


def neg_(t):
    """In-place negation (arithmetics.py:~1900)."""
    return _inplace(t, neg(t))


negative_ = neg_


def pos_(t):
    """In-place +t (arithmetics.py:~1950)."""
    return _inplace(t, pos(t))


positive_ = pos_


def pow_(t1, t2):
    """In-place power (arithmetics.py:~2010)."""
    return _inplace(t1, pow(t1, t2))


power_ = pow_


def right_shift_(t1, t2):
    """In-place right shift (arithmetics.py:~2140)."""
    return _inplace(t1, right_shift(t1, t2))


def sub_(t1, t2):
    """In-place subtraction (arithmetics.py:~2210)."""
    return _inplace(t1, sub(t1, t2))


subtract_ = sub_


# method + dunder bindings, mirroring the reference's module-bottom
# assignments (arithmetics.py:195-196 etc.)
for _name in (
    "add_", "bitwise_and_", "bitwise_not_", "bitwise_or_", "bitwise_xor_",
    "copysign_", "cumprod_", "cumproduct_", "cumsum_", "div_", "divide_",
    "floordiv_", "floor_divide_", "fmod_", "gcd_", "hypot_", "invert_",
    "lcm_", "left_shift_", "mod_", "mul_", "multiply_", "nan_to_num_",
    "neg_", "negative_", "pos_", "positive_", "pow_", "power_",
    "remainder_", "right_shift_", "sub_", "subtract_",
):
    setattr(DNDarray, _name, globals()[_name])
DNDarray.__ilshift__ = left_shift_
DNDarray.__irshift__ = right_shift_
DNDarray.__iand__ = bitwise_and_
DNDarray.__ior__ = bitwise_or_
DNDarray.__ixor__ = bitwise_xor_

__all__ += [
    "add_", "bitwise_and_", "bitwise_not_", "bitwise_or_", "bitwise_xor_",
    "copysign_", "cumprod_", "cumproduct_", "cumsum_", "div_", "divide_",
    "floordiv_", "floor_divide_", "fmod_", "gcd_", "hypot_", "invert_",
    "lcm_", "left_shift_", "mod_", "mul_", "multiply_", "nan_to_num_",
    "neg_", "negative_", "pos_", "positive_", "pow_", "power_",
    "remainder_", "right_shift_", "sub_", "subtract_",
]


# ---- numpy extensions beyond the reference's checklist -------------------

true_divide = div


def float_power(t1, t2, out=None, where=True):
    """t1**t2 computed in at least float64 precision (numpy extension)."""
    return _binary_op(jnp.float_power, t1, t2, out, where)


def heaviside(t1, t2, out=None, where=True):
    """Heaviside step function with value t2 at 0 (numpy extension)."""
    return _binary_op(jnp.heaviside, t1, t2, out, where)


def _nancumsum_op(a, axis):
    return jnp.nancumsum(a, axis=axis)


def _nancumprod_op(a, axis):
    return jnp.nancumprod(a, axis=axis)


def nancumsum(t, axis, dtype=None, out=None):
    """Cumulative sum treating NaN as zero (numpy extension).

    Module-level op callable (not a per-call lambda): the dispatch-layer
    executable cache keys on the callable's identity, and a fresh lambda
    per call would miss forever."""
    return _cum_op(_nancumsum_op, t, axis, 0, out, dtype)


def nancumprod(t, axis, dtype=None, out=None):
    """Cumulative product treating NaN as one (numpy extension)."""
    return _cum_op(_nancumprod_op, t, axis, 1, out, dtype)


def ediff1d(ary, to_end=None, to_begin=None):
    """Differences of the flattened array, with optional end caps (numpy
    extension).  1-D result; distributed along axis 0 when the input is
    split."""
    if not isinstance(ary, DNDarray):
        raise TypeError(f"expected ary to be a DNDarray, but was {type(ary)}")
    te = to_end._dense() if isinstance(to_end, DNDarray) else to_end
    tb = to_begin._dense() if isinstance(to_begin, DNDarray) else to_begin
    res = jnp.ediff1d(ary._dense().ravel(), to_end=te, to_begin=tb)
    return DNDarray.from_dense(res, 0 if ary.split is not None else None, ary.device, ary.comm)


def gradient(f, *varargs, axis=None, edge_order: int = 1):
    """Second-order central differences (numpy extension).

    Supports scalar spacing per axis (``varargs``); returns one DNDarray
    per requested axis (a single DNDarray for a single axis).
    """
    if not isinstance(f, DNDarray):
        raise TypeError(f"expected f to be a DNDarray, but was {type(f)}")
    if edge_order != 1:
        raise NotImplementedError("gradient: only edge_order=1 is supported")
    spacing = [v._dense() if isinstance(v, DNDarray) else v for v in varargs]
    res = jnp.gradient(f._dense(), *spacing, axis=axis)
    single = not isinstance(res, (list, tuple))
    outs = [DNDarray.from_dense(r, f.split, f.device, f.comm) for r in ([res] if single else res)]
    return outs[0] if single else outs


def trapz(y, x=None, dx: float = 1.0, axis: int = -1):
    """Trapezoidal-rule integral along an axis (numpy extension)."""
    if not isinstance(y, DNDarray):
        raise TypeError(f"expected y to be a DNDarray, but was {type(y)}")
    xs = x._dense() if isinstance(x, DNDarray) else x
    trapezoid = getattr(jnp, "trapezoid", None) or jnp.trapz
    res = trapezoid(y._dense(), x=xs, dx=dx, axis=axis)
    ax = axis % y.ndim
    if y.split is None or y.split == ax:
        out_split = None
    else:
        out_split = y.split - (1 if ax < y.split else 0)
    return DNDarray.from_dense(res, out_split, y.device, y.comm)


trapezoid = trapz


def interp(x, xp, fp, left=None, right=None, period=None):
    """1-D linear interpolation of x into sample points (xp, fp) (numpy
    extension).  The sample table is replicated; the query array keeps its
    distribution."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    xpd = xp._dense() if isinstance(xp, DNDarray) else jnp.asarray(xp)
    fpd = fp._dense() if isinstance(fp, DNDarray) else jnp.asarray(fp)
    res = jnp.interp(x._dense(), xpd, fpd, left=left, right=right, period=period)
    return DNDarray.from_dense(res, x.split, x.device, x.comm)


__all__ += [
    "ediff1d", "float_power", "gradient", "heaviside", "interp",
    "nancumprod", "nancumsum", "trapezoid", "trapz", "true_divide",
]
