"""Out-of-core HDF5 dataset, analog of heat/utils/data/partial_dataset.py.

The reference's ``PartialH5Dataset`` (partial_dataset.py:32) threads HDF5
chunk reads and overlaps load/convert with training via a custom loader
iterator (:224) fed by daemon threads running :func:`queue_thread`
(partial_dataset.py:20).  Here the same structure holds — a loader thread
reads the next HDF5 slab while the device executes the previous batch —
and the staging step is now *shard-aware* (overlap layer, docs/overlap.md):
each window is ``jax.device_put`` with the canonical split
``NamedSharding`` from the dataset's communication, so the host->device
copy AND the resharding ride behind compute instead of inside the
consuming step.  Windows handed out that were already staged when the
consumer asked count as ``prefetch_hits`` on the shared overlap stats
surface; underruns count as ``prefetch_misses``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis import tsan as _tsan
from ...core.dndarray import DNDarray
from ..overlap import _bump

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter", "queue_thread"]

try:
    import h5py

    _H5 = True
except ImportError:  # pragma: no cover
    _H5 = False


def queue_thread(q: "queue.Queue") -> None:
    """Worker loop for loader threads (partial_dataset.py:20): pop either a
    ``(func, *args)`` tuple or a bare callable off the queue, run it, and
    mark the item done.  ``None`` shuts the worker down."""
    while True:
        items = q.get()
        if items is None:
            q.task_done()
            return
        if isinstance(items, tuple):
            items[0](*items[1:])
        else:
            items()
        q.task_done()


class PartialH5Dataset:
    """Stream a large HDF5 dataset in windows (partial_dataset.py:32).

    ``comm`` names the mesh the staged windows are sharded over (default:
    the process-wide communication); divisible windows land with the
    canonical split-0 ``NamedSharding``, ragged ones on the default
    device.  Subclasses that override :meth:`read_window` (and set
    ``length``/``load_length``/``transforms``/``dataset_names``/``comm``)
    can feed the loader iterator from any source — the tests drive it
    from in-memory arrays without h5py.
    """

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names: Optional[List[str]] = None,
        initial_load: int = 7000,
        load_length: int = 1000,
        use_gpu: bool = True,
        np_buffer: bool = True,
        np_buffer_dataset_names: Optional[List[str]] = None,
        transforms=None,
    ):
        if not _H5:
            raise RuntimeError("h5py is not available")
        self.file = file
        self.comm = comm
        self.dataset_names = dataset_names or ["data"]
        self.initial_load = initial_load
        self.load_length = load_length
        self.transforms = transforms
        with h5py.File(file, "r") as f:
            self.length = f[self.dataset_names[0]].shape[0]

    def read_window(self, start: int, stop: int) -> List[np.ndarray]:
        """Read one ``[start, stop)`` slab of every named dataset from the
        backing store (runs on the loader thread)."""
        with h5py.File(self.file, "r") as f:
            return [np.asarray(f[name][start:stop]) for name in self.dataset_names]

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> "PartialH5DataLoaderIter":
        return PartialH5DataLoaderIter(self)


class PartialH5DataLoaderIter:
    """Windowed loader iterator (partial_dataset.py:224).

    A daemon thread running :func:`queue_thread` reads window ``i+1`` from
    the backing store while window ``i`` is being consumed, so disk latency
    hides behind compute the way the reference's loader/convert threads do;
    the thread also stages each window on device with the canonical split
    sharding, so the transfer overlaps too.
    """

    #: close() drain deadline — a loader thread wedged in a backing-store
    #: read beyond this is abandoned (daemon threads die with the process)
    _CLOSE_TIMEOUT_S = 10.0

    def __init__(self, dataset: PartialH5Dataset):
        from ...parallel.comm import sanitize_comm

        self._ds = dataset
        self._comm = sanitize_comm(getattr(dataset, "comm", None))
        self._split_sharding = self._comm.sharding(0)
        self._pos = 0
        self._work: "queue.Queue" = queue.Queue()
        self._ready: "queue.Queue" = queue.Queue(maxsize=2)
        # close() races itself: the consumer's StopIteration path, an
        # error path and __del__ (GC, possibly on another thread) can
        # all retire the worker concurrently — exactly one caller may
        # claim the thread handle
        self._lifecycle = _tsan.register_lock("data.partial_loader")
        self._thread = threading.Thread(target=queue_thread, args=(self._work,), daemon=True)
        self._thread.start()
        self._windows_queued = 0
        self._queue_next_read()  # prime the pipeline

    def _stage(self, chunk: np.ndarray):
        """Start the host->device copy of one window, sharded over the
        canonical split when the extent tiles the mesh (non-blocking:
        JAX async dispatch owns the transfer)."""
        if chunk.ndim >= 1 and chunk.shape[0] % self._comm.size == 0:
            return jax.device_put(chunk, self._split_sharding)
        return jnp.asarray(chunk)

    def _read_window(self, start: int, stop: int) -> None:
        try:
            out = []
            for chunk in self._ds.read_window(start, stop):
                arr = self._stage(chunk)
                if self._ds.transforms is not None and callable(self._ds.transforms):
                    arr = self._ds.transforms(arr)
                out.append(arr)
            self._ready.put(out[0] if len(out) == 1 else tuple(out))
        except BaseException as e:  # lint: allow H501(loader error surfaced on the consumer side)
            self._ready.put(e)

    def _queue_next_read(self) -> None:
        if self._pos >= self._ds.length:
            return
        stop = min(self._pos + self._ds.load_length, self._ds.length)
        self._work.put((self._read_window, self._pos, stop))
        self._pos = stop
        self._windows_queued += 1

    def close(self) -> None:
        """Retire the worker thread (safe to call more than once).

        The loader thread may be blocked in ``_ready.put`` with the ready
        queue full (two staged windows nobody consumed); the sentinel
        alone would never reach it.  Drain pending windows until the
        thread consumes the sentinel and exits, bounded by a deadline for
        a thread wedged inside a backing-store read."""
        lifecycle = getattr(self, "_lifecycle", None)
        if lifecycle is None:  # __init__ failed before the worker existed
            return
        with lifecycle:
            _tsan.note_access("data.partial_loader.state")
            t, self._thread = self._thread, None
        if t is None:
            return
        self._work.put(None)
        deadline = time.monotonic() + self._CLOSE_TIMEOUT_S
        while t.is_alive() and time.monotonic() < deadline:
            try:
                self._ready.get_nowait()  # unblock a full-queue put
            except queue.Empty:
                pass
            t.join(timeout=0.02)
        self._windows_queued = 0

    def __del__(self):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        if self._windows_queued == 0 or self._thread is None:
            self.close()
            raise StopIteration
        _bump("prefetch_misses" if self._ready.empty() else "prefetch_hits")
        batch = self._ready.get()
        self._windows_queued -= 1
        if isinstance(batch, BaseException):
            self.close()
            raise batch
        self._queue_next_read()  # overlap the next read with consumption
        return batch
