"""numpy.linalg parity extensions beyond the reference's linalg set.

The reference implements det/inv/qr/svd/solve_triangular and leaves the
rest of numpy.linalg uncovered; these close the block.  Everything runs
on the dense global view (GSPMD distributes the batched/matmul parts);
`eig`/`eigvals` have no TPU kernel in XLA and run on the in-process CPU
backend.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..dndarray import DNDarray

__all__ = [
    "cholesky",
    "cond",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "lstsq",
    "matrix_power",
    "matrix_rank",
    "multi_dot",
    "pinv",
    "slogdet",
    "solve",
    "tensorinv",
    "tensorsolve",
]


def _d(x):
    if isinstance(x, DNDarray):
        d = x._dense()
        if not jnp.issubdtype(d.dtype, jnp.inexact):
            d = d.astype(jnp.float32)
        return d
    return jnp.asarray(x)


def _ref(*xs):
    for x in xs:
        if isinstance(x, DNDarray):
            return x
    return None


def _wrap(result, *operands):
    from ..napi import _auto_split

    ref = _ref(*operands)
    if ref is None:
        return DNDarray.from_dense(result, None, None, None)
    return DNDarray.from_dense(result, _auto_split(result, ref), ref.device, ref.comm)


def _on_cpu(fn, *arrays):
    """Run fn on the in-process CPU backend (for factorizations without a
    TPU kernel: nonsymmetric eig)."""
    cpu = jax.devices("cpu")[0]
    moved = [jax.device_put(a, cpu) for a in arrays]
    return fn(*moved)


def cholesky(a):
    """Lower-triangular Cholesky factor of an SPD matrix.

    2-D split matrices run the distributed blocked right-looking program
    (factorizations.py): the matrix stays row-sharded, per-device memory
    O(n*b) — a split matrix larger than one device's memory factorizes."""
    from .factorizations import cholesky_dist, supports_dist_factor

    if isinstance(a, DNDarray) and supports_dist_factor(a):
        return cholesky_dist(a)
    return _wrap(jnp.linalg.cholesky(_d(a)), a)


def cond(x, p=None):
    """Condition number with respect to norm ``p``.

    .. note:: Beyond the reference's surface; computed as a global
       ``jnp.linalg`` call on the dense view — a SPLIT operand larger
       than one device's memory gathers here (no distributed
       eigensolver yet; see docs/design.md).
    """
    return _wrap(jnp.linalg.cond(_d(x), p=p), x)


def eigh(a, UPLO: str = "L"):
    """Eigendecomposition of a symmetric/Hermitian matrix.

    .. note:: Beyond the reference's surface; computed as a global
       ``jnp.linalg`` call on the dense view — a SPLIT operand larger
       than one device's memory gathers here (no distributed
       eigensolver yet; see docs/design.md).
    """
    w, v = jnp.linalg.eigh(_d(a), UPLO=UPLO)
    return _wrap(w, a), _wrap(v, a)


def eigvalsh(a, UPLO: str = "L"):
    """Eigenvalues of a symmetric/Hermitian matrix (gathers a split
    operand to the dense view — see the note on :func:`eigh`)."""
    return _wrap(jnp.linalg.eigvalsh(_d(a), UPLO=UPLO), a)


def eig(a):
    """General eigendecomposition (no TPU kernel in XLA: runs on the
    in-process CPU backend; complex output)."""
    w, v = _on_cpu(jnp.linalg.eig, _d(a))
    return _wrap(w, a), _wrap(v, a)


def eigvals(a):
    return _wrap(_on_cpu(jnp.linalg.eigvals, _d(a)), a)


def _qr_full_rank(r_small) -> bool:
    """Numerical full-rank check on R's diagonal (one tiny host fetch);
    the TS-QR normal route is only valid at full rank — rank-deficient
    systems fall back to the SVD-based paths."""
    rd = np.abs(np.asarray(jnp.diagonal(r_small)))
    n = rd.shape[0]
    eps = float(jnp.finfo(r_small.dtype).eps)
    return bool(rd.min() > rd.max() * eps * max(n, 1) * 16)


def _tall_split0(a) -> bool:
    """Tall row-split matrix on a mesh: the TS-QR normal route applies
    (each device block has at least as many rows as columns)."""
    return (
        isinstance(a, DNDarray)
        and a.ndim == 2
        and a.split == 0
        and a.comm.size > 1
        and a.shape[0] // a.comm.size >= a.shape[1]
    )


def lstsq(a, b, rcond=None):
    """Least-squares solve; returns (x, residuals, rank, singular values).

    Tall row-split systems route through the distributed TS-QR
    (qr.py shard_map tree merge): x = R^-1 Q^T b, with only the small
    (n, n) R replicated — the reference capability without a gather.
    ``rank`` is a lazy 0-d array — no host sync is forced inside the call
    (one full link round-trip on a tunneled chip); use ``int(rank)`` to
    materialize it."""
    ref = _ref(a, b)
    if rcond is None and _tall_split0(a) and isinstance(b, DNDarray):
        from . import basics
        from .qr import qr as ht_qr

        q, rm = ht_qr(a)
        r_small = rm._dense()
        if _qr_full_rank(r_small):
            qtb = basics.matmul(
                basics.transpose(q), b.reshape((b.shape[0], 1)) if b.ndim == 1 else b
            )
            x = jax.scipy.linalg.solve_triangular(r_small, qtb._dense(), lower=False)
            if b.ndim == 1:
                x = x[:, 0]
            # numpy contract: residual sum of squares and the TRUE spectrum
            # (singular values of A == singular values of R)
            r_vec = _d(b) - jnp.matmul(_d(a), x)
            rss = jnp.sum(r_vec * r_vec, axis=0)
            resid = rss.reshape((1,)) if b.ndim == 1 else rss
            rank = jnp.asarray(a.shape[1])
            sv = jnp.linalg.svd(r_small, compute_uv=False)
            return (_wrap(x, ref), _wrap(resid, ref), _wrap(rank, ref), _wrap(sv, ref))
    x, resid, rank, sv = jnp.linalg.lstsq(_d(a), _d(b), rcond=rcond)
    return (_wrap(x, ref), _wrap(resid, ref), _wrap(rank, ref), _wrap(sv, ref))


def matrix_power(a, n: int):
    """Repeated matrix product (gathers a split operand to the dense
    view — see the note on :func:`eigh`)."""
    return _wrap(jnp.linalg.matrix_power(_d(a), n), a)


def matrix_rank(a, tol=None):
    """Matrix rank as a lazy 0-d array (no forced host sync; ``int()`` it
    to materialize).  Gathers a split operand to the dense view for the
    SVD — see the note on :func:`eigh`."""
    return _wrap(jnp.linalg.matrix_rank(_d(a), rtol=None if tol is None else tol), a)


def multi_dot(arrays):
    """Chained matmul with optimal association order."""
    dense = [_d(a) for a in arrays]
    return _wrap(jnp.linalg.multi_dot(dense), *list(arrays))


def pinv(a, rcond=None, hermitian: bool = False):
    """Moore-Penrose pseudo-inverse.

    Tall full-rank row-split matrices: A+ = R^-1 Q^T over the distributed
    TS-QR (only the small R is replicated; Q stays row-sharded)."""
    if rcond is None and not hermitian and _tall_split0(a):
        from . import basics
        from .qr import qr as ht_qr

        q, rm = ht_qr(a)
        r_small = rm._dense()
        if _qr_full_rank(r_small):
            rinv = jnp.linalg.inv(r_small)  # (n, n), replicated
            rinv_arr = DNDarray.from_dense(rinv, None, a.device, a.comm)
            return basics.matmul(rinv_arr, basics.transpose(q))
    return _wrap(jnp.linalg.pinv(_d(a), rtol=rcond, hermitian=hermitian), a)


def slogdet(a):
    """Sign and log|det|."""
    sign, logabs = jnp.linalg.slogdet(_d(a))
    return _wrap(sign, a), _wrap(logabs, a)


def solve(a, b):
    """Solve the linear system a x = b.

    A 2-D split square ``a`` takes the distributed LU + blocked
    substitution path; everything else (batched, replicated) uses XLA."""
    from .factorizations import solve_dist, supports_dist_factor

    if (
        isinstance(a, DNDarray)
        and supports_dist_factor(a)
        and isinstance(b, DNDarray)
        and b.ndim in (1, 2)
    ):
        return solve_dist(a, b)
    return _wrap(jnp.linalg.solve(_d(a), _d(b)), _ref(a, b))


def tensorinv(a, ind: int = 2):
    return _wrap(jnp.linalg.tensorinv(_d(a), ind=ind), a)


def tensorsolve(a, b, axes=None):
    return _wrap(jnp.linalg.tensorsolve(_d(a), _d(b), axes=axes), _ref(a, b))
