"""Manipulation continuous benchmarks (reference: benchmarks/cb/manipulations.py)."""

# flake8: noqa
from typing import List, Optional

import heat_tpu as ht
from monitor import monitor


@monitor()
def concatenate(arrays):
    return ht.concatenate(arrays, axis=1)


@monitor()
def reshape(arrays, row_target: int):
    out = []
    for array in arrays:
        out.append(ht.reshape(array, (row_target, -1), new_split=1))
    return out


@monitor()
def resplit(array, new_splits: List[Optional[int]]):
    out = []
    for new_split in new_splits:
        out.append(ht.resplit(array, axis=new_split))
    return out


@monitor()
def sort_psrs(array):
    return ht.sort(array)[0]


@monitor()
def topk_merge(array):
    return ht.topk(array, 32)[0]


def run_manipulation_benchmarks(scale: float = 1.0):
    sizes = [max(int(s * scale), 128) for s in (10000, 20000, 40000)]
    rows = max(int(1000 * scale), 64)

    # reference reshapes every (1000, s) array to 1e7 rows; the scale-free
    # invariant is "rows x smallest size" so the -1 column count stays integral
    arrays = [ht.zeros((rows, size), split=1) for size in sizes]
    reshape(arrays, rows * sizes[0])

    arrays = [
        ht.zeros((rows, size), split=None if i == 1 else 1) for i, size in enumerate(sizes)
    ]
    concatenate(arrays)

    if ht.get_comm().size > 1:
        shape = [
            max(int(100 * scale), 8),
            max(int(50 * scale), 4),
            max(int(50 * scale), 4),
            max(int(20 * scale), 4),
            max(int(86 * scale), 8),
        ]
        n_elements = 1
        for s in shape:
            n_elements *= s
        array = ht.reshape(ht.arange(0, n_elements, split=0, dtype=ht.float32), shape)
        resplit(array, [None, 2, 4])

    # PSRS sample-sort + distributed top-k (reference sorts in its
    # manipulations suite; these are the round-2 no-gather collectives)
    import jax as _jax

    n_sort = max(int((1 << 22) * scale), 1 << 12)
    if ht.get_comm().size > 1 and _jax.config.read("jax_enable_x64"):
        from heat_tpu.core import sample_sort as _ss

        saved = _ss.SAMPLE_SORT_THRESHOLD
        _ss.SAMPLE_SORT_THRESHOLD = 1
        try:
            data = ht.random.rand(n_sort, split=0).astype(ht.float32)
            sort_psrs(data)
        finally:
            _ss.SAMPLE_SORT_THRESHOLD = saved
        topk_merge(data)
