"""KMedoids clustering, analog of heat/cluster/kmedoids.py (kmedoids.py:11).

Centers snap to the closest actual data point (medoid) after a
KMeans-style mean update, matching the reference's variant.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


@partial(jax.jit, static_argnames=("k", "max_iter"))
def _kmedoids_loop(dense: jax.Array, centers: jax.Array, k: int, max_iter: int):
    """Whole KMedoids fit as one on-device while_loop (medoids are data
    points, so the stop test is exact zero movement)."""

    def update(c):
        d = jnp.sum(jnp.abs(dense[:, None, :] - c[None, :, :]), axis=-1)
        labels = jnp.argmin(d, axis=1)
        new_rows = []
        for j in range(k):
            mask = labels == j
            cnt = jnp.sum(mask)
            mean = jnp.where(
                cnt > 0,
                jnp.sum(jnp.where(mask[:, None], dense, 0.0), axis=0) / jnp.maximum(cnt, 1),
                c[j],
            )
            dm = jnp.sum(jnp.abs(dense - mean[None, :]), axis=1)
            dm_in = jnp.where(mask, dm, jnp.inf)
            dm = jnp.where(cnt > 0, dm_in, dm)
            new_rows.append(dense[jnp.argmin(dm)])
        return jnp.stack(new_rows)

    def cond(carry):
        c, i, shift = carry
        return jnp.logical_and(i < max_iter, shift > 0.0)

    def body(carry):
        c, i, _ = carry
        new = update(c)
        shift = jnp.sum(jnp.abs(new - c)).astype(jnp.float32)
        return new, i + 1, shift

    init = (centers, jnp.int32(0), jnp.asarray(jnp.inf, jnp.float32))
    c, i, shift = jax.lax.while_loop(cond, body, init)
    return c, i, shift


class KMedoids(_KCluster):
    """Manhattan-metric k-medoids (kmedoids.py:11)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        if init == "kmedoids++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
            checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            resume_from=resume_from,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Mean update then snap to the nearest sample (kmedoids.py:70+)."""
        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        labels = matching_centroids._dense()
        old = self._cluster_centers._dense()
        new_centers = []
        for c in range(self.n_clusters):
            mask = labels == c
            cnt = jnp.sum(mask)
            mean = jnp.where(
                cnt > 0,
                jnp.sum(jnp.where(mask[:, None], dense, 0.0), axis=0) / jnp.maximum(cnt, 1),
                old[c],
            )
            # snap to closest member of the cluster (or global closest when empty)
            d = jnp.sum(jnp.abs(dense - mean[None, :]), axis=1)
            d = jnp.where(mask, d, jnp.inf)
            d = jnp.where(cnt > 0, d, jnp.sum(jnp.abs(dense - mean[None, :]), axis=1))
            new_centers.append(dense[jnp.argmin(d)])
        new = jnp.stack(new_centers)
        return DNDarray.from_dense(new, None, x.device, x.comm)

    def fit(self, x: DNDarray) -> "KMedoids":
        """Iterate until the medoids stop moving (kmedoids.py:~110)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        if self._resumable:
            dtype = dense.dtype

            def run_chunk(centers, n):
                return _kmedoids_loop(dense, jnp.asarray(centers, dtype), self.n_clusters, n)

            def init_centers():
                self._initialize_cluster_centers(x)
                return self._cluster_centers._dense().astype(dtype)

            new, n_iter = self._run_resumable(run_chunk, init_centers, "kmedoids.iter")
            new = jnp.asarray(new, dtype)
        else:
            self._initialize_cluster_centers(x)
            centers = self._cluster_centers._dense().astype(dense.dtype)
            new, n_iter, _ = _kmedoids_loop(dense, centers, self.n_clusters, self.max_iter)
        self._cluster_centers = DNDarray.from_dense(new, None, x.device, x.comm)
        self._n_iter = n_iter  # lazy host conversion in n_iter_
        self._labels = self._assign_to_cluster(x, eval_functional_value=True)
        return self
