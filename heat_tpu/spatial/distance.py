"""Pairwise distance computations, analog of heat/spatial/distance.py.

The reference's ``_dist`` (distance.py:209-747) is an explicit ring: each of
ceil(p/2) rounds sends a standing row-block to rank+iter and computes one
tile, exploiting symmetry when Y is X.  Under GSPMD the same schedule falls
out of one sharded expression: with X row-split, ``cdist`` keeps the output
row-split and XLA streams the replicated/other operand across shards over
ICI.  Metrics mirror _euclidian/_gaussian/_manhattan (distance.py:17-135).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["cdist", "cdist_small", "manhattan", "rbf"]


def _pairwise_sqeuclidean(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """||x_i - y_j||^2 via the expanded form (one MXU matmul instead of the
    reference's broadcast-subtract tile, distance.py:17)."""
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    y_sq = jnp.sum(y * y, axis=1, keepdims=True).T
    cross = jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    d = x_sq + y_sq - 2.0 * cross
    return jnp.maximum(d, 0.0)


def _pairwise_direct(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Exact broadcast-subtract form (distance.py:17-40).  More accurate than
    the expanded form for near-duplicate points (no catastrophic cancellation)
    at the cost of an O(n*m*f) intermediate that XLA fuses into the reduce."""
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _prep(X: DNDarray, Y: Optional[DNDarray]):
    sanitize_in(X)
    if X.ndim != 2:
        raise NotImplementedError(f"X should be a 2D DNDarray, but is {X.ndim}D")
    if X.split is not None and X.split != 0:
        raise NotImplementedError(f"Splittings other than 0 or None currently not supported, got {X.split}")
    xd = X._dense()
    if not types.heat_type_is_inexact(X.dtype):
        xd = xd.astype(jnp.float32)
    if Y is None:
        return xd, xd
    sanitize_in(Y)
    if Y.ndim != 2:
        raise NotImplementedError(f"Y should be a 2D DNDarray, but is {Y.ndim}D")
    if X.shape[1] != Y.shape[1]:
        raise ValueError(f"X and Y must have the same number of features, got {X.shape[1]} and {Y.shape[1]}")
    yd = Y._dense()
    if not types.heat_type_is_inexact(Y.dtype):
        yd = yd.astype(jnp.float32)
    return xd, yd


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Euclidean distance matrix (distance.py:136)."""
    xd, yd = _prep(X, Y)
    if quadratic_expansion:
        d = jnp.sqrt(_pairwise_sqeuclidean(xd, yd))
    else:
        d = _pairwise_direct(xd, yd)
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(d, split, X.device, X.comm)


cdist_small = cdist


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """City-block distance matrix (distance.py:182)."""
    xd, yd = _prep(X, Y)
    d = jnp.sum(jnp.abs(xd[:, None, :] - yd[None, :, :]), axis=-1)
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(d, split, X.device, X.comm)


def rbf(X: DNDarray, Y: Optional[DNDarray] = None, sigma: float = 1.0, quadratic_expansion: bool = False) -> DNDarray:
    """Gaussian (RBF) kernel matrix exp(-d^2 / (2 sigma^2)) (distance.py:158)."""
    xd, yd = _prep(X, Y)
    d2 = _pairwise_sqeuclidean(xd, yd)
    k = jnp.exp(-d2 / (2.0 * sigma * sigma))
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(k, split, X.device, X.comm)
