"""Adversarial inputs for the sharded-planes sparse engine: duplicate
COO entries, fully-skewed nnz (one shard owns everything), empty rows/
columns, single-row/column shapes, dtype extremes, and chained ops that
stress capacity compaction — scipy ground truth throughout.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import heat_tpu as ht


def test_duplicate_coo_entries_sum():
    rows = np.array([0, 0, 2, 2, 2, 4])
    cols = np.array([1, 1, 3, 3, 3, 0])
    vals = np.array([1.0, 2.0, 0.5, 0.25, 0.25, -1.0])
    coo = sp.coo_matrix((vals, (rows, cols)), shape=(5, 5))
    s = ht.sparse.sparse_csr_matrix(coo, split=0)
    want = coo.tocsr()
    want.sum_duplicates()
    assert s.gnnz == want.nnz  # duplicates merged at ingestion
    np.testing.assert_allclose(s.toarray(), want.toarray())
    np.testing.assert_array_equal(np.asarray(s.indptr), want.indptr)


def test_fully_skewed_distribution():
    """Every nonzero lives in the FIRST canonical chunk: capacity is set
    by one shard while the rest are pure padding."""
    a = np.zeros((64, 16), np.float64)
    a[:4] = np.random.default_rng(0).standard_normal((4, 16))
    s = ht.sparse.sparse_csr_matrix(sp.csr_matrix(a), split=0)
    counts, _ = s.counts_displs_nnz()
    assert counts[0] == 64 and sum(counts[1:]) == 0
    np.testing.assert_allclose(s.toarray(), a)
    # ops still correct with the empty shards
    np.testing.assert_allclose((s + s).toarray(), 2 * a)
    x = np.random.default_rng(1).standard_normal((16, 3))
    np.testing.assert_allclose((s @ ht.array(x, split=0)).numpy(), a @ x, rtol=1e-10)
    np.testing.assert_allclose(s.sum(axis=1).numpy(), a.sum(1), rtol=1e-10)


def test_last_shard_only():
    """All nonzeros in the LAST chunk (exercises offset bookkeeping)."""
    a = np.zeros((64, 8), np.float64)
    a[-3:] = 1.5
    s = ht.sparse.sparse_csr_matrix(sp.csr_matrix(a), split=0)
    counts, displs = s.counts_displs_nnz()
    assert counts[-1] == 24 and displs[-1] == 0
    np.testing.assert_allclose(s.toarray(), a)
    np.testing.assert_array_equal(np.asarray(s.indptr), sp.csr_matrix(a).indptr)


@pytest.mark.parametrize("shape", [(1, 50), (50, 1), (1, 1)])
def test_degenerate_shapes(shape):
    rng = np.random.default_rng(3)
    a = rng.standard_normal(shape)
    a[rng.random(shape) < 0.5] = 0.0
    want = sp.csr_matrix(a)
    s = ht.sparse.sparse_csr_matrix(want, split=0)
    np.testing.assert_allclose(s.toarray(), a)
    np.testing.assert_array_equal(np.asarray(s.indptr), want.indptr)
    np.testing.assert_allclose((s * s).toarray(), a * a)


def test_intersection_disjoint_patterns():
    """mul of disjoint patterns: the result is all-empty shards."""
    a = sp.csr_matrix(np.diag(np.arange(1.0, 9.0)))
    sa = ht.sparse.sparse_csr_matrix(a, split=0)
    sb = ht.sparse.sparse_csr_matrix(sp.csr_matrix(np.eye(8, k=1)), split=0)
    prod = sa * sb
    assert prod.gnnz == 0
    np.testing.assert_allclose(prod.toarray(), np.zeros((8, 8)))
    # and adding the empty result back is the identity
    np.testing.assert_allclose((sa + prod).toarray(), a.toarray())


def test_chained_adds_compact_capacity():
    """Repeated union ops must not balloon the static capacity: the
    post-op re-sync slices back to the true max shard occupancy."""
    m = sp.random(80, 40, density=0.05, random_state=7, format="csr")
    s = ht.sparse.sparse_csr_matrix(m, split=0)
    acc = s
    for _ in range(4):
        acc = acc + s  # same pattern: nnz constant, capacity must not grow
    assert acc.gnnz == s.gnnz
    assert acc._capacity == s._capacity
    np.testing.assert_allclose(acc.toarray(), (5 * m).toarray(), rtol=1e-6)


def test_cancellation_keeps_pattern():
    """a + (-a) keeps the union pattern with explicit zeros (torch/heat
    semantics: no implicit pruning on add)."""
    m = sp.random(30, 20, density=0.1, random_state=9, format="csr")
    s = ht.sparse.sparse_csr_matrix(m, split=0)
    z = s + (s * (-1.0))
    assert z.gnnz == s.gnnz  # pattern preserved, values zero
    np.testing.assert_allclose(z.toarray(), np.zeros((30, 20)))


def test_integer_dtype_matrix():
    a = np.zeros((12, 6), np.int64)
    a[::3, ::2] = 7
    s = ht.sparse.sparse_csr_matrix(sp.csr_matrix(a), split=0)
    assert s.dtype in (ht.int64, ht.int32)
    np.testing.assert_array_equal(s.toarray(), a)
    np.testing.assert_array_equal((s + s).toarray(), 2 * a)
    assert int(s.sum()) == int(a.sum())


def test_transpose_of_skewed_then_compute():
    a = np.zeros((40, 10), np.float64)
    a[0] = np.arange(10.0)
    s = ht.sparse.sparse_csr_matrix(sp.csr_matrix(a), split=0)
    t = s.T  # metadata-only: CSC over the same planes
    x = np.random.default_rng(11).standard_normal((40, 2))
    np.testing.assert_allclose(
        (t @ ht.array(x, split=0)).numpy(), a.T @ x, rtol=1e-10
    )
    np.testing.assert_allclose(t.sum(axis=0).numpy(), a.T.sum(0), rtol=1e-10)


def test_wide_matrix_csc_skew():
    a = np.zeros((6, 96), np.float64)
    a[:, :2] = np.random.default_rng(13).standard_normal((6, 2))
    s = ht.sparse.sparse_csc_matrix(sp.csc_matrix(a), split=1)
    counts, _ = s.counts_displs_nnz()
    assert counts[0] == 12 and sum(counts[1:]) == 0
    want = sp.csc_matrix(a)
    np.testing.assert_array_equal(np.asarray(s.indptr), want.indptr)
    np.testing.assert_allclose(s.toarray(), a)
