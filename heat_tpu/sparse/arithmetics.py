"""Sparse arithmetic over the sharded nnz planes, analog of
heat/sparse/arithmetics.py (add :17, mul :58 via ``__binary_op_csx``,
sparse/_operations.py:17-209).

The reference applies local torch sparse ops per chunk and Allreduces the
new nnz; here each op is one jitted shard_map program over the padded
planes (concat + two-key sort + neighbor merge for union/intersection,
gather + segment-sum (+ psum/psum_scatter) for the products) followed by
the same small nnz re-sync.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dndarray import DNDarray
from . import _planes as _pl
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix

__all__ = ["add", "mul", "sum", "matmul"]


def _binary_op_csx(op_name, t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Generic sparse-sparse elementwise op (sparse/_operations.py:17)."""
    if not isinstance(t1, DCSX_matrix) or not isinstance(t2, DCSX_matrix):
        raise TypeError(f"both operands must be sparse matrices, got {type(t1)}, {type(t2)}")
    if type(t1) is not type(t2):
        raise TypeError(
            f"operands must share the sparse format, got {type(t1).__name__} and {type(t2).__name__}"
        )
    if t1.shape != t2.shape:
        raise ValueError(f"shapes must match, got {t1.shape} and {t2.shape}")
    if t1.split != t2.split:
        # the operand with the differing split is re-chunked to t1's split
        t2 = _align_split(t2, t1.split)
    from ..core import types

    res_jt = jnp.promote_types(t1.dtype.jax_type(), t2.dtype.jax_type())
    a = t1 if t1._val.dtype == res_jt else t1.astype(res_jt)
    b = t2 if t2._val.dtype == res_jt else t2.astype(res_jt)
    comp, other, val, lnnz_dev, lnnz_host, out_C = _pl.merge_planes(
        op_name,
        (a._comp, a._other, a._val),
        (b._comp, b._other, b._val),
        a._nshards, a._capacity, b._capacity, a._comp_pad, a._dist, a.comm,
    )
    dtype = types.canonical_heat_type(res_jt)
    return a._with_planes((comp, other, val), lnnz_dev, lnnz_host, out_C, dtype=dtype)


def _align_split(t: DCSX_matrix, split):
    """Re-chunk a matrix to another split of the same compressed axis
    (None <-> compressed axis): an on-device layout change over the mesh
    (position scatter / bounded gather programs in ``_planes``), with only
    the standard (P,)-int capacity re-sync touching the host."""
    extent = t.shape[t._compressed_axis]
    comp, other, val, lnnz_dev, lnnz_host, C, comp_pad = _pl.rechunk_planes(
        t._comp, t._other, t._val, t._lnnz_dev, t._lnnz_host, extent,
        split is not None, t._nshards, t._capacity, t._comp_pad, t.comm,
    )
    return type(t)(
        (comp, other, val), lnnz_dev, lnnz_host, C, comp_pad,
        t.shape, t.dtype, split, t.device, t.comm,
    )


def add(t1, t2):
    """Element-wise sparse addition (sparse/arithmetics.py:17): pattern
    union with duplicate merging; a scalar operand is applied to the
    stored values only, like the reference (sparse/_operations.py:91-99)."""
    if isinstance(t1, DCSX_matrix) and np.isscalar(t2):
        return _scalar_op("add", t1, t2)
    if isinstance(t2, DCSX_matrix) and np.isscalar(t1):
        return _scalar_op("add", t2, t1)
    return _binary_op_csx("add", t1, t2)


def mul(t1, t2):
    """Element-wise sparse multiplication (sparse/arithmetics.py:58):
    pattern intersection; scalars scale the value plane in place."""
    if isinstance(t1, DCSX_matrix) and np.isscalar(t2):
        return _scalar_op("mul", t1, t2)
    if isinstance(t2, DCSX_matrix) and np.isscalar(t1):
        return _scalar_op("mul", t2, t1)
    return _binary_op_csx("mul", t1, t2)


def _scalar_op(op_name: str, t: DCSX_matrix, s) -> DCSX_matrix:
    from ..core import types

    res_jt = jnp.result_type(t._val.dtype, s)  # promote like dense numpy
    val = t._val.astype(res_jt)
    sv = jnp.asarray(s, res_jt)
    if op_name == "mul":
        val = val * sv
    else:
        # only real entries take the scalar: padding values must stay 0 so
        # they keep contributing nothing to any later segment-sum
        val = jnp.where(t._comp < t._comp_pad, val + sv, jnp.zeros((), res_jt))
    return t._with_planes(
        (t._comp, t._other, val),
        t._lnnz_dev, t._lnnz_host, t._capacity,
        dtype=types.canonical_heat_type(res_jt),
    )


def sum(t: DCSX_matrix, axis=None) -> "DNDarray":
    """Sparse sum reduction to a dense DNDarray.

    Beyond the reference's sparse surface (its DCSX has no reductions);
    axis=None gives the 0-d total, axis 0/1 a dense vector.  Per-shard
    segment-sums over the planes; the cross-shard combine is a
    psum_scatter when the reduced axis is the uncompressed one."""
    if not isinstance(t, DCSX_matrix):
        raise TypeError(f"expected a sparse matrix, got {type(t)}")
    if axis is None:
        res = _pl.sum_planes(
            t._comp, t._other, t._val, None, t._nshards, t._capacity,
            t._comp_pad, 0, t._dist, t.comm,
        )
        return DNDarray.from_dense(res, None, t.device, t.comm)
    axis = axis if axis >= 0 else axis + 2
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0, 1 or None, got {axis}")
    # reducing over `axis` leaves one value per index of the OTHER axis
    out_axis = 1 - axis
    axis_is_comp = out_axis == t._compressed_axis
    other_extent = t.shape[1 - t._compressed_axis]
    res = _pl.sum_planes(
        t._comp, t._other, t._val, axis_is_comp, t._nshards, t._capacity,
        t._comp_pad, other_extent, t._dist, t.comm,
    )
    out_len = t.shape[out_axis]
    if not t._dist:
        return DNDarray.from_dense(res[:out_len], None, t.device, t.comm)
    return DNDarray(res, (out_len,), t.dtype, 0, t.device, t.comm)


def matmul(a, b):
    """Sparse matrix product: sparse@sparse -> sparse, sparse@dense and
    dense@sparse -> dense DNDarray.

    Beyond the reference's sparse surface.  Row-compressed operands keep
    whole output rows per shard (one segment-sum per ring step; the dense
    operand's row chunks ride a ppermute ring, never a full replica);
    column-compressed operands contract against the co-chunked rows of
    the dense operand with NO gather and meet in a psum_scatter.
    sparse@sparse runs the same programs against the other operand's
    per-chunk densification, then re-packs (the GEMM-style spgemm trade:
    the result's dense row block is the per-device memory bound)."""
    a_sp = isinstance(a, DCSX_matrix)
    b_sp = isinstance(b, DCSX_matrix)
    if not a_sp and not b_sp:
        raise TypeError("at least one operand must be a sparse matrix")
    if a_sp and b_sp:
        return _spgemm(a, b)
    if a_sp:
        return _sp_dense(a, b)
    return _dense_sp(a, b)


def _dense_operand(x, comm):
    if isinstance(x, DNDarray):
        return x
    return DNDarray.from_dense(jnp.asarray(np.asarray(x)), None, None, comm)


def _sp_dense(a: DCSX_matrix, b) -> DNDarray:
    x = _dense_operand(b, a.comm)
    if a.shape[1] != x.shape[0]:
        raise ValueError(f"shape mismatch for matmul: {a.shape} @ {x.shape}")
    m, k = a.shape
    n = int(x.shape[1]) if x.ndim == 2 else 1
    xb = x if x.ndim == 2 else x.reshape((int(x.shape[0]), 1))
    if a._compressed_axis == 0:
        if a._dist:
            # CSR ring: X's row chunks ride a ppermute ring instead of a
            # full per-shard replica (VERDICT r4 weak #5) — peak memory
            # O((k/P + m/P) * n) per device, no all-gather of X
            xs = xb if xb.split == 0 else xb.resplit(0)
            k_pad = a.comm.padded_extent(k)
            out = _pl._spmm_comp_rows_ring_prog(
                a.comm, a._nshards, a._capacity, a._comp_pad, k_pad, n
            )(a._comp, a._other, a._val, xs.larray_padded)
        else:
            out = _pl._spmm_comp_rows_prog(
                a.comm, a._nshards, a._capacity, a._comp_pad, k, n, a._dist
            )(a._comp, a._other, a._val, xb._dense())
            out = out[:m]
        res = DNDarray(out, (m, n), out.dtype, 0 if a._dist else None, a.device, a.comm)
    else:
        # CSC: columns co-chunked with X's rows — no gather of X
        xs = xb if (not a._dist or xb.split == 0) else xb.resplit(0)
        x_in = xs.larray_padded if a._dist else xs._dense()
        m_pad = a.comm.padded_extent(m) if a._dist else m
        out = _pl._spmm_comp_inner_prog(
            a.comm, a._nshards, a._capacity, a._comp_pad, m_pad, n, a._dist
        )(a._comp, a._other, a._val, x_in)
        res = DNDarray(out, (m, n), out.dtype, 0 if a._dist else None, a.device, a.comm)
    if x.ndim == 1:
        res = res.reshape((m,))
    return res


def _dense_sp(a, b: DCSX_matrix) -> DNDarray:
    e = _dense_operand(a, b.comm)
    if e.shape[-1] != b.shape[0]:
        raise ValueError(f"shape mismatch for matmul: {e.shape} @ {b.shape}")
    vec = e.ndim == 1
    eb = e.reshape((1, int(e.shape[0]))) if vec else e
    q = int(eb.shape[0])
    m, n = b.shape
    if b._compressed_axis == 0:
        out = _pl._dense_times_comp_rows_prog(
            b.comm, b._nshards, b._capacity, b._comp_pad, q, n, b._dist
        )(b._comp, b._other, b._val, eb._dense())
        res = DNDarray.from_dense(out, 0 if (isinstance(a, DNDarray) and a.split == 0) else None, b.device, b.comm)
    else:
        out = _pl._dense_times_comp_cols_prog(
            b.comm, b._nshards, b._capacity, b._comp_pad, q, b._dist
        )(b._comp, b._other, b._val, eb._dense())
        if not b._dist:
            out = out[:, :n]
            res = DNDarray(out, (q, n), out.dtype, None, b.device, b.comm)
        else:
            res = DNDarray(out, (q, n), out.dtype, 1, b.device, b.comm)
    if vec:
        res = res.reshape((n,))
    return res


def _spgemm(a: DCSX_matrix, b: DCSX_matrix):
    """sparse @ sparse -> sparse of a's format.

    Default route (ISSUE 16 tentpole 1): an OUTPUT-SPARSE triplet ring —
    each ring step contracts the local CSR chunk of A against the arriving
    (comp, other, val) chunk of B and merges canonical partial products
    through ``merge_planes``, so a sparse result succeeds (and is fast)
    where the dense (m/P, n) block cannot even be allocated.  Column-
    compressed operands route through the metadata transpose
    (A @ B = (Bᵀ @ Aᵀ)ᵀ) and mixed formats through the triplet-preserving
    conversion — ``todense()`` is never called on either operand.

    When the ESTIMATED output density (independent-pattern model
    1 - exp(-nnz_A * nnz_B / (m*k*n))) reaches
    ``HEAT_TPU_SPGEMM_DENSE_DENSITY``, the GEMM-style dense route is the
    better trade (the ring's partial-triplet traffic exceeds the dense
    block) and is kept as the fallback."""
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"shape mismatch for matmul: {a.shape} @ {b.shape}")
    from ..core._env import env_float

    m, k = (int(s) for s in a.shape)
    n = int(b.shape[1])
    cells = float(m) * float(k) * float(n)
    lam = (float(a.gnnz) * float(b.gnnz) / cells) if cells else 0.0
    est_density = 1.0 - float(np.exp(-lam))
    if est_density >= env_float("HEAT_TPU_SPGEMM_DENSE_DENSITY"):
        return _spgemm_dense(a, b)
    from .manipulations import to_sparse_csr

    if a._compressed_axis == 1:
        # column-compressed result: run the row-compressed ring on the
        # metadata transposes and flip back — no data movement beyond the
        # (possible) triplet-preserving re-compression of Bᵀ
        at = a.T
        bt = b.T
        if bt._compressed_axis == 1:
            bt = to_sparse_csr(bt)
        return _spgemm_csr(bt, at).T
    b_csr = b if b._compressed_axis == 0 else to_sparse_csr(b)
    return _spgemm_csr(a, b_csr)


def _spgemm_csr(a: DCSR_matrix, b: DCSR_matrix) -> DCSR_matrix:
    """Row-compressed output-sparse ring product (both operands CSR)."""
    from ..core import types

    if b.split != a.split:
        b = _align_split(b, a.split)
    m = int(a.shape[0])
    n = int(b.shape[1])
    res_jt = jnp.promote_types(a.dtype.jax_type(), b.dtype.jax_type())
    r_max = _pl.max_row_occupancy(
        b._comp, b._nshards, b._capacity, b._comp_pad, b._dist, b.comm
    )
    comp, other, val, lnnz_dev, lnnz_host, C = _pl.spgemm_planes(
        (a._comp, a._other, a._val),
        (b._comp, b._other, b._val),
        a._nshards, a._capacity, b._capacity, a._comp_pad, b._comp_pad,
        r_max, res_jt, a._dist, a.comm,
    )
    return DCSR_matrix(
        (comp, other, val), lnnz_dev, lnnz_host, C, a._comp_pad,
        (m, n), types.canonical_heat_type(res_jt), a.split, a.device, a.comm,
    )


def _spgemm_dense(a: DCSX_matrix, b: DCSX_matrix):
    """GEMM-style fallback for dense-regime outputs: B densifies only
    per-chunk (``todense`` keeps B's rows sharded over the mesh), the
    product runs through the CSR X-ring / CSC psum_scatter SpMM programs,
    and the dense OUTPUT row block — O((m/P)*n) per device — is re-packed
    on device.  Scale bound: the *result's* dense chunk must fit; with
    ``HEAT_TPU_HBM_BUDGET_BYTES`` armed, a chunk that cannot fit raises
    :class:`MemoryError` up front instead of an opaque allocator failure
    mid-program (the OOM regime the output-sparse ring exists for)."""
    from ..core._env import env_int
    from .manipulations import to_sparse_csc, to_sparse_csr

    budget = env_int("HEAT_TPU_HBM_BUDGET_BYTES")
    if budget > 0:
        m, k = (int(s) for s in a.shape)
        n = int(b.shape[1])
        p = a.comm.size if a._dist else 1
        item = jnp.dtype(
            jnp.promote_types(a.dtype.jax_type(), b.dtype.jax_type())
        ).itemsize
        # per device: B's densified row chunk + the dense output row block
        per_dev = (-(-k // p) + -(-m // p)) * n * item
        if per_dev > budget:
            raise MemoryError(
                f"dense SpGEMM fallback needs ~{per_dev} bytes/device for a "
                f"({m}x{n}) dense block (budget {budget}); the output-sparse "
                "ring route (density below HEAT_TPU_SPGEMM_DENSE_DENSITY) "
                "has no dense intermediate"
            )
    dense = _sp_dense(a, b.todense())
    if isinstance(a, DCSR_matrix):
        return to_sparse_csr(dense)
    return to_sparse_csc(dense.resplit(1) if a._dist else dense)
