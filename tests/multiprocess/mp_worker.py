"""Multi-process worker battery: one host controller of an N-process SPMD run.

The analog of the reference's ``mpirun -n 3 pytest`` CI lane
(/root/reference/.github/workflows/ci.yaml:58-61): every process runs this
same program in lockstep; collective results must agree with numpy ground
truth on every process.  Launched by tests/test_multiprocess.py with
2 processes x 4 virtual CPU devices each.

Usage: python mp_worker.py <process_id> <num_processes> <port> [devices_per_proc]
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = int(sys.argv[3])
DEV_PER_PROC = int(sys.argv[4]) if len(sys.argv) > 4 else 4

os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={DEV_PER_PROC}"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

import heat_tpu as ht

ht.parallel.init(
    coordinator_address=f"localhost:{PORT}", num_processes=NPROC, process_id=PID
)

import jax.numpy as jnp  # noqa: E402  (after init: backend is now live)


def check(name, cond):
    if not cond:
        print(f"[{PID}] FAIL: {name}", flush=True)
        sys.exit(1)
    print(f"[{PID}] ok: {name}", flush=True)


NDEV = NPROC * DEV_PER_PROC

# ---------------------------------------------------------------- topology
comm = ht.get_comm()
check("global device count", comm.size == NDEV)
check("process count", comm.process_count == NPROC)
check("process rank", comm.rank == PID)
check(
    "local participants",
    comm.local_participants == list(range(PID * DEV_PER_PROC, (PID + 1) * DEV_PER_PROC)),
)

# ---------------------------------------------------------------- factories
a = ht.arange(2 * NDEV + 3, split=0)  # uneven extent exercises pad-and-mask
truth = np.arange(2 * NDEV + 3)
check("arange sum (collective reduce)", float(a.sum()) == truth.sum())
check("arange numpy allgather", np.array_equal(a.numpy(), truth))

# larray: this process's true block of the canonical distribution
off, lshape, _ = comm.process_chunk(a.shape, 0)
check("larray shape", a.larray.shape == lshape)
check("larray content", np.array_equal(np.asarray(a.larray), truth[off : off + lshape[0]]))

# ---------------------------------------------------------------- is_split
# ragged ingestion: process p contributes 5-p rows (not canonically aligned)
rows = 5 - PID
local = np.full((rows, 3), float(PID)) + np.arange(rows)[:, None]
g = ht.array(local, is_split=0)
total = sum(5 - q for q in range(NPROC))
check("is_split gshape", g.shape == (total, 3))
expected = np.concatenate(
    [np.full((5 - q, 3), float(q)) + np.arange(5 - q)[:, None] for q in range(NPROC)]
)
check("is_split content (ragged permute)", np.allclose(g.numpy(), expected))

# aligned ingestion fast path: chunk shapes straight from process_chunk
gshape = (3 * NDEV, 2)
off2, lsh2, _ = comm.process_chunk(gshape, 0)
mine = np.arange(off2, off2 + lsh2[0], dtype=np.float64)[:, None] * np.ones((1, 2))
g2 = ht.array(mine, is_split=0)
check("is_split aligned gshape", g2.shape == gshape)
check(
    "is_split aligned content",
    np.allclose(g2.numpy(), np.arange(gshape[0], dtype=np.float64)[:, None] * np.ones((1, 2))),
)

# ---------------------------------------------------------------- ops
x_np = np.linspace(0.0, 1.0, 7 * NDEV - 5).reshape(-1, 1) * np.ones((1, 4))
x = ht.array(x_np, split=0)
y = x * 2.0 + 1.0
check("elementwise", np.allclose(y.numpy(), x_np * 2.0 + 1.0))
check("reduction mean", abs(float(y.mean()) - (x_np * 2 + 1).mean()) < 1e-12)
check("axis reduction", np.allclose(x.sum(axis=0).numpy(), x_np.sum(0)))

# global setitem is collective (same scatter on every process)
x[3] = 9.0
x_np[3] = 9.0
check("setitem", np.allclose(x.numpy(), x_np))

# ---------------------------------------------------------------- resplit
r = x.resplit(1)
check("resplit 0->1", r.split == 1 and np.allclose(r.numpy(), x_np))
rn = x.resplit(None)
check("resplit 0->None", rn.split is None and np.allclose(rn.numpy(), x_np))

# ---------------------------------------------------------------- lloc write
b = ht.zeros((NDEV * 2, 2), split=0)
_, lsh3, _ = comm.process_chunk(b.shape, 0)
b._replace_local(jnp.full(lsh3, float(PID + 1)))
bn = b.numpy()
for q in range(NPROC):
    o, ls, _ = comm.process_chunk(b.shape, 0, process=q)
    if not np.allclose(bn[o : o + ls[0]], float(q + 1)):
        check(f"replace_local block of process {q}", False)
check("replace_local collective view", True)

# ---------------------------------------------------------------- linalg
m = ht.random.randn(8 * NDEV, 5, split=0, dtype=ht.float64)
q_, r_ = ht.qr(m)
check(
    "qr factorization",
    np.allclose(q_.numpy() @ r_.numpy(), m.numpy(), atol=1e-10),
)

# split=1 QR: the block-MGS shard_map collective over the cross-process mesh
m2 = ht.random.randn(3 * NDEV + 7, NDEV + 3, split=1, dtype=ht.float64)
q2_, r2_ = ht.qr(m2)
check(
    "qr split=1 (block MGS)",
    np.allclose(q2_.numpy() @ r2_.numpy(), m2.numpy(), atol=1e-8),
)

# ---------------------------------------------------------------- sample sort
from heat_tpu.core import sample_sort

_saved_gate = sample_sort.SAMPLE_SORT_THRESHOLD
sample_sort.SAMPLE_SORT_THRESHOLD = 1  # force the PSRS collective
rng_sort = np.random.default_rng(123)  # same data on every process (SPMD)
sort_data = rng_sort.standard_normal(7 * NDEV + 5).astype(np.float32)
sv, si = ht.sort(ht.array(sort_data, split=0))
check("psrs sort values", np.array_equal(sv.numpy(), np.sort(sort_data)))
check("psrs sort indices", np.array_equal(si.numpy(), np.argsort(sort_data, kind="stable")))
sample_sort.SAMPLE_SORT_THRESHOLD = _saved_gate

# ------------------------------------------------------------- pencil fft
# split-axis FFT rides all_to_all across the process boundary (gloo DCN)
fft_np = np.random.default_rng(77).standard_normal((4 * NDEV, 2 * NPROC))
fft_in = ht.array(fft_np, split=0)
spec = ht.fft.fft(fft_in, axis=0)
check("pencil fft cross-process", np.allclose(spec.numpy(), np.fft.fft(fft_np, axis=0), atol=1e-10))
back = ht.fft.ifft(spec, axis=0)
check("pencil ifft roundtrip", np.allclose(back.numpy().real, fft_np, atol=1e-10))

# ---------------------------------------------------------------- sharded io
import tempfile
import shutil

from jax.experimental import multihost_utils

io_dir = os.path.join(tempfile.gettempdir(), f"heat_mp_npy_{PORT}")
io_arr = ht.arange(3 * NDEV + 5, dtype=ht.float64, split=0)
ht.io.save_npy_from_path(io_arr, io_dir)  # each process writes its shards
multihost_utils.sync_global_devices("npy_written")
io_back = ht.load_npy_from_path(io_dir, dtype=ht.float64, split=0)
check("sharded npy roundtrip", np.array_equal(io_back.numpy(), np.arange(3 * NDEV + 5)))
multihost_utils.sync_global_devices("npy_read")
if PID == 0:
    shutil.rmtree(io_dir, ignore_errors=True)

if ht.io.supports_hdf5():
    h5_path = os.path.join(tempfile.gettempdir(), f"heat_mp_{PORT}.h5")
    ht.save_hdf5(io_arr, h5_path, "data")  # serialized process turns inside
    io_back2 = ht.load_hdf5(h5_path, "data", dtype=ht.float64, split=0)
    check("sharded hdf5 roundtrip", np.array_equal(io_back2.numpy(), np.arange(3 * NDEV + 5)))
    multihost_utils.sync_global_devices("h5_read")
    if PID == 0:
        os.remove(h5_path)

# ------------------------------------------------------- hierarchical DASO
# node == process: the reference DASO's exact topology (intra-node DDP over
# this process's devices, cross-node bf16 averaging over the process
# boundary — here riding the gloo DCN analog)
import optax

hc = ht.parallel.HierarchicalCommunication(grid=(NPROC, DEV_PER_PROC))
check("hier comm nodes == processes", hc.num_nodes == NPROC and hc.node_size == DEV_PER_PROC)
daso = ht.optim.DASO(
    local_optimizer=optax.sgd(0.1), total_epochs=100, comm=hc,
    warmup_epochs=0, cooldown_epochs=0,
)
daso.global_skip = 2
daso.batches_to_wait = 0
params = daso.replicate({"w": jnp.ones((4,), jnp.float32)})
grads = {
    "w": jnp.stack([jnp.full((4,), 1.0 + node, jnp.float32) for node in range(NPROC)])
}
def _host(x):
    """Gather a cross-process global array to every host."""
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


params = daso.step(params, grads)  # batch 0: local step + sync
w = _host(params["w"])
mean_traj = 1.0 - 0.1 * np.mean(1.0 + np.arange(NPROC))
check("daso cross-process sync is a real average", np.allclose(w, mean_traj, atol=2e-2))
params = daso.step(params, grads)  # batch 1: skipped -> replicas diverge
w = _host(params["w"])
check("daso skip leaves replicas diverged", abs(w[0, 0] - w[-1, 0]) > 0.05 * (NPROC - 1))

# ----------------------------------------------------- distributed sparse (r4)
import scipy.sparse as sp_sparse

sp_np = sp_sparse.random(6 * NDEV + 1, 40, density=0.1, random_state=9, format="csr",
                         dtype=np.float64)
from heat_tpu.sparse._planes import fetch_host as _sp_fetch

smat = ht.sparse.sparse_csr_matrix(sp_np, split=0)
check("sparse planes span the cross-process mesh",
      len(smat._val.sharding.device_set) == NDEV)
check("sparse indptr cross-process",
      np.array_equal(_sp_fetch(smat.indptr), sp_np.indptr))
dense_x = np.random.default_rng(5).standard_normal((40, 3))
sp_out = smat @ ht.array(dense_x, split=0)
check("sparse spmm cross-process", np.allclose(sp_out.numpy(), sp_np @ dense_x, atol=1e-10))
sp_sum = smat + smat
check("sparse add cross-process", np.allclose(sp_sum.toarray(), 2 * sp_np.toarray()))

# ------------------------------------------------- ragged redistribute_ (r4)
rd_np = np.arange(4 * NDEV, dtype=np.float64)
rd = ht.array(rd_np, split=0)
tgt = np.zeros((NDEV, 1), np.int64)
tgt[0] = 3 * NDEV
tgt[1] = NDEV
rd.redistribute_(target_map=tgt)
check("ragged lshape_map", tuple(rd.lshape_map[:2, 0]) == (3 * NDEV, NDEV))
counts_r, displs_r = rd.counts_displs()
check("ragged counts_displs", counts_r[0] == 3 * NDEV and displs_r[1] == 3 * NDEV)
check("ragged values intact", np.array_equal(rd.numpy(), rd_np))
rd.balance_()
check("balance_ drops the ragged layer", rd.is_balanced())

# ----------------------------------------------- pencil rfft kind (r4)
rf_np = np.random.default_rng(31).standard_normal((4 * NDEV, 2 * NPROC))
rf = ht.fft.rfft(ht.array(rf_np, split=0), axis=0)
check("real-kind pencil cross-process",
      np.allclose(rf.numpy(), np.fft.rfft(rf_np, axis=0), atol=1e-10))

# ----------------------------------------------- axis!=0 PSRS (r4)
_saved_gate = sample_sort.SAMPLE_SORT_THRESHOLD
sample_sort.SAMPLE_SORT_THRESHOLD = 1
ax_np = np.random.default_rng(41).standard_normal((3, 5 * NDEV)).astype(np.float64)
axv, axi = ht.sort(ht.array(ax_np, split=1), axis=1)
check("axis-1 psrs values", np.array_equal(axv.numpy(), np.sort(ax_np, axis=1)))
check("axis-1 psrs indices",
      np.array_equal(axi.numpy(), np.argsort(ax_np, axis=1, kind="stable")))
sample_sort.SAMPLE_SORT_THRESHOLD = _saved_gate

print(f"[{PID}] MP-OK", flush=True)
