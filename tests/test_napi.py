"""NumPy API extension sweep (heat_tpu/core/napi.py) — every function
compared against the numpy ground truth on the virtual mesh."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture(scope="module")
def m():
    return np.random.default_rng(0).standard_normal((9, 6))


@pytest.fixture
def x(m):
    return ht.array(m, split=0)


def test_sorting_family(m, x):
    np.testing.assert_array_equal(ht.argsort(x, axis=0).numpy(), np.argsort(m, axis=0))
    got = ht.partition(x, 3, axis=0).numpy()
    assert (np.sort(got, axis=0) == np.sort(m, axis=0)).all()
    # kth element is in sorted position per column
    for c in range(m.shape[1]):
        assert got[3, c] == np.sort(m[:, c])[3]
    ap = ht.argpartition(x, 3, axis=0).numpy()
    assert ap.shape == m.shape
    srt = np.sort(m[:, 0])
    np.testing.assert_array_equal(
        ht.searchsorted(ht.array(srt), ht.array([0.0, 1.0])).numpy(),
        np.searchsorted(srt, [0.0, 1.0]),
    )
    np.testing.assert_array_equal(
        ht.lexsort((ht.array([1.0, 2.0, 1.0]), ht.array([3.0, 1.0, 2.0]))).numpy(),
        np.lexsort((np.array([1.0, 2.0, 1.0]), np.array([3.0, 1.0, 2.0]))),
    )
    np.testing.assert_allclose(
        ht.sort_complex(ht.array([2 + 1j, 1 - 1j, 1 + 0j])).numpy(),
        np.sort_complex([2 + 1j, 1 - 1j, 1 + 0j]),
    )


def test_nan_family(m, x):
    mn = m.copy()
    mn[0, 0] = np.nan
    xn = ht.array(mn, split=0)
    np.testing.assert_allclose(float(ht.nanmax(xn)), np.nanmax(mn))
    np.testing.assert_allclose(float(ht.nanmin(xn)), np.nanmin(mn))
    np.testing.assert_allclose(ht.nanmean(xn, axis=1).numpy(), np.nanmean(mn, axis=1))
    np.testing.assert_allclose(float(ht.nanmedian(xn)), np.nanmedian(mn))
    np.testing.assert_allclose(float(ht.nanstd(xn, ddof=1)), np.nanstd(mn, ddof=1), rtol=1e-12)
    np.testing.assert_allclose(float(ht.nanvar(xn)), np.nanvar(mn), rtol=1e-12)
    assert int(ht.nanargmax(xn)) == np.nanargmax(mn)
    assert int(ht.nanargmin(xn)) == np.nanargmin(mn)
    np.testing.assert_allclose(float(ht.nanpercentile(xn, 70.0)), np.nanpercentile(mn, 70.0))
    np.testing.assert_allclose(float(ht.nanquantile(xn, 0.7)), np.nanquantile(mn, 0.7))
    np.testing.assert_allclose(float(ht.quantile(x, 0.3)), np.quantile(m, 0.3))


def test_statistics_extras(m, x):
    np.testing.assert_allclose(float(ht.ptp(x)), np.ptp(m))
    np.testing.assert_allclose(ht.corrcoef(x).numpy(), np.corrcoef(m), rtol=1e-10)
    assert int(ht.count_nonzero(x > 0)) == np.count_nonzero(m > 0)
    h, xe, ye = ht.histogram2d(ht.array(m[:, 0]), ht.array(m[:, 1]), bins=4)
    hn, xen, yen = np.histogram2d(m[:, 0], m[:, 1], bins=4)
    np.testing.assert_allclose(h.numpy(), hn)
    hd, edges = ht.histogramdd(x, bins=3)
    hdn, edgesn = np.histogramdd(m, bins=3)
    np.testing.assert_allclose(hd.numpy(), hdn)
    np.testing.assert_allclose(
        ht.histogram_bin_edges(x, bins=5).numpy(), np.histogram_bin_edges(m, bins=5)
    )


def test_manipulation_extras(m, x):
    np.testing.assert_allclose(ht.append(x, x, axis=0).numpy(), np.append(m, m, axis=0))
    np.testing.assert_allclose(ht.delete(x, 2, axis=0).numpy(), np.delete(m, 2, axis=0))
    np.testing.assert_allclose(ht.insert(x, 1, 5.0, axis=1).numpy(), np.insert(m, 1, 5.0, axis=1))
    np.testing.assert_allclose(ht.resize(x, (4, 4)).numpy(), np.resize(m, (4, 4)))
    np.testing.assert_allclose(ht.rollaxis(x, 1).numpy(), np.rollaxis(m, 1))
    np.testing.assert_allclose(ht.dstack([x, x]).numpy(), np.dstack([m, m]))
    np.testing.assert_allclose(ht.atleast_2d(ht.array([1.0, 2.0])).numpy(), np.atleast_2d([1.0, 2.0]))
    a1, a3 = ht.atleast_1d(ht.array(1.0)), ht.atleast_3d(x)
    assert a1.shape == (1,) and a3.ndim == 3
    np.testing.assert_allclose(
        ht.trim_zeros(ht.array([0.0, 0.0, 1.0, 2.0, 0.0])).numpy(),
        np.trim_zeros(np.array([0.0, 0.0, 1.0, 2.0, 0.0])),
    )
    parts = ht.array_split(x, 4, axis=0)
    nparts = np.array_split(m, 4, axis=0)
    assert len(parts) == len(nparts)
    for p, q in zip(parts, nparts):
        np.testing.assert_allclose(p.numpy(), q)


def test_copyto(m, x):
    dst = ht.array(m.copy(), split=0)
    ht.copyto(dst, 0.0, where=dst > 0)
    ref = m.copy()
    np.copyto(ref, 0.0, where=ref > 0)
    np.testing.assert_allclose(dst.numpy(), ref)


def test_indexing_extras(m, x):
    np.testing.assert_array_equal(ht.argwhere(x > 1).numpy(), np.argwhere(m > 1))
    np.testing.assert_array_equal(ht.flatnonzero(x > 1).numpy(), np.flatnonzero(m > 1))
    np.testing.assert_allclose(ht.extract(x > 1, x).numpy(), np.extract(m > 1, m))


def test_predicates(x):
    assert ht.isscalar(3.0) and not ht.isscalar(x)
    assert ht.iscomplexobj(ht.array([1 + 2j])) and not ht.iscomplexobj(x)
    assert ht.isrealobj(x)
    assert ht.array_equal(x, x) and not ht.array_equal(x, x + 1)
    assert ht.array_equiv(ht.array([1.0, 1.0]), ht.array([[1.0, 1.0], [1.0, 1.0]]))


def test_linalg_extras(m, x):
    np.testing.assert_allclose(
        ht.inner(ht.array(m[0]), ht.array(m[1])).numpy(), np.inner(m[0], m[1]), rtol=1e-10
    )
    np.testing.assert_allclose(
        ht.tensordot(x, ht.array(m.T), axes=1).numpy(), np.tensordot(m, m.T, axes=1), rtol=1e-10
    )
    np.testing.assert_allclose(
        ht.kron(ht.array([[1.0, 2.0]]), ht.array([[3.0], [4.0]])).numpy(),
        np.kron([[1.0, 2.0]], [[3.0], [4.0]]),
    )
    np.testing.assert_allclose(
        ht.einsum("ij,kj->ik", x, x).numpy(), np.einsum("ij,kj->ik", m, m), rtol=1e-10
    )
    np.testing.assert_allclose(ht.fmax(x, 0.0).numpy(), np.fmax(m, 0.0))
    np.testing.assert_allclose(ht.fmin(x, 0.0).numpy(), np.fmin(m, 0.0))


def test_factory_extras():
    np.testing.assert_allclose(ht.tri(4, 5, 1).numpy(), np.tri(4, 5, 1))
    np.testing.assert_allclose(ht.vander(ht.array([1.0, 2.0, 3.0])).numpy(), np.vander([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(
        ht.vander(ht.array([1.0, 2.0]), 4, increasing=True).numpy(),
        np.vander([1.0, 2.0], 4, increasing=True),
    )


def test_second_batch(m, x):
    np.testing.assert_allclose(float(ht.amax(x)), m.max())
    np.testing.assert_allclose(float(ht.amin(x)), m.min())
    np.testing.assert_allclose(ht.diagflat(ht.array([1.0, 2.0]), 1).numpy(), np.diagflat([1.0, 2.0], 1))
    np.testing.assert_allclose(
        ht.correlate(ht.array([1.0, 2.0, 3.0]), ht.array([0.0, 1.0, 0.5])).numpy(),
        np.correlate([1, 2, 3], [0, 1, 0.5]),
    )
    np.testing.assert_allclose(ht.block([[x, x], [x, x]]).numpy(), np.block([[m, m], [m, m]]))
    np.testing.assert_array_equal(
        ht.packbits(ht.array(np.array([1, 0, 1, 1], np.uint8))).numpy(), np.packbits([1, 0, 1, 1])
    )
    np.testing.assert_array_equal(
        ht.unpackbits(ht.array(np.array([176], np.uint8))).numpy(),
        np.unpackbits(np.array([176], np.uint8)),
    )
    assert ht.base_repr(10, 2) == np.base_repr(10, 2)
    assert ht.binary_repr(-3, 5) == np.binary_repr(-3, 5)
    assert ht.format_float_positional(ht.array([1.5]), precision=2) == "1.5"
    assert ht.einsum_path("ij,jk->ik", x, ht.array(m.T))[0] == np.einsum_path("ij,jk->ik", m, m.T)[0]
    assert "1." in ht.array2string(x) and "array" in ht.array_repr(x)
    assert isinstance(ht.array_str(x), str)
    g = ht.mgrid[0:3, 0:2]
    np.testing.assert_array_equal(g[0].numpy(), np.mgrid[0:3, 0:2][0])
    og = ht.ogrid[0:4]
    np.testing.assert_array_equal(og.numpy(), np.ogrid[0:4])
    assert ht.asfarray(ht.array([1, 2])).dtype == ht.float32
    assert ht.ascontiguousarray([1, 2]).shape == (2,)
    assert ht.asanyarray([1.5]).dtype in (ht.float32, ht.float64)
    with pytest.raises(ValueError):
        ht.asarray_chkfinite(ht.array([1.0, np.inf]))


def test_distribution_preserving_wrap():
    """Large grown/stacked outputs of split inputs stay distributed
    (VERDICT: kron/tensordot/histogram2d must not silently replicate)."""
    rng = np.random.default_rng(7)
    a = rng.standard_normal((8, 4))
    b = rng.standard_normal((4, 6))
    A = ht.array(a, split=0)
    B = ht.array(b, split=0)

    k = ht.kron(A, B)
    assert k.split is not None
    np.testing.assert_allclose(k.numpy(), np.kron(a, b), rtol=1e-12)

    t = ht.tensordot(A, B, axes=1)
    assert t.split is not None
    np.testing.assert_allclose(t.numpy(), np.tensordot(a, b, axes=1), rtol=1e-12)

    x = rng.standard_normal(256)
    y = rng.standard_normal(256)
    h = ht.histogram2d(ht.array(x, split=0), ht.array(y, split=0), bins=16)
    assert h[0].split is not None
    np.testing.assert_allclose(h[0].numpy(), np.histogram2d(x, y, bins=16)[0])

    # shape-preserving keeps the original split axis
    s = ht.argsort(ht.array(a, split=1), axis=0)
    assert s.split == 1

    # results smaller than one row per device replicate
    small_bins = ht.get_comm().size - 1  # edges = size, below the threshold
    e = ht.histogram_bin_edges(ht.array(x, split=0), bins=small_bins - 1)
    assert e.split is None


def test_lazy_rank_no_host_sync():
    rng = np.random.default_rng(8)
    A = rng.standard_normal((12, 5))
    r = ht.linalg.matrix_rank(ht.array(A, split=0))
    # lazy 0-d DNDarray, materializes on demand
    assert hasattr(r, "split")
    assert int(r) == np.linalg.matrix_rank(A)
    x, resid, rank, sv = ht.linalg.lstsq(ht.array(A, split=0), ht.array(rng.standard_normal(12)))
    assert int(rank) == 5
