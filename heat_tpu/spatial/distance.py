"""Pairwise distance computations, analog of heat/spatial/distance.py.

The reference's ``_dist`` (distance.py:209-747) is an explicit ring: each
of ceil(p/2) rounds sends a standing row-block to rank+iter and computes
one tile, exploiting symmetry when Y is X.  Here the ring is ONE shard_map
program: X's row-block stands still, Y's row-block rides ``lax.ppermute``
around the mesh, and every round contributes one (n/p, m/p) tile — memory
per device is O(nm/p + (n+m)f/p) instead of the full matrix, and the
Y-is-X case computes each off-diagonal tile once and ships its transpose
to the mirror owner, halving the MXU work exactly like the reference.
``cdist_topk`` fuses the ring with a running top-k so KNN never
materializes (n, m) at all — peak memory O(n(k+m/p)/p) per device.
Metrics mirror _euclidian/_gaussian/_manhattan (distance.py:17-135).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core._compat import shard_map as _shard_map

__all__ = ["cdist", "cdist_small", "cdist_topk", "manhattan", "rbf"]


def _pairwise_sqeuclidean(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """||x_i - y_j||^2 via the expanded form (one MXU matmul instead of the
    reference's broadcast-subtract tile, distance.py:17)."""
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    y_sq = jnp.sum(y * y, axis=1, keepdims=True).T
    cross = jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    d = x_sq + y_sq - 2.0 * cross
    return jnp.maximum(d, 0.0)


def _pairwise_direct(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Exact broadcast-subtract form (distance.py:17-40).  More accurate than
    the expanded form for near-duplicate points (no catastrophic cancellation)
    at the cost of an O(n*m*f) intermediate that XLA fuses into the reduce."""
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _pairwise_euclidean(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Expanded-form euclidean (the quadratic_expansion metric)."""
    return jnp.sqrt(_pairwise_sqeuclidean(x, y))


def _pairwise_sqeuclidean_bf16(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Mixed-precision expanded form: the O(n*m*f) cross term runs on
    bf16 operands with **f32 accumulation pinned** via
    ``preferred_element_type`` (the J203 rule's own prescription), while
    the O((n+m)*f) norms stay f32 — rounding enters only through the
    one-time bf16 quantization of the inputs, so the distance error is
    ~1e-2 relative (the KMeans ``tolerance`` policy's contract) for half
    the MXU traffic.  Only reachable under a tolerance-policy predict
    scope (see :func:`cdist`), which also sanctions the narrowing casts
    for the J201 dtype-flow rule."""
    xb = x.astype(jnp.bfloat16)
    yb = y.astype(jnp.bfloat16)
    cross = jnp.matmul(xb, yb.T, preferred_element_type=jnp.float32)
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    y_sq = jnp.sum(y * y, axis=1, keepdims=True).T
    d = x_sq + y_sq - 2.0 * cross
    return jnp.maximum(d, 0.0)


def _pairwise_euclidean_bf16(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Mixed-precision expanded-form euclidean (bf16 cross term)."""
    return jnp.sqrt(_pairwise_sqeuclidean_bf16(x, y))


def _active_lowp_dtype():
    """The predict scope's low-precision compute dtype name (None =
    native).  Lazy import: the policy layer sits above core, and the
    query is one contextvar read on the miss-free hot path."""
    from ..analysis import precision_policy as _pp

    return _pp.active_compute_dtype()


def _pairwise_manhattan(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """City-block tile (the reference _manhattan, distance.py:110)."""
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def _prep(X: DNDarray, Y: Optional[DNDarray]):
    sanitize_in(X)
    if X.ndim != 2:
        raise NotImplementedError(f"X should be a 2D DNDarray, but is {X.ndim}D")
    if X.split is not None and X.split != 0:
        raise NotImplementedError(f"Splittings other than 0 or None currently not supported, got {X.split}")
    xd = X._dense()
    if not types.heat_type_is_inexact(X.dtype):
        xd = xd.astype(jnp.float32)
    if Y is None:
        return xd, xd
    sanitize_in(Y)
    if Y.ndim != 2:
        raise NotImplementedError(f"Y should be a 2D DNDarray, but is {Y.ndim}D")
    if X.shape[1] != Y.shape[1]:
        raise ValueError(f"X and Y must have the same number of features, got {X.shape[1]} and {Y.shape[1]}")
    yd = Y._dense()
    if not types.heat_type_is_inexact(Y.dtype):
        yd = yd.astype(jnp.float32)
    return xd, yd


def _tile_metric(metric: str, x, y):
    """One (bn, bm) tile of the chosen metric (distance.py:17-135)."""
    if metric == "sqeuclidean":
        return _pairwise_sqeuclidean(x, y)
    if metric == "euclidean":
        return jnp.sqrt(_pairwise_sqeuclidean(x, y))
    if metric == "euclidean_direct":
        return _pairwise_direct(x, y)
    if metric == "manhattan":
        return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    raise ValueError(metric)


@functools.lru_cache(maxsize=64)
def _ring_cdist_fn(comm, metric: str, symmetric: bool, bn: int, bm: int, f: int, dtype: str):
    """Jitted ring distance program (reference _dist, distance.py:209-747).

    Per device: the standing X block (bn, f), a circulating Y block
    (bm, f), and the (bn, p*bm) output row-band.  ``symmetric`` runs only
    ceil(p/2) rounds and ppermutes each tile's transpose to its mirror
    owner.  The Python round loop unrolls at trace time, so every
    ppermute has a static permutation.
    """
    p = comm.size
    axis = comm.axis_name
    shift_back = [((i + 1) % p, i) for i in range(p)]  # receive from r+1

    def body(x_blk, y_blk):
        r = jax.lax.axis_index(axis)
        out = jnp.zeros((bn, p * bm), x_blk.dtype)
        y_cur = y_blk
        rounds = (p // 2 + 1) if symmetric else p
        zero = jnp.zeros((), jnp.int32)
        for it in range(rounds):
            j = (r + it) % p  # owner of the block currently held
            tile = _tile_metric(metric, x_blk, y_cur)
            out = jax.lax.dynamic_update_slice(out, tile, (zero, (j * bm).astype(jnp.int32)))
            if symmetric and 0 < it and not (p % 2 == 0 and it == p // 2):
                # mirror tile: rows of owner j, columns of owner r
                perm = [(i, (i + it) % p) for i in range(p)]
                mirror = jax.lax.ppermute(tile.T, axis, perm)
                src = (r - it) % p
                out = jax.lax.dynamic_update_slice(
                    out, mirror, (zero, (src * bm).astype(jnp.int32))
                )
            if it + 1 < rounds:
                y_cur = jax.lax.ppermute(y_cur, axis, shift_back)
        return out

    return jax.jit(
        _shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
    )


def _ring_eligible(X: DNDarray, Y: Optional[DNDarray]) -> bool:
    return (
        X.split == 0
        and X.comm.size > 1
        and (Y is None or (isinstance(Y, DNDarray) and Y.split == 0 and Y.comm == X.comm))
    )


def _ring_cdist(X: DNDarray, Y: Optional[DNDarray], metric: str) -> DNDarray:
    comm = X.comm
    symmetric = Y is None
    Yr = X if Y is None else Y
    x_blk = X.larray_padded
    y_blk = Yr.larray_padded
    if not types.heat_type_is_inexact(X.dtype):
        x_blk = x_blk.astype(jnp.float32)
    if not types.heat_type_is_inexact(Yr.dtype):
        y_blk = y_blk.astype(jnp.float32)
    if x_blk.dtype != y_blk.dtype:
        y_blk = y_blk.astype(x_blk.dtype)
    p = comm.size
    bn = x_blk.shape[0] // p
    bm = y_blk.shape[0] // p
    fn = _ring_cdist_fn(comm, metric, symmetric, bn, bm, int(X.shape[1]), str(x_blk.dtype))
    out = fn(x_blk, y_blk)  # (n_pad, m_pad) split 0
    m = Yr.shape[0]
    if out.shape[1] != m:
        out = out[:, :m]  # drop Y's padding columns (local slice per shard)
    return DNDarray(out, (X.shape[0], m), types.canonical_heat_type(out.dtype), 0, X.device, comm)


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Euclidean distance matrix (distance.py:136).

    Row-split inputs on a mesh take the memory-bounded ppermute ring
    (reference distance.py:209-747) — the full matrix exists only
    row-sharded, never per device."""
    if _ring_eligible(X, Y):
        _prep_checks(X, Y)
        # the distributed ring stays f32: its tile/output buffers share
        # the operand dtype, so the mixed-precision variant below (f32
        # accumulation over bf16 operands) applies to the eager tile
        # path only — the one serving's replicated predict batches take
        return _ring_cdist(X, Y, "euclidean" if quadratic_expansion else "euclidean_direct")
    xd, yd = _prep(X, Y)
    # through the executable cache: repeated shapes (iterative fits, the
    # serving layer's bucket-padded predict batches) hit one compiled
    # program instead of paying 4-6 eager jnp launches per call
    from ..core import dispatch

    op = _pairwise_euclidean if quadratic_expansion else _pairwise_direct
    if _active_lowp_dtype() == "bfloat16":
        # a tolerance-policy predict scope (precision_policy.scope +
        # HEAT_TPU_PREDICT_DTYPE=bfloat16) flips the cross term to bf16;
        # the direct metric also takes the expanded form here — its extra
        # cancellation error is far below the scope's declared rtol, and
        # bf16 has no broadcast-subtract MXU path to offer instead
        op = _pairwise_euclidean_bf16
    d = dispatch.eager_apply(op, (xd, yd))
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(d, split, X.device, X.comm)


def _prep_checks(X: DNDarray, Y: Optional[DNDarray]):
    sanitize_in(X)
    if X.ndim != 2:
        raise NotImplementedError(f"X should be a 2D DNDarray, but is {X.ndim}D")
    if Y is not None:
        sanitize_in(Y)
        if Y.ndim != 2:
            raise NotImplementedError(f"Y should be a 2D DNDarray, but is {Y.ndim}D")
        if X.shape[1] != Y.shape[1]:
            raise ValueError(
                f"X and Y must have the same number of features, got {X.shape[1]} and {Y.shape[1]}"
            )


cdist_small = cdist


@functools.lru_cache(maxsize=64)
def _ring_topk_fn(comm, k: int, bn: int, bm: int, m_true: int, dtype: str, lowp: bool = False):
    """Ring distance fused with a running k-smallest merge.

    The (bn, bm) tile of each round merges into a standing (bn, k)
    candidate set — the full (n, m) matrix never exists (reference KNN
    materializes it, kneighborsclassifier.py:114; this is the blocked
    fusion VERDICT r2 #3 asks for).  Returns (distances, global Y row
    indices), both (bn, k) per device.  ``lowp`` swaps the tile's cross
    term to bf16 operands with f32 accumulation (the tolerance-policy
    KNN predict path): the candidate/output buffers stay f32, so only
    the per-round MXU contraction narrows."""
    p = comm.size
    axis = comm.axis_name
    shift_back = [((i + 1) % p, i) for i in range(p)]

    def body(x_blk, y_blk):
        r = jax.lax.axis_index(axis)
        vals = jnp.full((bn, k), jnp.inf, x_blk.dtype)
        idxs = jnp.zeros((bn, k), jnp.int32)
        y_cur = y_blk
        for it in range(p):
            j = (r + it) % p
            if lowp:
                tile = _pairwise_sqeuclidean_bf16(x_blk, y_cur)
            else:
                tile = _tile_metric("sqeuclidean", x_blk, y_cur)
            gcol = j * bm + jnp.arange(bm, dtype=jnp.int32)  # global Y rows
            tile = jnp.where(gcol[None, :] < m_true, tile, jnp.inf)  # pad cols out
            cand_v = jnp.concatenate([vals, tile], axis=1)
            cand_i = jnp.concatenate([idxs, jnp.broadcast_to(gcol, (bn, bm))], axis=1)
            neg_top, pos = jax.lax.top_k(-cand_v, k)
            vals = -neg_top
            idxs = jnp.take_along_axis(cand_i, pos, axis=1)
            if it + 1 < p:
                y_cur = jax.lax.ppermute(y_cur, axis, shift_back)
        return jnp.sqrt(vals), idxs

    return jax.jit(
        _shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def cdist_topk(X: DNDarray, Y: DNDarray, k: int):
    """k smallest Euclidean distances and their Y-row indices per X row.

    Ring-fused on a mesh (peak memory O(n(k + m/p)/p) per device); dense
    distance + top_k otherwise.  Returns ``(dist, idx)`` DNDarrays of
    shape (n, k) with X's split."""
    _prep_checks(X, Y)
    k = int(k)
    if k > Y.shape[0]:
        raise ValueError(f"k={k} exceeds the number of Y rows ({Y.shape[0]})")
    if _ring_eligible(X, Y):
        comm = X.comm
        x_blk = X.larray_padded
        y_blk = Y.larray_padded
        if not types.heat_type_is_inexact(X.dtype):
            x_blk = x_blk.astype(jnp.float32)
        if not types.heat_type_is_inexact(Y.dtype):
            y_blk = y_blk.astype(jnp.float32)
        if x_blk.dtype != y_blk.dtype:
            y_blk = y_blk.astype(x_blk.dtype)
        p = comm.size
        lowp = _active_lowp_dtype() == "bfloat16" and x_blk.dtype == jnp.float32
        fn = _ring_topk_fn(
            comm, k, x_blk.shape[0] // p, y_blk.shape[0] // p, Y.shape[0], str(x_blk.dtype),
            lowp,
        )
        vals, idxs = fn(x_blk, y_blk)
        n = X.shape[0]
        dt = types.canonical_heat_type(vals.dtype)
        return (
            DNDarray(vals, (n, k), dt, 0, X.device, comm),
            DNDarray(idxs, (n, k), types.canonical_heat_type(idxs.dtype), 0, X.device, comm),
        )
    xd, yd = _prep(X, Y)
    if _active_lowp_dtype() == "bfloat16":
        d = _pairwise_sqeuclidean_bf16(xd, yd)
    else:
        d = _pairwise_sqeuclidean(xd, yd)
    neg_top, idx = jax.lax.top_k(-d, k)
    split = 0 if X.split is not None else None
    return (
        DNDarray.from_dense(jnp.sqrt(-neg_top), split, X.device, X.comm),
        DNDarray.from_dense(idx, split, X.device, X.comm),
    )


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """City-block distance matrix (distance.py:182).

    Ring-scheduled on a mesh like :func:`cdist`."""
    if _ring_eligible(X, Y):
        _prep_checks(X, Y)
        return _ring_cdist(X, Y, "manhattan")
    xd, yd = _prep(X, Y)
    from ..core import dispatch

    d = dispatch.eager_apply(_pairwise_manhattan, (xd, yd))
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(d, split, X.device, X.comm)


def rbf(X: DNDarray, Y: Optional[DNDarray] = None, sigma: float = 1.0, quadratic_expansion: bool = False) -> DNDarray:
    """Gaussian (RBF) kernel matrix exp(-d^2 / (2 sigma^2)) (distance.py:158).

    Ring-scheduled on a mesh; the exp is an elementwise pass over the
    row-sharded result."""
    if _ring_eligible(X, Y):
        _prep_checks(X, Y)
        d2 = _ring_cdist(X, Y, "sqeuclidean")
        out = jnp.exp(-d2.larray_padded / (2.0 * sigma * sigma))
        return DNDarray(out, d2.shape, d2.dtype, 0, X.device, X.comm)
    xd, yd = _prep(X, Y)
    d2 = _pairwise_sqeuclidean(xd, yd)
    k = jnp.exp(-d2 / (2.0 * sigma * sigma))
    split = 0 if X.split is not None else None
    return DNDarray.from_dense(k, split, X.device, X.comm)
