"""Declared control-plane protocol state machines (docs/static_analysis.md).

The decision journal (telemetry/journal.py) made every autonomous
controller's actions *observable*; this registry makes them
*verifiable*.  Each entry declares one controller's protocol as a
state machine — its states, its legal transitions, and the journal
``(actor, action)`` event each transition must emit — plus which
functions in the controller's module are sanctioned to write the
protocol state.  Three checkers share this one table:

* the **H8xx AST rules** (analysis/ast_lint.py): H801 flags protocol
  state written outside a registered transition/silent function, H802
  flags a registered transition function that never emits its declared
  journal event, H803 flags a journal emit whose literal ``(actor,
  action)`` pair is not declared here, H804 flags registry
  self-inconsistency (unreachable states, undeclared transition
  targets) — all enforced at cap 0 through ``scripts/lint_gate.py``;
* the **bounded model checker** (analysis/model_check.py) composes the
  declared machines with the small environment model below
  (:data:`ENVIRONMENT`) and exhaustively explores the product state
  space for the invariant :data:`PROPERTIES` — livelock cycles,
  unreachable recoveries, probe-count breaches — rendering each
  counterexample as a synthetic causal journal chain;
* the **runtime conformance checker** (analysis/conformance.py,
  ``HEAT_TPU_PROTOCOL_CHECK=0/1/raise``) replays the live
  ``DecisionEvent`` stream through the same machines and surfaces any
  illegal transition as an ``analysis.diags.H805`` diagnostic + a warn
  alert, one dict lookup per emit when off.

Like ``core/_env.py KNOBS``, ``resilience/faults.py KNOWN_SITES``,
``analysis/concurrency.py LOCK_REGISTRY`` and
``analysis/precision_policy.py POLICIES``, every table in this module
is a **pure literal**: ``ast.literal_eval`` over the source must
reproduce it exactly (the linter and the registry-hygiene tests parse
it statically, without importing anything).  Keep it that way — no
comprehensions, no name references, no function calls.

Registry schema (one entry per protocol)::

    "name": {
        "doc":      one-line description,
        "actor":    the journal actor every transition of this machine
                    emits under,
        "module":   repo-relative path of the owning controller module
                    (the H801/H802 rules apply inside it),
        "scope":    how conformance keys machine *instances*:
                    "model" (event.model), "replica"/"alert"/"gate"
                    (evidence key of that name) or "global",
        "initial":  the state a fresh instance starts in,
        "states":   every declared state,
        "transitions": records {"from", "to", "action", "when",
                    "effect"} — ``action`` is the journal action the
                    transition emits; ``when``/``effect`` are
                    model-checker atoms over :data:`ENVIRONMENT` vars
                    (and, in ``when``, other machines' states),
        "state_attrs": attribute names that ARE the protocol state in
                    the module (H801 flags writes outside sanctioned
                    functions),
        "state_keys": subscript string keys that hold the protocol
                    state (e.g. the canary window's ``"verdict"``),
        "transition_fns": functions sanctioned to write the state AND
                    required to contain the declared journal emit
                    (H802),
        "silent_fns": functions sanctioned to write the state without
                    emitting (``__init__``, lock-held helpers whose
                    caller emits),
    }

Atom syntax (model checker): ``"env.<var>=<value>"`` /
``"env.<var>!=<value>"`` tests an environment variable,
``"<machine>=<state>"`` / ``"<machine>!=<state>"`` tests another
machine in the same property's product.  Effects assign
(``"env.var=value"``) or step (``"env.var+=1"`` / ``"env.var-=1"``,
clamped to the declared domain).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, List, Set, Tuple

__all__ = [
    "PROTOCOLS",
    "ENVIRONMENT",
    "PROPERTIES",
    "declared_pairs",
    "protocol_for_pair",
    "registry_problems",
    "render_diagrams_markdown",
    # centralized journal vocabulary (derived-from-PROTOCOLS invariant
    # is asserted by tests/test_protocols.py)
    "ACTOR_ROUTER", "CB_TRIP", "CB_HALF_OPEN", "CB_READMIT", "CB_REOPEN",
    "ACTOR_CANARY", "CANARY_STAGE", "CANARY_VETO", "CANARY_PROMOTED",
    "CANARY_ROLLED_BACK", "CANARY_OBSERVED",
    "ACTOR_REPLICA", "REPLICA_WARM", "REPLICA_READY", "REPLICA_DRAIN",
    "REPLICA_STOP",
    "ACTOR_PREEMPT", "PREEMPT_RAISE", "PREEMPT_CLEAR",
    "ACTOR_AUTOSCALER", "SCALE_SPAWN", "SCALE_DRAIN",
    "ACTOR_REFRESH", "REFRESH_TRIGGER",
    "ACTOR_ALERTS", "ALERT_FIRE", "ALERT_RESOLVE",
    "ACTOR_STREAM", "STREAM_RESHARD",
    "ACTOR_ELASTIC", "ELASTIC_RESHAPE",
    "ACTOR_FLIGHT_RECORDER", "FLIGHT_RECORDER_BUNDLE",
]

# ----------------------------------------------------------------------
# the journal vocabulary: one constant per declared actor/action, so
# emit sites, /decisionz rendering and the docs cannot drift apart.
# tests assert this set equals exactly the set derived from PROTOCOLS.
# ----------------------------------------------------------------------
ACTOR_ROUTER = "router"
CB_TRIP = "cb_trip"
CB_HALF_OPEN = "cb_half_open"
CB_READMIT = "cb_readmit"
CB_REOPEN = "cb_reopen"

ACTOR_CANARY = "canary"
CANARY_STAGE = "stage"
CANARY_VETO = "veto"
CANARY_PROMOTED = "promoted"
CANARY_ROLLED_BACK = "rolled_back"
CANARY_OBSERVED = "observed"

ACTOR_REPLICA = "replica"
REPLICA_WARM = "warm"
REPLICA_READY = "ready"
REPLICA_DRAIN = "drain"
REPLICA_STOP = "stop"

ACTOR_PREEMPT = "preempt"
PREEMPT_RAISE = "raise"
PREEMPT_CLEAR = "clear"

ACTOR_AUTOSCALER = "autoscaler"
SCALE_SPAWN = "spawn"
SCALE_DRAIN = "drain"

ACTOR_REFRESH = "refresh"
REFRESH_TRIGGER = "trigger"

ACTOR_ALERTS = "alerts"
ALERT_FIRE = "fire"
ALERT_RESOLVE = "resolve"

ACTOR_STREAM = "stream"
STREAM_RESHARD = "reshard"

ACTOR_ELASTIC = "elastic"
ELASTIC_RESHAPE = "reshape"

ACTOR_FLIGHT_RECORDER = "flight_recorder"
FLIGHT_RECORDER_BUNDLE = "bundle"


#: every controller's declared protocol machine — PURE LITERAL (see
#: the module docstring for the schema and the atom syntax)
PROTOCOLS = {
    "router.breaker": {
        "doc": "per-replica circuit breaker in the fleet router: "
               "closed -> open on consecutive failures, exactly one "
               "half-open probe after the cooldown, readmit on a "
               "successful probe, re-open on a failed one",
        "actor": "router",
        "module": "heat_tpu/fleet/router.py",
        "scope": "replica",
        "initial": "closed",
        "states": ("closed", "open", "half_open"),
        "transitions": (
            {"from": "closed", "to": "open", "action": "cb_trip",
             "when": ("env.replica_up=no",), "effect": ()},
            {"from": "open", "to": "half_open", "action": "cb_half_open",
             "when": ("env.probes=0",), "effect": ("env.probes=1",)},
            {"from": "half_open", "to": "closed", "action": "cb_readmit",
             "when": ("env.replica_up=yes",), "effect": ("env.probes=0",)},
            {"from": "half_open", "to": "open", "action": "cb_reopen",
             "when": ("env.replica_up=no",), "effect": ("env.probes=0",)},
        ),
        "state_attrs": ("cb_open", "probing"),
        "state_keys": (),
        "transition_fns": ("_pick", "_report"),
        "silent_fns": ("__init__", "_cb_mark_probe", "_cb_on_success",
                       "_cb_on_failure"),
    },
    "canary": {
        "doc": "canary decision plane: a staged version is resident "
               "until the shadow window decides; a veto (firing drift/"
               "SLO alert) holds it resident — never terminal",
        "actor": "canary",
        "module": "heat_tpu/serving/canary.py",
        "scope": "model",
        "initial": "absent",
        "states": ("absent", "resident", "promoted", "rolled_back",
                   "observed"),
        "transitions": (
            {"from": "absent", "to": "resident", "action": "stage",
             "when": ("env.staged=yes",),
             "effect": ("env.staged=no", "env.shadow=collecting")},
            {"from": "resident", "to": "resident", "action": "stage",
             "when": ("env.staged=yes",),
             "effect": ("env.staged=no", "env.shadow=collecting")},
            {"from": "promoted", "to": "resident", "action": "stage",
             "when": ("env.staged=yes",),
             "effect": ("env.staged=no", "env.shadow=collecting")},
            {"from": "rolled_back", "to": "resident", "action": "stage",
             "when": ("env.staged=yes",),
             "effect": ("env.staged=no", "env.shadow=collecting")},
            {"from": "observed", "to": "resident", "action": "stage",
             "when": ("env.staged=yes",),
             "effect": ("env.staged=no", "env.shadow=collecting")},
            {"from": "resident", "to": "resident", "action": "veto",
             "when": ("env.shadow=pass", "env.drift=firing"),
             "effect": ()},
            {"from": "resident", "to": "promoted", "action": "promoted",
             "when": ("env.shadow=pass", "env.drift=idle"),
             "effect": ()},
            {"from": "resident", "to": "rolled_back",
             "action": "rolled_back",
             "when": ("env.shadow=fail",), "effect": ()},
            {"from": "resident", "to": "observed", "action": "observed",
             "when": ("env.shadow=pass", "env.drift=idle"),
             "effect": ()},
        ),
        "state_attrs": (),
        "state_keys": ("verdict",),
        "transition_fns": ("_journal_stage", "_hold", "_decide"),
        "silent_fns": (),
    },
    "replica": {
        "doc": "serving replica lifecycle behind /readyz: born ready "
               "in-process, warming in the fleet spawn path, draining "
               "finishes in-flight work, stopped is terminal",
        "actor": "replica",
        "module": "heat_tpu/serving/service.py",
        "scope": "replica",
        "initial": "ready",
        "states": ("warming", "ready", "draining", "stopped"),
        "transitions": (
            {"from": "ready", "to": "warming", "action": "warm",
             "when": (), "effect": ()},
            {"from": "warming", "to": "ready", "action": "ready",
             "when": (), "effect": ()},
            {"from": "ready", "to": "draining", "action": "drain",
             "when": (), "effect": ()},
            {"from": "warming", "to": "draining", "action": "drain",
             "when": (), "effect": ()},
            {"from": "ready", "to": "stopped", "action": "stop",
             "when": (), "effect": ()},
            {"from": "warming", "to": "stopped", "action": "stop",
             "when": (), "effect": ()},
            {"from": "draining", "to": "stopped", "action": "stop",
             "when": (), "effect": ()},
        ),
        "state_attrs": ("_state",),
        "state_keys": (),
        "transition_fns": ("set_state",),
        "silent_fns": ("__init__",),
    },
    "preempt": {
        "doc": "level-triggered preemption gate between latency "
               "traffic and checkpointed fits: a raise must always "
               "have a reachable clear",
        "actor": "preempt",
        "module": "heat_tpu/core/preempt.py",
        "scope": "gate",
        "initial": "idle",
        "states": ("idle", "raised"),
        "transitions": (
            {"from": "idle", "to": "raised", "action": "raise",
             "when": ("env.spike=on",), "effect": ()},
            {"from": "raised", "to": "idle", "action": "clear",
             "when": ("env.spike=off",), "effect": ()},
        ),
        "state_attrs": ("_reason",),
        "state_keys": (),
        "transition_fns": ("request", "clear"),
        "silent_fns": ("__init__",),
    },
    "autoscaler": {
        "doc": "hysteresis autoscaler actuations: spawn answers "
               "sustained overload, drain sustained underload — no "
               "spawn/drain cycle without an environment change",
        "actor": "autoscaler",
        "module": "heat_tpu/fleet/autoscaler.py",
        "scope": "global",
        "initial": "steady",
        "states": ("steady",),
        "transitions": (
            {"from": "steady", "to": "steady", "action": "spawn",
             "when": ("env.load=high",), "effect": ("env.load=normal",)},
            {"from": "steady", "to": "steady", "action": "drain",
             "when": ("env.load=low",), "effect": ("env.load=normal",)},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("_journal_scale",),
        "silent_fns": (),
    },
    "refresh": {
        "doc": "drift-triggered refresh driver: re-fit + fresh "
               "baseline + canary stage, only while no canary is "
               "already resident (the decision plane owns the next "
               "transition)",
        "actor": "refresh",
        "module": "heat_tpu/streaming/refresh.py",
        "scope": "model",
        "initial": "watching",
        "states": ("watching",),
        "transitions": (
            {"from": "watching", "to": "watching", "action": "trigger",
             "when": ("env.drift=firing", "canary!=resident"),
             "effect": ("env.baseline=fresh", "env.staged=yes")},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("_refresh",),
        "silent_fns": (),
    },
    "alerts": {
        "doc": "deduplicated alert lifecycle: one fired transition "
               "per active (name, labels), idempotent resolve",
        "actor": "alerts",
        "module": "heat_tpu/telemetry/alerts.py",
        "scope": "alert",
        "initial": "inactive",
        "states": ("inactive", "firing"),
        "transitions": (
            {"from": "inactive", "to": "firing", "action": "fire",
             "when": (), "effect": ()},
            {"from": "firing", "to": "inactive", "action": "resolve",
             "when": (), "effect": ()},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("fire", "resolve"),
        "silent_fns": (),
    },
    "stream": {
        "doc": "streaming consumer key-distribution watcher: a "
               "sustained PSI shift triggers exactly one reshard",
        "actor": "stream",
        "module": "heat_tpu/streaming/consumer.py",
        "scope": "global",
        "initial": "consuming",
        "states": ("consuming",),
        "transitions": (
            {"from": "consuming", "to": "consuming", "action": "reshard",
             "when": (), "effect": ()},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("_fold_keys",),
        "silent_fns": (),
    },
    "elastic": {
        "doc": "elastic supervisor mesh reshape after worker loss",
        "actor": "elastic",
        "module": "heat_tpu/elastic/supervisor.py",
        "scope": "global",
        "initial": "supervising",
        "states": ("supervising",),
        "transitions": (
            {"from": "supervising", "to": "supervising",
             "action": "reshape", "when": (), "effect": ()},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("_recover",),
        "silent_fns": (),
    },
    "flight_recorder": {
        "doc": "forensic bundle dump chained off a canary rollback",
        "actor": "flight_recorder",
        "module": "heat_tpu/serving/canary.py",
        "scope": "model",
        "initial": "armed",
        "states": ("armed",),
        "transitions": (
            {"from": "armed", "to": "armed", "action": "bundle",
             "when": (), "effect": ()},
        ),
        "state_attrs": (),
        "state_keys": (),
        "transition_fns": ("_dump_bundle",),
        "silent_fns": (),
    },
}


#: the small adversarial environment the model checker composes the
#: machines with — PURE LITERAL.  Variables are finite domains (the
#: first value is the initial one); events are the world's moves,
#: guarded by ``when`` atoms and applying ``set`` assignments.  The
#: environment is deliberately pessimistic: a firing drift alert only
#: resolves against a FRESH baseline (live traffic is never assumed to
#: drift back on its own), and a passing shadow window can always
#: degrade to fail (the window keeps accumulating until the decision).
ENVIRONMENT = {
    "vars": {
        "drift": ("idle", "firing"),
        "baseline": ("stale", "fresh"),
        "shadow": ("collecting", "pass", "fail"),
        "staged": ("no", "yes"),
        "spike": ("off", "on"),
        "load": ("normal", "high", "low"),
        "replica_up": ("yes", "no"),
        "probes": (0, 1, 2),
    },
    "events": (
        {"name": "drift_fires",
         "when": ("env.drift=idle", "env.baseline=stale"),
         "set": ("env.drift=firing",)},
        {"name": "drift_resolves",
         "when": ("env.drift=firing", "env.baseline=fresh"),
         "set": ("env.drift=idle",)},
        {"name": "distribution_shifts",
         "when": ("env.drift=idle", "env.baseline=fresh"),
         "set": ("env.baseline=stale",)},
        {"name": "shadow_passes",
         "when": ("env.shadow=collecting",),
         "set": ("env.shadow=pass",)},
        {"name": "shadow_fails",
         "when": ("env.shadow=collecting",),
         "set": ("env.shadow=fail",)},
        {"name": "shadow_degrades",
         "when": ("env.shadow=pass",),
         "set": ("env.shadow=fail",)},
        {"name": "operator_stages",
         "when": ("env.staged=no",),
         "set": ("env.staged=yes",)},
        {"name": "spike_starts",
         "when": ("env.spike=off",),
         "set": ("env.spike=on",)},
        {"name": "spike_ends",
         "when": ("env.spike=on",),
         "set": ("env.spike=off",)},
        {"name": "load_rises",
         "when": ("env.load=normal",),
         "set": ("env.load=high",)},
        {"name": "load_falls",
         "when": ("env.load=normal",),
         "set": ("env.load=low",)},
        {"name": "replica_dies",
         "when": ("env.replica_up=yes",),
         "set": ("env.replica_up=no",)},
        {"name": "replica_recovers",
         "when": ("env.replica_up=no",),
         "set": ("env.replica_up=yes",)},
    ),
}


#: the model-checked invariants — PURE LITERAL.  Kinds:
#:
#: * ``never``: the atom conjunction must hold in NO reachable product
#:   state (safety); counterexample = the path that reaches it.
#: * ``reach``: from EVERY reachable state satisfying ``when``, some
#:   state satisfying ``goal`` must be reachable (no stuck region);
#:   counterexample = the path into the stuck region plus the livelock
#:   cycle (or deadlock) it is trapped in.
#: * ``no_cycle``: no reachable cycle exists that contains every action
#:   in ``actions``, none in ``forbid_actions``, and (unless
#:   ``env_ok``) no environment event at all — the flap/livelock shape.
PROPERTIES = (
    {"name": "breaker_single_probe",
     "kind": "never",
     "doc": "the circuit breaker admits at most one half-open probe "
            "in flight per replica",
     "machines": ("router.breaker",),
     "atoms": ("env.probes=2",)},
    {"name": "breaker_recovers",
     "kind": "reach",
     "doc": "an open breaker can always readmit its replica once the "
            "replica recovers (closed stays reachable)",
     "machines": ("router.breaker",),
     "when": ("router.breaker=open",),
     "goal": ("router.breaker=closed",)},
    {"name": "canary_decides",
     "kind": "reach",
     "doc": "a resident canary can always reach a decision — the "
            "drift veto must never pin it resident forever",
     "machines": ("refresh", "canary"),
     "when": ("canary=resident",),
     "goal": ("canary!=resident", "canary!=absent")},
    {"name": "refresh_no_livelock",
     "kind": "no_cycle",
     "doc": "the refresh driver must not re-fire against its own "
            "vetoed canary: no trigger/veto cycle without an "
            "intervening decision",
     "machines": ("refresh", "canary"),
     "actions": ("trigger", "veto"),
     "forbid_actions": ("promoted", "rolled_back", "observed"),
     "env_ok": True},
    {"name": "preempt_clear_reachable",
     "kind": "reach",
     "doc": "a raised preemption request can always be cleared once "
            "the latency spike drains",
     "machines": ("preempt",),
     "when": ("preempt=raised",),
     "goal": ("preempt=idle",)},
    {"name": "autoscaler_no_flap",
     "kind": "no_cycle",
     "doc": "hysteresis holds: no spawn/drain cycle without an "
            "intervening load change",
     "machines": ("autoscaler",),
     "actions": ("spawn", "drain"),
     "forbid_actions": (),
     "env_ok": False},
)


# ----------------------------------------------------------------------
# derivations (shared by the linter loaders, conformance, the docs
# generator and the hygiene tests)
# ----------------------------------------------------------------------
def declared_pairs(
    protocols: Dict[str, Any] = None,
) -> Set[Tuple[str, str]]:
    """Every declared journal ``(actor, action)`` pair."""
    table = PROTOCOLS if protocols is None else protocols
    out: Set[Tuple[str, str]] = set()
    for rec in table.values():
        for t in rec["transitions"]:
            out.add((rec["actor"], t["action"]))
    return out


def protocol_for_pair(
    actor: str, action: str, protocols: Dict[str, Any] = None,
) -> List[str]:
    """Names of the protocols declaring ``(actor, action)`` (hygiene
    requires exactly one)."""
    table = PROTOCOLS if protocols is None else protocols
    return sorted(
        name for name, rec in table.items()
        if rec["actor"] == actor
        and any(t["action"] == action for t in rec["transitions"])
    )


def registry_problems(protocols: Dict[str, Any] = None) -> List[str]:
    """Structural defects in a PROTOCOLS-shaped table: transitions
    from/to undeclared states, an initial state outside ``states``,
    declared-but-unreachable states, and an ``(actor, action)`` pair
    claimed by two protocols.  Empty on the shipped registry (the H804
    rule and the hygiene tests both assert it)."""
    table = PROTOCOLS if protocols is None else protocols
    problems: List[str] = []
    pair_owner: Dict[Tuple[str, str], str] = {}
    for name, rec in sorted(table.items()):
        states = set(rec["states"])
        if rec["initial"] not in states:
            problems.append(
                f"{name}: initial state {rec['initial']!r} is not in "
                f"states {sorted(states)}"
            )
        adjacency: Dict[str, Set[str]] = {s: set() for s in states}
        for t in rec["transitions"]:
            for end, label in ((t["from"], "from"), (t["to"], "to")):
                if end not in states:
                    problems.append(
                        f"{name}: transition {t['action']!r} {label}-state "
                        f"{end!r} is not a declared state"
                    )
            if t["from"] in states and t["to"] in states:
                adjacency[t["from"]].add(t["to"])
            pair = (rec["actor"], t["action"])
            owner = pair_owner.setdefault(pair, name)
            if owner != name:
                problems.append(
                    f"{name}: journal pair {pair!r} is already declared "
                    f"by protocol {owner!r}"
                )
        if rec["initial"] in states:
            seen = {rec["initial"]}
            frontier = [rec["initial"]]
            while frontier:
                for nxt in adjacency.get(frontier.pop(), ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            for s in sorted(states - seen):
                problems.append(
                    f"{name}: state {s!r} is unreachable from initial "
                    f"{rec['initial']!r} via the declared transitions"
                )
    return problems


def transition_index(
    protocols: Dict[str, Any] = None,
) -> Dict[Tuple[str, str], Tuple[str, str, Tuple[Tuple[str, str], ...]]]:
    """``(actor, action) -> (protocol, scope, ((from, to), ...))`` — the
    lookup table the runtime conformance checker steps events through."""
    table = PROTOCOLS if protocols is None else protocols
    out: Dict[Tuple[str, str], Tuple[str, str, Tuple[Tuple[str, str], ...]]] = {}
    for name, rec in sorted(table.items()):
        for t in rec["transitions"]:
            pair = (rec["actor"], t["action"])
            prev = out.get(pair)
            edges = (prev[2] if prev else ()) + ((t["from"], t["to"]),)
            out[pair] = (name, rec["scope"], edges)
    return out


def render_diagrams_markdown(protocols: Dict[str, Any] = None) -> str:
    """Per-controller state-machine diagrams as markdown (embedded
    between the ``protocol-diagrams`` markers in docs/observability.md;
    tests assert the docs match this output)."""
    table = PROTOCOLS if protocols is None else protocols
    lines: List[str] = []
    for name in sorted(table):
        rec = table[name]
        lines.append(
            f"**`{name}`** — actor `{rec['actor']}`, `{rec['module']}`, "
            f"scope `{rec['scope']}` — {rec['doc']}"
        )
        lines.append("")
        lines.append("```")
        width = max(len(str(t["from"])) for t in rec["transitions"])
        for t in rec["transitions"]:
            frm = str(t["from"]).rjust(width)
            marker = " *" if t["from"] == rec["initial"] else "  "
            guard = ""
            if t["when"]:
                guard = "   [" + " & ".join(t["when"]) + "]"
            lines.append(f"{marker}{frm} --{t['action']}--> {t['to']}{guard}")
        lines.append("```")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"
