"""Parity tests for the NumPy APIs beyond the reference's checklist.

The reference's coverage_tables.md marks these ❌; implementing them is a
capability extension, so every function here is checked against the NumPy
ground truth across splits (the reference's assert_func_equal idiom).
"""

import numpy as np
import pytest

from utils import assert_func_equal

RNG = np.random.default_rng(7)
A = RNG.standard_normal((11, 5)).astype(np.float32)
P = np.abs(A) + 0.5
V = RNG.standard_normal(13).astype(np.float32)


class TestElementwiseExtras:
    def test_unary_extras(self, ht):
        for name, arg in [
            ("rint", A),
            ("fix", A),
            ("around", A),
            ("cbrt", A),
            ("reciprocal", P),
            ("spacing", P),
            ("sinc", A),
            ("i0", A),
        ]:
            np_fn = getattr(np, name)
            assert_func_equal(getattr(ht, name), np_fn, [arg], splits=(None, 0, 1), rtol=1e-5, atol=1e-6)

    def test_binary_extras(self, ht):
        for name, a, b in [
            ("ldexp", A, RNG.integers(-3, 4, A.shape).astype(np.int32)),
            ("nextafter", A, A + 1),
            ("float_power", P, A),
            ("heaviside", A, P),
            ("true_divide", A, P),
        ]:
            expected = getattr(np, name)(a, b)
            for split in (None, 0, 1):
                got = getattr(ht, name)(ht.array(a, split=split), ht.array(b, split=split))
                np.testing.assert_allclose(got.numpy(), expected, rtol=1e-6, err_msg=f"{name} split={split}")

    def test_frexp(self, ht):
        em, ee = np.frexp(P)
        for split in (None, 0, 1):
            m, e = ht.frexp(ht.array(P, split=split))
            np.testing.assert_allclose(m.numpy(), em)
            np.testing.assert_array_equal(e.numpy(), ee)

    def test_unwrap(self, ht):
        ph = np.cumsum(RNG.uniform(0, 4, 17)).astype(np.float64)
        for split in (None, 0):
            got = ht.unwrap(ht.array(ph, split=split))
            np.testing.assert_allclose(got.numpy(), np.unwrap(ph), rtol=1e-12)

    def test_real_if_close(self, ht):
        close = np.array([1 + 1e-16j, 2 + 0j])
        far = np.array([1 + 1j])
        assert ht.real_if_close(ht.array(close, split=0)).dtype == ht.float64
        assert ht.real_if_close(ht.array(far)).dtype == ht.complex128


class TestCumulativeAndDifference:
    def test_nancum(self, ht):
        a = A.copy()
        a[2, 3] = np.nan
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            np.testing.assert_allclose(ht.nancumsum(x, 0).numpy(), np.nancumsum(a, 0), rtol=1e-6)
            np.testing.assert_allclose(ht.nancumprod(x, 1).numpy(), np.nancumprod(a, 1), rtol=1e-5)

    def test_ediff1d(self, ht):
        for split in (None, 0):
            got = ht.ediff1d(ht.array(V, split=split), to_begin=np.float32(0), to_end=np.float32(9))
            np.testing.assert_allclose(got.numpy(), np.ediff1d(V, to_begin=np.float32(0), to_end=np.float32(9)), rtol=1e-6)

    def test_gradient(self, ht):
        m = RNG.standard_normal((9, 6)).astype(np.float64)
        for split in (None, 0, 1):
            g0, g1 = ht.gradient(ht.array(m, split=split))
            e0, e1 = np.gradient(m)
            np.testing.assert_allclose(g0.numpy(), e0, rtol=1e-12)
            np.testing.assert_allclose(g1.numpy(), e1, rtol=1e-12)
            gx = ht.gradient(ht.array(m, split=split), 2.5, axis=1)
            np.testing.assert_allclose(gx.numpy(), np.gradient(m, 2.5, axis=1), rtol=1e-12)

    def test_trapz_interp(self, ht):
        m = RNG.standard_normal((9, 6)).astype(np.float64)
        for split in (None, 0, 1):
            x = ht.array(m, split=split)
            np.testing.assert_allclose(ht.trapz(x, dx=0.5, axis=0).numpy(), np.trapz(m, dx=0.5, axis=0), rtol=1e-12)
            np.testing.assert_allclose(ht.trapezoid(x, axis=1).numpy(), np.trapezoid(m, axis=1) if hasattr(np, "trapezoid") else np.trapz(m, axis=1), rtol=1e-12)
        q = np.linspace(-1, 10, 23)
        for split in (None, 0):
            got = ht.interp(ht.array(q, split=split), [0.0, 4.0, 9.0], [1.0, -1.0, 5.0], left=-7.0, right=7.0)
            np.testing.assert_allclose(got.numpy(), np.interp(q, [0, 4, 9], [1, -1, 5], left=-7, right=7), rtol=1e-12)
