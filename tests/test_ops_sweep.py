"""Data-driven parity sweep: every op family vs NumPy across splits.

This is the analog of the reference's assert_func_equal idiom
(test_suites/basic_test.py): one ground truth, all distributions.
Shapes are non-divisible by the 8-device mesh to exercise pad-and-mask.
"""

import numpy as np
import pytest

RNG = np.random.default_rng(42)
A = RNG.standard_normal((13, 7)).astype(np.float32)
B = RNG.standard_normal((13, 7)).astype(np.float32)
P = np.abs(A) + 0.5  # strictly positive
I1 = RNG.integers(1, 20, (13, 7)).astype(np.int32)
I2 = RNG.integers(1, 20, (13, 7)).astype(np.int32)

UNARY = [
    ("sin", A), ("cos", A), ("tan", A), ("arcsin", np.clip(A, -0.9, 0.9)),
    ("arccos", np.clip(A, -0.9, 0.9)), ("arctan", A), ("sinh", A), ("cosh", A),
    ("tanh", A), ("arcsinh", A), ("arctanh", np.clip(A, -0.9, 0.9)),
    ("exp", A), ("expm1", A), ("exp2", A), ("log", P), ("log2", P),
    ("log10", P), ("log1p", P), ("sqrt", P), ("abs", A), ("ceil", A),
    ("floor", A), ("trunc", A), ("sign", A), ("negative", A),
    ("deg2rad", A), ("rad2deg", A), ("isnan", A), ("isinf", A), ("isfinite", A),
    ("signbit", A), ("square", A),
]

NP_ALIASES = {}

BINARY = [
    ("add", A, B), ("subtract", A, B), ("multiply", A, B),
    ("divide", A, P), ("floor_divide", A, P), ("mod", A, P),
    ("fmod", A, P), ("power", P, B), ("copysign", A, B), ("hypot", P, np.abs(B)),
    ("maximum", A, B), ("minimum", A, B), ("arctan2", A, B),
    ("gcd", I1, I2), ("lcm", I1, I2),
    ("logaddexp", A, B), ("logaddexp2", A, B),
]

REDUCTIONS = [
    ("sum", A, {}), ("prod", np.sign(A) * 1.01, {}), ("mean", A, {}),
    ("std", A, {}), ("var", A, {}), ("min", A, {}), ("max", A, {}),
    ("sum", A, {"axis": 0}), ("sum", A, {"axis": 1}),
    ("mean", A, {"axis": 0}), ("var", A, {"axis": 1}),
    ("min", A, {"axis": 0}), ("max", A, {"axis": 1}),
    ("nansum", np.where(A > 1, np.nan, A), {}),
    ("nanprod", np.where(A > 1, np.nan, np.sign(A) * 1.01), {}),
]

LOGICAL = [
    ("logical_and", A > 0, B > 0), ("logical_or", A > 0, B > 0),
    ("logical_xor", A > 0, B > 0),
]

MANIP = [
    ("flipud", A, {}), ("fliplr", A, {}), ("transpose", A, {}),
    ("ravel", A, {}), ("squeeze", A[None], {}), ("rot90", A, {}),
    ("swapaxes", A, {"axis1": 0, "axis2": 1}),
    ("moveaxis", A, {"source": 0, "destination": 1}),
]


def _splits_for(arr):
    return (None, 0, 1) if arr.ndim >= 2 else (None, 0)


class TestUnarySweep:
    @pytest.mark.parametrize("name,data", UNARY, ids=[u[0] for u in UNARY])
    def test_unary(self, ht, name, data):
        np_fn = NP_ALIASES.get(name, getattr(np, name, None))
        if np_fn is None:
            pytest.skip(f"no ground truth for {name}")
        expected = np_fn(data.astype(np.float64)) if data.dtype.kind == "f" else np_fn(data)
        fn = getattr(ht, name)
        for split in _splits_for(data):
            got = fn(ht.array(data, split=split)).numpy()
            np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5, err_msg=f"{name} split={split}")


class TestBinarySweep:
    @pytest.mark.parametrize("name,x,y", BINARY, ids=[b[0] for b in BINARY])
    def test_binary(self, ht, name, x, y):
        np_fn = getattr(np, name)
        expected = np_fn(x, y)
        fn = getattr(ht, name)
        for split in _splits_for(x):
            got = fn(ht.array(x, split=split), ht.array(y, split=split)).numpy()
            np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5, err_msg=f"{name} split={split}")

    def test_mixed_split_binary(self, ht):
        """Operands with different splits must still combine correctly
        (_operations.py:22 split-matching via sanitize_distribution)."""
        for s1 in (None, 0, 1):
            for s2 in (None, 0, 1):
                got = (ht.array(A, split=s1) + ht.array(B, split=s2)).numpy()
                np.testing.assert_allclose(got, A + B, rtol=1e-6, err_msg=f"{s1}+{s2}")

    def test_broadcasting(self, ht):
        row = B[0]
        for split in (None, 0, 1):
            got = (ht.array(A, split=split) * ht.array(row)).numpy()
            np.testing.assert_allclose(got, A * row, rtol=1e-6)
        col = B[:, :1]
        got = (ht.array(A, split=0) + ht.array(col, split=0)).numpy()
        np.testing.assert_allclose(got, A + col, rtol=1e-6)


class TestReductionSweep:
    @pytest.mark.parametrize(
        "name,data,kw", REDUCTIONS, ids=[f"{r[0]}-{r[2].get('axis','all')}" for r in REDUCTIONS]
    )
    def test_reduction(self, ht, name, data, kw):
        expected = getattr(np, name)(data.astype(np.float64), **kw)
        fn = getattr(ht, name)
        for split in _splits_for(data):
            got = fn(ht.array(data, split=split), **kw)
            got = got.numpy() if hasattr(got, "numpy") else np.asarray(got)
            np.testing.assert_allclose(
                got.astype(np.float64), expected, rtol=1e-4, atol=1e-5, err_msg=f"{name} split={split} {kw}"
            )

    def test_all_any_keepdims(self, ht):
        m = A > 0
        for split in (None, 0, 1):
            x = ht.array(m, split=split)
            assert bool(ht.all(x)) == bool(m.all())
            assert bool(ht.any(x)) == bool(m.any())
            np.testing.assert_array_equal(
                ht.all(x, axis=0, keepdims=True).numpy(), m.all(0, keepdims=True)
            )
            np.testing.assert_array_equal(
                ht.any(x, axis=1, keepdims=True).numpy(), m.any(1, keepdims=True)
            )

    def test_allclose_isclose_equal(self, ht):
        for split in (None, 0, 1):
            x = ht.array(A, split=split)
            y = ht.array(A + 1e-8, split=split)
            assert ht.allclose(x, y)
            assert bool(ht.isclose(x, y).all())
            assert ht.equal(x, ht.array(A, split=split))
            assert not ht.equal(x, ht.array(B, split=split))


class TestLogicalSweep:
    @pytest.mark.parametrize("name,x,y", LOGICAL, ids=[b[0] for b in LOGICAL])
    def test_logical(self, ht, name, x, y):
        expected = getattr(np, name)(x, y)
        fn = getattr(ht, name)
        for split in _splits_for(x):
            got = fn(ht.array(x, split=split), ht.array(y, split=split)).numpy()
            np.testing.assert_array_equal(got, expected)

    def test_logical_not(self, ht):
        m = A > 0
        for split in (None, 0, 1):
            np.testing.assert_array_equal(
                ht.logical_not(ht.array(m, split=split)).numpy(), ~m
            )


class TestManipulationSweep:
    @pytest.mark.parametrize("name,data,kw", MANIP, ids=[m[0] for m in MANIP])
    def test_manip(self, ht, name, data, kw):
        expected = getattr(np, name)(data, **kw)
        fn = getattr(ht, name)
        for split in _splits_for(data):
            got = fn(ht.array(data, split=split), **kw).numpy()
            np.testing.assert_allclose(got, expected, rtol=1e-6, err_msg=f"{name} split={split}")

    def test_where_nonzero(self, ht):
        for split in (None, 0, 1):
            x = ht.array(A, split=split)
            np.testing.assert_allclose(
                ht.where(x > 0, x, 0.0).numpy(), np.where(A > 0, A, 0.0), rtol=1e-6
            )
            nz = ht.nonzero(x > 0)
            np_nz = np.nonzero(A > 0)
            if isinstance(nz, (tuple, list)):
                for g, e in zip(nz, np_nz):
                    np.testing.assert_array_equal(g.numpy(), e)
            else:
                np.testing.assert_array_equal(nz.numpy(), np.stack(np_nz, 1))


class TestLinalgSweep:
    def test_norms(self, ht):
        for split in (None, 0, 1):
            x = ht.array(A, split=split)
            np.testing.assert_allclose(float(ht.norm(x)), np.linalg.norm(A), rtol=1e-5)
            np.testing.assert_allclose(
                ht.vector_norm(x, axis=1).numpy(), np.linalg.norm(A, axis=1), rtol=1e-5
            )
            np.testing.assert_allclose(
                float(ht.matrix_norm(x, ord="fro")), np.linalg.norm(A, "fro"), rtol=1e-5
            )

    def test_dot_outer_trace(self, ht):
        v = A[:, 0].copy()
        w = B[:, 0].copy()
        for split in (None, 0):
            hv, hw = ht.array(v, split=split), ht.array(w, split=split)
            np.testing.assert_allclose(float(ht.dot(hv, hw)), v @ w, rtol=1e-5)
            np.testing.assert_allclose(ht.outer(hv, hw).numpy(), np.outer(v, w), rtol=1e-5)
            np.testing.assert_allclose(float(ht.vdot(hv, hw)), np.vdot(v, w), rtol=1e-5)
        sq = A[:7, :7]
        for split in (None, 0, 1):
            np.testing.assert_allclose(
                float(ht.trace(ht.array(sq, split=split))), np.trace(sq), rtol=1e-5
            )

    def test_matmul_all_split_combos(self, ht):
        X = RNG.standard_normal((9, 5)).astype(np.float32)
        Y = RNG.standard_normal((5, 11)).astype(np.float32)
        expected = X @ Y
        for s1 in (None, 0, 1):
            for s2 in (None, 0, 1):
                got = ht.matmul(ht.array(X, split=s1), ht.array(Y, split=s2)).numpy()
                np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4, err_msg=f"{s1}x{s2}")

    def test_cross_tril_triu(self, ht):
        u = RNG.standard_normal((6, 3)).astype(np.float32)
        v = RNG.standard_normal((6, 3)).astype(np.float32)
        for split in (None, 0):
            np.testing.assert_allclose(
                ht.cross(ht.array(u, split=split), ht.array(v, split=split)).numpy(),
                np.cross(u, v),
                rtol=1e-5,
            )
        for split in (None, 0, 1):
            x = ht.array(A, split=split)
            np.testing.assert_allclose(ht.tril(x).numpy(), np.tril(A), rtol=1e-6)
            np.testing.assert_allclose(ht.triu(x, k=1).numpy(), np.triu(A, 1), rtol=1e-6)
