"""Sparse depth tests mirroring the reference's split sweeps
(heat/sparse/tests/test_dcsrmatrix.py, test_dcscmatrix.py,
test_arithmetics_csr.py, test_manipulations.py idiom: every property and
op checked against the scipy/numpy ground truth for split in (None, 0/1)).
"""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture(scope="module")
def mats():
    rng = np.random.default_rng(11)
    a = rng.standard_normal((9, 7))
    b = rng.standard_normal((9, 7))
    a[rng.random(a.shape) < 0.6] = 0.0
    b[rng.random(b.shape) < 0.6] = 0.0
    return a, b


def _csr_truth(m):
    import scipy.sparse as sp

    return sp.csr_matrix(m)


try:
    import scipy.sparse  # noqa: F401

    HAVE_SCIPY = True
except ImportError:  # pragma: no cover
    HAVE_SCIPY = False


@pytest.mark.parametrize("split", [None, 0])
def test_dcsr_triple_matches_scipy(mats, split):
    if not HAVE_SCIPY:
        pytest.skip("scipy missing")
    a, _ = mats
    s = ht.sparse.sparse_csr_matrix(a, split=split)
    truth = _csr_truth(a)
    assert s.gnnz == truth.nnz
    np.testing.assert_array_equal(np.asarray(s.indptr), truth.indptr)
    np.testing.assert_array_equal(np.asarray(s.indices), truth.indices)
    np.testing.assert_allclose(np.asarray(s.data), truth.data)
    # g-aliases (reference dcsx_matrix.py:143,167,196)
    np.testing.assert_array_equal(np.asarray(s.gindptr), truth.indptr)
    np.testing.assert_array_equal(np.asarray(s.gindices), truth.indices)
    np.testing.assert_allclose(np.asarray(s.gdata), truth.data)


def test_dcsc_triple_matches_scipy(mats):
    if not HAVE_SCIPY:
        pytest.skip("scipy missing")
    import scipy.sparse as sp

    a, _ = mats
    s = ht.sparse.sparse_csc_matrix(a, split=1)
    truth = sp.csc_matrix(a)
    assert s.gnnz == truth.nnz
    np.testing.assert_array_equal(np.asarray(s.indptr), truth.indptr)
    np.testing.assert_array_equal(np.asarray(s.indices), truth.indices)
    np.testing.assert_allclose(np.asarray(s.data), truth.data)


def test_counts_displs_nnz(mats):
    if not HAVE_SCIPY:
        pytest.skip("scipy missing")
    a, _ = mats
    s = ht.sparse.sparse_csr_matrix(a, split=0)
    counts, displs = s.counts_displs_nnz()
    truth = _csr_truth(a)
    assert sum(counts) == truth.nnz
    assert displs[0] == 0
    # displacements are the Exscan of counts (reference dcsx_matrix.py:278)
    np.testing.assert_array_equal(np.cumsum((0,) + counts[:-1]), displs)
    assert len(counts) == s.comm.size


@pytest.mark.parametrize("axis", [None, 0, 1, -1])
def test_sparse_sum(mats, axis):
    a, _ = mats
    s = ht.sparse.sparse_csr_matrix(a, split=0)
    res = ht.sparse.sum(s, axis=axis)
    np.testing.assert_allclose(np.asarray(res.numpy()), a.sum(axis=axis), rtol=1e-12)
    # method form
    res2 = s.sum(axis=axis)
    np.testing.assert_allclose(np.asarray(res2.numpy()), a.sum(axis=axis), rtol=1e-12)


def test_sparse_dense_matmul(mats):
    a, _ = mats
    rng = np.random.default_rng(12)
    d = rng.standard_normal((7, 5))
    s = ht.sparse.sparse_csr_matrix(a, split=0)

    out = s @ ht.array(d, split=0)
    np.testing.assert_allclose(out.numpy(), a @ d, rtol=1e-12)
    out = s @ d
    np.testing.assert_allclose(out.numpy(), a @ d, rtol=1e-12)

    # dense @ sparse
    e = rng.standard_normal((4, 9))
    out = ht.array(e, split=0) @ s
    np.testing.assert_allclose(out.numpy(), e @ a, rtol=1e-12)
    out = ht.sparse.matmul(e, s)
    np.testing.assert_allclose(out.numpy(), e @ a, rtol=1e-12)


def test_sparse_sparse_matmul(mats):
    a, b = mats
    s1 = ht.sparse.sparse_csr_matrix(a, split=0)
    s2 = ht.sparse.sparse_csr_matrix(b.T.copy(), split=0)
    out = s1 @ s2
    assert isinstance(out, ht.sparse.DCSR_matrix)
    np.testing.assert_allclose(out.todense().numpy(), a @ b.T, rtol=1e-12)
    assert out.shape == (9, 9)


@pytest.mark.parametrize("split", [None, 0])
def test_roundtrip_csr(mats, split):
    a, _ = mats
    x = ht.array(a, split=split)
    s = ht.sparse.to_sparse_csr(x)
    back = ht.sparse.to_dense(s)
    np.testing.assert_allclose(back.numpy(), a, rtol=1e-12)
    assert back.split == s.split


def test_roundtrip_csc(mats):
    a, _ = mats
    x = ht.array(a, split=1)
    s = ht.sparse.to_sparse_csc(x)
    assert s.split == 1
    back = ht.sparse.to_dense(s)
    np.testing.assert_allclose(back.numpy(), a, rtol=1e-12)


def test_sparse_add_mul_sweep(mats):
    a, b = mats
    for split in (None, 0):
        s1 = ht.sparse.sparse_csr_matrix(a, split=split)
        s2 = ht.sparse.sparse_csr_matrix(b, split=split)
        np.testing.assert_allclose((s1 + s2).todense().numpy(), a + b, rtol=1e-12)
        np.testing.assert_allclose((s1 * s2).todense().numpy(), a * b, rtol=1e-12)


def test_is_distributed(mats):
    a, _ = mats
    assert ht.sparse.sparse_csr_matrix(a, split=0).is_distributed()
    assert not ht.sparse.sparse_csr_matrix(a).is_distributed()


def test_astype_and_transpose(mats):
    a, _ = mats
    s = ht.sparse.sparse_csr_matrix(a, split=0)
    s32 = s.astype(ht.float32)
    assert s32.dtype == ht.float32
    t = s.T
    assert isinstance(t, ht.sparse.DCSC_matrix)
    assert t.split == 1
    np.testing.assert_allclose(t.todense().numpy(), a.T, rtol=1e-6)
