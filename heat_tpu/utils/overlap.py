"""Overlap layer: hide host-side latency behind device compute.

PR 1 made single-op dispatch cheap and PR 2 made long fits resumable,
but three host-side latencies were still paid *serially* on the device
timeline:

* ``Checkpointer.save`` blocked the fit loop for the full atomic write
  (~22 ms per ``checkpoint_every`` chunk on the CI grid);
* data loaders landed batches unsharded on the default device, paying
  the host->device copy inside the consuming step;
* the DP training path reduced gradients as one monolithic collective
  with no way to overlap transport with the remaining backward pass.

This module is the shared surface of the overlap layer that removes
them (the same latency-hiding pattern the reference implements with
per-layer ``Iallreduce`` hooks in its non-blocking DASO pipeline,
``heat/optim/dp_optimizer.py`` ``_nonblocking_hook``):

* :class:`AsyncCheckpointer` — snapshot device state non-blockingly and
  run the existing atomic-rename+CRC32 write (retry policy included) on
  a bounded background writer.  At most **one** save is in flight;
  overrun back-pressures; writer errors re-raise at the next
  ``save()``/``wait()``/``close()``.  The write itself stays the
  resilience layer's staged-dir-plus-atomic-rename commit, so a kill
  mid-async-write never leaves a partial step visible.  Fault site:
  ``checkpoint.async_write`` (evaluated on the writer thread, after the
  device snapshot is ready and before the filesystem write).
* the **overlap counters** (:func:`overlap_stats`): ``async_saves`` /
  ``sync_saves`` / ``ckpt_stall_ms`` from the checkpoint path,
  ``prefetch_hits`` / ``prefetch_misses`` from the device-prefetch
  iterators (:mod:`heat_tpu.utils.data.prefetch`,
  :class:`~heat_tpu.utils.data.PartialH5DataLoaderIter`), and
  ``grad_buckets`` from the bucketed gradient reduction
  (:func:`heat_tpu.nn.data_parallel.reduce_gradients`).  ``bench.py``'s
  ``bench_overlap`` config and ``scripts/perf_ci.py`` publish them.

``HEAT_TPU_ASYNC_CKPT=0`` disables the async path globally (resumable
fits fall back to fully synchronous saves).  See ``docs/overlap.md``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..analysis import tsan as _tsan
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing
from ..telemetry.spans import span as _span

__all__ = [
    "AsyncCheckpointer",
    "async_checkpoint_enabled",
    "overlap_stats",
    "reset_overlap_stats",
    "snapshot_state",
]


def async_checkpoint_enabled() -> bool:
    """Whether resumable fits use the async checkpoint path (default on;
    ``HEAT_TPU_ASYNC_CKPT=0`` selects the PR 2 synchronous saves)."""
    v = os.environ.get("HEAT_TPU_ASYNC_CKPT")
    if v is None:
        return True
    return v.strip().lower() not in ("0", "false", "no", "off")


# ----------------------------------------------------------------------
# shared overlap counters.  They live in the shared telemetry registry
# as ``overlap.*`` (``telemetry.snapshot()`` reports them alongside the
# dispatch/resilience/comm domains); :func:`overlap_stats` is a thin
# byte-compatible view.
# ----------------------------------------------------------------------
_COUNTER_NAMES = (
    "async_saves",
    "sync_saves",
    "ckpt_stall_ms",
    "prefetch_hits",
    "prefetch_misses",
    "grad_buckets",
)
_STATS = {n: _tm.counter(f"overlap.{n}") for n in _COUNTER_NAMES}


def _bump(name: str, amount=1) -> None:
    _STATS[name].inc(amount)


def overlap_stats() -> Dict[str, Any]:
    """Snapshot of the overlap counters.

    ``async_saves``/``sync_saves`` count checkpoint writes by schedule;
    ``ckpt_stall_ms`` is the cumulative wall time the *caller* spent
    blocked inside async ``save()``/``wait()`` — the part of the write
    the device timeline actually saw (a fully hidden write contributes
    ~0).  ``prefetch_hits``/``prefetch_misses`` count batches that were
    staged on device ahead of the consumer vs. staged synchronously on
    demand (``prefetch_hit_rate`` derives from them).  ``grad_buckets``
    counts collective buckets issued by the bucketed gradient-reduction
    schedule at trace time.

    A thin view over the shared telemetry registry (the counters live
    there as ``overlap.*``)."""
    s: Dict[str, Any] = {n: _STATS[n].value for n in _COUNTER_NAMES}
    s["ckpt_stall_ms"] = float(s["ckpt_stall_ms"])
    total = s["prefetch_hits"] + s["prefetch_misses"]
    s["prefetch_hit_rate"] = (s["prefetch_hits"] / total) if total else 0.0
    return s


def reset_overlap_stats() -> None:
    """Zero all overlap counters; delegates to
    ``telemetry.reset_all("overlap")``."""
    from ..telemetry import reset_all

    reset_all("overlap")


# ----------------------------------------------------------------------
# async checkpointing
# ----------------------------------------------------------------------
def snapshot_state(state: Any) -> Any:
    """Cheap consistent snapshot of a checkpoint payload.

    JAX arrays are immutable, so holding the reference *is* the snapshot
    — no host transfer happens here; ``block_until_ready`` +
    device-to-host conversion run on the writer thread.  DNDarrays
    snapshot as their (lazily forced) dense global array for the same
    reason.  NumPy leaves are mutable and are copied (a host memcpy,
    orders of magnitude cheaper than the encode+CRC+fsync write).
    Scalars/strings pass through."""
    from ..core.dndarray import DNDarray  # lazy: avoid import cycle
    from .checkpoint import DNDSnapshot

    def one(x):
        if isinstance(x, DNDarray):
            # carry the distribution intent (split, writer world) so the
            # cross-world restore codec can re-split the leaf later
            return DNDSnapshot(x._dense(), x.split, x.comm.size)
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x

    return jax.tree_util.tree_map(
        one, state, is_leaf=lambda x: isinstance(x, DNDarray)
    )


class AsyncCheckpointer:
    """Non-blocking front end over a :class:`~heat_tpu.utils.checkpoint.Checkpointer`.

    ``save(step, state)`` snapshots the (device) state without blocking
    on it and hands the atomic write to a background writer thread, so a
    fit loop overlaps the write with its next on-device chunk.  The
    write path is unchanged from the synchronous checkpointer — io retry
    policy, staged temp dir, CRC32 sidecars, one atomic directory rename
    — so every atomicity/bitwise-resume guarantee carries over; the only
    new failure surface is *when* an error is seen:

    * at most one save is in flight; a second ``save()`` during a write
      back-pressures (blocks) until the first completes;
    * a writer error is stored and re-raised at the next ``save()``,
      ``wait()`` or ``close()`` — never swallowed;
    * ``close()`` (or context-manager exit) drains the writer, so a
      caller returning from a fit knows its last checkpoint is durable.

    Read-side methods (``restore``/``latest_step``/``all_steps``/
    ``metadata``) first wait for any in-flight write, so a reader never
    misses the step it just saved.
    """

    def __init__(self, checkpointer, max_pending: int = 1):
        from .checkpoint import Checkpointer  # lazy: avoid import cycle

        if isinstance(checkpointer, str):
            checkpointer = Checkpointer(checkpointer)
        self.checkpointer = checkpointer
        if max_pending != 1:
            raise ValueError(
                f"AsyncCheckpointer is bounded at exactly 1 in-flight save, "
                f"got max_pending={max_pending!r}"
            )
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # written by the background writer, swapped out by the fit
        # thread — the registered lock is what the sanitizer checks
        self._error_lock = _tsan.register_lock("overlap.async_writer")

    # -- write side -----------------------------------------------------
    def save(self, step: int, state: Any, extra_metadata=None, async_: bool = True) -> None:
        """Enqueue one checkpoint write (or run it synchronously with
        ``async_=False``).  Blocks only for the snapshot and for any
        previous in-flight write (back-pressure); re-raises a pending
        writer error before accepting new work."""
        t0 = time.perf_counter()
        self.wait()  # back-pressure (<=1 in flight) + error surface
        if not async_:
            with _span("checkpoint.save", step=step, mode="sync"):
                self.checkpointer.save(step, state, extra_metadata)
            _bump("sync_saves")
            return
        with _span("checkpoint.save", step=step, mode="async"):
            snap = snapshot_state(state)
            ctx = _tracing.current_context()  # caller -> writer-thread handoff

            def _write():
                try:
                    # the writer's spans inherit the trace (if any) of
                    # whoever enqueued the save, so an async write shows
                    # up attached to its request/fit in /tracez
                    with _tracing.use_context(ctx), _span(
                        "checkpoint.async_write", step=step
                    ):
                        jax.block_until_ready(snap)  # device->writer handoff point
                        _inject("checkpoint.async_write", step=step)
                        self.checkpointer.save(step, snap, extra_metadata)
                except BaseException as e:  # lint: allow H501(writer error surfaced at next save/wait/close)
                    with self._error_lock:
                        _tsan.note_access("overlap.async_writer.state")
                        self._error = e

        t = threading.Thread(
            target=_write, name=f"heat-tpu-async-ckpt-{step}", daemon=True
        )
        self._thread = t
        t.start()
        _bump("async_saves")
        _bump("ckpt_stall_ms", (time.perf_counter() - t0) * 1e3)

    def wait(self) -> None:
        """Block until no write is in flight; re-raise any writer error."""
        t0 = time.perf_counter()
        t = self._thread
        if t is threading.current_thread():
            # re-entrant call from the writer itself (the write path's
            # pruning walks the step list, which drains-by-contract):
            # the in-flight save is this very call — nothing to wait for
            return
        if t is not None:
            with _span("checkpoint.drain"):
                t.join()
            self._thread = None
            _bump("ckpt_stall_ms", (time.perf_counter() - t0) * 1e3)
        with self._error_lock:
            _tsan.note_access("overlap.async_writer.state")
            err, self._error = self._error, None
        if err is not None:
            raise err

    def close(self) -> None:
        """Drain the writer (idempotent); re-raises a pending error."""
        self.wait()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            # don't mask the in-flight body exception with a writer error
            try:
                self.close()
            except BaseException:  # lint: allow H501(body exception wins over a writer error)
                pass

    # -- read side (sees in-flight writes through) ----------------------
    def restore(self, step=None, template=None, comm=None):
        """Drain in-flight writes, then restore — cross-world ``comm``
        re-splitting included (see ``Checkpointer.restore``)."""
        self.wait()
        with _span("checkpoint.restore", step=step if step is not None else -1):
            return self.checkpointer.restore(step, template, comm)

    def latest_step(self):
        self.wait()
        return self.checkpointer.latest_step()

    def world_size(self, step=None):
        self.wait()
        return self.checkpointer.world_size(step)

    def all_steps(self) -> List[int]:
        self.wait()
        return self.checkpointer.all_steps()

    def metadata(self, step: int):
        self.wait()
        return self.checkpointer.metadata(step)

    @property
    def directory(self) -> str:
        return self.checkpointer.directory
