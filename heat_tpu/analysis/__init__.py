"""Static analysis: SPMD program lint + framework-invariant AST lint +
concurrency sanitizer.

Three cooperating analyzers (docs/static_analysis.md):

* :mod:`~heat_tpu.analysis.program_lint` — walks the jaxpr and compiled
  (post-GSPMD) HLO of a program for SPMD hazards the type system cannot
  see: implicit unaccounted collectives (J101), accidental full gathers
  of the split axis (J102), weak-type / python-scalar recompile hazards
  (J103), donation misses (J104) and silent dtype promotion (J105).
  Hooked into the ``core/dispatch.py`` compile path
  (``HEAT_TPU_ANALYZE=0/1/raise`` — off/warn/error) and callable
  standalone via :func:`analyze`.  Diagnostics flow into the telemetry
  registry (``analysis.diags.{rule}`` counters) and a bounded ring
  (:func:`recent_diagnostics`).
* :mod:`~heat_tpu.analysis.ast_lint` — custom AST visitors enforcing
  the repo's own invariants with stable rule IDs (H101 raw writes, H201
  unregistered env knobs, H301 unaccounted collectives, H302
  unregistered fault sites, H401 host syncs in chunk bodies, H501
  fault-swallowing broad excepts, H601 clock-entropy seeding, and the
  H701–H705 concurrency rules over the central
  :data:`~heat_tpu.analysis.concurrency.LOCK_REGISTRY`).  Run as
  ``python -m heat_tpu.analysis <paths>``; ``scripts/lint_gate.py``
  gates CI against ``scripts/lint_baseline.json``.
* the precision & memory layer (ISSUE 12):
  :mod:`~heat_tpu.analysis.dtype_flow` walks the jaxpr for precision
  hazards (J201 silent truncation, J202 long-axis low-precision
  accumulation, J203 unpinned low-precision contractions, J204
  precision-policy violations);
  :mod:`~heat_tpu.analysis.memory_model` predicts peak per-device HBM
  from the jaxpr (liveness + donation aliasing + sharding division) and
  emits J301 against ``HEAT_TPU_HBM_BUDGET_BYTES``;
  :mod:`~heat_tpu.analysis.precision_policy` holds the pure-literal
  :data:`~heat_tpu.analysis.precision_policy.POLICIES` registry of
  per-estimator precision contracts (``bitwise`` | ``tolerance``),
  enforced at the dispatch hook, the model registry, and the
  ``python -m heat_tpu.analysis --rules J2,J3`` batch mode.
* :mod:`~heat_tpu.analysis.tsan` — the runtime concurrency sanitizer
  (``HEAT_TPU_TSAN=0/1/raise``): every lock in ``LOCK_REGISTRY`` is an
  instrumented proxy feeding a global lock-order graph (cycle =
  potential deadlock, ``tsan.lock_cycle``) and guarded-structure
  checkpoints (``tsan.unguarded_access``), with acquisition stacks
  attached to every finding.
* the control-plane protocol layer (ISSUE 20):
  :mod:`~heat_tpu.analysis.protocols` holds the pure-literal
  :data:`~heat_tpu.analysis.protocols.PROTOCOLS` registry — every
  autonomous controller's state machine, the journal ``(actor,
  action)`` each transition emits, and the temporal properties the
  composed system must satisfy — enforced statically by the H801–H804
  AST rules, exhaustively by the bounded model checker
  (``python -m heat_tpu.analysis.model_check``,
  :mod:`~heat_tpu.analysis.model_check`), and at runtime by
  :mod:`~heat_tpu.analysis.conformance`
  (``HEAT_TPU_PROTOCOL_CHECK=0/1/raise``), which steps every live
  journal emit through the declared machines and reports illegal
  transitions as H805.

This package ``__init__`` is **lazy** (PEP 562): the low-level modules
that create registered locks at import time (``telemetry.metrics`` is
among the first modules the package loads) import
``heat_tpu.analysis.tsan`` — a stdlib-only module — and must not drag
in the jax-dependent analyzers (``diagnostics`` reads the env-knob
registry, ``program_lint`` imports jax) while they are themselves mid-
import.  Attribute access resolves the public API on first use.
"""

from __future__ import annotations

import importlib

__all__ = [
    "AnalysisWarning",
    "Diagnostic",
    "LOCK_REGISTRY",
    "POLICIES",
    "PROPERTIES",
    "PROTOCOLS",
    "PrecisionPolicyError",
    "ProgramLintError",
    "RULES",
    "Violation",
    "analysis_mode",
    "analyze",
    "analyze_compiled_text",
    "analyze_dtype_flow",
    "analyze_jaxpr",
    "check_all",
    "check_property",
    "clear_diagnostics",
    "concurrency",
    "conformance",
    "conformance_report",
    "estimate_peak",
    "lint_file",
    "lint_paths",
    "model_check",
    "note_emit",
    "protocol_mode",
    "protocols",
    "recent_diagnostics",
    "set_analysis_mode",
    "set_protocol_mode",
    "tsan",
]

#: public name -> defining submodule (resolved lazily on first access)
_EXPORTS = {
    "RULES": "ast_lint",
    "Violation": "ast_lint",
    "lint_file": "ast_lint",
    "lint_paths": "ast_lint",
    "AnalysisWarning": "diagnostics",
    "Diagnostic": "diagnostics",
    "ProgramLintError": "diagnostics",
    "analysis_mode": "diagnostics",
    "clear_diagnostics": "diagnostics",
    "recent_diagnostics": "diagnostics",
    "set_analysis_mode": "diagnostics",
    "analyze": "program_lint",
    "analyze_compiled_text": "program_lint",
    "analyze_jaxpr": "program_lint",
    "analyze_dtype_flow": "dtype_flow",
    "estimate_peak": "memory_model",
    "POLICIES": "precision_policy",
    "PrecisionPolicyError": "precision_policy",
    "LOCK_REGISTRY": "concurrency",
    "PROTOCOLS": "protocols",
    "PROPERTIES": "protocols",
    "check_all": "model_check",
    "check_property": "model_check",
    "conformance_report": "conformance",
    "note_emit": "conformance",
    "protocol_mode": "conformance",
    "set_protocol_mode": "conformance",
}

_SUBMODULES = (
    "ast_lint",
    "concurrency",
    "conformance",
    "diagnostics",
    "dtype_flow",
    "memory_model",
    "model_check",
    "precision_policy",
    "program_lint",
    "protocols",
    "tsan",
)


def __getattr__(name: str):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    modname = _EXPORTS.get(name)
    if modname is not None:
        mod = importlib.import_module(f".{modname}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
