"""Streaming continuous learning (docs/streaming.md).

Exactly-once ingest over replayable sources, online estimator fits with
bitwise kill+resume (offset committed atomically with model state), and
the drift-triggered refresh driver that feeds the serving decision
plane a freshly trained canary.
"""

from .consumer import StreamConsumer
from .online import StreamingKMeans, StreamingLasso, StreamingPCA
from .refresh import RefreshDriver
from .source import FileSegmentLog, StreamSource, SyntheticStream

__all__ = [
    "FileSegmentLog",
    "RefreshDriver",
    "StreamConsumer",
    "StreamSource",
    "StreamingKMeans",
    "StreamingLasso",
    "StreamingPCA",
    "SyntheticStream",
]
