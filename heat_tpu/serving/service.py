"""Inference service: registry + coalescer + admission behind one surface.

:class:`InferenceService` is the composition the serving design doc
draws: a request enters through :meth:`~InferenceService.predict`
(Python) or ``POST /v1/predict`` (HTTP), passes **admission control**
(per-tenant quota, bounded depth — shed with
:class:`~heat_tpu.resilience.errors.OverloadedError`/429, never
queued-to-collapse), lands in its model's **coalescer** queue, rides a
padded **bucket** batch through the executable cache, and returns with
its slice of the batch result.

Every request runs under a **trace**
(:mod:`heat_tpu.telemetry.tracing`): one ``trace_id`` stamps the
``serve.request`` root, the per-stage spans (admission → coalesce_wait →
pad → dispatch → execute → scatter, across the request and batcher
threads), and any nested compile/comm spans.  End-to-end latency lands
in ``serving.latency_ms`` and each stage in its
``serving.stage.{stage}_ms`` histogram — bucket exemplars carry the
most recent trace_id, so a ``/metrics`` latency bucket links to the
concrete request retained in ``/tracez``; shed and errored requests are
always retained there.

HTTP surface (mounted on the telemetry introspection server through
:func:`~heat_tpu.telemetry.server.register_route` — one process, one
port):

=====================================  ================================
route                                  payload
=====================================  ================================
``GET /v1/models``                     registry listing: versions,
                                       active pointer, rollback history
``POST /v1/predict``                   ``{"model", "inputs", "tenant"?,
                                       "version"?}`` -> predictions
``GET /v1/models/<name>/healthz``      per-model liveness: loaded
                                       version, batcher thread alive,
                                       queue depth, last batch age
=====================================  ================================

Estimators are hot-swappable: the coalescer resolves the registry's
*active* version at every batch, so ``promote``/``rollback`` take
effect on the next tick with zero downtime and zero dropped requests.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..analysis import tsan as _tsan
from ..resilience.errors import OverloadedError
from ..resilience.faults import inject as _inject
from ..telemetry import alerts as _alerts
from ..telemetry import metrics as _tm
from ..telemetry import server as _tserver
from ..telemetry import sketch as _sketch
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..telemetry.spans import stage_note as _stage_note
from .admission import AdmissionController
from .coalescer import ModelBatcher, observe_stage
from .model_io import infer as _infer
from .registry import ModelRegistry

__all__ = [
    "InferenceService",
    "default_service",
    "start_serving",
    "stop_serving",
]

_LATENCY_H = _tm.histogram(
    "serving.latency_ms", "end-to-end predict latency (admission to result)"
)

#: route prefix the service mounts on the introspection server
ROUTE_PREFIX = "/v1/"


def _env():
    from ..core import _env as envmod

    return envmod


class InferenceService:
    """A running inference service over a :class:`ModelRegistry`.

    ``split`` is the batch axis distribution of coalesced batches:
    ``None`` (default) replicates the bucket-padded batch — the right
    call at online batch sizes, and the path whose every op rides the
    executable cache; ``0`` shards rows across the serving mesh for
    large-bucket deployments (its predict programs are the jitted ring
    kernels, cached per bucket by jax itself).  Knobs default from the
    registry (``HEAT_TPU_SERVE_*``); constructor arguments override per
    instance."""

    def __init__(
        self,
        registry: Optional[ModelRegistry] = None,
        comm=None,
        split: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
    ):
        env = _env()
        self.registry = registry if registry is not None else ModelRegistry(comm=comm)
        self.split = split
        self.max_batch = (
            int(max_batch) if max_batch is not None
            else env.env_int("HEAT_TPU_SERVE_MAX_BATCH")
        )
        delay_ms = (
            float(max_delay_ms) if max_delay_ms is not None
            else env.env_float("HEAT_TPU_SERVE_MAX_DELAY_MS")
        )
        self.max_delay_s = delay_ms / 1e3
        self.admission = AdmissionController(
            max_depth=(
                int(queue_depth) if queue_depth is not None
                else env.env_int("HEAT_TPU_SERVE_QUEUE_DEPTH")
            ),
            default_rate=(
                float(rate) if rate is not None
                else env.env_float("HEAT_TPU_SERVE_RATE")
            ),
            default_burst=(
                float(burst) if burst is not None
                else env.env_float("HEAT_TPU_SERVE_BURST")
            ),
        )
        self._batchers: Dict[str, ModelBatcher] = {}
        self._open = True
        self._started_monitor = False
        self._lock = _tsan.register_lock("serving.service")

    # -- model lifecycle (thin registry delegates) ----------------------
    def load(self, name: str, directory: str, **kwargs) -> int:
        """Hot-load a model version (see :meth:`ModelRegistry.load`)."""
        return self.registry.load(name, directory, **kwargs)

    def load_async(self, name: str, directory: str, **kwargs):
        """Background hot-load (see :meth:`ModelRegistry.load_async`)."""
        return self.registry.load_async(name, directory, **kwargs)

    def set_quota(self, tenant: str, rate: float, burst: Optional[float] = None) -> None:
        self.admission.set_quota(tenant, rate, burst)

    # -- the hot path ---------------------------------------------------
    def _batcher(self, name: str) -> ModelBatcher:
        self.registry.record(name)  # KeyError -> 404 before a thread spawns
        with self._lock:
            _tsan.note_access("serving.service.state")
            if not self._open:
                raise RuntimeError("inference service is closed")
            b = self._batchers.get(name)
            if b is None:
                b = self._batchers[name] = ModelBatcher(
                    name,
                    lambda rows, _n=name: self._infer_batch(_n, rows),
                    max_batch=self.max_batch,
                    max_delay_s=self.max_delay_s,
                    # drift sketches fold each batch's TRUE rows in
                    # after the callers are woken (HEAT_TPU_SKETCH)
                    on_batch=lambda rows, _n=name: _sketch.record_batch(_n, rows),
                )
            return b

    def _infer_batch(self, name: str, rows: np.ndarray) -> np.ndarray:
        """One coalesced inference on the ACTIVE version (batcher thread,
        under the primary request's trace context).  Decomposed into the
        ``dispatch`` stage (DNDarray wrap + program dispatch — any
        compile span nests here and inherits the trace) and the
        ``execute`` stage (forcing the result: device compute + fetch)."""
        from ..core import factories

        est = self.registry.get(name)
        tid = _tracing.current_trace_id()
        t0 = time.perf_counter_ns()
        # the ambient trace context is live here, so a cold bucket's
        # dispatch.compile span inherits the request that paid for it
        x = factories.array(rows, split=self.split, comm=self.registry.comm)
        y = _infer(est, x)
        t1 = time.perf_counter_ns()
        _stage_note("serve.dispatch", t0, t1 - t0, model=name, rows=int(rows.shape[0]))
        observe_stage("dispatch", (t1 - t0) / 1e6, tid)
        t0 = time.perf_counter_ns()
        out = y.numpy()
        t1 = time.perf_counter_ns()
        _stage_note("serve.execute", t0, t1 - t0, model=name)
        observe_stage("execute", (t1 - t0) / 1e6, tid)
        return out

    def predict(
        self,
        name: str,
        rows,
        tenant: str = "default",
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        """Predict ``rows`` (one (n, features) request) on model
        ``name``; blocks until the coalesced batch answers.

        Raises :class:`OverloadedError` when shed, ``KeyError`` for an
        unknown model, the batch's error when its dispatch failed."""
        out, _info = self._predict(name, rows, tenant=tenant, timeout=timeout)
        return out

    def _predict(
        self,
        name: str,
        rows,
        tenant: str = "default",
        timeout: Optional[float] = None,
    ):
        """The traced predict path: returns ``(out, info)`` where
        ``info`` carries the request's ``trace_id`` and its measured
        ``latency_ms`` — the ONE timing source both the
        ``serving.latency_ms`` histogram and the HTTP response report
        (the route must never re-time the request independently)."""
        rows = np.asarray(rows)
        if rows.ndim == 1:
            rows = rows[None, :]
        _inject("serve.predict", model=name, rows=int(rows.shape[0]))
        n = int(rows.shape[0])
        req = _tracing.request_span(
            f"/v1/predict/{name}", model=name, tenant=tenant, rows=n
        )
        with req:
            t0 = time.perf_counter_ns()
            try:
                self.admission.admit(tenant, n)
            finally:
                t1 = time.perf_counter_ns()
                _stage_note(
                    "serve.admission", t0, t1 - t0, tenant=tenant, rows=n
                )
            observe_stage("admission", (t1 - t0) / 1e6, req.trace_id)
            try:
                out = self._batcher(name).submit(rows, timeout=timeout)
            finally:
                self.admission.release(n)
        _LATENCY_H.observe(
            req.duration_ms,
            exemplar=req.trace_id
            if (req.trace_id and _tracing.exemplars_enabled())
            else None,
        )
        return out, {"trace_id": req.trace_id, "latency_ms": req.duration_ms}

    # -- per-model health ----------------------------------------------
    def model_health(self, name: str) -> Dict[str, Any]:
        """``(healthy, doc)`` folded into one doc with a ``healthy``
        key: loaded version, batcher liveness, queue depth, last-batch
        timestamp + trace_id — enough for an operator to tell "idle"
        (no queue, old batch) from "stuck" (deep queue, old batch) and
        to jump from a stuck model straight to its last served trace in
        ``/tracez``, without scraping ``/varz``."""
        rec = self.registry.record(name)  # KeyError -> 404 upstream
        with self._lock:
            _tsan.note_access("serving.service.state", write=False)
            b = self._batchers.get(name)
        now = time.time()
        doc: Dict[str, Any] = {
            "model": name,
            "status": "ok",
            "healthy": True,
            "version": rec["version"],
            "kind": rec["kind"],
            "loaded_age_s": round(now - rec["loaded_at"], 3),
            "world_size_written": rec["world_size_written"],
            "world_size_serving": rec["world_size_serving"],
            "queued_rows": b.queued_rows() if b is not None else 0,
            "admitted_rows_in_flight": self.admission.depth(),
            "last_batch_ts": (
                b.last_batch_ts if b is not None and b.last_batch_ts > 0 else None
            ),
            "last_batch_age_s": (
                round(now - b.last_batch_ts, 3)
                if b is not None and b.last_batch_ts > 0
                else None
            ),
            "last_batch_trace_id": b.last_batch_trace_id if b is not None else None,
        }
        if b is None:
            doc["status"] = "idle"  # loaded, no traffic yet — healthy
        elif not b.alive():
            doc["status"] = "dead"
            doc["healthy"] = False
        # quality signals: the model's drift score and any alert that
        # names it — liveness (healthy/503) is unaffected, but the
        # status string flips so a canary driver or operator sees a
        # drifting model without scraping /driftz
        drift = _sketch.SKETCHES.status(name)
        doc["drift"] = {
            "score": drift["score"],
            "drifting": drift["drifting"],
            "threshold": drift["threshold"],
            "baseline": drift["baseline"],
            "sketched_rows": drift["sketched_rows"],
        }
        doc["alerts"] = [
            a for a in _alerts.active_alerts()
            if a["labels"].get("model") == name or a["name"] == f"drift:{name}"
        ]
        if drift["drifting"] and doc["status"] in ("ok", "idle"):
            doc["status"] = "drifting"
        return doc

    def freeze_baseline(self, name: str) -> Dict[str, Any]:
        """Freeze the model's live input sketch as its drift baseline
        (runtime capture — e.g. right after warm-up traffic known to be
        in-distribution); returns the baseline document, which
        :func:`~heat_tpu.serving.model_io.save_model` can persist with
        the next version."""
        self.registry.record(name)  # KeyError -> 404 upstream
        return _sketch.SKETCHES.freeze_baseline(name)

    # -- HTTP -----------------------------------------------------------
    def serve(self, port: Optional[int] = None) -> str:
        """Mount the /v1 routes on the introspection server (starting it
        if needed), install the default serving SLOs, and start the
        burn-rate monitor tick (``HEAT_TPU_SLO_TICK_S``; unset/0 falls
        back to 1 s for a serving process — a fleet replica must page
        itself without configuration); returns the server URL."""
        srv = _tserver.start_server(port)
        _tserver.register_route(ROUTE_PREFIX, self._handle_http)
        _slo.install_default_slos()
        tick = _env().env_float("HEAT_TPU_SLO_TICK_S")
        self._started_monitor = _slo.start_monitor(tick if tick > 0 else 1.0)
        return srv.url

    def _handle_http(self, method: str, path: str, body: Optional[bytes]):
        try:
            if method == "GET" and path == "/v1/models":
                return 200, "application/json", json.dumps(
                    {"models": self.registry.models()}, indent=1, default=str
                )
            if method == "GET" and path.startswith("/v1/models/") and path.endswith("/healthz"):
                name = path[len("/v1/models/") : -len("/healthz")].strip("/")
                doc = self.model_health(name)
                return (
                    200 if doc["healthy"] else 503,
                    "application/json",
                    json.dumps(doc, indent=1, default=str),
                )
            if method == "POST" and path == "/v1/predict":
                return self._handle_predict(body)
            return 404, "text/plain", f"unknown serving route {path!r}\n"
        except KeyError as e:
            return 404, "application/json", json.dumps({"error": str(e)})
        except OverloadedError as e:
            headers = {}
            if e.retry_after_s is not None:
                headers["Retry-After"] = f"{max(e.retry_after_s, 0.001):.3f}"
            return (
                429,
                "application/json",
                json.dumps(
                    {"error": str(e), "cause": e.cause, "tenant": e.tenant,
                     "retry_after_s": e.retry_after_s}
                ),
                headers,
            )
        except (ValueError, TypeError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}
            )

    def _handle_predict(self, body: Optional[bytes]):
        try:
            doc = json.loads(body or b"")
        except ValueError:
            return 400, "application/json", json.dumps(
                {"error": "request body must be a JSON object"}
            )
        if not isinstance(doc, dict) or "model" not in doc or "inputs" not in doc:
            return 400, "application/json", json.dumps(
                {"error": 'POST /v1/predict needs {"model": name, "inputs": [[...], ...]}'}
            )
        name = doc["model"]
        rows = np.asarray(doc["inputs"], dtype=np.float32)
        tenant = str(doc.get("tenant", "default"))
        # one timing source: the latency (and trace id) the response
        # reports IS the measurement serving.latency_ms observed — the
        # route never re-times the request independently
        out, info = self._predict(
            name, rows, tenant=tenant, timeout=doc.get("timeout")
        )
        version = self.registry.active_version(name)
        return 200, "application/json", json.dumps(
            {
                "model": name,
                "version": version,
                "n": int(np.asarray(out).shape[0]),
                "predictions": np.asarray(out).tolist(),
                "latency_ms": round(info["latency_ms"], 3),
                "trace_id": info["trace_id"],
            }
        )

    # -- shutdown -------------------------------------------------------
    def close(self) -> None:
        """Unmount the routes, drain and join every batcher, drain the
        registry's background loader.  Idempotent."""
        _tserver.unregister_route(ROUTE_PREFIX)
        if self._started_monitor:
            self._started_monitor = False
            _slo.stop_monitor()
        with self._lock:
            _tsan.note_access("serving.service.state")
            self._open = False
            batchers, self._batchers = dict(self._batchers), {}
        for b in batchers.values():
            b.close()
        self.registry.close()

    def __enter__(self) -> "InferenceService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


# ----------------------------------------------------------------------
# process-default service (the HTTP deployment shape: one process, one
# registry, one port)
# ----------------------------------------------------------------------
_SERVICE: Optional[InferenceService] = None
_SERVICE_LOCK = _tsan.register_lock("serving.service")


def default_service(**kwargs) -> InferenceService:
    """Get-or-create the process's default :class:`InferenceService`
    (kwargs apply only on creation)."""
    global _SERVICE
    with _SERVICE_LOCK:
        _tsan.note_access("serving.service.state")
        if _SERVICE is None:
            _SERVICE = InferenceService(**kwargs)
        return _SERVICE


def start_serving(port: Optional[int] = None, **kwargs) -> InferenceService:
    """Start the default service and mount its HTTP routes; returns the
    service (its URL comes from ``telemetry.server``)."""
    svc = default_service(**kwargs)
    svc.serve(port)
    return svc


def stop_serving() -> None:
    """Close and drop the default service (no-op when none is running)."""
    global _SERVICE
    with _SERVICE_LOCK:
        _tsan.note_access("serving.service.state")
        svc, _SERVICE = _SERVICE, None
    if svc is not None:
        svc.close()
