"""Transparent cached-executable dispatch for the generic op wrappers.

Every NumPy-level op funnels through the four wrappers in
``core/_operations.py``.  Before this module they executed as *eager*
``jnp`` calls: one Python dispatch + one XLA executable launch per op, so
a chain like ``(a * b + c).sum()`` paid four launches — the measured
bottleneck of the bench history (dpsgd only beats the dispatch floor by
hand-batching steps, kmeans idles against the link-sync floor).  This
module gives the hot paths the two levers ``fusion.jit`` offers opt-in,
without any user opt-in:

1. **Executable cache** — op applications route through ``jax.jit``-
   compiled closures keyed by ``(op, abstract spec of operands, static
   kwargs)``.  Repeated shapes (the only case in iterative ML: kmeans /
   lasso / PCA / DASO loops) hit a compiled executable instead of
   re-dispatching through the jnp eager machinery.  Hit/miss/dispatch
   counters are exposed via :func:`cache_stats`.

2. **Lazy elementwise chain fusion** — element-wise results carry a small
   pending-expression node (:class:`PendingExpr`: bounded depth,
   element-wise only, same padded layout) instead of a concrete buffer.
   Materialization is deferred until a reduction, collective, indexing,
   print, or host read forces it — every such boundary funnels through
   ``DNDarray.larray_padded`` — at which point the whole chain compiles
   as ONE fused XLA computation through the cache.  A reduction/cum-op
   consuming a pending chain folds the chain, the pad-masking, and the
   reduction into a single cached executable (:func:`chain_apply`).

3. **Buffer donation** — in-place ops (``resplit_``, ``out=`` stores,
   ``__iadd__``-style dunders) donate the target's dead backing buffer to
   the compiled program (``donate_argnums``), letting XLA reuse the HBM
   allocation instead of holding both generations live.  Donation is
   gated on a CPython refcount proof that the buffer is unshared
   (:func:`_refcount_at_most`): two DNDarrays sharing a backing array, a
   pending expression holding the buffer as a leaf, or a user-held
   ``larray_padded`` reference all suppress donation (donating a shared
   buffer would poison every other holder).

Environment knobs (all default-on):

* ``HEAT_TPU_DISPATCH_CACHE=0`` — disable the executable cache (ops run
  as plain eager jnp calls; fusion is disabled too).
* ``HEAT_TPU_FUSION=0`` — disable lazy chain fusion only.
* ``HEAT_TPU_FUSION_DEPTH`` — max pending-chain depth before a subchain
  is materialized (default 16).
* ``HEAT_TPU_DONATE=0`` — disable buffer donation.
* ``HEAT_TPU_ANALYZE=1`` (or ``raise``) — run the SPMD program analyzer
  (``heat_tpu/analysis/program_lint.py``) over every freshly compiled
  executable: unaccounted implicit collectives, accidental full
  gathers, scalar-dtype recompile churn and donation misses surface as
  structured diagnostics (default ``0`` = off, free).  The same hook
  arms the precision layer (``analysis/dtype_flow.py`` — J201-J204
  against the active predict scope's precision policy) and the static
  peak-HBM estimator (``analysis/memory_model.py`` — J301 against
  ``HEAT_TPU_HBM_BUDGET_BYTES``).
* ``HEAT_TPU_COST_ANALYSIS=1`` — record XLA's per-executable cost/memory
  analysis on every cache miss (``dispatch.flops_total``,
  :func:`cost_summary`; surfaced by the introspection server's
  ``/statusz`` page and the crash flight recorder).  Default off.

See ``docs/dispatch.md`` for the cache-key, donation, and
fusion-boundary semantics, and ``docs/static_analysis.md`` for the
analyzer.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import warnings
from collections import OrderedDict
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import tsan as _tsan
from ..resilience.errors import ChecksumError as _ChecksumError
from ..resilience.errors import PermanentFault as _PermanentFault
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from ..telemetry import observatory as _obsv
from ..telemetry.spans import span as _span
from . import _env as _env
from . import aot_cache as _aot

__all__ = [
    "PendingExpr",
    "batch_bucket",
    "cache_enabled",
    "cache_keys",
    "cache_stats",
    "chain_apply",
    "clear_cache",
    "cost_accounting_enabled",
    "cost_summary",
    "eager_apply",
    "fusion_enabled",
    "make_node",
    "materialize",
    "meter_costs",
    "record_external_dispatch",
    "reset_stats",
    "set_cost_accounting",
]


# knob reads go through the central registry (core/_env.py KNOBS) —
# the H201 lint rule enforces the same table on direct os.environ reads
_CACHE_ENABLED = _env.env_flag("HEAT_TPU_DISPATCH_CACHE")
_FUSION_ENABLED = _env.env_flag("HEAT_TPU_FUSION")
_DONATE_ENABLED = _env.env_flag("HEAT_TPU_DONATE")
FUSION_DEPTH = _env.env_int("HEAT_TPU_FUSION_DEPTH")
_CACHE_MAXSIZE = _env.env_int("HEAT_TPU_DISPATCH_CACHE_SIZE")
_COST_ENABLED = _env.env_flag("HEAT_TPU_COST_ANALYSIS")


def cache_enabled() -> bool:
    """Whether the executable cache is active."""
    return _CACHE_ENABLED


def fusion_enabled() -> bool:
    """Whether lazy elementwise chain fusion is active."""
    return _CACHE_ENABLED and _FUSION_ENABLED


# ----------------------------------------------------------------------
# counters + cache.  The counters live in the shared telemetry registry
# (``telemetry.snapshot()`` reports them as ``dispatch.*`` alongside the
# resilience/overlap/comm domains); :func:`cache_stats` is a thin
# byte-compatible view over them.
# ----------------------------------------------------------------------
_COUNTER_NAMES = ("hits", "misses", "dispatches", "fused_ops", "donations",
                  "external_dispatches", "compile_fallbacks")
_C = {n: _tm.counter(f"dispatch.{n}") for n in _COUNTER_NAMES}

#: per-compile wall time (jit trace + XLA compile + first execution of a
#: fresh cache entry), milliseconds
_COMPILE_MS = _tm.histogram(
    "dispatch.compile_ms", "wall time of compile+first-run per cache miss"
)

#: LRU of compiled executables.  Bounded because op callables created
#: inline (lambdas/partials) key by object identity and would otherwise
#: accumulate one dead entry per call.
_cache: "OrderedDict[Any, Callable]" = OrderedDict()

#: the cache (and the cost records below) are mutated per dispatch on
#: the fit thread but ITERATED from other threads — /statusz handler
#: threads call cache_keys()/cost_summary(), the crash excepthook reads
#: the same, and iterating an OrderedDict mid-insert raises.  Every
#: mutation and every iteration holds this registered lock; lookups
#: inside the lock keep the LRU move-to-end ordered.
_CACHE_LOCK = _tsan.register_lock("dispatch.cache")

_tm.gauge("dispatch.cache_size", "live compiled-executable cache entries",
          fn=lambda: len(_cache))
_tm.gauge(
    "dispatch.hit_rate", "hits / (hits + misses), 0.0 before any lookup",
    fn=lambda: (
        _C["hits"].value / t if (t := _C["hits"].value + _C["misses"].value) else 0.0
    ),
)

#: (op, arg avals, kwargs) -> ShapeDtypeStruct; jax.eval_shape costs
#: ~1 ms per call, far too slow to pay per dispatch.
_aval_cache: dict = {}


def cache_stats() -> dict:
    """Snapshot of the dispatch counters.

    ``hits``/``misses`` count executable-cache lookups, ``dispatches``
    the compiled-program launches issued through this layer,
    ``fused_ops`` the number of elementwise/reduce ops folded into those
    launches (fused_ops >> dispatches means fusion is working), and
    ``donations`` the in-place launches that donated a dead buffer.
    ``external_dispatches`` are launches recorded by consumers with their
    own jitted programs (kmeans' Lloyd loop, lasso's CD loop,
    ``fusion.jit``).  ``compile_fallbacks`` counts compiled executions
    that failed (trace/compile error, injected compile fault) and were
    re-run eagerly instead of crashing the op.  ``hit_rate`` is
    hits / (hits + misses), 0.0 before any lookup.

    A thin view over the shared telemetry registry (the counters live
    there as ``dispatch.*``); ``telemetry.snapshot()`` reports the same
    values alongside every other domain."""
    s = {n: _C[n].value for n in _COUNTER_NAMES}
    total = s["hits"] + s["misses"]
    s["hit_rate"] = (s["hits"] / total) if total else 0.0
    s["cache_size"] = len(_cache)
    return s


def reset_stats() -> None:
    """Zero all dispatch counters (the compiled cache itself is kept);
    delegates to ``telemetry.reset_all("dispatch")``."""
    from ..telemetry import reset_all

    reset_all("dispatch")


def clear_cache() -> None:
    """Drop every compiled executable (and its cost records) and zero
    the counters."""
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache")
        _cache.clear()
        _cost_records.clear()
    _aval_cache.clear()
    reset_stats()


def record_external_dispatch(n: int = 1) -> None:
    """Count ``n`` executable launches made outside this layer (consumers
    with their own jitted programs: kmeans/lasso loops, ``fusion.jit``)."""
    _C["external_dispatches"].inc(n)


def batch_bucket(n: int, cap: Optional[int] = None) -> int:
    """Quantized leading extent for variable-size batch dispatch.

    Online traffic produces arbitrary batch sizes; dispatching each one
    verbatim would mint one executable-cache key (and one XLA compile)
    per distinct size.  Padding every batch up to the next power of two
    — capped at ``cap``, which is itself a valid bucket — bounds the key
    set to ``log2(cap)+1`` shapes: after one warmup pass per bucket, any
    traffic mix runs entirely on cache hits.  The serving layer's
    request coalescer (``heat_tpu/serving/coalescer.py``) pads with real
    rows to the returned extent, so the bucket is the true shape every
    cached program sees."""
    n = int(n)
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    b = 1 << (n - 1).bit_length()
    if cap is not None:
        cap = int(cap)
        if n > cap:
            raise ValueError(f"batch size {n} exceeds the bucket cap {cap}")
        b = min(b, cap)
    return b


# ----------------------------------------------------------------------
# per-executable cost accounting (docs/observability.md).  Opt-in
# (``HEAT_TPU_COST_ANALYSIS=1``): on every cache miss the fresh entry is
# re-lowered and XLA's own cost/memory analysis recorded per cache key —
# the static FLOP and byte footprint of every compiled program in the
# process, the inventory ``/statusz`` and the flight recorder expose.
# Off by default because the extra trace+lower per miss is measurable in
# compile-bound workloads (the analysis itself is version-guarded: any
# jax without Lowered.cost_analysis just records nothing).
# ----------------------------------------------------------------------
_FLOPS_TOTAL = _tm.counter(
    "dispatch.flops_total", "XLA cost-analysis flops summed over compiled executables"
)
_COST_BYTES_TOTAL = _tm.counter(
    "dispatch.cost_bytes_total",
    "XLA cost-analysis bytes-accessed summed over compiled executables",
)

#: cache key -> cost record for every analyzed executable (bounded like
#: the executable cache itself)
_cost_records: "OrderedDict[Any, dict]" = OrderedDict()


def cost_accounting_enabled() -> bool:
    """Whether per-executable cost accounting is active."""
    return _COST_ENABLED


def set_cost_accounting(enabled: bool) -> bool:
    """Enable/disable cost accounting at runtime (overrides the env
    knob); returns the previous state.  Bench/test hook."""
    global _COST_ENABLED
    prev = _COST_ENABLED
    _COST_ENABLED = bool(enabled)
    return prev


def _fmt_key_part(obj, depth: int = 0) -> str:
    if callable(obj):
        return getattr(obj, "__name__", type(obj).__name__)
    if isinstance(obj, (tuple, list)):
        if depth > 3:
            return "(...)"
        return "(" + ", ".join(_fmt_key_part(o, depth + 1) for o in obj) + ")"
    return str(obj)


def _key_repr(key, limit: int = 200) -> str:
    """Compact human-readable form of a cache key (op names, shapes,
    dtypes; shardings stringify) for /statusz and crash bundles."""
    s = _fmt_key_part(key)
    return s if len(s) <= limit else s[: limit - 3] + "..."


def cache_keys() -> list:
    """Readable reprs of every live executable-cache key (insertion
    order: oldest first, like the LRU itself)."""
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache", write=False)
        keys = list(_cache)
    return [_key_repr(k) for k in keys]


def cost_summary() -> dict:
    """Cost-accounting view: totals plus the per-executable records.

    ``{"enabled", "executables", "flops_total", "bytes_total",
    "per_key": {key_repr: {flops, bytes_accessed, ...}}}`` — totals are
    the ``dispatch.flops_total`` / ``dispatch.cost_bytes_total``
    registry counters, so they survive record eviction."""
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache", write=False)
        per_key = {_key_repr(k): dict(v) for k, v in _cost_records.items()}
        n = len(_cost_records)
    return {
        "enabled": _COST_ENABLED,
        "executables": n,
        "flops_total": _FLOPS_TOTAL.value,
        "bytes_total": _COST_BYTES_TOTAL.value,
        "per_key": per_key,
    }


def _record_cost(key, entry, leaves) -> None:
    """Record XLA's cost/memory analysis for a freshly compiled entry.

    Version-guarded throughout: ``Lowered.cost_analysis`` /
    ``Compiled.memory_analysis`` vary across jax releases (dict vs
    [dict], missing attributes) — any probe failure records nothing and
    costs nothing downstream."""
    try:
        lowered = entry.lower(*leaves)
        cost = lowered.cost_analysis()
    except Exception:  # lint: allow H501(version-guarded probe; accounting is best-effort)
        return
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return
    rec = {
        "flops": float(cost.get("flops", 0.0) or 0.0),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
        "transcendentals": float(cost.get("transcendentals", 0.0) or 0.0),
    }
    try:
        mem = lowered.compile().memory_analysis()
        for attr in (
            "generated_code_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
        ):
            v = getattr(mem, attr, None)
            if v is not None:
                rec[attr] = int(v)
    except Exception:  # lint: allow H501(memory analysis missing on this jax/backend; flops still recorded)
        pass
    _FLOPS_TOTAL.inc(rec["flops"])
    _COST_BYTES_TOTAL.inc(rec["bytes_accessed"])
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache")
        _cost_records[key] = rec
        while len(_cost_records) > _CACHE_MAXSIZE:
            _cost_records.popitem(last=False)


class CostMeter:
    """Accumulated analyzed cost of the executables one thread ran.

    Filled by :func:`_run` while a :func:`meter_costs` scope is active
    on the thread: each dispatch adds its cached cost record's FLOPs and
    bytes.  ``unmetered_calls`` counts dispatches with no cost record
    (accounting off, analysis probe failed, or record evicted) — the
    honesty counter that distinguishes "this work was free" from "this
    work was invisible"."""

    __slots__ = ("flops", "bytes_accessed", "calls", "unmetered_calls")

    def __init__(self) -> None:
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.calls = 0
        self.unmetered_calls = 0


_METER_TLS = threading.local()


@contextlib.contextmanager
def meter_costs():
    """Meter the analyzed cost of every dispatch on this thread.

    Thread-local and re-entrant (a nested scope meters independently
    and the outer scope resumes on exit) — the serving path wraps one
    coalesced batch's inference in a scope to attribute the batch's
    FLOPs/bytes to its member tenants (/tenantz).  Yields the
    :class:`CostMeter` being filled."""
    meter = CostMeter()
    prev = getattr(_METER_TLS, "meter", None)
    _METER_TLS.meter = meter
    try:
        yield meter
    finally:
        _METER_TLS.meter = prev


def _meter_note(key) -> None:
    """Add ``key``'s analyzed cost to the thread's active meter (no-op
    without one: one TLS read on the unmetered hot path)."""
    meter = getattr(_METER_TLS, "meter", None)
    if meter is None:
        return
    rec = None
    if key is not None:
        with _CACHE_LOCK:
            _tsan.note_access("dispatch.cache", write=False)
            rec = _cost_records.get(key)
    if rec is None:
        meter.unmetered_calls += 1
        return
    meter.calls += 1
    meter.flops += rec["flops"]
    meter.bytes_accessed += rec["bytes_accessed"]


def _note_lookup(hit: bool) -> None:
    _C["hits" if hit else "misses"].inc()


# ----------------------------------------------------------------------
# pending expressions
# ----------------------------------------------------------------------
class PendingExpr:
    """One deferred elementwise op over pending/concrete operands.

    ``args`` holds :class:`PendingExpr` children and/or concrete
    ``jax.Array`` leaves; ``shape``/``dtype`` are the abstract result
    (from a cached ``jax.eval_shape``), so metadata queries never force
    materialization.  Nodes are immutable: leaves are captured as the
    *buffers* they were at op time, so later in-place mutation of an
    operand DNDarray cannot change an already-built chain's value."""

    __slots__ = ("op", "args", "kwargs", "shape", "dtype", "depth", "nops")

    def __init__(self, op, args, kwargs, shape, dtype, depth, nops):
        self.op = op
        self.args = args
        self.kwargs = kwargs
        self.shape = shape
        self.dtype = dtype
        self.depth = depth
        self.nops = nops


def _kw_key(kwargs: dict) -> Tuple:
    key = tuple(sorted(kwargs.items()))
    hash(key)  # TypeError for unhashable values -> caller falls back
    return key


def _leaf_spec(buf) -> Tuple:
    return (tuple(buf.shape), buf.dtype, getattr(buf, "sharding", None))


def _abstract_eval(op, arg_avals: Tuple, kw_key: Tuple, kwargs: dict):
    k = (op, arg_avals, kw_key)
    out = _aval_cache.get(k)
    if out is None:
        out = jax.eval_shape(
            lambda *a: op(*a, **kwargs),
            *[jax.ShapeDtypeStruct(s, d) for (s, d) in arg_avals],
        )
        if len(_aval_cache) > 4 * _CACHE_MAXSIZE:
            _aval_cache.clear()
        _aval_cache[k] = out
    return out


def make_node(op, args: Sequence, kwargs: Optional[dict] = None) -> Optional[PendingExpr]:
    """Build a pending elementwise node, or None when it cannot be fused
    (fusion disabled, unhashable kwargs, abstract eval failed).

    ``args`` entries are PendingExpr or concrete jax.Array.  A child at
    the depth limit is materialized on the spot so chains stay bounded."""
    if not fusion_enabled():
        return None
    kwargs = kwargs or {}
    try:
        kw_key = _kw_key(kwargs)
    except TypeError:
        return None
    args = tuple(
        materialize(a) if isinstance(a, PendingExpr) and a.depth >= FUSION_DEPTH else a
        for a in args
    )
    arg_avals = []
    depth = 1
    nops = 1
    for a in args:
        if isinstance(a, PendingExpr):
            depth = max(depth, a.depth + 1)
            nops += a.nops
            arg_avals.append((a.shape, a.dtype))
        else:
            arg_avals.append((tuple(a.shape), a.dtype))
    try:
        aval = _abstract_eval(op, tuple(arg_avals), kw_key, kwargs)
    except Exception:  # lint: allow H501(unfusable node -> eager path, no fault sites inside)
        return None
    if not isinstance(aval, jax.ShapeDtypeStruct):
        return None  # multi-output ops don't fuse
    return PendingExpr(op, args, kwargs, tuple(aval.shape), aval.dtype, depth, nops)


def _astype(a, *, dtype):
    return a.astype(dtype)


#: (type, value, dtype) -> 0-d jax.Array.  Scalar operands used to pay a
#: full factories.array round trip (0-d DNDarray + device_put) on EVERY
#: op — the profile-dominant cost of a chain like (a*b+c)/2.0.  Reusing
#: one leaf object also dedups the compiled program's inputs.
_scalar_cache: dict = {}


def scalar_leaf(value, dtype):
    """Cached 0-d constant leaf for a Python-number operand.

    Built as a NUMPY scalar, never ``jnp.asarray``: inside an active
    trace (``ht.jit`` bodies) jnp constants come back as tracers, and a
    cached tracer leaks into every later call outside the trace.  A
    numpy constant is always concrete, converts on the compiled call,
    and constant-folds when the consumer itself is being traced."""
    key = (type(value), value, dtype)
    buf = _scalar_cache.get(key)
    if buf is None:
        buf = np.asarray(value, dtype)
        if len(_scalar_cache) > 512:
            _scalar_cache.clear()
        _scalar_cache[key] = buf
    return buf


def cast_node(x, dtype) -> Optional[PendingExpr]:
    """Pending ``astype`` node (the __local_op float32 pre-cast)."""
    return make_node(_astype, (x,), {"dtype": dtype})


def _mask_pad(a, *, split, extent, neutral):
    """Overwrite the canonical padding rows with ``neutral`` (the fused
    equivalent of ``DNDarray._masked``)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, a.shape, split)
    return jnp.where(idx < extent, a, jnp.asarray(neutral, a.dtype))


# ----------------------------------------------------------------------
# linearization + compiled-program cache
# ----------------------------------------------------------------------
def _linearize(root):
    """DAG -> (topo-ordered node list, deduped leaf list, leaf arg-slot
    counts).  Node refs are ``(is_node, index)`` pairs; shared subtrees
    and repeated leaves dedupe by object identity, so a buffer is passed
    to the compiled program exactly once however often it appears."""
    nodes: list = []
    node_ix: dict = {}
    leaves: list = []
    leaf_ix: dict = {}
    leaf_slots: dict = {}

    def walk(n):
        if isinstance(n, PendingExpr):
            ix = node_ix.get(id(n))
            if ix is None:
                refs = tuple(walk(a) for a in n.args)
                nodes.append((n.op, n.kwargs, refs))
                ix = len(nodes) - 1
                node_ix[id(n)] = ix
            return (True, ix)
        ix = leaf_ix.get(id(n))
        if ix is None:
            leaves.append(n)
            ix = len(leaves) - 1
            leaf_ix[id(n)] = ix
        leaf_slots[ix] = leaf_slots.get(ix, 0) + 1
        return (False, ix)

    walk(root)
    return nodes, leaves, leaf_slots


def _program_key(tag: str, nodes, leaves, extra: Tuple = ()) -> Tuple:
    nk = tuple((op, _kw_key(kwargs), refs) for op, kwargs, refs in nodes)
    lk = tuple(_leaf_spec(l) for l in leaves)
    key = (tag, nk, lk) + extra
    hash(key)
    return key


def _build_program(nodes):
    def program(*leaves):
        vals = []
        for op, kwargs, refs in nodes:
            args = [vals[i] if is_node else leaves[i] for (is_node, i) in refs]
            vals.append(op(*args, **kwargs))
        return vals[-1]
    return program


def _eval_nodes(nodes, leaves):
    """Uncached eager evaluation (cache disabled / unhashable key)."""
    return _build_program(nodes)(*leaves)


def _maybe_analyze(entry, leaves, key, donate_argnums=()) -> None:
    """SPMD program-lint hook on the compile path (docs/static_analysis.md).

    Off mode (``HEAT_TPU_ANALYZE=0``, the default) costs one lazy-import
    dict lookup and a string compare per cache MISS — nothing per hit.
    Warn/raise mode re-lowers the fresh entry and walks its compiled
    module for unaccounted collectives, full gathers and donation misses
    (roughly one extra trace+compile per miss)."""
    from ..analysis.diagnostics import analysis_mode

    if analysis_mode() == "off":
        return
    from ..analysis.program_lint import note_dispatch_key, on_dispatch_compile

    note_dispatch_key(key)
    on_dispatch_compile(entry, leaves, key, donate_argnums=donate_argnums)


def _aot_entry(key, jitted, leaves):
    """AOT-cache resolution of a fresh in-memory miss (armed caches
    only; see ``core/aot_cache.py``).  Returns the compiled executable
    to install — a deserialized artifact when one matches, else the
    eagerly ``lower().compile()``-ed (and persisted) program — or
    ``None`` to fall back to the plain lazy-jit path.  Either way the
    compile accounting (``dispatch.compile`` span + ``compile_ms``)
    happens HERE, so callers treat the returned entry as warm."""
    compiled = _aot.load(key)
    if compiled is not None:
        return compiled
    try:
        t0 = time.perf_counter()
        with _span("dispatch.compile", aot=True):
            compiled = jitted.lower(*leaves).compile()
        _COMPILE_MS.observe((time.perf_counter() - t0) * 1e3)
    except Exception:  # lint: allow H501(AOT pre-compile failed; the lazy jit path re-raises any real error)
        return None
    _aot.save(key, compiled)
    return compiled


def _get_compiled(key, builder, donate_argnums=None, out_sharding=None, leaves=None):
    """Cached jitted executable for ``key``; returns ``(entry, fresh)``
    where ``fresh`` marks a miss — the first execution of a fresh entry
    pays trace+compile, which :func:`_run` times into the
    ``dispatch.compile_ms`` histogram.

    With the on-disk AOT cache armed (``HEAT_TPU_AOT_CACHE``) and
    ``leaves`` provided, a miss first consults the artifact store: a
    matching artifact installs a deserialized executable with NO
    compile; otherwise the program is compiled eagerly and persisted.
    Both AOT paths return ``fresh=False`` (their compile accounting is
    internal); donated entries and armed-analyzer runs
    (``HEAT_TPU_ANALYZE``) keep the plain lazy-jit path — the analyzer
    must be able to re-lower the fresh entry."""
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache")
        entry = _cache.get(key)
        if entry is not None:
            _cache.move_to_end(key)
    if entry is not None:
        _note_lookup(True)
        return entry, False
    _note_lookup(False)
    _inject("dispatch.compile")
    jit_kwargs: dict = {}
    if out_sharding is not None:
        jit_kwargs["out_shardings"] = out_sharding
    if donate_argnums:
        jit_kwargs["donate_argnums"] = donate_argnums
    entry = jax.jit(builder(), **jit_kwargs)
    fresh = True
    if leaves is not None and not donate_argnums and _aot.enabled():
        from ..analysis.diagnostics import analysis_mode

        if analysis_mode() == "off":
            aot = _aot_entry(key, entry, leaves)
            if aot is not None:
                entry, fresh = aot, False
    with _CACHE_LOCK:
        _tsan.note_access("dispatch.cache")
        _cache[key] = entry
        while len(_cache) > _CACHE_MAXSIZE:
            _cache.popitem(last=False)
    return entry, fresh


def _run(compiled, leaves, n_ops: int, donated: bool = False, fresh: bool = False,
         key=None):
    _C["dispatches"].inc()
    _C["fused_ops"].inc(n_ops)
    if donated:
        _C["donations"].inc()

    def call():
        if donated:
            with warnings.catch_warnings():
                # XLA may decline an unusable donation (layout mismatch);
                # that is a perf note, not a user-facing condition
                warnings.filterwarnings("ignore", message=".*[Dd]onat")
                return compiled(*leaves)
        return compiled(*leaves)

    if not fresh:
        if key is not None and _obsv.armed():
            # roofline observatory: every warm call is a measurement
            # (monotonic enqueue time; every Nth per key is fenced
            # inside note() so the sample measures device time)
            t0 = time.perf_counter()
            out = call()
            _obsv.note(key, time.perf_counter() - t0, out)
            _meter_note(key)
            return out
        out = call()
        _meter_note(key)
        return out
    # cache miss: the first call traces + compiles; record the wall time
    # so ``where did the compile time go?`` is answerable from telemetry
    t0 = time.perf_counter()
    with _span("dispatch.compile", ops=n_ops):
        out = call()
    _COMPILE_MS.observe((time.perf_counter() - t0) * 1e3)
    if _COST_ENABLED and key is not None:
        # outside the timed window: the accounting re-lower must not
        # inflate the compile_ms histogram it sits next to
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=".*[Dd]onat")
            _record_cost(key, compiled, leaves)
    _meter_note(key)
    return out


def _compiled_or_fallback(key, builder, leaves, n_ops, eager_fn, out_sharding=None):
    """Run through the executable cache; on a trace/compile/run failure
    fall back to ONE eager execution instead of crashing the op.

    The broken cache entry is dropped so the next call re-attempts a
    compile (a transient compile failure — injected or an XLA hiccup —
    heals itself); ``compile_fallbacks`` in :func:`cache_stats` counts
    the events and a ``RuntimeWarning`` surfaces each one.  A genuine
    error in the op (bad shapes, bad dtype) re-raises from the eager
    run, so user-facing exceptions are unchanged.  Donating paths never
    come through here: a partially-run donated program may have
    consumed its input, making re-execution unsafe."""
    try:
        compiled, fresh = _get_compiled(
            key, builder, out_sharding=out_sharding, leaves=leaves
        )
        if fresh:
            _maybe_analyze(compiled, leaves, key)
        return _run(compiled, leaves, n_ops, fresh=fresh, key=key)
    except (_PermanentFault, _ChecksumError):
        # non-retryable resilience faults must propagate — an eager
        # fallback here would SWALLOW a permanent failure the caller's
        # recovery logic (and the H501 lint rule) depends on seeing
        raise
    except Exception as e:  # lint: allow H501(compile fallback; non-retryables re-raised above)
        if type(e).__name__ == "ProgramLintError":
            # raise-mode analyzer diagnostics are verdicts, not transient
            # compile failures — an eager fallback would hide exactly the
            # hazard HEAT_TPU_ANALYZE=raise exists to stop on (lazy name
            # check: importing analysis here would cycle through core)
            raise
        _C["compile_fallbacks"].inc()
        with _CACHE_LOCK:
            _tsan.note_access("dispatch.cache")
            _cache.pop(key, None)
        warnings.warn(
            f"dispatch: compiled execution failed ({type(e).__name__}: {e}); "
            "falling back to eager execution for this call",
            RuntimeWarning,
            stacklevel=3,
        )
        # the same event, routed into the alert layer: a warn-severity
        # deduplicated alert (re-fires only update value/message) so an
        # operator watching /statusz or /decisionz sees fallback storms
        # without scraping stderr for RuntimeWarnings.  Lazy import:
        # telemetry.alerts at module level would cycle through core.
        try:
            from ..telemetry import alerts as _alerts

            _alerts.fire(
                "dispatch:compile_fallback",
                severity="warn",
                message=(
                    f"compiled execution failed ({type(e).__name__}); "
                    "eager fallback taken"
                ),
                value=float(_C["compile_fallbacks"].value),
                evidence={"error": type(e).__name__,
                          "series": ["dispatch.compile_fallbacks"]},
            )
        except Exception:  # lint: allow H501(alerting is best-effort; the fallback itself must proceed)
            pass
        return eager_fn()


# ----------------------------------------------------------------------
# entry points
# ----------------------------------------------------------------------
def materialize(expr: PendingExpr, out_sharding=None):
    """Compile-and-run a pending chain as one executable through the
    cache; returns the concrete jax.Array.  ``out_sharding`` (the array's
    canonical NamedSharding) pins the result placement the eager path
    used to establish with a per-op device_put."""
    nodes, leaves, _ = _linearize(expr)
    if not _CACHE_ENABLED:
        return _eval_nodes(nodes, leaves)
    try:
        key = _program_key("expr", nodes, leaves, (out_sharding,))
    except TypeError:
        return _eval_nodes(nodes, leaves)
    return _compiled_or_fallback(
        key, lambda: _build_program(nodes), leaves, len(nodes),
        lambda: _eval_nodes(nodes, leaves), out_sharding=out_sharding,
    )


def eager_apply(op, args: Sequence, kwargs: Optional[dict] = None):
    """Immediate op application through a cached executable (the slow
    binary path, helpers with concrete operands).  Falls back to a plain
    eager call when caching is off or the key is unhashable."""
    kwargs = kwargs or {}
    if not _CACHE_ENABLED:
        return op(*args, **kwargs)
    try:
        key = ("apply", op, _kw_key(kwargs),
               tuple(_leaf_spec(a) for a in args))
        hash(key)
    except TypeError:
        return op(*args, **kwargs)
    return _compiled_or_fallback(
        key, lambda: (lambda *a: op(*a, **kwargs)), args, 1,
        lambda: op(*args, **kwargs),
    )


def chain_apply(op, x, kwargs: Optional[dict] = None, mask=None):
    """Apply ``op(arr, **kwargs)`` where ``x`` is a pending chain or a
    concrete buffer: the chain, the optional pad-masking, and the op
    itself compile as ONE cached executable (the reduction/cum-op
    boundary of the fusion design).

    ``mask``: None, or ``(split, true_extent, neutral)`` — the padding
    rows are overwritten with ``neutral`` before ``op`` (the fused analog
    of ``DNDarray._masked``)."""
    kwargs = dict(kwargs or {})
    if isinstance(x, PendingExpr):
        nodes, leaves, _ = _linearize(x)
        root = (True, len(nodes) - 1)
    else:
        nodes, leaves = [], [x]
        root = (False, 0)
    if mask is not None:
        split, extent, neutral = mask
        nodes.append((_mask_pad,
                      {"split": int(split), "extent": int(extent), "neutral": neutral},
                      (root,)))
        root = (True, len(nodes) - 1)
    nodes.append((op, kwargs, (root,)))
    if not _CACHE_ENABLED:
        return _eval_nodes(nodes, leaves)
    try:
        key = _program_key("chain", nodes, leaves)
    except TypeError:
        return _eval_nodes(nodes, leaves)
    return _compiled_or_fallback(
        key, lambda: _build_program(nodes), leaves, len(nodes),
        lambda: _eval_nodes(nodes, leaves),
    )


# ----------------------------------------------------------------------
# donation-aware in-place paths
# ----------------------------------------------------------------------
def _probe_inner(obj):
    return sys.getrefcount(obj)


def _probe_outer(obj):
    # mirrors caller -> repad/cast_store -> _refcount_at_most -> getrefcount
    return _probe_inner(obj)


class _ProbeHolder:
    __slots__ = ("x", "args")


def _calibrate_plumbing() -> int:
    """Measured refcount of an object whose ONLY owner is one attribute,
    observed through the exact call shape the donation checks use
    (owner attribute + caller argument temp + two call frames +
    getrefcount's own argument).  Calibrated empirically because the
    per-frame reference cost depends on the CPython version's calling
    convention."""
    h = _ProbeHolder()
    h.x = object()
    return _probe_outer(h.x)


def _probe_leaf_site(dst, src):
    # mirrors cast_store's leaf check: one arg-slot tuple ref, the
    # deduped leaves list, the scan loop's binding, then the helper call
    leaves = [src.args[0]]
    for _i, leaf in enumerate(leaves):
        if leaf is dst:
            return _probe_inner(dst)
    return -1  # pragma: no cover


def _calibrate_leaf_site() -> int:
    """Refcount of a single-arg-slot, otherwise-unshared buffer at
    cast_store's leaf-donation check (owner attribute + plumbing + the
    arg-slot tuple + leaves list + loop binding)."""
    h = _ProbeHolder()
    h.x = object()
    h.args = (h.x,)
    return _probe_leaf_site(h.x, h)


#: refcount of a provably-unshared buffer at the check site
_RC_BASE = _calibrate_plumbing()
#: same, at the leaf-donation site with exactly one arg-slot reference
_RC_LEAF_BASE = _calibrate_leaf_site()


def _refcount_at_most(buf, extra: int = 0) -> bool:
    """CPython proof that ``buf`` has no holders beyond its owner
    attribute, the call plumbing (calibrated ``_RC_BASE``), and ``extra``
    known internal references (leaf lists, expression arg slots).  A
    shared backing array, a pending-expression leaf elsewhere, or a
    user-held ``larray_padded`` all push the count higher and suppress
    donation — the safe direction."""
    if not _DONATE_ENABLED or buf is None:
        return False
    try:
        return sys.getrefcount(buf) <= _RC_BASE + extra
    except Exception:  # lint: allow H501(non-CPython refcount probe -> donation off)
        return False


def _expr_private(root: PendingExpr, leaf_buf) -> bool:
    """Exact CPython proof that every chain node from which ``leaf_buf``
    is REACHABLE has no holder outside the chain itself (another
    DNDarray's pending attribute, a user variable).  Required before
    donating a LEAF buffer the chain consumes: a shared sub-expression
    that can reach the leaf would materialize later against the deleted
    buffer.  Nodes that cannot reach the leaf (e.g. the ``g * 0.1``
    sub-chain of ``w += g * 0.1``, still referenced by the dunder's
    temporary) are irrelevant and may be shared freely.

    Reference accounting per checked node: the ``order`` list entry +
    the loop variable + the getrefcount argument + one per arg-slot in
    parent nodes; the root additionally carries its owner's
    ``__pending`` attribute, the caller's ``src`` parameter, and this
    function's ``root`` parameter."""
    slots: dict = {}
    seen: set = set()
    order: list = []
    stack = [root]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        order.append(n)
        for a in n.args:
            if isinstance(a, PendingExpr):
                slots[id(a)] = slots.get(id(a), 0) + 1
                stack.append(a)

    reaches: dict = {}

    def _reaches(n: PendingExpr) -> bool:
        r = reaches.get(id(n))
        if r is None:
            reaches[id(n)] = False  # cycle guard (DAGs only, but cheap)
            r = any(
                (a is leaf_buf)
                or (isinstance(a, PendingExpr) and _reaches(a))
                for a in n.args
            )
            reaches[id(n)] = r
        return r

    for n in order:
        _reaches(n)
    for n in order:
        if not reaches.get(id(n)):
            continue
        if n is root:
            allowed = _RC_BASE + 2 + slots.get(id(n), 0)
        else:
            allowed = 3 + slots.get(id(n), 0)
        try:
            if sys.getrefcount(n) > allowed:
                return False
        except Exception:  # lint: allow H501(non-CPython refcount probe -> donation off)
            return False
    return True


def _refcount_leaf_at_most(buf, slots: int) -> bool:
    """Leaf-donation variant of :func:`_refcount_at_most`: compares
    against the calibrated leaf-site base (which already includes one
    arg-slot reference) plus any additional arg-slot references."""
    if not _DONATE_ENABLED or buf is None:
        return False
    try:
        return sys.getrefcount(buf) <= _RC_LEAF_BASE + (slots - 1)
    except Exception:  # lint: allow H501(non-CPython refcount probe -> donation off)
        return False


def repad(buf, old_slice, pad_widths, sharding, donate: bool = False):
    """Slice off the old padding, pad the new split axis, and place with
    the new canonical sharding — one cached executable (the body of
    ``resplit_``, which the eager path ran as slice + pad + device_put).

    ``old_slice``: None or ``(axis, true_extent)``; ``pad_widths``: None
    or the full jnp.pad width spec.  ``donate=True`` donates ``buf``
    (the array's dead backing buffer) when a refcount proof shows it is
    unshared.  Call with the buffer in argument position (no extra local
    bindings) so the calibrated refcount accounting holds."""
    donate = donate and _refcount_at_most(buf)
    if pad_widths is not None:
        pad_widths = tuple((int(a), int(b)) for a, b in pad_widths)
        if not any(b for _, b in pad_widths) and not any(a for a, _ in pad_widths):
            pad_widths = None
    if old_slice is not None:
        old_slice = (int(old_slice[0]), int(old_slice[1]))

    def build():
        def program(x):
            if old_slice is not None:
                ax, ext = old_slice
                x = jax.lax.slice_in_dim(x, 0, ext, axis=ax)
            if pad_widths is not None:
                x = jnp.pad(x, pad_widths)
            return x
        return program

    if not _CACHE_ENABLED:
        return jax.device_put(build()(buf), sharding)
    try:
        key = ("repad", _leaf_spec(buf), old_slice, pad_widths, sharding, donate)
        hash(key)
    except TypeError:
        return jax.device_put(build()(buf), sharding)
    if not donate:
        return _compiled_or_fallback(
            key, build, (buf,), 1,
            lambda: jax.device_put(build()(buf), sharding), out_sharding=sharding,
        )
    compiled, fresh = _get_compiled(key, build, donate_argnums=(0,), out_sharding=sharding)
    if fresh:
        _maybe_analyze(compiled, (buf,), key, donate_argnums=(0,))
    return _run(compiled, (buf,), 1, donated=True, fresh=fresh, key=key)


def cast_store(dst_buf, src, dtype, out_sharding=None):
    """Compute ``src`` (pending chain or concrete buffer) cast to
    ``dtype`` as one cached executable, donating ``dst_buf`` — the
    ``out=`` / in-place target's about-to-die backing buffer — when a
    refcount proof shows it is unshared.

    Two donation shapes:

    * ``dst_buf`` IS a leaf of the chain (the ``a += b`` case): that leaf
      argument is donated, the classic ``donate_argnums`` aliasing.
    * ``dst_buf`` is not an operand (``mul(x, y, out=z)``): it is passed
      as an extra trailing argument, donated, so XLA may reuse its
      allocation for the output.

    Pass ``dst_buf`` in argument position (no extra local binding in the
    caller); the refcount proof compares against the calibrated call
    plumbing plus the leaf-list and arg-slot references when it is a
    leaf."""
    if isinstance(src, PendingExpr):
        nodes, leaves, leaf_slots = _linearize(src)
        root = (True, len(nodes) - 1)
    else:
        nodes, leaves, leaf_slots = [], [src], {0: 1}
        root = (False, 0)
    nodes.append((_astype, {"dtype": dtype}, (root,)))

    donate_ix = None
    trailing_dst = False
    if dst_buf is not None and _DONATE_ENABLED:
        for i, leaf in enumerate(leaves):
            if leaf is dst_buf:
                # the `a += b` aliasing case: donating an OPERAND needs
                # both proofs — the buffer itself is unshared (beyond
                # the calibrated plumbing: the leaves-list entry, this
                # loop's `leaf` binding, and one per expression arg-slot)
                # AND the whole chain is private (no other DNDarray
                # holds a sub-expression that would later materialize
                # against the deleted buffer)
                if (
                    isinstance(src, PendingExpr)
                    and _refcount_leaf_at_most(dst_buf, leaf_slots.get(i, 1))
                    and _expr_private(src, dst_buf)
                ):
                    donate_ix = i
                break
        else:
            # dst is not an operand: donated as an extra trailing
            # argument so XLA may reuse its allocation for the output
            if _refcount_at_most(dst_buf):
                donate_ix = len(leaves)
                trailing_dst = True

    if trailing_dst:
        n_real = len(leaves)
        inner = _build_program(nodes)

        def build():
            def program(*args):
                return inner(*args[:n_real])
            return program

        leaves = leaves + [dst_buf]
    else:
        def build():
            return _build_program(nodes)

    if not _CACHE_ENABLED:
        return _eval_nodes(nodes, leaves if not trailing_dst else leaves[:-1])
    try:
        key = _program_key(
            "cast_store", nodes, leaves,
            (out_sharding, donate_ix, trailing_dst),
        )
    except TypeError:
        return _eval_nodes(nodes, leaves if not trailing_dst else leaves[:-1])
    if donate_ix is None:
        return _compiled_or_fallback(
            key, build, leaves, len(nodes),
            lambda: _eval_nodes(nodes, leaves), out_sharding=out_sharding,
        )
    compiled, fresh = _get_compiled(
        key, build, donate_argnums=(donate_ix,), out_sharding=out_sharding
    )
    if fresh:
        _maybe_analyze(compiled, leaves, key, donate_argnums=(donate_ix,))
    return _run(compiled, leaves, len(nodes), donated=True, fresh=fresh, key=key)
