"""The rolling-median trend gate (ROADMAP 5c satellite of ISSUE 11).

The contract: per-metric k-run rolling medians over BENCH_HISTORY.jsonl,
drift flagged only when the newest k-run median moves against the
metric's direction of good by more than the drift threshold vs the k
runs before — sustained regressions that single-run spread_pct slack
absorbs, without flapping on one noisy run.  Metrics with fewer than 2k
runs warm up silently; informational metrics (anchors) never gate.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from bench_history import (  # noqa: E402
    ROLL_K,
    append_history,
    backfill_history,
    extract_record,
    headline,
    headline_kind,
    load_history,
    trend_check,
    trend_verdict,
    trend_verdicts,
)


def _records(values, kind="seconds", name="m"):
    return [
        {"metrics": {name: v}, "kinds": {name: kind}} for v in values
    ]


class TestTrendVerdict:
    def test_sustained_regression_flags_drift(self):
        # five healthy runs, then five 2%-per-run creeps: single-run
        # gating absorbs each step; the window-vs-window median does not
        series = [1.0] * 5 + [1.02, 1.05, 1.30, 1.32, 1.35]
        v = trend_verdict(series, direction=-1, k=5, drift_pct=10)
        assert v["verdict"] == "DRIFT"
        assert v["move_pct"] > 10

    def test_single_noisy_run_does_not_flag(self):
        series = [1.0] * 9 + [1.5]  # one outlier cannot move the median
        v = trend_verdict(series, direction=-1, k=5, drift_pct=10)
        assert v["verdict"] == "ok"

    def test_direction_of_good_respected(self):
        rising = [1.0] * 5 + [1.3] * 5
        # seconds rising = bad; anchored ratio rising = good
        assert trend_verdict(rising, -1, k=5)["verdict"] == "DRIFT"
        assert trend_verdict(rising, +1, k=5)["verdict"] == "ok"
        falling = [1.3] * 5 + [1.0] * 5
        assert trend_verdict(falling, -1, k=5)["verdict"] == "ok"
        assert trend_verdict(falling, +1, k=5)["verdict"] == "DRIFT"

    def test_warming_below_two_windows(self):
        v = trend_verdict([1.0] * (2 * ROLL_K - 1), direction=-1)
        assert v["verdict"] == "warming"

    def test_informational_metrics_never_gate(self):
        v = trend_verdict([1.0] * 20 + [9.0] * 20, direction=0)
        assert v["verdict"] == "n/a"

    def test_overhead_pct_drifts_on_absolute_points(self):
        # a near-zero-median paired statistic: −0.18pp → 0.46pp is a
        # +356% relative move but only 0.64 absolute points — noise,
        # not drift (the per-run 3% hard cap is the primary gate)
        series = [-0.53, 0.29, -0.75, 0.64, -0.18, 0.46, 0.58, -0.77,
                  2.68, -0.51]
        v = trend_verdict(series, direction=-1, k=5, kind="overhead_pct")
        assert v["verdict"] == "ok"
        # a sustained 2-point median creep IS drift
        crept = [0.0] * 5 + [2.0] * 5
        v = trend_verdict(crept, direction=-1, k=5, kind="overhead_pct")
        assert v["verdict"] == "DRIFT" and v["move_pct"] == 2.0

    def test_noisy_window_scales_relative_threshold(self):
        # the previous window's own span is ~32% of its median: an 18%
        # median move is inside the demonstrated run-to-run noise
        series = [0.30, 0.31, 0.38, 0.38, 0.40, 0.30, 0.46, 0.30, 0.31,
                  0.44]
        v = trend_verdict(series, direction=+1, k=5, kind="rel_to_anchor")
        assert v["verdict"] == "ok"
        # a tight window certifies the same relative move as drift
        tight = [0.38] * 5 + [0.31] * 5
        v = trend_verdict(tight, direction=+1, k=5, kind="rel_to_anchor")
        assert v["verdict"] == "DRIFT"


class TestTrendCheck:
    def test_check_counts_drifts_with_current_run_appended(self, tmp_path):
        # identical-metrics appends are idempotent, so stamp a tick
        path = str(tmp_path / "hist.jsonl")
        for i, v in enumerate([1.0] * 5 + [1.3, 1.3, 1.3, 1.3]):
            assert append_history(
                path, {"metrics": {"m": v, "tick": i}, "kinds": {"m": "seconds"}}
            )
        res = trend_check(path, {"m": 1.3, "tick": 99}, {"m": "seconds"})
        assert res["count"] == 1
        assert "m:" in res["items"][0]

    def test_empty_history_is_green(self, tmp_path):
        res = trend_check(str(tmp_path / "none.jsonl"), {"m": 1.0}, {"m": "seconds"})
        assert res["count"] == 0 and res["runs_recorded"] == 1

    def test_missing_runs_skipped_in_series(self):
        recs = _records([1.0] * 10)
        recs[3]["metrics"]["m"] = None  # a broken-kernel run
        verdicts = trend_verdicts(recs, k=4)
        assert verdicts["m"]["verdict"] in ("ok", "warming")


class TestHistoryIO:
    def test_extract_record_stamps_kinds(self):
        bench = {
            "hsvd": {"rel_to_anchor": 0.2, "seconds": 0.1},
            "lane": {"count": 0, "max_count": 0},
            "anchor": {"value": 111.0},
            "broken": {"error": "boom"},
        }
        rec = extract_record(bench, rev="abc", timestamp="t")
        assert rec["metrics"]["hsvd"] == 0.2
        assert rec["kinds"] == {"hsvd": "rel_to_anchor", "lane": "count",
                               "anchor": "value"}
        assert headline(bench["broken"]) is None
        assert headline_kind(bench["broken"]) is None

    def test_append_idempotent_and_checksummed(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        rec = {"metrics": {"m": 1.0}, "kinds": {"m": "seconds"}}
        assert append_history(path, rec)
        assert not append_history(path, dict(rec))
        assert os.path.exists(path + ".crc32")
        assert len(load_history(path)) == 1

    def test_backfill_idempotent_against_real_archives(self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = str(tmp_path / "h.jsonl")
        n1 = backfill_history(path, repo)
        n2 = backfill_history(path, repo)
        assert n2 == 0
        records = load_history(path)
        assert len(records) == n1
        assert all(r.get("archived") for r in records)
        if n1:  # archives present in this checkout
            assert all(r["metrics"] for r in records)
