"""Input-drift sketches + the end-to-end quality-signal loop (ISSUE 11).

The contract under test (docs/observability.md "Drift detection"):

* FeatureSketch moments are exact (batch Welford merge == one-shot)
  and the signed log-bucket tables are symmetric, zero-aware, and
  vectorized per batch;
* PSI reads ~0 for same-distribution traffic (above the small-sample
  floor), large for a shifted distribution; documents round-trip;
* the serving layer folds each coalesced batch's TRUE rows in AFTER
  the callers are woken, a baseline persists through save_model /
  Checkpointer / registry hot-load (and swaps on promote/rollback),
  and a drifted model flips its ``/driftz`` score, its per-model
  ``/healthz`` status, and a deduplicated ``drift:<model>`` alert;
* the acceptance loop: shifted traffic + a synthetic latency injection
  fire (then resolve) their alerts with an exemplar trace_id
  resolvable via ``/tracez?trace_id=``, visible in a merged
  cross-worker snapshot and a crash flight-recorder bundle;
* every user-influenced string in the HTML renderers (/tracez /sloz
  /driftz) is escaped — a model named ``<script>...`` renders inert.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving, telemetry
from heat_tpu.telemetry import aggregate
from heat_tpu.telemetry import alerts
from heat_tpu.telemetry import flight_recorder
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver
from heat_tpu.telemetry import sketch
from heat_tpu.telemetry import slo
from heat_tpu.telemetry import tracing

RNG = np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _clean_quality_signals():
    sketch.SKETCHES.clear()
    sketch.set_enabled(True)
    slo.reset_monitors()
    alerts.clear_alerts()
    yield
    sketch.SKETCHES.clear()
    sketch.set_enabled(True)
    slo.reset_monitors()
    alerts.clear_alerts()


def _in_dist(n, d=6, rng=None):
    return ((rng or RNG).normal(0.0, 1.0, (n, d))).astype(np.float32)


def _shifted(n, d=6, rng=None):
    return ((rng or RNG).normal(6.0, 4.0, (n, d))).astype(np.float32)


# ----------------------------------------------------------------------
# the sketch primitives
# ----------------------------------------------------------------------
class TestFeatureSketch:
    def test_moments_exact_and_batch_order_free(self):
        vals = RNG.normal(3.0, 2.0, 1000)
        one = sketch.FeatureSketch()
        one.update_batch(vals)
        split = sketch.FeatureSketch()
        for chunk in np.array_split(vals, 7):
            split.update_batch(chunk)
        for s in (one, split):
            assert s.count == 1000
            assert s.mean == pytest.approx(float(vals.mean()), rel=1e-9)
            assert s.variance == pytest.approx(float(vals.var()), rel=1e-6)
            assert s.min == float(vals.min()) and s.max == float(vals.max())

    def test_signed_zero_aware_buckets(self):
        s = sketch.FeatureSketch()
        s.update_batch(np.asarray([0.0, 1e-9, 2.0, -2.0, 2000.0]))
        b = s.buckets
        assert b.get(0) == 2  # both zeros
        pos = [k for k in b if k > 0]
        neg = [k for k in b if k < 0]
        assert len(pos) == 2 and len(neg) == 1
        assert -min(pos) in b  # +-2.0 mirror into symmetric buckets

    def test_doc_roundtrip(self):
        s = sketch.FeatureSketch()
        s.update_batch(RNG.normal(0, 1, 100))
        s2 = sketch.FeatureSketch.from_doc(json.loads(json.dumps(s.doc())))
        assert s2.count == s.count and s2.buckets == s.buckets
        assert s2.mean == pytest.approx(s.mean)

    def test_empty_batch_noop(self):
        s = sketch.FeatureSketch()
        s.update_batch(np.asarray([]))
        assert s.count == 0
        assert s.doc()["min"] is None


class TestDivergence:
    def test_psi_identity_and_shift(self):
        a = sketch.FeatureSketch()
        a.update_batch(RNG.normal(0, 1, 2000))
        b = sketch.FeatureSketch()
        b.update_batch(RNG.normal(0, 1, 2000))
        c = sketch.FeatureSketch()
        c.update_batch(RNG.normal(6, 4, 2000))
        assert sketch.psi(a.buckets, a.buckets) == pytest.approx(0.0, abs=1e-12)
        assert sketch.psi(a.buckets, b.buckets) < 0.1
        assert sketch.psi(a.buckets, c.buckets) > 0.25
        assert sketch.kl_divergence(a.buckets, c.buckets) > 0.1
        assert sketch.psi({}, {}) == 0.0

    def test_model_sketch_and_divergence_doc(self):
        ms = sketch.ModelSketch("m", 3)
        ms.update(_in_dist(500, 3))
        base = ms.doc()
        live = sketch.ModelSketch("m", 3)
        live.update(_shifted(500, 3))
        div = sketch.divergence(live.doc(), base)
        assert div["score"] > 0.25
        assert len(div["features"]) == 3
        assert div["worst_feature"] in (0, 1, 2)

    def test_model_sketch_width_mismatch_raises(self):
        ms = sketch.ModelSketch("m", 3)
        with pytest.raises(ValueError):
            ms.update(_in_dist(8, 5))


# ----------------------------------------------------------------------
# the registry: lifecycle, floors, toggles
# ----------------------------------------------------------------------
class TestSketchRegistry:
    def test_record_freeze_score(self):
        sketch.SKETCHES.record("m", _in_dist(1000))
        base = sketch.SKETCHES.freeze_baseline("m")
        assert base["count"] == 1000
        sketch.SKETCHES.record("m", _in_dist(400))
        st = sketch.SKETCHES.status("m")
        assert st["baseline"] and st["score"] is not None
        assert not st["drifting"]
        sketch.SKETCHES.reset_live("m")
        sketch.SKETCHES.record("m", _shifted(400))
        st = sketch.SKETCHES.status("m")
        assert st["drifting"]

    def test_small_sample_floor_reports_warming(self):
        sketch.SKETCHES.record("m", _in_dist(1000))
        sketch.SKETCHES.freeze_baseline("m")
        sketch.SKETCHES.record("m", _shifted(50))  # under HEAT_TPU_DRIFT_MIN_ROWS
        st = sketch.SKETCHES.status("m")
        assert st["warming"] and st["score"] is None and not st["drifting"]

    def test_freeze_without_traffic_raises(self):
        with pytest.raises(ValueError):
            sketch.SKETCHES.freeze_baseline("never_served")

    def test_disabled_records_nothing(self):
        sketch.set_enabled(False)
        assert not sketch.SKETCHES.record("m", _in_dist(100))
        assert sketch.SKETCHES.model_names() == []

    def test_check_drift_fires_and_resolves_alert(self):
        sketch.SKETCHES.record("m", _in_dist(1000))
        sketch.SKETCHES.freeze_baseline("m")
        sketch.SKETCHES.record("m", _shifted(400))
        sketch.check_drift()
        assert alerts.is_firing("drift:m", labels={"model": "m"})
        # back in distribution: score drops, alert resolves
        sketch.SKETCHES.reset_live("m")
        sketch.SKETCHES.record("m", _in_dist(400))
        sketch.check_drift()
        assert not alerts.is_firing("drift:m", labels={"model": "m"})
        ev = [e["event"] for e in alerts.alert_events() if e["name"] == "drift:m"]
        assert ev == ["fired", "resolved"]

    def test_digest_travels_in_snapshot_and_merges(self):
        sketch.SKETCHES.record("m", _in_dist(1000))
        sketch.SKETCHES.freeze_baseline("m")
        sketch.SKETCHES.record("m", _shifted(400))
        snap = aggregate.tag_snapshot()
        assert snap["drift"][0]["model"] == "m"
        other = dict(snap, process_index=1)
        merged = aggregate.merge_snapshots([snap, other], publish=False)
        assert merged["drift"]["m"]["drifting"]
        assert set(merged["drift"]["m"]["workers"]) == {"0", "1"}
        assert merged["drift"]["m"]["worst_score"] is not None


# ----------------------------------------------------------------------
# baseline persistence through the model store
# ----------------------------------------------------------------------
class TestBaselinePersistence:
    def _save(self, tmp_path, version=1, baseline_rows=1000, name="km"):
        x = ht.array(_in_dist(256), split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3,
                               random_state=0).fit(x)
        ms = sketch.ModelSketch(name, 6)
        ms.update(_in_dist(baseline_rows))
        d = str(tmp_path / f"model_v{version}")
        serving.save_model(km, d, version=version, name=name, baseline=ms.doc())
        return d

    def test_baseline_roundtrips_through_checkpointer(self, tmp_path):
        d = self._save(tmp_path)
        reg = serving.ModelRegistry()
        reg.load("km", d)
        assert reg.record("km")["baseline"]["count"] == 1000
        # the drift monitor got it attached on load
        assert sketch.SKETCHES.baseline("km")["count"] == 1000

    def test_save_without_baseline_still_loads(self, tmp_path):
        x = ht.array(_in_dist(256), split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3,
                               random_state=0).fit(x)
        d = str(tmp_path / "plain")
        serving.save_model(km, d, version=1, name="km")
        reg = serving.ModelRegistry()
        reg.load("km", d)
        assert reg.record("km")["baseline"] is None

    def test_promote_and_rollback_swap_baselines(self, tmp_path):
        d1 = self._save(tmp_path, version=1, baseline_rows=1000)
        d2 = self._save(tmp_path, version=2, baseline_rows=500)
        reg = serving.ModelRegistry()
        reg.load("km", d1)
        reg.load("km", d2, activate=False)  # canary: baseline unattached
        assert sketch.SKETCHES.baseline("km")["count"] == 1000
        reg.promote("km", 2)
        assert sketch.SKETCHES.baseline("km")["count"] == 500
        reg.rollback("km")
        assert sketch.SKETCHES.baseline("km")["count"] == 1000


# ----------------------------------------------------------------------
# renderer escaping (the XSS-shaped satellite)
# ----------------------------------------------------------------------
class TestRendererEscaping:
    EVIL = '<script>alert("pwn")</script>'

    def test_tracez_escapes_hostile_model_and_route(self):
        telemetry.set_tracing(True)
        with tracing.request_span(f"/v1/predict/{self.EVIL}", model=self.EVIL):
            pass
        html = tracing.render_tracez_html()
        assert "<script>" not in html
        assert "&lt;script&gt;" in html
        tracing.reset_store()

    def test_driftz_escapes_hostile_model_name(self):
        sketch.SKETCHES.record(self.EVIL, _in_dist(1000))
        sketch.SKETCHES.freeze_baseline(self.EVIL)
        sketch.SKETCHES.record(self.EVIL, _shifted(400))
        sketch.check_drift()  # the alert label carries the name too
        html = sketch.render_driftz_html()
        assert "<script>" not in html
        assert "&lt;script&gt;" in html

    def test_predict_route_with_hostile_model_name_stays_inert(self, tmp_path):
        # the full HTTP path: a hostile model name POSTed to /v1/predict
        # lands (as a 404) yet taints the trace store; /tracez must
        # render it escaped
        telemetry.set_tracing(True)
        svc = serving.InferenceService(max_delay_ms=1.0)
        try:
            url = svc.serve(0)
            body = json.dumps(
                {"model": self.EVIL, "inputs": [[0.0] * 6]}
            ).encode()
            req = urllib.request.Request(
                url + "/v1/predict", data=body,
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req, timeout=5)
            assert exc_info.value.code == 404
            html = urllib.request.urlopen(url + "/tracez", timeout=5).read().decode()
            assert "<script>" not in html
        finally:
            svc.close()
            tserver.stop_server()
            tracing.reset_store()


# ----------------------------------------------------------------------
# the end-to-end quality-signal loop (the ISSUE 11 acceptance test)
# ----------------------------------------------------------------------
class TestEndToEndQualitySignals:
    def test_drift_flip_slo_burn_merge_and_bundle(self, tmp_path):
        telemetry.set_tracing(True)
        rng = np.random.default_rng(7)

        # a fitted model saved WITH its training-distribution baseline
        x = ht.array(_in_dist(512, rng=rng), split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3,
                               random_state=0).fit(x)
        ms = sketch.ModelSketch("km", 6)
        ms.update(_in_dist(2000, rng=rng))
        d = str(tmp_path / "km")
        serving.save_model(km, d, version=1, name="km", baseline=ms.doc())

        svc = serving.InferenceService(max_delay_ms=1.0, max_batch=64)
        try:
            svc.load("km", d)
            url = svc.serve(0)

            # -- phase 1: in-distribution traffic scores clean --------
            for _ in range(12):
                svc.predict("km", _in_dist(32, rng=rng))
            deadline = time.time() + 5
            while sketch.SKETCHES.status("km")["warming"] and time.time() < deadline:
                time.sleep(0.01)  # the post-batch hook runs off-path
            st = sketch.SKETCHES.status("km")
            assert st["score"] is not None and not st["drifting"], st

            # -- phase 2: deliberately shifted distribution -----------
            for _ in range(14):
                svc.predict("km", _shifted(32, rng=rng))
            deadline = time.time() + 5
            while not sketch.SKETCHES.status("km")["drifting"] and time.time() < deadline:
                time.sleep(0.01)
            st = sketch.SKETCHES.status("km")
            assert st["drifting"], st
            sketch.check_drift()
            assert alerts.is_firing("drift:km", labels={"model": "km"})

            # /driftz flips
            rep = json.loads(
                urllib.request.urlopen(url + "/driftz?format=json", timeout=5).read()
            )
            mdoc = [m for m in rep["models"] if m["model"] == "km"][0]
            assert mdoc["drifting"] and mdoc["score"] > mdoc["threshold"]
            # per-model /healthz flips status (liveness stays 200)
            hb = json.loads(
                urllib.request.urlopen(url + "/v1/models/km/healthz", timeout=5).read()
            )
            assert hb["status"] == "drifting" and hb["healthy"]
            assert hb["drift"]["score"] == mdoc["score"]
            assert any(a["name"] == "drift:km" for a in hb["alerts"])

            # -- phase 3: synthetic latency injection -> fast burn ----
            lat = tm.histogram("serving.latency_ms")
            lat.reset()  # drop phase-1/2 exemplars: the alert must pin
            # one of the synthetic injected traces below (and the reset
            # itself exercises the windowed math's reset safety)
            slo.install_default_slos()
            t0 = time.time()
            slo.evaluate(now=t0)
            tids = []
            for _ in range(150):
                with tracing.request_span("/v1/predict/km", model="km") as req:
                    pass
                lat.observe(90.0, exemplar=req.trace_id)
                tids.append(req.trace_id)
            verdicts = {v["name"]: v for v in slo.evaluate(now=t0 + 60)}
            assert verdicts["serving_latency"]["firing"]
            assert alerts.is_firing("slo:serving_latency")
            alert = [a for a in alerts.active_alerts()
                     if a["name"] == "slo:serving_latency"][0]
            assert alert["trace_id"] in tids

            # the exemplar resolves through /tracez?trace_id=
            tz = json.loads(
                urllib.request.urlopen(
                    url + f"/tracez?trace_id={alert['trace_id']}", timeout=5
                ).read()
            )
            assert tz["trace_id"] == alert["trace_id"]
            assert tz["route"] == "/v1/predict/km"

            # /sloz shows the firing objective
            sz = json.loads(
                urllib.request.urlopen(url + "/sloz?format=json", timeout=5).read()
            )
            assert any(s["firing"] for s in sz["slos"])

            # -- phase 4: both events in a merged cross-worker view ---
            snap = aggregate.tag_snapshot()
            merged = aggregate.merge_snapshots(
                [snap, dict(snap, process_index=1)], publish=False
            )
            assert merged["drift"]["km"]["drifting"]
            names = {a["name"] for a in merged["alerts"]["active"]}
            assert {"drift:km", "slo:serving_latency"} <= names

            # ...and in a crash flight-recorder bundle
            bdir = str(tmp_path / "bundles")
            path = flight_recorder.dump_bundle(
                RuntimeError("boom"), reason="test", directory=bdir
            )
            bundle = json.load(open(path))
            b_names = {a["name"] for a in bundle["alerts"]["active"]}
            assert {"drift:km", "slo:serving_latency"} <= b_names
            assert any(m["drifting"] for m in bundle["drift"]["models"])
            from heat_tpu.telemetry.inspect import format_bundle

            txt = format_bundle(bundle)
            assert "drift:km" in txt and "slo:serving_latency" in txt

            # -- phase 5: recovery resolves the burn alert ------------
            for _ in range(3000):
                lat.observe(2.0)
            slo.evaluate(now=t0 + 120)
            slo.evaluate(now=t0 + 190)
            assert not alerts.is_firing("slo:serving_latency")
            ev = [e["event"] for e in alerts.alert_events()
                  if e["name"] == "slo:serving_latency"]
            assert ev == ["fired", "resolved"]
        finally:
            svc.close()
            tserver.stop_server()
            tracing.reset_store()
            tm.reset("serving.")
