"""Gaussian naive Bayes, analog of heat/naive_bayes/gaussianNB.py
(gaussianNB.py:13).

Per-class mean/variance come from masked global reductions over the
sharded sample axis; ``partial_fit`` keeps the reference's incremental
moment-merge update (gaussianNB.py:180+).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


class GaussianNB(BaseEstimator, ClassificationMixin):
    """Gaussian likelihood naive Bayes classifier (gaussianNB.py:13)."""

    def __init__(self, priors: Optional[DNDarray] = None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.epsilon_ = None

    sigma_ = property(lambda self: self.var_)  # alias kept by the reference

    def fit(self, x: DNDarray, y: DNDarray, sample_weight: Optional[DNDarray] = None) -> "GaussianNB":
        """Estimate per-class Gaussian parameters (gaussianNB.py:120)."""
        self.classes_ = None
        self.theta_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(
        self,
        x: DNDarray,
        y: DNDarray,
        classes: Optional[DNDarray] = None,
        sample_weight: Optional[DNDarray] = None,
    ) -> "GaussianNB":
        """Incremental fit on a batch (gaussianNB.py:180), merging moments
        with the reference's count-weighted update."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"expected x to be 2D, got {x.ndim}D")
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        yd = y._dense().reshape(-1).astype(jnp.int32)
        if sample_weight is not None:
            w = sample_weight._dense().reshape(-1).astype(xd.dtype)
        else:
            w = jnp.ones((xd.shape[0],), xd.dtype)

        if self.classes_ is None:
            if classes is not None:
                cls = np.asarray(classes._dense() if isinstance(classes, DNDarray) else classes)
            else:
                cls = np.unique(np.asarray(yd))
            self.classes_ = DNDarray.from_dense(jnp.asarray(cls), None, x.device, x.comm)
            n_cls = len(cls)
            n_feat = xd.shape[1]
            self.theta_ = jnp.zeros((n_cls, n_feat), xd.dtype)
            self.var_ = jnp.zeros((n_cls, n_feat), xd.dtype)
            self.class_count_ = jnp.zeros((n_cls,), xd.dtype)

        cls_arr = self.classes_._dense()
        self.epsilon_ = self.var_smoothing * float(jnp.max(jnp.var(xd, axis=0)))

        theta = jnp.asarray(self.theta_) if not isinstance(self.theta_, DNDarray) else self.theta_._dense()
        var = jnp.asarray(self.var_) if not isinstance(self.var_, DNDarray) else self.var_._dense()
        counts = jnp.asarray(self.class_count_) if not isinstance(self.class_count_, DNDarray) else self.class_count_._dense()
        # remove the smoothing added by the previous partial_fit before
        # merging (sklearn/reference semantics), else epsilon compounds
        var = var - getattr(self, "_eps_applied", 0.0)

        new_theta, new_var, new_counts = [], [], []
        for i in range(cls_arr.shape[0]):
            mask = (yd == cls_arr[i]).astype(xd.dtype) * w
            n_new = jnp.sum(mask)
            safe = jnp.maximum(n_new, 1e-30)
            mu_new = jnp.sum(xd * mask[:, None], axis=0) / safe
            var_new = jnp.sum(((xd - mu_new[None, :]) ** 2) * mask[:, None], axis=0) / safe
            n_old = counts[i]
            mu_old = theta[i]
            var_old = var[i]
            n_tot = n_old + n_new
            safe_tot = jnp.maximum(n_tot, 1e-30)
            mu_tot = (n_old * mu_old + n_new * mu_new) / safe_tot
            # merged second moment (gaussianNB.py ~_update_mean_variance)
            ssd = (
                n_old * var_old
                + n_new * var_new
                + (n_old * n_new / safe_tot) * (mu_old - mu_new) ** 2
            )
            var_tot = ssd / safe_tot
            has_new = n_new > 0
            new_theta.append(jnp.where(n_tot > 0, mu_tot, mu_old))
            new_var.append(jnp.where(n_tot > 0, var_tot, var_old))
            new_counts.append(n_tot)
        counts_new = jnp.stack(new_counts)
        if self.priors is not None:
            pri = self.priors._dense() if isinstance(self.priors, DNDarray) else jnp.asarray(self.priors)
        else:
            pri = counts_new / jnp.maximum(jnp.sum(counts_new), 1e-30)

        # public attributes are DNDarrays (reference parity)
        wrap = lambda a: DNDarray.from_dense(a, None, x.device, x.comm)
        self.theta_ = wrap(jnp.stack(new_theta))
        self.var_ = wrap(jnp.stack(new_var) + self.epsilon_)
        self._eps_applied = self.epsilon_
        self.class_count_ = wrap(counts_new)
        self.class_prior_ = wrap(pri)
        return self

    def _joint_log_likelihood(self, x: DNDarray) -> jnp.ndarray:
        """Per-class joint log likelihood (gaussianNB.py:320)."""
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        theta = self.theta_._dense() if isinstance(self.theta_, DNDarray) else jnp.asarray(self.theta_)
        var = self.var_._dense() if isinstance(self.var_, DNDarray) else jnp.asarray(self.var_)
        prior_a = (
            self.class_prior_._dense()
            if isinstance(self.class_prior_, DNDarray)
            else jnp.asarray(self.class_prior_)
        )
        jll = []
        for i in range(theta.shape[0]):
            prior = jnp.log(jnp.maximum(prior_a[i], 1e-30))
            n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var[i]))
            n_ij = n_ij - 0.5 * jnp.sum(((xd - theta[i]) ** 2) / var[i], axis=1)
            jll.append(prior + n_ij)
        return jnp.stack(jll, axis=1)

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample (gaussianNB.py:360)."""
        if self.theta_ is None:
            raise RuntimeError("fit needs to be called before predict")
        jll = self._joint_log_likelihood(x)
        cls = self.classes_._dense()
        pred = cls[jnp.argmax(jll, axis=1)]
        return DNDarray.from_dense(pred, x.split, x.device, x.comm)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (gaussianNB.py:390)."""
        jll = self._joint_log_likelihood(x)
        log_prob = jll - jax_logsumexp(jll, axis=1, keepdims=True)
        return DNDarray.from_dense(jnp.exp(log_prob), x.split, x.device, x.comm)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        jll = self._joint_log_likelihood(x)
        return DNDarray.from_dense(jll - jax_logsumexp(jll, axis=1, keepdims=True), x.split, x.device, x.comm)


def jax_logsumexp(a, axis=None, keepdims=False):
    from jax.scipy.special import logsumexp

    return logsumexp(a, axis=axis, keepdims=keepdims)
