"""Second batch of reference test families (heat/core/tests/test_random.py,
test_types.py, test_complex_math.py, test_signal.py, test_logical.py
idiom): split-swept, numpy ground truth."""

import numpy as np
import pytest

import heat_tpu as ht


class TestRandomFamily:
    """test_random.py:1-900 behaviors."""

    def test_randint_bounds_and_dtype(self):
        ht.random.seed(9)
        a = ht.random.randint(3, 17, size=(200,), split=0)
        v = a.numpy()
        assert v.min() >= 3 and v.max() < 17
        assert np.issubdtype(v.dtype, np.integer)
        # single-arg form: [0, high)
        b = ht.random.randint(5, size=(50,))
        assert b.numpy().min() >= 0 and b.numpy().max() < 5

    def test_rand_range_and_randn_moments(self):
        ht.random.seed(10)
        u = ht.random.rand(4096, split=0).numpy()
        assert u.min() >= 0.0 and u.max() < 1.0
        n = ht.random.randn(8192, split=0).numpy()
        assert abs(n.mean()) < 0.1 and abs(n.std() - 1.0) < 0.1

    def test_permutation_and_randperm(self):
        ht.random.seed(11)
        p = ht.random.randperm(31).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(31))
        x = np.arange(17)
        q = ht.random.permutation(ht.array(x, split=0)).numpy()
        np.testing.assert_array_equal(np.sort(q), x)

    def test_get_set_state_roundtrip(self):
        ht.random.seed(12)
        _ = ht.random.rand(10)
        state = ht.random.get_state()
        a = ht.random.rand(20, split=0).numpy()
        ht.random.set_state(state)
        b = ht.random.rand(20, split=0).numpy()
        np.testing.assert_array_equal(a, b)

    def test_normal_loc_scale(self):
        ht.random.seed(13)
        v = ht.random.normal(5.0, 0.5, (8192,), split=0).numpy()
        assert abs(v.mean() - 5.0) < 0.1
        assert abs(v.std() - 0.5) < 0.1

    def test_choice(self):
        ht.random.seed(14)
        pool = ht.array(np.array([2.0, 4.0, 8.0, 16.0]))
        picks = ht.random.choice(pool, 64).numpy()
        assert set(np.unique(picks)).issubset({2.0, 4.0, 8.0, 16.0})


class TestTypePromotionMatrix:
    """test_types.py promotion table, exhaustively over the numeric lattice."""

    TYPES = ["uint8", "int8", "int16", "int32", "int64", "bfloat16", "float32", "float64"]

    def test_promote_types_commutes_and_is_idempotent(self):
        for a in self.TYPES:
            ta = ht.canonical_heat_type(a)
            assert ht.promote_types(ta, ta) == ta
            for b in self.TYPES:
                tb = ht.canonical_heat_type(b)
                ab = ht.promote_types(ta, tb)
                ba = ht.promote_types(tb, ta)
                assert ab == ba, (a, b)
                # promotion result absorbs both inputs
                assert ht.promote_types(ab, ta) == ab
                assert ht.promote_types(ab, tb) == ab

    def test_binary_op_result_types(self):
        a32 = ht.arange(4, dtype=ht.int32)
        f32 = ht.arange(4, dtype=ht.float32)
        assert (a32 + f32).dtype == ht.float32
        assert (a32 + a32).dtype in (ht.int32, ht.int64)
        b16 = ht.arange(4, dtype=ht.bfloat16)
        assert (b16 + f32).dtype == ht.float32

    def test_heat_type_of(self):
        assert ht.heat_type_of(np.zeros(3, np.float64)) == ht.float64
        assert ht.heat_type_of(ht.arange(3)) in (ht.int32, ht.int64)

    def test_iinfo_finfo(self):
        assert ht.iinfo(ht.int16).max == 32767
        assert ht.finfo(ht.float32).eps == np.finfo(np.float32).eps


class TestComplexFamily:
    """test_complex_math.py behaviors on the (host-capable) complex path."""

    def test_real_imag_conj_angle(self):
        z = np.array([1 + 2j, -3 + 0.5j, 0 - 1j], np.complex64)
        a = ht.array(z, split=0)
        np.testing.assert_allclose(ht.real(a).numpy(), z.real, rtol=1e-6)
        np.testing.assert_allclose(ht.imag(a).numpy(), z.imag, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ht.conjugate(a).numpy()), np.conj(z), rtol=1e-6)
        np.testing.assert_allclose(ht.angle(a).numpy(), np.angle(z), rtol=1e-6)
        np.testing.assert_allclose(ht.angle(a, deg=True).numpy(), np.angle(z, True), rtol=1e-6)

    def test_abs_of_complex(self):
        z = np.array([3 + 4j, 0 + 0j], np.complex64)
        np.testing.assert_allclose(ht.abs(ht.array(z)).numpy(), [5.0, 0.0], rtol=1e-6)

    def test_iscomplex_isreal(self):
        z = np.array([1 + 1j, 2 + 0j], np.complex64)
        np.testing.assert_array_equal(ht.iscomplex(ht.array(z)).numpy(), [True, False])
        np.testing.assert_array_equal(ht.isreal(ht.array(z)).numpy(), [False, True])


class TestSignalFamily:
    """test_signal.py: convolve across modes, kernels and splits."""

    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    @pytest.mark.parametrize("split", [None, 0])
    def test_convolve_modes(self, mode, split):
        rng = np.random.default_rng(20)
        sig = rng.standard_normal(41)
        # 'same' requires odd kernels (the reference's restriction,
        # heat/core/signal.py); other modes accept even lengths too
        for klen in (3, 5) if mode == "same" else (3, 5, 8):
            ker = rng.standard_normal(klen)
            got = ht.convolve(ht.array(sig, split=split), ht.array(ker), mode=mode)
            np.testing.assert_allclose(
                got.numpy(), np.convolve(sig, ker, mode=mode), atol=1e-10,
                err_msg=f"{mode}/{klen}/{split}",
            )

    def test_convolve_same_rejects_even_kernel(self):
        with pytest.raises(ValueError):
            ht.convolve(ht.arange(10, dtype=ht.float32), ht.ones(4), mode="same")

    def test_convolve_uneven_extent(self):
        # 13 over 8 devices: halo exchange with ragged shards
        sig = np.arange(13.0)
        ker = np.array([1.0, 2.0, 1.0])
        got = ht.convolve(ht.array(sig, split=0), ht.array(ker), mode="same")
        np.testing.assert_allclose(got.numpy(), np.convolve(sig, ker, mode="same"), atol=1e-12)


class TestLogicalFamily:
    """test_logical.py: all/any/isclose/allclose/logical ops across splits."""

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_all_any_axis(self, split):
        m = np.array([[True, True, False], [True, True, True]])
        a = ht.array(m, split=split)
        assert bool(ht.all(a)) == m.all()
        assert bool(ht.any(a)) == m.any()
        np.testing.assert_array_equal(np.asarray(ht.all(a, axis=0).numpy()), m.all(0))
        np.testing.assert_array_equal(np.asarray(ht.any(a, axis=1).numpy()), m.any(1))

    def test_isclose_allclose(self):
        a = ht.array(np.array([1.0, 2.0, 3.0]), split=0)
        b = ht.array(np.array([1.0, 2.0 + 1e-9, 3.1]), split=0)
        np.testing.assert_array_equal(
            ht.isclose(a, b).numpy(), np.isclose([1, 2, 3], [1, 2 + 1e-9, 3.1])
        )
        assert not bool(ht.allclose(a, b))
        assert bool(ht.allclose(a, a))

    def test_logical_ops(self):
        x = ht.array(np.array([True, False, True]), split=0)
        y = ht.array(np.array([True, True, False]), split=0)
        np.testing.assert_array_equal(ht.logical_and(x, y).numpy(), [True, False, False])
        np.testing.assert_array_equal(ht.logical_or(x, y).numpy(), [True, True, True])
        np.testing.assert_array_equal(ht.logical_xor(x, y).numpy(), [False, True, True])
        np.testing.assert_array_equal(ht.logical_not(x).numpy(), [False, True, False])
