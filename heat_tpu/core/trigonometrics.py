"""Trigonometric operations, analog of heat/core/trigonometrics.py (24 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import __local_op as _local_op
from ._operations import __binary_op as _binary_op

__all__ = [
    "arccos",
    "acos",
    "arccosh",
    "acosh",
    "arcsin",
    "asin",
    "arcsinh",
    "asinh",
    "arctan",
    "atan",
    "arctan2",
    "atan2",
    "arctanh",
    "atanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "i0",
    "sin",
    "sinc",
    "sinh",
    "tan",
    "tanh",
    "unwrap",
]


def arccos(x, out=None):
    """Inverse cosine (trigonometrics.py:30)."""
    return _local_op(jnp.arccos, x, out)


acos = arccos


def arccosh(x, out=None):
    """Inverse hyperbolic cosine (trigonometrics.py:66)."""
    return _local_op(jnp.arccosh, x, out)


acosh = arccosh


def arcsin(x, out=None):
    """Inverse sine (trigonometrics.py:102)."""
    return _local_op(jnp.arcsin, x, out)


asin = arcsin


def arcsinh(x, out=None):
    """Inverse hyperbolic sine (trigonometrics.py:138)."""
    return _local_op(jnp.arcsinh, x, out)


asinh = arcsinh


def arctan(x, out=None):
    """Inverse tangent (trigonometrics.py:174)."""
    return _local_op(jnp.arctan, x, out)


atan = arctan


def arctan2(t1, t2):
    """Quadrant-aware arctan(t1/t2) (trigonometrics.py:210)."""
    return _binary_op(jnp.arctan2, t1, t2)


atan2 = arctan2


def arctanh(x, out=None):
    """Inverse hyperbolic tangent (trigonometrics.py:247)."""
    return _local_op(jnp.arctanh, x, out)


atanh = arctanh


def cos(x, out=None):
    """Cosine (trigonometrics.py:283)."""
    return _local_op(jnp.cos, x, out)


def cosh(x, out=None):
    """Hyperbolic cosine (trigonometrics.py:319)."""
    return _local_op(jnp.cosh, x, out)


def deg2rad(x, out=None):
    """Degrees to radians (trigonometrics.py:355)."""
    return _local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x, out=None):
    """Radians to degrees (trigonometrics.py:419)."""
    return _local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x, out=None):
    """Sine (trigonometrics.py:450)."""
    return _local_op(jnp.sin, x, out)


def sinh(x, out=None):
    """Hyperbolic sine (trigonometrics.py:486)."""
    return _local_op(jnp.sinh, x, out)


def tan(x, out=None):
    """Tangent (trigonometrics.py:522)."""
    return _local_op(jnp.tan, x, out)


def tanh(x, out=None):
    """Hyperbolic tangent (trigonometrics.py:558)."""
    return _local_op(jnp.tanh, x, out)


def sinc(x, out=None):
    """Normalized sinc sin(pi x)/(pi x) (numpy extension beyond the
    reference's checklist)."""
    return _local_op(jnp.sinc, x, out)


def i0(x, out=None):
    """Modified Bessel function of the first kind, order 0 (numpy
    extension beyond the reference)."""
    return _local_op(jnp.i0, x, out)


def unwrap(p, discont=None, axis: int = -1, period: float = 6.283185307179586):
    """Unwrap a phase signal along ``axis`` (numpy extension).

    A cumulative correction along the axis: computed on the dense global
    view so split-axis padding can never leak into the scan.
    """
    from .dndarray import DNDarray

    if not isinstance(p, DNDarray):
        raise TypeError(f"expected p to be a DNDarray, but was {type(p)}")
    arr = p._dense()
    if not types.heat_type_is_inexact(p.dtype):
        arr = arr.astype(jnp.result_type(arr.dtype, float))
    res = jnp.unwrap(arr, discont=discont, axis=axis, period=period)
    return DNDarray.from_dense(res, p.split, p.device, p.comm)
