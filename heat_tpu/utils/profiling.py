"""Profiling/tracing hooks.

The reference instruments benchmarks with the external ``perun``
runtime/energy monitor (``@monitor()`` decorators, benchmarks/cb/
linalg.py:4,7); the library itself has no tracing (SURVEY.md §5).  The
TPU-native equivalent is jax.profiler: Xprof/perfetto traces with named
regions so collectives show up attributed to framework ops.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, Optional

import jax

__all__ = ["annotate", "monitor", "start_trace", "stop_trace", "trace"]


def start_trace(log_dir: str) -> None:
    """Begin an Xprof/perfetto trace (analog of starting a perun run)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None):
    """Context manager tracing the enclosed region."""
    if log_dir is None:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: str):
    """Named trace region; nests into the XLA timeline."""
    return jax.profiler.TraceAnnotation(name)


def monitor(name: Optional[str] = None):
    """Decorator measuring wall time of a benchmark function — the drop-in
    analog of perun's ``@monitor()`` (benchmarks/cb/linalg.py:7).  Blocks on
    the function's jax outputs so async dispatch doesn't hide device time.
    """

    def deco(fn: Callable):
        label = name or fn.__name__

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                out = fn(*args, **kwargs)
                out = jax.block_until_ready(out) if _is_jax_tree(out) else out
            wrapped.last_runtime = time.perf_counter() - t0
            return out

        wrapped.last_runtime = None
        return wrapped

    return deco


def _is_jax_tree(x) -> bool:
    leaves = jax.tree_util.tree_leaves(x)
    return any(isinstance(l, jax.Array) for l in leaves)
