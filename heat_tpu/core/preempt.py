"""Cooperative preemption of long checkpointed fits (QoS scheduling).

The serving layer and the analytics fits share one device pool (the
paper's premise), so a latency spike arriving while a multi-minute
batch fit owns the chips is the steady state, not the exception.  This
module is the handshake that resolves the contention without killing
the fit's progress:

* A **requester** (the admission controller when a latency-class
  request is admitted under ``HEAT_TPU_QOS_PREEMPT_ON_LATENCY``, or an
  operator/test directly) calls :meth:`PreemptionGate.request` — a
  level-triggered signal ("the latency lane needs the chips"), not an
  edge: it stays pending until :meth:`PreemptionGate.clear`, so every
  fit that reaches a chunk boundary while the spike is on yields, not
  just the first one.
* A **fit** consults the gate between chunks of
  :func:`~heat_tpu.core.base.resumable_fit_loop` via
  :meth:`PreemptionGate.take` — *after* the boundary checkpoint is
  scheduled, so the pause is durable (the checkpoint machinery already
  guarantees killed+resumed == uninterrupted bitwise; a cooperative
  preemption simply stops at the same boundary a kill would).  A fit
  running without a checkpointer has nothing durable to pause into, so
  the gate refuses to preempt it (counted in
  ``qos.preempt_ignored``) — losing an un-checkpointed fit's work
  would cost more device time than the spike saves.

The honoring fit evaluates the ``qos.preempt`` fault site immediately
before raising :class:`~heat_tpu.resilience.errors.PreemptedError`, so
kill-and-resume tests can script "host dies at the exact moment the
fit yields" (``HEAT_TPU_FAULT_PLAN='{"qos.preempt": [{"at": 0,
"kind": "kill"}]}'``) and assert the resumed result is bitwise-equal
either way.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional

from ..analysis import tsan as _tsan
from ..analysis.protocols import ACTOR_PREEMPT, PREEMPT_CLEAR, PREEMPT_RAISE
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm

__all__ = ["PreemptionGate", "preemption_gate"]

#: requests/honors/refusals are process-lifetime counters in the shared
#: telemetry registry, so a bench or /varz scrape can see preemption
#: pressure without holding the gate
_REQUESTS_C = _tm.counter("qos.preempt_requests")
_PREEMPTIONS_C = _tm.counter("qos.preemptions")
_IGNORED_C = _tm.counter("qos.preempt_ignored")
_PENDING_G = _tm.gauge(
    "qos.preempt_pending", "1 while a preemption request is outstanding"
)


class PreemptionGate:
    """Level-triggered yield request between latency traffic and fits.

    ``request()`` raises the level (idempotent — re-requesting while
    pending refreshes the reason but counts one spike, not many),
    ``clear()`` lowers it, ``take(durable=...)`` is the fit-side poll
    at a chunk boundary.  ``take`` deliberately does NOT consume the
    pending request: the spike persists until the requester clears it,
    so *every* checkpointed fit hitting a boundary during the spike
    yields.
    """

    def __init__(self) -> None:
        # requesters are admission/handler threads, pollers are fit
        # threads: the registered lock keeps the pending slot and the
        # per-gate counters coherent and sanitizer-checkable
        self._lock = _tsan.register_lock("core.preemption")
        self._reason: Optional[str] = None
        self._requests = 0
        self._preemptions = 0
        self._ignored = 0
        #: stable per-gate key the journal events carry (the protocol
        #: conformance checker tracks one raise/clear machine per gate)
        self._gate_key = f"gate{next(_GATE_SEQ)}"

    # -- requester side -------------------------------------------------
    def request(self, reason: str = "latency spike") -> None:
        """Ask running checkpointed fits to yield at their next chunk
        boundary.  Level-triggered: stays pending until :meth:`clear`."""
        with self._lock:
            _tsan.note_access("core.preemption.state")
            fresh = self._reason is None
            self._reason = str(reason)
            if fresh:
                self._requests += 1
                _REQUESTS_C.inc()
        if fresh:
            _PENDING_G.set(1.0)
            # journal after our lock is released (emit takes its own)
            _journal.emit(
                ACTOR_PREEMPT, PREEMPT_RAISE,
                severity="warn",
                message=f"preemption requested: {reason}",
                evidence={"reason": str(reason), "gate": self._gate_key},
            )

    def clear(self) -> None:
        """Withdraw the request (the latency lane drained)."""
        with self._lock:
            _tsan.note_access("core.preemption.state")
            was, self._reason = self._reason, None
        _PENDING_G.set(0.0)
        if was is not None:
            raised = _journal.find_last(actor=ACTOR_PREEMPT, action=PREEMPT_RAISE)
            _journal.emit(
                ACTOR_PREEMPT, PREEMPT_CLEAR,
                severity="info",
                message=f"preemption cleared: {was}",
                cause=raised["event_id"] if raised else None,
                evidence={"reason": was, "gate": self._gate_key},
            )

    # -- fit side -------------------------------------------------------
    def pending(self) -> Optional[str]:
        """The outstanding request's reason, or None."""
        with self._lock:
            _tsan.note_access("core.preemption.state")
            return self._reason

    def take(self, durable: bool) -> Optional[str]:
        """Fit-side poll at a chunk boundary.

        Returns the reason to yield for, or None to keep computing.
        ``durable`` says whether this fit has a committed checkpoint to
        pause into — without one the gate refuses (the request stays
        pending for fits that can honor it) and counts the refusal.
        """
        with self._lock:
            _tsan.note_access("core.preemption.state")
            reason = self._reason
            if reason is None:
                return None
            if not durable:
                self._ignored += 1
                _IGNORED_C.inc()
                return None
            self._preemptions += 1
            _PREEMPTIONS_C.inc()
            return reason

    def stats(self) -> Dict[str, object]:
        """Snapshot of this gate's lifetime accounting."""
        with self._lock:
            _tsan.note_access("core.preemption.state")
            return {
                "pending": self._reason,
                "requests": self._requests,
                "preemptions": self._preemptions,
                "ignored": self._ignored,
            }


#: per-process gate counter behind each gate's journal scope key
_GATE_SEQ = itertools.count()

_GATE = PreemptionGate()


def preemption_gate() -> PreemptionGate:
    """The process-wide gate (admission arms it, fit loops poll it)."""
    return _GATE
