"""Non-finite guards for iterative fits.

An iterative solver that walks into NaN keeps "converging" — the shift
``sum((new - old)**2)`` of two NaN iterates is NaN, every comparison
with the tolerance is False, and the loop runs to ``max_iter`` before
handing the caller NaN centroids with a clean exit code.
:func:`guard_finite` turns that into a structured
:class:`DivergenceError` carrying the last finite iterate, so callers
can restart from it instead of discovering the NaNs three pipeline
stages later.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from .errors import DivergenceError

__all__ = ["guard_finite", "all_finite"]


def _as_array(x):
    # DNDarray duck-type: anything carrying _dense() reads its global array
    dense = getattr(x, "_dense", None)
    if callable(dense):
        return dense()
    return x


def all_finite(x) -> bool:
    """Host bool: every element of ``x`` (array / DNDarray / dict /
    list / tuple pytree) is finite.  Containers recurse leaf-wise — the
    streaming fits carry dict states (model arrays + the committed
    stream offset) through :func:`resumable_fit_loop`, and a NaN in any
    leaf must trip the divergence guard.  Forces a device sync — call at
    checkpoint cadence, not per iteration."""
    if isinstance(x, dict):
        return all(all_finite(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return all(all_finite(v) for v in x)
    arr = _as_array(x)
    if not hasattr(arr, "dtype"):
        arr = np.asarray(arr)
    if not jnp.issubdtype(arr.dtype, jnp.inexact):
        return True
    return bool(jnp.all(jnp.isfinite(arr)))


def guard_finite(
    x,
    what: str = "iterate",
    iteration: Optional[int] = None,
    last_good: Any = None,
    last_good_iteration: Optional[int] = None,
):
    """Raise :class:`DivergenceError` if ``x`` contains NaN/Inf.

    ``x`` passes through unchanged when finite, so the guard drops into
    an update chain: ``centers = guard_finite(step(centers), ...)``.
    ``last_good``/``last_good_iteration`` ride the raised error — the
    most recent finite iterate a caller can degrade to."""
    if not all_finite(x):
        where = f" at iteration {iteration}" if iteration is not None else ""
        hint = (
            f"; last finite iterate was iteration {last_good_iteration}"
            if last_good_iteration is not None
            else ""
        )
        raise DivergenceError(
            f"non-finite values in {what}{where} — the fit has diverged{hint}",
            iteration=iteration,
            last_good=last_good,
            last_good_iteration=last_good_iteration,
        )
    return x
