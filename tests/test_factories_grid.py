"""Deep width for the factories family: the analog of
heat/core/tests/test_factories.py's per-factory batteries (arange call
forms and dtype inference, linspace endpoint/retstep/num grids, logspace
bases, eye shapes, full/empty/zeros/ones plus the *_like split- and
dtype-inheritance contracts, meshgrid indexing modes, array ndmin/copy
semantics, exception contracts), table-compressed against numpy ground
truth on the virtual mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


# ------------------------------------------------------------------ arange

def test_arange_call_forms():
    cases = [
        ((10,), {}),
        ((2, 10), {}),
        ((2, 10, 3), {}),
        ((10, 2, -2), {}),
        ((0.0, 1.0, 0.25), {}),
        ((5.5,), {}),
        ((3, 30, 5), {"dtype": ht.float32}),
    ]
    for args, kw in cases:
        np_kw = {"dtype": np.float32} if kw else {}
        np.testing.assert_allclose(
            ht.arange(*args, **kw).numpy(), np.arange(*args, **np_kw),
            err_msg=f"arange{args}",
        )


def test_arange_dtype_inference():
    assert ht.arange(5).dtype == ht.int32
    assert ht.arange(5.0).dtype == ht.float32
    assert ht.arange(0, 1, 0.1).dtype == ht.float32
    assert ht.arange(5, dtype=ht.float64).dtype == ht.float64


@pytest.mark.parametrize("split", SPLITS)
def test_arange_split_matches_numpy(split):
    # 13 elements on an 8-device mesh: remainder chunks
    x = ht.arange(13, split=split)
    assert x.split == split
    np.testing.assert_array_equal(x.numpy(), np.arange(13))


def test_arange_empty_and_negative_ranges():
    np.testing.assert_array_equal(ht.arange(5, 5).numpy(), np.arange(5, 5))
    np.testing.assert_array_equal(ht.arange(5, 2).numpy(), np.arange(5, 2))
    np.testing.assert_array_equal(ht.arange(5, 2, -1).numpy(), np.arange(5, 2, -1))


# --------------------------------------------------------------- linspace

@pytest.mark.parametrize("split", SPLITS)
def test_linspace_grid(split):
    for start, stop, num, endpoint in [
        (0, 10, 7, True), (0, 10, 7, False), (-5, 5, 11, True),
        (3, 3, 5, True), (10, 0, 4, True), (0, 1, 1, True),
    ]:
        got = ht.linspace(start, stop, num, endpoint=endpoint, split=split)
        np.testing.assert_allclose(
            got.numpy(), np.linspace(start, stop, num, endpoint=endpoint),
            rtol=1e-6, err_msg=f"linspace({start},{stop},{num},{endpoint})",
        )


def test_linspace_retstep_and_dtype():
    vals, step = ht.linspace(0, 10, 5, retstep=True)
    nvals, nstep = np.linspace(0, 10, 5, retstep=True)
    np.testing.assert_allclose(vals.numpy(), nvals)
    assert abs(float(step) - nstep) < 1e-12
    assert ht.linspace(0, 1, 4, dtype=ht.float64).dtype == ht.float64


@pytest.mark.parametrize("base", [2.0, 10.0, np.e])
def test_logspace_bases(base):
    got = ht.logspace(0, 4, 9, base=base)
    np.testing.assert_allclose(got.numpy(), np.logspace(0, 4, 9, base=base), rtol=1e-5)
    got = ht.logspace(2, -2, 5, base=base, endpoint=False)
    np.testing.assert_allclose(
        got.numpy(), np.logspace(2, -2, 5, base=base, endpoint=False), rtol=1e-5
    )


# ---------------------------------------------------------------- eye/full

@pytest.mark.parametrize("split", SPLITS)
def test_eye_shape_grid(split):
    for shape, want in [
        (4, np.eye(4)),
        ((3, 5), np.eye(3, 5)),
        ((5, 3), np.eye(5, 3)),
        ((1, 1), np.eye(1)),
    ]:
        got = ht.eye(shape, split=split)
        np.testing.assert_array_equal(got.numpy(), want.astype(got.numpy().dtype))


@pytest.mark.parametrize("split", SPLITS)
def test_full_fill_values(split):
    for shape, fill in [((6, 5), 3), ((6, 5), -1.5), ((9,), True), ((2, 3, 4), 0)]:
        got = ht.full(shape, fill, split=split if np.ndim(shape) or split is None else split)
        np.testing.assert_allclose(got.numpy(), np.full(shape, fill, got.numpy().dtype))


@pytest.mark.parametrize("fname", ["zeros", "ones", "empty"])
@pytest.mark.parametrize("split", SPLITS)
def test_basic_factories_shape_dtype(fname, split):
    fn = getattr(ht, fname)
    for shape in [(7,), (5, 6), (2, 3, 5)]:
        for dtype in (ht.float32, ht.int32, ht.float64):
            got = fn(shape, dtype=dtype, split=split)
            assert got.shape == shape and got.dtype == dtype and got.split == split
            if fname != "empty":
                want = getattr(np, fname)(shape)
                np.testing.assert_allclose(got.numpy().astype(np.float64), want)


# ------------------------------------------------------------- *_like grid

@pytest.mark.parametrize("split", SPLITS)
def test_like_factories_inherit_and_override(split):
    base = ht.array(np.arange(30.0, dtype=np.float32).reshape(5, 6), split=split)
    for fname in ("zeros_like", "ones_like", "empty_like"):
        got = getattr(ht, fname)(base)
        assert got.shape == base.shape
        assert got.dtype == base.dtype
        assert got.split == base.split, fname
        # dtype override
        got64 = getattr(ht, fname)(base, dtype=ht.int64)
        assert got64.dtype == ht.int64
    fl = ht.full_like(base, 9.5)
    assert fl.split == base.split and fl.shape == base.shape
    np.testing.assert_allclose(fl.numpy(), np.full((5, 6), 9.5, np.float32))
    # split=None means inherit (reference __factory_like semantics);
    # an explicit axis overrides
    assert ht.zeros_like(base, split=None).split == base.split
    assert ht.zeros_like(base, split=1).split == 1


# ---------------------------------------------------------------- meshgrid

@pytest.mark.parametrize("indexing", ["xy", "ij"])
def test_meshgrid_modes(indexing):
    a, b, c = np.arange(3.0), np.arange(4.0), np.arange(2.0)
    got = ht.meshgrid(ht.array(a), ht.array(b), ht.array(c), indexing=indexing)
    want = np.meshgrid(a, b, c, indexing=indexing)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g.numpy(), w)


def test_meshgrid_empty_and_single():
    assert ht.meshgrid() == []
    (g,) = ht.meshgrid(ht.arange(4))
    np.testing.assert_array_equal(g.numpy(), np.arange(4))
    with pytest.raises(ValueError):
        ht.meshgrid(ht.arange(3), indexing="bad")


# ------------------------------------------------------------ array() forms

def test_array_ndmin_and_nested():
    got = ht.array([[1, 2], [3, 4]], ndmin=3)
    want = np.array([[1, 2], [3, 4]], ndmin=3)
    assert got.shape == want.shape
    np.testing.assert_array_equal(got.numpy(), want)
    # scalars, nested lists, numpy scalars
    assert ht.array(5).shape == ()
    np.testing.assert_array_equal(
        ht.array([[True, False], [False, True]]).numpy(),
        np.array([[True, False], [False, True]]),
    )
    assert ht.array(np.float64(2.5)).dtype == ht.float64


def test_array_dtype_override_and_copy_semantics():
    src = np.arange(6, dtype=np.int32)
    got = ht.array(src, dtype=ht.float32)
    assert got.dtype == ht.float32
    np.testing.assert_allclose(got.numpy(), src.astype(np.float32))
    # mutating the source after construction must not change the array
    arr = ht.array(src, copy=True)
    src[0] = 99
    assert arr.numpy()[0] == 0


@pytest.mark.parametrize("split", [0, 1])
def test_array_is_split_assembles_global(split):
    """is_split declares pre-chunked local data: the analog of the
    reference's is_split path assembling the global array from per-rank
    locals (factories.py:207-260)."""
    full = np.arange(24.0, dtype=np.float32).reshape(4, 6)
    got = ht.array(full, is_split=split)
    assert got.split == split
    assert got.shape[split] % full.shape[split] == 0  # n_devices copies joined


def test_factory_exceptions():
    with pytest.raises((ValueError, TypeError)):
        ht.zeros((3, 3), split=5)
    with pytest.raises((ValueError, TypeError)):
        ht.linspace(0, 1, -3)
    with pytest.raises((ValueError, TypeError)):
        ht.array([[1, 2], [3]])  # ragged nested list


# ------------------------------------------------- asarray / copy contracts

def test_asarray_passthrough_and_convert():
    x = ht.arange(5, dtype=ht.float32)
    assert ht.asarray(x) is x
    got = ht.asarray([1.0, 2.0])
    np.testing.assert_allclose(got.numpy(), np.asarray([1.0, 2.0], np.float32))
    # dtype change forces a new array
    y = ht.asarray(x, dtype=ht.int32)
    assert y.dtype == ht.int32


@pytest.mark.parametrize("split", SPLITS)
def test_fromfunction_like_grid(split):
    # linspace x arange outer combination exercises both factory paths in
    # one expression the way the reference's combined cases do
    row = ht.arange(5, dtype=ht.float32, split=None)
    col = ht.linspace(0, 1, 4, split=split)
    got = ht.expand_dims(col, 1) * row
    want = np.linspace(0, 1, 4)[:, None] * np.arange(5, dtype=np.float32)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-6)
