"""Distributed sample-sort (PSRS) as a shard_map collective program.

The analog of the reference's parallel sample-sort behind ``ht.sort``
(heat/core/manipulations.py:2497-2750: local sort -> gathered pivots ->
Alltoallv exchange -> local merge).  The TPU-native formulation keeps every
buffer statically shaped:

1.  **Pack**: each element becomes one uint64 key
    ``(order_bits(value) << 32) | global_index``.  ``order_bits`` maps the
    value to a uint32 whose unsigned order equals the value order
    (sign-flip trick for floats, offset for ints), and the global index
    makes every key DISTINCT — ties are broken exactly like a stable sort,
    and the classic PSRS bucket bound (no bucket exceeds 2·B for distinct
    keys, Shi & Schaeffer 1992) holds unconditionally, even for
    all-equal inputs.  Canonical padding positions get the max-uint64
    sentinel, which sorts strictly after every real key.
2.  **Local sort** of the packed keys (one radix/comparison sort of B).
3.  **Pivots**: p regular samples per shard, one all_gather of p*p keys,
    replicated sort, p-1 regular pivots.
4.  **Bucket exchange**: each element's bucket is found by searchsorted
    against the pivots; elements scatter into a (p, B) send buffer (bucket
    b's run goes to row b) and one ``all_to_all`` routes row b to shard b.
5.  **Local merge**: the 2·B bound lets ``top_k`` on the order-reversed
    keys (bitwise NOT) extract *all* real keys of the bucket, already
    sorted — no full p·B re-sort.
6.  **Rebalance**: bucket sizes are exchanged (all_gather of p counts),
    every key's exact global rank is its bucket offset + local position,
    and a second ``all_to_all`` routes each key to the canonical owner of
    its rank (device rank//B, column rank%B).  A column-wise min folds the
    received (p, B) buffer to the final (B,) block — exactly one source
    holds a real key per column.
7.  **Unpack** values and original indices from the final keys.

Total traffic: two all_to_alls of p·B keys + two small all_gathers,
against the gather path's full replication of the array on every device;
every local sort is B or 2B elements instead of the global N.

Caveats (documented, the gather path remains the fallback): 1-D along the
split axis, ascending, float32/int32/int64-packable dtypes, global size
< 2^32.  All NaN bit patterns sort last (as one canonical NaN key),
matching numpy and the gather path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ["sample_sort_1d", "supports_sample_sort", "SAMPLE_SORT_THRESHOLD"]

#: Global element count above which ``ht.sort`` prefers the sample-sort
#: collective over the gather path (tests lower it to force the path).
SAMPLE_SORT_THRESHOLD = 1 << 22

# numpy scalar: evaluating jnp.uint64 at import time OverflowErrors when
# jax_enable_x64 is off (the gate below requires x64, the import must not)
_SENT = np.uint64(0xFFFFFFFFFFFFFFFF)


def supports_sample_sort(a, axis: int, descending: bool) -> bool:
    """Whether the PSRS fast path applies to this sort call."""
    return (
        a.ndim == 1
        and a.split == 0
        and axis == 0
        and not descending
        and a.comm.size > 1
        and a.shape[0] >= SAMPLE_SORT_THRESHOLD
        and a.shape[0] < (1 << 32)
        and np.dtype(a.dtype.jax_type()) in (np.dtype("float32"), np.dtype("int32"))
        and jax.config.read("jax_enable_x64")
    )


def _order_bits(vals):
    """uint32 whose unsigned order equals the value order (NaNs sort last)."""
    if jnp.issubdtype(vals.dtype, jnp.floating):
        u = jax.lax.bitcast_convert_type(vals.astype(jnp.float32), jnp.uint32)
        # negative floats: flip all bits; non-negative: flip the sign bit
        mask = jnp.where(u >> 31 == 1, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000))
        # any NaN pattern -> the max key, matching the gather path's and the
        # reference's NaN-last convention (unpacks to the canonical qNaN)
        return jnp.where(jnp.isnan(vals), jnp.uint32(0xFFFFFFFF), u ^ mask)
    # int32/int64 in-range: offset shifts the order onto uint32
    return (vals.astype(jnp.int64) + jnp.int64(0x80000000)).astype(jnp.uint32)


def _unorder_bits(u, dtype):
    """Inverse of :func:`_order_bits`."""
    if jnp.issubdtype(dtype, jnp.floating):
        mask = jnp.where(u >> 31 == 1, jnp.uint32(0x80000000), jnp.uint32(0xFFFFFFFF))
        return jax.lax.bitcast_convert_type(u ^ mask, jnp.float32).astype(dtype)
    return (u.astype(jnp.int64) - jnp.int64(0x80000000)).astype(dtype)


@functools.lru_cache(maxsize=32)
def _psrs_fn(comm, m: int, b: int, dtype_name: str):
    """Jitted, cached PSRS executable for (mesh, global extent m, block b)."""
    mesh = comm.mesh
    axis = comm.axis_name
    p = comm.size
    dtype = jnp.dtype(dtype_name)

    def body(a_loc):
        # ---- 1. pack (value order bits, global index) into uint64 keys
        # all size-indexed arithmetic is int64: the gate admits m < 2^32,
        # so idx*b and per-bucket positions can exceed int32
        idx = jax.lax.axis_index(axis)
        gid = (idx.astype(jnp.int64) * b + jnp.arange(b, dtype=jnp.int64)).astype(jnp.uint64)
        keys = (_order_bits(a_loc).astype(jnp.uint64) << 32) | gid
        keys = jnp.where(gid < m, keys, _SENT)  # canonical padding -> sentinel

        # ---- 2. local sort
        keys = jnp.sort(keys)

        # ---- 3. regular samples -> gathered, replicated pivot selection
        sample_pos = ((jnp.arange(p) + 1) * b) // (p + 1)
        samples = keys[sample_pos]  # (p,)
        all_samples = jnp.sort(jax.lax.all_gather(samples, axis, axis=0, tiled=True))
        pivots = all_samples[(jnp.arange(p - 1) + 1) * p]  # (p-1,)

        # ---- 4. bucket exchange (reference's Alltoallv, manipulations.py:2600)
        bkt = jnp.searchsorted(pivots, keys, side="left").astype(jnp.int32)  # (b,)
        run_start = jnp.searchsorted(bkt, jnp.arange(p), side="left")  # (p,)
        col = jnp.arange(b, dtype=jnp.int64) - run_start[bkt].astype(jnp.int64)
        send = jnp.full((p, b), _SENT, jnp.uint64).at[bkt, col].set(keys, mode="drop")
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=True)

        # ---- 5. local merge via order-reversed top_k (2B bound, distinct keys)
        cap = min(2 * b, p * b)
        inv = ~recv.reshape(-1)  # order-reversing bijection on uint64
        top, _ = jax.lax.top_k(inv, cap)
        bucket = ~top  # ascending, all real keys first, sentinels last
        # int64 sum: a bucket may hold > 2^31 keys at the gate's upper bound
        k_real = jnp.sum((bucket != _SENT).astype(jnp.int64))

        # ---- 6. rebalance to the canonical distribution by exact rank
        # int64 throughout: int32 cumsum/rank would overflow for m >= 2^31
        # while the gate admits m < 2^32 (x64 is a gate requirement)
        counts = jax.lax.all_gather(k_real[None], axis, axis=0, tiled=True)  # (p,)
        offset = jnp.cumsum(counts) - counts
        rank = offset[idx] + jnp.arange(cap, dtype=jnp.int64)
        valid = jnp.arange(cap, dtype=jnp.int64) < k_real
        dest = jnp.where(valid, rank // b, p).astype(jnp.int32)  # p -> dropped
        dcol = jnp.where(valid, rank % b, 0).astype(jnp.int32)
        send2 = jnp.full((p, b), _SENT, jnp.uint64).at[dest, dcol].set(bucket, mode="drop")
        recv2 = jax.lax.all_to_all(send2, axis, split_axis=0, concat_axis=0, tiled=True)
        final_keys = jnp.min(recv2, axis=0)  # one real key per column

        # ---- 7. unpack
        vals = _unorder_bits((final_keys >> 32).astype(jnp.uint32), dtype)
        gids = (final_keys & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
        return vals, gids

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


def sample_sort_1d(a):
    """Sort a 1-D split-0 DNDarray ascending via the PSRS collective.

    Returns ``(values, indices)`` as DNDarrays with the input's split —
    the backing arrays come straight out of the shard_map in canonical
    layout; nothing is gathered.
    """
    from .dndarray import DNDarray

    comm = a.comm
    m = a.shape[0]
    b = a.larray_padded.shape[0] // comm.size
    fn = _psrs_fn(comm, m, b, str(jnp.dtype(a.dtype.jax_type())))
    vals, gids = fn(a.larray_padded)
    values = DNDarray(vals, (m,), a.dtype, 0, a.device, a.comm)
    from . import types

    indices = DNDarray(gids, (m,), types.int64, 0, a.device, a.comm)
    return values, indices
