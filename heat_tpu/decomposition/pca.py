"""Principal component analysis, analog of heat/decomposition/pca.py
(pca.py:19-496).

svd_solver options match the reference: 'full' (tall-skinny exact SVD),
'hierarchical' (hsvd_rank / hsvd_rtol) and 'randomized' (rsvd).
"""

from __future__ import annotations

import sys
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, TransformMixin, lazy_scalar_property, validate_resume_params
from ..core.dndarray import DNDarray
from ..core.linalg.svd import svd as _exact_svd
from ..core.linalg import svdtools
from ..telemetry.spans import span as _span

__all__ = ["PCA"]

#: checkpoint step ids of the two fit stages (directory-per-step layout)
_STAGE_MEAN = 0
_STAGE_FITTED = 1


class PCA(BaseEstimator, TransformMixin):
    """Linear dimensionality reduction via SVD of centered data (pca.py:19)."""

    def __init__(
        self,
        n_components: Optional[Union[int, float]] = None,
        copy: bool = True,
        whiten: bool = False,
        svd_solver: str = "hierarchical",
        tol: Optional[float] = None,
        iterated_power: Union[str, int] = "auto",
        n_oversamples: int = 10,
        power_iteration_normalizer: str = "qr",
        random_state: Optional[int] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        if whiten:
            raise NotImplementedError("whitening is not yet supported (matching pca.py:135)")
        if svd_solver not in ("full", "hierarchical", "randomized"):
            raise ValueError(f"svd_solver must be 'full', 'hierarchical' or 'randomized', got {svd_solver!r}")
        if random_state is not None and not isinstance(random_state, int):
            raise ValueError(f"random_state must be None or int, got {type(random_state)}")
        validate_resume_params(checkpoint_every, checkpoint_dir, resume_from)
        # PCA's fit is staged (mean -> solver) rather than iterated;
        # checkpoint_every acts as the enable flag for stage checkpoints
        self.checkpoint_every = checkpoint_every
        self.checkpoint_dir = checkpoint_dir
        self.resume_from = resume_from

        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.svd_solver = svd_solver
        self.tol = tol
        self.iterated_power = iterated_power
        self.n_oversamples = n_oversamples
        self.power_iteration_normalizer = power_iteration_normalizer
        self.random_state = random_state

        self.components_ = None
        self.explained_variance_ = None
        self.explained_variance_ratio_ = None
        self.singular_values_ = None
        self.mean_ = None
        self.n_components_ = None
        self._tevr = None
        self.noise_variance_ = None

    # fits store a lazy device scalar (no host sync inside fit); the
    # conversion happens once on first access
    total_explained_variance_ratio_ = lazy_scalar_property("_tevr", float)

    def _checkpointer(self, for_write: bool):
        directory = self.checkpoint_dir or self.resume_from
        if directory is None or (for_write and self.checkpoint_every is None):
            return None
        from ..utils.checkpoint import Checkpointer
        from ..utils.overlap import async_checkpoint_enabled

        ck = Checkpointer(directory)
        if for_write and async_checkpoint_enabled():
            # stage writes run on the overlap layer's background writer:
            # the mean-stage checkpoint overlaps the SVD solve, and fit()
            # drains the writer before returning or re-raising
            return ck.as_async()
        return ck

    def _restore_fitted(self, saved: dict, X: DNDarray) -> None:
        as_dnd = lambda a: DNDarray.from_dense(jnp.asarray(a), None, X.device, X.comm)
        self.mean_ = as_dnd(saved["mean"])
        self.components_ = as_dnd(saved["components"])
        self.singular_values_ = as_dnd(saved["singular_values"])
        self.explained_variance_ = as_dnd(saved["explained_variance"])
        self.explained_variance_ratio_ = as_dnd(saved["explained_variance_ratio"])
        self._tevr = saved["tevr"]
        self.n_components_ = saved["n_components"]

    def _fitted_payload(self) -> dict:
        # device references: the writer thread does the host transfer
        as_np = lambda d: d._dense()
        return {
            "stage": "fitted",
            "mean": as_np(self.mean_),
            "components": as_np(self.components_),
            "singular_values": as_np(self.singular_values_),
            "explained_variance": as_np(self.explained_variance_),
            "explained_variance_ratio": as_np(self.explained_variance_ratio_),
            "tevr": float(self._tevr),
            "n_components": int(self.n_components_),
        }

    def fit(self, X: DNDarray, y=None) -> "PCA":
        """Estimate principal components (pca.py:210).

        With ``checkpoint_every``/``checkpoint_dir`` set, the two fit
        stages (column mean, SVD solve) each commit a checkpoint;
        ``resume_from=dir`` skips every completed stage — a fit killed
        between the stages resumes with only the solver left, and a
        fully fitted checkpoint restores without touching the data.
        The recomputed stages are deterministic functions of X and the
        restored state, so a resumed fit reproduces the uninterrupted
        result exactly."""
        if not isinstance(X, DNDarray):
            raise TypeError(f"X must be a DNDarray, got {type(X)}")
        if X.ndim != 2:
            raise ValueError(f"X must be 2D, got {X.ndim}D")
        if y is not None:
            raise ValueError("PCA is an unsupervised transform; y must be None")
        from ..core import statistics
        from ..resilience.faults import inject

        writer = self._checkpointer(for_write=True)
        restored_mean = None
        if self.resume_from is not None:
            reader = self._checkpointer(for_write=False)
            step = reader.latest_step() if reader is not None else None
            if step is not None:
                saved = reader.restore(step)
                if saved.get("stage") == "fitted":
                    self._restore_fitted(saved, X)
                    return self
                restored_mean = saved["mean"]

        # async stage writes are drained on every exit path, so a
        # caller (or a test) listing the checkpoint directory right
        # after fit() raises/returns sees a deterministic step set
        solver_span = None
        try:
            n, f = X.shape
            if restored_mean is None:
                inject("pca.stage", stage="mean")
                with _span("pca.stage", stage="mean"):
                    mean = statistics.mean(X, axis=0)
                self.mean_ = mean
                if writer is not None:
                    # device reference, not a host copy: the snapshot is free and
                    # the device-to-host transfer runs on the writer thread
                    writer.save(_STAGE_MEAN, {"stage": "mean", "mean": mean._dense()})
            else:
                mean = DNDarray.from_dense(jnp.asarray(restored_mean), None, X.device, X.comm)
                self.mean_ = mean
            inject("pca.stage", stage="solver")
            # stage heartbeat; closed in the finally so an aborted solve
            # still records its span (and never leaks nesting depth)
            solver_span = _span("pca.stage", stage="solver", solver=self.svd_solver)
            solver_span.__enter__()
            centered = X - mean

            if self.random_state is not None:
                from ..core import random as ht_random

                ht_random.seed(self.random_state)

            rank_cap = min(n, f)
            if isinstance(self.n_components, float):
                if not 0.0 < self.n_components <= 1.0:
                    raise ValueError("float n_components must be in (0, 1]")
                k = None
                rtol = (1 - self.n_components) ** 0.5
            else:
                k = min(self.n_components, rank_cap) if self.n_components else rank_cap
                rtol = None

            if self.svd_solver == "full":
                U, S, V = _exact_svd(centered)
                s = S._dense()
                kk = k if k is not None else rank_cap
                self.components_ = DNDarray.from_dense(V._dense()[:, :kk].T, None, X.device, X.comm)
                self.singular_values_ = DNDarray.from_dense(s[:kk], None, X.device, X.comm)
                ev = s**2 / max(n - 1, 1)
                self.explained_variance_ = DNDarray.from_dense(ev[:kk], None, X.device, X.comm)
                ratio = ev / jnp.maximum(jnp.sum(ev), 1e-30)
                self.explained_variance_ratio_ = DNDarray.from_dense(ratio[:kk], None, X.device, X.comm)
                self._tevr = jnp.sum(ratio[:kk])
                self.n_components_ = kk
            elif self.svd_solver == "hierarchical":
                if rtol is not None:
                    U, S, V, err = svdtools.hsvd_rtol(centered, rtol=rtol, compute_sv=True)
                else:
                    U, S, V, err = svdtools.hsvd_rank(centered, maxrank=k, compute_sv=True)
                self.components_ = DNDarray.from_dense(V._dense().T, None, X.device, X.comm)
                self.singular_values_ = S
                s = S._dense()
                ev = s**2 / max(n - 1, 1)
                self.explained_variance_ = DNDarray.from_dense(ev, None, X.device, X.comm)
                total_var = jnp.sum(centered._dense().astype(jnp.float32) ** 2) / max(n - 1, 1)
                ratio = ev / jnp.maximum(total_var, 1e-30)
                self.explained_variance_ratio_ = DNDarray.from_dense(ratio, None, X.device, X.comm)
                self._tevr = 1.0 - err**2
                self.n_components_ = int(s.shape[0])
            else:  # randomized
                if k is None:
                    raise ValueError("randomized solver requires an integer n_components")
                p_iter = 0 if self.iterated_power == "auto" else int(self.iterated_power)
                U, S, V = svdtools.rsvd(centered, rank=k, n_oversamples=self.n_oversamples, power_iter=p_iter)
                self.components_ = DNDarray.from_dense(V._dense().T, None, X.device, X.comm)
                self.singular_values_ = S
                s = S._dense()
                ev = s**2 / max(n - 1, 1)
                self.explained_variance_ = DNDarray.from_dense(ev, None, X.device, X.comm)
                total_var = jnp.sum(centered._dense().astype(jnp.float32) ** 2) / max(n - 1, 1)
                self.explained_variance_ratio_ = DNDarray.from_dense(
                    ev / jnp.maximum(total_var, 1e-30), None, X.device, X.comm
                )
                self._tevr = jnp.sum(ev) / jnp.maximum(total_var, 1e-30)
                self.n_components_ = k
            if writer is not None:
                writer.save(_STAGE_FITTED, self._fitted_payload())
            return self
        finally:
            if solver_span is not None:
                solver_span.__exit__(*sys.exc_info())
            if writer is not None:
                if sys.exc_info()[0] is None:
                    writer.close()
                else:
                    try:  # the body exception wins over a writer error
                        writer.close()
                    except BaseException:  # lint: allow H501(body exception wins over a writer error)
                        pass

    def transform(self, X: DNDarray) -> DNDarray:
        """Project onto the principal axes (pca.py:380).

        Runs under the PCA precision scope: with a tolerance-policy bf16
        request active (``HEAT_TPU_PREDICT_DTYPE=bfloat16``), the
        projection matmul takes bf16 operands with f32 accumulation
        pinned — rounding enters only through the one-time quantization
        of the centered data and the fitted axes, keeping the projected
        coordinates within the declared rtol of the native path."""
        if self.components_ is None:
            raise RuntimeError("fit needs to be called before transform")
        if not isinstance(X, DNDarray):
            raise TypeError(f"X must be a DNDarray, got {type(X)}")
        from ..analysis import precision_policy as _pp
        from ..core.linalg import basics

        with _pp.scope("PCA"):
            centered = X - self.mean_
            if _pp.active_compute_dtype() == "bfloat16":
                xd = centered._dense().astype(jnp.bfloat16)
                w = self.components_._dense().T.astype(jnp.bfloat16)
                proj = jnp.matmul(xd, w, preferred_element_type=jnp.float32)
                split = 0 if X.split == 0 else None
                return DNDarray.from_dense(proj, split, X.device, X.comm)
            return basics.matmul(centered, self.components_.T)

    def inverse_transform(self, X: DNDarray) -> DNDarray:
        """Back-project to the original space (pca.py:430)."""
        if self.components_ is None:
            raise RuntimeError("fit needs to be called before inverse_transform")
        from ..core.linalg import basics

        return basics.matmul(X, self.components_) + self.mean_
