"""R5 FFT byte-cut experiments (VERDICT r4 #1).

The r4 roofline note pinned the problem: the planar 512^3 transform
schedules 43.1 GB against a 6.44 GB minimal model because every DFT stage
is 3 Karatsuba dots + combines + a separate twiddle pass (~112 B/el per
axis pass).  The candidates here re-express a complex DFT stage as ONE
real dot over an interleaved representation:

    z[..., 2j+c] (c in {re, im})  @  W2[2j+c, 2k+d]  ->  out[..., 2k+d]

with W2 the real 2x2-block form of the complex DFT matrix, and (for the
four-step variant) the twiddle folded into the stage-B batched matrices,
so no separate twiddle pass exists at all.

Each candidate is validated against np.fft.fftn at 128^3, then compiled
at 512^3 to read XLA's scheduled bytes (cost_analysis) and timed with
floor-aware amortized windows.  Prints one JSON line per candidate.
"""

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

PREC = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}


# ----------------------------------------------------------------------
# interleaved DFT constants
# ----------------------------------------------------------------------
@functools.lru_cache(maxsize=64)
def _wc(n: int, inverse: bool):
    j = np.arange(n, dtype=np.float64)
    jk = np.outer(j, j) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    return np.cos(ang), sign * np.sin(ang)


def _block2(wre, wim, dtype):
    """Real 2x2-block (interleaved) form of a complex matrix stack.

    wre/wim: (..., J, K) -> (..., J, 2, K, 2) with
    [c=0,d=0]=re, [c=1,d=0]=-im, [c=0,d=1]=im, [c=1,d=1]=re.
    """
    shp = wre.shape[:-2] + (wre.shape[-2], 2, wre.shape[-1], 2)
    W = np.zeros(shp, np.float64)
    W[..., 0, :, 0] = wre
    W[..., 1, :, 0] = -wim
    W[..., 0, :, 1] = wim
    W[..., 1, :, 1] = wre
    return W.astype(dtype)


@functools.lru_cache(maxsize=64)
def w2_full(n: int, inverse: bool, dtype: str):
    """(2n, 2n) interleaved complex DFT matrix."""
    wre, wim = _wc(n, inverse)
    return _block2(wre, wim, dtype).reshape(2 * n, 2 * n)


@functools.lru_cache(maxsize=64)
def w2_real_in(n: int, inverse: bool, dtype: str):
    """(n, 2n): real input -> interleaved complex output."""
    wre, wim = _wc(n, inverse)
    W = np.zeros((n, n, 2), np.float64)
    W[..., 0] = wre
    W[..., 1] = wim
    return W.astype(dtype).reshape(n, 2 * n)


@functools.lru_cache(maxsize=64)
def w2_fourstep(n: int, n1: int, inverse: bool, dtype: str):
    """Stage matrices for the one-dot-per-stage four-step.

    j = j1 + n1*j2, k = k2 + n2*k1 (C-order (j2, j1) in, (k1, k2) out).
    Stage A contracts j2 with W_{n2}; stage B contracts j1 with the
    twiddle FOLDED in: WB[k2, j1, k1] = T[j1, k2] * W_{n1}[j1, k1].
    Returns (WA (n2,2,n2,2), WB (n2, n1, 2, n1, 2)) block forms.
    """
    n2 = n // n1
    are, aim = _wc(n2, inverse)
    WA = _block2(are, aim, dtype)
    bre, bim = _wc(n1, inverse)
    j1 = np.arange(n1, dtype=np.float64)
    k2 = np.arange(n2, dtype=np.float64)
    jk = np.outer(j1, k2) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    tre, tim = np.cos(ang), sign * np.sin(ang)  # (n1, k2)
    # complex product (T * W): [k2, j1, k1]
    cre = tre.T[:, :, None] * bre[None, :, :] - tim.T[:, :, None] * bim[None, :, :]
    cim = tre.T[:, :, None] * bim[None, :, :] + tim.T[:, :, None] * bre[None, :, :]
    WB = _block2(cre, cim, dtype)
    return WA, WB


# ----------------------------------------------------------------------
# candidate passes.  All operate on an interleaved array z of logical
# shape (..., n, 2) (real input: no trailing 2) and transform ``axis``.
# ----------------------------------------------------------------------
_L = "abefghmn"  # batch letters (never j/i/k/l/c/d)


def _spec3(ndim_sp, axis, lhs_core, rhs_core, out_core):
    """Einsum spec with spatial dims ndim_sp, transform at ``axis``."""
    lead = _L[:axis]
    trail = _L[axis + 1 : ndim_sp]
    return f"{lead}{lhs_core}{trail}c,{rhs_core}->{lead}{out_core}{trail}d"


def pass_direct(z, axis, n, inverse, prec, real_in=False):
    """One-dot direct DFT along ``axis`` of interleaved z."""
    dt = str(z.dtype)
    ndim_sp = z.ndim - (0 if real_in else 1)
    lead = _L[:axis]
    trail = _L[axis + 1 : ndim_sp]
    if real_in:
        W = jnp.asarray(w2_real_in(n, inverse, dt).reshape(n, n, 2))
        spec = f"{lead}j{trail},jkd->{lead}k{trail}d"
        return jnp.einsum(spec, z, W, precision=prec)
    W = jnp.asarray(w2_full(n, inverse, dt).reshape(n, 2, n, 2))
    spec = f"{lead}j{trail}c,jckd->{lead}k{trail}d"
    return jnp.einsum(spec, z, W, precision=prec)


def pass_fourstep(z, axis, n, n1, inverse, prec, real_in=False):
    """Two-dot four-step along ``axis`` (twiddle folded into stage B)."""
    dt = str(z.dtype)
    n2 = n // n1
    WA, WB = w2_fourstep(n, n1, inverse, dt)
    ndim_sp = z.ndim - (0 if real_in else 1)
    lead = _L[:axis]
    trail = _L[axis + 1 : ndim_sp]
    shp = z.shape
    # split axis n -> (n2, n1): C-order puts x[j1 + n1*j2] at [j2, j1]
    pre = shp[:axis]
    post = shp[axis + 1 :]
    post_sp = post if real_in else post[:-1]  # spatial trail (no c dim)
    z = z.reshape(*pre, n2, n1, *post)
    if real_in:
        WAr = jnp.asarray(WA[:, 0])  # (n2, k2, 2): real input row
        sA = f"{lead}ji{trail},jkd->{lead}ki{trail}d"
        y = jnp.einsum(sA, z, WAr, precision=prec)
    else:
        sA = f"{lead}ji{trail}c,jckd->{lead}ki{trail}d"
        y = jnp.einsum(sA, z, jnp.asarray(WA), precision=prec)
    # y: (..., k2, j1, ..., d); stage B batched over k2, contract (j1, c)
    sB = f"{lead}kj{trail}c,kjcld->{lead}lk{trail}d"
    y = jnp.einsum(sB, y, jnp.asarray(WB), precision=prec)
    # (..., k1, k2, ..., d) -> merge to (..., n, ..., d): k = k2 + n2*k1
    return y.reshape(*pre, n, *post_sp, 2)


def hermitian_extend(z, axis, n_out, shape_sp):
    """Full interleaved spectrum from its first m = n//2+1 bins: one fused
    gather (index arithmetic over all spatial axes at once) + concat."""
    m = z.shape[axis]
    idx = []
    for d, s in enumerate(shape_sp):
        if d == axis:
            ar = n_out - np.arange(m, n_out)
        else:
            ar = np.concatenate([[0], np.arange(s - 1, 0, -1)])
        sh = [1] * len(shape_sp)
        sh[d] = -1
        idx.append(jnp.asarray(ar.reshape(sh)))
    ext = z[tuple(idx) + (slice(None),)]
    ext = ext * jnp.asarray([1.0, -1.0], z.dtype)
    return jnp.concatenate([z, ext], axis=axis)


# ----------------------------------------------------------------------
# full rfftn-3d candidates: x (S,S,S) real -> (re, im) full spectrum
# ----------------------------------------------------------------------
def make_v1(prec_name):
    prec = PREC[prec_name]

    def run(x):
        S = x.shape[0]
        m = S // 2 + 1
        z = pass_direct(x, 2, S, False, prec, real_in=True)  # (S,S,S,2)
        z = z[:, :, :m]
        z = pass_direct(z, 1, S, False, prec)
        z = pass_direct(z, 0, S, False, prec)
        z = hermitian_extend(z, 2, S, (S, S, S))
        return z[..., 0], z[..., 1]

    return run


def make_v2(prec_name, n1):
    prec = PREC[prec_name]

    def run(x):
        S = x.shape[0]
        m = S // 2 + 1
        z = pass_fourstep(x, 2, S, n1, False, prec, real_in=True)
        z = z[:, :, :m]
        z = pass_fourstep(z, 1, S, n1, False, prec)
        z = pass_fourstep(z, 0, S, n1, False, prec)
        z = hermitian_extend(z, 2, S, (S, S, S))
        return z[..., 0], z[..., 1]

    return run


def make_v0():
    from heat_tpu.fft import _planar as _pl

    def run(x):
        return _pl.real_fftn(x, [0, 1, 2], None)

    return run


# ----------------------------------------------------------------------
# harness
# ----------------------------------------------------------------------
def accuracy(fn, s=128):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((s, s, s)).astype(np.float32)
    re, im = jax.jit(fn)(jnp.asarray(x))
    got = np.asarray(re) + 1j * np.asarray(im)
    want = np.fft.fftn(x)
    return float(np.linalg.norm(got - want) / np.linalg.norm(want))


def measure(fn, s=512, n_iter=32, windows=3):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((s, s, s)).astype(np.float32))
    jit = jax.jit(fn)
    lowered = jit.lower(x)
    comp = lowered.compile()
    ca = comp.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    bytes_gb = float(ca.get("bytes accessed", 0.0)) / 1e9
    re, im = jit(x)
    float(re[0, 0, 0])  # drain compile
    f0 = jax.jit(lambda v: v + 1.0)
    zz = jnp.zeros(())
    float(f0(zz))
    floor = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(f0(zz))
        floor = min(floor, time.perf_counter() - t0)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        out = None
        for _ in range(n_iter):
            out = jit(x)
        float(out[0][0, 0, 0])
        best = min(best, (time.perf_counter() - t0 - floor) / n_iter)
    return bytes_gb, best


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None
    cands = {
        "v0_current": make_v0(),
        "v1_direct_highest": make_v1("highest"),
        "v1_direct_high": make_v1("high"),
        "v1_direct_default": make_v1("default"),
        "v2_fourstep64_highest": make_v2("highest", 64),
        "v2_fourstep64_high": make_v2("high", 64),
    }
    n = 512 ** 3
    for name, fn in cands.items():
        if only and only not in name:
            continue
        try:
            rel = accuracy(fn)
            gb, sec = measure(fn)
            print(
                json.dumps(
                    {
                        "cand": name,
                        "rel_err_128": float(f"{rel:.3g}"),
                        "bytes_gb_512": round(gb, 2),
                        "sec_512": round(sec, 4),
                        "nominal_gflops": round(5.0 * n * np.log2(n) / sec / 1e9, 1),
                        "pct_bw_minimal": round(100 * 6.44 / 652.8 / sec, 1),
                    }
                ),
                flush=True,
            )
        except Exception as e:
            print(json.dumps({"cand": name, "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)


if __name__ == "__main__":
    main()
