"""Neural network layer, analog of heat/nn.

The reference mounts ``torch.nn`` behind a module ``__getattr__`` fallback
(nn/__init__.py:19-31) so any layer not overridden resolves to torch.  The
TPU-native substrate is flax.linen: ``heat_tpu.nn.Dense`` etc. resolve to
``flax.linen`` layers the same way, with :class:`DataParallel` layered on
top.  ``heat_tpu.nn.functional`` falls through to ``jax.nn`` (the analog of
heat/nn/functional.py).
"""

from . import functional
from .attention import ring_attention, scaled_dot_product_attention, ulysses_attention
from .data_parallel import DataParallel, DataParallelMultiGPU

__all__ = [
    "DataParallel",
    "DataParallelMultiGPU",
    "functional",
    "ring_attention",
    "scaled_dot_product_attention",
    "ulysses_attention",
]


def __getattr__(name):
    """Fall back to flax.linen for unoverridden layers (nn/__init__.py:19)."""
    import flax.linen as _linen

    try:
        return getattr(_linen, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn' has no attribute {name!r}")
