"""Resilience-layer tests (ISSUE 2 tentpole).

The contract under test (docs/resilience.md):

* fault plans fire deterministically at scripted per-site call indices
  (and with a seeded probability), with per-site hit counters;
* RetryPolicy retries typed-retryable failures on the exact backoff
  schedule, never retries permanent faults, and supports a no-sleep
  deterministic test mode;
* every io writer is atomic — a crash mid-write is never visible to a
  reader — and a corrupt file fails loudly with ChecksumError on load;
* a transient injected fault on save is survived by the retry layer;
* the filesystem-native Checkpointer commits whole steps atomically and
  verifies checksums on restore;
* kmeans / lasso / pca fits killed at iteration/stage k and resumed from
  their checkpoints reproduce the uninterrupted result exactly;
* guard_finite turns NaN divergence into a structured DivergenceError
  carrying the last finite iterate;
* a dispatch compile failure falls back to eager execution once instead
  of crashing the op.
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.core import dispatch
from heat_tpu.utils.checkpoint import Checkpointer


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    # deterministic no-sleep retries for every test in this module
    monkeypatch.setenv("HEAT_TPU_RETRY_NO_SLEEP", "1")


# ----------------------------------------------------------------------
# fault plans
# ----------------------------------------------------------------------
class TestFaultPlans:
    def test_at_index_and_kinds(self):
        with rz.fault_plan({"io.write": [0, {"at": 2, "kind": "permanent"}]}) as inj:
            with pytest.raises(rz.TransientFault) as e:
                rz.inject("io.write")
            assert e.value.site == "io.write" and e.value.index == 0
            rz.inject("io.write")  # index 1: clean
            with pytest.raises(rz.PermanentFault):
                rz.inject("io.write")
        assert inj.hits["io.write"] == 3
        assert inj.injected["io.write"] == [(0, "transient"), (2, "permanent")]
        # deactivated on exit
        rz.inject("io.write")

    def test_glob_pattern_and_isolation(self):
        with rz.fault_plan({"io.*": [{"at": 0, "kind": "transient"}]}) as inj:
            with pytest.raises(rz.TransientFault):
                rz.inject("io.read")
            rz.inject("comm.collective")  # unmatched site: clean
        assert inj.hits == {"io.read": 1, "comm.collective": 1}

    def test_probability_deterministic_per_seed(self):
        def run(seed):
            fired = []
            with rz.fault_plan({"s": [{"p": 0.3, "kind": "transient"}]}, seed=seed) as inj:
                for i in range(50):
                    try:
                        rz.inject("s")
                    except rz.TransientFault:
                        fired.append(i)
            return fired

        a, b, c = run(0), run(0), run(1)
        assert a == b  # same seed + call sequence -> identical injections
        assert a != c  # different seed -> different schedule
        assert a  # p=0.3 over 50 calls fires at least once

    def test_times_cap(self):
        with rz.fault_plan({"s": [{"p": 1.0, "kind": "transient", "times": 2}]}) as inj:
            for _ in range(2):
                with pytest.raises(rz.TransientFault):
                    rz.inject("s")
            rz.inject("s")  # cap reached: clean
        assert len(inj.injected["s"]) == 2

    def test_env_plan_hook(self, monkeypatch):
        from heat_tpu.resilience import faults

        plan = {"plan": {"env.site": [{"at": 0, "kind": "permanent"}]}, "seed": 3}
        monkeypatch.setenv(faults.PLAN_ENV, json.dumps(plan))

        inj = faults.refresh_env_plan()
        try:
            assert inj is not None
            with pytest.raises(rz.PermanentFault):
                rz.inject("env.site")
        finally:
            faults._ACTIVE = None  # deactivate the process-global plan

    def test_bad_rules_rejected(self):
        with pytest.raises(ValueError):
            rz.fault_plan({"s": [{"at": 0, "kind": "wat"}]})
        with pytest.raises(ValueError):
            rz.fault_plan({"s": [{"kind": "transient"}]})
        with pytest.raises(ValueError):
            rz.fault_plan({"s": [{"p": 1.5}]})


# ----------------------------------------------------------------------
# retry policy
# ----------------------------------------------------------------------
class TestRetryPolicy:
    def test_backoff_schedule(self):
        pol = rz.RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.5, backoff=2.0, no_sleep=True)
        assert pol.schedule() == [0.1, 0.2, 0.4, 0.5]

    def test_succeeds_after_transients_records_delays(self):
        pol = rz.RetryPolicy(max_attempts=4, base_delay=0.05, no_sleep=True)
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise rz.TransientFault("flake")
            return "ok"

        assert pol.call(flaky) == "ok"
        assert len(attempts) == 3
        assert pol.last_delays == [0.05, 0.1]

    def test_gives_up_after_max_attempts(self):
        pol = rz.RetryPolicy(max_attempts=3, no_sleep=True)
        calls = []

        def always():
            calls.append(1)
            raise rz.TransientFault("down")

        with pytest.raises(rz.TransientFault):
            pol.call(always)
        assert len(calls) == 3

    def test_permanent_and_checksum_never_retried(self):
        pol = rz.RetryPolicy(max_attempts=5, no_sleep=True, retryable=(Exception,))
        for exc in (rz.PermanentFault("no"), rz.ChecksumError("f", 1, 2)):
            calls = []

            def fail(exc=exc):
                calls.append(1)
                raise exc

            with pytest.raises(type(exc)):
                pol.call(fail)
            assert len(calls) == 1  # zero retries

    def test_typed_filter(self):
        pol = rz.RetryPolicy(max_attempts=3, no_sleep=True, retryable=(OSError,))
        calls = []

        def typeerr():
            calls.append(1)
            raise TypeError("not retryable")

        with pytest.raises(TypeError):
            pol.call(typeerr)
        assert len(calls) == 1

    def test_attempt_timeout(self):
        import time as _time

        pol = rz.RetryPolicy(max_attempts=2, no_sleep=True, attempt_timeout=0.1)
        # the sleep only needs to outlive the 0.1s attempt budget with
        # margin; the executor's shutdown joins the sleeping worker, so
        # every extra second here is paid twice (once per attempt)
        with pytest.raises(rz.RetryTimeout):
            pol.call(lambda: _time.sleep(0.75))

    def test_decorator_and_stats(self):
        rz.reset_retry_stats()
        pol = rz.RetryPolicy(max_attempts=3, no_sleep=True)
        state = {"n": 0}

        @pol
        def op():
            state["n"] += 1
            if state["n"] < 2:
                raise rz.TransientFault("once")
            return 7

        assert op() == 7
        s = rz.retry_stats()
        assert s["retries"] == 1 and s["succeeded_after_retry"] == 1 and s["gave_up"] == 0


# ----------------------------------------------------------------------
# atomic io + checksums
# ----------------------------------------------------------------------
class TestAtomicIO:
    def test_torn_write_never_visible(self, tmp_path):
        p = str(tmp_path / "data.bin")
        with rz.atomic_write(p) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"generation one")
        with pytest.raises(RuntimeError):
            with rz.atomic_write(p) as tmp:
                with open(tmp, "wb") as f:
                    f.write(b"gen")  # partial second generation
                raise RuntimeError("crash mid-write")
        # reader sees the previous complete generation; no temp litter
        with open(p, "rb") as f:
            assert f.read() == b"generation one"
        assert sorted(os.listdir(tmp_path)) == ["data.bin", "data.bin.crc32"]
        assert rz.verify_checksum(p) is True

    def test_checksum_mismatch_fails_loudly(self, tmp_path):
        p = str(tmp_path / "x.npy")
        ht.save(ht.arange(32, dtype=ht.float32), p)
        with open(p, "r+b") as f:  # corrupt one byte of the payload
            f.seek(-1, 2)
            f.write(b"\xff")
        with pytest.raises(rz.ChecksumError) as e:
            ht.load(p)
        assert "checksum mismatch" in str(e.value)

    def test_save_load_roundtrip_with_sidecars(self, tmp_path):
        a = ht.arange(24, dtype=ht.float32, split=0).reshape(6, 4)
        for name in ("r.csv", "r.npy", "r.npz", "r.txt", "r.h5"):
            p = str(tmp_path / name)
            if name.endswith(".h5"):
                if not ht.io.supports_hdf5():
                    continue
                ht.save(a, p, "data")
                out = ht.load(p, "data")
            else:
                ht.save(a, p)
                out = ht.load(p)
            assert os.path.exists(p + ".crc32"), name
            got = np.asarray(out._dense()).reshape(6, 4)
            np.testing.assert_allclose(got, np.arange(24, dtype=np.float32).reshape(6, 4))

    def test_transient_fault_on_save_is_survived(self, tmp_path):
        rz.reset_retry_stats()
        p = str(tmp_path / "x.npy")
        with rz.fault_plan({"io.write": [0]}) as inj:
            ht.save(ht.arange(8, dtype=ht.float32), p)
        assert inj.injected["io.write"] == [(0, "transient")]
        out = np.asarray(ht.load(p)._dense())
        np.testing.assert_allclose(out, np.arange(8, dtype=np.float32))
        s = rz.retry_stats()
        assert s["retries"] >= 1 and s["succeeded_after_retry"] >= 1

    def test_transient_fault_on_read_is_survived(self, tmp_path):
        p = str(tmp_path / "x.csv")
        ht.save(ht.arange(6, dtype=ht.float32).reshape(3, 2), p)
        with rz.fault_plan({"io.open": [0]}) as inj:
            out = ht.load(p)
        assert inj.injected["io.open"] == [(0, "transient")]
        assert np.asarray(out._dense()).shape == (3, 2)

    def test_permanent_fault_on_save_propagates(self, tmp_path):
        p = str(tmp_path / "x.npy")
        with rz.fault_plan({"io.write": [{"at": 0, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ht.save(ht.arange(8, dtype=ht.float32), p)
        assert not os.path.exists(p)  # nothing partial was committed

    def test_checksum_disabled_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_IO_CHECKSUM", "0")
        p = str(tmp_path / "x.npy")
        ht.save(ht.arange(4, dtype=ht.float32), p)
        assert not os.path.exists(p + ".crc32")
        ht.load(p)


# ----------------------------------------------------------------------
# filesystem-native checkpointer
# ----------------------------------------------------------------------
class TestCheckpointer:
    def test_nested_roundtrip_and_steps(self, tmp_path):
        import jax.numpy as jnp

        ck = Checkpointer(str(tmp_path / "ck"))
        state = {
            "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)},
            "arr": ht.arange(10, dtype=ht.float32, split=0),
            "step": jnp.asarray(7),
            "meta": ["a", 2, (3.5, None)],
        }
        ck.save(0, state, extra_metadata={"epoch": 1})
        ck.save(5, state)
        assert ck.all_steps() == [0, 5] and ck.latest_step() == 5
        r = ck.restore(0)
        np.testing.assert_allclose(np.asarray(r["params"]["w"]), np.arange(6.0).reshape(2, 3))
        np.testing.assert_allclose(np.asarray(r["arr"]), np.arange(10.0))
        assert int(np.asarray(r["step"])) == 7
        assert r["meta"] == ["a", 2, (3.5, None)]  # tuple/list fidelity
        assert ck.metadata(0) == {"epoch": 1}

    def test_max_to_keep_prunes(self, tmp_path):
        ck = Checkpointer(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(4):
            ck.save(s, {"v": np.asarray([s])})
        assert ck.all_steps() == [2, 3]

    def test_kill_during_save_leaves_no_partial_step(self, tmp_path):
        d = str(tmp_path / "ck")
        ck = Checkpointer(d)
        ck.save(1, {"v": np.arange(4)})
        # permanent fault inside the step write: the staged dir must be
        # cleaned up and step 1 must stay the latest complete checkpoint
        with rz.fault_plan({"checkpoint.write": [{"at": 0, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ck.save(2, {"v": np.arange(8)})
        assert ck.all_steps() == [1]
        assert not [n for n in os.listdir(d) if n.startswith(".tmp")]
        np.testing.assert_allclose(np.asarray(ck.restore(1)["v"]), np.arange(4))

    def test_transient_save_fault_retried(self, tmp_path):
        ck = Checkpointer(str(tmp_path / "ck"))
        with rz.fault_plan({"checkpoint.save": [0]}) as inj:
            ck.save(3, {"v": np.arange(3)})
        assert inj.injected["checkpoint.save"] == [(0, "transient")]
        assert ck.latest_step() == 3

    def test_corrupt_checkpoint_raises_checksum_error(self, tmp_path):
        d = str(tmp_path / "ck")
        ck = Checkpointer(d)
        ck.save(0, {"v": np.arange(16, dtype=np.float64)})
        npz = os.path.join(d, "step_0", "arrays.npz")
        with open(npz, "r+b") as f:
            f.seek(20)  # flip payload bytes (not the already-zero zip tail)
            f.write(b"\xff\xff")
        with pytest.raises(rz.ChecksumError):
            ck.restore(0)


# ----------------------------------------------------------------------
# resumable estimator fits
# ----------------------------------------------------------------------
def _data(n=240, f=6, seed=13):
    ht.random.seed(seed)
    return ht.random.randn(n, f, split=0).astype(ht.float32)


class TestResumableFits:
    def test_kmeans_chunked_matches_plain(self, tmp_path):
        x = _data()
        kw = dict(n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3)
        plain = ht.cluster.KMeans(**kw).fit(x)
        ck = ht.cluster.KMeans(**kw, checkpoint_every=5, checkpoint_dir=str(tmp_path)).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()), np.asarray(ck.cluster_centers_._dense())
        )
        assert plain.n_iter_ == ck.n_iter_
        assert Checkpointer(str(tmp_path)).latest_step() == ck.n_iter_

    @pytest.mark.parametrize("est", ["kmeans", "kmedians", "kmedoids"])
    def test_kcluster_kill_and_resume_exact(self, tmp_path, est):
        x = _data()
        mk = {
            "kmeans": lambda **kw: ht.cluster.KMeans(n_clusters=4, init="random", max_iter=40,
                                                     tol=1e-4, random_state=3, **kw),
            "kmedians": lambda **kw: ht.cluster.KMedians(n_clusters=4, init="random", max_iter=40,
                                                         tol=1e-4, random_state=3, **kw),
            "kmedoids": lambda **kw: ht.cluster.KMedoids(n_clusters=4, init="random", max_iter=40,
                                                         random_state=3, **kw),
        }[est]
        plain = mk().fit(x)
        d = str(tmp_path / "ck")
        with rz.fault_plan({f"{est}.iter": [{"at": 1, "kind": "permanent"}]}):
            try:
                mk(checkpoint_every=2, checkpoint_dir=d).fit(x)
                interrupted = False  # converged before the scripted chunk
            except rz.PermanentFault:
                interrupted = True
        resumed = mk(checkpoint_every=2, resume_from=d).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(resumed.cluster_centers_._dense()),
        ), f"{est} resumed centers differ (interrupted={interrupted})"
        assert np.array_equal(
            np.asarray(plain.labels_._dense()), np.asarray(resumed.labels_._dense())
        )
        assert plain.n_iter_ == resumed.n_iter_

    def test_lasso_kill_and_resume_exact(self, tmp_path):
        x = _data(128, 6, seed=9)
        w = ht.array(np.asarray([1.5, 0.0, -2.0, 0.0, 0.5, 0.0], np.float32).reshape(-1, 1))
        y = x @ w
        kw = dict(lam=0.05, max_iter=50, tol=1e-7)
        plain = ht.regression.Lasso(**kw).fit(x, y)
        d = str(tmp_path / "ck")
        with rz.fault_plan({"lasso.iter": [{"at": 1, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ht.regression.Lasso(**kw, checkpoint_every=3, checkpoint_dir=d).fit(x, y)
        resumed = ht.regression.Lasso(**kw, checkpoint_every=3, resume_from=d).fit(x, y)
        assert np.array_equal(
            np.asarray(plain.theta._dense()), np.asarray(resumed.theta._dense())
        )
        assert plain.n_iter == resumed.n_iter

    @pytest.mark.parametrize("solver", ["hierarchical", "randomized"])
    def test_pca_kill_between_stages_and_resume_exact(self, tmp_path, solver):
        x = _data(64, 12, seed=11)
        kw = dict(n_components=4, svd_solver=solver, random_state=5)
        plain = ht.decomposition.PCA(**kw).fit(x)
        d = str(tmp_path / "ck")
        # stage index 1 is the solver: the mean checkpoint exists, the fit dies
        with rz.fault_plan({"pca.stage": [{"at": 1, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ht.decomposition.PCA(**kw, checkpoint_every=1, checkpoint_dir=d).fit(x)
        assert Checkpointer(d).all_steps() == [0]  # mean stage committed
        resumed = ht.decomposition.PCA(**kw, checkpoint_every=1, resume_from=d).fit(x)
        for attr in ("components_", "singular_values_", "explained_variance_"):
            assert np.array_equal(
                np.asarray(getattr(plain, attr)._dense()),
                np.asarray(getattr(resumed, attr)._dense()),
            ), attr
        # a fully fitted checkpoint restores without recomputation
        restored = ht.decomposition.PCA(**kw, resume_from=d).fit(x)
        assert np.array_equal(
            np.asarray(plain.components_._dense()), np.asarray(restored.components_._dense())
        )
        assert restored.n_components_ == plain.n_components_

    def test_kmeans_subprocess_kill_and_resume(self, tmp_path):
        """Real host preemption: the child process is os._exit-killed by
        the env fault plan at chunk 2 of the fit; the parent resumes from
        the surviving checkpoint and must match the uninterrupted run."""
        d = str(tmp_path / "ck")
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"  # mirror conftest
            "import heat_tpu as ht\n"
            "ht.random.seed(13)\n"
            "x = ht.random.randn(240, 6, split=0).astype(ht.float32)\n"
            f"ht.cluster.KMeans(n_clusters=4, init='random', max_iter=40, tol=1e-4,\n"
            f"                  random_state=3, checkpoint_every=2, checkpoint_dir={d!r}).fit(x)\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
            {"plan": {"kmeans.iter": [{"at": 1, "kind": "kill", "exit_code": 137}]}}
        )
        proc = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True, timeout=300
        )
        assert proc.returncode == 137, proc.stderr.decode()[-2000:]
        assert Checkpointer(d).latest_step() is not None  # chunk 1 survived
        x = _data()
        plain = ht.cluster.KMeans(
            n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3
        ).fit(x)
        resumed = ht.cluster.KMeans(
            n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3,
            checkpoint_every=2, resume_from=d,
        ).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(resumed.cluster_centers_._dense()),
        )

    def test_checkpoint_every_requires_dir(self):
        with pytest.raises(ValueError):
            ht.cluster.KMeans(n_clusters=2, checkpoint_every=5)
        with pytest.raises(ValueError):
            ht.regression.Lasso(checkpoint_every=0, checkpoint_dir="/tmp/x")


# ----------------------------------------------------------------------
# divergence guard
# ----------------------------------------------------------------------
class TestGuardFinite:
    def test_passthrough_and_raise(self):
        a = np.asarray([1.0, 2.0])
        assert rz.guard_finite(a, "v") is a
        with pytest.raises(rz.DivergenceError) as e:
            rz.guard_finite(np.asarray([1.0, np.inf]), "centers",
                            iteration=7, last_good=a, last_good_iteration=6)
        assert e.value.iteration == 7
        assert e.value.last_good_iteration == 6
        np.testing.assert_allclose(e.value.last_good, a)

    def test_integer_arrays_are_finite(self):
        assert rz.all_finite(np.arange(5))

    def test_kmeans_divergence_detected(self, tmp_path):
        bad = ht.array(np.full((32, 4), np.nan, np.float32), split=0)
        with pytest.raises(rz.DivergenceError) as e:
            ht.cluster.KMeans(
                n_clusters=2, init="random", max_iter=10, random_state=0,
                checkpoint_every=2, checkpoint_dir=str(tmp_path),
            ).fit(bad)
        assert e.value.iteration is not None
        assert e.value.last_good is not None  # structured last-good payload


# ----------------------------------------------------------------------
# dispatch compile-failure fallback + comm/init sites
# ----------------------------------------------------------------------
class TestDispatchFallback:
    def test_injected_compile_fault_falls_back_to_eager(self):
        a = ht.arange(16, dtype=ht.float32, split=0)
        dispatch.clear_cache()
        before = dispatch.cache_stats()["compile_fallbacks"]
        with rz.fault_plan({"dispatch.compile": [0]}):
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = float((a + 5.0).sum())
        assert out == float(np.arange(16, dtype=np.float32).sum() + 5.0 * 16)
        stats = dispatch.cache_stats()
        assert stats["compile_fallbacks"] == before + 1
        assert any("falling back to eager" in str(x.message) for x in w)
        # the broken entry was dropped: the op recompiles cleanly after
        assert float((a + 5.0).sum()) == out

    def test_genuine_errors_still_raise(self):
        a = ht.arange(8, dtype=ht.float32, split=0)
        b = ht.arange(6, dtype=ht.float32, split=0)
        with pytest.raises(Exception):
            (a + b).sum()  # shape mismatch surfaces from the eager path too

    def test_init_retries_transient_bootstrap_fault(self):
        with rz.fault_plan({"comm.init": [0]}) as inj:
            ht.parallel.init()  # transient at attempt 0, clean no-op retry
        assert inj.injected["comm.init"] == [(0, "transient")]
        assert inj.hits["comm.init"] >= 2
        assert ht.parallel.is_initialized()

    def test_collective_site_evaluated(self):
        comm = ht.get_comm()
        with rz.fault_plan({}) as inj:
            # trace-time evaluation of the injection point, no fault scripted
            try:
                import jax

                jax.eval_shape(
                    lambda v: comm.psum(v),
                    jax.ShapeDtypeStruct((4,), np.float32),
                )
            except Exception:
                pass  # psum outside shard_map may reject; the site still counts
        assert inj.hits.get("comm.collective", 0) >= 1


class TestResilienceStats:
    def test_merged_counters(self):
        rz.reset_retry_stats()
        rz.reset_fault_stats()
        with rz.fault_plan({"s": [0]}):
            with pytest.raises(rz.TransientFault):
                rz.inject("s")
        s = rz.resilience_stats()
        assert s["faults_injected"] == 1 and s["sites_evaluated"] == 1
        assert "retries" in s and "gave_up" in s
