"""Inference service: registry + coalescer + admission behind one surface.

:class:`InferenceService` is the composition the serving design doc
draws: a request enters through :meth:`~InferenceService.predict`
(Python) or ``POST /v1/predict`` (HTTP), passes **admission control**
(per-tenant quota, bounded depth — shed with
:class:`~heat_tpu.resilience.errors.OverloadedError`/429, never
queued-to-collapse), lands in its model's **coalescer** queue, rides a
padded **bucket** batch through the executable cache, and returns with
its slice of the batch result.

Every request runs under a **trace**
(:mod:`heat_tpu.telemetry.tracing`): one ``trace_id`` stamps the
``serve.request`` root, the per-stage spans (admission → coalesce_wait →
pad → dispatch → execute → scatter, across the request and batcher
threads), and any nested compile/comm spans.  End-to-end latency lands
in ``serving.latency_ms`` and each stage in its
``serving.stage.{stage}_ms`` histogram — bucket exemplars carry the
most recent trace_id, so a ``/metrics`` latency bucket links to the
concrete request retained in ``/tracez``; shed and errored requests are
always retained there.

HTTP surface (mounted on the telemetry introspection server through
:func:`~heat_tpu.telemetry.server.register_route` — one process, one
port):

=====================================  ================================
route                                  payload
=====================================  ================================
``GET /v1/models``                     registry listing: versions,
                                       active pointer, rollback history
``POST /v1/predict``                   ``{"model", "inputs", "tenant"?,
                                       "version"?}`` -> predictions
``GET /v1/models/<name>/healthz``      per-model liveness: loaded
                                       version, batcher thread alive,
                                       queue depth, last batch age
=====================================  ================================

Estimators are hot-swappable: the coalescer resolves the registry's
*active* version at every batch, so ``promote``/``rollback`` take
effect on the next tick with zero downtime and zero dropped requests.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..analysis import tsan as _tsan
from ..analysis.protocols import (
    ACTOR_REPLICA, REPLICA_DRAIN, REPLICA_READY, REPLICA_STOP, REPLICA_WARM,
)
from ..resilience.errors import OverloadedError
from ..resilience.faults import inject as _inject
from ..telemetry import alerts as _alerts
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry import server as _tserver
from ..telemetry import sketch as _sketch
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..telemetry.spans import stage_note as _stage_note
from . import canary as _canary
from .admission import QOS_CLASSES, AdmissionController
from .coalescer import ModelBatcher, observe_stage
from .model_io import infer as _infer
from .registry import ModelRegistry

__all__ = [
    "InferenceService",
    "default_service",
    "start_serving",
    "stop_serving",
]

#: lifecycle journal action per target state (PROTOCOLS "replica")
_STATE_ACTIONS = {
    "warming": REPLICA_WARM,
    "ready": REPLICA_READY,
    "draining": REPLICA_DRAIN,
    "stopped": REPLICA_STOP,
}

#: per-process instance counter behind each service's replica key
_SERVICE_SEQ = itertools.count()

_LATENCY_H = _tm.histogram(
    "serving.latency_ms", "end-to-end predict latency (admission to result)"
)

#: route prefix the service mounts on the introspection server
ROUTE_PREFIX = "/v1/"


def _env():
    from ..core import _env as envmod

    return envmod


class InferenceService:
    """A running inference service over a :class:`ModelRegistry`.

    ``split`` is the batch axis distribution of coalesced batches:
    ``None`` (default) replicates the bucket-padded batch — the right
    call at online batch sizes, and the path whose every op rides the
    executable cache; ``0`` shards rows across the serving mesh for
    large-bucket deployments (its predict programs are the jitted ring
    kernels, cached per bucket by jax itself).  Knobs default from the
    registry (``HEAT_TPU_SERVE_*``); constructor arguments override per
    instance."""

    def __init__(
        self,
        registry: Optional[ModelRegistry] = None,
        comm=None,
        split: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
    ):
        env = _env()
        self.registry = registry if registry is not None else ModelRegistry(comm=comm)
        self.split = split
        self.max_batch = (
            int(max_batch) if max_batch is not None
            else env.env_int("HEAT_TPU_SERVE_MAX_BATCH")
        )
        delay_ms = (
            float(max_delay_ms) if max_delay_ms is not None
            else env.env_float("HEAT_TPU_SERVE_MAX_DELAY_MS")
        )
        self.max_delay_s = delay_ms / 1e3
        self.admission = AdmissionController(
            max_depth=(
                int(queue_depth) if queue_depth is not None
                else env.env_int("HEAT_TPU_SERVE_QUEUE_DEPTH")
            ),
            default_rate=(
                float(rate) if rate is not None
                else env.env_float("HEAT_TPU_SERVE_RATE")
            ),
            default_burst=(
                float(burst) if burst is not None
                else env.env_float("HEAT_TPU_SERVE_BURST")
            ),
        )
        self._batchers: Dict[str, ModelBatcher] = {}
        self._open = True
        self._started_monitor = False
        #: per-tenant cost metering (HEAT_TPU_QOS_METER): each coalesced
        #: batch's analyzed FLOPs/bytes + device-ms are attributed to
        #: its member tenants pro rata by rows (/tenantz)
        self._meter = env.env_flag("HEAT_TPU_QOS_METER")
        #: batcher-thread-local handoff from _infer_batch (which meters
        #: the inference) to _account_batch (which settles it) — both
        #: run on the same batcher thread, in that order, per batch
        self._infer_cost = threading.local()
        #: lifecycle state the /readyz readiness verdict keys off:
        #: "warming" (up, pre-warming the executable cache — not ready),
        #: "ready" (routable), "draining" (finishing in-flight work —
        #: not ready), "stopped" (terminal, post-close).  Liveness
        #: (/healthz) is unaffected by any of it.  The machine is
        #: declared in analysis/protocols.py ("replica"); every change
        #: goes through :meth:`set_state`, which journals it.
        self._state = "ready"
        #: stable per-instance key the lifecycle journal events carry
        #: (the conformance checker tracks one machine per replica)
        self._replica_key = f"pid{os.getpid()}-svc{next(_SERVICE_SEQ)}"
        #: (model, bucket_rows, features, dtype) per coalesced-batch
        #: shape this service has dispatched — the pre-warm manifest a
        #: fresh replica replays to reach hit rate 1.0 before its first
        #: request (export_prewarm_manifest/prewarm)
        self._seen_shapes: set = set()
        self._lock = _tsan.register_lock("serving.service")
        #: the canary decision plane: shadow-mirrors a fraction of every
        #: coalesced batch to the loaded canary version (registry
        #: ``load(activate=False)``), compares online, auto-promotes /
        #: auto-rolls-back — see serving/canary.py and /canaryz
        self.canary = _canary.CanaryController(self)
        # roofline join: with the observatory armed, every predict
        # bucket's compile records its XLA flops/bytes so /rooflinez can
        # pair them with measured time.  Serving compiles are bounded
        # (one per (model, bucket)), so the per-miss accounting cost is
        # a warmup-only tax; processes with the observatory disabled
        # keep cost accounting at its knob default.
        from ..core import dispatch as _dispatch
        from ..telemetry import observatory as _observatory

        if _observatory.armed() and not _dispatch.cost_accounting_enabled():
            _dispatch.set_cost_accounting(True)

    # -- model lifecycle (thin registry delegates) ----------------------
    def load(self, name: str, directory: str, **kwargs) -> int:
        """Hot-load a model version (see :meth:`ModelRegistry.load`)."""
        return self.registry.load(name, directory, **kwargs)

    def load_async(self, name: str, directory: str, **kwargs):
        """Background hot-load (see :meth:`ModelRegistry.load_async`)."""
        return self.registry.load_async(name, directory, **kwargs)

    def set_quota(self, tenant: str, rate: float, burst: Optional[float] = None) -> None:
        self.admission.set_quota(tenant, rate, burst)

    def set_class(self, tenant: str, cls: str) -> None:
        """Pin ``tenant``'s QoS class (``latency``/``standard``/``batch``,
        docs/serving.md "QoS scheduling")."""
        self.admission.set_class(tenant, cls)

    # -- the hot path ---------------------------------------------------
    def _batcher(self, name: str) -> ModelBatcher:
        self.registry.record(name)  # KeyError -> 404 before a thread spawns
        with self._lock:
            _tsan.note_access("serving.service.state")
            if not self._open:
                raise RuntimeError("inference service is closed")
            b = self._batchers.get(name)
            if b is None:
                b = self._batchers[name] = ModelBatcher(
                    name,
                    lambda rows, _n=name: self._infer_batch(_n, rows),
                    max_batch=self.max_batch,
                    max_delay_s=self.max_delay_s,
                    # drift sketches fold each batch's TRUE rows in
                    # after the callers are woken (HEAT_TPU_SKETCH)
                    on_batch=lambda rows, _n=name: _sketch.record_batch(_n, rows),
                    # shadow mirroring to the loaded canary version —
                    # sampling + a bounded enqueue only; the canary
                    # inference runs on the controller's shadow thread
                    on_mirror=lambda rows, out, tid, ms, _n=name: (
                        self.canary.offer(_n, rows, out, tid, ms)
                    ),
                    # per-tenant cost settlement (HEAT_TPU_QOS_METER) —
                    # reads the metered inference cost _infer_batch
                    # parked on this same batcher thread
                    on_account=(
                        (lambda parts, ms, _n=name: self._account_batch(_n, parts, ms))
                        if self._meter
                        else None
                    ),
                )
            return b

    def _infer_batch(self, name: str, rows: np.ndarray) -> np.ndarray:
        """One coalesced inference on the ACTIVE version (batcher thread,
        under the primary request's trace context).  Decomposed into the
        ``dispatch`` stage (DNDarray wrap + program dispatch — any
        compile span nests here and inherits the trace) and the
        ``execute`` stage (forcing the result: device compute + fetch)."""
        from contextlib import nullcontext

        from ..core import dispatch as _dispatch
        from ..core import factories

        est = self.registry.get(name)
        with self._lock:
            _tsan.note_access("serving.service.state")
            self._seen_shapes.add(
                (name, int(rows.shape[0]), int(rows.shape[1]), str(rows.dtype))
            )
        tid = _tracing.current_trace_id()
        td0 = time.perf_counter_ns()
        # cost metering scope: every dispatch of this batch's inference
        # adds its analyzed FLOPs/bytes to the meter; _account_batch
        # (same batcher thread, right after the callers wake) splits it
        # across the batch's tenants
        with (_dispatch.meter_costs() if self._meter else nullcontext(None)) as meter:
            t0 = time.perf_counter_ns()
            # the ambient trace context is live here, so a cold bucket's
            # dispatch.compile span inherits the request that paid for it
            x = factories.array(rows, split=self.split, comm=self.registry.comm)
            y = _infer(est, x)
            t1 = time.perf_counter_ns()
            _stage_note("serve.dispatch", t0, t1 - t0, model=name, rows=int(rows.shape[0]))
            observe_stage("dispatch", (t1 - t0) / 1e6, tid)
            t0 = time.perf_counter_ns()
            out = y.numpy()
            t1 = time.perf_counter_ns()
            _stage_note("serve.execute", t0, t1 - t0, model=name)
            observe_stage("execute", (t1 - t0) / 1e6, tid)
        if meter is not None:
            self._infer_cost.last = (
                meter.flops,
                meter.bytes_accessed,
                (time.perf_counter_ns() - td0) / 1e6,
            )
        return out

    def _account_batch(self, name: str, parts, infer_ms: float) -> None:
        """Settle one coalesced batch into the tenant ledger (/tenantz):
        the metered cost _infer_batch parked on this thread, split pro
        rata by rows.  Batcher-thread hook — never a caller's latency."""
        from ..telemetry import tenants as _tenants

        cost = getattr(self._infer_cost, "last", None)
        self._infer_cost.last = None
        flops, bytes_accessed, device_ms = cost if cost else (0.0, 0.0, float(infer_ms))
        _tenants.note_batch(
            name, parts, flops=flops, bytes_accessed=bytes_accessed,
            device_ms=device_ms,
        )

    def predict(
        self,
        name: str,
        rows,
        tenant: str = "default",
        timeout: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> np.ndarray:
        """Predict ``rows`` (one (n, features) request) on model
        ``name``; blocks until the coalesced batch answers.

        ``deadline_s`` is an explicit coalescing deadline budget
        (seconds from now; default: the tenant's class budget,
        ``HEAT_TPU_QOS_DEADLINE_*_MS``).  Raises
        :class:`OverloadedError` when shed, ``KeyError`` for an unknown
        model, the batch's error when its dispatch failed."""
        out, _info = self._predict(
            name, rows, tenant=tenant, timeout=timeout, deadline_s=deadline_s
        )
        return out

    def _predict(
        self,
        name: str,
        rows,
        tenant: str = "default",
        timeout: Optional[float] = None,
        trace_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ):
        """The traced predict path: returns ``(out, info)`` where
        ``info`` carries the request's ``trace_id`` and its measured
        ``latency_ms`` — the ONE timing source both the
        ``serving.latency_ms`` histogram and the HTTP response report
        (the route must never re-time the request independently).

        ``trace_id`` adopts an inbound id (the fleet router stamps its
        own into the forwarded body), so one routed request's spans
        stitch across router and replica by the existing trace_id
        merge."""
        rows = np.asarray(rows)
        if rows.ndim == 1:
            rows = rows[None, :]
        _inject("serve.predict", model=name, rows=int(rows.shape[0]))
        n = int(rows.shape[0])
        req = _tracing.request_span(
            f"/v1/predict/{name}", trace_id=trace_id, model=name, tenant=tenant, rows=n
        )
        with req:
            t0 = time.perf_counter_ns()
            try:
                cls = self.admission.admit(tenant, n)
            finally:
                t1 = time.perf_counter_ns()
                _stage_note(
                    "serve.admission", t0, t1 - t0, tenant=tenant, rows=n
                )
            observe_stage("admission", (t1 - t0) / 1e6, req.trace_id)
            try:
                out = self._batcher(name).submit(
                    rows, timeout=timeout, tenant=tenant, cls=cls,
                    deadline_s=deadline_s,
                )
            finally:
                self.admission.release(n, cls)
        _LATENCY_H.observe(
            req.duration_ms,
            exemplar=req.trace_id
            if (req.trace_id and _tracing.exemplars_enabled())
            else None,
        )
        return out, {"trace_id": req.trace_id, "latency_ms": req.duration_ms}

    # -- lifecycle state + readiness ------------------------------------
    _STATES = ("warming", "ready", "draining", "stopped")

    @property
    def state(self) -> str:
        """Lifecycle state: "warming" / "ready" / "draining" /
        "stopped"."""
        with self._lock:
            _tsan.note_access("serving.service.state", write=False)
            return self._state

    def set_state(self, state: str) -> str:
        """Set the lifecycle state (readiness flips with it); returns
        the previous state.  The registered transition helper of the
        ``replica`` protocol: every actual change is journaled (actor
        ``replica``) after the lock is released, keyed by this
        instance's replica key."""
        if state not in self._STATES:
            raise ValueError(
                f"unknown service state {state!r}; expected one of {self._STATES}"
            )
        with self._lock:
            _tsan.note_access("serving.service.state")
            prev, self._state = self._state, state
        if prev != state:
            _journal.emit(
                ACTOR_REPLICA, _STATE_ACTIONS[state],
                severity="info",
                message=f"replica lifecycle: {prev} -> {state}",
                evidence={"replica": self._replica_key, "prev": prev},
            )
        return prev

    def readiness(self):
        """``(ready, doc)`` for the introspection server's ``/readyz``:
        ready iff the service is in state "ready".  The doc carries the
        state, the loaded model names (the router's placement map), the
        queue/in-flight picture, and the dispatch-cache counters at
        scrape time (the cold-start gate reads the miss count at
        ready-time from here)."""
        from ..core import aot_cache as _aot
        from ..core import dispatch as _dispatch

        with self._lock:
            _tsan.note_access("serving.service.state", write=False)
            state = self._state
            batchers = list(self._batchers.values())
        stats = _dispatch.cache_stats()
        doc: Dict[str, Any] = {
            "ready": state == "ready",
            "state": state,
            "models": self.registry.model_names(),
            "queued_rows": sum(b.queued_rows() for b in batchers),
            "admitted_rows_in_flight": self.admission.depth(),
            "dispatch": {
                "misses": stats["misses"],
                "hits": stats["hits"],
                "hit_rate": stats["hit_rate"],
            },
            "aot": {
                k: v for k, v in _aot.stats().items() if k in ("hits", "saves", "errors")
            },
        }
        return doc["ready"], doc

    # -- pre-warm manifest ----------------------------------------------
    def export_prewarm_manifest(self, path: Optional[str] = None) -> Dict[str, Any]:
        """The (model, bucket, features, dtype) shapes this live service
        has dispatched, as a manifest document a fresh replica replays
        before taking traffic.  ``path`` writes it atomically with a
        CRC32 sidecar like every other artifact."""
        with self._lock:
            _tsan.note_access("serving.service.state", write=False)
            shapes = sorted(self._seen_shapes)
        doc = {
            "version": 1,
            "exported_at": time.time(),
            "entries": [
                {"model": m, "bucket": b, "features": f, "dtype": dt}
                for (m, b, f, dt) in shapes
            ],
        }
        if path is not None:
            from ..resilience.atomic import atomic_write

            with atomic_write(path, fault_site="io.write") as tmp:
                with open(tmp, "w") as fh:
                    json.dump(doc, fh, indent=1, sort_keys=True)
        return doc

    @staticmethod
    def load_prewarm_manifest(path: str) -> Dict[str, Any]:
        """Read (and checksum-verify) a manifest written by
        :meth:`export_prewarm_manifest`."""
        from ..resilience.atomic import verify_checksum

        verify_checksum(path)
        with open(path) as fh:
            return json.load(fh)

    def prewarm(
        self,
        manifest: Optional[Dict[str, Any]] = None,
        path: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Drive one synthetic coalesced batch per manifest entry so
        every (model, bucket) executable is resident — loaded from the
        AOT cache when armed, compiled otherwise — BEFORE the first real
        request.  Entries naming models this service has not loaded are
        skipped (counted).  Returns ``{"warmed", "skipped",
        "new_compiles", "aot_hits"}`` where ``new_compiles`` is actual
        compiles (in-memory misses minus AOT artifact loads): with a
        populated AOT cache it is 0 — the cold-start elimination the
        fleet gate enforces."""
        from ..core import aot_cache as _aot
        from ..core import dispatch as _dispatch

        if manifest is None:
            if path is None:
                raise ValueError("prewarm needs a manifest document or a path")
            manifest = self.load_prewarm_manifest(path)
        s0 = _dispatch.cache_stats()
        a0 = _aot.stats()
        warmed = skipped = 0
        for entry in manifest.get("entries", ()):
            name = str(entry["model"])
            try:
                self.registry.record(name)
            except KeyError:
                skipped += 1
                continue
            rows = np.zeros(
                (int(entry["bucket"]), int(entry["features"])),
                dtype=np.dtype(str(entry.get("dtype", "float32"))),
            )
            self._batcher(name)  # the batcher thread exists before traffic
            self._infer_batch(name, rows)  # the exact coalesced-batch program
            warmed += 1
        s1 = _dispatch.cache_stats()
        a1 = _aot.stats()
        aot_hits = a1["hits"] - a0["hits"]
        return {
            "warmed": warmed,
            "skipped": skipped,
            "new_compiles": (s1["misses"] - s0["misses"]) - aot_hits,
            "aot_hits": aot_hits,
        }

    # -- graceful drain -------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: flip to "draining" (readiness goes 503 so
        a router stops sending new work), keep serving until every
        admitted row is answered and every queue is empty (bounded by
        ``timeout``, default ``HEAT_TPU_FLEET_DRAIN_TIMEOUT_S``), then
        :meth:`close`.  Returns True when the drain completed with zero
        abandoned requests.  The SIGTERM path of a fleet replica."""
        if timeout is None:
            timeout = _env().env_float("HEAT_TPU_FLEET_DRAIN_TIMEOUT_S")
        self.set_state("draining")
        deadline = time.monotonic() + max(float(timeout), 0.0)
        drained = False
        while True:
            with self._lock:
                _tsan.note_access("serving.service.state", write=False)
                batchers = list(self._batchers.values())
            if self.admission.depth() == 0 and all(
                b.queued_rows() == 0 for b in batchers
            ):
                drained = True
                break
            if time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        self.close()
        return drained

    # -- per-model health ----------------------------------------------
    def model_health(self, name: str) -> Dict[str, Any]:
        """``(healthy, doc)`` folded into one doc with a ``healthy``
        key: loaded version, batcher liveness, queue depth, last-batch
        timestamp + trace_id — enough for an operator to tell "idle"
        (no queue, old batch) from "stuck" (deep queue, old batch) and
        to jump from a stuck model straight to its last served trace in
        ``/tracez``, without scraping ``/varz``."""
        rec = self.registry.record(name)  # KeyError -> 404 upstream
        with self._lock:
            _tsan.note_access("serving.service.state", write=False)
            b = self._batchers.get(name)
        now = time.time()
        doc: Dict[str, Any] = {
            "model": name,
            "status": "ok",
            "healthy": True,
            "version": rec["version"],
            "kind": rec["kind"],
            "loaded_age_s": round(now - rec["loaded_at"], 3),
            "world_size_written": rec["world_size_written"],
            "world_size_serving": rec["world_size_serving"],
            "queued_rows": b.queued_rows() if b is not None else 0,
            "admitted_rows_in_flight": self.admission.depth(),
            "last_batch_ts": (
                b.last_batch_ts if b is not None and b.last_batch_ts > 0 else None
            ),
            "last_batch_age_s": (
                round(now - b.last_batch_ts, 3)
                if b is not None and b.last_batch_ts > 0
                else None
            ),
            "last_batch_trace_id": b.last_batch_trace_id if b is not None else None,
        }
        # per-lane picture: queued rows + oldest-waiting-age from this
        # model's coalescer joined with the service-wide admission lane
        # depths/limits — "latency stuck behind batch" is diagnosable
        # from this route alone, no /varz scrape needed
        queue_lanes = (
            b.lane_depths()
            if b is not None
            else {c: {"queued_rows": 0, "oldest_wait_s": 0.0} for c in QOS_CLASSES}
        )
        adm_lanes = self.admission.lane_depths()
        doc["lanes"] = {
            c: {
                "queued_rows": queue_lanes[c]["queued_rows"],
                "oldest_wait_s": queue_lanes[c]["oldest_wait_s"],
                "admitted_rows_in_flight": adm_lanes[c]["depth"],
                "depth_limit": adm_lanes[c]["limit"],
            }
            for c in QOS_CLASSES
        }
        if b is None:
            doc["status"] = "idle"  # loaded, no traffic yet — healthy
        elif not b.alive():
            doc["status"] = "dead"
            doc["healthy"] = False
        # lifecycle state rides along so "idle" (no traffic yet) and
        # "warming" (pre-warm still running) are distinguishable, and a
        # draining replica's models say so; liveness is unaffected —
        # readiness is /readyz's verdict, not this route's
        state = self.state
        doc["state"] = state
        if state != "ready" and doc["status"] in ("ok", "idle"):
            doc["status"] = state
        # quality signals: the model's drift score and any alert that
        # names it — liveness (healthy/503) is unaffected, but the
        # status string flips so a canary driver or operator sees a
        # drifting model without scraping /driftz
        drift = _sketch.SKETCHES.status(name)
        doc["drift"] = {
            "score": drift["score"],
            "drifting": drift["drifting"],
            "threshold": drift["threshold"],
            "baseline": drift["baseline"],
            "sketched_rows": drift["sketched_rows"],
        }
        doc["alerts"] = [
            a for a in _alerts.active_alerts()
            if a["labels"].get("model") == name or a["name"] == f"drift:{name}"
        ]
        if drift["drifting"] and doc["status"] in ("ok", "idle"):
            doc["status"] = "drifting"
        # canary state rides along so an operator sees "a canary is
        # under evaluation / its last verdict" without scraping /canaryz
        cstate = _canary.status(name)
        doc["canary_version"] = self.registry.canary_version(name)
        doc["shadow_sampled_rows"] = cstate["rows"] if cstate else 0
        doc["last_canary_verdict"] = (
            (cstate.get("decision") or {}).get("verdict") or cstate.get("verdict")
            if cstate else None
        )
        return doc

    def freeze_baseline(self, name: str) -> Dict[str, Any]:
        """Freeze the model's live input sketch as its drift baseline
        (runtime capture — e.g. right after warm-up traffic known to be
        in-distribution); returns the baseline document, which
        :func:`~heat_tpu.serving.model_io.save_model` can persist with
        the next version."""
        self.registry.record(name)  # KeyError -> 404 upstream
        return _sketch.SKETCHES.freeze_baseline(name)

    # -- HTTP -----------------------------------------------------------
    def serve(self, port: Optional[int] = None) -> str:
        """Mount the /v1 routes on the introspection server (starting it
        if needed), install the default serving SLOs, and start the
        burn-rate monitor tick (``HEAT_TPU_SLO_TICK_S``; unset/0 falls
        back to 1 s for a serving process — a fleet replica must page
        itself without configuration); returns the server URL."""
        srv = _tserver.start_server(port)
        _tserver.register_route(ROUTE_PREFIX, self._handle_http)
        # readiness (/readyz) now reflects THIS service's lifecycle
        # state — a fleet router keys routing off it (docs/fleet.md)
        _tserver.set_readiness(self.readiness)
        _slo.install_default_slos()
        tick = _env().env_float("HEAT_TPU_SLO_TICK_S")
        self._started_monitor = _slo.start_monitor(tick if tick > 0 else 1.0)
        return srv.url

    def _handle_http(self, method: str, path: str, body: Optional[bytes]):
        try:
            if method == "GET" and path == "/v1/models":
                return 200, "application/json", json.dumps(
                    {"models": self.registry.models()}, indent=1, default=str
                )
            if method == "GET" and path.startswith("/v1/models/") and path.endswith("/healthz"):
                name = path[len("/v1/models/") : -len("/healthz")].strip("/")
                doc = self.model_health(name)
                return (
                    200 if doc["healthy"] else 503,
                    "application/json",
                    json.dumps(doc, indent=1, default=str),
                )
            if method == "POST" and path == "/v1/predict":
                return self._handle_predict(body)
            return 404, "text/plain", f"unknown serving route {path!r}\n"
        except KeyError as e:
            return 404, "application/json", json.dumps({"error": str(e)})
        except OverloadedError as e:
            headers = {}
            if e.retry_after_s is not None:
                headers["Retry-After"] = f"{max(e.retry_after_s, 0.001):.3f}"
            return (
                429,
                "application/json",
                json.dumps(
                    {"error": str(e), "cause": e.cause, "tenant": e.tenant,
                     "retry_after_s": e.retry_after_s}
                ),
                headers,
            )
        except (ValueError, TypeError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}
            )

    def _handle_predict(self, body: Optional[bytes]):
        try:
            doc = json.loads(body or b"")
        except ValueError:
            return 400, "application/json", json.dumps(
                {"error": "request body must be a JSON object"}
            )
        if not isinstance(doc, dict) or "model" not in doc or "inputs" not in doc:
            return 400, "application/json", json.dumps(
                {"error": 'POST /v1/predict needs {"model": name, "inputs": [[...], ...]}'}
            )
        name = doc["model"]
        rows = np.asarray(doc["inputs"], dtype=np.float32)
        tenant = str(doc.get("tenant", "default"))
        # explicit coalescing deadline: the ``deadline_ms`` body field
        # wins over the ``X-Heat-Deadline-Ms`` header (the body rides
        # through the fleet router's proxy verbatim; the header works at
        # the replica surface)
        deadline_ms = doc.get("deadline_ms")
        if deadline_ms is None:
            deadline_ms = _tserver.request_headers().get("x-heat-deadline-ms")
        try:
            deadline_s = float(deadline_ms) / 1e3 if deadline_ms is not None else None
        except (TypeError, ValueError):
            return 400, "application/json", json.dumps(
                {"error": f"deadline_ms must be a number, got {deadline_ms!r}"}
            )
        # one timing source: the latency (and trace id) the response
        # reports IS the measurement serving.latency_ms observed — the
        # route never re-times the request independently
        trace_id = doc.get("trace_id")
        out, info = self._predict(
            name, rows, tenant=tenant, timeout=doc.get("timeout"),
            trace_id=str(trace_id) if trace_id else None,
            deadline_s=deadline_s,
        )
        version = self.registry.active_version(name)
        return 200, "application/json", json.dumps(
            {
                "model": name,
                "version": version,
                "n": int(np.asarray(out).shape[0]),
                "predictions": np.asarray(out).tolist(),
                "latency_ms": round(info["latency_ms"], 3),
                "trace_id": info["trace_id"],
            }
        )

    # -- shutdown -------------------------------------------------------
    def close(self) -> None:
        """Unmount the routes, drain and join every batcher, drain the
        registry's background loader.  Idempotent."""
        self.set_state("stopped")  # terminal lifecycle transition (journaled once)
        _tserver.unregister_route(ROUTE_PREFIX)
        _tserver.clear_readiness(self.readiness)
        if self._started_monitor:
            self._started_monitor = False
            _slo.stop_monitor()
        with self._lock:
            _tsan.note_access("serving.service.state")
            self._open = False
            batchers, self._batchers = dict(self._batchers), {}
        for b in batchers.values():
            b.close()
        self.canary.close()
        self.registry.close()

    def __enter__(self) -> "InferenceService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


# ----------------------------------------------------------------------
# process-default service (the HTTP deployment shape: one process, one
# registry, one port)
# ----------------------------------------------------------------------
_SERVICE: Optional[InferenceService] = None
_SERVICE_LOCK = _tsan.register_lock("serving.service")


def default_service(**kwargs) -> InferenceService:
    """Get-or-create the process's default :class:`InferenceService`
    (kwargs apply only on creation)."""
    global _SERVICE
    with _SERVICE_LOCK:
        _tsan.note_access("serving.service.state")
        if _SERVICE is None:
            _SERVICE = InferenceService(**kwargs)
        return _SERVICE


def start_serving(port: Optional[int] = None, **kwargs) -> InferenceService:
    """Start the default service and mount its HTTP routes; returns the
    service (its URL comes from ``telemetry.server``)."""
    svc = default_service(**kwargs)
    svc.serve(port)
    return svc


def stop_serving() -> None:
    """Close and drop the default service (no-op when none is running)."""
    global _SERVICE
    with _SERVICE_LOCK:
        _tsan.note_access("serving.service.state")
        svc, _SERVICE = _SERVICE, None
    if svc is not None:
        svc.close()
