"""Control-plane decision journal tests (ISSUE 19 tentpole).

The contract under test (docs/observability.md):

* every ``emit`` lands a typed DecisionEvent in the bounded hot ring
  (monotonic + wall timestamps, actor/action, optional cause link and
  exemplar trace_id, JSON-safe evidence);
* ``causal_chain`` walks one decision back to its root and forward to
  its transitive effects, terminating on cycles and dangling causes;
* arming ``HEAT_TPU_JOURNAL_DIR`` makes every event durable as an
  atomic single-event segment with a CRC32 sidecar; ``read_journal``
  verifies, orders and deduplicates; a corrupted segment is detected;
* ``/decisionz`` serves the timeline as HTML and JSON and explains one
  event's causal chain; per-worker snapshots merge deterministically;
* the offline twin ``python -m heat_tpu.telemetry.replay`` rebuilds
  the incident timeline from the durable directory alone — no live
  process required;
* forced incident: a degraded canary under 4-thread live load rolls
  back with the full ``drift evidence -> rollback -> page alert +
  flight-recorder bundle`` chain on ``/decisionz``, every link carrying
  an exemplar trace_id and evidence series resolvable via ``/queryz``,
  and the replay CLI (a fresh process — the "after kill+restart" leg)
  reconstructs the same chain from the durable journal.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.resilience.atomic import ChecksumError
from heat_tpu.serving import canary as cn
from heat_tpu.serving import model_io
from heat_tpu.telemetry import aggregate
from heat_tpu.telemetry import alerts as talerts
from heat_tpu.telemetry import flight_recorder
from heat_tpu.telemetry import journal as tjournal
from heat_tpu.telemetry import replay as treplay
from heat_tpu.telemetry import server as tserver
from heat_tpu.telemetry import tsdb as ttsdb

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RNG = np.random.default_rng(7)
PTS = RNG.standard_normal((160, 6)).astype(np.float32)


@pytest.fixture(autouse=True)
def _clean_state():
    tjournal.set_journal_dir(None)
    tjournal.reset_journal()
    talerts.clear_alerts()
    ttsdb.reset_tsdb()
    yield
    tjournal.set_journal_dir(None)
    tjournal.reset_journal()
    talerts.clear_alerts()
    ttsdb.reset_tsdb()
    cn.reset_canary_state()


@pytest.fixture
def live_server():
    srv = tserver.start_server(0)
    yield srv
    tserver.stop_server()


def _get(srv, route):
    import urllib.request

    with urllib.request.urlopen(f"{srv.url}{route}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def _get_json(srv, route):
    status, _ctype, body = _get(srv, route)
    assert status == 200
    return json.loads(body)


# ----------------------------------------------------------------------
# the hot ring
# ----------------------------------------------------------------------
class TestEmit:
    def test_emit_returns_typed_doc(self):
        before = time.time()
        ev = tjournal.emit(
            "autoscaler", "spawn", model="km", tenant="acme",
            severity="warn", message="scale-up", trace_id="t-123",
            evidence={"p99_ms": 80.0},
        )
        assert ev["actor"] == "autoscaler" and ev["action"] == "spawn"
        assert ev["model"] == "km" and ev["tenant"] == "acme"
        assert ev["severity"] == "warn" and ev["message"] == "scale-up"
        assert ev["trace_id"] == "t-123"
        assert ev["evidence"] == {"p99_ms": 80.0}
        assert ev["cause"] is None
        assert before <= ev["ts"] <= time.time()
        assert isinstance(ev["mono"], float)
        assert ev["event_id"].endswith(f"{ev['seq']:06d}")

    def test_seq_monotonic_and_ids_unique(self):
        docs = [tjournal.emit("a", "act") for _ in range(5)]
        seqs = [d["seq"] for d in docs]
        assert seqs == sorted(seqs) and len(set(seqs)) == 5
        assert len({d["event_id"] for d in docs}) == 5

    def test_journal_events_oldest_first_with_limit(self):
        for i in range(6):
            tjournal.emit("a", f"act{i}")
        events = tjournal.journal_events()
        assert [e["action"] for e in events] == [f"act{i}" for i in range(6)]
        assert [e["action"] for e in tjournal.journal_events(limit=2)] == [
            "act4", "act5",
        ]

    def test_get_event_and_find_last(self):
        tjournal.emit("canary", "promoted", model="km")
        mid = tjournal.emit("canary", "rolled_back", model="lr")
        tjournal.emit("alerts", "fire", model="lr")
        assert tjournal.get_event(mid["event_id"])["action"] == "rolled_back"
        assert tjournal.get_event("nope") is None
        assert tjournal.find_last(actor="canary")["action"] == "rolled_back"
        assert tjournal.find_last(actor="canary", model="km")["action"] == "promoted"
        assert tjournal.find_last(actor="canary", action="vetoed") is None

    def test_ring_bound_env_keeps_newest(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_JOURNAL_RING", "4")
        tjournal.refresh_env()
        try:
            for i in range(10):
                tjournal.emit("a", f"act{i}")
            events = tjournal.journal_events()
            assert [e["action"] for e in events] == [
                "act6", "act7", "act8", "act9",
            ]
        finally:
            monkeypatch.undo()
            tjournal.refresh_env()

    def test_evidence_is_copied_not_aliased(self):
        evidence = {"k": 1}
        ev = tjournal.emit("a", "act", evidence=evidence)
        evidence["k"] = 2
        assert tjournal.get_event(ev["event_id"])["evidence"] == {"k": 1}


# ----------------------------------------------------------------------
# causal chains
# ----------------------------------------------------------------------
class TestCausalChain:
    def test_chain_root_first_and_transitive_effects(self):
        root = tjournal.emit("alerts", "fire", message="drift")
        mid = tjournal.emit("canary", "rolled_back", cause=root["event_id"])
        eff1 = tjournal.emit("alerts", "fire", cause=mid["event_id"])
        eff2 = tjournal.emit(
            "flight_recorder", "bundle", cause=mid["event_id"]
        )
        grand = tjournal.emit("alerts", "resolve", cause=eff1["event_id"])
        doc = tjournal.causal_chain(mid["event_id"])
        assert doc["found"]
        assert [e["event_id"] for e in doc["chain"]] == [
            root["event_id"], mid["event_id"],
        ]
        assert [e["event_id"] for e in doc["effects"]] == [
            eff1["event_id"], eff2["event_id"], grand["event_id"],
        ]

    def test_unknown_event(self):
        doc = tjournal.causal_chain("missing")
        assert doc == {
            "event_id": "missing", "found": False, "chain": [], "effects": [],
        }

    def test_dangling_cause_terminates(self):
        ev = tjournal.emit("a", "act", cause="gone-from-ring")
        doc = tjournal.causal_chain(ev["event_id"])
        assert [e["event_id"] for e in doc["chain"]] == [ev["event_id"]]

    def test_cycle_terminates(self):
        pool = [
            {"event_id": "a", "cause": "b", "ts": 1.0},
            {"event_id": "b", "cause": "a", "ts": 2.0},
        ]
        doc = tjournal.causal_chain("a", events=pool)
        assert doc["found"]
        assert [e["event_id"] for e in doc["chain"]] == ["b", "a"]
        # "b" is already on the chain, so the effects walk must not loop
        assert doc["effects"] == []


# ----------------------------------------------------------------------
# the durable log
# ----------------------------------------------------------------------
class TestDurable:
    def test_hot_ring_only_without_dir(self, tmp_path):
        tjournal.emit("a", "act")
        assert tjournal.journal_dir() is None
        assert tjournal.read_journal(str(tmp_path)) == []

    def test_segments_with_crc_sidecars(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        assert tjournal.journal_dir() == d
        docs = [tjournal.emit("a", f"act{i}") for i in range(3)]
        segs = sorted(n for n in os.listdir(d) if n.endswith(".jsonl"))
        assert len(segs) == 3
        for seg in segs:
            assert os.path.exists(os.path.join(d, seg + ".crc32"))
        back = tjournal.read_journal(d)
        assert [e["event_id"] for e in back] == [e["event_id"] for e in docs]
        assert back[0]["evidence"] == {}

    def test_restart_resumes_segment_numbering_and_dedups(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        for _ in range(3):
            tjournal.emit("a", "before")
        # simulated restart: the ring dies, the durable cursor re-scans
        tjournal.reset_journal()
        tjournal.set_journal_dir(d)
        for _ in range(2):
            tjournal.emit("a", "after")
        segs = sorted(n for n in os.listdir(d) if n.endswith(".jsonl"))
        assert len(segs) == 5
        starts = [int(n.split("-")[1]) for n in segs]
        assert starts == [0, 1, 2, 3, 4]
        # the restarted process reuses seq 1..2 under the same epoch, so
        # the reader's event_id dedup collapses them — the committed
        # record is never double-counted
        back = tjournal.read_journal(d)
        assert len(back) == len({e["event_id"] for e in back})

    def test_corrupt_segment_detected(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        tjournal.emit("a", "act")
        seg = [n for n in os.listdir(d) if n.endswith(".jsonl")][0]
        with open(os.path.join(d, seg), "a") as f:
            f.write('{"event_id": "forged"}\n')
        with pytest.raises(ChecksumError):
            tjournal.read_journal(d)

    def test_env_arming(self, tmp_path, monkeypatch):
        d = str(tmp_path / "journal")
        monkeypatch.setenv("HEAT_TPU_JOURNAL_DIR", d)
        tjournal.refresh_env()
        try:
            assert tjournal.journal_dir() == d
            tjournal.emit("a", "act")
            assert len(tjournal.read_journal(d)) == 1
        finally:
            monkeypatch.undo()
            tjournal.refresh_env()
        assert tjournal.journal_dir() is None


# ----------------------------------------------------------------------
# reports, snapshots, fleet merge
# ----------------------------------------------------------------------
class TestReportsAndMerge:
    def test_decisionz_report_shape(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        tjournal.emit("a", "act")
        doc = tjournal.decisionz_report()
        assert doc["dir"] == d and doc["ring"] >= 1
        assert len(doc["events"]) == 1
        assert json.loads(json.dumps(doc))  # JSON-safe end to end

    def test_journal_snapshot_limit(self):
        for i in range(5):
            tjournal.emit("a", f"act{i}")
        snap = tjournal.journal_snapshot(limit=2)
        assert [e["action"] for e in snap["events"]] == ["act3", "act4"]

    def test_merge_interleaves_by_ts_then_worker(self):
        snap0 = {"events": [
            {"event_id": "x", "actor": "canary", "ts": 2.0},
            {"event_id": "y", "actor": "alerts", "ts": 4.0},
        ]}
        snap1 = {"events": [
            {"event_id": "z", "actor": "canary", "ts": 3.0},
        ]}
        merged = tjournal.merge_journal_snapshots([("1", snap1), ("0", snap0)])
        assert merged["event_count"] == 3
        assert [(e["event_id"], e["worker"]) for e in merged["events"]] == [
            ("x", "0"), ("z", "1"), ("y", "0"),
        ]
        assert merged["actors"] == {"alerts": 1, "canary": 2}

    def test_merge_tolerates_missing_snapshots(self):
        merged = tjournal.merge_journal_snapshots([("0", None), ("1", {})])
        assert merged == {"events": [], "event_count": 0, "actors": {}}

    def test_aggregate_snapshot_carries_journal(self):
        tjournal.emit("canary", "rolled_back", model="km")
        snap = aggregate.tag_snapshot()
        assert snap["journal"]["events"][-1]["action"] == "rolled_back"
        merged = aggregate.merge_snapshots([snap], publish=False)
        events = merged["journal"]["events"]
        assert events[-1]["action"] == "rolled_back"
        assert events[-1]["worker"] == str(int(snap["process_index"]))


# ----------------------------------------------------------------------
# /decisionz
# ----------------------------------------------------------------------
class TestDecisionzEndpoint:
    def test_html_and_json_timeline(self, live_server):
        root = tjournal.emit("alerts", "fire", message="drift high")
        tjournal.emit(
            "canary", "rolled_back", model="km", severity="page",
            message="canary v3 FAILED", cause=root["event_id"],
        )
        status, ctype, body = _get(live_server, "/decisionz")
        assert status == 200 and "text/html" in ctype
        assert "rolled_back" in body and "drift high" in body
        doc = _get_json(live_server, "/decisionz?format=json")
        assert [e["action"] for e in doc["events"]] == ["fire", "rolled_back"]
        limited = _get_json(live_server, "/decisionz?format=json&limit=1")
        assert [e["action"] for e in limited["events"]] == ["rolled_back"]

    def test_event_id_explains_chain(self, live_server):
        root = tjournal.emit("alerts", "fire", message="drift high")
        mid = tjournal.emit("canary", "rolled_back", cause=root["event_id"])
        eff = tjournal.emit("alerts", "fire", cause=mid["event_id"])
        doc = _get_json(
            live_server, f"/decisionz?format=json&event_id={mid['event_id']}"
        )
        assert doc["found"]
        assert [e["event_id"] for e in doc["chain"]] == [
            root["event_id"], mid["event_id"],
        ]
        assert [e["event_id"] for e in doc["effects"]] == [eff["event_id"]]
        status, ctype, body = _get(
            live_server, f"/decisionz?event_id={mid['event_id']}"
        )
        assert status == 200 and "text/html" in ctype
        assert mid["event_id"] in body and root["event_id"] in body


# ----------------------------------------------------------------------
# offline replay
# ----------------------------------------------------------------------
class TestReplay:
    def _seed_incident(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        root = tjournal.emit("alerts", "fire", message="drift:km fired",
                             evidence={"alert": "drift:km"})
        mid = tjournal.emit("canary", "rolled_back", model="km",
                            severity="page", cause=root["event_id"],
                            trace_id="t-9")
        eff = tjournal.emit("flight_recorder", "bundle",
                            cause=mid["event_id"])
        return d, root, mid, eff

    def test_replay_report_pure(self, tmp_path):
        d, root, mid, eff = self._seed_incident(tmp_path)
        doc = treplay.replay_report(d, event_id=mid["event_id"])
        assert doc["event_count"] == 3
        assert doc["actors"] == {
            "alerts": 1, "canary": 1, "flight_recorder": 1,
        }
        assert doc["roots"] == [root["event_id"]]
        assert [e["event_id"] for e in doc["explain"]["chain"]] == [
            root["event_id"], mid["event_id"],
        ]
        text = treplay.format_replay(doc)
        assert "causal chain" in text and "exemplar trace_id=t-9" in text

    def test_cli_timeline_and_explain(self, tmp_path):
        d, root, mid, _eff = self._seed_incident(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.replay", d],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        )
        assert out.returncode == 0, out.stderr
        assert "3 event(s)" in out.stdout and "canary/rolled_back" in out.stdout
        out = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.replay", d,
             "--event-id", mid["event_id"], "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        )
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert [e["event_id"] for e in doc["explain"]["chain"]] == [
            root["event_id"], mid["event_id"],
        ]

    def test_cli_empty_dir_exits_nonzero(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.replay",
             str(tmp_path)],
            capture_output=True, text=True, cwd=REPO_ROOT,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert out.returncode == 1
        assert "0 event(s)" in out.stdout


# ----------------------------------------------------------------------
# the forced incident (acceptance e2e)
# ----------------------------------------------------------------------
def _fit_kmeans():
    x = ht.array(PTS, split=0)
    return ht.cluster.KMeans(
        n_clusters=3, init="random", max_iter=5, random_state=0
    ).fit(x)


def _degrade_kmeans(est):
    bad = model_io.build_estimator(model_io.export_state(est))
    centers = np.asarray(bad._cluster_centers.numpy())
    bad._cluster_centers = ht.array(centers[::-1].copy(), split=None)
    return bad


@pytest.fixture
def model_dir(tmp_path):
    est = _fit_kmeans()
    d = str(tmp_path / "km")
    serving.save_model(est, d, version=1, name="km")
    serving.save_model(_degrade_kmeans(est), d, version=3, name="km")
    return d


class TestForcedIncident:
    def test_degraded_canary_chain_live_and_replayed(
        self, model_dir, live_server, tmp_path
    ):
        jdir = str(tmp_path / "journal")
        tjournal.set_journal_dir(jdir)
        flight_recorder.install(str(tmp_path / "bundles"))
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
        try:
            # the quality signal that provokes the incident: a drift
            # alert for the model, its sample landed in the TSDB first
            # so the journal evidence is resolvable via /queryz
            ttsdb.record("drift.km.psi", 0.41)
            talerts.fire(
                "drift:km", severity="warn", value=0.41, threshold=0.2,
                message="input PSI drift on km",
                labels={"model": "km"},
                evidence={"series": ["drift.km.psi"]},
            )
            drift_ev = tjournal.find_last(actor="alerts", action="fire")
            assert drift_ev is not None

            svc.load("km", model_dir, version=1)
            svc.load("km", model_dir, version=3, activate=False)
            svc.canary.fraction = 1.0
            svc.canary.min_rows = 48

            errors = []

            def client(seed):
                rng = np.random.default_rng(seed)
                for i in range(40):
                    off = int(rng.integers(0, 64))
                    rows = (3, 5, 8, 13)[i % 4]
                    try:
                        svc.predict("km", PTS[off:off + rows])
                    except Exception as e:  # lint: allow H501(the e2e asserts zero client failures of ANY kind)
                        errors.append(e)

            threads = [
                threading.Thread(target=client, args=(s,)) for s in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert svc.canary.wait_idle(60)
            assert not errors

            st = cn.status("km")
            assert st["decision"]["action"] == "rolled_back"

            # -- the live chain: drift fire -> rollback -> page + bundle
            rb = tjournal.find_last(actor="canary", action="rolled_back")
            assert rb is not None and rb["model"] == "km"
            assert rb["trace_id"]
            assert rb["cause"] == drift_ev["event_id"]
            assert rb["evidence"]["mismatch_pct"] is not None
            assert "canary.mismatch_pct" in rb["evidence"]["series"]

            chain = tjournal.causal_chain(rb["event_id"])
            assert [e["event_id"] for e in chain["chain"]] == [
                drift_ev["event_id"], rb["event_id"],
            ]
            by_actor = {
                (e["actor"], e["action"]): e for e in chain["effects"]
            }
            page = by_actor[("alerts", "fire")]
            assert page["severity"] == "page"
            assert page["evidence"]["alert"].startswith("canary:km")
            bundle = by_actor[("flight_recorder", "bundle")]
            assert bundle["trace_id"] == rb["trace_id"]
            assert os.path.exists(bundle["evidence"]["path"])

            # -- every cited series resolves via /queryz
            for series in ("drift.km.psi", "canary.mismatch_pct"):
                doc = _get_json(
                    live_server,
                    f"/queryz?format=json&series={series}&window=600",
                )
                assert doc["series"][series]["stats"]["n"] >= 1

            # -- /decisionz explains the rollback over HTTP
            doc = _get_json(
                live_server,
                f"/decisionz?format=json&event_id={rb['event_id']}",
            )
            assert [e["event_id"] for e in doc["chain"]] == [
                drift_ev["event_id"], rb["event_id"],
            ]
            assert {e["event_id"] for e in doc["effects"]} >= {
                page["event_id"], bundle["event_id"],
            }

            # -- kill+restart leg: a FRESH process reconstructs the same
            # chain from the durable journal directory alone
            out = subprocess.run(
                [sys.executable, "-m", "heat_tpu.telemetry.replay", jdir,
                 "--event-id", rb["event_id"], "--json"],
                capture_output=True, text=True, cwd=REPO_ROOT,
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
            )
            assert out.returncode == 0, out.stderr
            replayed = json.loads(out.stdout)["explain"]
            assert replayed["found"]
            assert [e["event_id"] for e in replayed["chain"]] == [
                drift_ev["event_id"], rb["event_id"],
            ]
            assert {e["event_id"] for e in replayed["effects"]} >= {
                page["event_id"], bundle["event_id"],
            }
            replayed_rb = replayed["chain"][-1]
            assert replayed_rb["trace_id"] == rb["trace_id"]
            assert replayed_rb["evidence"]["mismatch_pct"] == \
                rb["evidence"]["mismatch_pct"]
        finally:
            svc.close()
            flight_recorder.uninstall()
