"""Plot helper for the lasso demo (analog of examples/lasso/plotfkt.py)."""

import numpy as np


def plot_lasso_path(lambdas, theta_lasso, out: str = "lasso_path.png") -> None:
    """Plot each feature's coefficient against the regularization strength."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        from matplotlib import pyplot as plt
    except ImportError:
        print("matplotlib not available; skipping plot")
        return

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for i in range(theta_lasso.shape[0]):
        ax.plot(np.log10(lambdas), theta_lasso[i], label=f"feature {i}")
    ax.set_xlabel(r"$\log_{10}\,\lambda$")
    ax.set_ylabel("coefficient")
    ax.set_title("Lasso regularization path (diabetes)")
    ax.legend(fontsize=7, ncol=2)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"saved {out}")
