"""Functional NN ops, analog of heat/nn/functional.py (falls through to
jax.nn the way the reference falls through to torch.nn.functional via
``func_getattr``, nn/functional.py:9)."""

__all__ = ["func_getattr"]


def func_getattr(name):
    """Resolve ``name`` against the local framework's functional namespace.

    The reference's ``func_getattr`` (nn/functional.py:9) forwards to
    ``torch.nn.functional``; here the substrate is ``jax.nn``.
    """
    import jax.nn as _jnn

    try:
        return getattr(_jnn, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute {name!r}")


def __getattr__(name):
    return func_getattr(name)
