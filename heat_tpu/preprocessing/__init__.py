"""Preprocessing transforms (analog of heat/preprocessing)."""

from .preprocessing import (
    MaxAbsScaler,
    MinMaxScaler,
    Normalizer,
    RobustScaler,
    StandardScaler,
)

__all__ = ["StandardScaler", "MinMaxScaler", "Normalizer", "MaxAbsScaler", "RobustScaler"]
