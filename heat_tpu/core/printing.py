"""Printing, analog of heat/core/printing.py.

The reference gathers data to rank 0 via resplit(None) and prints there
(printing.py:184-287); in single-controller JAX the driver process already
addresses the global array, so printing is a numpy round-trip of the dense
view (or just the edges when summarizing).
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_printoptions", "global_printing", "local_printing", "print0", "printoptions", "set_printoptions", "set_string_function"]

_LOCAL_PRINTING = False

# mirror torch-style defaults used by the reference (printing.py:150)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)


def get_printoptions() -> dict:
    """Current print options (printing.py:16)."""
    return dict(__PRINT_OPTIONS)


def global_printing() -> None:
    """Print global arrays (default; printing.py:66)."""
    global _LOCAL_PRINTING
    _LOCAL_PRINTING = False


def local_printing() -> None:
    """Print only the process-local chunk (printing.py:30)."""
    global _LOCAL_PRINTING
    _LOCAL_PRINTING = True


def print0(*args, **kwargs) -> None:
    """Print once, on the root process only (printing.py:100)."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure formatting (printing.py:150)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=np.inf, edgeitems=3, linewidth=120)
    for k, v in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if v is not None:
            __PRINT_OPTIONS[k] = v
    np.set_printoptions(
        precision=int(__PRINT_OPTIONS["precision"]),
        threshold=__PRINT_OPTIONS["threshold"],
        edgeitems=int(__PRINT_OPTIONS["edgeitems"]),
        linewidth=int(__PRINT_OPTIONS["linewidth"]),
    )


def __str__(dndarray) -> str:
    """Format a DNDarray (printing.py:184).

    Printing is a fusion boundary: a pending elementwise chain behind the
    array compiles and runs as one cached executable on the
    ``larray_padded``/``numpy()`` access below (core/dispatch.py)."""
    if _LOCAL_PRINTING:
        data = np.asarray(dndarray.larray)
        return (
            f"DNDarray(local={data}, device={dndarray.device}, split={dndarray.split})"
        )
    data = dndarray.numpy()
    body = np.array2string(
        data,
        precision=int(__PRINT_OPTIONS["precision"]),
        threshold=__PRINT_OPTIONS["threshold"],
        edgeitems=int(__PRINT_OPTIONS["edgeitems"]),
        separator=", ",
        prefix="DNDarray(",
    )
    return f"DNDarray({body}, dtype=ht.{dndarray.dtype.__name__}, device={dndarray.device}, split={dndarray.split})"


import contextlib


@contextlib.contextmanager
def printoptions(**kwargs):
    """Context manager temporarily applying print options (np.printoptions)."""
    saved = dict(__PRINT_OPTIONS)
    saved_np = np.get_printoptions()  # set_printoptions mirrors into numpy
    try:
        set_printoptions(**kwargs)
        yield get_printoptions()
    finally:
        # restore the raw dict: set_printoptions skips None values, which
        # would leak options whose saved value was None (e.g. sci_mode) —
        # and restore the mirrored numpy globals too, or the temporary
        # threshold/precision would leak into numpy formatting process-wide
        __PRINT_OPTIONS.clear()
        __PRINT_OPTIONS.update(saved)
        np.set_printoptions(**saved_np)


def set_string_function(f, repr: bool = True) -> None:
    """Override DNDarray's __str__/__repr__ rendering (legacy
    np.set_string_function); pass None to restore the default."""
    from .dndarray import DNDarray

    attr = "__repr_override__" if repr else "__str_override__"
    if f is None:
        if hasattr(DNDarray, attr):
            delattr(DNDarray, attr)
    else:
        setattr(DNDarray, attr, staticmethod(f))
