"""Backward-compatible alias of :mod:`heat_tpu.telemetry.profiling`.

The profiling hooks moved into the unified telemetry layer
(``heat_tpu/telemetry/``, docs/observability.md); every public name is
re-exported here so existing ``heat_tpu.utils.profiling`` imports keep
working unchanged.
"""

from __future__ import annotations

from ..telemetry.profiling import (  # noqa: F401
    annotate,
    monitor,
    start_trace,
    stop_trace,
    trace,
)

__all__ = ["annotate", "monitor", "start_trace", "stop_trace", "trace"]
