"""Admission control: per-tenant quotas and bounded queues, shed don't sink.

An overloaded batch system slows down; an overloaded *serving* system
must stay fast for the traffic it admits and refuse the rest loudly.
Two mechanisms, both evaluated before a request touches a coalescer
queue:

* **per-tenant token buckets** — each tenant refills at ``rate`` tokens
  per second up to ``burst``; a request costs one token per row.  A
  tenant over its quota is shed with a typed
  :class:`~heat_tpu.resilience.errors.OverloadedError`
  (``cause="quota"``, HTTP 429 with a computed ``Retry-After``) and
  never competes with in-quota tenants for batch slots — the isolation
  property the acceptance gate measures (an over-quota tenant hammers,
  in-quota p99 holds).
* **bounded admission depth, in priority lanes** — at most
  ``HEAT_TPU_SERVE_QUEUE_DEPTH`` rows may be queued-or-in-flight across
  the service, but the bound is applied per **QoS class** with strict
  ordering (docs/serving.md "QoS scheduling").  Each tenant carries a
  class (:data:`QOS_CLASSES`: ``latency`` / ``standard`` / ``batch``,
  default from ``HEAT_TPU_QOS_DEFAULT_CLASS``), and each class sheds
  (``cause="queue"``) at its own depth limit: ``batch`` first (at
  ``HEAT_TPU_QOS_BATCH_LIMIT_PCT`` percent of the bound), ``standard``
  next (the bound minus the ``HEAT_TPU_QOS_LATENCY_RESERVED_PCT``
  percent reserved for the latency lane), ``latency`` last (the full
  bound).  Because the lower lanes stop admitting before the reserve is
  reached, a saturated batch lane can never starve latency-class
  admission — the reserve is headroom only the latency lane may use.
  The shed's ``Retry-After`` is computed from the **lane's own measured
  drain rate** (rows of that class released over a sliding window):
  ``excess_rows / lane_drain_rate``, clamped to [1 ms, 30 s] — so a
  slow-draining batch lane does not inflate the latency lane's
  advertised backoff (the all-lane rate is the cold-lane fallback,
  ``None`` before any drain has been observed at all).

Admitting a latency-class request under
``HEAT_TPU_QOS_PREEMPT_ON_LATENCY`` also raises the process-wide
:class:`~heat_tpu.core.preempt.PreemptionGate` — running checkpointed
batch fits yield the chips at their next resumable-fit chunk boundary
— and the gate is cleared when the latency lane drains empty.

Every decision is accounted in the metrics registry:
``serving.requests`` / ``serving.shed_quota`` / ``serving.shed_queue``
counters (queue sheds also per lane, ``serving.shed_queue.<class>``)
and the ``serving.queue_depth`` / ``serving.lane_depth.<class>``
gauges — the signals a load balancer or autoscaler watches on
``/metrics``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from ..analysis import tsan as _tsan
from ..core._env import env_flag, env_float, env_str
from ..resilience.errors import OverloadedError
from ..telemetry import metrics as _tm

__all__ = ["AdmissionController", "QOS_CLASSES", "TokenBucket"]

#: Priority classes, highest first.  Strict ordering at the depth gate:
#: a class's depth limit is never below any lower class's, so the lanes
#: shed in reverse priority order as the queue fills.
QOS_CLASSES = ("latency", "standard", "batch")

_REQS_C = _tm.counter("serving.requests", "prediction requests admitted")
_SHED_QUOTA_C = _tm.counter(
    "serving.shed_quota", "requests shed by per-tenant quota (429)"
)
_SHED_QUEUE_C = _tm.counter(
    "serving.shed_queue", "requests shed by the bounded admission queue (429)"
)
_DEPTH_G = _tm.gauge(
    "serving.queue_depth", "rows admitted and not yet answered"
)
_LANE_SHED_C = {
    cls: _tm.counter(
        f"serving.shed_queue.{cls}",
        f"{cls}-class requests shed at the lane's depth limit (429)",
    )
    for cls in QOS_CLASSES
}
_LANE_DEPTH_G = {
    cls: _tm.gauge(
        f"serving.lane_depth.{cls}",
        f"{cls}-class rows admitted and not yet answered",
    )
    for cls in QOS_CLASSES
}


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.

    ``rate <= 0`` means unlimited (every take succeeds).  Not
    self-locking — the controller serializes access."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def take(self, cost: float = 1.0, now: Optional[float] = None) -> float:
        """Try to spend ``cost`` tokens; returns 0.0 on success or the
        seconds until enough tokens will have refilled (the 429
        ``Retry-After``)."""
        if self.rate <= 0:
            return 0.0
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= cost:
            self.tokens -= cost
            return 0.0
        return (cost - self.tokens) / self.rate


class AdmissionController:
    """Per-tenant quotas + one bounded admission count for the service.

    ``admit(tenant, rows)`` either accounts the rows in (returning a
    token the caller must ``release``) or raises
    :class:`OverloadedError`; unknown tenants get a bucket at the
    default rate/burst on first sight."""

    #: sliding window (seconds) over which the queue drain rate is
    #: estimated for queue-shed Retry-After computation
    DRAIN_WINDOW_S = 5.0

    def __init__(
        self,
        max_depth: int,
        default_rate: float = 0.0,
        default_burst: float = 64.0,
    ):
        self.max_depth = int(max_depth)
        self.default_rate = float(default_rate)
        self.default_burst = float(default_burst)
        self._buckets: Dict[str, TokenBucket] = {}
        self._depth = 0
        #: (monotonic, rows) per release inside the sliding window — the
        #: measured service drain rate a queue-caused shed's Retry-After
        #: is computed from (rows ahead / rows-per-second drained).
        #: ``_drained`` is the all-lane window (cold-lane fallback);
        #: ``_lane_drained[cls]`` is the lane's own window, so one slow
        #: lane cannot mis-pace another lane's advertised backoff.
        self._drained: deque = deque()
        self._lane_drained: Dict[str, deque] = {cls: deque() for cls in QOS_CLASSES}
        self._lane_depth: Dict[str, int] = {cls: 0 for cls in QOS_CLASSES}
        self._classes: Dict[str, str] = {}
        self.default_class = env_str("HEAT_TPU_QOS_DEFAULT_CLASS")
        if self.default_class not in QOS_CLASSES:
            raise ValueError(
                f"HEAT_TPU_QOS_DEFAULT_CLASS must be one of {QOS_CLASSES}, "
                f"got {self.default_class!r}"
            )
        # strict class ordering: batch limit <= standard limit <= bound,
        # so the lanes shed lowest-priority-first as the queue fills and
        # the top (100 - reserved)% .. 100% band is latency-only
        reserved = self.max_depth * env_float("HEAT_TPU_QOS_LATENCY_RESERVED_PCT") / 100.0
        standard_limit = max(1, int(round(self.max_depth - reserved)))
        batch_limit = max(
            1,
            min(
                standard_limit,
                int(round(self.max_depth * env_float("HEAT_TPU_QOS_BATCH_LIMIT_PCT") / 100.0)),
            ),
        )
        self.lane_limits: Dict[str, int] = {
            "latency": self.max_depth,
            "standard": standard_limit,
            "batch": batch_limit,
        }
        self._preempt_on_latency = env_flag("HEAT_TPU_QOS_PREEMPT_ON_LATENCY")
        self._lock = _tsan.register_lock("serving.admission")

    def set_quota(self, tenant: str, rate: float, burst: Optional[float] = None) -> None:
        """Pin ``tenant``'s refill rate (rows/s) and burst (defaults to
        ``rate``, floor 1); replaces any existing bucket."""
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            self._buckets[tenant] = TokenBucket(
                rate, burst if burst is not None else max(rate, 1.0)
            )

    def set_class(self, tenant: str, cls: str) -> None:
        """Pin ``tenant``'s QoS class (``latency``/``standard``/``batch``);
        unknown tenants default to ``HEAT_TPU_QOS_DEFAULT_CLASS``."""
        if cls not in QOS_CLASSES:
            raise ValueError(f"QoS class must be one of {QOS_CLASSES}, got {cls!r}")
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            self._classes[tenant] = cls

    def class_of(self, tenant: str) -> str:
        """``tenant``'s QoS class (the registered default when unset)."""
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            return self._classes.get(tenant, self.default_class)

    def admit(self, tenant: str, rows: int = 1) -> str:
        """Admit ``rows`` for ``tenant`` or raise :class:`OverloadedError`.

        Queue bound (at the tenant's lane limit) first — protects the
        process — quota second (bills the tenant only for admittable
        work).  Returns the tenant's QoS class; pass it back to
        :meth:`release` so the lane accounting stays balanced."""
        rows = max(1, int(rows))
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            cls = self._classes.get(tenant, self.default_class)
            limit = self.lane_limits[cls]
            if self._depth + rows > limit:
                _SHED_QUEUE_C.inc()
                _LANE_SHED_C[cls].inc()
                retry_after = self._queue_retry_after(rows, cls)
                raise OverloadedError(
                    f"admission queue full for the {cls} lane ({self._depth} rows "
                    f"in flight, lane limit {limit}/{self.max_depth}); request "
                    f"of {rows} rows shed",
                    tenant=tenant,
                    cause="queue",
                    retry_after_s=retry_after,
                )
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    self.default_rate, self.default_burst
                )
            retry_after = bucket.take(rows)
            if retry_after > 0.0:
                _SHED_QUOTA_C.inc()
                raise OverloadedError(
                    f"tenant {tenant!r} over quota ({bucket.rate:g} rows/s, "
                    f"burst {bucket.burst:g}); retry in {retry_after:.3f}s",
                    tenant=tenant,
                    cause="quota",
                    retry_after_s=retry_after,
                )
            self._depth += rows
            self._lane_depth[cls] += rows
            _DEPTH_G.set(self._depth)
            _LANE_DEPTH_G[cls].set(self._lane_depth[cls])
        _REQS_C.inc()
        if cls == "latency" and self._preempt_on_latency:
            # outside the admission lock: the gate has its own lock and
            # the request is level-triggered, so ordering races between
            # concurrent admits are harmless
            from ..core.preempt import preemption_gate  # lazy: serving->core edge

            preemption_gate().request("latency-lane admission")
        return cls

    def release(self, rows: int = 1, cls: Optional[str] = None) -> None:
        """Return ``rows`` previously admitted (request answered or
        failed); ``cls`` is the class :meth:`admit` returned (defaults
        to the controller's default class).  Each release feeds both the
        all-lane and the lane's own drain-rate window queue-shed
        Retry-After estimates are computed from."""
        rows = max(1, int(rows))
        now = time.monotonic()
        lane_empty = False
        with self._lock:
            _tsan.note_access("serving.admission.buckets")
            if cls is None or cls not in QOS_CLASSES:
                cls = self.default_class
            self._depth = max(0, self._depth - rows)
            self._lane_depth[cls] = max(0, self._lane_depth[cls] - rows)
            _DEPTH_G.set(self._depth)
            _LANE_DEPTH_G[cls].set(self._lane_depth[cls])
            self._drained.append((now, rows))
            self._lane_drained[cls].append((now, rows))
            self._prune(now)
            lane_empty = cls == "latency" and self._lane_depth["latency"] == 0
        if lane_empty and self._preempt_on_latency:
            from ..core.preempt import preemption_gate  # lazy: serving->core edge

            preemption_gate().clear()

    def _prune(self, now: float) -> None:
        cutoff = now - self.DRAIN_WINDOW_S
        while self._drained and self._drained[0][0] < cutoff:
            self._drained.popleft()
        for lane in self._lane_drained.values():
            while lane and lane[0][0] < cutoff:
                lane.popleft()

    def drain_rate(self) -> float:
        """Measured service drain rate (rows released per second over
        the sliding window), 0.0 before any release."""
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            now = time.monotonic()
            self._prune(now)
            if not self._drained:
                return 0.0
            rows = sum(r for _, r in self._drained)
            # span floor: a single just-now release must not read as an
            # (effectively infinite) instantaneous rate
            span = max(now - self._drained[0][0], 0.1)
            return rows / span

    def _queue_retry_after(self, rows: int, cls: Optional[str] = None) -> Optional[float]:
        """Retry-After for a queue-caused shed: how long until the queue
        has drained enough headroom below ``cls``'s lane limit for
        ``rows``, at the **lane's own** measured drain rate (caller
        holds the lock).  A lane that has not drained inside the window
        falls back to the all-lane rate — better a blended estimate
        than none — and ``None`` before any drain has been observed at
        all: a cold process has no basis for an estimate and the coarse
        constant it would fabricate mis-paces every client."""
        now = time.monotonic()
        self._prune(now)
        window = self._lane_drained.get(cls) if cls is not None else None
        if not window:
            window = self._drained
        if not window:
            return None
        drained_rows = sum(r for _, r in window)
        # span floor: a single just-now release must not read as an
        # (effectively infinite) instantaneous rate
        span = max(now - window[0][0], 0.1)
        rate = drained_rows / span
        if rate <= 0.0:
            return None
        limit = self.lane_limits.get(cls, self.max_depth)
        excess = self._depth + rows - limit
        return min(max(excess / rate, 0.001), 30.0)

    def depth(self) -> int:
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            return self._depth

    def lane_depths(self) -> Dict[str, Dict[str, float]]:
        """Per-class admission accounting: rows in flight, the lane's
        depth limit and its windowed drain rate (rows/s) — the
        per-model healthz and /tenantz surfaces read this."""
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("serving.admission.buckets", write=False)
            self._prune(now)
            out: Dict[str, Dict[str, float]] = {}
            for cls in QOS_CLASSES:
                window = self._lane_drained[cls]
                rate = 0.0
                if window:
                    rate = sum(r for _, r in window) / max(now - window[0][0], 0.1)
                out[cls] = {
                    "depth": self._lane_depth[cls],
                    "limit": self.lane_limits[cls],
                    "drain_rate": round(rate, 3),
                }
            return out
