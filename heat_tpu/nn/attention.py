"""Sequence-parallel attention: ring attention and all-to-all (Ulysses).

The reference has no attention (SURVEY.md §2: TP/PP/CP "ABSENT in the
reference" — heat is not an LLM framework), but its long-dimension
primitives — halo exchange (dndarray.py:387), the spatial ring
(distance.py:209), and pencil resplit (fft.py:100-137) — are exactly the
communication patterns context parallelism needs.  This module closes that
loop: the same ``shard_map`` + ``ppermute`` / ``all_to_all`` machinery the
rest of the framework uses, applied to scaled-dot-product attention so
sequences longer than one chip's HBM are first-class.

Two strategies, both exact (not approximations):

* **ring**: every device holds one sequence block of Q, K, V; K/V blocks
  rotate around the ICI ring (one ``ppermute`` per step, overlapped with
  the block matmuls by XLA) while a numerically-stable online softmax
  (flash-attention accumulation) folds each visiting block into the
  output.  Memory per device is O(seq/p); the full (seq x seq) score
  matrix never materializes.
* **ulysses** (all-to-all): one ``all_to_all`` re-shards from
  sequence-split to head-split, each device runs full-sequence attention
  on its heads, and a second ``all_to_all`` restores sequence sharding.
  Requires ``heads % p == 0``; cheaper for moderate sequences, two
  collectives total.
"""

from __future__ import annotations

import functools
import math
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dndarray import DNDarray
from ..parallel.comm import Communication, sanitize_comm
from ..core._compat import shard_map as _shard_map

__all__ = ["scaled_dot_product_attention", "ring_attention", "ulysses_attention"]

_NEG_INF = -1e30


def _flash_available() -> bool:
    """Whether the TPU Pallas flash-attention kernel can be used.

    The kernel's win on TPU is MEMORY, not raw speed: the (h, seq, seq)
    score tensor never materializes, so full-sequence local attention
    scales to lengths where the einsum path OOMs.  Opt out with
    HEAT_TPU_FLASH=0."""
    if os.environ.get("HEAT_TPU_FLASH", "1") != "1":
        return False
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
    except ImportError:  # pragma: no cover - jax always ships it on tpu
        return False
    return True


def _local_flash(q, k, v, scale, causal, n_true):
    """Full-sequence attention via the Pallas flash kernel.

    ``q``/``k``/``v`` are (seq, heads, head_dim); padded tail positions
    (>= n_true) are isolated with segment ids so real tokens never attend
    padding.  Raises at trace time (caught by callers, who fall back to
    the einsum path) when the kernel rejects the shape."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention,
    )

    s = q.shape[0]
    qb = q.transpose(1, 0, 2)[None].astype(jnp.float32)  # (1, h, s, d)
    kb = k.transpose(1, 0, 2)[None].astype(jnp.float32)
    vb = v.transpose(1, 0, 2)[None].astype(jnp.float32)
    seg = None
    if n_true < s:
        ids = (jnp.arange(s) >= n_true).astype(jnp.int32)[None]
        seg = SegmentIds(q=ids, kv=ids)
    out = flash_attention(qb, kb, vb, causal=causal, sm_scale=scale, segment_ids=seg)
    return out[0].transpose(1, 0, 2).astype(q.dtype)


def _block_attn_update(o, m, l, q, k, v, q_off, k_off, scale, causal, n_true):
    """Fold one K/V block into the running (output, max, denom) triple.

    Flash-attention online softmax: scores are computed in f32, the running
    max ``m`` and denominator ``l`` are rescaled as new blocks arrive.
    ``q_off``/``k_off`` are the global positions of the local blocks —
    needed for causal masking and for masking the padded tail rows
    (global index >= n_true) the pad-and-mask invariant introduces.
    """
    sq, h, d = q.shape
    sk = k.shape[0]
    scores = (
        jnp.einsum(
            "qhd,khd->hqk", q, k,
            preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST,
        )
        * scale
    )
    k_pos = k_off + jnp.arange(sk)
    mask = (k_pos < n_true)[None, None, :]
    if causal:
        q_pos = q_off + jnp.arange(sq)
        mask = mask & (k_pos[None, None, :] <= q_pos[None, :, None])
    scores = jnp.where(mask, scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))  # (h, sq)
    corr = jnp.exp(m - m_new)
    p_block = jnp.exp(scores - m_new[..., None])  # (h, sq, sk)
    # rows whose every key so far is masked have m_new == -inf and
    # exp(scores - m_new) == exp(0): zero those weights explicitly so a
    # fully-masked block contributes nothing regardless of arrival order
    p_block = jnp.where(mask, p_block, 0.0)
    l_new = l * corr + p_block.sum(axis=-1)
    pv = jnp.einsum(
        "hqk,khd->qhd", p_block, v.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST
    )
    o_new = o * corr.T[..., None] + pv
    return o_new, m_new, l_new


def _ring_body(q, k, v, *, comm: Communication, scale, causal, n_true, block):
    """shard_map body: one sequence block of q/k/v per device."""
    p = comm.size
    name = comm.axis_name
    idx = jax.lax.axis_index(name)
    sq, h, d = q.shape
    qf = q.astype(jnp.float32)
    o = jnp.zeros((sq, h, d), jnp.float32)
    m = jnp.full((h, sq), _NEG_INF, jnp.float32)
    l = jnp.zeros((h, sq), jnp.float32)
    q_off = idx * block
    for step in range(p):
        src = (idx - step) % p  # owner of the K/V block currently held
        o, m, l = _block_attn_update(
            o, m, l, qf, k, v, q_off, src * block, scale, causal, n_true
        )
        if step != p - 1:
            perm = [(i, (i + 1) % p) for i in range(p)]
            k = jax.lax.ppermute(k, name, perm)
            v = jax.lax.ppermute(v, name, perm)
    return (o / jnp.maximum(l, 1e-30).T[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    comm: Optional[Communication] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    n_true: Optional[int] = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded around the ICI ring.

    ``q``/``k``/``v`` are global arrays of shape (seq, heads, head_dim)
    whose leading axis length is a multiple of ``comm.size`` (the
    pad-and-mask layer guarantees this for DNDarray inputs; raw callers
    pass padded arrays plus ``n_true``).
    """
    comm = sanitize_comm(comm)
    seq, h, d = q.shape
    if seq % comm.size:
        raise ValueError(f"padded sequence {seq} must divide the mesh size {comm.size}")
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    n_true = seq if n_true is None else n_true
    block = seq // comm.size
    return _ring_fn(comm, float(scale), bool(causal), int(n_true), block)(q, k, v)


@functools.lru_cache(maxsize=128)
def _ring_fn(comm, scale, causal, n_true, block):
    """Jitted, cached ring-attention executable — rebuilding the shard_map
    per call would retrace and recompile every time."""
    body = partial(
        _ring_body, comm=comm, scale=scale, causal=causal, n_true=n_true, block=block
    )
    return jax.jit(
        _shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(P(comm.axis_name), P(comm.axis_name), P(comm.axis_name)),
            out_specs=P(comm.axis_name),
        )
    )


def _ulysses_body(q, k, v, *, comm, scale, causal, n_true, use_flash):
    """shard_map body: all_to_all seq->heads, local attention, reverse."""
    name = comm.axis_name
    # (block, h, d) -> (seq, h/p, d): gather sequence, scatter heads
    qg = jax.lax.all_to_all(q, name, split_axis=1, concat_axis=0, tiled=True)
    kg = jax.lax.all_to_all(k, name, split_axis=1, concat_axis=0, tiled=True)
    vg = jax.lax.all_to_all(v, name, split_axis=1, concat_axis=0, tiled=True)
    seq = qg.shape[0]
    og = None
    if use_flash:
        # each device now holds the FULL sequence for h/p heads — the
        # shape flash attention wants; the (h/p, seq, seq) score tensor
        # of the einsum path never materializes
        try:
            og = _local_flash(qg, kg, vg, scale, causal, n_true)
        except Exception:  # lint: allow H501(trace-time shape rejection -> einsum fallback)
            og = None
    if og is None:
        scores = (
            jnp.einsum(
                "qhd,khd->hqk", qg.astype(jnp.float32), kg,
                preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST,
            )
            * scale
        )
        k_pos = jnp.arange(seq)
        mask = (k_pos < n_true)[None, None, :]
        if causal:
            mask = mask & (k_pos[None, None, :] <= k_pos[None, :, None])
        scores = jnp.where(mask, scores, _NEG_INF)
        weights = jax.nn.softmax(scores, axis=-1)
        og = jnp.einsum(
            "hqk,khd->qhd", weights, vg.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST
        ).astype(q.dtype)
    # (seq, h/p, d) -> (block, h, d)
    return jax.lax.all_to_all(og, name, split_axis=0, concat_axis=1, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    comm: Optional[Communication] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    n_true: Optional[int] = None,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Exact attention via all-to-all sequence parallelism (Ulysses style).

    ``use_flash=True`` runs the local full-sequence attention through the
    Pallas flash kernel (TPU only): the (h/p, seq, seq) score tensor never
    materializes, trading the einsum path's HIGHEST-precision matmuls for
    the kernel's default MXU precision (~1e-2 f32 outputs).
    """
    comm = sanitize_comm(comm)
    seq, h, d = q.shape
    if seq % comm.size:
        raise ValueError(f"padded sequence {seq} must divide the mesh size {comm.size}")
    if h % comm.size:
        raise ValueError(f"ulysses needs heads ({h}) divisible by the mesh size ({comm.size})")
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    n_true = seq if n_true is None else n_true
    flash = bool(use_flash) and _flash_available()
    return _ulysses_fn(comm, float(scale), bool(causal), int(n_true), flash)(q, k, v)


@functools.lru_cache(maxsize=128)
def _ulysses_fn(comm, scale, causal, n_true, use_flash=False):
    """Jitted, cached Ulysses executable (see _ring_fn)."""
    body = partial(
        _ulysses_body, comm=comm, scale=scale, causal=causal, n_true=n_true,
        use_flash=use_flash,
    )
    return jax.jit(
        _shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(P(comm.axis_name), P(comm.axis_name), P(comm.axis_name)),
            out_specs=P(comm.axis_name),
        )
    )


def scaled_dot_product_attention(
    q: DNDarray,
    k: DNDarray,
    v: DNDarray,
    causal: bool = False,
    scale: Optional[float] = None,
    method: str = "ring",
) -> DNDarray:
    """DNDarray-level exact attention over the sequence-split axis.

    Inputs are (seq, heads, head_dim) DNDarrays, all with the same split:
    ``split=0`` runs the distributed strategy chosen by ``method``
    ("ring", "ulysses", or its alias "alltoall"); ``split=None`` computes
    locally.
    """
    for name, t in (("q", q), ("k", k), ("v", v)):
        if not isinstance(t, DNDarray):
            raise TypeError(f"{name} must be a DNDarray, got {type(t)}")
        if t.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), got {t.ndim}-D")
    if not (q.split == k.split == v.split):
        raise ValueError(f"q/k/v must share a split, got {q.split}/{k.split}/{v.split}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError("q/k/v must have identical shapes (self-attention blocks)")

    seq, h, d = q.shape
    scale = 1.0 / math.sqrt(d) if scale is None else scale

    if method not in ("ring", "ulysses", "alltoall", "flash"):
        raise ValueError(
            f'method must be "ring", "ulysses", "alltoall" or "flash", got {method!r}'
        )

    if q.split is None:
        qd, kd, vd = q._dense(), k._dense(), v._dense()
        if method == "flash" and _flash_available():
            # memory-bounded local kernel (opt-in): scales past the einsum
            # path's (h, seq, seq) materialization limit at the cost of
            # the kernel's default MXU precision
            try:
                out = _local_flash(qd, kd, vd, scale, causal, seq)
                return DNDarray.from_dense(out, None, q.device, q.comm)
            except Exception:  # lint: allow H501(kernel shape rejection -> einsum fallback)
                pass  # kernel rejected the shape -> einsum path
        scores = (
            jnp.einsum(
                "qhd,khd->hqk", qd.astype(jnp.float32), kd,
                precision=jax.lax.Precision.HIGHEST,
            )
            * scale
        )
        if causal:
            pos = jnp.arange(seq)
            scores = jnp.where(pos[None, None, :] <= pos[None, :, None], scores, _NEG_INF)
        out = jnp.einsum(
            "hqk,khd->qhd", jax.nn.softmax(scores, -1), vd.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )
        return DNDarray.from_dense(out.astype(qd.dtype), None, q.device, q.comm)
    if q.split != 0:
        raise ValueError(f"attention is sequence-parallel over split=0, got split={q.split}")

    # "flash" on a split sequence = Ulysses re-sharding with the flash
    # local kernel (each device gets the full sequence for its heads)
    if method == "ring":
        out_padded = ring_attention(
            q.larray_padded, k.larray_padded, v.larray_padded,
            comm=q.comm, causal=causal, scale=scale, n_true=seq,
        )
    else:
        out_padded = ulysses_attention(
            q.larray_padded, k.larray_padded, v.larray_padded,
            comm=q.comm, causal=causal, scale=scale, n_true=seq,
            use_flash=(method == "flash"),
        )
    sliced = out_padded[:seq]
    return DNDarray.from_dense(sliced, 0, q.device, q.comm)
