"""Embedded metric-history (TSDB) tests (ISSUE 19 tentpole).

The contract under test (docs/observability.md):

* ``record`` pushes controller-side points into bounded per-series
  rings; ``query``/``window_stats`` trim to the trailing window and
  summarize in the exact shape controllers embed as journal evidence;
* ``sample_once`` scrapes the live metric registry through the
  allowlist (env-overridable, ``*`` suffix = prefix match), fanning
  histograms out into ``.count``/``.p50``/``.p99`` sub-series;
* the background sampler thread arms/disarms idempotently and the
  ``HEAT_TPU_TSDB_*`` knobs re-apply mid-process via ``refresh_env``
  (existing rings re-bounded, points kept);
* ``/queryz`` serves per-series points + stats as JSON and an HTML
  table, and the snapshot form bounds itself for crash bundles.
"""

import json
import time

import pytest

from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver
from heat_tpu.telemetry import tsdb as ttsdb


@pytest.fixture(autouse=True)
def _clean_tsdb():
    ttsdb.reset_tsdb()
    yield
    ttsdb.reset_tsdb()
    ttsdb.refresh_env()


@pytest.fixture
def live_server():
    srv = tserver.start_server(0)
    yield srv
    tserver.stop_server()


def _get(srv, route):
    import urllib.request

    with urllib.request.urlopen(f"{srv.url}{route}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


# ----------------------------------------------------------------------
# record / query / window stats
# ----------------------------------------------------------------------
class TestRecordAndQuery:
    def test_record_and_query_oldest_first(self):
        ttsdb.record("canary.mismatch_pct", 1.0, ts=10.0)
        ttsdb.record("canary.mismatch_pct", 3.0, ts=20.0)
        assert ttsdb.query("canary.mismatch_pct") == [(10.0, 1.0), (20.0, 3.0)]
        assert ttsdb.series_names() == ["canary.mismatch_pct"]
        assert ttsdb.query("unknown.series") == []

    def test_window_trims_to_trailing_seconds(self):
        for i in range(5):
            ttsdb.record("s", float(i), ts=100.0 + 10 * i)
        assert ttsdb.query("s", window_s=20.0) == [
            (120.0, 2.0), (130.0, 3.0), (140.0, 4.0),
        ]

    def test_window_stats_shape(self):
        for v in (4.0, 1.0, 7.0):
            ttsdb.record("s", v, ts=time.time())
        st = ttsdb.window_stats("s", window_s=60.0)
        assert st["series"] == "s" and st["window_s"] == 60.0
        assert st["n"] == 3 and st["min"] == 1.0 and st["max"] == 7.0
        assert st["mean"] == 4.0 and st["first"] == 4.0 and st["last"] == 7.0

    def test_window_stats_empty(self):
        st = ttsdb.window_stats("nothing")
        assert st["n"] == 0 and st["min"] is None and st["last"] is None

    def test_retention_bounds_each_ring(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_TSDB_RETENTION", "4")
        ttsdb.refresh_env()
        for i in range(10):
            ttsdb.record("s", float(i), ts=float(i))
        assert ttsdb.query("s") == [
            (6.0, 6.0), (7.0, 7.0), (8.0, 8.0), (9.0, 9.0),
        ]

    def test_refresh_env_rebounds_existing_rings(self, monkeypatch):
        for i in range(10):
            ttsdb.record("s", float(i), ts=float(i))
        monkeypatch.setenv("HEAT_TPU_TSDB_RETENTION", "3")
        ttsdb.refresh_env()
        assert ttsdb.query("s") == [(7.0, 7.0), (8.0, 8.0), (9.0, 9.0)]


# ----------------------------------------------------------------------
# allowlist + registry scrape
# ----------------------------------------------------------------------
class TestScrape:
    def test_allowlist_default_and_env_override(self, monkeypatch):
        assert ttsdb._matches("canary.mismatch_pct", ttsdb.allowed_series())
        assert ttsdb._matches("dispatch.compile_fallbacks",
                              ttsdb.allowed_series())
        assert not ttsdb._matches("dispatch.cache_hits",
                                  ttsdb.allowed_series())
        monkeypatch.setenv("HEAT_TPU_TSDB_SERIES", "custom.*, exact.name")
        ttsdb.refresh_env()
        assert ttsdb.allowed_series() == ("custom.*", "exact.name")
        assert ttsdb._matches("custom.anything", ttsdb.allowed_series())
        assert ttsdb._matches("exact.name", ttsdb.allowed_series())
        assert not ttsdb._matches("exact.name.sub", ttsdb.allowed_series())

    def test_sample_once_scrapes_allowlisted_scalars(self, monkeypatch):
        tm.gauge("stream.test_lag").set(5.0)
        tm.counter("dispatch.cache_hits")  # outside the allowlist
        n = ttsdb.sample_once(now=123.0)
        assert n >= 1
        assert ttsdb.query("stream.test_lag") == [(123.0, 5.0)]
        assert ttsdb.query("dispatch.cache_hits") == []

    def test_sample_once_fans_out_histograms(self):
        h = tm.histogram("serve.test_latency_ms")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        ttsdb.sample_once(now=50.0)
        for sub in ("count", "p50", "p99"):
            pts = ttsdb.query(f"serve.test_latency_ms.{sub}")
            assert len(pts) == 1 and pts[0][0] == 50.0
        assert ttsdb.query("serve.test_latency_ms.count")[0][1] == 4.0

    def test_sampler_thread_idempotent(self):
        assert not ttsdb.sampler_running()
        try:
            assert ttsdb.start_sampler() is True
            assert ttsdb.start_sampler() is False  # already armed
            assert ttsdb.sampler_running()
        finally:
            ttsdb.stop_sampler()
            ttsdb.stop_sampler()  # idempotent
        assert not ttsdb.sampler_running()


# ----------------------------------------------------------------------
# reports + /queryz
# ----------------------------------------------------------------------
class TestReports:
    def test_queryz_report_shape(self):
        ttsdb.record("canary.mismatch_pct", 2.5, ts=time.time())
        doc = ttsdb.queryz_report()
        assert doc["sampler_running"] is False
        assert "canary.*" in doc["allowlist"]
        entry = doc["series"]["canary.mismatch_pct"]
        assert entry["stats"]["n"] == 1 and entry["stats"]["last"] == 2.5
        assert len(entry["points"]) == 1
        assert json.loads(json.dumps(doc))  # JSON-safe end to end

    def test_queryz_report_selects_series(self):
        ttsdb.record("a.one", 1.0)
        ttsdb.record("b.two", 2.0)
        doc = ttsdb.queryz_report(series=["a.one"])
        assert list(doc["series"]) == ["a.one"]

    def test_tsdb_snapshot_bounds_points(self):
        for i in range(50):
            ttsdb.record("s", float(i), ts=float(i))
        snap = ttsdb.tsdb_snapshot(max_points=8)
        assert len(snap["series"]["s"]) == 8
        assert snap["series"]["s"][-1] == [49.0, 49.0]

    def test_queryz_endpoint_json_and_html(self, live_server):
        ttsdb.record("canary.mismatch_pct", 7.5, ts=time.time())
        status, ctype, body = _get(
            live_server, "/queryz?format=json&series=canary.mismatch_pct"
        )
        assert status == 200 and "application/json" in ctype
        doc = json.loads(body)
        assert doc["series"]["canary.mismatch_pct"]["stats"]["last"] == 7.5
        status, ctype, body = _get(live_server, "/queryz")
        assert status == 200 and "text/html" in ctype
        assert "canary.mismatch_pct" in body and "7.5" in body

    def test_queryz_html_empty_state(self, live_server):
        status, _ctype, body = _get(live_server, "/queryz")
        assert status == 200
        assert "no series retained" in body
