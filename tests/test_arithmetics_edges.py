"""Arithmetics edge matrix at reference width (VERDICT r3 #8): the deep
edge families of heat/core/tests/test_arithmetics.py (4,519 LoC) —
negative-operand mod/fmod/floordiv, division-by-zero, pow corners,
promotion pairs, NaN/inf relationals, integer wraparound, scalar-lhs
forms, where+out interplay, in-place dtype rules — checked against numpy
ground truth across splits on the 8-device mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


def _pair(split, a, b):
    return ht.array(a, split=split), ht.array(b, split=split)


@pytest.mark.parametrize("split", SPLITS)
def test_mod_negative_operands(split):
    a = np.array([7, -7, 7, -7, 5, -5, 0, 3], np.int64)
    b = np.array([3, 3, -3, -3, 2, 2, 5, -2], np.int64)
    ha, hb = _pair(split, a, b)
    np.testing.assert_array_equal(ht.mod(ha, hb).numpy(), np.mod(a, b))
    np.testing.assert_array_equal(ht.remainder(ha, hb).numpy(), np.remainder(a, b))
    np.testing.assert_array_equal(ht.floordiv(ha, hb).numpy(), a // b)


@pytest.mark.parametrize("split", SPLITS)
def test_fmod_follows_c_semantics(split):
    a = np.array([7.0, -7.0, 7.5, -7.5, 5.25], np.float32)
    b = np.array([3.0, 3.0, -3.0, -3.0, 2.5], np.float32)
    ha, hb = _pair(split, a, b)
    np.testing.assert_allclose(ht.fmod(ha, hb).numpy(), np.fmod(a, b), rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
def test_float_division_by_zero(split):
    a = np.array([1.0, -1.0, 0.0, 5.0], np.float32)
    b = np.array([0.0, 0.0, 0.0, 2.0], np.float32)
    ha, hb = _pair(split, a, b)
    with np.errstate(divide="ignore", invalid="ignore"):
        want = a / b
    got = (ha / hb).numpy()
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    np.testing.assert_array_equal(np.isposinf(got), np.isposinf(want))
    np.testing.assert_array_equal(np.isneginf(got), np.isneginf(want))


@pytest.mark.parametrize("split", SPLITS)
def test_pow_corners(split):
    a = np.array([0.0, 0.0, 2.0, -2.0, 4.0, 2.0], np.float32)
    b = np.array([0.0, 2.0, -1.0, 2.0, 0.5, 10.0], np.float32)
    ha, hb = _pair(split, a, b)
    np.testing.assert_allclose(ht.pow(ha, hb).numpy(), a**b, rtol=1e-5)
    # integer pow with non-negative exponents
    ia = np.array([2, 3, 5, 1], np.int64)
    ib = np.array([10, 3, 0, 7], np.int64)
    hia, hib = _pair(split, ia, ib)
    np.testing.assert_array_equal(ht.power(hia, hib).numpy(), ia**ib)


PROMOTION_PAIRS = [
    (np.int32, np.int64, np.int64),
    (np.int64, np.float32, np.float32),
    (np.float32, np.float64, np.float64),
    (np.uint8, np.int32, np.int32),
    (np.int8, np.uint8, np.int16),
    (np.float32, np.float32, np.float32),
]


@pytest.mark.parametrize("dt1,dt2,want", PROMOTION_PAIRS)
def test_add_promotion_table(dt1, dt2, want):
    a = np.ones(10, dt1)
    b = np.ones(10, dt2)
    got = (ht.array(a, split=0) + ht.array(b, split=0)).numpy()
    assert got.dtype == np.dtype(want), f"{dt1}+{dt2} -> {got.dtype}, want {want}"
    np.testing.assert_array_equal(got, a + b)


@pytest.mark.parametrize("split", SPLITS)
def test_relational_with_nan_inf(split):
    a = np.array([np.nan, np.inf, -np.inf, 1.0, np.nan], np.float32)
    b = np.array([np.nan, 1.0, -np.inf, np.nan, 2.0], np.float32)
    ha, hb = _pair(split, a, b)
    for op in ("__lt__", "__le__", "__gt__", "__ge__", "__eq__", "__ne__"):
        got = getattr(ha, op)(hb).numpy()
        want = getattr(a, op)(b)
        np.testing.assert_array_equal(got, want, err_msg=op)


def test_integer_wraparound_matches_numpy():
    a = np.array([np.iinfo(np.int32).max, np.iinfo(np.int32).min], np.int32)
    one = np.ones(2, np.int32)
    with np.errstate(over="ignore"):
        want_add = a + one
        want_sub = a - one
    np.testing.assert_array_equal((ht.array(a, split=0) + ht.array(one, split=0)).numpy(), want_add)
    np.testing.assert_array_equal((ht.array(a, split=0) - ht.array(one, split=0)).numpy(), want_sub)


@pytest.mark.parametrize("split", SPLITS)
def test_scalar_lhs_forms(split):
    a = np.array([1.0, 2.0, 4.0, 8.0], np.float32)
    ha = ht.array(a, split=split)
    np.testing.assert_allclose((2.0 - ha).numpy(), 2.0 - a)
    np.testing.assert_allclose((2.0 / ha).numpy(), 2.0 / a)
    np.testing.assert_allclose((2.0 * ha).numpy(), 2.0 * a)
    np.testing.assert_allclose((16 // ha.astype(ht.int64)).numpy(), 16 // a.astype(np.int64))
    np.testing.assert_allclose((2.0 ** ha).numpy(), 2.0**a)


@pytest.mark.parametrize("split", SPLITS)
def test_where_and_out_together(split):
    a = np.arange(16, dtype=np.float32)
    b = np.full(16, 3.0, np.float32)
    mask = (np.arange(16) % 3 == 0)
    ha, hb = _pair(split, a, b)
    out = ht.zeros((16,), dtype=ht.float32, split=split)
    res = ht.add(ha, hb, out=out, where=ht.array(mask, split=split))
    assert res is out
    want = np.where(mask, a + b, 0.0)
    np.testing.assert_allclose(out.numpy(), want)


@pytest.mark.parametrize("split", SPLITS)
def test_out_dtype_cast(split):
    a = np.arange(10, dtype=np.float64) + 0.6
    ha = ht.array(a, split=split)
    out = ht.zeros((10,), dtype=ht.int32, split=split)
    ht.add(ha, ha, out=out)
    np.testing.assert_array_equal(out.numpy(), (a + a).astype(np.int32))


def test_inplace_keeps_lhs_dtype():
    a = np.arange(8, dtype=np.float32)
    ha = ht.array(a, split=0)
    ha += ht.array(np.full(8, 0.5, np.float64), split=0)
    assert ha.dtype == ht.float32
    np.testing.assert_allclose(ha.numpy(), a + 0.5)


@pytest.mark.parametrize("split", SPLITS)
def test_float_binary_extras(split):
    a = np.array([3.0, -4.0, 0.5, 100.0], np.float32)
    b = np.array([4.0, 3.0, -0.5, 0.01], np.float32)
    ha, hb = _pair(split, a, b)
    np.testing.assert_allclose(ht.hypot(ha, hb).numpy(), np.hypot(a, b), rtol=1e-6)
    np.testing.assert_allclose(ht.copysign(ha, hb).numpy(), np.copysign(a, b))
    np.testing.assert_allclose(ht.logaddexp(ha, hb).numpy(), np.logaddexp(a, b), rtol=1e-6)
    np.testing.assert_allclose(ht.logaddexp2(ha, hb).numpy(), np.logaddexp2(a, b), rtol=1e-6)
    np.testing.assert_array_equal(ht.signbit(hb).numpy(), np.signbit(b))


@pytest.mark.parametrize("split", SPLITS)
def test_int_binary_extras(split):
    a = np.array([12, 18, 7, 0], np.int64)
    b = np.array([8, 27, 14, 5], np.int64)
    ha, hb = _pair(split, a, b)
    np.testing.assert_array_equal(ht.gcd(ha, hb).numpy(), np.gcd(a, b))
    np.testing.assert_array_equal(ht.lcm(ha, hb).numpy(), np.lcm(a, b))
    np.testing.assert_array_equal(ht.left_shift(ha, hb % 5).numpy(), np.left_shift(a, b % 5))
    np.testing.assert_array_equal(ht.right_shift(ha, hb % 5).numpy(), np.right_shift(a, b % 5))


@pytest.mark.parametrize("split", SPLITS)
def test_rounding_family_half_cases(split):
    a = np.array([0.5, 1.5, 2.5, -0.5, -1.5, 2.675], np.float32)
    ha = ht.array(a, split=split)
    np.testing.assert_allclose(ht.rint(ha).numpy(), np.rint(a))  # banker's
    np.testing.assert_allclose(ht.floor(ha).numpy(), np.floor(a))
    np.testing.assert_allclose(ht.ceil(ha).numpy(), np.ceil(a))
    np.testing.assert_allclose(ht.trunc(ha).numpy(), np.trunc(a))
    np.testing.assert_allclose(ht.fix(ha).numpy(), np.fix(a))


@pytest.mark.parametrize("split", SPLITS)
def test_clip_broadcast_bounds(split):
    a = np.arange(-5, 11, dtype=np.float32)
    ha = ht.array(a, split=split)
    np.testing.assert_allclose(ht.clip(ha, -2.0, 7.0).numpy(), np.clip(a, -2.0, 7.0))
    np.testing.assert_allclose(ht.clip(ha, None, 3.0).numpy(), np.clip(a, None, 3.0))
    np.testing.assert_allclose(ht.clip(ha, 0.0, None).numpy(), np.clip(a, 0.0, None))


def test_uneven_split_edge_extents():
    """Extents that leave high devices empty (1, 7, 9 over 8 devices)."""
    for n in (1, 7, 9, 17):
        a = np.arange(n, dtype=np.float32)
        ha = ht.array(a, split=0)
        np.testing.assert_allclose((ha + ha).numpy(), a + a)
        np.testing.assert_allclose(float(ha.sum()), a.sum(), rtol=1e-6)
        np.testing.assert_allclose(float((ha * 2 - 1).prod()), (a * 2 - 1).prod(), rtol=1e-4)


@pytest.mark.parametrize("split", SPLITS)
def test_divmod_pair(split):
    a = np.array([7.0, -7.0, 9.5, 0.0], np.float32)
    b = np.array([3.0, 3.0, -2.0, 5.0], np.float32)
    ha, hb = _pair(split, a, b)
    d, m = ht.divmod(ha, hb)
    wd, wm = np.divmod(a, b)
    np.testing.assert_allclose(d.numpy(), wd)
    np.testing.assert_allclose(m.numpy(), wm)


def test_bool_arithmetic_promotes():
    a = np.array([True, False, True, True])
    ha = ht.array(a, split=0)
    got = (ha + ha).numpy()
    np.testing.assert_array_equal(got, a + a)
    got_sum = int(ht.sum(ha))
    assert got_sum == int(a.sum())


@pytest.mark.parametrize("split", SPLITS)
def test_nan_reductions(split):
    a = np.array([1.0, np.nan, 3.0, np.nan, 5.0], np.float32)
    ha = ht.array(a, split=split)
    np.testing.assert_allclose(float(ht.nansum(ha)), np.nansum(a))
    np.testing.assert_allclose(float(ht.nanprod(ha)), np.nanprod(a))
    assert np.isnan(float(ht.sum(ha)))


@pytest.mark.parametrize("split", SPLITS)
def test_heaviside_and_sign_zoo(split):
    a = np.array([-3.0, -0.0, 0.0, 2.0, np.inf, -np.inf], np.float32)
    h = np.array([0.5, 0.5, 0.5, 0.5, 0.5, 0.5], np.float32)
    ha, hh = _pair(split, a, h)
    np.testing.assert_allclose(ht.heaviside(ha, hh).numpy(), np.heaviside(a, h))
    np.testing.assert_allclose(ht.sign(ha).numpy(), np.sign(a))


def test_broadcast_binary_splits_2d():
    a = np.arange(24, dtype=np.float32).reshape(8, 3)
    row = np.arange(3, dtype=np.float32)
    col = np.arange(8, dtype=np.float32).reshape(8, 1)
    for split in (None, 0, 1):
        ha = ht.array(a, split=split)
        np.testing.assert_allclose((ha + ht.array(row)).numpy(), a + row)
        np.testing.assert_allclose((ha * ht.array(col)).numpy(), a * col)
        np.testing.assert_allclose((ht.array(row) - ha).numpy(), row - a)


def test_ldexp_frexp_roundtrip():
    a = np.array([1.5, -3.25, 1024.0, 0.15625], np.float32)
    ha = ht.array(a, split=0)
    m, e = ht.frexp(ha)
    wm, we = np.frexp(a)
    np.testing.assert_allclose(m.numpy(), wm)
    np.testing.assert_array_equal(e.numpy(), we)
    back = ht.ldexp(m, e)
    np.testing.assert_allclose(back.numpy(), a)


def test_nextafter_direction():
    a = np.array([1.0, -1.0, 0.0], np.float32)
    b = np.array([2.0, -2.0, -1.0], np.float32)
    got = ht.nextafter(ht.array(a, split=0), ht.array(b, split=0)).numpy()
    np.testing.assert_array_equal(got, np.nextafter(a, b))
