"""High-value reference test families ported to the split-sweep +
numpy-ground-truth idiom (VERDICT #9).

Sources: heat/core/tests/test_dndarray.py (indexing matrix),
test_manipulations.py (concatenate/pad/unique sweeps),
test_statistics.py (moments: mean/var/std/skew/kurtosis/average/cov),
test_suites/basic_test.py:77+ (assert-vs-numpy-across-splits idiom).
Extents are non-divisible by the 8-device mesh on purpose (the analog of
the reference's mpirun -n 3 remainder chunks).
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS_2D = [None, 0, 1]


@pytest.fixture(scope="module")
def base():
    rng = np.random.default_rng(42)
    return rng.standard_normal((11, 7))


# ---------------------------------------------------------------- indexing


class TestIndexingMatrix:
    """The reference's getitem/setitem key matrix (test_dndarray.py:600+),
    swept over splits."""

    KEYS = [
        3,
        -2,
        slice(2, 9),
        slice(None, None, 2),
        slice(8, 2, -2),
        (slice(1, 6), 2),
        (slice(None), slice(1, 4)),
        (4, slice(None)),
        (slice(2, 10, 3), slice(0, 6, 2)),
        ...,
        (Ellipsis, 1),
        None,
    ]

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_getitem_matrix(self, base, split):
        a = ht.array(base, split=split)
        for key in self.KEYS:
            got = a[key]
            want = base[key]
            np.testing.assert_allclose(
                np.asarray(got.numpy()), want, rtol=1e-12, err_msg=f"key={key}"
            )
            assert got.shape == want.shape

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_getitem_bool_and_array_keys(self, base, split):
        a = ht.array(base, split=split)
        mask = base[:, 0] > 0
        np.testing.assert_allclose(a[ht.array(mask)].numpy(), base[mask], rtol=1e-12)
        idx = np.array([0, 4, 2, 10])
        np.testing.assert_allclose(a[ht.array(idx)].numpy(), base[idx], rtol=1e-12)

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_setitem_matrix(self, base, split):
        for key, value in [
            (2, 5.0),
            (slice(1, 4), -1.0),
            ((slice(None), 3), 0.5),
            ((slice(2, 8, 2), slice(1, 5)), 9.0),
            (-1, 7.0),
        ]:
            a = ht.array(base.copy(), split=split)
            want = base.copy()
            a[key] = value
            want[key] = value
            np.testing.assert_allclose(a.numpy(), want, rtol=1e-12, err_msg=f"key={key}")

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_setitem_array_value(self, base, split):
        a = ht.array(base.copy(), split=split)
        want = base.copy()
        val = np.arange(7, dtype=base.dtype)
        a[5] = ht.array(val)
        want[5] = val
        np.testing.assert_allclose(a.numpy(), want, rtol=1e-12)


# ------------------------------------------------------------ manipulations


class TestManipulationSweeps:
    """concatenate/pad/unique and friends (test_manipulations.py idiom)."""

    @pytest.mark.parametrize("split", SPLITS_2D)
    @pytest.mark.parametrize("axis", [0, 1])
    def test_concatenate(self, base, split, axis):
        other = np.linspace(0, 1, base.size).reshape(base.shape)
        got = ht.concatenate(
            [ht.array(base, split=split), ht.array(other, split=split)], axis=axis
        )
        np.testing.assert_allclose(got.numpy(), np.concatenate([base, other], axis), rtol=1e-12)

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_pad_modes(self, base, split):
        a = ht.array(base, split=split)
        for width in [1, (2, 3), ((1, 2), (3, 0))]:
            np.testing.assert_allclose(
                ht.pad(a, width).numpy(), np.pad(base, width), rtol=1e-12, err_msg=str(width)
            )
        np.testing.assert_allclose(
            ht.pad(a, 2, mode="constant", constant_values=5).numpy(),
            np.pad(base, 2, constant_values=5),
            rtol=1e-12,
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_unique_sweep(self, split):
        data = np.array([3, 1, 3, 2, 1, 7, 7, 7, 0, 2, 5], dtype=np.float64)
        a = ht.array(data, split=split)
        got = ht.unique(a, sorted=True)
        np.testing.assert_array_equal(np.sort(np.asarray(got.numpy())), np.unique(data))
        got_v, inv = ht.unique(a, sorted=True, return_inverse=True)
        vals = np.asarray(got_v.numpy())
        np.testing.assert_array_equal(vals[np.asarray(inv.numpy())], data)

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_flip_roll_rot90(self, base, split):
        a = ht.array(base, split=split)
        np.testing.assert_allclose(ht.flip(a, 0).numpy(), np.flip(base, 0), rtol=1e-12)
        np.testing.assert_allclose(ht.roll(a, 3, axis=0).numpy(), np.roll(base, 3, 0), rtol=1e-12)
        np.testing.assert_allclose(ht.rot90(a).numpy(), np.rot90(base), rtol=1e-12)

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_stack_family(self, base, split):
        a = ht.array(base, split=split)
        b = ht.array(base * 2, split=split)
        np.testing.assert_allclose(ht.stack([a, b]).numpy(), np.stack([base, base * 2]), rtol=1e-12)
        np.testing.assert_allclose(ht.vstack([a, b]).numpy(), np.vstack([base, base * 2]), rtol=1e-12)
        np.testing.assert_allclose(ht.hstack([a, b]).numpy(), np.hstack([base, base * 2]), rtol=1e-12)
        np.testing.assert_allclose(
            ht.column_stack([a, b]).numpy(), np.column_stack([base, base * 2]), rtol=1e-12
        )

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_reshape_ravel_transpose(self, base, split):
        a = ht.array(base, split=split)
        np.testing.assert_allclose(a.reshape((7, 11)).numpy(), base.reshape(7, 11), rtol=1e-12)
        np.testing.assert_allclose(a.ravel().numpy(), base.ravel(), rtol=1e-12)
        np.testing.assert_allclose(a.T.numpy(), base.T, rtol=1e-12)
        np.testing.assert_allclose(
            ht.moveaxis(a, 0, 1).numpy(), np.moveaxis(base, 0, 1), rtol=1e-12
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_repeat_tile(self, split):
        data = np.arange(10, dtype=np.float64)
        a = ht.array(data, split=split)
        np.testing.assert_array_equal(ht.repeat(a, 3).numpy(), np.repeat(data, 3))
        np.testing.assert_array_equal(ht.tile(a, 2).numpy(), np.tile(data, 2))


# --------------------------------------------------------------- statistics


class TestMoments:
    """mean/var/std/skew/kurtosis/average/cov (test_statistics.py:192-1397)."""

    @pytest.mark.parametrize("split", SPLITS_2D)
    @pytest.mark.parametrize("axis", [None, 0, 1])
    def test_mean_var_std(self, base, split, axis):
        a = ht.array(base, split=split)
        np.testing.assert_allclose(
            np.asarray(ht.mean(a, axis=axis).numpy()), base.mean(axis=axis), rtol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(ht.var(a, axis=axis).numpy()), base.var(axis=axis), rtol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(ht.std(a, axis=axis).numpy()), base.std(axis=axis), rtol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(ht.var(a, axis=axis, ddof=1).numpy()),
            base.var(axis=axis, ddof=1),
            rtol=1e-10,
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_skew_kurtosis(self, split):
        rng = np.random.default_rng(3)
        data = rng.gamma(2.0, size=37)
        a = ht.array(data, split=split)
        m = data.mean()
        c = data - m
        skew_np = (c**3).mean() / (c**2).mean() ** 1.5
        kurt_np = (c**4).mean() / (c**2).mean() ** 2 - 3.0
        # biased (population) moments match the plain numpy formulas
        np.testing.assert_allclose(float(ht.skew(a, unbiased=False)), skew_np, rtol=1e-6)
        np.testing.assert_allclose(float(ht.kurtosis(a, unbiased=False)), kurt_np, rtol=1e-6)
        # default unbiased estimators apply the standard corrections
        n = data.size
        skew_unb = skew_np * np.sqrt(n * (n - 1)) / (n - 2)
        np.testing.assert_allclose(float(ht.skew(a)), skew_unb, rtol=1e-6)

    @pytest.mark.parametrize("split", SPLITS_2D)
    def test_average_weighted(self, base, split):
        a = ht.array(base, split=split)
        w = np.abs(np.random.default_rng(4).standard_normal(7)) + 0.1
        got = ht.average(a, axis=1, weights=ht.array(w))
        np.testing.assert_allclose(got.numpy(), np.average(base, axis=1, weights=w), rtol=1e-10)

    @pytest.mark.parametrize("split", [None, 0])
    def test_cov(self, split):
        rng = np.random.default_rng(5)
        data = rng.standard_normal((5, 40))
        a = ht.array(data, split=None if split is None else 1)
        np.testing.assert_allclose(ht.cov(a).numpy(), np.cov(data), rtol=1e-8)

    @pytest.mark.parametrize("split", SPLITS_2D)
    @pytest.mark.parametrize("axis", [None, 0])
    def test_minmax_arg(self, base, split, axis):
        a = ht.array(base, split=split)
        np.testing.assert_allclose(
            np.asarray(ht.max(a, axis=axis).numpy()), base.max(axis=axis), rtol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(ht.min(a, axis=axis).numpy()), base.min(axis=axis), rtol=1e-12
        )
        np.testing.assert_array_equal(
            np.asarray(ht.argmax(a, axis=axis).numpy()), base.argmax(axis=axis)
        )
        np.testing.assert_array_equal(
            np.asarray(ht.argmin(a, axis=axis).numpy()), base.argmin(axis=axis)
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_percentile_median(self, split):
        rng = np.random.default_rng(6)
        data = rng.standard_normal(53)
        a = ht.array(data, split=split)
        for q in (10, 50, 92.5):
            np.testing.assert_allclose(
                float(ht.percentile(a, q)), np.percentile(data, q), rtol=1e-8
            )
        np.testing.assert_allclose(float(ht.median(a)), np.median(data), rtol=1e-10)

    @pytest.mark.parametrize("split", [None, 0])
    def test_bincount_digitize(self, split):
        data = np.array([0, 1, 1, 3, 2, 1, 7, 3], dtype=np.int64)
        a = ht.array(data, split=split)
        np.testing.assert_array_equal(ht.bincount(a).numpy(), np.bincount(data))
        bins = np.array([0.0, 2.0, 4.0, 6.0])
        np.testing.assert_array_equal(
            ht.digitize(ht.array(data.astype(np.float64), split=split), ht.array(bins)).numpy(),
            np.digitize(data, bins),
        )
