"""Smoke + core-runtime tests: comm, factories, DNDarray metadata,
pad-and-mask correctness on non-divisible extents (the analog of the
reference's mpirun -n 3 remainder coverage)."""

import numpy as np
import pytest

import heat_tpu as ht


def test_devices_present():
    import jax
    import os

    want = int(os.environ.get("HEAT_TPU_TEST_DEVICES", "8"))
    assert len(jax.devices()) == want
    assert ht.get_comm().size == want


def test_smoke_arange_split0():
    # BASELINE config 1: ht.arange(10, split=0) on a device mesh
    a = ht.arange(10, split=0)
    assert a.shape == (10,)
    assert a.split == 0
    assert a.dtype == ht.int32
    np.testing.assert_array_equal(a.numpy(), np.arange(10, dtype=np.int32))


def test_comm_chunk():
    comm = ht.get_comm()
    p = comm.size
    per = -(-10 // p)  # ceil(10/p) rows per rank in the padded layout
    off, lshape, _ = comm.chunk((10,), 0, rank=0)
    assert (off, lshape) == (0, (per,))
    covered = 0
    for r in range(p):
        off, lshape, _ = comm.chunk((10,), 0, rank=r)
        assert off == min(r * per, 10)
        covered += lshape[0]
    assert covered == 10  # true rows partition exactly


def test_lshape_map():
    a = ht.arange(10, split=0)
    lmap = a.lshape_map
    assert lmap.shape == (ht.get_comm().size, 1)
    assert lmap[:, 0].sum() == 10


@pytest.mark.parametrize("split", [None, 0, 1])
def test_factories_match_numpy(split):
    for fn, np_fn in [(ht.zeros, np.zeros), (ht.ones, np.ones)]:
        a = fn((5, 7), split=split)
        np.testing.assert_array_equal(a.numpy(), np_fn((5, 7), dtype=np.float32))
        assert a.split == split
        assert a.dtype == ht.float32


def test_full_eye_linspace():
    np.testing.assert_array_equal(ht.full((3, 5), 7, split=0).numpy(), np.full((3, 5), 7))
    np.testing.assert_array_equal(ht.eye(5, split=1).numpy(), np.eye(5, dtype=np.float32))
    np.testing.assert_allclose(
        ht.linspace(0, 1, 11, split=0).numpy(), np.linspace(0, 1, 11, dtype=np.float32), rtol=1e-6
    )
    np.testing.assert_allclose(
        ht.logspace(0, 2, 5).numpy(), np.logspace(0, 2, 5), rtol=1e-5
    )


def test_array_is_split_roundtrip():
    data = np.arange(12.0).reshape(3, 4)
    a = ht.array(data, is_split=0)
    np.testing.assert_array_equal(a.numpy(), data)
    assert a.split == 0


@pytest.mark.parametrize("n", [8, 10, 13])  # divisible, uneven, prime
def test_pad_and_mask_sum(n):
    a = ht.arange(n, dtype=ht.float32, split=0)
    assert float(a.sum()) == float(np.arange(n).sum())
    assert float(a.prod()) == pytest.approx(float(np.arange(n).prod()), rel=1e-6)


def test_resplit_roundtrip():
    data = np.arange(30.0).reshape(5, 6)
    a = ht.array(data, split=0)
    a2 = a.resplit(1)
    assert a2.split == 1
    np.testing.assert_array_equal(a2.numpy(), data)
    a3 = a2.resplit(None)
    assert a3.split is None
    np.testing.assert_array_equal(a3.numpy(), data)
    a.resplit_(1)
    assert a.split == 1
    np.testing.assert_array_equal(a.numpy(), data)


def test_astype_and_types():
    a = ht.arange(5, split=0)
    b = a.astype(ht.float64)
    assert b.dtype == ht.float64
    assert ht.promote_types(ht.int32, ht.float32) == ht.float32
    assert ht.promote_types(ht.bfloat16, ht.float32) == ht.float32
    assert ht.result_type(a, 1.0) in (ht.float32, ht.float64)
    assert ht.canonical_heat_type("float32") == ht.float32
    assert ht.issubdtype(ht.int32, ht.integer)
    assert not ht.issubdtype(ht.float32, ht.integer)
    assert ht.can_cast(ht.int32, ht.float64)
    assert not ht.can_cast(ht.float64, ht.int32, casting="safe")
    info = ht.finfo(ht.bfloat16)
    assert info.bits == 16


def test_dtype_instantiation_casts():
    a = ht.float32([1, 2, 3])
    assert a.dtype == ht.float32
    np.testing.assert_array_equal(a.numpy(), np.array([1, 2, 3], dtype=np.float32))


def test_item_and_scalars():
    a = ht.array(42)
    assert a.item() == 42
    assert int(ht.array([5])[0]) == 5


def test_getitem_setitem():
    data = np.arange(24.0).reshape(4, 6)
    for split in (None, 0, 1):
        a = ht.array(data, split=split)
        np.testing.assert_array_equal(a[1].numpy(), data[1])
        np.testing.assert_array_equal(a[:, 2].numpy(), data[:, 2])
        np.testing.assert_array_equal(a[1:3, ::2].numpy(), data[1:3, ::2])
        np.testing.assert_array_equal(a[a > 10].numpy(), data[data > 10])
        b = ht.array(data.copy(), split=split)
        b[0] = 0.0
        expected = data.copy()
        expected[0] = 0
        np.testing.assert_array_equal(b.numpy(), expected)


def test_partitioned_protocol():
    a = ht.arange(16, split=0)
    p = a.__partitioned__
    assert p["shape"] == (16,)
    assert len(p["partitions"]) == ht.get_comm().size
    b = ht.from_partition_dict(
        {
            "shape": (4,),
            "partition_tiling": (1,),
            "partitions": {(0,): {"data": np.arange(4), "start": (0,), "shape": (4,), "location": [0]}},
        }
    )
    np.testing.assert_array_equal(b.numpy(), np.arange(4))


def test_repr_smoke():
    s = repr(ht.arange(5, split=0))
    assert "DNDarray" in s and "split=0" in s


def test_transpose_padded():
    data = np.arange(30.0).reshape(5, 6)
    for split in (None, 0, 1):
        a = ht.array(data, split=split)
        t = a.T
        np.testing.assert_array_equal(t.numpy(), data.T)
        if split is not None:
            assert t.split == 1 - split


class TestHtJit:
    """ht.jit fusion layer (SURVEY build-plan decision 2)."""

    def test_fuses_and_matches_eager(self, ht):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((13, 7))
        w = rng.standard_normal((7, 4))

        def pipeline(a, b, scale):
            y = ht.tanh(a @ b) * scale
            return y - ht.mean(y, axis=0), ht.sum(y)

        fused = ht.jit(pipeline)
        for split in (None, 0, 1):
            got, tot = fused(ht.array(x, split=split), ht.array(w), 2.0)
            want, wtot = pipeline(ht.array(x, split=split), ht.array(w), 2.0)
            np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-10)
            np.testing.assert_allclose(float(tot), float(wtot), rtol=1e-10)
            assert got.split == want.split

    def test_retrace_on_new_shape_and_static(self, ht):
        calls = []

        @ht.jit
        def f(a, k):
            calls.append(1)
            return a * k

        a = ht.arange(10, dtype=ht.float32, split=0)
        f(a, 2.0)
        f(a, 2.0)  # cached: no retrace
        assert len(calls) == 1
        f(a, 3.0)  # new static value -> retrace
        assert len(calls) == 2
        f(ht.arange(20, dtype=ht.float32, split=0), 3.0)  # new shape
        assert len(calls) == 3

    def test_rejects_unhashable_static(self, ht):
        @ht.jit
        def f(a, opts):
            return a

        with pytest.raises(TypeError):
            f(ht.arange(4), np.zeros(3))  # raw ndarray: unhashable static

    def test_container_statics_work(self, ht):
        @ht.jit
        def f(a, opts):
            return a * opts["scale"] + opts["bias"][0]

        a = ht.arange(5, dtype=ht.float32, split=0)
        got = f(a, {"scale": 2.0, "bias": (1.0,)})
        np.testing.assert_allclose(got.numpy(), np.arange(5) * 2.0 + 1.0)

    def test_rejects_positional_jit_options(self, ht):
        with pytest.raises(TypeError):
            ht.jit(lambda a: a, donate_argnums=0)

    def test_device_in_cache_key(self, ht):
        # same shapes on different comms/devices must not share a trace
        import jax as _jax
        from heat_tpu.parallel import Communication

        sub = Communication(_jax.devices()[:2])
        f = ht.jit(lambda a: a * 2)
        r1 = f(ht.arange(8, dtype=ht.float32, split=0))
        r2 = f(ht.arange(8, dtype=ht.float32, split=0, comm=sub))
        assert r1.comm.size != r2.comm.size
        np.testing.assert_allclose(r1.numpy(), r2.numpy())
