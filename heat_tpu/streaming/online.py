"""Online estimators: minibatch KMeans, incremental PCA, SGD Lasso
(docs/streaming.md).

``partial_fit``-style variants of the batch estimators, riding the same
:func:`~heat_tpu.core.base.resumable_fit_loop` the finite fits use —
one "iteration" = one stream window, ``commit_every`` windows per
atomic checkpoint commit.  The committed state dict carries the model
arrays AND the stream offset in ONE ``Checkpointer`` step, which is the
whole exactly-once argument: a kill between window commits resumes from
``(model_k, offset_k)``, replays the identical fixed-size windows from
``offset_k`` (sources are replayable by contract), and reproduces the
uninterrupted fit bitwise — the PR 2/3 guarantee extended to unbounded
streams.  ``exhausted_converges=False`` makes a dry stream head PAUSE
the fit (checkpointed ``converged=False``) instead of converging it, so
the same directory resumes consuming when more rows land.

Every fit is divergence-guarded (``all_finite`` over the dict state at
each commit boundary), heartbeats through ``fit.heartbeat_ts``, and
exposes the ``stream.commit`` fault site at each window-commit boundary
(the kill+resume tests script it).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.base import BaseEstimator, resumable_fit_loop
from ..core.dndarray import DNDarray
from .consumer import StreamConsumer
from .source import StreamSource

__all__ = ["StreamingKMeans", "StreamingPCA", "StreamingLasso"]


# ----------------------------------------------------------------------
# jitted window updates (fixed window shape -> one compile per estimator)
# ----------------------------------------------------------------------
@jax.jit
def _mb_kmeans_update(xw, centers, counts):
    """One Sculley minibatch step: assign the window, move each center
    toward its assigned mass with per-center learning rate 1/count."""
    d2 = (
        jnp.sum(xw * xw, axis=1)[:, None]
        - 2.0 * xw @ centers.T
        + jnp.sum(centers * centers, axis=1)[None, :]
    )
    labels = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(labels, centers.shape[0], dtype=xw.dtype)
    wc = jnp.sum(onehot, axis=0)
    ws = onehot.T @ xw
    nc = counts + wc
    denom = jnp.maximum(nc, 1.0)[:, None]
    new_centers = centers + (ws - wc[:, None] * centers) / denom
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, nc, shift


def _fix_signs(vt):
    """Deterministic component orientation: each row's max-|.| entry is
    made positive (stabilizes to_estimator output across SVD backends)."""
    idx = jnp.argmax(jnp.abs(vt), axis=1)
    signs = jnp.sign(vt[jnp.arange(vt.shape[0]), idx])
    signs = jnp.where(signs == 0, 1.0, signs)
    return vt * signs[:, None]


@functools.partial(jax.jit, static_argnames=("k",))
def _ipca_init(xw, k):
    mean = jnp.mean(xw, axis=0)
    xc = xw - mean
    _, s, vt = jnp.linalg.svd(xc, full_matrices=False)
    vt = _fix_signs(vt)
    m2 = jnp.sum(xc * xc, axis=0)
    n = jnp.asarray(xw.shape[0], xw.dtype)
    return mean, m2, vt[:k], s[:k], n


@functools.partial(jax.jit, static_argnames=("k",))
def _ipca_update(xw, mean, m2, comps, svals, n, k):
    """Incremental PCA merge (Ross et al. / sklearn IncrementalPCA):
    SVD of [S*V ; centered window ; mean-correction row]."""
    m = jnp.asarray(xw.shape[0], xw.dtype)
    batch_mean = jnp.mean(xw, axis=0)
    new_n = n + m
    new_mean = mean + (batch_mean - mean) * (m / new_n)
    xc = xw - batch_mean
    corr = jnp.sqrt(n * m / new_n) * (mean - batch_mean)
    stack = jnp.concatenate([svals[:, None] * comps, xc, corr[None, :]], axis=0)
    _, s, vt = jnp.linalg.svd(stack, full_matrices=False)
    vt = _fix_signs(vt)
    new_m2 = m2 + jnp.sum(xc * xc, axis=0) + (n * m / new_n) * (mean - batch_mean) ** 2
    shift = jnp.sum((vt[:k] - comps) ** 2)
    return new_mean, new_m2, vt[:k], s[:k], new_n, shift


@jax.jit
def _ista_update(rows, theta, lam, lr):
    """One proximal-gradient (ISTA) step on the window: gradient of the
    least-squares loss, soft-threshold everything but the intercept."""
    x = rows[:, :-1]
    y = rows[:, -1:]
    xi = jnp.concatenate([jnp.ones((x.shape[0], 1), x.dtype), x], axis=1)
    grad = xi.T @ (xi @ theta - y) / jnp.asarray(x.shape[0], x.dtype)
    z = theta - lr * grad
    thr = lr * lam
    new = jnp.sign(z) * jnp.maximum(jnp.abs(z) - thr, 0.0)
    new = new.at[0].set(z[0])
    shift = jnp.sum((new - theta) ** 2)
    return new, shift


# ----------------------------------------------------------------------
# shared streaming-fit driver
# ----------------------------------------------------------------------
class _OnlineEstimator(BaseEstimator):
    """Shared ``fit_stream`` plumbing of the online estimators.

    ``commit_every``/``checkpoint_dir``/``resume_from`` mirror the batch
    estimators' resume parameters; ``max_windows`` is the CUMULATIVE
    window cap (the resumable loop's ``max_iter`` — a resumed fit counts
    from its committed total, not from zero)."""

    _what = "state"
    _site = "stream.commit"

    def __init__(
        self,
        window_rows: Optional[int] = None,
        commit_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
        max_windows: int = 1_000_000,
        tol: float = 0.0,
    ):
        from ..core._env import env_int
        from ..core.base import validate_resume_params

        if checkpoint_dir is not None or resume_from is not None:
            if commit_every is None:
                commit_every = env_int("HEAT_TPU_STREAM_COMMIT_EVERY", 1)
        validate_resume_params(commit_every, checkpoint_dir, resume_from)
        self.window_rows = window_rows
        self.commit_every = commit_every
        self.checkpoint_dir = checkpoint_dir
        self.resume_from = resume_from
        self.max_windows = int(max_windows)
        self.tol = float(tol)
        self.n_windows_ = 0
        self._recent_dev = None  # device ref; host copy is lazy (recent_window_)
        self._recent_dnd: Optional[DNDarray] = None

    # subclass hooks ----------------------------------------------------
    def _init_state(self, consumer: StreamConsumer) -> Dict:
        raise NotImplementedError

    def _update_state(self, dev: Dict, xw) -> Dict:
        """One window folded into the device-state dict; returns the new
        dict with a ``"__shift"`` scratch entry."""
        raise NotImplementedError

    def _ingest_state(self, state: Dict, consumer: StreamConsumer) -> None:
        raise NotImplementedError

    # driver ------------------------------------------------------------
    def _consume_windows(self, consumer: StreamConsumer, state: Dict, n: int):
        offset = int(state["offset"])
        dev = {k: v for k, v in state.items() if k != "offset"}
        iters = 0
        shift = 0.0
        while iters < n:
            nxt = consumer.next_window(offset)
            if nxt is None:
                break
            _, xw = nxt
            dev = self._update_state(dev, xw)
            shift = dev.pop("__shift")
            offset += consumer.window_rows
            iters += 1
            # keep the rolling recent-window view the refresh driver
            # baselines from (device ref only — the host copy is lazy),
            # and apply any pending key-drift reshard to its persistent
            # split-axis form
            self._recent_dev = xw
            if consumer.maybe_reshard(self._recent_dnd):
                self._recent_dnd = DNDarray.from_dense(
                    jnp.asarray(xw), 0, None, consumer.comm
                )
        new_state = dict(dev)
        new_state["offset"] = offset
        return new_state, iters, shift

    def _as_consumer(self, stream) -> StreamConsumer:
        if isinstance(stream, StreamConsumer):
            return stream
        if isinstance(stream, StreamSource):
            return StreamConsumer(stream, window_rows=self.window_rows)
        raise TypeError(
            f"fit_stream takes a StreamSource or StreamConsumer, got {type(stream)}"
        )

    def fit_stream(self, stream, max_windows: Optional[int] = None) -> "_OnlineEstimator":
        """Consume full windows from ``stream`` until the head runs dry,
        the cumulative ``max_windows`` cap is reached, or (``tol > 0``)
        the window-to-window state shift converges.  Safe to call again
        (or in a fresh process with ``resume_from``) to continue."""
        consumer = self._as_consumer(stream)
        cap = int(max_windows if max_windows is not None else self.max_windows)

        def run_chunk(state, n):
            return self._consume_windows(consumer, state, n)

        def init_state():
            return self._init_state(consumer)

        try:
            state, total = resumable_fit_loop(
                run_chunk,
                init_state,
                max_iter=cap,
                tol=self.tol,
                checkpoint_every=self.commit_every,
                checkpoint_dir=self.checkpoint_dir,
                resume_from=self.resume_from,
                site=self._site,
                what=self._what,
                converged_when=lambda s, t: t > 0.0 and s <= t,
                exhausted_converges=False,
            )
        finally:
            consumer.close()
        self._ingest_state(state, consumer)
        self.n_windows_ = int(total)
        self.offset_ = int(state["offset"])
        return self

    @property
    def recent_window_(self) -> Optional[np.ndarray]:
        """The most recently consumed window (host rows) — the refresh
        driver builds the fresh drift baseline from it."""
        if self._recent_dev is None:
            return None
        return np.asarray(self._recent_dev)


# ----------------------------------------------------------------------
# the estimators
# ----------------------------------------------------------------------
class StreamingKMeans(_OnlineEstimator):
    """Minibatch KMeans (Sculley): the seed window's first ``n_clusters``
    rows initialize the centers, then every window moves each center
    toward its assigned rows with per-center learning rate 1/count."""

    _what = "centers"

    def __init__(self, n_clusters: int = 8, **kwargs):
        super().__init__(**kwargs)
        if n_clusters < 1:
            raise ValueError(f"n_clusters must be >= 1, got {n_clusters}")
        self.n_clusters = int(n_clusters)
        self.cluster_centers_: Optional[np.ndarray] = None

    def _init_state(self, consumer: StreamConsumer) -> Dict:
        seed = consumer.peek(0)
        if seed is None:
            raise ValueError(
                "stream holds fewer committed rows than one full window; "
                "nothing to initialize from"
            )
        if seed.shape[0] < self.n_clusters:
            raise ValueError(
                f"window_rows ({seed.shape[0]}) must be >= n_clusters "
                f"({self.n_clusters}) to seed the centers"
            )
        centers = jnp.asarray(seed[: self.n_clusters], jnp.float32)
        counts = jnp.zeros((self.n_clusters,), jnp.float32)
        return {"centers": centers, "counts": counts, "offset": 0}

    def _update_state(self, dev: Dict, xw) -> Dict:
        centers, counts, shift = _mb_kmeans_update(
            jnp.asarray(xw, jnp.float32),
            jnp.asarray(dev["centers"], jnp.float32),
            jnp.asarray(dev["counts"], jnp.float32),
        )
        return {"centers": centers, "counts": counts, "__shift": shift}

    def _ingest_state(self, state: Dict, consumer: StreamConsumer) -> None:
        self.cluster_centers_ = np.asarray(state["centers"])
        self.counts_ = np.asarray(state["counts"])

    def to_estimator(self, comm=None):
        """A servable fitted :class:`~heat_tpu.cluster.KMeans` (the
        ``save_model``/registry kinds are the batch estimators)."""
        from ..cluster import KMeans

        if self.cluster_centers_ is None:
            raise RuntimeError("fit_stream must run before to_estimator")
        est = KMeans(n_clusters=self.n_clusters, init="random", max_iter=1)
        est._cluster_centers = DNDarray.from_dense(
            jnp.asarray(self.cluster_centers_, jnp.float32), None, None, comm
        )
        return est


class StreamingPCA(_OnlineEstimator):
    """Incremental PCA: the seed window's exact SVD initializes the
    basis; each window merges through the [S*V; window; correction]
    SVD update, tracking the running mean and per-feature M2 so the
    explained-variance ratio stays exact."""

    _what = "components"

    def __init__(self, n_components: int = 2, **kwargs):
        super().__init__(**kwargs)
        if n_components < 1:
            raise ValueError(f"n_components must be >= 1, got {n_components}")
        self.n_components = int(n_components)
        self.components_: Optional[np.ndarray] = None

    def _init_state(self, consumer: StreamConsumer) -> Dict:
        seed = consumer.peek(0)
        if seed is None:
            raise ValueError(
                "stream holds fewer committed rows than one full window; "
                "nothing to initialize from"
            )
        k = min(self.n_components, min(seed.shape))
        mean, m2, comps, svals, n = _ipca_init(jnp.asarray(seed, jnp.float32), k)
        # the seed window IS the first consumed window: offset advances
        return {
            "mean": mean, "m2": m2, "components": comps,
            "singular_values": svals, "n_seen": n,
            "offset": consumer.window_rows,
        }

    def _update_state(self, dev: Dict, xw) -> Dict:
        k = int(np.asarray(dev["components"]).shape[0])
        mean, m2, comps, svals, n, shift = _ipca_update(
            jnp.asarray(xw, jnp.float32),
            jnp.asarray(dev["mean"], jnp.float32),
            jnp.asarray(dev["m2"], jnp.float32),
            jnp.asarray(dev["components"], jnp.float32),
            jnp.asarray(dev["singular_values"], jnp.float32),
            jnp.asarray(dev["n_seen"], jnp.float32),
            k,
        )
        return {
            "mean": mean, "m2": m2, "components": comps,
            "singular_values": svals, "n_seen": n, "__shift": shift,
        }

    def _ingest_state(self, state: Dict, consumer: StreamConsumer) -> None:
        self.mean_ = np.asarray(state["mean"])
        self.m2_ = np.asarray(state["m2"])
        self.components_ = np.asarray(state["components"])
        self.singular_values_ = np.asarray(state["singular_values"])
        self.n_seen_ = float(np.asarray(state["n_seen"]))

    def to_estimator(self, comm=None):
        """A servable fitted :class:`~heat_tpu.decomposition.PCA`."""
        from ..decomposition import PCA

        if self.components_ is None:
            raise RuntimeError("fit_stream must run before to_estimator")
        k = self.components_.shape[0]
        denom = max(self.n_seen_ - 1.0, 1.0)
        ev = (self.singular_values_.astype(np.float64) ** 2) / denom
        total_var = float(self.m2_.astype(np.float64).sum()) / denom
        ratio = ev / max(total_var, 1e-30)
        as_dnd = lambda a: DNDarray.from_dense(jnp.asarray(a, jnp.float32), None, None, comm)
        est = PCA(n_components=k, svd_solver="full")
        est.mean_ = as_dnd(self.mean_)
        est.components_ = as_dnd(self.components_)
        est.singular_values_ = as_dnd(self.singular_values_)
        est.explained_variance_ = as_dnd(ev)
        est.explained_variance_ratio_ = as_dnd(ratio)
        est._tevr = float(ratio.sum())
        est.n_components_ = int(k)
        return est


class StreamingLasso(_OnlineEstimator):
    """SGD (proximal-gradient / ISTA) Lasso over supervised stream rows
    ``[x_0 .. x_{f-1}, y]`` (target in the LAST column); one thresholded
    gradient step per window, intercept unpenalized like the batch
    coordinate-descent fit."""

    _what = "theta"

    def __init__(self, lam: float = 0.1, lr: float = 0.05, **kwargs):
        super().__init__(**kwargs)
        if lr <= 0:
            raise ValueError(f"lr must be > 0, got {lr}")
        self.lam = float(lam)
        self.lr = float(lr)
        self.theta_: Optional[np.ndarray] = None

    def _init_state(self, consumer: StreamConsumer) -> Dict:
        f = consumer.n_features
        if f is None:
            seed = consumer.peek(0)
            if seed is None:
                raise ValueError(
                    "stream holds fewer committed rows than one full window; "
                    "nothing to initialize from"
                )
            f = seed.shape[1]
        if f < 2:
            raise ValueError(
                "StreamingLasso rows are [features..., target]; need >= 2 columns"
            )
        theta = jnp.zeros((int(f), 1), jnp.float32)  # intercept + (f-1) weights
        return {"theta": theta, "offset": 0}

    def _update_state(self, dev: Dict, xw) -> Dict:
        theta, shift = _ista_update(
            jnp.asarray(xw, jnp.float32),
            jnp.asarray(dev["theta"], jnp.float32),
            jnp.float32(self.lam),
            jnp.float32(self.lr),
        )
        return {"theta": theta, "__shift": shift}

    def _ingest_state(self, state: Dict, consumer: StreamConsumer) -> None:
        self.theta_ = np.asarray(state["theta"])

    def to_estimator(self, comm=None):
        """A servable fitted :class:`~heat_tpu.regression.Lasso`."""
        from ..regression import Lasso

        if self.theta_ is None:
            raise RuntimeError("fit_stream must run before to_estimator")
        est = Lasso(lam=self.lam, max_iter=1)
        est._Lasso__theta = DNDarray.from_dense(
            jnp.asarray(self.theta_, jnp.float32).reshape(-1, 1), None, None, comm
        )
        return est
