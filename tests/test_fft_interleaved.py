"""Interleaved-minor 3-D FFT paths (r5 headline): the one-dot-per-stage
real transform, the complex-input engine behind fftn->filter->ifftn
chains, and the conj-trick real ifftn — all against numpy across shapes
and norms.  The representation invariant (no materialized (..., 2)
tensor, no index-grid gathers) is what keeps the 512^3 transform at
16.7 GB scheduled instead of 43.1 (docs/round5_notes.md).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.fft import _planar as P

SHAPES = [(32, 16, 24), (17, 9, 13), (8, 8, 8), (2, 3, 2)]
NORMS = [None, "ortho", "forward"]


def _np_norm(norm):
    return norm


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("norm", NORMS)
def test_rfft3_matches_numpy(shape, norm):
    rng = np.random.default_rng(1)
    x = rng.standard_normal(shape).astype(np.float32)
    re, im = jax.jit(lambda v: P.real_fftn(v, [0, 1, 2], norm))(jnp.asarray(x))
    got = np.asarray(re) + 1j * np.asarray(im)
    want = np.fft.fftn(x, norm=_np_norm(norm))
    rel = np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-30)
    assert rel < 5e-5, (shape, norm, rel)


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("inverse", [False, True])
def test_cfft3_matches_numpy(shape, inverse):
    rng = np.random.default_rng(2)
    x = rng.standard_normal(shape).astype(np.float32)
    y = rng.standard_normal(shape).astype(np.float32)
    re, im = jax.jit(lambda a, b: P.cfft3_interleaved(a, b, inverse, None))(
        jnp.asarray(x), jnp.asarray(y)
    )
    got = np.asarray(re) + 1j * np.asarray(im)
    fn = np.fft.ifftn if inverse else np.fft.fftn
    want = fn(x + 1j * y)
    rel = np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-30)
    assert rel < 5e-5, (shape, inverse, rel)


def test_fftn_ifftn_round_trip_planar():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((24, 12, 18)).astype(np.float32)
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        f = ht.fft.fftn(ht.array(x))
        assert f._planar is not None
        b = ht.fft.ifftn(f)  # complex planar input -> cfft3 engine
        got = np.asarray(b.numpy())
        np.testing.assert_allclose(got.real, x, atol=6e-4)
        assert np.abs(got.imag).max() < 6e-4
        # real ifftn (conj trick)
        bi = ht.fft.ifftn(ht.array(x))
        want_bi = np.fft.ifftn(x)
        np.testing.assert_allclose(
            np.asarray(bi.numpy()), want_bi,
            atol=1e-4 * max(np.abs(want_bi).max(), 1e-3),
        )
    finally:
        os.environ.pop("HEAT_TPU_PLANAR", None)


def test_norms_compose_through_round_trip():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((16, 10, 14)).astype(np.float32)
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        for norm in NORMS:
            f = ht.fft.fftn(ht.array(x), norm=norm)
            b = ht.fft.ifftn(f, norm=norm)
            np.testing.assert_allclose(np.asarray(b.numpy()).real, x, atol=6e-4)
    finally:
        os.environ.pop("HEAT_TPU_PLANAR", None)


@pytest.mark.parametrize("shape", [(16, 12, 20), (9, 7, 13)])
def test_rfftn_irfftn_interleaved(shape):
    rng = np.random.default_rng(7)
    x = rng.standard_normal(shape).astype(np.float32)
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        f = ht.fft.rfftn(ht.array(x))
        want = np.fft.rfftn(x)
        sc = np.abs(want).max()
        np.testing.assert_allclose(np.asarray(f.numpy()), want, atol=1e-4 * sc, rtol=1e-3)
        b = ht.fft.irfftn(f)
        np.testing.assert_allclose(np.asarray(b.numpy()), np.fft.irfftn(want), atol=6e-4)
        # ARBITRARY (non-Hermitian-consistent) half input must still match
        # numpy's ifft-then-extend order (the engine extends first with the
        # rev-compensated rule, which is algebraically identical)
        m2 = shape[2] // 2 + 1
        carr = (
            rng.standard_normal((shape[0], shape[1], m2))
            + 1j * rng.standard_normal((shape[0], shape[1], m2))
        ).astype(np.complex64)
        got = ht.fft.irfftn(ht.array(carr))
        want2 = np.fft.irfftn(carr)
        np.testing.assert_allclose(
            np.asarray(got.numpy()), want2,
            atol=2e-5 * max(1.0, np.abs(carr).max()), rtol=1e-3,
        )
    finally:
        os.environ.pop("HEAT_TPU_PLANAR", None)


@pytest.mark.parametrize("shape", [(24, 18), (13, 9), (8, 8)])
def test_2d_engine_all_kinds(shape):
    rng = np.random.default_rng(11)
    x = rng.standard_normal(shape).astype(np.float32)
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        f = ht.fft.fft2(ht.array(x))
        want = np.fft.fft2(x)
        np.testing.assert_allclose(
            np.asarray(f.numpy()), want, atol=1e-4 * np.abs(want).max(), rtol=1e-3
        )
        b = ht.fft.ifft2(f)
        np.testing.assert_allclose(np.asarray(b.numpy()).real, x, atol=6e-4)
        rf = ht.fft.rfft2(ht.array(x))
        wrf = np.fft.rfft2(x)
        np.testing.assert_allclose(
            np.asarray(rf.numpy()), wrf, atol=1e-4 * np.abs(wrf).max(), rtol=1e-3
        )
        rb = ht.fft.irfft2(rf)
        np.testing.assert_allclose(np.asarray(rb.numpy()), np.fft.irfft2(wrf), atol=6e-4)
        m1 = shape[1] // 2 + 1
        carr = (
            rng.standard_normal((shape[0], m1))
            + 1j * rng.standard_normal((shape[0], m1))
        ).astype(np.complex64)
        got = ht.fft.irfft2(ht.array(carr))
        np.testing.assert_allclose(
            np.asarray(got.numpy()), np.fft.irfft2(carr),
            atol=3e-5 * max(1.0, np.abs(carr).max()), rtol=1e-3,
        )
    finally:
        os.environ.pop("HEAT_TPU_PLANAR", None)


@pytest.mark.parametrize("shape", [(12, 10, 9), (8, 6)])
@pytest.mark.parametrize("norm", NORMS)
def test_hfftn_ihfftn_engine(shape, norm):
    rng = np.random.default_rng(13)
    x = rng.standard_normal(shape).astype(np.float32)
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        got = ht.fft.ihfftn(ht.array(x), norm=norm)
        want = np.fft.ihfft(x, axis=-1, norm=norm)
        for ax in range(len(shape) - 1):
            want = np.fft.ifft(want, axis=ax, norm=norm)
        np.testing.assert_allclose(np.asarray(got.numpy()), want, atol=2e-5, rtol=1e-3)

        m = shape[-1]
        carr = (
            rng.standard_normal(shape[:-1] + (m,))
            + 1j * rng.standard_normal(shape[:-1] + (m,))
        ).astype(np.complex64)
        goth = ht.fft.hfftn(ht.array(carr), norm=norm)
        wanth = carr.copy()
        for ax in range(len(shape) - 1):
            wanth = np.fft.fft(wanth, axis=ax, norm=norm)
        wanth = np.fft.hfft(wanth, axis=-1, norm=norm)
        sc = max(np.abs(wanth).max(), 1.0)
        np.testing.assert_allclose(
            np.asarray(goth.numpy()), wanth, atol=2e-4 * sc, rtol=1e-3
        )
    finally:
        os.environ.pop("HEAT_TPU_PLANAR", None)


def test_env_gate_and_fallback_agree():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((12, 8, 10)).astype(np.float32)
    fast = jax.jit(lambda v: P.real_fftn(v, [0, 1, 2], None))(jnp.asarray(x))
    os.environ["HEAT_TPU_FFT_INTERLEAVED"] = "0"
    try:
        slow = jax.jit(lambda v: P.real_fftn(v, [0, 1, 2], None))(jnp.asarray(x))
    finally:
        del os.environ["HEAT_TPU_FFT_INTERLEAVED"]
    for a, b in zip(fast, slow):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3, rtol=1e-4)


def test_bad_precision_env_is_diagnostic():
    os.environ["HEAT_TPU_FFT_PRECISION"] = "hi"
    try:
        with pytest.raises(ValueError, match="HEAT_TPU_FFT_PRECISION"):
            P._interleaved_precision()
    finally:
        del os.environ["HEAT_TPU_FFT_PRECISION"]
