"""Distributed lasso regularization-path demo (analog of examples/lasso/demo.py).

Loads the bundled diabetes dataset as split-0 DNDarrays, sweeps the
regularization strength, and fits the coordinate-descent Lasso at each
value; every dot product in the descent is a sharded reduction over the
mesh.  Saves the regularization-path plot next to this script when
matplotlib is available.
"""

import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import numpy as np

import heat_tpu as ht
from heat_tpu.regression import Lasso

import plotfkt


def main() -> None:
    X = ht.load_hdf5(ht.datasets.path("diabetes.h5"), dataset="x", split=0)
    y = ht.load_hdf5(ht.datasets.path("diabetes.h5"), dataset="y", split=0)

    # normalize features to unit second moment (as the reference demo does)
    X = X / ht.sqrt(ht.mean(X**2, axis=0))

    lambdas = np.logspace(0, 4, 10) / 10
    theta_path = []
    total_iters = 0
    t0 = time.perf_counter()
    for lam in lambdas:
        estimator = Lasso(lam=float(lam), max_iter=100)
        estimator.fit(X, y)
        total_iters += int(estimator.n_iter or 0)
        theta = estimator.theta.numpy().ravel()
        theta_path.append(theta)
        nnz = int((np.abs(theta[1:]) > 1e-10).sum())
        print(f"lambda={lam:8.2f}: {nnz:2d} active features, |theta|_1={np.abs(theta[1:]).sum():.3f}")
    sweep_s = time.perf_counter() - t0
    # one-line observability summary over the whole path sweep
    print(ht.telemetry.summary_line(total_iters / sweep_s if sweep_s > 0 else None))

    # drop the intercept row, features x lambdas
    theta_lasso = np.stack(theta_path).T[1:, :]
    plotfkt.plot_lasso_path(lambdas, theta_lasso, out="lasso_path.png")


if __name__ == "__main__":
    main()
