"""numpy.linalg parity extensions beyond the reference's linalg set.

The reference implements det/inv/qr/svd/solve_triangular and leaves the
rest of numpy.linalg uncovered; these close the block.  Everything runs
on the dense global view (GSPMD distributes the batched/matmul parts);
`eig`/`eigvals` have no TPU kernel in XLA and run on the in-process CPU
backend.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..dndarray import DNDarray

__all__ = [
    "cholesky",
    "cond",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "lstsq",
    "matrix_power",
    "matrix_rank",
    "multi_dot",
    "pinv",
    "slogdet",
    "solve",
    "tensorinv",
    "tensorsolve",
]


def _d(x):
    if isinstance(x, DNDarray):
        d = x._dense()
        if not jnp.issubdtype(d.dtype, jnp.inexact):
            d = d.astype(jnp.float32)
        return d
    return jnp.asarray(x)


def _ref(*xs):
    for x in xs:
        if isinstance(x, DNDarray):
            return x
    return None


def _wrap(result, *operands):
    from ..napi import _auto_split

    ref = _ref(*operands)
    if ref is None:
        return DNDarray.from_dense(result, None, None, None)
    return DNDarray.from_dense(result, _auto_split(result, ref), ref.device, ref.comm)


def _on_cpu(fn, *arrays):
    """Run fn on the in-process CPU backend (for factorizations without a
    TPU kernel: nonsymmetric eig)."""
    cpu = jax.devices("cpu")[0]
    moved = [jax.device_put(a, cpu) for a in arrays]
    return fn(*moved)


def cholesky(a):
    """Lower-triangular Cholesky factor of an SPD matrix."""
    return _wrap(jnp.linalg.cholesky(_d(a)), a)


def cond(x, p=None):
    """Condition number with respect to norm ``p``."""
    return _wrap(jnp.linalg.cond(_d(x), p=p), x)


def eigh(a, UPLO: str = "L"):
    """Eigendecomposition of a symmetric/Hermitian matrix."""
    w, v = jnp.linalg.eigh(_d(a), UPLO=UPLO)
    return _wrap(w, a), _wrap(v, a)


def eigvalsh(a, UPLO: str = "L"):
    return _wrap(jnp.linalg.eigvalsh(_d(a), UPLO=UPLO), a)


def eig(a):
    """General eigendecomposition (no TPU kernel in XLA: runs on the
    in-process CPU backend; complex output)."""
    w, v = _on_cpu(jnp.linalg.eig, _d(a))
    return _wrap(w, a), _wrap(v, a)


def eigvals(a):
    return _wrap(_on_cpu(jnp.linalg.eigvals, _d(a)), a)


def lstsq(a, b, rcond=None):
    """Least-squares solve; returns (x, residuals, rank, singular values).

    ``rank`` is a lazy 0-d array — no host sync is forced inside the call
    (one full link round-trip on a tunneled chip); use ``int(rank)`` to
    materialize it."""
    x, resid, rank, sv = jnp.linalg.lstsq(_d(a), _d(b), rcond=rcond)
    ref = _ref(a, b)
    return (_wrap(x, ref), _wrap(resid, ref), _wrap(rank, ref), _wrap(sv, ref))


def matrix_power(a, n: int):
    return _wrap(jnp.linalg.matrix_power(_d(a), n), a)


def matrix_rank(a, tol=None):
    """Matrix rank as a lazy 0-d array (no forced host sync; ``int()`` it
    to materialize)."""
    return _wrap(jnp.linalg.matrix_rank(_d(a), rtol=None if tol is None else tol), a)


def multi_dot(arrays):
    """Chained matmul with optimal association order."""
    dense = [_d(a) for a in arrays]
    return _wrap(jnp.linalg.multi_dot(dense), *list(arrays))


def pinv(a, rcond=None, hermitian: bool = False):
    """Moore-Penrose pseudo-inverse."""
    return _wrap(jnp.linalg.pinv(_d(a), rtol=rcond, hermitian=hermitian), a)


def slogdet(a):
    """Sign and log|det|."""
    sign, logabs = jnp.linalg.slogdet(_d(a))
    return _wrap(sign, a), _wrap(logabs, a)


def solve(a, b):
    """Solve the linear system a x = b."""
    return _wrap(jnp.linalg.solve(_d(a), _d(b)), _ref(a, b))


def tensorinv(a, ind: int = 2):
    return _wrap(jnp.linalg.tensorinv(_d(a), ind=ind), a)


def tensorsolve(a, b, axes=None):
    return _wrap(jnp.linalg.tensorsolve(_d(a), _d(b), axes=axes), _ref(a, b))
