"""Complex-number operations, analog of heat/core/complex_math.py.

Planar-backed complex arrays (``DNDarray._planar``, produced by the fft
layer on complex-less accelerators) get plane-level fast paths: the result
is computed from the (re, im) planes ON the device mesh instead of
materializing a host complex array first."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real", "real_if_close"]


def _plane_result(x: DNDarray, plane) -> DNDarray:
    """Wrap one real plane (already padded, canonically placed)."""
    from . import types

    return DNDarray(
        plane, x.shape, types.canonical_heat_type(plane.dtype), x.split, x.device, x.comm
    )


def angle(x, deg: bool = False, out=None):
    """Argument of complex values (complex_math.py:15)."""
    if isinstance(x, DNDarray) and x._planar is not None and out is None:
        re, im = x._planar
        a = jnp.arctan2(im, re)
        return _plane_result(x, jnp.rad2deg(a) if deg else a)
    return _local_op(lambda a: jnp.angle(a, deg=deg), x, out, no_cast=True)


def conjugate(x, out=None):
    """Complex conjugate (complex_math.py:48)."""
    if isinstance(x, DNDarray) and x._planar is not None and out is None:
        re, im = x._planar
        return DNDarray.from_planar(re, -im, x.shape, x.split, x.device, x.comm)
    return _local_op(jnp.conjugate, x, out, no_cast=True)


conj = conjugate


def imag(x, out=None):
    """Imaginary part (complex_math.py:78)."""
    if isinstance(x, DNDarray) and x._planar is not None and out is None:
        return _plane_result(x, x._planar[1])
    return _local_op(jnp.imag, x, out, no_cast=True)


def real(x, out=None):
    """Real part (complex_math.py:98)."""
    if isinstance(x, DNDarray) and x._planar is not None and out is None:
        return _plane_result(x, x._planar[0])
    return _local_op(jnp.real, x, out, no_cast=True)


def real_if_close(x, tol: float = 100.0):
    """Return the real part when all imaginary components are within
    ``tol`` machine epsilons of zero (numpy extension beyond the
    reference's checklist).  The all-close check is a global reduction."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    from . import types

    if not types.heat_type_is_complexfloating(x.dtype):
        return x
    import numpy as np

    if tol > 1:  # numpy semantics: tol > 1 scales machine eps, else absolute
        tol = tol * float(np.finfo(x._dense().real.dtype).eps)
    if bool(jnp.all(jnp.abs(jnp.imag(x._dense())) < tol)):
        return real(x)
    return x
