"""Device-trace hooks (Xprof/perfetto) — the telemetry layer's bridge to
``jax.profiler``.

The reference instruments benchmarks with the external ``perun``
runtime/energy monitor (``@monitor()`` decorators, benchmarks/cb/
linalg.py:4,7); the library itself has no tracing (SURVEY.md §5).  The
TPU-native equivalent is jax.profiler: Xprof/perfetto traces with named
regions so collectives show up attributed to framework ops.  Host-side
structured spans live in :mod:`heat_tpu.telemetry.spans`; this module
starts/stops the *device* trace those spans annotate.

Previously ``heat_tpu.utils.profiling`` (still importable there as a
backward-compatible alias).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, Optional

import jax

__all__ = ["annotate", "monitor", "start_trace", "stop_trace", "trace"]


def start_trace(log_dir: str) -> None:
    """Begin an Xprof/perfetto trace (analog of starting a perun run)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None):
    """Context manager tracing the enclosed region."""
    if log_dir is None:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: str):
    """Named trace region; nests into the XLA timeline."""
    return jax.profiler.TraceAnnotation(name)


def monitor(name: Optional[str] = None):
    """Decorator measuring wall time of a benchmark function — the drop-in
    analog of perun's ``@monitor()`` (benchmarks/cb/linalg.py:7).  Blocks on
    the function's jax outputs so async dispatch doesn't hide device time.
    ``last_runtime`` is set even when the wrapped function raises (the
    elapsed time up to the raise), so a failed call can never leave a
    stale measurement from the previous call behind.
    """

    def deco(fn: Callable):
        label = name or fn.__name__

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                with jax.profiler.TraceAnnotation(label):
                    out = fn(*args, **kwargs)
                    out = jax.block_until_ready(out) if _is_jax_tree(out) else out
                return out
            finally:
                wrapped.last_runtime = time.perf_counter() - t0

        wrapped.last_runtime = None
        return wrapped

    return deco


def _is_jax_tree(x) -> bool:
    leaves = jax.tree_util.tree_leaves(x)
    return any(isinstance(l, jax.Array) for l in leaves)
