"""User-level fusion: compile a DNDarray -> DNDarray function to one XLA
program.

SURVEY.md build-plan decision 2: the library is eager (every op dispatches
a cached executable) so sklearn-style loops just work, "offer ht.jit-style
fusion on top".  Since the dispatch layer landed (core/dispatch.py), the
eager path itself routes ops through cached executables and lazily fuses
element-wise chains by DEFAULT — ``ht.jit`` remains the explicit tool for
fusing ACROSS non-elementwise boundaries (reductions, matmuls, whole
pipelines) into one program.  ``ht.jit`` traces the wrapped function
once per (structure, DNDarray shapes/dtypes/splits, static values), so a
whole pipeline of ops — elementwise chains, reductions, linalg — fuses
into a single device program with one dispatch.  On a tunneled chip each
eager dispatch is a link round-trip, so fusing an n-op pipeline is
roughly an n-fold latency win; on any chip XLA can fuse across the op
boundaries the eager layer keeps.

Semantics and limits (the usual jax.jit contract, surfaced at this level):

* DNDarray arguments become traced values; everything else (ints, strings,
  shapes...) is STATIC — a new compilation per distinct value.
* The function must be functional over its DNDarray inputs.  Host syncs
  (``float(x)``, ``x.numpy()``, data-dependent Python control flow) raise
  jax's ConcretizationTypeError inside.  In-place updates to an ARGUMENT
  (``a += 1``, ``a[0] = ...``) do NOT raise — they rebind the traced
  value, so the result is correct but the caller's array is left
  unmodified (under eager execution the caller's array would mutate).
  Return what you change.
* Returned DNDarrays keep the split/device/comm they were constructed
  with inside the trace.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax

from .dndarray import DNDarray

__all__ = ["jit"]


class _ASpec:
    """Hashable stand-in for a DNDarray argument in the cache key."""

    __slots__ = ("shape", "dtype", "split", "device", "comm", "pshape", "pdtype")

    def __init__(self, x: DNDarray):
        self.shape = x.shape
        self.dtype = x.dtype
        self.split = x.split
        self.device = x.device
        self.comm = x.comm
        # metadata-only: a pending fusion chain must not be forced just
        # to build a cache key (core/dispatch.py)
        self.pshape = x._padded_shape
        self.pdtype = str(x._padded_dtype)

    def _key(self):
        return (
            self.shape, self.dtype, self.split, self.device, self.comm,
            self.pshape, self.pdtype,
        )

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, _ASpec) and self._key() == other._key()

    def rebuild(self, arr) -> DNDarray:
        return DNDarray(arr, self.shape, self.dtype, self.split, self.device, self.comm)


def jit(fn: Callable = None, **jit_kwargs) -> Callable:
    """Fuse a function over DNDarrays into one compiled program.

    ::

        @ht.jit
        def step(x, w):
            return ht.tanh(x @ w) - ht.mean(x, axis=0)

        y = step(a, b)     # one device dispatch, however many ops inside
    """
    if fn is None:
        return lambda f: jit(f, **jit_kwargs)

    # argument-indexed jax.jit options would be interpreted against the
    # internal flattened array-leaf signature, not the user's parameters —
    # silently donating/pinning the wrong argument.  Reject them.
    _positional = {
        "static_argnums", "static_argnames", "donate_argnums",
        "donate_argnames", "in_shardings", "out_shardings",
    }
    bad = _positional.intersection(jit_kwargs)
    if bad:
        raise TypeError(
            f"ht.jit does not accept argument-indexed jax.jit options "
            f"({sorted(bad)}): indices would refer to the internal flattened "
            f"signature, not your function's parameters"
        )

    cache = {}

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any):
        is_d = lambda x: isinstance(x, DNDarray)
        flat, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=is_d)
        arrays = [x.larray_padded for x in flat if is_d(x)]
        key_leaves = tuple(_ASpec(x) if is_d(x) else ("static", x) for x in flat)
        try:
            key = (treedef, key_leaves)
            hash(key)
        except TypeError:
            raise TypeError(
                "ht.jit arguments must be DNDarrays or hashable statics; "
                "got an unhashable non-array argument"
            ) from None

        from . import dispatch as _dispatch

        entry = cache.get(key)
        if entry is None:
            out_side = {}

            def inner(*arrs):
                it = iter(arrs)
                rebuilt = [
                    k.rebuild(next(it)) if isinstance(k, _ASpec) else k[1]
                    for k in key_leaves
                ]
                a2, k2 = jax.tree_util.tree_unflatten(treedef, rebuilt)
                out = fn(*a2, **k2)
                out_flat, out_tree = jax.tree_util.tree_flatten(out, is_leaf=is_d)
                out_side["tree"] = out_tree
                out_side["meta"] = [
                    (x.shape, x.dtype, x.split, x.device, x.comm) if is_d(x) else None
                    for x in out_flat
                ]
                return tuple(
                    x.larray_padded if is_d(x) else x for x in out_flat
                )

            entry = (jax.jit(inner, **jit_kwargs), out_side)
            cache[key] = entry

        compiled, out_side = entry
        # user-level fusion rides the same accounting as the transparent
        # dispatch layer: one compiled launch, however many ops inside
        _dispatch.record_external_dispatch()
        out_arrays = compiled(*arrays)
        rebuilt_out = [
            DNDarray(arr, *meta) if meta is not None else arr
            for arr, meta in zip(out_arrays, out_side["meta"])
        ]
        return jax.tree_util.tree_unflatten(out_side["tree"], rebuilt_out)

    return wrapper
