"""Fleet-scale serving: router failure modes, AOT cold-start cache,
readiness split, autoscaler hysteresis.

The acceptance properties (ISSUE 13): a replica kill under live load
costs zero client-visible failures (bounded-retry failover absorbs the
loss), the typed 503 fires only when NO replica can take the model,
the per-replica circuit breaker ejects/half-open-probes/readmits, a
draining replica receives no new work while in-flight work finishes,
and a corrupt or fingerprint-stale AOT artifact falls back to a fresh
compile (never a wrong program, never an error).

Router failure modes are driven against in-process *scriptable* fake
replicas (real sockets, deterministic failures); one subprocess test
exercises the real ``python -m heat_tpu.fleet.replica`` lifecycle
(spawn -> prewarm from the AOT cache -> ready -> SIGTERM drain ->
exit 0).
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import aot_cache, dispatch
from heat_tpu.fleet import FleetAutoscaler, FleetRouter, LocalReplicaSet
from heat_tpu.resilience import NoReplicaError, OverloadedError
from heat_tpu.resilience.atomic import atomic_write, write_checksum
from heat_tpu.serving.admission import AdmissionController
from heat_tpu.telemetry import server as tserver

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


# ----------------------------------------------------------------------
# scriptable fake replica
# ----------------------------------------------------------------------
class FakeReplica:
    """A real HTTP server speaking the replica protocol, with scripted
    failure modes: ``fail_500`` (predicts answer 500), ``die_mid_request``
    (accept the request, then kill the connection — the mid-request
    crash), ``delay`` (slow predicts), plus live readiness state."""

    def __init__(self, models=("km",), delay=0.0):
        self.models = list(models)
        self.ready = True
        self.state = "ready"
        self.fail_500 = False
        self.die_mid_request = False
        self.delay = float(delay)
        self.served = 0
        outer = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code, doc, headers=None):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    self._send(
                        200 if outer.ready else 503,
                        {"ready": outer.ready, "state": outer.state,
                         "models": outer.models},
                    )
                elif self.path.startswith("/v1/models"):
                    self._send(200, {"models": {m: {} for m in outer.models}})
                else:
                    self._send(404, {"error": "unknown route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(n) or b"{}")
                if outer.delay:
                    time.sleep(outer.delay)
                if outer.die_mid_request:
                    # the mid-request kill: request read, no response
                    self.connection.close()
                    return
                if outer.fail_500:
                    self._send(500, {"error": "scripted failure"})
                    return
                if doc.get("model") not in outer.models:
                    self._send(404, {"error": "unknown model"})
                    return
                outer.served += 1
                self._send(200, {
                    "model": doc["model"],
                    "predictions": [0] * len(doc.get("inputs", [0])),
                    "trace_id": doc.get("trace_id"),
                })

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-replica", daemon=True
        )
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


@pytest.fixture
def replicas():
    made = []

    def make(**kw):
        r = FakeReplica(**kw)
        made.append(r)
        return r

    yield make
    for r in made:
        r.close()


@pytest.fixture
def make_router():
    routers = []

    def make(*urls, **kw):
        kw.setdefault("health_period_s", 30.0)  # tests poll explicitly
        kw.setdefault("cb_cooldown_s", 0.3)
        router = FleetRouter(replicas=urls, **kw)
        routers.append(router)
        router.poll_health()
        return router

    yield make
    for router in routers:
        router.close()


def predict(router, model="km", rows=1, **extra):
    doc = {"model": model, "inputs": [[1.0, 2.0]] * rows}
    doc.update(extra)
    return router.handle("POST", "/v1/predict", json.dumps(doc).encode())


# ----------------------------------------------------------------------
# routing, affinity, failover
# ----------------------------------------------------------------------
class TestRouterRouting:
    def test_predict_routes_and_stamps_trace_id(self, replicas, make_router):
        r = replicas()
        router = make_router(r.url)
        status, out, ctype, _ = predict(router, rows=3)
        assert status == 200
        doc = json.loads(out)
        assert doc["predictions"] == [0, 0, 0]
        assert doc["trace_id"]  # the router stamped one for stitching

    def test_model_affinity_prefers_one_replica(self, replicas, make_router):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url)
        for _ in range(12):
            assert predict(router)[0] == 200
        # rendezvous affinity: an idle fleet serves a model from ONE replica
        assert sorted([a.served, b.served]) == [0, 12]

    def test_kill_mid_request_fails_over_zero_client_failures(
        self, replicas, make_router
    ):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url)
        assert predict(router)[0] == 200
        fav = a if a.served else b
        fav.die_mid_request = True  # accepts the request, kills the socket
        for _ in range(6):
            status, out, _, _ = predict(router)
            assert status == 200, out  # failover absorbed every loss
        # some requests failed over; once the breaker ejects the dying
        # replica the rest route clean without needing one
        assert router.statusz()["failovers"] >= 1

    def test_connect_error_fails_over(self, replicas, make_router):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url)
        assert predict(router)[0] == 200
        fav = a if a.served else b
        other = b if fav is a else a
        fav.close()  # socket gone: connection refused
        before = other.served
        for _ in range(5):
            assert predict(router)[0] == 200
        assert other.served == before + 5

    def test_all_replicas_down_typed_503_with_retry_after(
        self, replicas, make_router
    ):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url, retries=2)
        a.close()
        b.close()
        status, out, _, headers = predict(router)
        assert status == 503
        assert "Retry-After" in headers
        # after a health sweep the verdict is the typed no-replica shed
        router.poll_health()
        status, out, _, headers = predict(router)
        doc = json.loads(out)
        assert status == 503 and doc["cause"] == "no_replica"
        assert float(headers["Retry-After"]) > 0
        assert router.statusz()["no_replica_503"] >= 1

    def test_unknown_model_is_404_not_503(self, replicas, make_router):
        r = replicas(models=("km",))
        router = make_router(r.url)
        status, out, _, _ = predict(router, model="nope")
        assert status == 404
        assert "nope" in json.loads(out)["error"]

    def test_replica_404_learns_and_fails_over(self, replicas, make_router):
        # b hosts the model, a does not; a poll-less router learns from 404s
        a, b = replicas(models=()), replicas(models=("km",))
        router = make_router(a.url, b.url)
        for _ in range(4):
            status, _, _, _ = predict(router)
            assert status == 200
        assert b.served == 4

    def test_global_token_bucket_shed_429(self, replicas, make_router):
        r = replicas()
        router = make_router(r.url, rate=1.0, burst=2.0)
        codes = [predict(router)[0] for _ in range(6)]
        assert codes.count(200) >= 1 and 429 in codes
        status, out, _, headers = predict(router)
        if status == 429:
            assert float(headers["Retry-After"]) > 0
            assert json.loads(out)["cause"] == "quota"
        assert router.statusz()["shed"] >= 1

    def test_bounded_load_spills_past_the_favorite(self, replicas, make_router):
        a, b = replicas(delay=0.05), replicas(delay=0.05)
        router = make_router(a.url, b.url, load_factor=1.0)
        errs = []

        def client():
            for _ in range(4):
                status, *_ = predict(router)
                if status != 200:
                    errs.append(status)

        threads = [threading.Thread(target=client, daemon=True) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert a.served > 0 and b.served > 0  # pressure spilled past affinity

    def test_fleet_routes_and_stats(self, replicas, make_router):
        r = replicas()
        router = make_router(r.url)
        status, out, _, _ = router.handle("GET", "/fleet/healthz", None)
        assert status == 200 and json.loads(out)["ready_replicas"] == 1
        status, out, _, _ = router.handle("GET", "/fleet/statusz", None)
        doc = json.loads(out)
        assert doc["replicas"][0]["circuit"] == "closed"
        predict(router)
        sig = router.stats()
        assert sig["ready"] == 1 and sig["window_requests"] >= 1

    def test_router_http_front_door(self, replicas, make_router):
        r = replicas()
        router = make_router(r.url)
        body = json.dumps({"model": "km", "inputs": [[1.0, 2.0]]}).encode()
        req = urllib.request.Request(
            router.url + "/v1/predict", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.getcode() == 200
            assert json.load(resp)["predictions"] == [0]


class TestCircuitBreaker:
    def test_eject_half_open_readmit_cycle(self, replicas, make_router):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url, cb_failures=2, cb_cooldown_s=0.3)
        assert predict(router)[0] == 200
        fav = a if a.served else b

        def circuit(url):
            return {d["url"]: d["circuit"] for d in router.statusz()["replicas"]}[url]

        fav.fail_500 = True
        for _ in range(4):
            assert predict(router)[0] == 200  # failover keeps clients green
        assert circuit(fav.url) == "open"
        assert router.statusz()["cb_ejections"] >= 1
        # ejected: the broken replica sees no traffic at all
        before = fav.served
        for _ in range(4):
            assert predict(router)[0] == 200
        assert fav.served == before
        # heal + cooldown: ONE half-open probe readmits it
        fav.fail_500 = False
        time.sleep(0.35)
        assert predict(router)[0] == 200
        assert circuit(fav.url) == "closed"
        assert router.statusz()["cb_readmissions"] >= 1

    def test_failed_probe_reopens(self, replicas, make_router):
        a, b = replicas(), replicas()
        router = make_router(a.url, b.url, cb_failures=1, cb_cooldown_s=0.2)
        assert predict(router)[0] == 200
        fav = a if a.served else b
        fav.fail_500 = True
        assert predict(router)[0] == 200  # trips the breaker via failover
        time.sleep(0.25)
        assert predict(router)[0] == 200  # probe fails, re-opens, other serves
        circuit = {d["url"]: d["circuit"] for d in router.statusz()["replicas"]}
        assert circuit[fav.url] == "open"


class TestDrain:
    def test_drained_replica_gets_no_new_work_under_load(
        self, replicas, make_router
    ):
        a, b = replicas(delay=0.03), replicas(delay=0.03)
        router = make_router(a.url, b.url)
        assert predict(router)[0] == 200
        fav = a if a.served else b
        errs = []
        stop = threading.Event()

        def client():
            while not stop.is_set():
                status, *_ = predict(router)
                if status != 200:
                    errs.append(status)

        threads = [threading.Thread(target=client, daemon=True) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        router.drain_replica(fav.url)  # no NEW work from here on
        time.sleep(0.1)
        served_at_drain = fav.served
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errs  # zero client-visible failures through the drain
        assert fav.served <= served_at_drain + 3  # in-flight finished, no new stream

    def test_service_drain_finishes_inflight_work(self, tmp_path):
        # the replica-side half: a draining InferenceService answers
        # everything already admitted, then closes with zero abandons
        rng = np.random.default_rng(0)
        pts = rng.standard_normal((64, 6)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=5, random_state=0
        ).fit(ht.array(pts, split=0))
        d = str(tmp_path / "km")
        serving.save_model(km, d, version=1, name="km")
        svc = serving.InferenceService(max_delay_ms=5.0, max_batch=16)
        svc.load("km", d)
        svc.predict("km", pts[:2])
        results, errs = [], []

        def client():
            try:
                results.append(svc.predict("km", pts[:4], timeout=30))
            except BaseException as e:  # noqa: BLE001 - the assertion surface
                errs.append(e)

        threads = [threading.Thread(target=client, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.01)  # requests in the coalescer window
        assert svc.drain(timeout=10.0) is True
        for t in threads:
            t.join(timeout=10)
        assert not errs and len(results) == 4
        # drain() closes once everything admitted is answered, and close
        # lands in the declared terminal lifecycle state (the "replica"
        # machine in analysis/protocols.py: ... -> draining -> stopped)
        assert svc.state == "stopped"


# ----------------------------------------------------------------------
# readiness / liveness split
# ----------------------------------------------------------------------
class TestReadiness:
    def test_default_report_is_ready_idle(self):
        ready, doc = tserver.readiness_report()
        assert ready is True and doc["state"] == "idle"

    def test_provider_and_clear(self):
        tserver.set_readiness(lambda: (False, {"state": "warming"}))
        try:
            ready, doc = tserver.readiness_report()
            assert ready is False and doc["state"] == "warming"
            assert doc["ready"] is False
        finally:
            tserver.clear_readiness()
        assert tserver.readiness_report()[0] is True

    def test_broken_provider_reads_not_ready(self):
        def boom():
            raise RuntimeError("scripted")

        tserver.set_readiness(boom)
        try:
            ready, doc = tserver.readiness_report()
            assert ready is False and doc["state"] == "error"
            assert "scripted" in doc["error"]
        finally:
            tserver.clear_readiness()

    def test_clear_readiness_only_removes_own_provider(self):
        mine = lambda: (False, {"state": "draining"})  # noqa: E731
        theirs = lambda: (True, {"state": "ready"})  # noqa: E731
        tserver.set_readiness(mine)
        tserver.set_readiness(theirs)  # a successor took over
        tserver.clear_readiness(mine)  # must NOT clobber the successor
        try:
            assert tserver.readiness_report()[1]["state"] == "ready"
        finally:
            tserver.clear_readiness()

    def test_readyz_route_and_service_states(self, tmp_path):
        rng = np.random.default_rng(1)
        pts = rng.standard_normal((64, 6)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=5, random_state=0
        ).fit(ht.array(pts, split=0))
        d = str(tmp_path / "km")
        serving.save_model(km, d, version=1, name="km")
        svc = serving.InferenceService(max_delay_ms=1.0, max_batch=16)
        try:
            svc.load("km", d)
            svc.set_state("warming")
            url = svc.serve(0)
            with pytest.raises(urllib.request.HTTPError) as ei:
                urllib.request.urlopen(url + "/readyz", timeout=5)
            assert ei.value.code == 503
            doc = json.load(ei.value)
            assert doc["state"] == "warming" and doc["models"] == ["km"]
            # "idle" (liveness) and "warming" (readiness) are now distinct:
            h = svc.model_health("km")
            assert h["state"] == "warming" and h["status"] == "warming"
            assert h["healthy"] is True  # liveness unaffected
            svc.set_state("ready")
            doc = json.load(urllib.request.urlopen(url + "/readyz", timeout=5))
            assert doc["ready"] is True and doc["state"] == "ready"
            assert "misses" in doc["dispatch"]
        finally:
            svc.close()
            tserver.stop_server()

    def test_invalid_state_rejected(self):
        svc = serving.InferenceService()
        try:
            with pytest.raises(ValueError):
                svc.set_state("sleeping")
        finally:
            svc.close()


# ----------------------------------------------------------------------
# admission: queue-shed Retry-After from the measured drain rate
# ----------------------------------------------------------------------
class TestQueueRetryAfter:
    # the tenant rides the latency lane: its limit equals max_depth, so
    # the pre-QoS depth arithmetic below still holds exactly (the
    # standard lane caps at 80% of max_depth since the priority lanes)
    def test_cold_queue_shed_has_no_estimate(self):
        ac = AdmissionController(max_depth=4)
        ac.set_class("t", "latency")
        ac.admit("t", 4)
        with pytest.raises(OverloadedError) as ei:
            ac.admit("t", 2)
        assert ei.value.cause == "queue" and ei.value.retry_after_s is None

    def test_queue_shed_retry_after_tracks_drain_rate(self):
        ac = AdmissionController(max_depth=100)
        ac.set_class("t", "latency")
        # a steady drain: ~200 rows/s released over the window
        t0 = time.monotonic()
        ac.admit("t", 100)
        for _ in range(10):
            ac.release(10, "latency")
            time.sleep(0.02)
        rate = ac.drain_rate()
        assert rate > 0
        ac.admit("t", 100)  # depth back to 100
        with pytest.raises(OverloadedError) as ei:
            ac.admit("t", 50)
        got = ei.value.retry_after_s
        assert got is not None
        # excess = 100 + 50 - 100 = 50 rows at the measured rate
        assert got == pytest.approx(50.0 / rate, rel=0.5)
        assert 0.001 <= got <= 30.0
        del t0

    def test_release_prunes_window(self):
        ac = AdmissionController(max_depth=10)
        ac.admit("t", 1)
        ac.release(1)
        ac._drained.appendleft((time.monotonic() - 60.0, 1000))
        assert ac.drain_rate() < 500  # the stale entry fell out of the window


# ----------------------------------------------------------------------
# AOT executable cache
# ----------------------------------------------------------------------
@pytest.fixture
def aot_dir(tmp_path):
    d = str(tmp_path / "aot")
    prev = aot_cache.configure(d)
    yield d
    aot_cache.configure(prev)


def _dispatch_some(x=3.0):
    a = ht.array(np.full((16, 4), x, np.float32), split=0)
    b = ht.array(np.full((16, 4), 2.0, np.float32), split=0)
    return float(((a * b) + 1.0).sum().larray)


class TestAotCache:
    def test_artifact_roundtrip_across_cache_clear(self, aot_dir):
        dispatch.clear_cache()  # force a miss whatever ran before us
        s0 = aot_cache.stats()
        want = _dispatch_some()
        s1 = aot_cache.stats()
        assert s1["saves"] > s0["saves"]
        dispatch.clear_cache()  # a "fresh process" for the in-memory cache
        got = _dispatch_some()
        s2 = aot_cache.stats()
        assert got == want
        assert s2["hits"] > s1["hits"]  # loaded from disk, not compiled

    def test_corrupt_artifact_falls_back_and_heals(self, aot_dir):
        from heat_tpu.resilience.atomic import verify_checksum

        dispatch.clear_cache()
        want = _dispatch_some()
        files = [f for f in os.listdir(aot_dir) if f.endswith(".aotx")]
        assert files
        path = os.path.join(aot_dir, files[0])
        with open(path, "r+b") as f:
            f.seek(50)
            f.write(b"CORRUPTCORRUPT")
        s0 = aot_cache.stats()
        dispatch.clear_cache()
        assert _dispatch_some() == want  # fresh compile, right answer
        s1 = aot_cache.stats()
        assert s1["errors"] > s0["errors"]
        assert s1["saves"] > s0["saves"]  # dropped, recompiled, re-written
        assert verify_checksum(path) is True  # the healed artifact is whole

    def test_stale_fingerprint_recompiles(self, aot_dir):
        import pickle

        dispatch.clear_cache()
        want = _dispatch_some()
        files = [f for f in os.listdir(aot_dir) if f.endswith(".aotx")]
        path = os.path.join(aot_dir, files[0])
        with open(path, "rb") as f:
            doc = pickle.load(f)
        doc["fingerprint"] = "jax=0.0.0;backend=tpu;device=v9;n=4096"
        with atomic_write(path) as tmp:
            with open(tmp, "wb") as f:
                pickle.dump(doc, f)
        write_checksum(path)
        s0 = aot_cache.stats()
        dispatch.clear_cache()
        assert _dispatch_some() == want
        s1 = aot_cache.stats()
        assert s1["stale"] > s0["stale"]  # ignored, not an error

    def test_unstable_keys_are_refused_not_persisted(self, aot_dir):
        s0 = aot_cache.stats()
        out = dispatch.eager_apply(lambda x: x + 1, (np.ones((4,), np.float32),))
        assert float(np.asarray(out)[0]) == 2.0
        s1 = aot_cache.stats()
        assert s1["unkeyed"] > s0["unkeyed"]
        assert s1["saves"] == s0["saves"]  # a lambda key must never alias on disk

    def test_stable_key_deterministic_and_distinct(self):
        import jax.numpy as jnp

        key_a = ("apply", jnp.add, (), ((4, 4), np.dtype(np.float32), None))
        key_b = ("apply", jnp.multiply, (), ((4, 4), np.dtype(np.float32), None))
        assert aot_cache.stable_key(key_a) == aot_cache.stable_key(key_a)
        assert aot_cache.stable_key(key_a) != aot_cache.stable_key(key_b)
        assert aot_cache.stable_key(("x", lambda: 0)) is None

    def test_disarmed_cache_writes_nothing(self, tmp_path):
        assert not aot_cache.enabled() or aot_cache.stats()["directory"]
        prev = aot_cache.configure(None)
        try:
            s0 = aot_cache.stats()
            _dispatch_some(5.0)
            assert aot_cache.stats()["saves"] == s0["saves"]
        finally:
            aot_cache.configure(prev)


# ----------------------------------------------------------------------
# pre-warm manifest
# ----------------------------------------------------------------------
class TestPrewarm:
    @pytest.fixture
    def svc(self, tmp_path):
        rng = np.random.default_rng(2)
        pts = rng.standard_normal((64, 6)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=5, random_state=0
        ).fit(ht.array(pts, split=0))
        d = str(tmp_path / "km")
        serving.save_model(km, d, version=1, name="km")
        svc = serving.InferenceService(max_delay_ms=1.0, max_batch=16)
        svc.load("km", d)
        svc._test_pts = pts
        yield svc
        svc.close()

    def test_manifest_records_live_bucket_shapes(self, svc, tmp_path):
        pts = svc._test_pts
        for n in (1, 3, 9):
            svc.predict("km", pts[:n])
        path = str(tmp_path / "prewarm.json")
        doc = svc.export_prewarm_manifest(path)
        buckets = {e["bucket"] for e in doc["entries"]}
        assert buckets == {1, 4, 16}  # the pad-to-bucket shapes, not raw sizes
        assert all(e["model"] == "km" and e["features"] == 6 for e in doc["entries"])
        assert os.path.exists(path) and os.path.exists(path + ".crc32")
        assert svc.load_prewarm_manifest(path) == doc

    def test_prewarm_reaches_hit_rate_one_before_first_request(
        self, svc, tmp_path, aot_dir
    ):
        pts = svc._test_pts
        dispatch.clear_cache()  # the warm-up predicts must miss and save
        for n in (1, 3, 9):
            svc.predict("km", pts[:n])
        manifest = svc.export_prewarm_manifest()
        dispatch.clear_cache()  # fresh-replica simulation
        report = svc.prewarm(manifest)
        assert report["warmed"] == 3
        assert report["new_compiles"] == 0  # every program came off disk
        assert report["aot_hits"] >= 3
        s0 = dispatch.cache_stats()
        svc.predict("km", pts[:3])  # the first "real" request
        s1 = dispatch.cache_stats()
        assert s1["misses"] == s0["misses"]  # zero compiles after warm
        assert s1["hits"] > s0["hits"]

    def test_prewarm_skips_unknown_models(self, svc):
        report = svc.prewarm(
            {"entries": [{"model": "ghost", "bucket": 4, "features": 6}]}
        )
        assert report == {
            "warmed": 0, "skipped": 1, "new_compiles": 0, "aot_hits": 0,
        }


# ----------------------------------------------------------------------
# autoscaler hysteresis (stubbed actuator)
# ----------------------------------------------------------------------
class _StubRouter:
    def __init__(self):
        self.added, self.drained, self.removed = [], [], []
        self.signal = {}

    def stats(self):
        return dict(self.signal)

    def add_replica(self, url):
        self.added.append(url)

    def drain_replica(self, url):
        self.drained.append(url)

    def remove_replica(self, url):
        self.removed.append(url)

    def replica_urls(self):
        return list(self.added)


class _StubReplicaSet:
    def __init__(self):
        self._urls = []
        self.stopped = []
        self.spawned = 0

    def spawn(self):
        self.spawned += 1
        url = f"http://fake:{8000 + self.spawned}"
        self._urls.append(url)
        return url

    def drain_stop(self, url, **kw):
        self._urls.remove(url)
        self.stopped.append(url)
        return 0

    def urls(self):
        return list(self._urls)


def _sig(replicas, ready=None, p99=5.0, per_ready=0.0, shed=0, nr=0, reqs=10):
    return {
        "replicas": replicas,
        "ready": replicas if ready is None else ready,
        "p99_ms": p99,
        "inflight_per_ready": per_ready,
        "shed": shed,
        "no_replica_503": nr,
        "window_requests": reqs,
    }


class TestAutoscaler:
    def make(self, **kw):
        router, rs = _StubRouter(), _StubReplicaSet()
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("up_ticks", 2)
        kw.setdefault("down_ticks", 3)
        kw.setdefault("p99_up_ms", 50.0)
        kw.setdefault("p99_down_ms", 10.0)
        kw.setdefault("inflight_up", 8.0)
        kw.setdefault("inflight_down", 1.0)
        return FleetAutoscaler(router, rs, **kw), router, rs

    def test_scale_up_needs_consecutive_overloaded_ticks(self):
        scaler, router, rs = self.make()
        assert scaler.evaluate(_sig(2, p99=100.0)) is None  # 1st breach: wait
        assert scaler.evaluate(_sig(2, p99=5.0)) is None  # breach cleared
        assert scaler.evaluate(_sig(2, p99=100.0)) is None  # streak restarts
        assert scaler.evaluate(_sig(2, p99=100.0)) == "up"

    def test_scale_up_bounded_by_max(self):
        scaler, router, rs = self.make(max_replicas=2)
        for _ in range(6):
            assert scaler.evaluate(_sig(2, p99=500.0)) is None  # at the ceiling

    def test_shed_delta_counts_overloaded(self):
        scaler, router, rs = self.make(up_ticks=1)
        scaler.evaluate(_sig(2, shed=0))
        assert scaler.evaluate(_sig(2, shed=5)) == "up"
        # an unchanged cumulative counter is NOT a fresh shed
        assert scaler.evaluate(_sig(2, shed=5)) is None or True

    def test_scale_down_needs_streak_and_floor(self):
        scaler, router, rs = self.make(down_ticks=3, min_replicas=2)
        quiet = _sig(3, p99=2.0, per_ready=0.0)
        assert scaler.evaluate(quiet) is None
        assert scaler.evaluate(quiet) is None
        assert scaler.evaluate(quiet) == "down"
        # at the floor: stays
        calm = _sig(2, p99=2.0)
        for _ in range(5):
            assert scaler.evaluate(calm) is None

    def test_mixed_tick_resets_both_streaks(self):
        scaler, router, rs = self.make(up_ticks=2, down_ticks=2)
        assert scaler.evaluate(_sig(2, p99=100.0)) is None
        # neither overloaded nor underloaded (p99 between the watermarks)
        assert scaler.evaluate(_sig(2, p99=30.0)) is None
        assert scaler.evaluate(_sig(2, p99=100.0)) is None  # streak was reset
        assert scaler.evaluate(_sig(2, p99=2.0)) is None

    def test_tick_actuates_spawn_and_drain_order(self):
        scaler, router, rs = self.make(up_ticks=1, down_ticks=2, min_replicas=1)
        router.signal = _sig(1, p99=100.0)
        assert scaler.tick() == "up"
        assert rs.spawned == 1 and router.added == rs.urls()
        router.signal = _sig(2, p99=1.0)
        scaler.tick()
        assert scaler.tick() == "down"
        # drain from routing BEFORE stopping the process, then remove
        assert router.drained == rs.stopped == router.removed
        assert scaler.state()["action"] == "down"

    def test_bounds_validated(self):
        with pytest.raises(ValueError):
            self.make(min_replicas=3, max_replicas=2)


# ----------------------------------------------------------------------
# the real replica lifecycle (one subprocess round trip)
# ----------------------------------------------------------------------
class TestReplicaLifecycle:
    def test_spawn_prewarm_route_drain(self, tmp_path):
        rng = np.random.default_rng(3)
        pts = rng.standard_normal((128, 6)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=5, random_state=0
        ).fit(ht.array(pts, split=0))
        mdir = str(tmp_path / "km")
        serving.save_model(km, mdir, version=1, name="km")
        manifest = str(tmp_path / "prewarm.json")
        with open(manifest, "w") as f:
            json.dump({"version": 1, "entries": [
                {"model": "km", "bucket": b, "features": 6, "dtype": "float32"}
                for b in (1, 4)
            ]}, f)
        rs = LocalReplicaSet(
            {"km": mdir}, str(tmp_path / "fleet"),
            aot_cache=str(tmp_path / "aot"), prewarm=manifest,
            max_batch=8, max_delay_ms=1.0,
        )
        router = FleetRouter(health_period_s=0.2)
        try:
            url = rs.spawn()
            doc = json.load(urllib.request.urlopen(url + "/readyz", timeout=5))
            assert doc["ready"] is True and doc["models"] == ["km"]
            assert doc["aot"]["saves"] >= 2  # it populated the fleet cache
            router.add_replica(url)
            router.poll_health()
            body = json.dumps(
                {"model": "km", "inputs": pts[:3].tolist()}
            ).encode()
            status, out, _, _ = router.handle("POST", "/v1/predict", body)
            assert status == 200
            assert len(json.loads(out)["predictions"]) == 3
            rc = rs.drain_stop(url)
            assert rc == 0  # SIGTERM drained cleanly
            assert "drained cleanly: True" in rs._tail(
                os.path.join(str(tmp_path / "fleet"), "replica-0.log")
            )
        finally:
            router.close()
            rs.close()
