"""Ops-layer tests: arithmetics/trig/exp/rounding/relational/logical over
the split sweep (reference idiom: test_arithmetics.py etc.)."""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    return rng.standard_normal((6, 10)).astype(np.float32)  # 6, 10: uneven over 8


@pytest.mark.parametrize("split", SPLITS)
def test_binary_ops(data, split):
    other = (data * 2 + 1).astype(np.float32)
    a = ht.array(data, split=split)
    b = ht.array(other, split=split)
    np.testing.assert_allclose((a + b).numpy(), data + other, rtol=1e-6)
    np.testing.assert_allclose((a - b).numpy(), data - other, rtol=1e-6)
    np.testing.assert_allclose((a * b).numpy(), data * other, rtol=1e-6)
    np.testing.assert_allclose((a / b).numpy(), data / other, rtol=1e-5)
    np.testing.assert_allclose(ht.pow(a, 2).numpy(), data**2, rtol=1e-5)
    np.testing.assert_allclose((a + 1.5).numpy(), data + 1.5, rtol=1e-6)
    np.testing.assert_allclose((2.0 - a).numpy(), 2.0 - data, rtol=1e-6)


def test_binary_mixed_splits(data):
    a = ht.array(data, split=0)
    b = ht.array(data, split=1)
    np.testing.assert_allclose((a + b).numpy(), data + data, rtol=1e-6)


def test_binary_broadcast(data):
    a = ht.array(data, split=0)
    row = np.arange(10, dtype=np.float32)
    b = ht.array(row)
    np.testing.assert_allclose((a + b).numpy(), data + row, rtol=1e-6)
    col = np.arange(6, dtype=np.float32)[:, None]
    c = ht.array(col, split=0)
    np.testing.assert_allclose((a * c).numpy(), data * col, rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reductions(data, split, axis):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.sum(a, axis=axis).numpy(), data.sum(axis=axis), rtol=1e-5)
    np.testing.assert_allclose(ht.max(a, axis=axis).numpy(), data.max(axis=axis), rtol=1e-6)
    np.testing.assert_allclose(ht.min(a, axis=axis).numpy(), data.min(axis=axis), rtol=1e-6)
    np.testing.assert_allclose(ht.mean(a, axis=axis).numpy(), data.mean(axis=axis), rtol=1e-5)


@pytest.mark.parametrize("split", SPLITS)
def test_reduction_keepdims_and_prod(data, split):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(
        ht.sum(a, axis=1, keepdims=True).numpy(), data.sum(axis=1, keepdims=True), rtol=1e-5
    )
    small = np.abs(data[:2, :3]) + 0.5
    b = ht.array(small, split=split if split != 1 else 1)
    np.testing.assert_allclose(ht.prod(b).numpy(), small.prod(), rtol=1e-5)


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("axis", [0, 1])
def test_cum_ops(data, split, axis):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.cumsum(a, axis).numpy(), data.cumsum(axis=axis), rtol=1e-4, atol=1e-5)
    assert ht.cumsum(a, axis).split == split


@pytest.mark.parametrize("split", SPLITS)
def test_unary_ops(data, split):
    a = ht.array(data, split=split)
    np.testing.assert_allclose(ht.exp(a).numpy(), np.exp(data), rtol=1e-5)
    np.testing.assert_allclose(ht.sin(a).numpy(), np.sin(data), rtol=1e-5)
    np.testing.assert_allclose(ht.tanh(a).numpy(), np.tanh(data), rtol=1e-5)
    np.testing.assert_allclose(ht.floor(a).numpy(), np.floor(data))
    np.testing.assert_allclose(ht.ceil(a).numpy(), np.ceil(data))
    np.testing.assert_allclose(ht.abs(a).numpy(), np.abs(data), rtol=1e-6)
    np.testing.assert_allclose(ht.sqrt(ht.abs(a)).numpy(), np.sqrt(np.abs(data)), rtol=1e-6)
    np.testing.assert_allclose(ht.log(ht.abs(a) + 1).numpy(), np.log(np.abs(data) + 1), rtol=1e-5)


def test_int_float_cast_local_op():
    a = ht.arange(5, split=0)  # int32
    assert ht.sin(a).dtype == ht.float32


@pytest.mark.parametrize("split", SPLITS)
def test_relational_logical(data, split):
    a = ht.array(data, split=split)
    b = ht.array(np.zeros_like(data), split=split)
    np.testing.assert_array_equal((a > b).numpy(), data > 0)
    np.testing.assert_array_equal((a <= b).numpy(), data <= 0)
    np.testing.assert_array_equal((a == a).numpy(), np.ones_like(data, dtype=bool))
    assert ht.equal(a, a)
    assert not ht.equal(a, b)
    assert bool(ht.any(a > 100)) is False
    assert bool(ht.all(ht.abs(a) < 100)) is True
    np.testing.assert_array_equal(ht.all(a > 0, axis=0).numpy(), (data > 0).all(axis=0))
    np.testing.assert_array_equal(ht.any(a > 0, axis=1).numpy(), (data > 0).any(axis=1))


def test_isnan_isinf():
    x = np.array([1.0, np.nan, np.inf, -np.inf], dtype=np.float32)
    a = ht.array(x, split=0)
    np.testing.assert_array_equal(ht.isnan(a).numpy(), np.isnan(x))
    np.testing.assert_array_equal(ht.isinf(a).numpy(), np.isinf(x))
    np.testing.assert_array_equal(ht.isfinite(a).numpy(), np.isfinite(x))
    assert bool(ht.allclose(a, a, equal_nan=True))
    np.testing.assert_allclose(ht.nansum(a[:2]).numpy(), 1.0)


def test_bitwise_int_guard():
    a = ht.arange(8, split=0)
    b = ht.ones(8, dtype=ht.int32, split=0)
    np.testing.assert_array_equal(ht.bitwise_and(a, b).numpy(), np.arange(8) & 1)
    with pytest.raises(TypeError):
        ht.bitwise_and(ht.ones(4), ht.ones(4))


def test_mod_floordiv():
    x = np.array([5, -5, 7, -7], dtype=np.int32)
    y = np.array([3, 3, -3, -3], dtype=np.int32)
    a, b = ht.array(x, split=0), ht.array(y, split=0)
    np.testing.assert_array_equal(ht.mod(a, b).numpy(), np.mod(x, y))
    np.testing.assert_array_equal(ht.fmod(a, b).numpy(), np.fmod(x, y))
    np.testing.assert_array_equal(ht.floordiv(a, b).numpy(), x // y)


def test_diff():
    x = np.array([1.0, 3.0, 6.0, 10.0], dtype=np.float32)
    a = ht.array(x, split=0)
    np.testing.assert_allclose(ht.diff(a).numpy(), np.diff(x))
    np.testing.assert_allclose(ht.diff(a, n=2).numpy(), np.diff(x, n=2))


def test_inplace_ops(data):
    a = ht.array(data.copy(), split=0)
    a += 1.0
    np.testing.assert_allclose(a.numpy(), data + 1.0, rtol=1e-6)
    a *= 2.0
    np.testing.assert_allclose(a.numpy(), (data + 1.0) * 2, rtol=1e-6)


def test_out_param(data):
    a = ht.array(data, split=0)
    out = ht.zeros_like(a)
    res = ht.add(a, a, out=out)
    assert res is out
    np.testing.assert_allclose(out.numpy(), data * 2, rtol=1e-6)


def test_where_param(data):
    a = ht.array(data, split=0)
    cond = ht.array(data > 0, split=0)
    res = ht.add(a, 1.0, where=cond)
    expected = np.where(data > 0, data + 1.0, 0.0)
    np.testing.assert_allclose(res.numpy(), expected, rtol=1e-6)


def test_size1_split_dim_does_not_carry_distribution(ht):
    """A size-1 split dim broadcasts; it must not impose its split on the
    output (the `!= 1` guard in _out_split_binary)."""
    import numpy as np

    a = ht.ones((1, 6), split=0)      # split axis has global size 1
    b = ht.ones((5, 6), split=None)
    out = a + b
    assert out.shape == (5, 6)
    assert out.split is None          # size-1 split must not carry
    np.testing.assert_allclose(out.numpy(), np.full((5, 6), 2.0))

    c = ht.ones((5, 6), split=0)      # real split still carries
    out2 = a + c
    assert out2.split == 0
