"""Fused Pallas FFT axis-pass kernel (fft/_pallas_fft.py): opt-in, but
its correctness is gated here through the interpreter on the virtual
mesh — complex/real input, inverse, several factorizations, and the
end-to-end planar fftn with the kernel forced on.
"""

import os

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.fft import _pallas_fft as pf


@pytest.fixture(autouse=True)
def kernel_on():
    os.environ["HEAT_TPU_FFT_PALLAS"] = "1"
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        yield
    finally:
        del os.environ["HEAT_TPU_FFT_PALLAS"]
        del os.environ["HEAT_TPU_PLANAR"]


def test_factor_table():
    assert pf._split_factors(512) == (128, 4)
    assert pf._split_factors(384) == (128, 3)
    assert pf._split_factors(96) == (96, 1)
    assert pf._split_factors(1000) == (125, 8)
    assert pf._split_factors(131072) is None  # radix too large
    # a prime <= 128 is a legal single-stage (n1, 1) pair
    assert pf._split_factors(127) == (127, 1)


@pytest.mark.parametrize("n", [512, 384, 256, 96])
@pytest.mark.parametrize("inverse", [False, True])
def test_axis_pass_matches_numpy(n, inverse):
    rng = np.random.default_rng(3)
    x = rng.standard_normal((6, n)).astype(np.float32)
    y = rng.standard_normal((6, n)).astype(np.float32)
    import jax.numpy as jnp

    re, im = pf.fused_axis_pass(jnp.asarray(x), jnp.asarray(y), inverse, "highest")
    got = np.asarray(re) + 1j * np.asarray(im)
    z = x + 1j * y
    want = np.fft.ifft(z, axis=-1) * n if inverse else np.fft.fft(z, axis=-1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def test_real_input_variant():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((4, 256)).astype(np.float32)
    import jax.numpy as jnp

    re, im = pf.fused_axis_pass(jnp.asarray(x), None, False, "highest")
    got = np.asarray(re) + 1j * np.asarray(im)
    np.testing.assert_allclose(got, np.fft.fft(x, axis=-1), rtol=2e-4, atol=2e-3)


def test_end_to_end_fftn_with_kernel():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((8, 96)).astype(np.float32)
    a = ht.array(x, split=0)
    got = ht.fft.fftn(a)
    assert got._planar is not None
    np.testing.assert_allclose(got.numpy(), np.fft.fftn(x), rtol=1e-3, atol=5e-3)
    back = ht.fft.ifftn(got)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=2e-3)
