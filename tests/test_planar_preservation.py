"""Plane-preservation guarantees (VERDICT r3 #7): elementwise chains on
planar complex arrays stay on the mesh — fftn(x) * H -> ifftn never
materializes host complex storage — and demotions are loud.

The planar representation is forced via HEAT_TPU_PLANAR=1 (the
complex-less-runtime switch); materialization is trapped by poisoning
DNDarray._DNDarray__materialize_planar for the duration.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.dndarray import DNDarray


@pytest.fixture()
def planar_mode():
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        yield
    finally:
        del os.environ["HEAT_TPU_PLANAR"]


class _NoMaterialize:
    """Poison planar materialization so any host/complex fallback fails."""

    def __enter__(self):
        self._orig = DNDarray._DNDarray__materialize_planar

        def boom(self_arr):
            raise AssertionError("planar array was materialized mid-chain")

        DNDarray._DNDarray__materialize_planar = boom
        return self

    def __exit__(self, *exc):
        DNDarray._DNDarray__materialize_planar = self._orig
        return False


def test_fftn_filter_ifftn_stays_on_mesh(planar_mode):
    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((16, 8)).astype(np.float32)
    h_np = rng.standard_normal((16, 8)).astype(np.float32)
    x = ht.array(x_np, split=0)
    h = ht.array(h_np, split=0)
    with _NoMaterialize():
        spec = ht.fft.fftn(x)
        assert spec._planar is not None
        filt = spec * h  # planar * real-array fast path
        assert filt._planar is not None
        back = ht.fft.ifftn(filt)
        assert back._planar is not None
    want = np.fft.ifftn(np.fft.fftn(x_np) * h_np)
    np.testing.assert_allclose(np.asarray(back.numpy()), want, atol=1e-4)


def test_planar_binary_table(planar_mode):
    rng = np.random.default_rng(1)
    a_np = rng.standard_normal((12, 6)).astype(np.float32)
    b_np = rng.standard_normal((12, 6)).astype(np.float32)
    a = ht.fft.fft(ht.array(a_np, split=0), axis=0)
    b = ht.fft.fft(ht.array(b_np, split=0), axis=0)
    fa = np.fft.fft(a_np, axis=0)
    fb = np.fft.fft(b_np, axis=0)
    cases = [
        (a + b, fa + fb),
        (a - b, fa - fb),
        (a * b, fa * fb),
        (a / b, fa / fb),
        (a + 2.0, fa + 2.0),
        (a * (1.5 - 0.5j), fa * (1.5 - 0.5j)),
        (a / 2.0, fa / 2.0),
        (3.0 * a, 3.0 * fa),
        (-a, -fa),
    ]
    with _NoMaterialize():
        for got, _ in cases:
            assert got._planar is not None, "plane path skipped"
    for got, want in cases:
        np.testing.assert_allclose(np.asarray(got.numpy()), want, atol=1e-3)


def test_scalar_complex_div(planar_mode):
    rng = np.random.default_rng(2)
    a_np = rng.standard_normal(32).astype(np.float64)
    a = ht.fft.fft(ht.array(a_np, split=0))
    fa = np.fft.fft(a_np)
    with _NoMaterialize():
        got = a / (2.0 + 1.0j)
        assert got._planar is not None
    np.testing.assert_allclose(np.asarray(got.numpy()), fa / (2.0 + 1.0j), atol=1e-10)


def test_demotion_is_loud_midchain_only(planar_mode, monkeypatch):
    import warnings

    from heat_tpu.core import dndarray as dd

    # force the complex-less-runtime branch (the CPU test backend supports
    # complex, so the host-demotion path must be simulated)
    monkeypatch.setattr(dd, "_tpu_complex_ok", lambda: False)
    monkeypatch.setattr(dd.jax, "default_backend", lambda: "tpu")
    dd._planar_demotions_warned.clear()
    a = ht.fft.fft(ht.array(np.ones((4, 8), np.float32), split=0), axis=1)
    assert a._planar is not None
    # a framework op WITHOUT a plane fast path warns, naming the site
    with pytest.warns(RuntimeWarning, match="demoted to HOST complex"):
        try:
            ht.sum(a)
        except Exception:
            pass  # the simulated-TPU path may fail downstream on CPU
    # terminal fetches are intentional host transfers: silent
    b = ht.fft.fft(ht.array(np.ones(8, np.float32), split=0))
    dd._planar_demotions_warned.clear()
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        try:
            b.numpy()
        except RuntimeWarning:
            raise
        except Exception:
            pass
        try:
            b.larray_padded  # direct user buffer access: intentional
        except RuntimeWarning:
            raise
        except Exception:
            pass
