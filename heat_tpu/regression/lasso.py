"""Lasso regression, analog of heat/regression/lasso.py (lasso.py:10).

Coordinate descent with soft thresholding; every inner product is a
distributed dot over the sharded sample axis (an MXU matvec + psum).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from functools import partial

from ..core import dispatch, types
from ..core.base import BaseEstimator, RegressionMixin, lazy_scalar_property
from ..core.dndarray import DNDarray


def _linear_predict_op(xd, th):
    """Intercept + coefficients in one cached program (the predict hot
    path the serving layer batches)."""
    yest = jnp.matmul(xd, th[1:], precision=jax.lax.Precision.HIGHEST) + th[0]
    return yest.reshape(-1, 1)


def _soft_threshold_op(d, *, lam):
    return jnp.sign(d) * jnp.maximum(jnp.abs(d) - lam, 0.0)


@partial(jax.jit, static_argnames=("max_iter",))
def _cd_loop(X, yd, col_sq, lam, tol, max_iter, theta0):
    """Whole cyclic-coordinate-descent fit as one on-device while_loop.

    A host-side sweep loop costs a device->host sync per sweep (a full
    link RTT on a tunneled chip); lam/tol are traced so a regularization-
    path sweep (examples/lasso) reuses one compiled executable.
    ``theta0`` is the starting iterate (zeros for a fresh fit; a restored
    checkpoint for the resumable path — the sweep sequence continues
    exactly where it stopped).  Returns (theta, sweeps_run, last_delta).
    """
    m = X.shape[1]
    hp = jax.lax.Precision.HIGHEST

    def one_sweep(th):
        def body(j, t):
            resid = yd - jnp.matmul(X, t, precision=hp) + X[:, j] * t[j]
            rho = jnp.matmul(X[:, j], resid, precision=hp)
            new_j = jnp.where(
                j == 0,
                rho / jnp.maximum(col_sq[0], 1e-30),  # intercept not penalized
                (jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0))
                / jnp.maximum(col_sq[j], 1e-30),
            )
            return t.at[j].set(new_j)

        return jax.lax.fori_loop(0, m, body, th)

    def cond(carry):
        th, it, delta = carry
        return jnp.logical_and(it < max_iter, delta >= tol)

    def body(carry):
        th, it, _ = carry
        new = one_sweep(th)
        delta = jnp.max(jnp.abs(new - th)).astype(jnp.float32)
        return new, it + 1, delta

    init = (jnp.asarray(theta0, X.dtype), jnp.int32(0), jnp.asarray(jnp.inf, jnp.float32))
    theta, it, delta = jax.lax.while_loop(cond, body, init)
    return theta, it, delta

__all__ = ["Lasso"]


class Lasso(BaseEstimator, RegressionMixin):
    """L1-regularized linear regression via coordinate descent (lasso.py:10).

    ``checkpoint_every=N`` + ``checkpoint_dir`` checkpoint ``theta``
    every N sweeps through the filesystem-native Checkpointer;
    ``resume_from=dir`` continues a killed fit from its last checkpoint
    with the identical sweep sequence (the resumed result matches the
    uninterrupted one exactly).  The chunked path raises
    :class:`~heat_tpu.resilience.DivergenceError` on NaN/Inf."""

    def __init__(
        self,
        lam: float = 0.1,
        max_iter: int = 100,
        tol: float = 1e-6,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        from ..core.base import validate_resume_params

        validate_resume_params(checkpoint_every, checkpoint_dir, resume_from)
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.checkpoint_every = checkpoint_every
        self.checkpoint_dir = checkpoint_dir
        self.resume_from = resume_from
        self.__theta = None
        self._n_iter = None

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def lam(self) -> float:
        return self.__lam

    @lam.setter
    def lam(self, arg: float):
        self.__lam = arg

    @property
    def theta(self):
        return self.__theta

    def soft_threshold(self, rho):
        """Soft-thresholding operator (lasso.py:80).

        The sign/max/abs chain runs as ONE cached executable through the
        dispatch layer — a regularization-path sweep calling this per
        lambda re-uses the compiled program instead of paying three
        eager launches each time."""
        lam = float(self.__lam)
        if isinstance(rho, DNDarray):
            out = dispatch.eager_apply(_soft_threshold_op, (rho._dense(),), {"lam": lam})
            return DNDarray.from_dense(out, rho.split, rho.device, rho.comm)
        return dispatch.eager_apply(_soft_threshold_op, (jnp.asarray(rho),), {"lam": lam})

    def rmse(self, gt: DNDarray, yest: DNDarray) -> float:
        """Root mean squared error (lasso.py:100)."""
        diff = gt._dense().ravel() - yest._dense().ravel()
        return float(jnp.sqrt(jnp.mean(diff * diff)))

    # fit stores the device scalar so it never blocks on the link
    n_iter = lazy_scalar_property("_n_iter", int)

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Cyclic coordinate descent (lasso.py:120)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"x needs to be 2D, got {x.ndim}D")
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        yd = y._dense().reshape(-1).astype(xd.dtype)
        n, f = xd.shape
        # prepend intercept column (lasso.py:135)
        X = jnp.concatenate([jnp.ones((n, 1), xd.dtype), xd], axis=1)
        col_sq = jnp.sum(X * X, axis=0)

        lam = jnp.asarray(self.__lam, xd.dtype)
        tol = jnp.asarray(self.tol, jnp.float32)
        if self.checkpoint_every is not None or self.resume_from is not None:
            # chunked checkpoint/resume path: same sweep sequence as the
            # single-launch fit, theta checkpointed (and NaN-guarded)
            # every checkpoint_every sweeps
            from ..core.base import resumable_fit_loop

            def run_chunk(theta, n_sweeps):
                dispatch.record_external_dispatch()
                return _cd_loop(X, yd, col_sq, lam, tol, n_sweeps, theta)

            theta, it = resumable_fit_loop(
                run_chunk,
                lambda: jnp.zeros((X.shape[1],), X.dtype),
                self.max_iter,
                float(self.tol),
                checkpoint_every=self.checkpoint_every,
                checkpoint_dir=self.checkpoint_dir,
                resume_from=self.resume_from,
                site="lasso.iter",
                what="theta",
                converged_when=lambda s, t: s < t,  # cd cond: delta >= tol continues
            )
            theta = jnp.asarray(theta, X.dtype)
        else:
            # one launch for the whole coordinate-descent fit — the same
            # dispatch-amortization shape as the kmeans Lloyd loop
            dispatch.record_external_dispatch()
            theta, it, _ = _cd_loop(
                X, yd, col_sq, lam, tol, self.max_iter,
                jnp.zeros((X.shape[1],), X.dtype),
            )
        self._n_iter = it  # lazy: n_iter converts on first access
        self.__theta = DNDarray.from_dense(theta.reshape(-1, 1), None, x.device, x.comm)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction with intercept (lasso.py:200)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        th = self.__theta._dense().ravel()
        yest = dispatch.eager_apply(_linear_predict_op, (xd, th))
        return DNDarray.from_dense(yest, x.split, x.device, x.comm)
