"""Distributed compressed sparse matrices, analog of
heat/sparse/dcsx_matrix.py (DCSR_matrix/DCSC_matrix, dcsx_matrix.py:19-423).

The reference stores one torch.sparse_csr/csc chunk per rank, split=0 for
CSR / split=1 for CSC only, with ``global_indptr()`` reconstructed via an
Exscan-style cumsum of local nnz (:65+).  The TPU-native layout shards
padded COO planes over the device mesh — data/indices aligned to the
compressed-axis chunks, capacity = max per-shard nnz (static shapes for
XLA), sentinel-padded tails (see :mod:`._planes`).  All accessors
(``indptr``/``lindptr``/``indices``/``data``/``lnnz``) are jitted device
programs over the planes; the only host traffic is the (size,)-int nnz
re-sync that the reference also performs after every op.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.devices import Device
from ..parallel.comm import Communication
from . import _planes as _pl

__all__ = ["DCSR_matrix", "DCSC_matrix", "DCSX_matrix"]


class DCSX_matrix:
    """Shared base of DCSR/DCSC (dcsx_matrix.py:19)."""

    _compressed_axis: int = 0

    def __init__(
        self,
        planes: Tuple[jax.Array, jax.Array, jax.Array],
        lnnz_dev: jax.Array,
        lnnz_host: Tuple[int, ...],
        capacity: int,
        comp_pad: int,
        gshape: Tuple[int, int],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: bool = True,
    ):
        self._comp, self._other, self._val = planes
        self._lnnz_dev = lnnz_dev
        self._lnnz_host = tuple(int(v) for v in lnnz_host)
        self._capacity = int(capacity)
        self._comp_pad = int(comp_pad)
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = types.canonical_heat_type(dtype)
        self.__split = split
        self.__device = device
        self.__comm = comm

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    @classmethod
    def from_host_coo(cls, rows, cols, vals, gshape, split, device, comm) -> "DCSX_matrix":
        comp, other, val, lnnz_dev, lnnz_host, C, comp_pad = _pl.build_from_host_coo(
            rows, cols, vals, gshape, cls._compressed_axis, split, comm
        )
        return cls(
            (comp, other, val), lnnz_dev, lnnz_host, C, comp_pad,
            gshape, val.dtype, split, device, comm,
        )

    @classmethod
    def from_dense_padded(cls, x_masked, gshape, split, device, comm) -> "DCSX_matrix":
        """Device-side packing of a (masked) padded dense buffer."""
        comp, other, val, lnnz_dev, lnnz_host, C, comp_pad = _pl.pack_from_dense(
            x_masked, gshape, cls._compressed_axis, split, comm
        )
        return cls(
            (comp, other, val), lnnz_dev, lnnz_host, C, comp_pad,
            gshape, val.dtype, split, device, comm,
        )

    def _with_planes(self, planes, lnnz_dev, lnnz_host, capacity, dtype=None, cls=None):
        cls = cls or type(self)
        return cls(
            planes, lnnz_dev, lnnz_host, capacity, self._comp_pad,
            self.__gshape, dtype or self.__dtype, self.__split, self.__device, self.__comm,
        )

    @property
    def _nshards(self) -> int:
        return self.__comm.size if self.__split is not None else 1

    @property
    def _dist(self) -> bool:
        return self.__split is not None

    # ------------------------------------------------------------------
    @property
    def larray(self):
        """A global jax BCOO view, assembled on device from the packed
        planes (interop/back-compat; the planes are the storage)."""
        from jax.experimental import sparse as jsparse

        indptr = self.indptr
        indices, data = self._packed()
        counts = jnp.diff(indptr)
        comp_ids = jnp.repeat(
            jnp.arange(self.__gshape[self._compressed_axis], dtype=indices.dtype),
            counts,
            total_repeat_length=self.gnnz,
        )
        if self._compressed_axis == 0:
            idx = jnp.stack([comp_ids, indices], axis=1)
        else:
            idx = jnp.stack([indices, comp_ids], axis=1)
        return jsparse.BCOO((data, idx), shape=self.__gshape, indices_sorted=self._compressed_axis == 0)

    @property
    def shape(self) -> Tuple[int, int]:
        return self.__gshape

    gshape = shape

    @property
    def lshape(self) -> Tuple[int, int]:
        """Process-local block shape; in single-controller mode one process
        addresses every shard, so this is the global shape (the same
        convention as ``DNDarray.larray``)."""
        if self.__split is None or jax.process_count() == 1:
            return self.__gshape
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split)  # pragma: no cover
        return lshape

    @property
    def dtype(self):
        return self.__dtype

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def comm(self) -> Communication:
        return self.__comm

    @property
    def balanced(self) -> bool:
        return True

    @property
    def ndim(self) -> int:
        return 2

    @property
    def gnnz(self) -> int:
        """Global number of stored values (dcsx_matrix.py:80)."""
        return sum(self._lnnz_host)

    @property
    def nnz(self) -> int:
        return self.gnnz

    @property
    def lnnz(self) -> int:
        """Process-local nnz (dcsx_matrix.py:70); single-controller mode
        addresses every shard, so this is the global count."""
        start, stop = self._local_shard_range()
        return sum(self._lnnz_host[start:stop])

    def _local_shard_range(self) -> Tuple[int, int]:
        if self.__split is None or jax.process_count() == 1:
            return 0, self._nshards
        parts = self.__comm.local_participants  # pragma: no cover
        return parts[0], parts[-1] + 1  # pragma: no cover

    # ------------------------------------------------------------------
    # accessors — all device programs over the planes
    # ------------------------------------------------------------------
    def _packed(self):
        cached = getattr(self, "_packed_cache", None)
        if cached is None:
            cached = _pl.packed_indices_data(
                self._other, self._val, self._lnnz_dev,
                self._nshards, self._capacity, self.gnnz, self.__comm,
            )
            self._packed_cache = cached
        return cached

    @property
    def indptr(self) -> jnp.ndarray:
        """Global compressed pointers (``global_indptr``, dcsx_matrix.py:65):
        per-shard local indptrs shifted by the Exscan of shard nnz, fused
        in one device program."""
        return _pl.global_indptr(
            self._comp, self._lnnz_dev, self._nshards, self._capacity,
            self._comp_pad, self.__gshape[self._compressed_axis],
            self._dist, self.__comm,
        )

    global_indptr = indptr

    @property
    def lindptr(self) -> jnp.ndarray:
        """Local pointers, re-based to the chunk (dcsx_matrix.py:95)."""
        blocks = _pl.lindptr_blocks(
            self._comp, self._nshards, self._capacity, self._comp_pad,
            self._dist, self.__comm,
        )
        if self.__split is None or jax.process_count() == 1:
            if self._nshards == 1:
                return blocks
            # single controller: "local" spans every shard — stitch the
            # per-shard indptrs into one (still on device)
            return self.indptr
        s0, s1 = self._local_shard_range()  # pragma: no cover
        per = self._comp_pad + 1  # pragma: no cover
        return blocks[s0 * per : s1 * per]  # pragma: no cover

    @property
    def gindptr(self) -> jnp.ndarray:
        """Alias of :attr:`indptr` (reference's ``gindptr``, dcsx_matrix.py:167)."""
        return self.indptr

    @property
    def indices(self) -> jnp.ndarray:
        """Global uncompressed indices (dcsx_matrix.py:110)."""
        return self._packed()[0]

    @property
    def gindices(self) -> jnp.ndarray:
        """Alias of :attr:`indices` (dcsx_matrix.py:196)."""
        return self.indices

    @property
    def lindices(self) -> jnp.ndarray:
        return self._packed()[0] if jax.process_count() == 1 else self._local_packed()[0]

    @property
    def data(self) -> jnp.ndarray:
        """Global stored values (dcsx_matrix.py:130)."""
        return self._packed()[1]

    @property
    def gdata(self) -> jnp.ndarray:
        """Alias of :attr:`data` (dcsx_matrix.py:143)."""
        return self.data

    @property
    def ldata(self) -> jnp.ndarray:
        return self._packed()[1] if jax.process_count() == 1 else self._local_packed()[1]

    def _local_packed(self):  # pragma: no cover - multi-host only
        s0, s1 = self._local_shard_range()
        lo = sum(self._lnnz_host[:s0])
        hi = sum(self._lnnz_host[:s1])
        ind, dat = self._packed()
        return ind[lo:hi], dat[lo:hi]

    def is_distributed(self) -> bool:
        """Whether the data is split across participants (dcsx_matrix.py:272)."""
        return self.__split is not None and self.__comm.is_distributed

    def counts_displs_nnz(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-participant (nnz counts, nnz displacements) along the
        compressed axis (dcsx_matrix.py:278) — straight off the host nnz
        re-sync metadata, the reference's Exscan over local nnz."""
        counts = self._lnnz_host
        displs = tuple(int(v) for v in np.cumsum((0,) + counts[:-1]))
        return counts, displs

    # ------------------------------------------------------------------
    def todense(self):
        """Convert to a dense DNDarray (manipulations.py:105 ``to_dense``):
        one scatter-add per shard into the canonical padded layout — the
        output is already sharded the way a split=``_compressed_axis``
        DNDarray wants it."""
        from ..core.dndarray import DNDarray

        other_extent = self.__gshape[1 - self._compressed_axis]
        padded = _pl.todense_padded(
            self._comp, self._other, self._val, self._compressed_axis,
            self._nshards, self._capacity, self._comp_pad, other_extent,
            self._dist, self.__comm,
        )
        if not self._dist:
            # unsplit: comp_pad may exceed the true extent only when extent==0
            padded = padded[: self.__gshape[0], : self.__gshape[1]]
        return DNDarray(
            padded, self.__gshape, self.__dtype, self.__split, self.__device, self.__comm
        )

    to_dense = todense

    def toarray(self) -> np.ndarray:
        return self.todense().numpy()  # multihost-safe gather

    def astype(self, dtype) -> "DCSX_matrix":
        dtype = types.canonical_heat_type(dtype)
        return self._with_planes(
            (self._comp, self._other, self._val.astype(dtype.jax_type())),
            self._lnnz_dev, self._lnnz_host, self._capacity, dtype=dtype,
        )

    @property
    def T(self):
        """Transpose flips CSR<->CSC (dcsx_matrix.py:380) — pure metadata:
        the (comp, other, val) planes of A in (row, col) order ARE the
        planes of A^T in (col, row) order under the same chunking, so no
        data moves at all."""
        other_cls = DCSC_matrix if isinstance(self, DCSR_matrix) else DCSR_matrix
        new_split = None if self.__split is None else 1 - self.__split
        return other_cls(
            (self._comp, self._other, self._val),
            self._lnnz_dev, self._lnnz_host, self._capacity, self._comp_pad,
            (self.__gshape[1], self.__gshape[0]),
            self.__dtype, new_split, self.__device, self.__comm,
        )

    def __repr__(self) -> str:
        cls = type(self).__name__
        return (
            f"{cls}(gnnz={self.gnnz}, shape={self.__gshape}, dtype=ht.{self.__dtype.__name__}, "
            f"split={self.__split})"
        )

    # arithmetic operators (bound to sparse arithmetics, dcsx_matrix.py:300)
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    __radd__ = __add__

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    __rmul__ = __mul__

    def __matmul__(self, other):
        from . import arithmetics

        return arithmetics.matmul(self, other)

    def __rmatmul__(self, other):
        from . import arithmetics

        return arithmetics.matmul(other, self)

    def sum(self, axis=None):
        from . import arithmetics

        return arithmetics.sum(self, axis=axis)

    def matmul(self, other):
        from . import arithmetics

        return arithmetics.matmul(self, other)


class DCSR_matrix(DCSX_matrix):
    """Row-compressed distributed sparse matrix; split 0 or None
    (dcsx_matrix.py:19).  split=0 shards the nnz planes over the mesh
    aligned to the canonical row chunks."""

    _compressed_axis = 0


class DCSC_matrix(DCSX_matrix):
    """Column-compressed distributed sparse matrix; split 1 or None
    (dcsx_matrix.py:230).  split=1 shards the nnz planes aligned to the
    canonical column chunks — a native layout, not a transpose view."""

    _compressed_axis = 1
