"""Durable stream sources (docs/streaming.md).

The continuous-learning loop needs an input the fits can *replay*: the
exactly-once guarantee (a killed fit resumed from its committed offset
reproduces the uninterrupted fit bitwise) only holds if reading rows
``[k, k+n)`` returns the same bytes every time.  Two sources provide
that property:

* :class:`FileSegmentLog` — an append-only directory of immutable
  ``.npy`` segments (atomic-rename committed, CRC32 sidecars).  Rows
  are addressed by a monotone offset; a read spanning segments
  reassembles exactly the appended bytes.  This is the durable source
  the tests, the kill+resume scenarios and the ingest bench use.
* :class:`SyntheticStream` — a deterministic generator whose row ``i``
  is a pure function of ``(seed, i)``, optionally shifting its
  distribution after ``drift_at`` rows.  Unbounded by default; the
  MULTICHIP scenarios and the drift e2e tests use it because it needs
  no disk and replays identically from any offset.

Both speak the same two-method protocol: ``read(offset, max_rows)``
returns up to ``max_rows`` rows starting at ``offset`` (possibly zero
at the stream head) and ``size`` reports the rows currently available
(``None`` = unbounded).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Tuple

import numpy as np

from ..analysis import tsan as _tsan
from ..resilience.atomic import atomic_write, verify_checksum

__all__ = ["StreamSource", "FileSegmentLog", "SyntheticStream"]

_SEGMENT_RE = re.compile(r"^seg-(\d{12})-(\d{8})\.npy$")


class StreamSource:
    """Protocol of a replayable row stream.

    ``read(offset, max_rows)`` must be a pure function of its arguments
    and the committed log contents: the streaming fits commit their
    offset atomically with model state and rely on replay returning the
    identical window bytes."""

    @property
    def n_features(self) -> Optional[int]:
        raise NotImplementedError

    @property
    def size(self) -> Optional[int]:
        """Rows currently readable; ``None`` = unbounded."""
        raise NotImplementedError

    def read(self, offset: int, max_rows: int) -> np.ndarray:
        raise NotImplementedError


class FileSegmentLog(StreamSource):
    """Append-only segment log over a directory of immutable ``.npy`` files.

    Layout: ``seg-<start:012d>-<count:08d>.npy`` (+ ``.crc`` sidecars
    from the atomic-write layer).  Appends are chunked to
    ``segment_rows`` and committed by atomic rename, so a concurrent or
    crashed producer can never expose a torn segment: a reader's scan
    sees only fully committed files, and the log's end offset is derived
    from the committed file names alone (no separate metadata file to
    desynchronize).
    """

    def __init__(self, directory: str, segment_rows: Optional[int] = None):
        from ..core._env import env_int

        self._dir = os.fspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.segment_rows = int(segment_rows if segment_rows is not None
                                else env_int("HEAT_TPU_STREAM_SEGMENT_ROWS", 4096))
        if self.segment_rows < 1:
            raise ValueError(f"segment_rows must be >= 1, got {self.segment_rows}")
        self._lock = _tsan.register_lock("streaming.segment_log")
        #: sorted committed segments: (start_offset, rows, path)
        self._segments: List[Tuple[int, int, str]] = []
        self._n_features: Optional[int] = None
        with self._lock:
            _tsan.note_access("streaming.segment_log.index")
            self._rescan_locked()

    # -- index ----------------------------------------------------------
    def _rescan_locked(self) -> None:
        segs: List[Tuple[int, int, str]] = []
        for name in os.listdir(self._dir):
            m = _SEGMENT_RE.match(name)
            if m:
                segs.append((int(m.group(1)), int(m.group(2)),
                             os.path.join(self._dir, name)))
        segs.sort()
        self._segments = segs

    def _snapshot(self, want_end: Optional[int] = None) -> List[Tuple[int, int, str]]:
        """Committed segment list; rescans when another process may have
        appended past our cached view (cross-process tail)."""
        with self._lock:
            _tsan.note_access("streaming.segment_log.index")
            if want_end is not None and self._end_locked() < want_end:
                self._rescan_locked()
            return list(self._segments)

    def _end_locked(self) -> int:
        if not self._segments:
            return 0
        start, count, _ = self._segments[-1]
        return start + count

    # -- protocol -------------------------------------------------------
    @property
    def n_features(self) -> Optional[int]:
        if self._n_features is None:
            segs = self._snapshot()
            if segs:
                self._n_features = int(np.load(segs[0][2], mmap_mode="r").shape[1])
        return self._n_features

    @property
    def size(self) -> int:
        with self._lock:
            _tsan.note_access("streaming.segment_log.index", write=False)
            end = self._end_locked()
        if end == 0:
            # a producer in another process may have committed segments
            # we have never scanned
            with self._lock:
                _tsan.note_access("streaming.segment_log.index")
                self._rescan_locked()
                end = self._end_locked()
        return end

    def append(self, rows: np.ndarray) -> int:
        """Durably append ``rows`` ((n, f) array); returns the new end
        offset.  Each written segment is fsynced, CRC-sidecarred and
        atomically renamed in before the index (and therefore any
        reader) can see it."""
        rows = np.ascontiguousarray(rows)
        if rows.ndim != 2:
            raise ValueError(f"rows must be 2D (n, features), got {rows.ndim}D")
        if rows.shape[0] == 0:
            return self.size
        with self._lock:
            _tsan.note_access("streaming.segment_log.index")
            end = self._end_locked()
            cursor = 0
            while cursor < rows.shape[0]:
                part = rows[cursor:cursor + self.segment_rows]
                path = os.path.join(
                    self._dir, f"seg-{end:012d}-{part.shape[0]:08d}.npy"
                )
                with atomic_write(path, fault_site="io.write") as tmp:
                    with open(tmp, "wb") as fh:
                        np.save(fh, part)
                self._segments.append((end, part.shape[0], path))
                end += part.shape[0]
                cursor += part.shape[0]
            return end

    def read(self, offset: int, max_rows: int) -> np.ndarray:
        """Rows ``[offset, offset + max_rows)`` clipped to the committed
        end; returns fewer (possibly zero) rows at the head."""
        if offset < 0 or max_rows < 0:
            raise ValueError(f"offset/max_rows must be >= 0, got {offset}/{max_rows}")
        segs = self._snapshot(want_end=offset + max_rows)
        parts: List[np.ndarray] = []
        need = max_rows
        for start, count, path in segs:
            if need <= 0 or start + count <= offset:
                continue
            if start >= offset + max_rows:
                break
            verify_checksum(path)
            arr = np.load(path)
            lo = max(offset - start, 0)
            hi = min(lo + need, count)
            parts.append(arr[lo:hi])
            need -= hi - lo
            offset = start + hi
        if not parts:
            f = self.n_features
            return np.empty((0, f if f is not None else 0), dtype=np.float32)
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)


class SyntheticStream(StreamSource):
    """Deterministic synthetic stream: block ``j`` of ``block_rows``
    rows is drawn from ``np.random.default_rng((seed, j))``, so any
    ``read(offset, n)`` replays identically regardless of window size or
    read order.  Rows with global index >= ``drift_at`` shift by
    ``drift_shift`` — the covariate-drift injection the refresh
    scenarios use."""

    def __init__(
        self,
        n_features: int = 8,
        seed: int = 0,
        block_rows: int = 256,
        total_rows: Optional[int] = None,
        drift_at: Optional[int] = None,
        drift_shift: float = 3.0,
        scale: float = 1.0,
        center: float = 0.0,
    ):
        if n_features < 1 or block_rows < 1:
            raise ValueError("n_features and block_rows must be >= 1")
        self._f = int(n_features)
        self.seed = int(seed)
        self.block_rows = int(block_rows)
        self.total_rows = None if total_rows is None else int(total_rows)
        self.drift_at = None if drift_at is None else int(drift_at)
        self.drift_shift = float(drift_shift)
        self.scale = float(scale)
        self.center = float(center)

    @property
    def n_features(self) -> int:
        return self._f

    @property
    def size(self) -> Optional[int]:
        return self.total_rows

    def _block(self, j: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, j))
        arr = rng.standard_normal((self.block_rows, self._f)).astype(np.float32)
        arr = arr * np.float32(self.scale) + np.float32(self.center)
        if self.drift_at is not None:
            start = j * self.block_rows
            idx = np.arange(start, start + self.block_rows)
            arr = arr + np.float32(self.drift_shift) * (idx >= self.drift_at)[:, None].astype(np.float32)
        return arr

    def read(self, offset: int, max_rows: int) -> np.ndarray:
        if offset < 0 or max_rows < 0:
            raise ValueError(f"offset/max_rows must be >= 0, got {offset}/{max_rows}")
        if self.total_rows is not None:
            max_rows = min(max_rows, max(self.total_rows - offset, 0))
        if max_rows == 0:
            return np.empty((0, self._f), dtype=np.float32)
        parts: List[np.ndarray] = []
        pos = offset
        remaining = max_rows
        while remaining > 0:
            j, lo = divmod(pos, self.block_rows)
            hi = min(lo + remaining, self.block_rows)
            parts.append(self._block(j)[lo:hi])
            remaining -= hi - lo
            pos += hi - lo
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
