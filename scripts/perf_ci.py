"""CI perf grid: small anchored measurements that gate regressions.

The chip bench (bench.py) needs the attached TPU; CI runners have none,
and their absolute speed varies between runner generations.  So the CI
grid measures each kernel AGAINST same-process anchors (matmul peak and
stream bandwidth, measured first in the same job) and publishes the
dimensionless ratio — the quantity that moves when a kernel regresses
and holds when the runner is merely slower.  ``scripts/perf_gate.py``
compares a fresh run to the committed ``BENCH_CI.json`` with the
median-minus-spread rule (VERDICT r4 #7; the reference's cb trigger,
.github/workflows/bench_trigger.yml).

    python scripts/perf_ci.py > /tmp/current.json
    python scripts/perf_gate.py BENCH_CI.json /tmp/current.json
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


def _timeit(fn, fetch, windows=5, n_iter=3):
    fetch(fn())  # compile
    samples = []
    for _ in range(windows):
        t0 = time.perf_counter()
        out = None
        for _ in range(n_iter):
            out = fn()
        fetch(out)
        samples.append((time.perf_counter() - t0) / n_iter)
    best = min(samples)
    med = float(np.median(samples))
    spread = 100.0 * (med - best) / best if best else 0.0
    return best, round(spread, 1)


def _timeit_interleaved(specs, rounds=8):
    """Interleaved min-of-k for noise-prone metrics (the r5 KMeans bench
    method): one window of each metric per round, rounds alternating, so
    a monotone runner drift (CI neighbors waking up mid-job) degrades
    every metric's sample set equally instead of landing on whichever
    metric ran last — the committed kmeans_lloyd (22.5%) and
    checkpoint_roundtrip (17.6%) spreads were exactly that artifact.
    ``specs`` is ``[(fn, fetch, n_iter), ...]``; returns one
    ``(best, spread_pct)`` per spec from the min over all its rounds."""
    for fn, fetch, _ in specs:
        fetch(fn())  # compile/warm outside the sample set
    samples = [[] for _ in specs]
    for _ in range(rounds):
        for j, (fn, fetch, n_iter) in enumerate(specs):
            t0 = time.perf_counter()
            out = None
            for _ in range(n_iter):
                out = fn()
            fetch(out)
            samples[j].append((time.perf_counter() - t0) / n_iter)
    results = []
    for s in samples:
        best = min(s)
        med = float(np.median(s))
        results.append((best, round(100.0 * (med - best) / best if best else 0.0, 1)))
    return results


def _paired_overhead_pct(fn_on, fn_off, fetch, rounds=10, n_iter=3):
    """Overhead of ``fn_on`` over ``fn_off`` as the MEDIAN of per-round
    paired MIN-of-``n_iter`` deltas.

    Hard-cap overhead gates compare two ~40 ms measurements whose
    difference is the signal; one global min-vs-min (the anchored
    kernels' method) leaves the full fast-noise floor in the result —
    measured ±5% on this runner against a <3% cap, i.e. a flaky gate.
    Three layers of de-noising instead: (1) each round's ON and OFF run
    back to back (order alternating), so slow runner drift hits both
    sides of a pair equally and divides out of that round's delta;
    (2) each side of a round is the MIN over ``n_iter`` calls — the
    noise here is one-sided (GC pauses, scheduler preemption land as
    slow outliers), so the min is a far tighter location estimate than
    the mean; (3) the median over rounds shrugs off whole bad rounds.
    Measured on this runner: the gate statistic stays within ±1.2% of
    zero across repeated trials (single-fit deltas swing ±22%).
    Returns ``(overhead_pct, best_on_s, best_off_s, spread_pct)``."""
    fetch(fn_on())  # warm/compile both variants outside the sample set
    fetch(fn_off())

    def min_of(fn):
        best = None
        for _ in range(n_iter):
            t0 = time.perf_counter()
            out = fn()
            fetch(out)
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return best

    deltas, on_samples, off_samples = [], [], []
    for r in range(rounds):
        if r % 2 == 0:
            on = min_of(fn_on)
            off = min_of(fn_off)
        else:
            off = min_of(fn_off)
            on = min_of(fn_on)
        on_samples.append(on)
        off_samples.append(off)
        if off > 0:
            deltas.append(100.0 * (on - off) / off)
    best_on, best_off = min(on_samples), min(off_samples)
    med = float(np.median(on_samples))
    spread = 100.0 * (med - best_on) / best_on if best_on else 0.0
    return float(np.median(deltas)), best_on, best_off, round(spread, 1)


def main():
    import heat_tpu as ht

    results = {}

    # anchors
    n = 1024
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    t_mm, sp = _timeit(lambda: mm(a), lambda o: float(o[0, 0]))
    anchor_flops = 2.0 * n**3 / t_mm
    results["anchor_matmul_gflops"] = {"value": round(anchor_flops / 1e9, 1), "spread_pct": sp}

    m = 1 << 24
    v = jax.random.normal(jax.random.PRNGKey(1), (m,), jnp.float32)
    st = jax.jit(lambda x: x * 1.000001 + 0.5)
    t_st, sp = _timeit(lambda: st(v), lambda o: float(o[0]))
    anchor_bw = 8.0 * m / t_st
    results["anchor_stream_gbytes"] = {"value": round(anchor_bw / 1e9, 1), "spread_pct": sp}

    # kernels under gate: each publishes rel = achieved/anchor
    def record(name, per_iter, spread, model_num, anchor):
        # 6 decimals: kernels with tiny anchored ratios (sort_psrs is
        # ~1.5e-4) must not quantize to one significant digit — at 4
        # decimals an anchor speedup alone could halve the recorded
        # ratio and trip the gate on an unchanged kernel
        results[name] = {
            "seconds": round(per_iter, 5),
            "rel_to_anchor": round(model_num / per_iter / anchor, 6),
            "spread_pct": spread,
        }

    def guarded(name, fn):
        """Run one kernel's measurement; a kernel broken in THIS runner
        (e.g. a jax API the installed version lacks) records an explicit
        error entry — with no ``rel_to_anchor``, the gate skips it —
        instead of killing the whole grid."""
        try:
            fn()
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}"[:160]}

    # kmeans lloyd iteration (stream-anchored: reads the point set).
    # Measured below, interleaved with the checkpoint roundtrip — the two
    # flakiest gate metrics share one drift-resistant sample schedule.
    nk, f, k = 1 << 16, 16, 8
    ht.random.seed(0)
    x = ht.random.randn(nk, f, split=0).astype(ht.float32)
    float(x.sum())

    def fit():
        km = ht.cluster.KMeans(n_clusters=k, init="random", max_iter=10, tol=-1.0, random_state=0)
        km.fit(x)
        return km

    # hsvd (matmul-anchored)
    def bench_hsvd():
        nh, fh = 1 << 16, 64
        xh = ht.random.randn(nh, fh, split=0).astype(ht.float32)
        float(xh.sum())
        per, sp = _timeit(lambda: ht.linalg.hsvd_rank(xh, 10, compute_sv=False)[0],
                          lambda u: float(u.sum()), n_iter=1)
        record("hsvd", per, sp, 2.0 * nh * fh * fh, anchor_flops)

    guarded("hsvd", bench_hsvd)

    # fft3d 64^3 planar (stream-anchored, minimal 48B/el model)
    def bench_fft():
        os.environ["HEAT_TPU_PLANAR"] = "1"
        s3 = 64
        xf = ht.random.randn(s3, s3, s3, split=0).astype(ht.float32)
        float(xf.sum())

        def fft():
            return ht.fft.fftn(xf)

        def fetch_fft(r):
            re, im = r._planar
            return float(re[0, 0, 0])

        per, sp = _timeit(fft, fetch_fft, n_iter=2)
        record("fft3d_64", per, sp, 48.0 * s3**3, anchor_bw)

    guarded("fft3d_64", bench_fft)

    # distributed sort (stream-anchored; 2^18 keeps the CI job under a
    # minute — the PSRS program is the same shape at any extent).
    # Regime anchor (ROADMAP 5b): a bytes-moved bandwidth model like
    # fft's 48 B/el instead of the former bare one-pass 4 B/el ratio —
    # PSRS touches every f32 key ~7 times (local sort read+write, pivot
    # partition read, all-to-all exchange read+write, final merge
    # read+write), so the ratio now reads as "fraction of the minimal
    # PSRS traffic the kernel sustains vs the stream anchor" (the same
    # quantity /rooflinez reports per dispatch key from its bytes×time
    # ledger; docs/perf_history.md "Regime anchors").
    def bench_sort():
        n_el = 1 << 18
        xs = ht.random.randn(n_el, split=0).astype(ht.float32)
        float(xs.sum())
        per, sp = _timeit(lambda: ht.sort(xs)[0], lambda r: float(r[0]), n_iter=1, windows=3)
        bytes_moved = 28.0 * n_el  # 7 passes x 4 B/el
        record("sort_psrs", per, sp, bytes_moved, anchor_bw)
        results["sort_psrs"]["bytes_model"] = "psrs-7pass-28B/el"
        results["sort_psrs"]["model_gbytes_per_s"] = round(bytes_moved / per / 1e9, 4)

    guarded("sort_psrs", bench_sort)

    # sparse CSR ring SpMM (stream-anchored on the dense operand).
    # Regime anchor (ROADMAP 5b): the ring circulates the whole dense
    # operand past every one of the p shards (p reads of X), each shard
    # streams its CSR block once (12 B per nnz: f64 value + int32
    # column), and the f64 output is written once — vs the former bare
    # one-read-of-X model that undercounted the ring by ~10x.
    def bench_sparse():
        import scipy.sparse as sp_m

        A = sp_m.random(4096, 4096, density=0.01, random_state=0, format="csr", dtype=np.float64)
        sa = ht.sparse.sparse_csr_matrix(A, split=0)
        xd = ht.random.randn(4096, 64, split=0).astype(ht.float64)
        float(xd.sum())
        per, spd = _timeit(lambda: sa @ xd, lambda r: float(r[0, 0]), n_iter=2)
        p = xd.comm.size
        x_bytes = 8.0 * 4096 * 64
        bytes_moved = p * x_bytes + 12.0 * A.nnz + x_bytes
        record("sparse_spmm_ring", per, spd, bytes_moved, anchor_bw)
        results["sparse_spmm_ring"]["bytes_model"] = (
            f"ring-p{p}: p*X + 12B/nnz + out"
        )
        results["sparse_spmm_ring"]["model_gbytes_per_s"] = round(
            bytes_moved / per / 1e9, 4
        )

    guarded("sparse_spmm_ring", bench_sparse)

    # checkpoint save+restore roundtrip (stream-anchored on the state
    # bytes; catches resilience-layer overhead regressions — a lost
    # atomic-rename batching or a doubled checksum pass shows up here),
    # measured INTERLEAVED with the kmeans lloyd iteration: the two gate
    # metrics with the worst committed spreads take one window each per
    # round so runner drift cancels instead of accumulating on one of them
    import shutil
    import tempfile

    from heat_tpu.utils.checkpoint import Checkpointer

    ck_state = {
        "state": np.random.default_rng(0).standard_normal((512, 256)).astype(np.float32),
        "n_iter": 1,
        "shift": 0.5,
        "converged": False,
    }
    ck_dir = tempfile.mkdtemp(prefix="heat_tpu_ci_ck_")
    try:
        ck = Checkpointer(os.path.join(ck_dir, "sync"))
        step_box = {"i": 0}

        def ck_roundtrip():
            step_box["i"] += 1
            ck.save(step_box["i"], ck_state)
            return ck.restore(step_box["i"])

        (km_per, km_sp), (ck_per, ck_sp) = _timeit_interleaved(
            [
                (fit, lambda km: float(km.cluster_centers_.sum()), 1),
                (ck_roundtrip, lambda r: float(r["state"][0, 0]), 2),
            ],
            rounds=8,
        )
        record("kmeans_lloyd", km_per / 10, km_sp, nk * f * 4.0, anchor_bw)
        record("checkpoint_roundtrip", ck_per, ck_sp, 2.0 * ck_state["state"].nbytes, anchor_bw)

        # async checkpoint stall (overlap layer): the caller-visible cost
        # of one AsyncCheckpointer.save — snapshot + enqueue — for the
        # same state; the write itself is drained outside the window.  A
        # regression here (a snapshot that started copying device buffers
        # synchronously, a lost back-pressure bound) erases the overlap
        # win even while checkpoint_roundtrip stays healthy.
        ack = Checkpointer(os.path.join(ck_dir, "async")).as_async()
        ack.save(0, ck_state)
        ack.wait()  # warm (directory creation, first staging)
        stalls = []
        for i in range(1, 11):
            t0 = time.perf_counter()
            ack.save(i, ck_state)
            stalls.append(time.perf_counter() - t0)
            ack.wait()
        ack.close()
        best = min(stalls)
        med = float(np.median(stalls))
        record(
            "checkpoint_async_stall",
            best,
            round(100.0 * (med - best) / best if best else 0.0, 1),
            ck_state["state"].nbytes,
            anchor_bw,
        )
    finally:
        shutil.rmtree(ck_dir, ignore_errors=True)

    # telemetry overhead: the SAME kmeans lloyd kernel with span tracing
    # enabled vs disabled, paired per-round deltas (median) so runner
    # drift cancels out of the comparison instead of landing in it.
    # Gated as a hard cap (``max_overhead_pct``) rather than an anchored
    # ratio: the acceptance bound is absolute — instrumentation must stay
    # under 3% of the kernel it instruments.
    def bench_telemetry_overhead():
        from heat_tpu import telemetry

        prev = telemetry.tracing_enabled()

        def fit_traced():
            telemetry.set_tracing(True)
            return fit()

        def fit_untraced():
            telemetry.set_tracing(False)
            return fit()

        try:
            fetch = lambda km: float(km.cluster_centers_.sum())
            overhead_pct, en_per, dis_per, sp = _paired_overhead_pct(
                fit_traced, fit_untraced, fetch
            )
        finally:
            telemetry.set_tracing(prev)
            telemetry.clear_spans()
        results["telemetry_overhead"] = {
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": 3.0,
            "enabled_s": round(en_per, 5),
            "disabled_s": round(dis_per, 5),
            "spread_pct": sp,
        }

    guarded("telemetry_overhead", bench_telemetry_overhead)

    # introspection overhead: the SAME kmeans lloyd kernel with the FULL
    # ISSUE-6 introspection layer live (HTTP endpoint serving on an
    # ephemeral port, crash flight recorder armed, per-executable cost
    # accounting on, tracing on) vs everything off — paired per-round
    # median, same methodology as telemetry_overhead.  Hard cap: the
    # acceptance bound is absolute (<3% of the kernel it introspects).
    def bench_introspection_overhead():
        import shutil
        import tempfile
        import urllib.request

        from heat_tpu import telemetry
        from heat_tpu.core import dispatch
        from heat_tpu.telemetry import flight_recorder
        from heat_tpu.telemetry import server as tserver

        prev_trace = telemetry.tracing_enabled()
        prev_cost = dispatch.cost_accounting_enabled()
        fr_dir = tempfile.mkdtemp(prefix="heat_tpu_ci_fr_")

        # the passive pieces — bound HTTP socket, armed excepthook —
        # stay up for the WHOLE measurement; the per-op pieces (span
        # tracing, per-executable cost accounting) toggle per variant.
        # No concurrent scraper inside the timed windows: a ~0.6 ms
        # scrape landing randomly inside a ~40 ms window is a ±1.5%
        # coin flip that makes a hard-cap gate flaky; per-scrape cost
        # has its own metric (bench_telemetry scrape_metrics_us) — this
        # gate isolates the steady per-op tax on the kernel.  The warm
        # call below still exercises one scrape against the live server.
        srv = tserver.start_server(0)
        flight_recorder.install(fr_dir)
        urllib.request.urlopen(f"{srv.url}/metrics", timeout=5).read()

        def fit_introspected():
            telemetry.set_tracing(True)
            dispatch.set_cost_accounting(True)
            return fit()

        def fit_plain():
            telemetry.set_tracing(False)
            dispatch.set_cost_accounting(False)
            return fit()

        try:
            fetch = lambda km: float(km.cluster_centers_.sum())
            overhead_pct, on_per, off_per, sp = _paired_overhead_pct(
                fit_introspected, fit_plain, fetch
            )
        finally:
            tserver.stop_server()
            flight_recorder.uninstall()
            telemetry.set_tracing(prev_trace)
            dispatch.set_cost_accounting(prev_cost)
            telemetry.clear_spans()
            shutil.rmtree(fr_dir, ignore_errors=True)
        results["introspection_overhead"] = {
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": 3.0,
            "enabled_s": round(on_per, 5),
            "disabled_s": round(off_per, 5),
            "spread_pct": sp,
        }

    guarded("introspection_overhead", bench_introspection_overhead)

    # concurrency-sanitizer overhead: the SAME kmeans lloyd kernel with
    # HEAT_TPU_TSAN armed (every registered lock recording acquisition
    # stacks + guarded-structure checkpoints live) vs disarmed — paired
    # per-round median, same methodology as the other overhead gates.
    # Hard cap: the sanitizer must stay under 3% of the kernel it
    # sanitizes, or nobody will run the sanitized lane.
    def bench_tsan_overhead():
        from heat_tpu.analysis import tsan

        def fit_sanitized():
            tsan.arm("1")
            return fit()

        def fit_plain():
            tsan.disarm()
            return fit()

        try:
            fetch = lambda km: float(km.cluster_centers_.sum())
            overhead_pct, on_per, off_per, sp = _paired_overhead_pct(
                fit_sanitized, fit_plain, fetch
            )
            n_findings = tsan.finding_count()
        finally:
            tsan.disarm()
            tsan.clear_findings()
        results["tsan_overhead"] = {
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": 3.0,
            "enabled_s": round(on_per, 5),
            "disabled_s": round(off_per, 5),
            "spread_pct": sp,
            "findings_during_bench": n_findings,
        }

    guarded("tsan_overhead", bench_tsan_overhead)

    # elastic worker-loss recovery: a real subprocess fit killed mid-fit
    # (os._exit 137 via the fault plan), the mesh reshaped one device
    # smaller, the fit resumed from the surviving checkpoint.  The gated
    # quantity is the recovery latency — loss detection to the resumed
    # worker's first heartbeat (jax import + recompile + restore) — as
    # an absolute ``max_seconds`` cap: a recovery path that starts
    # re-importing twice, re-running lost iterations, or hanging on a
    # stale mesh blows the cap long before users feel it on a pod.
    def bench_elastic_recovery():
        import shutil
        import tempfile

        from heat_tpu.elastic.process import ProcessSupervisor, kmeans_worker_source

        d = tempfile.mkdtemp(prefix="heat_tpu_ci_elastic_")
        kill_plan = json.dumps(
            {"plan": {"kmeans.iter": [{"at": 1, "kind": "kill", "exit_code": 137}]}}
        )

        def build(ws, resume, attempt):
            src = kmeans_worker_source(d, resume_from=resume, x64=False)
            return (
                [sys.executable, "-c", src],
                {"HEAT_TPU_FAULT_PLAN": kill_plan if attempt == 0 else ""},
            )

        try:
            out = ProcessSupervisor(
                build, d, world_size=4, shrink_by=1, max_recoveries=2,
                poll_s=0.2, attempt_timeout_s=280,
            ).run()
            assert out["recoveries"] == 1 and out["world_size"] == 3, out
            results["elastic_recovery"] = {
                "seconds": round(out["recovery_s"][0], 2),
                "max_seconds": 120.0,
                "world_from": 4,
                "world_to": out["world_size"],
            }
        finally:
            shutil.rmtree(d, ignore_errors=True)

    guarded("elastic_recovery", bench_elastic_recovery)

    # online serving gates (ISSUE 9): a fitted KMeans saved, hot-loaded
    # into an InferenceService, and driven under sustained concurrent
    # load with an over-quota tenant shedding alongside.  Two absolute
    # caps (max_seconds): serving_p99 — the in-quota tail latency under
    # load (a recompile-per-request regression, a lost pad-to-bucket, or
    # a sleep-polling coalescer all blow it by an order of magnitude) —
    # and serving_overhead — the p50 stack tax of one request (admission
    # + coalescer handoff + scatter) over the same rows predicted
    # directly, which catches a lost warm path even when the tail gate
    # stays green.  Both records also assert the cache property:
    # steady-state new compiles must be 0.
    def bench_serving_gates():
        import shutil
        import tempfile
        import threading

        from heat_tpu import serving as srv
        from heat_tpu.core import dispatch
        from heat_tpu.resilience import OverloadedError
        from heat_tpu.serving import model_io

        rows = np.random.default_rng(3).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_srv_")
        svc = None
        try:
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
            svc.load("km", d)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            # stack overhead: p50 of a single warmed request through
            # admission+coalescer+scatter vs the same padded rows
            # predicted directly (the coalescer's own dispatch shape)
            est = svc.registry.get("km")
            direct, stacked = [], []
            for _ in range(40):
                t0 = time.perf_counter()
                model_io.infer(est, ht.array(rows[:8], split=None)).numpy()
                direct.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                svc.predict("km", rows[:8], timeout=30)
                stacked.append(time.perf_counter() - t0)
            overhead = float(np.median(stacked) - np.median(direct))

            # sustained load: 4 client threads x 60 varied-size requests,
            # one over-quota tenant hammering its token bucket alongside
            svc.set_quota("noisy", rate=2.0, burst=4.0)
            stop = threading.Event()
            noisy_counts = {"ok": 0, "shed": 0}

            def noisy():
                while not stop.is_set():
                    try:
                        svc.predict("km", rows[:2], tenant="noisy", timeout=30)
                        noisy_counts["ok"] += 1
                    except OverloadedError:
                        noisy_counts["shed"] += 1
                    time.sleep(0.002)

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)
            lat_lock = threading.Lock()
            latencies = []

            def client(w):
                for i in range(60):
                    n = sizes[(w + i) % len(sizes)]
                    t1 = time.perf_counter()
                    svc.predict("km", rows[:n], timeout=30)
                    dt = time.perf_counter() - t1
                    with lat_lock:
                        latencies.append(dt)

            nt = threading.Thread(target=noisy, daemon=True)
            s0 = dispatch.cache_stats()
            nt.start()
            t0 = time.perf_counter()
            clients = [
                threading.Thread(target=client, args=(w,), daemon=True)
                for w in range(4)
            ]
            for t in clients:
                t.start()
            for t in clients:
                t.join()
            wall = time.perf_counter() - t0
            stop.set()
            nt.join()
            s1 = dispatch.cache_stats()
            lat = np.sort(np.asarray(latencies))
            results["serving_p99"] = {
                "seconds": round(float(lat[int(len(lat) * 0.99)]), 5),
                "max_seconds": 0.25,
                "p50_seconds": round(float(lat[len(lat) // 2]), 5),
                "req_per_s": round(len(lat) / wall, 1),
                "steady_state_new_compiles": s1["misses"] - s0["misses"],
                "noisy_tenant_shed": noisy_counts["shed"],
                "noisy_tenant_admitted": noisy_counts["ok"],
            }
            results["serving_overhead"] = {
                "seconds": round(max(overhead, 0.0), 5),
                "max_seconds": 0.05,
                "stack_p50_s": round(float(np.median(stacked)), 5),
                "direct_p50_s": round(float(np.median(direct)), 5),
            }
        finally:
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("serving_p99", bench_serving_gates)

    # fleet-scale serving gates (ISSUE 13): real replica subprocesses
    # behind the fleet router (bench.fleet_scenario).  Three gates:
    # fleet_scaleout — aggregate routed req/s at 4 replicas over 1
    # replica, min 3x.  Each replica's capacity is its bounded admission
    # queue over the coalescing residency (sleep-shaped, NOT core-count-
    # shaped), so the ratio measures the router's bounded-load spillover
    # + queue-shed failover: a router that stops spreading pins it to
    # ~1x on any hardware.  fleet_kill_failed_requests — SIGKILL the
    # rendezvous-favorite replica under live load; bounded-retry
    # failover must absorb every in-flight loss (hard cap 0 failed).
    # fleet_cold_start / fleet_cold_compiles — a fresh replica boots
    # from the AOT executable cache + pre-warm manifest: first request
    # within 2x its own steady p99, and ZERO compiles after ready
    # (executable-cache hit rate 1.0 from request one).
    def bench_fleet_gates():
        import bench as bench_mod

        raw = bench_mod.fleet_scenario(
            scale_window_s=3.0, clients=12, kill_window_s=3.0
        )
        assert raw["drain_rc"] == 0, f"drain exited {raw['drain_rc']}: {raw}"
        assert raw["failed_1_replica"] + raw["failed_4_replicas"] == 0, raw
        results["fleet_scaleout"] = {
            "value": raw["scaleout_ratio"],
            "min_value": 3.0,
            "rate_1_replica": raw["rate_1_replica"],
            "rate_4_replicas": raw["rate_4_replicas"],
            "shed_1_replica": raw["shed_1_replica"],
            "shed_4_replicas": raw["shed_4_replicas"],
        }
        results["fleet_kill_failed_requests"] = {
            "count": raw["kill_failed_requests"],
            "max_count": 0,
            "requests_ok": raw["kill_requests_ok"],
            "failovers": raw["kill_failovers"],
        }
        results["fleet_cold_start"] = {
            "value": raw["cold_vs_steady_p99"],
            "max_value": 2.0,
            "first_request_ms": raw["cold_first_request_ms"],
            "steady_p99_ms": raw["steady_p99_ms"],
            "spawn_cold_s": raw["spawn_cold_s"],
        }
        results["fleet_cold_compiles"] = {
            "count": raw["cold_compiles_after_ready"],
            "max_count": 0,
            "aot_hits": raw["cold_aot_hits"],
        }

    guarded("fleet_scaleout", bench_fleet_gates)

    # request-tracing overhead (ISSUE 10): a sustained request stream
    # through the bench_serving service (same model, same size mix,
    # registry-default coalescing delay) with the FULL tracing stack
    # armed — trace context propagation, per-stage spans + tail-store
    # retention + bucket exemplars — vs tracing off, as the paired
    # per-round median of end-to-end request latency.  The stream is
    # SEQUENTIAL: a threaded closed loop couples the statistic to the
    # coalescer's deadline-pairing lottery (whether two in-flight
    # requests share a tick swings wall time by whole milliseconds in
    # either direction — measured ±5% run to run against a 3% cap),
    # while the sequential stream makes every request's latency the
    # deterministic sum of the coalescing delay and the serving stack,
    # which is exactly the path tracing instruments.  Hard cap: request
    # tracing must stay under 3% of end-to-end request latency, or
    # production keeps it off and p99 spikes stay undebuggable.
    def bench_tracing_overhead():
        import shutil
        import tempfile

        from heat_tpu import serving as srv
        from heat_tpu import telemetry
        from heat_tpu.telemetry import tracing as ttracing

        rows = np.random.default_rng(5).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_trace_")
        svc = None
        prev_trace = telemetry.tracing_enabled()
        try:
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_batch=64)  # default MAX_DELAY_MS
            svc.load("km", d)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)  # the bench_serving mix

            # per-REQUEST alternation: the tightest form of the PR 6
            # paired estimator — adjacent ~4 ms requests flip between
            # armed and off, so runner drift at any scale above one
            # request cancels out of the two medians; 200 pairs pin
            # each repetition's median.  The gate statistic is the MIN
            # over 3 repetitions (the kernel gates' min-of-windows
            # principle): the tracing tax is a fixed quantity and
            # environment pollution only ever ADDS to a repetition, so
            # the cleanest repetition estimates it best — measured
            # repetitions swing ~2x on this runner while their min
            # stays put.
            def one_rep(n_pairs=200):
                lat_on, lat_off = [], []
                for i in range(n_pairs):
                    sz = sizes[i % len(sizes)]
                    telemetry.set_tracing(True)
                    ttracing.set_exemplars(True)
                    t0 = time.perf_counter()
                    svc.predict("km", rows[:sz], timeout=30)
                    lat_on.append(time.perf_counter() - t0)
                    telemetry.set_tracing(False)
                    ttracing.set_exemplars(False)
                    t0 = time.perf_counter()
                    svc.predict("km", rows[:sz], timeout=30)
                    lat_off.append(time.perf_counter() - t0)
                on_med = float(np.median(lat_on))
                off_med = float(np.median(lat_off))
                return 100.0 * (on_med - off_med) / off_med, on_med, off_med

            reps = [one_rep() for _ in range(3)]
            overhead_pct, on_med, off_med = min(reps)
            results["tracing_overhead"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 3.0,
                "request_latency_on_s": round(on_med, 6),
                "request_latency_off_s": round(off_med, 6),
                "rep_overheads_pct": [round(r[0], 2) for r in reps],
                "pairs_per_rep": 200,
            }
        finally:
            telemetry.set_tracing(prev_trace)
            ttracing.set_exemplars(True)
            telemetry.clear_spans()
            ttracing.reset_store()
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("tracing_overhead", bench_tracing_overhead)

    # quality-signals overhead (ISSUE 11): the bench_serving request
    # stream with the FULL quality-signal layer armed — input-drift
    # sketches folding every coalesced batch, the default SLOs
    # registered, and the burn-rate monitor ticking at 4 Hz — vs
    # everything off.  Rep-level pairing (150 sequential requests per
    # side, order alternating per pair, min over 3 pairs): the sketch
    # fold runs per BATCH on the batcher thread and the monitor on its
    # own tick thread, so per-request alternation cannot toggle them
    # meaningfully; the min-over-pairs keeps the one-sided environment
    # noise out of the statistic like the tracing gate.  Hard cap: the
    # layer that decides "is this model degrading" must stay under 3%
    # of the request stream it judges, or production arms neither.
    def bench_quality_signals_overhead():
        import shutil
        import tempfile

        from heat_tpu import serving as srv
        from heat_tpu.telemetry import alerts as talerts
        from heat_tpu.telemetry import sketch as tsketch
        from heat_tpu.telemetry import slo as tslo

        rows = np.random.default_rng(7).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_qs_")
        svc = None
        prev_sketch = tsketch.sketch_enabled()
        try:
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_batch=64)  # default MAX_DELAY_MS
            svc.load("km", d)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)  # the bench_serving mix

            def one_side(armed, n=150):
                if armed:
                    tsketch.set_enabled(True)
                    tslo.install_default_slos()
                    tslo.start_monitor(0.25)
                else:
                    tslo.stop_monitor()
                    tsketch.set_enabled(False)
                lat = []
                try:
                    for i in range(n):
                        t0 = time.perf_counter()
                        svc.predict("km", rows[: sizes[i % len(sizes)]], timeout=30)
                        lat.append(time.perf_counter() - t0)
                finally:
                    if armed:
                        tslo.stop_monitor()
                return float(np.median(lat))

            pairs = []
            on_med = off_med = None
            for p in range(3):
                if p % 2 == 0:
                    on_med = one_side(True)
                    off_med = one_side(False)
                else:
                    off_med = one_side(False)
                    on_med = one_side(True)
                if off_med > 0:
                    pairs.append((100.0 * (on_med - off_med) / off_med, on_med, off_med))
            overhead_pct, on_med, off_med = min(pairs)
            results["quality_signals_overhead"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 3.0,
                "request_latency_on_s": round(on_med, 6),
                "request_latency_off_s": round(off_med, 6),
                "pair_overheads_pct": [round(p[0], 2) for p in pairs],
                "requests_per_side": 150,
            }
        finally:
            tsketch.set_enabled(prev_sketch)
            tslo.reset_monitors()
            talerts.clear_alerts()
            tsketch.SKETCHES.clear()
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("quality_signals_overhead", bench_quality_signals_overhead)

    # decision-journal + TSDB overhead (ISSUE 19): the bench_serving
    # request stream with the FULL explainability plane armed — the
    # durable decision journal writing atomic+CRC segments for a 20 Hz
    # control-plane decision storm (an order of magnitude above a real
    # controller's rate) on its emitter thread, and the TSDB sampler
    # scraping the whole metric registry through the allowlist at
    # 20 Hz — vs everything disarmed.  Rep-level pairing (150
    # sequential requests per side, order alternating per pair, min
    # over 3 pairs): the journal writes and scrapes happen on their
    # own threads, so per-request alternation cannot toggle them
    # meaningfully — the same argument as the quality-signals gate.
    # Hard cap: the layer that explains every autonomous action must
    # stay under 3% of the request stream it explains, or production
    # runs blind.
    def bench_journal_overhead():
        import shutil
        import tempfile
        import threading as th

        from heat_tpu import serving as srv
        from heat_tpu.telemetry import journal as tjournal
        from heat_tpu.telemetry import tsdb as ttsdb

        rows = np.random.default_rng(19).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_journal_")
        jdir = os.path.join(d, "journal")
        svc = None
        prev_interval = os.environ.get("HEAT_TPU_TSDB_INTERVAL_S")
        emitted = [0]
        try:
            os.environ["HEAT_TPU_TSDB_INTERVAL_S"] = "0.05"
            ttsdb.refresh_env()
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_batch=64)  # default MAX_DELAY_MS
            svc.load("km", d)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)  # the bench_serving mix

            def storm(stop):
                # a 20 Hz decision storm: each tick records the sample
                # its decision cites, then commits a durable segment
                i = 0
                while not stop.wait(0.05):
                    i += 1
                    ttsdb.record("fleet.p99_ms", 5.0 + (i % 7))
                    tjournal.emit(
                        "autoscaler", "tick", severity="info",
                        message="steady-state probe",
                        evidence={"i": i, "series": ["fleet.p99_ms"]},
                    )
                emitted[0] += i

            def one_side(armed, n=150):
                stop = th.Event()
                ticker = None
                if armed:
                    tjournal.set_journal_dir(jdir)
                    ttsdb.start_sampler()
                    ticker = th.Thread(target=storm, args=(stop,), daemon=True)
                    ticker.start()
                else:
                    ttsdb.stop_sampler()
                    tjournal.set_journal_dir(None)
                lat = []
                try:
                    for i in range(n):
                        t0 = time.perf_counter()
                        svc.predict("km", rows[: sizes[i % len(sizes)]], timeout=30)
                        lat.append(time.perf_counter() - t0)
                finally:
                    stop.set()
                    if ticker is not None:
                        ticker.join(5)
                    if armed:
                        ttsdb.stop_sampler()
                        tjournal.set_journal_dir(None)
                return float(np.median(lat))

            pairs = []
            on_med = off_med = None
            for p in range(3):
                if p % 2 == 0:
                    on_med = one_side(True)
                    off_med = one_side(False)
                else:
                    off_med = one_side(False)
                    on_med = one_side(True)
                if off_med > 0:
                    pairs.append((100.0 * (on_med - off_med) / off_med, on_med, off_med))
            overhead_pct, on_med, off_med = min(pairs)
            results["journal_overhead"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 3.0,
                "request_latency_on_s": round(on_med, 6),
                "request_latency_off_s": round(off_med, 6),
                "pair_overheads_pct": [round(p[0], 2) for p in pairs],
                "requests_per_side": 150,
                "decisions_emitted": emitted[0],
            }
        finally:
            if prev_interval is None:
                os.environ.pop("HEAT_TPU_TSDB_INTERVAL_S", None)
            else:
                os.environ["HEAT_TPU_TSDB_INTERVAL_S"] = prev_interval
            ttsdb.reset_tsdb()
            ttsdb.refresh_env()
            tjournal.set_journal_dir(None)
            tjournal.reset_journal()
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("journal_overhead", bench_journal_overhead)

    # shadow-traffic overhead (ISSUE 15): the bench_serving request
    # stream with a resident canary version and HEAT_TPU_SHADOW_FRACTION
    # at 1.0 — EVERY coalesced batch mirrored to the canary's own
    # inference on the shadow thread — vs shadowing disarmed, as the
    # paired p99 of primary-path request latency.  Three methodology
    # choices, each forced by a measured artifact on this runner:
    # (1) the stream is PACED (~4 ms gaps, ~50% duty cycle): the canary
    # contract is "mirroring is off the caller's LATENCY PATH", and a
    # saturated closed loop has no idle capacity for the shadow compute
    # to land in, so it measures a capacity collision (2x compute at
    # fraction 1.0 -> +10-20% tail on a CPU runner at ANY design), not
    # the latency-path tax; a production replica runs with headroom, and
    # the paced stream is that honest denominator (docs/serving.md);
    # (2) block-interleaved pairing (10 alternating blocks of 20 per
    # side per rep) with a TRIMMED tail estimator (drop the 2 worst,
    # mean of the remaining top 5%): the raw p99-of-200 swings ±30%
    # off-vs-off on this runner (one scheduler outlier IS the p99), the
    # trimmed form's off-vs-off floor measures ±3%;
    # (3) MIN over 4 reps (the tracing gate's principle: the tax is a
    # fixed quantity, pollution only ever ADDS, so the cleanest rep
    # estimates it best — armed reps measured [19.7, -1.6, -3.8] with
    # the pollution confined to single reps).  The controller runs
    # observe-only (auto off) so no promotion can mutate the registry
    # mid-measurement.  Hard cap: shadowing must stay under 3% of
    # primary-path p99, or production never arms it and every canary
    # ships blind.
    def bench_shadow_overhead():
        import shutil
        import tempfile

        from heat_tpu import serving as srv
        from heat_tpu.serving import canary as cnry
        from heat_tpu.telemetry import metrics as tmm

        rows = np.random.default_rng(15).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_shadow_")
        svc = None
        try:
            srv.save_model(km, d, version=1, name="km")
            srv.save_model(km, d, version=2, name="km")
            svc = srv.InferenceService(max_batch=64)  # default MAX_DELAY_MS
            svc.load("km", d, version=1)
            svc.load("km", d, version=2, activate=False)  # the canary
            svc.canary.auto = False  # observe-only: registry stays put
            svc.canary.min_rows = 1 << 30  # never decide mid-gate
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])
            # warm the shadow lane too (its first mirrored batch pays
            # the canary estimator's device upload)
            svc.canary.fraction = 1.0
            for b in (1, 8, 64):
                svc.predict("km", rows[:b])
            svc.canary.wait_idle(30)

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)  # the bench_serving mix

            def block(armed, n=20):
                svc.canary.fraction = 1.0 if armed else 0.0
                lat = []
                for i in range(n):
                    t0 = time.perf_counter()
                    svc.predict("km", rows[: sizes[i % len(sizes)]], timeout=30)
                    lat.append(time.perf_counter() - t0)
                    time.sleep(0.004)  # the paced-stream headroom
                if armed:
                    svc.canary.wait_idle(30)
                return lat

            def tail(samples):
                s = np.sort(np.asarray(samples))[:-2]
                k = max(1, int(len(s) * 0.05))
                return float(s[-k:].mean())

            def one_rep(blocks=10):
                on, off = [], []
                for b in range(blocks):
                    if b % 2 == 0:
                        on += block(True)
                        off += block(False)
                    else:
                        off += block(False)
                        on += block(True)
                t_on, t_off = tail(on), tail(off)
                return 100.0 * (t_on - t_off) / t_off, t_on, t_off

            c0 = tmm.counter("canary.comparisons").value
            reps = [one_rep() for _ in range(4)]
            overhead_pct, on_p99, off_p99 = min(reps)
            results["shadow_overhead"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 3.0,
                "request_p99_shadowed_s": round(on_p99, 6),
                "request_p99_bare_s": round(off_p99, 6),
                "rep_overheads_pct": [round(r[0], 2) for r in reps],
                "requests_per_side_per_rep": 200,
                "shadow_batches_compared": tmm.counter("canary.comparisons").value - c0,
            }
        finally:
            if svc is not None:
                svc.close()
            cnry.reset_canary_state()
            shutil.rmtree(d, ignore_errors=True)

    guarded("shadow_overhead", bench_shadow_overhead)

    # streaming kill+resume recovery (ISSUE 17): a real subprocess
    # streaming-KMeans fit over a durable segment log, os._exit-killed by
    # the fault plan at the 5th ``stream.commit`` window boundary, then
    # resumed in-process from the surviving checkpoint directory over the
    # same log.  The gated quantity is the resume latency — restore of
    # the committed {model state, offset} pair plus the replay of every
    # window from that offset to the stream end — as an absolute cap: a
    # resume path that re-reads the whole log from offset 0, loses the
    # committed offset (and silently re-trains), or hangs on a torn
    # segment blows the cap.  The record also asserts exactly-once
    # semantics: the resumed offset must land on the stream end.
    def bench_streaming_kill_resume():
        import shutil
        import subprocess
        import tempfile

        from heat_tpu.streaming import FileSegmentLog, StreamingKMeans
        from heat_tpu.utils.checkpoint import Checkpointer

        d = tempfile.mkdtemp(prefix="heat_tpu_ci_stream_kill_")
        window, feat, n_windows = 64, 16, 12
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import sys\n"
            "from heat_tpu.streaming import FileSegmentLog, StreamingKMeans\n"
            "StreamingKMeans(n_clusters=8, window_rows=%d, commit_every=1,\n"
            "                checkpoint_dir=sys.argv[1], resume_from=sys.argv[1]\n"
            "                ).fit_stream(FileSegmentLog(sys.argv[2]))\n" % window
        )
        try:
            log_dir = os.path.join(d, "log")
            rows = np.random.default_rng(21).standard_normal(
                (window * n_windows, feat)).astype(np.float32)
            FileSegmentLog(log_dir, segment_rows=512).append(rows)
            ck = os.path.join(d, "ck")
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
                {"plan": {"stream.commit": [
                    {"at": 5, "kind": "kill", "exit_code": 137}]}}
            )
            proc = subprocess.run(
                [sys.executable, "-c", child, ck, log_dir],
                env=env, capture_output=True, timeout=280,
            )
            assert proc.returncode == 137, proc.stderr.decode()[-500:]
            step = Checkpointer(ck).latest_step()
            assert step is not None and step < n_windows, step

            t0 = time.perf_counter()
            resumed = StreamingKMeans(
                n_clusters=8, window_rows=window, commit_every=1,
                checkpoint_dir=ck, resume_from=ck,
            ).fit_stream(FileSegmentLog(log_dir))
            resume_s = time.perf_counter() - t0
            assert resumed.offset_ == window * n_windows, resumed.offset_
            results["streaming_kill_resume"] = {
                "seconds": round(resume_s, 3),
                "max_seconds": 60.0,
                "killed_at_window": step,
                "windows_replayed": n_windows - step,
                "child_exit": proc.returncode,
            }
        finally:
            shutil.rmtree(d, ignore_errors=True)

    guarded("streaming_kill_resume", bench_streaming_kill_resume)

    # streaming model staleness (ISSUE 17): how stale a served model gets
    # before the continuous-learning loop replaces it.  A streamed KMeans
    # is served with a drift baseline, covariate-shifted traffic is
    # driven through it, and the clock runs from the first drifted batch
    # to the refreshed canary AUTO-promoting — drift detection (sketch
    # PSI over the live window) + online re-fit from the warm checkpoint
    # + save with a FRESH baseline + shadow compare + promote, end to
    # end.  An absolute cap: a refresh driver that never fires, a
    # baseline that keeps the alert latched (vetoing promotion), or a
    # canary that never collects comparisons all show up as a blown cap,
    # not a silent stale model.
    def bench_streaming_staleness():
        import shutil
        import tempfile

        from heat_tpu import serving as srv
        from heat_tpu.serving import canary as cnry
        from heat_tpu.streaming import FileSegmentLog, RefreshDriver, StreamingKMeans
        from heat_tpu.telemetry import alerts as _al
        from heat_tpu.telemetry import sketch as _sk

        feat = 16
        centers = np.array([[0.0] * feat, [40.0] * feat, [80.0] * feat], np.float32)

        def rows_of(n, rng, shift=0.0):
            labels = np.arange(n) % 3
            return (centers[labels]
                    + rng.standard_normal((n, feat)).astype(np.float32) * 0.5
                    + np.float32(shift)).astype(np.float32)

        d = tempfile.mkdtemp(prefix="heat_tpu_ci_stream_stale_")
        svc = None
        try:
            log = FileSegmentLog(os.path.join(d, "log"), segment_rows=1024)
            log.append(rows_of(64 * 8, np.random.default_rng(1)))
            ck = os.path.join(d, "ck")
            km = StreamingKMeans(n_clusters=3, window_rows=64, commit_every=1,
                                 checkpoint_dir=ck, resume_from=ck)
            km.fit_stream(log)
            sk = _sk.ModelSketch("stream_km", feat)
            sk.update(km.recent_window_)
            md = os.path.join(d, "models")
            srv.save_model(km.to_estimator(), md, version=1, name="stream_km",
                           baseline=sk.doc())
            svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
            svc.load("stream_km", md, version=1)
            svc.canary.fraction = 1.0
            svc.canary.min_rows = 48

            def fitter():
                log.append(rows_of(64 * 4, np.random.default_rng(2), shift=4.0))
                fresh = StreamingKMeans(n_clusters=3, window_rows=64,
                                        commit_every=1, checkpoint_dir=ck,
                                        resume_from=ck)
                return fresh.fit_stream(log)

            drv = RefreshDriver(svc, "stream_km", md, fitter)
            rng = np.random.default_rng(9)
            t0 = time.perf_counter()
            deadline = t0 + 120.0
            while time.perf_counter() < deadline:
                svc.predict("stream_km", rows_of(8, rng, shift=4.0))
                drv.check()
                if svc.registry.active_version("stream_km") == 2:
                    break
            staleness_s = time.perf_counter() - t0
            assert svc.registry.active_version("stream_km") == 2, \
                "refresh never promoted"
            assert not _al.is_firing("drift:stream_km",
                                     labels={"model": "stream_km"})
            results["streaming_staleness"] = {
                "seconds": round(staleness_s, 3),
                "max_seconds": 30.0,
                "refreshes": drv.refreshes,
                "promoted_version": 2,
            }
        finally:
            if svc is not None:
                svc.close()
            cnry.reset_canary_state()
            _al.clear_alerts()
            _sk.SKETCHES.clear()
            shutil.rmtree(d, ignore_errors=True)

    guarded("streaming_staleness", bench_streaming_staleness)

    # multi-tenant QoS noisy neighbor (ISSUE 18): a latency-class tenant's
    # request stream measured SOLO, then again with four batch-class
    # clients flooding 64-row requests through the same service — the
    # strict-priority depth gate plus EDF batch pick must keep the
    # latency tail pinned to its solo shape.  The flood clients honor
    # the shed's lane-aware ``retry_after_s`` hint (clamped to
    # [5, 50] ms) — a client that hammers a full lane in a busy loop
    # measures GIL churn from its own retry storm (+15% on this runner),
    # not the scheduler; the Retry-After contract exists exactly so
    # well-behaved batch clients don't.  Methodology follows the
    # shadow gate: block-interleaved pairing (alternating contended/solo
    # blocks so runner drift divides out), a TRIMMED tail estimator
    # (drop the 2 worst, mean of the remaining top 5% — one scheduler
    # outlier must not BE the p99), and the MIN over reps (the QoS tax
    # is a fixed quantity; pollution only ever adds).  Two gates:
    # qos_noisy_neighbor — contended trimmed-p99 within 10% of solo —
    # and qos_latency_sheds — ZERO latency-class requests shed while
    # the batch lane saturates (the reserved-share admission property).
    def bench_qos_noisy_neighbor():
        import shutil
        import tempfile
        import threading

        from heat_tpu import serving as srv
        from heat_tpu.resilience import OverloadedError

        rows = np.random.default_rng(18).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_qos_")
        svc = None
        try:
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
            svc.load("km", d)
            svc.set_class("slo", "latency")
            svc.set_class("bulk", "batch")
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            sizes = (1, 3, 7, 12)  # the latency-class small-request mix
            sheds = {"latency": 0, "batch_ok": 0, "batch_shed": 0}

            def lat_block(i0, n=25):
                lat = []
                for i in range(n):
                    t0 = time.perf_counter()
                    try:
                        svc.predict(
                            "km", rows[: sizes[(i0 + i) % len(sizes)]],
                            tenant="slo", timeout=30,
                        )
                    except OverloadedError:
                        sheds["latency"] += 1
                        continue
                    lat.append(time.perf_counter() - t0)
                return lat

            stop = threading.Event()
            flood_on = threading.Event()

            def bulk():
                while not stop.is_set():
                    if not flood_on.is_set():
                        flood_on.wait(0.01)
                        continue
                    try:
                        svc.predict("km", rows[:64], tenant="bulk", timeout=30)
                        sheds["batch_ok"] += 1
                    except OverloadedError as e:
                        sheds["batch_shed"] += 1
                        time.sleep(min(max(e.retry_after_s or 0.01, 0.005), 0.05))

            floods = [threading.Thread(target=bulk, daemon=True) for _ in range(4)]
            for t in floods:
                t.start()
            # warm the contended regime once outside the sample set
            flood_on.set()
            time.sleep(0.1)
            lat_block(0)
            flood_on.clear()
            time.sleep(0.05)

            def tail(samples):
                s = np.sort(np.asarray(samples))[:-2]
                k = max(1, int(len(s) * 0.05))
                return float(s[-k:].mean())

            def one_rep(blocks=8):
                on, off = [], []
                for b in range(blocks):
                    armed_first = b % 2 == 0
                    for armed in ((True, False) if armed_first else (False, True)):
                        if armed:
                            flood_on.set()
                            time.sleep(0.05)  # flood back to steady state
                        else:
                            flood_on.clear()
                            time.sleep(0.05)  # drain the batch lane
                        (on if armed else off).extend(lat_block(b * 25))
                t_on, t_off = tail(on), tail(off)
                return 100.0 * (t_on - t_off) / t_off, t_on, t_off

            try:
                reps = [one_rep() for _ in range(4)]
            finally:
                stop.set()
                flood_on.set()  # unblock any waiter
                for t in floods:
                    t.join()
            overhead_pct, on_p99, off_p99 = min(reps)
            results["qos_noisy_neighbor"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 10.0,
                "latency_p99_contended_s": round(on_p99, 6),
                "latency_p99_solo_s": round(off_p99, 6),
                "rep_overheads_pct": [round(r[0], 2) for r in reps],
                "batch_admitted": sheds["batch_ok"],
                "batch_shed": sheds["batch_shed"],
            }
            results["qos_latency_sheds"] = {
                "count": sheds["latency"],
                "max_count": 0,
                "batch_shed_alongside": sheds["batch_shed"],
            }
        finally:
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("qos_noisy_neighbor", bench_qos_noisy_neighbor)

    # preempt + resume (ISSUE 18): a real subprocess checkpointed KMeans
    # fit, preempted at a resumable_fit_loop chunk boundary by a latency
    # admission spike (HEAT_TPU_QOS_PREEMPT_ON_LATENCY raises the
    # process-wide gate; the fault plan converts the qos.preempt site
    # into an os._exit kill), then resumed in-process from the surviving
    # boundary checkpoint.  The gated quantity is the resume latency —
    # restore + the remaining iterations — as an absolute cap; the
    # record also asserts the QoS contract end to end: the killed+resumed
    # centers must be BITWISE equal to an uninterrupted fit's.
    def bench_qos_preempt_resume():
        import shutil
        import subprocess
        import tempfile

        from heat_tpu.utils.checkpoint import Checkpointer

        d = tempfile.mkdtemp(prefix="heat_tpu_ci_qos_preempt_")
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import sys, threading, time\n"
            "import heat_tpu as ht\n"
            "from heat_tpu.serving.admission import AdmissionController\n"
            "ht.random.seed(13)\n"
            "x = ht.random.randn(240, 6, split=0).astype(ht.float32)\n"
            "ac = AdmissionController(max_depth=64)\n"
            "ac.set_class('slo', 'latency')\n"
            "threading.Timer(0.05, lambda: ac.admit('slo', 1)).start()\n"
            "ht.cluster.KMeans(n_clusters=4, init='random', max_iter=40,\n"
            "                  tol=1e-4, random_state=3, checkpoint_every=2,\n"
            "                  checkpoint_dir=sys.argv[1]).fit(x)\n"
        )
        try:
            ck = os.path.join(d, "ck")
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["HEAT_TPU_QOS_PREEMPT_ON_LATENCY"] = "1"
            env["HEAT_TPU_ASYNC_CKPT"] = "0"  # boundary save durable pre-kill
            env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
                {"plan": {"qos.preempt": [
                    {"at": 0, "kind": "kill", "exit_code": 137}]}}
            )
            proc = subprocess.run(
                [sys.executable, "-c", child, ck],
                env=env, capture_output=True, timeout=280,
            )
            assert proc.returncode == 137, proc.stderr.decode()[-500:]
            step = Checkpointer(ck).latest_step()
            assert step is not None and step < 40, step

            ht.random.seed(13)
            x = ht.random.randn(240, 6, split=0).astype(ht.float32)

            def km(**kw):
                return ht.cluster.KMeans(
                    n_clusters=4, init="random", max_iter=40, tol=1e-4,
                    random_state=3, **kw,
                ).fit(x)

            t0 = time.perf_counter()
            resumed = km(checkpoint_every=2, checkpoint_dir=ck, resume_from=ck)
            resume_s = time.perf_counter() - t0
            plain = km()
            assert np.array_equal(
                np.asarray(resumed.cluster_centers_._dense()),
                np.asarray(plain.cluster_centers_._dense()),
            ), "killed+resumed fit is not bitwise equal to the uninterrupted fit"
            assert resumed.n_iter_ == plain.n_iter_
            results["qos_preempt_resume"] = {
                "seconds": round(resume_s, 3),
                "max_seconds": 60.0,
                "preempted_at_iter": step,
                "iters_total": int(plain.n_iter_),
                "child_exit": proc.returncode,
                "bitwise_equal": True,
            }
        finally:
            shutil.rmtree(d, ignore_errors=True)

    guarded("qos_preempt_resume", bench_qos_preempt_resume)

    # precision-analyzer overhead (ISSUE 12): the SAME kmeans lloyd
    # kernel with HEAT_TPU_ANALYZE=warn — the J2 dtype-flow walker, the
    # J3 static peak-HBM estimator AND the J1 HLO checks armed at the
    # dispatch hook — vs off, paired per-round median like the other
    # overhead gates.  The analyzers only run on executable-cache
    # MISSES, so the warmed steady state (the production shape) must
    # measure ~0; a regression here means someone put analyzer work on
    # the per-hit path.  Off-mode stays one dict lookup per miss by
    # construction (dispatch._maybe_analyze).  Hard cap <3%.
    def bench_analysis_precision_overhead():
        import warnings as _w

        from heat_tpu import analysis
        from heat_tpu.analysis import diagnostics as adiag

        def fit_analyzed():
            adiag.set_analysis_mode("warn")
            with _w.catch_warnings():
                _w.simplefilter("ignore")
                return fit()

        def fit_plain():
            adiag.set_analysis_mode("off")
            return fit()

        try:
            fetch = lambda km: float(km.cluster_centers_.sum())
            overhead_pct, on_per, off_per, sp = _paired_overhead_pct(
                fit_analyzed, fit_plain, fetch
            )
        finally:
            adiag.set_analysis_mode("off")
            analysis.clear_diagnostics()
        results["analysis_precision_overhead"] = {
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": 3.0,
            "enabled_s": round(on_per, 5),
            "disabled_s": round(off_per, 5),
            "spread_pct": sp,
        }

    guarded("analysis_precision_overhead", bench_analysis_precision_overhead)

    # bf16 KMeans predict (ISSUE 12): the tolerance-policy mixed-
    # precision predict path (HEAT_TPU_PREDICT_DTYPE=bfloat16 — bf16
    # cross term, f32 norms + accumulation) vs the native f32 path on
    # the same fitted model and rows.  Records the speedup and the
    # max-abs distance error against the f32 reference (the tolerance
    # policy's rtol budget is 0.02 of the distance scale) plus label
    # agreement.  Informational record ("value" = speedup, trend-
    # tracked): CPU runners have no bf16 MXU, so the time ratio here is
    # about regression visibility, not the TPU win.
    def bench_kmeans_predict_bf16():
        from heat_tpu.analysis import precision_policy as pp
        from heat_tpu.spatial import distance

        km = fit()
        rows = ht.array(
            np.random.default_rng(11).standard_normal((4096, f)).astype(np.float32),
            split=None,
        )
        fetch = lambda r: int(np.asarray(r._dense())[0])

        def pred():
            return km.predict(rows)

        f32_per, f32_sp = _timeit(pred, fetch)
        lab32 = np.asarray(pred()._dense())
        prev = pp.set_predict_dtype("bfloat16")
        try:
            bf_per, bf_sp = _timeit(pred, fetch)
            lab16 = np.asarray(pred()._dense())
        finally:
            pp.set_predict_dtype(prev)
        xd = rows._dense()
        cd = km.cluster_centers_._dense()
        ref = np.asarray(distance._pairwise_euclidean(xd, cd))
        lo = np.asarray(distance._pairwise_euclidean_bf16(xd, cd))
        err = float(np.abs(ref - lo).max())
        scale = float(np.abs(ref).max())
        results["kmeans_predict_bf16"] = {
            "value": round(f32_per / bf_per, 3),  # speedup_x (trend headline)
            "f32_s": round(f32_per, 5),
            "bf16_s": round(bf_per, 5),
            "spread_pct": max(f32_sp, bf_sp),
            "max_abs_err": round(err, 6),
            "rel_err": round(err / scale, 6) if scale else 0.0,
            "policy_rtol": 0.02,
            "labels_agree_pct": round(100.0 * float((lab32 == lab16).mean()), 2),
        }

    guarded("kmeans_predict_bf16", bench_kmeans_predict_bf16)

    # roofline-observatory overhead (ISSUE 14): the SAME kmeans lloyd
    # kernel with the execution ledger + fenced sampling + watermark
    # cross-checks armed (HEAT_TPU_PERF_SYNC_EVERY at its default 16)
    # vs the observatory disarmed — paired per-round median like the
    # other overhead gates.  Hard cap <3%: the observatory is ON BY
    # DEFAULT in production, so its per-dispatch tax must be noise.
    def bench_observatory_overhead():
        from heat_tpu.telemetry import observatory as obsv

        prev_sync = obsv.set_sync_every(16)

        def fit_observed():
            obsv.set_enabled(True)
            return fit()

        def fit_plain():
            obsv.set_enabled(False)
            return fit()

        try:
            fetch = lambda km: float(km.cluster_centers_.sum())
            overhead_pct, on_per, off_per, sp = _paired_overhead_pct(
                fit_observed, fit_plain, fetch
            )
        finally:
            obsv.set_enabled(True)
            obsv.set_sync_every(prev_sync)
            obsv.reset()
        results["observatory_overhead"] = {
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": 3.0,
            "enabled_s": round(on_per, 5),
            "disabled_s": round(off_per, 5),
            "spread_pct": sp,
        }

    guarded("observatory_overhead", bench_observatory_overhead)

    # roofline sanity (ISSUE 14): the calibrated matmul kernel driven
    # through the dispatch cache with every call fenced must report at
    # least 20% of this runner's own measured peak — the end-to-end
    # proof that the ledger's time, the cost join's FLOPs and the
    # calibration all describe the same machine.  A broken fence (enqueue
    # time mistaken for device time), a dropped cost join, or a
    # miscalibrated peak all push the utilization off this floor.
    def bench_roofline_sanity():
        from heat_tpu.core import dispatch as disp
        from heat_tpu.telemetry import observatory as obsv

        obsv.reset_peaks()
        peaks = obsv.device_peaks(calibrate=True)
        prev_cost = disp.set_cost_accounting(True)
        prev_sync = obsv.set_sync_every(1)
        obsv.reset()
        try:
            side = 512
            buf = jax.device_put(np.ones((side, side), np.float32))
            for _ in range(12):
                disp.eager_apply(jnp.matmul, (buf, buf))
            rows = [
                r for r in obsv.ledger_report(peaks)
                if "matmul" in r["key"] and r.get("utilization") is not None
            ]
            assert rows, "the matmul must land in the ledger with a cost join"
            best = max(rows, key=lambda r: r["utilization"])
            results["roofline_sanity"] = {
                "value": round(best["utilization"], 4),
                "min_value": 0.2,
                "gflops_per_s": best["gflops_per_s"],
                "peak_gflops": round(peaks["flops"] / 1e9, 1),
                "bound": best["bound"],
                "calibration_source": peaks["source"],
            }
        finally:
            disp.set_cost_accounting(prev_cost)
            obsv.set_sync_every(prev_sync)
            obsv.reset()

    guarded("roofline_sanity", bench_roofline_sanity)

    # per-kernel roofline floors (ISSUE 16): roofline_sanity generalized
    # from the calibration matmul to the flagship kernels.  Each
    # kernel's computational core runs through dispatch.eager_apply with
    # every call fenced, and the gate is a min_value on the ledger's
    # utilization (achieved GFLOP/s or GB/s against this runner's own
    # calibrated peaks and the key's XLA cost model) — a regression in
    # DELIVERED bandwidth fails CI even when wall-time ratios drift
    # inside tolerance.  Values above 1.0 are expected for kernels whose
    # logical cost model overcounts physical traffic (kmeans' fused
    # distance matrix, spgemm's ELL expansion); the floor is calibrated
    # per kernel at roughly 0.4x the utilization measured at gate
    # introduction on this runner, so it trips on structural
    # regressions (a lost fusion, a dead fast path, a dropped cost
    # join), not on runner weather.
    def bench_kernel_floors():
        import scipy.sparse as sp_m

        from heat_tpu.core import dispatch as disp
        from heat_tpu.fft import _planar
        from heat_tpu.sparse import _planes as spl
        from heat_tpu.telemetry import observatory as obsv

        obsv.reset_peaks()
        peaks = obsv.device_peaks(calibrate=True)
        prev_cost = disp.set_cost_accounting(True)
        prev_sync = obsv.set_sync_every(1)
        obsv.reset()
        try:
            kf = jax.random.PRNGKey(7)

            # named pure-jax kernel cores: the ledger joins rows by the
            # callable's __name__, so each name below IS the gate key
            def fftn_leading(xx):
                fre, fim = _planar.real_fftn(xx, [0, 1, 2], None)
                return fre + fim

            def kmeans_lloyd(xx, cc):
                d = (
                    (xx * xx).sum(1)[:, None]
                    - 2.0 * xx @ cc.T
                    + (cc * cc).sum(1)[None, :]
                )
                oh = jax.nn.one_hot(jnp.argmin(d, 1), cc.shape[0], dtype=xx.dtype)
                return (oh.T @ xx) / jnp.maximum(oh.sum(0)[:, None], 1.0)

            def sort_psrs(xx):
                return jnp.sort(xx)

            def hsvd_leaf(xx):
                g = jnp.matmul(xx.T, xx, precision=jax.lax.Precision.HIGHEST)
                _lam, vv = jnp.linalg.eigh(g)
                return jnp.matmul(
                    xx, vv[:, ::-1][:, :10], precision=jax.lax.Precision.HIGHEST
                )

            # the PRODUCTION output-sparse SpGEMM step program (ELL
            # expand + canonicalize), single-shard instance
            A = sp_m.random(
                2048, 2048, density=0.01, random_state=0, format="csr",
                dtype=np.float32,
            )
            sa = ht.sparse.sparse_csr_matrix(A)
            r_max = spl.max_row_occupancy(
                sa._comp, sa._nshards, sa._capacity, sa._comp_pad,
                sa._dist, sa.comm,
            )
            step = spl._spgemm_step_prog(
                sa.comm, 1, sa._capacity, sa._capacity, sa._comp_pad,
                sa._comp_pad, r_max, "float32", False,
            )

            def spgemm_ring(ac, ao, av, t):
                return step(ac, ao, av, ac, ao, av, t)

            drives = {
                "fftn_leading": (
                    fftn_leading,
                    (jax.random.normal(kf, (64, 64, 64), jnp.float32),),
                ),
                "kmeans_lloyd": (
                    kmeans_lloyd,
                    (jax.random.normal(kf, (1 << 16, 16), jnp.float32),
                     jax.random.normal(kf, (8, 16), jnp.float32)),
                ),
                "sort_psrs": (
                    sort_psrs,
                    (jax.random.normal(kf, (1 << 20,), jnp.float32),),
                ),
                "hsvd_leaf": (
                    hsvd_leaf,
                    (jax.random.normal(kf, (1 << 14, 64), jnp.float32),),
                ),
                "spgemm_ring": (
                    spgemm_ring,
                    (sa._comp, sa._other, sa._val, jnp.asarray(0, jnp.int32)),
                ),
            }
            # floors sit ~3x under the WORST utilization observed across
            # calibration runs on this runner class (run-to-run swing is
            # ~2.5x — the peaks and the kernels calibrate at different
            # moments of a shared-host job), while a route regression (a
            # kernel falling off its engine onto a fallback) costs 5-20x:
            # noise clears the floor, a lost engine does not
            floors = {
                "fftn_leading": 0.0012,
                "kmeans_lloyd": 0.35,
                "sort_psrs": 0.0007,
                "hsvd_leaf": 0.07,
                "spgemm_ring": 0.55,
            }
            for _ in range(8):
                for opf, opargs in drives.items():
                    disp.eager_apply(opargs[0], opargs[1])
            rows = obsv.ledger_report(peaks)
            for name, floor in floors.items():
                cand = [
                    r for r in rows
                    if name in r["key"] and r.get("utilization") is not None
                ]
                if not cand:
                    results[f"kernel_floor_{name}"] = {
                        "error": "no ledger row with a cost join"
                    }
                    continue
                best = max(cand, key=lambda r: r["utilization"])
                results[f"kernel_floor_{name}"] = {
                    "value": round(best["utilization"], 4),
                    "min_value": floor,
                    "gflops_per_s": best["gflops_per_s"],
                    "gbytes_per_s": best["gbytes_per_s"],
                    "bound": best["bound"],
                }
        finally:
            disp.set_cost_accounting(prev_cost)
            obsv.set_sync_every(prev_sync)
            obsv.reset()

    guarded("kernel_floors", bench_kernel_floors)

    # compat-matrix smoke lane (ROADMAP 5a): the collective-wrapper test
    # subset under BOTH core/_compat.py resolver branches (legacy
    # experimental adapter AND the native top-level API, simulated when
    # this jax lacks it) — gated as a hard-cap count: a red branch fails
    # the same perf_gate run that guards the kernels
    def bench_compat_matrix():
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from compat_matrix import run_matrix

        results["compat_matrix"] = run_matrix(quiet=True)

    guarded("compat_matrix", bench_compat_matrix)

    # sanitized test lane: the threaded test subset (test_overlap /
    # test_introspection / test_telemetry) in a subprocess under
    # HEAT_TPU_TSAN=1 — gated as a hard-cap count: red tests or ANY
    # sanitizer finding (lock-order cycle, off-thread unguarded access)
    # fails the same perf_gate run that guards the kernels
    def bench_tsan_lane():
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tsan_lane import run_lane

        results["tsan_lane"] = run_lane(quiet=True)

    guarded("tsan_lane", bench_tsan_lane)

    # framework-invariant lint gate (scripts/lint_gate.py): violations
    # are reported alongside the perf metrics and gated as a hard-cap
    # count — ANY new violation (not in scripts/lint_baseline.json)
    # fails the same perf_gate run that guards the kernels
    def bench_lint_gate():
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from lint_gate import run_gate

        res = run_gate(quiet=True)
        results["lint_new_violations"] = {
            "count": res["new_count"],
            "max_count": 0,
            "total_violations": res["total"],
            "baseline_violations": res["baseline"],
            "stale_baseline": res["fixed_count"],
            "items": [
                f"{e['file']}:{e['line']} {e['rule']}" for e in res["new"]
            ],
        }

    guarded("lint_new_violations", bench_lint_gate)

    # control-plane protocol gate (ISSUE 20): the bounded model checker
    # must prove every declared PROPERTY on the shipped PROTOCOLS
    # registry, the registry itself must be hygienic, and the checker
    # must still have teeth — each seeded defect class
    # (--seed-defect's triple) must yield a counterexample — plus any
    # runtime H805s the protocol_overhead storm stepped into, all gated
    # as one hard-cap count.
    def bench_protocol_gate():
        from heat_tpu.analysis import model_check, protocols

        problems = protocols.registry_problems()
        violated = model_check.check_all()
        missed = []
        for name in ("refresh_livelock", "breaker_double_probe", "autoscaler_flap"):
            p, e, props = model_check.seeded_defect(name)
            if not model_check.check_all(p, e, props):
                missed.append(name)
        runtime = results.get("protocol_overhead", {}).get("violations", 0)
        results["protocol_gate"] = {
            "count": len(problems) + len(violated) + len(missed) + runtime,
            "max_count": 0,
            "machines": len(protocols.PROTOCOLS),
            "properties_checked": len(protocols.PROPERTIES),
            "declared_pairs": len(protocols.declared_pairs()),
            "registry_problems": problems,
            "violated_properties": [v["property"] for v in violated],
            "seeded_defects_missed": missed,
            "runtime_violations": runtime,
        }

    # protocol-conformance overhead (ISSUE 20): the bench_serving
    # request stream with a 20 Hz declared-pair decision storm running
    # on BOTH sides — HEAT_TPU_PROTOCOL_CHECK=warn (every emit stepped
    # through the declared machines) vs off (one global read per emit)
    # — as the paired median of request latency, best of 3 alternating
    # pairs (the bench_journal_overhead methodology; only the
    # conformance mode differs between sides, so the delta isolates
    # the hook).  The storm walks the preempt machine's legal
    # raise/clear pair each tick, so the armed side steps REAL
    # transitions and must step them clean (violations feed the
    # protocol_gate hard cap).
    def bench_protocol_overhead():
        import shutil
        import tempfile
        import threading as th

        from heat_tpu import serving as srv
        from heat_tpu.analysis import conformance
        from heat_tpu.analysis.protocols import (
            ACTOR_PREEMPT, PREEMPT_CLEAR, PREEMPT_RAISE,
        )
        from heat_tpu.telemetry import journal as tjournal

        rows = np.random.default_rng(23).standard_normal((64, f)).astype(np.float32)
        km = fit()
        d = tempfile.mkdtemp(prefix="heat_tpu_ci_protocol_")
        svc = None
        emitted = [0]
        try:
            srv.save_model(km, d, version=1, name="km")
            svc = srv.InferenceService(max_batch=64)  # default MAX_DELAY_MS
            svc.load("km", d)
            for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
                svc.predict("km", rows[:b])

            sizes = (1, 3, 7, 12, 18, 27, 33, 50, 64)  # the bench_serving mix

            def storm(stop):
                # a complete raise/clear pair per tick: idle -> raised
                # -> idle, so the machine is back at its initial state
                # wherever the stop lands and every armed side resumes
                # on a legal edge
                i = 0
                while not stop.wait(0.05):
                    i += 1
                    for action in (PREEMPT_RAISE, PREEMPT_CLEAR):
                        tjournal.emit(
                            ACTOR_PREEMPT, action, severity="info",
                            message="protocol-overhead storm",
                            evidence={"gate": "bench", "i": i},
                        )
                emitted[0] += 2 * i

            def one_side(armed, n=150):
                conformance.set_protocol_mode("warn" if armed else "0")
                stop = th.Event()
                ticker = th.Thread(target=storm, args=(stop,), daemon=True)
                ticker.start()
                lat = []
                try:
                    for i in range(n):
                        t0 = time.perf_counter()
                        svc.predict("km", rows[: sizes[i % len(sizes)]], timeout=30)
                        lat.append(time.perf_counter() - t0)
                finally:
                    stop.set()
                    ticker.join(5)
                    conformance.set_protocol_mode("0")
                return float(np.median(lat))

            pairs = []
            on_med = off_med = None
            for p in range(3):
                if p % 2 == 0:
                    on_med = one_side(True)
                    off_med = one_side(False)
                else:
                    off_med = one_side(False)
                    on_med = one_side(True)
                if off_med > 0:
                    pairs.append((100.0 * (on_med - off_med) / off_med, on_med, off_med))
            stepped = len(conformance.violations())
            overhead_pct, on_med, off_med = min(pairs)
            results["protocol_overhead"] = {
                "overhead_pct": round(overhead_pct, 2),
                "max_overhead_pct": 3.0,
                "request_latency_on_s": round(on_med, 6),
                "request_latency_off_s": round(off_med, 6),
                "pair_overheads_pct": [round(pp[0], 2) for pp in pairs],
                "requests_per_side": 150,
                "storm_emits": emitted[0],
                "violations": stepped,
            }
        finally:
            conformance.set_protocol_mode("0")
            tjournal.reset_journal()
            if svc is not None:
                svc.close()
            shutil.rmtree(d, ignore_errors=True)

    guarded("protocol_overhead", bench_protocol_overhead)
    guarded("protocol_gate", bench_protocol_gate)

    # rolling-median trend gate (ROADMAP 5c): THIS run's headline
    # numbers appended to BENCH_HISTORY.jsonl's record, per-metric
    # k-run medians compared window-against-window — sustained drift
    # that single-run spread_pct hides fails the same perf_gate run.
    # Runs LAST so every gate metric above is in the judged set.
    def bench_perf_trend():
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_history import headline, headline_kind, trend_check

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        current_metrics = {
            name: headline(rec)
            for name, rec in results.items()
            if isinstance(rec, dict)
        }
        current_kinds = {
            name: headline_kind(rec)
            for name, rec in results.items()
            if isinstance(rec, dict) and headline_kind(rec) is not None
        }
        results["perf_trend"] = trend_check(
            os.path.join(repo, "BENCH_HISTORY.jsonl"),
            current_metrics, current_kinds,
        )

    guarded("perf_trend", bench_perf_trend)

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
