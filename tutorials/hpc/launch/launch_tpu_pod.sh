#!/usr/bin/env bash
# Launch an SPMD heat_tpu program on every host of a Cloud TPU pod slice.
#
# On Cloud TPU VMs, the coordinator/topology environment is pre-populated,
# so the program itself just calls ht.parallel.init() with no arguments
# (tutorials/hpc/01_pod_bringup.md); launching is "run the same command on
# every worker", which gcloud does natively:
#
#   ./launch_tpu_pod.sh my-pod us-east5-b train.py --epochs 10
set -euo pipefail

TPU_NAME="$1"; ZONE="$2"; shift 2

gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
  --zone "$ZONE" \
  --worker=all \
  --command="cd ~/heat_tpu && python $*"
