"""Perf regression gate: fail CI when a kernel's anchored ratio drops
below the committed record's median-minus-spread band (VERDICT r4 #7).

    python scripts/perf_gate.py BENCH_CI.json current.json [--margin-pct 30]

Rule per gated metric (every key with ``rel_to_anchor``):

    threshold = committed.rel * (1 - (committed.spread + current.spread
                + margin) / 100), clamped to >= 0.5 * committed.rel

    FAIL if current.rel < threshold

The margin absorbs cross-runner microarchitecture variance (the ratios
are anchored against same-job matmul/stream measurements, which removes
frequency/core-count scaling but not cache-hierarchy differences); the
0.5 clamp guarantees a deliberate 2x slowdown always fails.
"""

import argparse
import json
import sys


def gate(committed: dict, current: dict, margin_pct: float) -> int:
    failures = []
    for name, rec in committed.items():
        if not isinstance(rec, dict):
            continue
        # hard-cap count metrics (``max_count``): absolute integer bound,
        # e.g. the lint gate's new-violation count must stay at 0
        if "max_count" in rec:
            cur = current.get(name)
            if cur is None or "count" not in cur:
                failures.append(f"{name}: missing from current run")
                continue
            cap = int(rec["max_count"])
            got = int(cur["count"])
            failed = got > cap
            status = "FAIL" if failed else "ok"
            print(f"{name}: current {got} cap {cap} [{status}]")
            if failed:
                failures.append(f"{name}: {got} > cap {cap}")
                for item in cur.get("items", [])[:20]:
                    failures.append(f"{name}:   {item}")
            continue
        # floor metrics (``min_value``): the measured value must stay AT
        # OR ABOVE the committed floor — e.g. the fleet 1->4 replica
        # scale-out ratio must stay >= 3x
        if "min_value" in rec:
            cur = current.get(name)
            if cur is None or "value" not in cur:
                failures.append(f"{name}: missing from current run")
                continue
            floor = float(rec["min_value"])
            got = float(cur["value"])
            failed = got < floor
            status = "FAIL" if failed else "ok"
            print(f"{name}: current {got:.2f} floor {floor:.2f} [{status}]")
            if failed:
                failures.append(f"{name}: {got:.2f} < floor {floor:.2f}")
            continue
        # ceiling metrics (``max_value``): dimensionless ratio bound —
        # e.g. a fresh replica's first-request latency over its steady
        # p99 must stay <= 2x
        if "max_value" in rec:
            cur = current.get(name)
            if cur is None or "value" not in cur:
                failures.append(f"{name}: missing from current run")
                continue
            cap = float(rec["max_value"])
            got = float(cur["value"])
            failed = got > cap
            status = "FAIL" if failed else "ok"
            print(f"{name}: current {got:.2f} cap {cap:.2f} [{status}]")
            if failed:
                failures.append(f"{name}: {got:.2f} > cap {cap:.2f}")
            continue
        # hard-cap latency metrics (``max_seconds``): absolute wall-time
        # bound, e.g. the elastic worker-loss recovery (loss detection
        # -> resumed worker's first heartbeat) must stay under its cap
        if "max_seconds" in rec:
            cur = current.get(name)
            if cur is None or "seconds" not in cur:
                failures.append(f"{name}: missing from current run")
                continue
            cap = float(rec["max_seconds"])
            got = float(cur["seconds"])
            failed = got > cap
            status = "FAIL" if failed else "ok"
            print(f"{name}: current {got:.2f}s cap {cap:.2f}s [{status}]")
            if failed:
                failures.append(f"{name}: {got:.2f}s > cap {cap:.2f}s")
            continue
        # hard-cap metrics (``max_overhead_pct``): absolute bound, no
        # anchor or slack — e.g. telemetry tracing overhead must stay
        # under its cap regardless of runner speed
        if "max_overhead_pct" in rec:
            cur = current.get(name)
            if cur is None or "overhead_pct" not in cur:
                failures.append(f"{name}: missing from current run")
                continue
            cap = float(rec["max_overhead_pct"])
            got = float(cur["overhead_pct"])
            failed = got > cap
            status = "FAIL" if failed else "ok"
            print(f"{name}: current {got:.2f}% cap {cap:.2f}% [{status}]")
            if failed:
                failures.append(f"{name}: {got:.2f}% > cap {cap:.2f}%")
            continue
        if "rel_to_anchor" not in rec:
            continue
        cur = current.get(name)
        if cur is None or "rel_to_anchor" not in cur:
            failures.append(f"{name}: missing from current run")
            continue
        want = float(rec["rel_to_anchor"])
        got = float(cur["rel_to_anchor"])
        slack = (
            float(rec.get("spread_pct", 0.0))
            + float(cur.get("spread_pct", 0.0))
            + margin_pct
        )
        hard = 0.5 * want
        threshold = max(want * (1.0 - slack / 100.0), hard)
        # strict at the clamp: an exactly-2x slowdown must fail even when
        # the accumulated slack reaches 50%
        failed = got < threshold or got <= hard
        status = "FAIL" if failed else "ok"
        print(
            f"{name}: committed {want:.4f} current {got:.4f} "
            f"threshold {threshold:.4f} [{status}]"
        )
        if failed:
            failures.append(
                f"{name}: {got:.4f} < {threshold:.4f} "
                f"(committed {want:.4f}, slack {slack:.0f}%)"
            )
    if failures:
        print("\nPERF GATE FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("\nperf gate passed")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("committed")
    ap.add_argument("current")
    ap.add_argument("--margin-pct", type=float, default=30.0)
    args = ap.parse_args()
    with open(args.committed) as f:
        committed = json.load(f)
    with open(args.current) as f:
        current = json.load(f)
    sys.exit(gate(committed, current, args.margin_pct))


if __name__ == "__main__":
    main()
