"""Input-drift sketches: streaming per-feature distribution monitoring.

A served model keeps predicting whatever arrives — silently, even when
the serving distribution has left the distribution it was trained on
(the failure mode no latency metric can see).  This module makes drift
a *number* with the telemetry layer's bounded-memory discipline:

* a :class:`FeatureSketch` is a streaming **moment + log-bucket
  histogram** sketch of one feature: exact count/mean/variance (batch
  Welford merge) and min/max, plus a signed geometric bucket table
  (the registry histograms' ~12% ladder, mirrored for negative values
  with a dedicated zero bucket) — O(buckets touched) memory, never
  O(observations), updated **vectorized per batch**;
* a :class:`ModelSketch` holds one FeatureSketch per input column.
  The serving layer records the true (un-padded) rows of every
  coalesced ``/v1/predict`` batch AFTER the waiting callers have been
  woken — one numpy pass per batch on the batcher thread, never on any
  caller's latency path (the PR 10 stage-note principle applied to
  data);
* a **baseline** is a frozen sketch document: captured explicitly
  (:meth:`SketchRegistry.freeze_baseline`), or persisted at
  ``save_model`` time through the Checkpointer (the model version and
  its training-distribution fingerprint travel as one atomic
  artifact) and re-attached on registry hot-load;
* the online **divergence score** compares the live sketch against
  the baseline per feature: **PSI** (population stability index) over
  the smoothed bucket distributions — the industry drift score whose
  conventional readings (<0.1 stable, 0.1-0.25 moderate, >0.25
  shifted) give ``HEAT_TPU_DRIFT_THRESHOLD`` its 0.25 default — plus
  KL(live‖baseline) and the moment deltas; the model score is the
  worst feature's PSI;
* :func:`check_drift` (called by the SLO monitor tick) fires/resolves
  a deduplicated ``drift:<model>`` alert through
  :mod:`~heat_tpu.telemetry.alerts` when a scored model crosses the
  threshold.

``/driftz`` renders :func:`drift_report`; per-model ``/healthz``
carries the model's score; cross-worker snapshots ship per-model
digests.  ``HEAT_TPU_SKETCH=0`` disables recording entirely (the
``quality_signals_overhead`` perf gate's toggle).

Thread-safety: the registry's model table is only touched under the
registered ``telemetry.sketch`` lock; each ModelSketch is updated by
exactly one batcher thread and snapshotted under the same lock.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis import tsan as _tsan
from . import alerts as _alerts
from . import metrics as _metrics

__all__ = [
    "FeatureSketch",
    "ModelSketch",
    "SketchRegistry",
    "SKETCHES",
    "check_drift",
    "drift_report",
    "psi",
    "kl_divergence",
    "record_batch",
    "set_enabled",
    "sketch_enabled",
]

#: positive magnitude ladder: half-decade (~3.16x) steps from 1e-6 to
#: 1e12 — deliberately COARSER than the registry histograms' ~12%
#: ladder.  PSI compares per-bucket *proportions*, and with the fine
#: ladder a realistic feature spreads a few hundred samples one or two
#: deep across dozens of buckets, so smoothing noise alone reads as
#: drift; half-decade buckets put a unit-scale feature in ~5 buckets
#: with solid occupancy (the classic ~10-bucket PSI regime) while a
#: half-decade mean shift still moves visible mass.  Signed index 0 is
#: the zero bucket, +k / -k mirror the ladder for negative values.
_BOUNDS = np.asarray([10.0 ** (e / 2.0) for e in range(-12, 25)])
_ZERO_EPS = float(_BOUNDS[0])  # |v| <= 1e-6 counts as zero

# knobs ARE registered in core/_env.py KNOBS; read directly because this
# module loads at `heat_tpu.telemetry` import, before core._env is safe
_ENABLED = os.environ.get("HEAT_TPU_SKETCH", "1").strip().lower() not in (
    "0", "false", "no", "off"
)
_THRESHOLD = float(os.environ.get("HEAT_TPU_DRIFT_THRESHOLD", "0.25"))
_MIN_ROWS = int(os.environ.get("HEAT_TPU_DRIFT_MIN_ROWS", "200"))

_BATCHES_C = _metrics.counter(
    "drift.batches_sketched", "coalesced input batches folded into drift sketches"
)
_ROWS_C = _metrics.counter("drift.rows_sketched", "input rows folded into drift sketches")

#: PSI smoothing: every union bucket gets this pseudo-count so a bucket
#: present on one side only contributes a finite, bounded term
_PSI_EPS = 0.5


def sketch_enabled() -> bool:
    """Whether input sketches are being recorded (``HEAT_TPU_SKETCH``)."""
    return _ENABLED


def set_enabled(enabled: bool) -> bool:
    """Enable/disable sketch recording at runtime; returns the previous
    state (the ``quality_signals_overhead`` perf gate's toggle)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    return prev


def refresh_env() -> None:
    """Re-read the sketch knobs (tests that flip the env mid-process)."""
    global _ENABLED, _THRESHOLD, _MIN_ROWS
    _ENABLED = os.environ.get("HEAT_TPU_SKETCH", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )
    _THRESHOLD = float(os.environ.get("HEAT_TPU_DRIFT_THRESHOLD", "0.25"))
    _MIN_ROWS = int(os.environ.get("HEAT_TPU_DRIFT_MIN_ROWS", "200"))


def _bucket_indices(col: np.ndarray) -> np.ndarray:
    """Signed geometric bucket index per value: 0 for |v| <= 1e-6,
    else ``sign(v) * (searchsorted(|v|) + 1)``."""
    mag = np.abs(col)
    idx = np.searchsorted(_BOUNDS, mag, side="left") + 1
    signed = np.where(col < 0, -idx, idx)
    return np.where(mag <= _ZERO_EPS, 0, signed)


class FeatureSketch:
    """Streaming sketch of one feature: exact moments + bucket table."""

    __slots__ = ("count", "mean", "m2", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    def update_batch(self, col: np.ndarray) -> None:
        """Fold one batch column in (vectorized: one Welford merge +
        one bucket-count pass per batch, not per value)."""
        col = np.asarray(col, dtype=np.float64)
        n = int(col.size)
        if n == 0:
            return
        b_mean = float(col.mean())
        b_m2 = float(((col - b_mean) ** 2).sum())
        if self.count == 0:
            self.mean, self.m2 = b_mean, b_m2
        else:
            # parallel-variance merge (Chan et al.): exact, order-free
            delta = b_mean - self.mean
            tot = self.count + n
            self.mean += delta * n / tot
            self.m2 += b_m2 + delta * delta * self.count * n / tot
        self.count += n
        self.min = min(self.min, float(col.min()))
        self.max = max(self.max, float(col.max()))
        ixs, counts = np.unique(_bucket_indices(col), return_counts=True)
        for ix, c in zip(ixs.tolist(), counts.tolist()):
            self.buckets[ix] = self.buckets.get(ix, 0) + c

    @property
    def variance(self) -> float:
        return self.m2 / self.count if self.count > 1 else 0.0

    def doc(self) -> Dict[str, Any]:
        """JSON-safe document (bucket keys stringified for transport)."""
        return {
            "count": self.count,
            "mean": self.mean,
            "m2": self.m2,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "buckets": {str(k): v for k, v in sorted(self.buckets.items())},
        }

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "FeatureSketch":
        s = cls()
        s.count = int(doc.get("count", 0))
        s.mean = float(doc.get("mean", 0.0))
        s.m2 = float(doc.get("m2", 0.0))
        s.min = math.inf if doc.get("min") is None else float(doc["min"])
        s.max = -math.inf if doc.get("max") is None else float(doc["max"])
        s.buckets = {int(k): int(v) for k, v in (doc.get("buckets") or {}).items()}
        return s


def psi(p_buckets: Dict[int, int], q_buckets: Dict[int, int]) -> float:
    """Population stability index between two bucket tables (symmetric;
    smoothed so one-sided buckets stay finite).  0 = identical;
    conventional reading: <0.1 stable, 0.1-0.25 moderate, >0.25 shifted."""
    keys = set(p_buckets) | set(q_buckets)
    if not keys:
        return 0.0
    k = len(keys)
    p_tot = sum(p_buckets.values()) + _PSI_EPS * k
    q_tot = sum(q_buckets.values()) + _PSI_EPS * k
    if p_tot <= 0 or q_tot <= 0:
        return 0.0
    out = 0.0
    for key in keys:
        p = (p_buckets.get(key, 0) + _PSI_EPS) / p_tot
        q = (q_buckets.get(key, 0) + _PSI_EPS) / q_tot
        out += (p - q) * math.log(p / q)
    return out


def kl_divergence(p_buckets: Dict[int, int], q_buckets: Dict[int, int]) -> float:
    """KL(p‖q) between two (smoothed) bucket tables — the asymmetric
    companion score (p = live traffic, q = baseline)."""
    keys = set(p_buckets) | set(q_buckets)
    if not keys:
        return 0.0
    k = len(keys)
    p_tot = sum(p_buckets.values()) + _PSI_EPS * k
    q_tot = sum(q_buckets.values()) + _PSI_EPS * k
    if p_tot <= 0 or q_tot <= 0:
        return 0.0
    out = 0.0
    for key in keys:
        p = (p_buckets.get(key, 0) + _PSI_EPS) / p_tot
        q = (q_buckets.get(key, 0) + _PSI_EPS) / q_tot
        out += p * math.log(p / q)
    return out


class ModelSketch:
    """One served model's input sketch: a FeatureSketch per column."""

    __slots__ = ("name", "n_features", "features", "n_batches", "updated_ts",
                 "started_ts")

    def __init__(self, name: str, n_features: int):
        self.name = name
        self.n_features = int(n_features)
        self.features = [FeatureSketch() for _ in range(self.n_features)]
        self.n_batches = 0
        self.started_ts = time.time()
        self.updated_ts = 0.0

    def update(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[1] != self.n_features:
            raise ValueError(
                f"sketch for {self.name!r} expects (n, {self.n_features}) "
                f"rows, got shape {tuple(rows.shape)}"
            )
        for j, fs in enumerate(self.features):
            fs.update_batch(rows[:, j])
        self.n_batches += 1
        self.updated_ts = time.time()

    @property
    def count(self) -> int:
        return self.features[0].count if self.features else 0

    def doc(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "n_features": self.n_features,
            "n_batches": self.n_batches,
            "count": self.count,
            "started_ts": self.started_ts,
            "updated_ts": self.updated_ts or None,
            "features": [fs.doc() for fs in self.features],
        }

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "ModelSketch":
        s = cls(doc.get("name", "?"), int(doc.get("n_features", 0)))
        s.features = [FeatureSketch.from_doc(d) for d in doc.get("features") or []]
        s.n_features = len(s.features)
        s.n_batches = int(doc.get("n_batches", 0))
        s.started_ts = float(doc.get("started_ts") or 0.0)
        s.updated_ts = float(doc.get("updated_ts") or 0.0)
        return s


def divergence(live: Dict[str, Any], baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Per-feature PSI/KL + moment deltas of a live sketch document
    against a baseline document; the model ``score`` is the worst
    feature's PSI.  Pure function of the two documents (cross-worker
    merges and tests call it on shipped snapshots)."""
    live_f = live.get("features") or []
    base_f = baseline.get("features") or []
    feats: List[Dict[str, Any]] = []
    score = 0.0
    for j in range(min(len(live_f), len(base_f))):
        lf, bf = live_f[j], base_f[j]
        lb = {int(k): int(v) for k, v in (lf.get("buckets") or {}).items()}
        bb = {int(k): int(v) for k, v in (bf.get("buckets") or {}).items()}
        p = psi(lb, bb)
        feats.append(
            {
                "feature": j,
                "psi": round(p, 6),
                "kl": round(kl_divergence(lb, bb), 6),
                "mean_delta": round(
                    float(lf.get("mean", 0.0)) - float(bf.get("mean", 0.0)), 6
                ),
                "live_count": int(lf.get("count", 0)),
                "baseline_count": int(bf.get("count", 0)),
            }
        )
        score = max(score, p)
    return {
        "score": round(score, 6),
        "worst_feature": max(feats, key=lambda f: f["psi"])["feature"] if feats else None,
        "features": feats,
    }


class SketchRegistry:
    """name -> (live ModelSketch, frozen baseline document)."""

    def __init__(self):
        # name -> {"live": ModelSketch|None, "baseline": doc|None}
        self._models: Dict[str, Dict[str, Any]] = {}
        self._lock = _tsan.register_lock("telemetry.sketch")

    def record(self, name: str, rows: np.ndarray) -> bool:
        """Fold one batch of true (un-padded) input rows into the
        model's live sketch; returns False when recording is disabled.
        The sketch is created lazily from the first batch's width."""
        if not _ENABLED:
            return False
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[0] == 0:
            return False
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry")
            entry = self._models.setdefault(name, {"live": None, "baseline": None})
            live = entry["live"]
            if live is None or live.n_features != rows.shape[1]:
                live = entry["live"] = ModelSketch(name, rows.shape[1])
            live.update(rows)
        _BATCHES_C.inc()
        _ROWS_C.inc(int(rows.shape[0]))
        return True

    def freeze_baseline(self, name: str) -> Dict[str, Any]:
        """Freeze the model's CURRENT live sketch as its baseline and
        restart the live sketch — the runtime capture path (the
        save-time path passes the returned document to ``save_model``
        so it persists with the version)."""
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry")
            entry = self._models.get(name)
            if entry is None or entry["live"] is None or entry["live"].count == 0:
                raise ValueError(
                    f"no live input sketch for model {name!r} to freeze; "
                    "serve (or sketch) some traffic first"
                )
            doc = entry["live"].doc()
            entry["baseline"] = doc
            entry["live"] = ModelSketch(name, entry["live"].n_features)
        return doc

    def set_baseline(self, name: str, baseline: Optional[Dict[str, Any]]) -> None:
        """Attach a persisted baseline document (registry hot-load
        path); ``None`` detaches."""
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry")
            entry = self._models.setdefault(name, {"live": None, "baseline": None})
            entry["baseline"] = dict(baseline) if baseline else None

    def baseline(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry", write=False)
            entry = self._models.get(name)
            return dict(entry["baseline"]) if entry and entry["baseline"] else None

    def reset_live(self, name: str) -> None:
        """Restart the model's live sketch (keeps the baseline)."""
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry")
            entry = self._models.get(name)
            if entry is not None and entry["live"] is not None:
                entry["live"] = ModelSketch(name, entry["live"].n_features)

    def status(self, name: str) -> Dict[str, Any]:
        """One model's drift status: live sketch digest, baseline
        presence, divergence score (None without both sides)."""
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry", write=False)
            entry = self._models.get(name)
            live = entry["live"].doc() if entry and entry["live"] else None
            base = entry["baseline"] if entry else None
        doc: Dict[str, Any] = {
            "model": name,
            "sketched_batches": (live or {}).get("n_batches", 0),
            "sketched_rows": (live or {}).get("count", 0),
            "n_features": (live or {}).get("n_features"),
            "baseline": base is not None,
            "baseline_rows": int(base.get("count", 0)) if base else 0,
            "score": None,
            "drifting": False,
            "warming": False,
            "threshold": _THRESHOLD,
            "min_rows": _MIN_ROWS,
        }
        if live is not None and base is not None and live["count"] > 0:
            if live["count"] < _MIN_ROWS:
                # below the small-sample floor the PSI is noise, not a
                # verdict: report "warming", never a score
                doc["warming"] = True
            else:
                div = divergence(live, base)
                doc["score"] = div["score"]
                doc["worst_feature"] = div["worst_feature"]
                doc["features"] = div["features"]
                doc["drifting"] = div["score"] > _THRESHOLD
        return doc

    def model_names(self) -> List[str]:
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry", write=False)
            return sorted(self._models)

    def digest(self) -> List[Dict[str, Any]]:
        """Compact per-model digests (score + counts, no bucket tables)
        — the form that travels in cross-worker snapshots."""
        out = []
        for name in self.model_names():
            st = self.status(name)
            out.append(
                {
                    "model": name,
                    "score": st["score"],
                    "drifting": st["drifting"],
                    "sketched_rows": st["sketched_rows"],
                    "baseline": st["baseline"],
                }
            )
        return out

    def clear(self) -> None:
        """Drop every sketch and baseline (tests, ``reset_all``)."""
        with self._lock:
            _tsan.note_access("telemetry.sketch.registry")
            self._models.clear()


#: the process-global sketch registry the serving layer records into
SKETCHES = SketchRegistry()


def record_batch(name: str, rows: np.ndarray) -> bool:
    """Fold one coalesced batch's true rows into the global registry."""
    return SKETCHES.record(name, rows)


def check_drift() -> List[Dict[str, Any]]:
    """Score every model with a baseline and fire/resolve its
    deduplicated ``drift:<model>`` alert (called by the SLO monitor
    tick; tests call it directly).  Returns the status documents."""
    out = []
    for name in SKETCHES.model_names():
        st = SKETCHES.status(name)
        out.append(st)
        if st["score"] is None:
            continue
        if st["drifting"]:
            _alerts.fire(
                f"drift:{name}",
                severity="warn",
                message=(
                    f"input drift on model {name!r}: PSI {st['score']:g} > "
                    f"{st['threshold']:g} (worst feature "
                    f"{st.get('worst_feature')})"
                ),
                value=st["score"],
                threshold=st["threshold"],
                labels={"model": name},
            )
        else:
            _alerts.resolve(f"drift:{name}", labels={"model": name})
    return out


def drift_report() -> Dict[str, Any]:
    """The ``/driftz`` payload: every sketched model's status (scores,
    per-feature PSI where a baseline exists) plus the active drift
    alerts."""
    return {
        "timestamp": time.time(),
        "enabled": _ENABLED,
        "threshold": _THRESHOLD,
        "models": [SKETCHES.status(n) for n in SKETCHES.model_names()],
        "alerts": [
            a for a in _alerts.active_alerts() if a["name"].startswith("drift:")
        ],
    }


def render_driftz_html() -> str:
    """``/driftz`` as a small dependency-free HTML page: one row per
    sketched model (score vs threshold, per-feature PSI for scored
    models) plus the active drift alerts.  Model names arrive verbatim
    from request bodies, so every interpolated string goes through
    ``html.escape``."""
    import html as _html

    from .slo import _HTML_HEAD, _render_alert_table

    esc = lambda s: _html.escape(str(s), quote=True)
    rep = drift_report()
    parts = [
        _HTML_HEAD.replace("/sloz", "/driftz"),
        "<h1>/driftz — input-drift sketches</h1>",
        f"<p>sketching {'enabled' if rep['enabled'] else 'DISABLED'} · "
        f"PSI threshold {esc(rep['threshold'])} · "
        f"generated {time.strftime('%H:%M:%S')}</p>",
    ]
    if rep["models"]:
        parts.append(
            "<table><tr><th class=l>model</th><th>rows sketched</th>"
            "<th>baseline rows</th><th>PSI score</th><th>worst feature</th>"
            "<th>state</th></tr>"
        )
        for m in rep["models"]:
            state = (
                "DRIFTING" if m["drifting"]
                else ("ok" if m["score"] is not None
                      else ("no baseline" if not m["baseline"]
                            else ("warming" if m.get("warming") else "no traffic")))
            )
            cls = "firing" if m["drifting"] else ""
            parts.append(
                f'<tr class="{esc(cls)}"><td class=l>{esc(m["model"])}</td>'
                f'<td>{esc(m["sketched_rows"])}</td><td>{esc(m["baseline_rows"])}</td>'
                f'<td>{esc(m["score"] if m["score"] is not None else "·")}</td>'
                f'<td>{esc(m.get("worst_feature", "·"))}</td><td>{esc(state)}</td></tr>'
            )
        parts.append("</table>")
        for m in rep["models"]:
            if not m.get("features"):
                continue
            parts.append(f"<h3>{esc(m['model'])} — per-feature PSI</h3>"
                         "<table><tr><th>feature</th><th>PSI</th><th>KL</th>"
                         "<th>mean Δ</th></tr>")
            for f in m["features"]:
                cls = "firing" if f["psi"] > rep["threshold"] else ""
                parts.append(
                    f'<tr class="{esc(cls)}"><td>{esc(f["feature"])}</td>'
                    f'<td>{esc(f["psi"])}</td><td>{esc(f["kl"])}</td>'
                    f'<td>{esc(f["mean_delta"])}</td></tr>'
                )
            parts.append("</table>")
    else:
        parts.append("<p>(no models sketched yet — serve some traffic)</p>")
    parts.append(_render_alert_table(rep["alerts"], esc))
    parts.append("<p>JSON form: <a href='/driftz?format=json'>/driftz?format=json</a>"
                 " · SLOs: <a href='/sloz'>/sloz</a></p></body></html>")
    return "".join(parts)
