"""Random-module width (heat/core/tests/test_random.py family): the
edges beyond the existing seed/moments tests — choice semantics,
shuffle/permutation contracts, distribution parameter grids, dtype and
split invariants, counter-PRNG mesh-size independence.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


@pytest.mark.parametrize("split", SPLITS)
def test_choice_with_replacement_range(split):
    ht.random.seed(10)
    c = ht.random.choice(20, size=(500,), comm=None) if split is None else ht.random.choice(20, size=(500,))
    vals = np.asarray(c.numpy())
    assert vals.shape == (500,)
    assert vals.min() >= 0 and vals.max() < 20


def test_choice_from_array_and_probabilities():
    ht.random.seed(11)
    pool = ht.array(np.array([10.0, 20.0, 30.0, 40.0], np.float32))
    c = ht.random.choice(pool, size=(2000,))
    vals = np.asarray(c.numpy())
    assert set(np.unique(vals)).issubset({10.0, 20.0, 30.0, 40.0})
    # skewed p concentrates mass (law of large numbers at loose tolerance)
    try:
        c2 = ht.random.choice(pool, size=(4000,), p=np.array([0.85, 0.05, 0.05, 0.05]))
    except TypeError:
        pytest.skip("choice(p=) not supported")
    share = float((np.asarray(c2.numpy()) == 10.0).mean())
    assert share > 0.7


def test_shuffle_is_permutation_inplace():
    ht.random.seed(12)
    a = ht.arange(64, split=0)
    before = a.numpy().copy()
    ht.random.shuffle(a)
    after = a.numpy()
    assert not np.array_equal(before, after)  # astronomically unlikely
    np.testing.assert_array_equal(np.sort(after), before)


def test_permutation_leaves_source_untouched():
    ht.random.seed(13)
    a = ht.arange(32, split=0)
    p = ht.random.permutation(a)
    np.testing.assert_array_equal(a.numpy(), np.arange(32))
    np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(32))
    q = ht.random.permutation(16)
    np.testing.assert_array_equal(np.sort(q.numpy()), np.arange(16))


@pytest.mark.parametrize("split", SPLITS)
def test_uniform_bounds_and_moments(split):
    ht.random.seed(14)
    u = ht.random.uniform(-3.0, 5.0, size=(1 << 16,), split=split)
    vals = np.asarray(u.numpy())
    assert vals.min() >= -3.0 and vals.max() < 5.0
    assert abs(vals.mean() - 1.0) < 0.1
    # variance of U(a,b) = (b-a)^2/12
    assert abs(vals.var() - 64.0 / 12.0) < 0.2


@pytest.mark.parametrize("split", SPLITS)
def test_normal_loc_scale(split):
    ht.random.seed(15)
    # heat signature: normal(mean, std, shape) (reference random.py:293)
    x = ht.random.normal(2.0, 3.0, (1 << 16,), split=split)
    vals = np.asarray(x.numpy())
    assert abs(vals.mean() - 2.0) < 0.1
    assert abs(vals.std() - 3.0) < 0.1


def test_randint_exclusive_high_and_dtype():
    ht.random.seed(16)
    r = ht.random.randint(5, 9, size=(4000,))
    vals = np.asarray(r.numpy())
    assert vals.min() >= 5 and vals.max() <= 8
    assert np.issubdtype(vals.dtype, np.integer)
    # single-argument form: [0, high)
    r2 = ht.random.randint(3, size=(1000,))
    assert np.asarray(r2.numpy()).max() <= 2


def test_counter_prng_mesh_size_independence():
    """The same seed yields the same stream regardless of split — the
    Threefry-style contract the reference guarantees across comm sizes."""
    ht.random.seed(99)
    a = ht.random.randn(257, split=0).numpy()
    ht.random.seed(99)
    b = ht.random.randn(257, split=None).numpy()
    np.testing.assert_array_equal(a, b)


def test_bytes_length_and_entropy():
    ht.random.seed(17)
    b = ht.random.bytes(64)
    assert isinstance(b, (bytes, bytearray)) and len(b) == 64
    assert len(set(b)) > 10  # not a constant fill


def test_rand_aliases_agree_on_shape():
    ht.random.seed(18)
    for fn in (ht.random.random_sample, ht.random.random, ht.random.ranf, ht.random.sample):
        out = fn((7, 3))
        assert out.shape == (7, 3)
        vals = np.asarray(out.numpy())
        assert vals.min() >= 0.0 and vals.max() < 1.0


def test_standard_normal_shape_contract():
    ht.random.seed(19)
    s = ht.random.standard_normal((5, 4))
    assert s.shape == (5, 4)
    z = ht.random.standard_normal()
    assert np.asarray(z.numpy() if hasattr(z, "numpy") else z).shape in ((), (1,))
