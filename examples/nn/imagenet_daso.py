"""Hierarchical DASO training over an out-of-core HDF5 dataset (analog of
examples/nn/imagenet-DASO.py).

The reference's flagship training demo combines three pieces: NCCL DDP
inside a node, the DASO optimizer skipping/delaying global syncs across
nodes, and a threaded out-of-core HDF5 loader.  The TPU-native pieces are
the same shapes: GSPMD data parallelism inside the mesh, ht.optim.DASO for
the skipped/delayed bfloat16 global averaging, and PartialH5Dataset
streaming windows off host disk while the device computes.

ImageNet itself is not bundled; the demo synthesizes an ImageNet-shaped
HDF5 file (tiny by default) so the full pipeline is runnable anywhere.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import os
import tempfile

import numpy as np

import heat_tpu as ht


def synthesize_imagenet_h5(path: str, n: int = 512, size: int = 32, classes: int = 10) -> None:
    import h5py

    rng = np.random.default_rng(0)
    base = rng.standard_normal((classes, size, size, 3)).astype(np.float32)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    images = base[labels] + 0.25 * rng.standard_normal((n, size, size, 3)).astype(np.float32)
    with h5py.File(path, "w") as f:
        f.create_dataset("images", data=images)
        f.create_dataset("labels", data=labels)


def make_model(classes: int = 10):
    import flax.linen as lnn

    class SmallResNetish(lnn.Module):
        @lnn.compact
        def __call__(self, x):
            x = lnn.Conv(32, (3, 3), strides=(2, 2))(x)
            x = lnn.relu(x)
            x = lnn.Conv(64, (3, 3), strides=(2, 2))(x)
            x = lnn.relu(x)
            x = x.mean(axis=(1, 2))  # global average pool
            return lnn.Dense(classes)(x)

    return SmallResNetish()


def main(epochs: int = 5, batch_size: int = 64, window: int = 128) -> None:
    import jax
    import jax.numpy as jnp
    import optax

    with tempfile.TemporaryDirectory() as tmp:
        h5path = os.path.join(tmp, "imagenet_synth.h5")
        synthesize_imagenet_h5(h5path)

        model = make_model()
        comm = ht.get_comm()

        # The reference's topology: DDP inside a node, DASO across nodes.
        # Arrange the mesh as (n_node, per_node) — per-node parameter
        # replicas ride the 'global' axis, intra-node gradient psums the
        # 'node' axis.  One device degenerates to the plain optimizer.
        n_node = 2 if comm.size % 2 == 0 and comm.size >= 2 else 1
        hc = ht.parallel.HierarchicalCommunication(grid=(n_node, comm.size // n_node))
        daso = ht.optim.DASO(
            local_optimizer=optax.adam(1e-3),
            total_epochs=epochs,
            comm=hc,
            warmup_epochs=1,
            cooldown_epochs=1,
        )
        dp = ht.nn.DataParallelMultiGPU(model, daso=daso) if n_node > 1 else None
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        if dp is not None:
            dp.set_params(params)

        def batch_loss(pred, yb):
            return optax.softmax_cross_entropy_with_integer_labels(pred, yb).mean()

        def loss_fn(p, xb, yb):
            return batch_loss(model.apply(p, xb), yb)

        grad_fn = jax.jit(jax.value_and_grad(loss_fn))

        for epoch in range(epochs):
            ds = ht.utils.data.PartialH5Dataset(
                h5path, dataset_names=["images", "labels"], load_length=window
            )
            losses = []
            for images, labels in ds:
                for start in range(0, images.shape[0] - batch_size + 1, batch_size):
                    xb = images[start : start + batch_size]
                    yb = labels[start : start + batch_size]
                    if dp is not None:
                        losses.append(dp.step(batch_loss, xb, yb))
                    else:
                        loss, grads = grad_fn(params, xb, yb)
                        params = daso.step(params, grads)
                        losses.append(float(loss))
            daso.epoch_loss_logic(float(np.mean(losses)))
            daso.next_epoch()  # advances the warmup/cycling/cooldown phases
            print(
                f"epoch {epoch}: mean loss {np.mean(losses):.4f}, "
                f"global_skip {daso.global_skip}"
            )
        if dp is not None:
            params = daso.collect(daso.last_batch(dp.params))
        else:
            params = daso.last_batch(params)
        print("done — final global sync applied")


if __name__ == "__main__":
    main()
