"""Kill-mid-stream exactly-once resume (ISSUE 17).

Real host preemption for each online estimator: the child process is
``os._exit``-killed by the env fault plan between window commits
(``stream.commit``), the parent resumes the same checkpoint directory
over the same segment log, and the final model must equal the
uninterrupted fit **bitwise** — the committed offset rides in the same
atomic checkpoint step as the model state, so the resumed consumer
replays the identical window sequence.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from heat_tpu.streaming import (
    FileSegmentLog,
    StreamingKMeans,
    StreamingLasso,
    StreamingPCA,
)
from heat_tpu.utils.checkpoint import Checkpointer

_CHILD = """
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', True)  # mirror conftest
import sys
from heat_tpu.streaming import (FileSegmentLog, StreamingKMeans,
                                StreamingLasso, StreamingPCA)
name, log_dir, ck = sys.argv[1], sys.argv[2], sys.argv[3]
log = FileSegmentLog(log_dir)
kw = dict(window_rows=32, commit_every=1, checkpoint_dir=ck, resume_from=ck)
if name == 'kmeans':
    est = StreamingKMeans(n_clusters=3, **kw)
elif name == 'pca':
    est = StreamingPCA(n_components=2, **kw)
else:
    est = StreamingLasso(lam=0.01, lr=0.1, **kw)
est.fit_stream(log)
"""


def _make(name, **kw):
    if name == "kmeans":
        return StreamingKMeans(n_clusters=3, window_rows=32, **kw)
    if name == "pca":
        return StreamingPCA(n_components=2, window_rows=32, **kw)
    return StreamingLasso(lam=0.01, lr=0.1, window_rows=32, **kw)


_FITTED = {
    "kmeans": ("cluster_centers_", "counts_"),
    "pca": ("components_", "singular_values_", "mean_", "m2_"),
    "lasso": ("theta_",),
}


@pytest.mark.parametrize("name", ["kmeans", "pca", "lasso"])
def test_kill_between_window_commits_resumes_bitwise(tmp_path, name):
    log_dir = str(tmp_path / "log")
    rows = np.random.default_rng(5).standard_normal((32 * 12, 4)).astype(np.float32)
    FileSegmentLog(log_dir, segment_rows=80).append(rows)

    # the uninterrupted reference (same process as the resume leg)
    ref = _make(name).fit_stream(FileSegmentLog(log_dir))

    # the child dies at the 5th window-commit boundary
    ck = str(tmp_path / "ck")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
        {"plan": {"stream.commit": [{"at": 5, "kind": "kill", "exit_code": 137}]}}
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, name, log_dir, ck],
        env=env, capture_output=True, timeout=300,
    )
    assert proc.returncode == 137, proc.stderr.decode()[-2000:]
    step = Checkpointer(ck).latest_step()
    assert step is not None and step < 12, "the kill must land mid-stream"
    committed = Checkpointer(ck).restore(step)
    assert committed["converged"] is False
    # the offset rode the commit (PCA's SVD seed consumes window 0
    # outside the iteration count, so its offset runs one window ahead)
    seed_rows = 32 if name == "pca" else 0
    assert committed["state"]["offset"] == step * 32 + seed_rows

    # the parent resumes the surviving directory over the same log
    resumed = _make(name, commit_every=1, resume_from=ck).fit_stream(
        FileSegmentLog(log_dir)
    )
    assert resumed.offset_ == ref.offset_ == 32 * 12
    for attr in _FITTED[name]:
        assert np.array_equal(
            np.asarray(getattr(ref, attr)), np.asarray(getattr(resumed, attr))
        ), attr
