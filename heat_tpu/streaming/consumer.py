"""Stream consumer: fixed-size windows, device staging, key-drift
resharding (docs/streaming.md).

The consumer is the bridge between a replayable :class:`StreamSource`
and the online fits: it cuts the stream into FIXED-SIZE windows (the
resumable-fit chunk unit — fixed size is what makes the window sequence
a pure function of the committed offset), stages them shard-aware
through :func:`~heat_tpu.utils.data.prefetch.prefetch_to_device` from
the stream head, and watches the key-column distribution across windows
— when it drifts past ``HEAT_TPU_STREAM_RESHARD_PSI``, the next
``maybe_reshard`` call rebalances the caller's persistent split-axis
state (``balance_`` within the mesh, ``reshard_`` across meshes).

Reads run under the io retry policy with the ``stream.read`` fault site
evaluated per attempt, so a scripted transient is absorbed exactly like
an io transient.  The consumer is single-threaded by contract (like the
data loaders): one fit drives it; producers append to the source from
any thread/process.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from ..resilience.faults import inject
from ..resilience.retry import default_io_policy
from ..analysis.protocols import ACTOR_STREAM, STREAM_RESHARD
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry import tsdb as _tsdb
from ..telemetry.spans import span as _span
from .source import StreamSource

__all__ = ["StreamConsumer"]

_WINDOWS = _tm.counter("stream.windows")
_ROWS = _tm.counter("stream.rows")
_SEEKS = _tm.counter("stream.seeks")
_RESHARDS = _tm.counter("stream.reshards")


def _key_hist(vals: np.ndarray) -> Dict[int, int]:
    """Signed full-decade magnitude buckets of the key column.

    Deliberately COARSER than the drift sketches' half-decade ladder:
    this histogram scores window-size samples (hundreds of rows, not
    the sketch monitor's 200+ row floor over whole traffic), and the
    reshard trigger wants robustness against sampling noise, not
    resolution — a key shift worth redistributing the split axis for
    moves whole decades."""
    v = np.asarray(vals, dtype=np.float64).ravel()
    v = v[np.isfinite(v)]  # non-finite keys are the divergence guard's problem
    out: Dict[int, int] = {}
    tiny = np.abs(v) < 1e-9
    n_tiny = int(tiny.sum())
    if n_tiny:
        out[0] = n_tiny
    v = v[~tiny]
    if v.size:
        mag = np.clip(np.floor(np.log10(np.abs(v))), -8, 8).astype(np.int64)
        signed = np.where(v >= 0, mag + 10, -(mag + 10))
        keys, counts = np.unique(signed, return_counts=True)
        for k, c in zip(keys.tolist(), counts.tolist()):
            out[int(k)] = out.get(int(k), 0) + int(c)
    return out


def _psi(ref: Dict[int, int], cur: Dict[int, int]) -> float:
    """Population stability index between two bucket histograms."""
    eps = 1e-4
    ref_n = max(sum(ref.values()), 1)
    cur_n = max(sum(cur.values()), 1)
    score = 0.0
    for k in set(ref) | set(cur):
        p = max(ref.get(k, 0) / ref_n, eps)
        q = max(cur.get(k, 0) / cur_n, eps)
        score += (q - p) * np.log(q / p)
    return float(score)


class StreamConsumer:
    """Windowed, prefetched, replayable view over a stream source.

    ``next_window(offset)`` returns ``(offset, staged_rows)`` for the
    full window starting at ``offset`` or ``None`` while the head holds
    fewer than ``window_rows`` committed rows (partial windows are never
    consumed — they would make the window sequence depend on arrival
    timing and break bitwise replay).  Sequential offsets ride the
    prefetch pipeline; a non-sequential offset (a resume) reseeks it.
    """

    def __init__(
        self,
        source: StreamSource,
        window_rows: Optional[int] = None,
        comm=None,
        key_col: int = 0,
        prefetch: Optional[int] = None,
        reshard_psi: Optional[float] = None,
        reshard_check: bool = True,
        reshard_window: int = 4,
    ):
        from ..core._env import env_float, env_int
        from ..parallel.comm import sanitize_comm

        self.source = source
        self.window_rows = int(window_rows if window_rows is not None
                               else env_int("HEAT_TPU_STREAM_WINDOW", 256))
        if self.window_rows < 1:
            raise ValueError(f"window_rows must be >= 1, got {self.window_rows}")
        self.comm = sanitize_comm(comm)
        self.key_col = int(key_col)
        self.prefetch = int(prefetch if prefetch is not None
                            else env_int("HEAT_TPU_STREAM_PREFETCH", 2))
        self.reshard_psi = float(reshard_psi if reshard_psi is not None
                                 else env_float("HEAT_TPU_STREAM_RESHARD_PSI", 0.25))
        self.reshard_check = bool(reshard_check)
        self.reshard_window = int(reshard_window)
        if self.reshard_window < 1:
            raise ValueError(f"reshard_window must be >= 1, got {self.reshard_window}")
        self.reshard_events = 0
        self.last_key_psi: Optional[float] = None
        # drift monitor state: an ACCUMULATED reference histogram of the
        # confirmed-stable history vs a ROLLING current one of the last
        # ``reshard_window`` windows — single-window PSI at typical
        # window sizes is dominated by sampling noise, the rolling form
        # is not (same smoothing the sketch-based model monitor gets
        # from its much larger live sample)
        self._key_ref: Dict[int, int] = {}
        self._ref_windows = 0
        self._key_recent: "deque" = deque()
        self._needs_reshard = False
        self._pipe: Optional[Iterator] = None
        self._pipe_offset: Optional[int] = None

    @property
    def n_features(self) -> Optional[int]:
        return self.source.n_features

    # -- raw reads ------------------------------------------------------
    def _read_full_window(self, offset: int) -> Optional[np.ndarray]:
        """One full window at ``offset`` through retry + fault site, or
        None while the committed head holds fewer rows."""
        need = self.window_rows

        def attempt():
            inject("stream.read", offset=offset)
            return self.source.read(offset, need)

        rows = default_io_policy().call(attempt)
        if rows.shape[0] < need:
            return None
        return rows

    def peek(self, offset: int) -> Optional[np.ndarray]:
        """A full window at ``offset`` WITHOUT consuming it (no pipeline
        advance, no key-hist fold) — the online estimators' state
        initializers read their seed window through this."""
        return self._read_full_window(offset)

    # -- key-distribution drift across the split axis -------------------
    @staticmethod
    def _merge_hist(into: Dict[int, int], hist: Dict[int, int]) -> None:
        for k, c in hist.items():
            into[k] = into.get(k, 0) + c

    def _fold_keys(self, rows: np.ndarray) -> None:
        if not self.reshard_check:
            return
        hist = _key_hist(rows[:, self.key_col])
        r = self.reshard_window
        if self._ref_windows < r:
            # warm-up: the first windows ARE the reference
            self._merge_hist(self._key_ref, hist)
            self._ref_windows += 1
            return
        self._key_recent.append(hist)
        if len(self._key_recent) > r:
            # the window falling out of the rolling view was stable:
            # graduate it into the accumulated reference
            self._merge_hist(self._key_ref, self._key_recent.popleft())
            self._ref_windows += 1
        if len(self._key_recent) < r:
            return
        cur: Dict[int, int] = {}
        for h in self._key_recent:
            self._merge_hist(cur, h)
        score = _psi(self._key_ref, cur)
        self.last_key_psi = score
        _tsdb.record("stream.key_psi", score)
        if score > self.reshard_psi:
            # re-anchor by re-entering warm-up: the rolling view that
            # tripped straddles the transition, so the NEXT windows
            # (fully post-shift for a step change) become the new
            # reference — one sustained shift triggers exactly one
            # reshard, not one per window
            self._key_ref = {}
            self._ref_windows = 0
            self._key_recent.clear()
            self.reshard_events += 1
            self._needs_reshard = True
            _RESHARDS.inc()
            _journal.emit(
                ACTOR_STREAM, STREAM_RESHARD,
                severity="warn",
                message=(
                    f"key-distribution drift PSI {score:.4f} > "
                    f"{self.reshard_psi:g}: split-axis reshard pending"
                ),
                evidence={"psi": round(score, 6),
                          "threshold": self.reshard_psi,
                          "reshard_events": self.reshard_events,
                          "series": ["stream.key_psi"]},
            )

    def maybe_reshard(self, dnd=None) -> bool:
        """Apply a pending key-drift reshard to the caller's persistent
        split-axis array (in place): ``balance_`` re-levels the canonical
        split distribution within the mesh; when the array lives on a
        different comm (an elastic reshape happened under the fit),
        ``reshard_`` moves it first.  Returns True when a reshard was
        pending (whether or not an array was passed)."""
        if not self._needs_reshard:
            return False
        self._needs_reshard = False
        if dnd is not None:
            with _span("stream.reshard", rows=int(dnd.shape[0])):
                if dnd.comm is not self.comm:
                    dnd.reshard_(self.comm)
                dnd.balance_()
        return True

    # -- the prefetched window pipeline ---------------------------------
    def _raw_windows(self, offset: int) -> Iterator[Tuple[int, np.ndarray]]:
        off = offset
        while True:
            rows = self._read_full_window(off)
            if rows is None:
                return
            self._fold_keys(rows)
            _WINDOWS.inc()
            _ROWS.inc(rows.shape[0])
            yield off, rows
            off += self.window_rows

    def _reseek(self, offset: int) -> None:
        from ..utils.data.prefetch import prefetch_to_device, sharding_for_batch

        self.close()
        sharding = sharding_for_batch(self.window_rows, self.comm)
        self._pipe = prefetch_to_device(
            self._raw_windows(offset), size=self.prefetch, sharding=sharding
        )
        self._pipe_offset = offset
        _SEEKS.inc()

    def next_window(self, offset: int):
        """``(offset, device_staged_rows)`` for the full window at
        ``offset``, or None while the stream head is short of one."""
        if self._pipe is None or self._pipe_offset != offset:
            self._reseek(offset)
        try:
            out = next(self._pipe)
        except StopIteration:
            # head ran dry mid-pipeline; drop it so a later call (after
            # the producer appended more) rebuilds from this offset
            self.close()
            return None
        self._pipe_offset = offset + self.window_rows
        return out

    def close(self) -> None:
        """Release the prefetch pipeline (never drains an unbounded
        head — see ``_DevicePrefetcher.close``).  Idempotent."""
        pipe, self._pipe = self._pipe, None
        self._pipe_offset = None
        if pipe is not None:
            pipe.close()

    def __enter__(self) -> "StreamConsumer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
