"""Cross-process elastic supervision: the kill-and-resume harness as a
production driver.

A real preemption kills a *worker process*, not an exception handler.
:class:`ProcessSupervisor` drives one single-controller fit subprocess
per attempt — the simulated mesh size rides
``--xla_force_host_platform_device_count`` exactly like the MULTICHIP
dryrun — and supervises it through two loss signals:

* **exit code** — a worker that dies non-zero (the fault injector's
  ``kind: "kill"`` ``os._exit(137)``, a real OOM-kill, a preemption
  SIGKILL) is a lost worker;
* **heartbeat file** — the worker's ``resumable_fit_loop`` touches
  ``HEAT_TPU_HEARTBEAT_FILE`` at every chunk boundary (the file-mtime
  projection of the ``fit.heartbeat_ts`` gauge); a live process whose
  heartbeat goes stale past ``heartbeat_timeout_s`` is *hung* and gets
  killed, then treated as lost.

On loss the supervisor reshapes the simulated world (``shrink_by``
devices smaller, never below ``min_world``) and relaunches with
``resume_from=checkpoint_dir``, so the fit continues from its last
durable step on the smaller mesh.  Recovery latency — loss detection to
the resumed worker's first heartbeat — feeds the shared
``elastic.recovery_ms`` histogram; losses/reshapes/world-size use the
same counters and gauge as the in-process supervisor.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, List, Optional, Tuple

from ..core._env import env_float, env_int
from ..resilience.errors import ReshapeError, WorkerLostError
from ..resilience.faults import inject as _inject
from .supervisor import LOSSES_C, RECOVERY_H, RESHAPES_C, WORLD_G

__all__ = ["ProcessSupervisor", "kmeans_worker_source"]

#: build_worker(world_size, resume_from, attempt) -> (argv, extra_env)
WorkerBuilder = Callable[[int, Optional[str], int], Tuple[List[str], dict]]


def kmeans_worker_source(
    checkpoint_dir: str,
    *,
    resume_from: Optional[str] = None,
    n: int = 240,
    f: int = 6,
    k: int = 4,
    max_iter: int = 40,
    tol: float = 1e-4,
    seed: int = 13,
    random_state: int = 3,
    checkpoint_every: int = 2,
    x64: bool = True,
) -> str:
    """Source of a self-contained KMeans fit worker.

    The canonical elastic workload: seeded data generation is
    world-size-independent (a global array is drawn, then sharded), the
    fit checkpoints every ``checkpoint_every`` iterations into
    ``checkpoint_dir``, and the final converged state is readable from
    the same checkpoint directory — the supervisor never parses stdout.
    Used by the elastic tests, the MULTICHIP ``elastic_recovery``
    scenario and the ``bench_resilience`` recovery-time metric."""
    lines = [
        "import jax",
        "jax.config.update('jax_platforms', 'cpu')",
    ]
    if x64:
        lines.append("jax.config.update('jax_enable_x64', True)")
    lines += [
        "import heat_tpu as ht",
        f"ht.random.seed({seed})",
        f"x = ht.random.randn({n}, {f}, split=0).astype(ht.float32)",
        f"km = ht.cluster.KMeans(n_clusters={k}, init='random', max_iter={max_iter},",
        f"                       tol={tol}, random_state={random_state},",
        f"                       checkpoint_every={checkpoint_every},",
        f"                       checkpoint_dir={checkpoint_dir!r},",
        f"                       resume_from={resume_from!r})",
        "km.fit(x)",
        "print('ELASTIC-WORKER-OK', km.n_iter_, flush=True)",
    ]
    return "\n".join(lines)


class ProcessSupervisor:
    """Supervise a fit subprocess through preemption, reshape, resume.

    ``build_worker(world_size, resume_from, attempt)`` returns
    ``(argv, extra_env)`` for one attempt; the supervisor adds the mesh
    size (``XLA_FLAGS`` host-device count), the heartbeat file and a
    clean CPU platform to the environment.  ``run()`` returns a summary
    dict (final world size, recoveries, per-recovery latency, worker
    tails); a worker that keeps dying past ``max_recoveries`` raises
    :class:`WorkerLostError`, a shrink below ``min_world`` raises
    :class:`ReshapeError`."""

    def __init__(
        self,
        build_worker: WorkerBuilder,
        checkpoint_dir: str,
        world_size: int,
        *,
        min_world: Optional[int] = None,
        shrink_by: int = 1,
        max_recoveries: Optional[int] = None,
        heartbeat_timeout_s: Optional[float] = None,
        poll_s: Optional[float] = None,
        attempt_timeout_s: float = 600.0,
        env: Optional[dict] = None,
    ):
        if world_size < 1:
            raise ReshapeError(f"world_size must be >= 1, got {world_size}")
        self.build_worker = build_worker
        self.checkpoint_dir = os.path.abspath(checkpoint_dir)
        self.world_size = int(world_size)
        self.min_world = (
            env_int("HEAT_TPU_ELASTIC_MIN_WORLD") if min_world is None else int(min_world)
        )
        self.shrink_by = int(shrink_by)
        self.max_recoveries = (
            env_int("HEAT_TPU_ELASTIC_MAX_RECOVERIES")
            if max_recoveries is None
            else int(max_recoveries)
        )
        self.heartbeat_timeout_s = (
            env_float("HEAT_TPU_ELASTIC_HEARTBEAT_TIMEOUT_S")
            if heartbeat_timeout_s is None
            else float(heartbeat_timeout_s)
        )
        self.poll_s = env_float("HEAT_TPU_ELASTIC_POLL_S") if poll_s is None else float(poll_s)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.base_env = dict(os.environ if env is None else env)

    # -- one attempt ----------------------------------------------------
    def _attempt_env(self, world: int, extra: dict, hb_path: str) -> dict:
        env = dict(self.base_env)
        # the worker controls the platform itself (jax.config): strip
        # inherited overrides that would pin the parent's device count
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={world}"
        env["HEAT_TPU_HEARTBEAT_FILE"] = hb_path
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(extra or {})
        return env

    def _await_worker(
        self, proc: subprocess.Popen, hb_path: str, launched_wall: float
    ) -> Tuple[int, Optional[float]]:
        """Poll one worker to completion (or kill it for staleness /
        attempt timeout).  Returns ``(returncode, first_beat_monotonic)``."""
        started = time.monotonic()
        first_beat: Optional[float] = None
        while True:
            rc = proc.poll()
            try:
                beat_wall: Optional[float] = os.path.getmtime(hb_path)
            except OSError:
                beat_wall = None
            if first_beat is None and beat_wall is not None and beat_wall >= launched_wall:
                first_beat = time.monotonic()
            if rc is not None:
                return rc, first_beat
            now_wall = time.time()
            hb_age = now_wall - (beat_wall if beat_wall is not None else launched_wall)
            if self.heartbeat_timeout_s > 0 and hb_age > self.heartbeat_timeout_s:
                proc.kill()
                proc.wait()
                return -9, first_beat  # hung worker: killed, counts as lost
            if time.monotonic() - started > self.attempt_timeout_s:
                proc.kill()
                proc.wait()
                raise WorkerLostError(
                    f"worker exceeded the attempt timeout "
                    f"({self.attempt_timeout_s:.0f}s) without finishing",
                    world_size=self.world_size,
                )
            time.sleep(self.poll_s)

    @staticmethod
    def _tail(path: str, limit: int = 2000) -> str:
        try:
            with open(path, "rb") as f:
                data = f.read()
            return data[-limit:].decode("utf-8", "replace")
        except OSError:
            return ""

    # -- the supervision loop -------------------------------------------
    def run(self) -> dict:
        """Drive attempts until a worker finishes cleanly.

        Returns ``{"world_size", "recoveries", "recovery_s": [...],
        "attempts": [{"world_size", "returncode", "tail"}, ...]}``."""
        world = self.world_size
        WORLD_G.set(world)
        resume: Optional[str] = None
        recoveries = 0
        recovery_s: List[float] = []
        attempts: List[dict] = []
        hb_path = os.path.join(self.checkpoint_dir, ".heartbeat")
        t_loss: Optional[float] = None
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        while True:
            argv, extra = self.build_worker(world, resume, len(attempts))
            env = self._attempt_env(world, extra, hb_path)
            log_path = os.path.join(self.checkpoint_dir, f".worker-{len(attempts)}.log")
            launched_wall = time.time()
            log_fd = os.open(log_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
            try:
                proc = subprocess.Popen(
                    argv, env=env, stdout=log_fd, stderr=subprocess.STDOUT
                )
            finally:
                os.close(log_fd)
            rc, first_beat = self._await_worker(proc, hb_path, launched_wall)
            attempts.append(
                {"world_size": world, "returncode": rc, "tail": self._tail(log_path)}
            )
            if t_loss is not None:
                # recovery latency: previous worker's loss -> this
                # worker's first heartbeat (its completion when it
                # resumed straight into a converged checkpoint)
                end = first_beat if first_beat is not None else time.monotonic()
                dt = max(0.0, end - t_loss)
                recovery_s.append(dt)
                RECOVERY_H.observe(dt * 1000.0)
                t_loss = None
            if rc == 0:
                return {
                    "world_size": world,
                    "recoveries": recoveries,
                    "recovery_s": recovery_s,
                    "attempts": attempts,
                }
            # -- loss detected ------------------------------------------
            t_loss = time.monotonic()
            _inject("elastic.detect", returncode=rc, world_size=world)
            LOSSES_C.inc()
            recoveries += 1
            if recoveries > self.max_recoveries:
                raise WorkerLostError(
                    f"worker died (rc={rc}) and the recovery budget "
                    f"({self.max_recoveries}) is exhausted; last output:\n"
                    + attempts[-1]["tail"],
                    world_size=world,
                )
            target = world - self.shrink_by
            if target < self.min_world:
                raise ReshapeError(
                    f"worker loss would shrink the world to {target}, below "
                    f"the configured minimum {self.min_world}",
                    old_size=world,
                    new_size=target,
                )
            _inject("elastic.reshape", old=world, new=target)
            world = target
            RESHAPES_C.inc()
            WORLD_G.set(world)
            resume = self.checkpoint_dir
            _inject("elastic.resume", world_size=world)
