"""Fault-tolerant fleet router: N shared-nothing replicas, one front door.

The serving layer (``heat_tpu/serving``) is one process on one port —
a crash drops every in-flight request and nothing shares its load.
:class:`FleetRouter` is the explicit front door of a *replica set*:
a stdlib HTTP process that owns routing policy and admission, in front
of N replicas that share nothing (PAPER.md's shape — explicit
communication, no hidden coordinator).  Five mechanisms:

* **Consistent-hash model affinity with bounded load** — a request for
  model M prefers the replica that rendezvous-hashes highest for M
  (warm executable caches, warm model state), but spills to the next
  replica in M's preference order when the favorite's in-flight count
  exceeds ``HEAT_TPU_FLEET_LOAD_FACTOR`` x the ready-replica average
  + 1 (consistent hashing with bounded loads): affinity when idle,
  fan-out under pressure — the property the 1->4 replica scale-out
  gate measures.
* **Health-aware routing** — a poller thread scrapes every replica's
  ``/readyz`` each ``HEAT_TPU_FLEET_HEALTH_PERIOD_S``: readiness,
  lifecycle state (a *draining* replica stops receiving new work), and
  the replica's model list (the placement map 404-free routing needs).
* **Bounded-retry failover** — ``POST /v1/predict`` is idempotent, so
  a connect error, timeout or 5xx fails over to the next healthy
  replica under a :class:`~heat_tpu.resilience.retry.RetryPolicy`
  (``HEAT_TPU_FLEET_RETRIES`` attempts, short backoff).  Only when no
  replica can take the model does the client see a **typed 503**
  (:class:`~heat_tpu.resilience.errors.NoReplicaError`) with a
  ``Retry-After`` of one health period.  Replica-side verdicts that
  retrying cannot change (400/404/429) pass through.
* **Per-replica circuit breaker** — ``HEAT_TPU_FLEET_CB_FAILURES``
  consecutive failures eject a replica from routing; after
  ``HEAT_TPU_FLEET_CB_COOLDOWN_S`` ONE half-open probe request is
  admitted — success readmits the replica, failure re-opens the
  breaker.  A flapping replica costs its own probes, never the fleet's
  tail latency.
* **Global admission** — one fleet-wide token bucket
  (``HEAT_TPU_FLEET_RATE``/``BURST``) sheds with a 429 + Retry-After
  *before* any replica is touched: N replicas must not mean N times
  the configured quota.

**Cross-replica tracing**: the router stamps a fresh trace_id into
every forwarded predict body; the replica adopts it for its
``serve.request`` tree, so ``aggregate.stitch_traces`` reassembles one
request across router and replica by the existing trace_id merge.

Run in-process (tests, the autoscaler harness) or as its own process::

    python -m heat_tpu.fleet.router --port 8000 \
        --replica http://host:8001 --replica http://host:8002

Routes: ``/v1/*`` proxied with failover; ``/fleet/statusz`` (replica
table, breaker states, counters), ``/fleet/healthz`` (200 iff >= 1
ready replica), ``/fleetz`` (fleet-wide roofline rollup: the health
poller collects each ready replica's ``/rooflinez`` observatory
snapshot and this route renders the merged per-kernel utilization +
watermark table, slowest replica per key highlighted via the PR 6
straggler score, plus each ready replica's ``/canaryz`` canary
decision-plane snapshot rolled into a fleet-wide per-model verdict
table with divergent-replica highlighting; ``?format=json`` for the
machine form), ``/tenantz`` (the fleet-merged per-tenant cost ledger:
each ready replica's ``/tenantz`` accounts summed per tenant via
``aggregate.merge_tenant_accounts`` — the fleet answer to "which tenant
cost what"), ``/metrics`` (the router process's own registry).
"""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import tsan as _tsan
from ..analysis.protocols import (
    ACTOR_ROUTER, CB_HALF_OPEN, CB_READMIT, CB_REOPEN, CB_TRIP,
)
from ..resilience.errors import NoReplicaError, OverloadedError, TransientFault
from ..resilience.faults import inject as _inject
from ..resilience.retry import RetryPolicy
from ..serving.admission import TokenBucket
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing

__all__ = ["FleetRouter", "ReplicaFailure"]

_REQS_C = _tm.counter("fleet.requests", "requests routed (all verbs)")
_FAILOVERS_C = _tm.counter(
    "fleet.failovers", "attempts that failed over to another replica"
)
_SHED_C = _tm.counter("fleet.shed", "requests shed by the fleet-global token bucket")
_NO_REPLICA_C = _tm.counter(
    "fleet.no_replica", "typed 503s: no replica could take the model"
)
_CB_OPEN_C = _tm.counter("fleet.cb_ejections", "circuit-breaker replica ejections")
_CB_CLOSE_C = _tm.counter(
    "fleet.cb_readmissions", "circuit-breaker readmissions (successful half-open probe)"
)
_LATENCY_H = _tm.histogram("fleet.latency_ms", "end-to-end routed request latency")


class ReplicaFailure(TransientFault):
    """One replica attempt failed retryably (connect error, timeout,
    5xx); the failover loop picks another replica on the next attempt."""

    def __init__(self, message: str, url: str = ""):
        super().__init__(message)
        self.url = url


class _Replica:
    """Router-side bookkeeping for one replica (guarded by the router
    lock)."""

    __slots__ = (
        "url", "ready", "state", "models", "not_models", "inflight", "fails",
        "cb_open", "cb_open_until", "probing", "last_poll_ok", "added_at",
        "observatory", "observatory_ts", "canary", "canary_ts",
        "tenants", "tenants_ts", "journal", "journal_ts",
    )

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.ready = False
        self.state = "unknown"
        self.models: Optional[frozenset] = None  # None until first poll
        self.not_models: set = set()  # 404-learned absences until the next poll
        self.inflight = 0
        self.fails = 0
        self.cb_open = False
        self.cb_open_until = 0.0
        self.probing = False
        self.last_poll_ok = 0.0
        self.added_at = time.time()
        #: last /rooflinez?format=json snapshot the health poller pulled
        #: (None until the replica answers one) — the /fleetz rollup's
        #: per-replica half
        self.observatory: Optional[Dict[str, Any]] = None
        self.observatory_ts = 0.0
        #: last /canaryz?format=json snapshot (same throttled cadence) —
        #: the fleet-wide canary rollup's per-replica half
        self.canary: Optional[Dict[str, Any]] = None
        self.canary_ts = 0.0
        #: last /tenantz?format=json snapshot (same throttled cadence) —
        #: the fleet-wide per-tenant cost rollup's per-replica half
        self.tenants: Optional[Dict[str, Any]] = None
        self.tenants_ts = 0.0
        #: last /decisionz?format=json snapshot (same throttled cadence) —
        #: the fleet-wide decision-timeline rollup's per-replica half
        self.journal: Optional[Dict[str, Any]] = None
        self.journal_ts = 0.0

    def doc(self) -> Dict[str, Any]:
        return {
            "url": self.url,
            "ready": self.ready,
            "state": self.state,
            "models": sorted(self.models) if self.models is not None else None,
            "inflight": self.inflight,
            "consecutive_failures": self.fails,
            "circuit": (
                "half_open" if self.cb_open and self.probing
                else "open" if self.cb_open
                else "closed"
            ),
            "last_poll_ok_age_s": (
                round(time.time() - self.last_poll_ok, 3) if self.last_poll_ok else None
            ),
        }


def _env():
    from ..core import _env as envmod

    return envmod


class FleetRouter:
    """A running fleet router: replica table + health poller + HTTP
    front door.  Constructor arguments override the ``HEAT_TPU_FLEET_*``
    knob defaults per instance."""

    def __init__(
        self,
        replicas: Tuple[str, ...] = (),
        port: int = 0,
        host: str = "127.0.0.1",
        retries: Optional[int] = None,
        timeout_s: Optional[float] = None,
        cb_failures: Optional[int] = None,
        cb_cooldown_s: Optional[float] = None,
        health_period_s: Optional[float] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        load_factor: Optional[float] = None,
    ):
        env = _env()
        self.retries = int(retries) if retries is not None else env.env_int("HEAT_TPU_FLEET_RETRIES")
        self.timeout_s = float(timeout_s) if timeout_s is not None else env.env_float("HEAT_TPU_FLEET_TIMEOUT_S")
        self.cb_failures = int(cb_failures) if cb_failures is not None else env.env_int("HEAT_TPU_FLEET_CB_FAILURES")
        self.cb_cooldown_s = float(cb_cooldown_s) if cb_cooldown_s is not None else env.env_float("HEAT_TPU_FLEET_CB_COOLDOWN_S")
        self.health_period_s = float(health_period_s) if health_period_s is not None else env.env_float("HEAT_TPU_FLEET_HEALTH_PERIOD_S")
        self.load_factor = float(load_factor) if load_factor is not None else env.env_float("HEAT_TPU_FLEET_LOAD_FACTOR")
        self._bucket = TokenBucket(
            float(rate) if rate is not None else env.env_float("HEAT_TPU_FLEET_RATE"),
            float(burst) if burst is not None else env.env_float("HEAT_TPU_FLEET_BURST"),
        )
        self._replicas: Dict[str, _Replica] = {}
        #: (monotonic, latency_ms) per routed request, bounded — the
        #: autoscaler's p99 window
        self._latencies: deque = deque(maxlen=4096)
        self._lock = _tsan.register_lock("fleet.router")
        self._closed = False
        for url in replicas:
            self.add_replica(url)
        _tm.gauge(
            "fleet.replicas_ready", "replicas currently ready for routing",
            fn=lambda: self._count_ready(),
        )
        # HTTP front door
        router = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "heat-tpu-fleet-router/1"

            def log_message(self, fmt, *args):  # clients poll; stay silent
                pass

            def _reply(self, status: int, body: str, ctype: str = "application/json",
                       headers: Optional[Dict[str, str]] = None) -> None:
                payload = body.encode("utf-8")
                self.send_response(int(status))
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    status, body, ctype, headers = router.handle("GET", self.path, None)
                    self._reply(status, body, ctype, headers)
                except BrokenPipeError:
                    pass
                except Exception as e:  # lint: allow H501(a handler bug must 500, never kill the router thread)
                    try:
                        self._reply(500, json.dumps({"error": f"{type(e).__name__}: {e}"}))
                    except Exception:  # lint: allow H501(socket already gone)
                        pass

            def do_POST(self):  # noqa: N802 - http.server API
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    status, out, ctype, headers = router.handle("POST", self.path, body)
                    self._reply(status, out, ctype, headers)
                except BrokenPipeError:
                    pass
                except Exception as e:  # lint: allow H501(a handler bug must 500, never kill the router thread)
                    try:
                        self._reply(500, json.dumps({"error": f"{type(e).__name__}: {e}"}))
                    except Exception:  # lint: allow H501(socket already gone)
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._address = self._httpd.server_address
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="heat-tpu-fleet-router", daemon=True
        )
        self._serve_thread.start()
        # health poller: Event-driven cadence (close() wakes it)
        self._stop = threading.Event()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="heat-tpu-fleet-health", daemon=True
        )
        self._poll_thread.start()

    # -- replica set ----------------------------------------------------
    @property
    def port(self) -> int:
        return self._address[1]

    @property
    def url(self) -> str:
        return f"http://{self._address[0]}:{self.port}"

    def add_replica(self, url: str) -> None:
        """Register a replica (idempotent); it becomes routable after
        its first successful readiness poll."""
        r = _Replica(url)
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            self._replicas.setdefault(r.url, r)

    def remove_replica(self, url: str) -> None:
        """Drop a replica from the table (no-op when absent)."""
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            self._replicas.pop(url.rstrip("/"), None)

    def drain_replica(self, url: str) -> None:
        """Stop routing NEW work to a replica (its in-flight requests
        finish normally) — the autoscaler calls this before SIGTERM."""
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            r = self._replicas.get(url.rstrip("/"))
            if r is not None:
                r.state = "draining"
                r.ready = False

    def replica_urls(self) -> List[str]:
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            return sorted(self._replicas)

    def preferred(self, model: str) -> Optional[str]:
        """The replica URL ``model``'s traffic currently prefers (the
        rendezvous-hash favorite among ready replicas) — what a
        kill-under-load scenario should aim at, and what an operator
        asks before draining 'the hot one'."""
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            ready = [r for r in self._replicas.values() if r.ready and r.state != "draining"]
            order = self._preference(model, ready)
            return order[0].url if order else None

    def _count_ready(self) -> int:
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            return sum(1 for r in self._replicas.values() if r.ready)

    # -- health polling -------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_health()
            self._stop.wait(self.health_period_s)

    def poll_health(self) -> None:
        """One readiness sweep over the replica table (the poller thread
        runs this every period; tests call it directly for determinism)."""
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            urls = list(self._replicas)
            obs_ts = {u: self._replicas[u].observatory_ts for u in urls}
        now = time.time()
        # the observatory sweep runs on its own (slower) cadence: the
        # readiness poll can tick sub-second, but re-pulling a ledger
        # snapshot that fast buys nothing and the replica's first
        # /rooflinez answer may include its one-shot peak calibration
        obs_period = max(self.health_period_s, 2.0)
        for url in urls:
            ready, state, models = self._probe_readyz(url)
            # the same sweep collects the replica's roofline-observatory
            # and canary-decision-plane snapshots (the per-replica halves
            # of the /fleetz fleet rollup) on the throttled cadence.
            # Only ready replicas are asked: a warming/draining replica's
            # ledger and windows are noise.
            due = ready and now - obs_ts.get(url, 0.0) >= obs_period
            obs = self._probe_rooflinez(url) if due else None
            can = self._probe_canaryz(url) if due else None
            ten = self._probe_tenantz(url) if due else None
            jnl = self._probe_decisionz(url) if due else None
            with self._lock:
                _tsan.note_access("fleet.router.replicas")
                r = self._replicas.get(url)
                if r is None:
                    continue
                if obs is not None:
                    r.observatory = obs
                    r.observatory_ts = time.time()
                if can is not None:
                    r.canary = can
                    r.canary_ts = time.time()
                if ten is not None:
                    r.tenants = ten
                    r.tenants_ts = time.time()
                if jnl is not None:
                    r.journal = jnl
                    r.journal_ts = time.time()
                if r.state == "draining" and state not in ("ready",):
                    # a locally initiated drain sticks until the replica
                    # itself reports ready again (a cancelled drain)
                    r.models = models if models is not None else r.models
                    continue
                r.ready = ready
                r.state = state
                if models is not None:
                    r.models = models
                    r.not_models = set()  # the poll is fresher truth
                if ready:
                    r.last_poll_ok = time.time()

    def _probe_readyz(self, url: str):
        """(ready, state, models) for one replica; never raises."""
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=2.0) as resp:
                doc = json.load(resp)
            code = 200
        except urllib.error.HTTPError as e:
            try:
                doc = json.load(e)
            except Exception:  # lint: allow H501(non-JSON 5xx body; the status code is the verdict)
                doc = {}
            code = e.code
        except Exception:  # lint: allow H501(unreachable replica is a routing verdict, not an error)
            return False, "unreachable", None
        state = str(doc.get("state", "unknown"))
        models = doc.get("models")
        models = frozenset(str(m) for m in models) if isinstance(models, list) else None
        return code == 200 and bool(doc.get("ready", code == 200)), state, models

    def _probe_rooflinez(self, url: str) -> Optional[Dict[str, Any]]:
        """One replica's observatory snapshot, or None (replica without
        the route, unreachable, or malformed — never raises)."""
        try:
            with urllib.request.urlopen(
                url + "/rooflinez?format=json&limit=64", timeout=2.0
            ) as resp:
                doc = json.load(resp)
            return doc if isinstance(doc, dict) else None
        except Exception:  # lint: allow H501(an observatory-less replica is a rollup gap, not an error)
            return None

    def _probe_canaryz(self, url: str) -> Optional[Dict[str, Any]]:
        """One replica's canary decision-plane snapshot, or None
        (replica without the route, unreachable, or malformed — never
        raises)."""
        try:
            with urllib.request.urlopen(
                url + "/canaryz?format=json", timeout=2.0
            ) as resp:
                doc = json.load(resp)
            return doc if isinstance(doc, dict) else None
        except Exception:  # lint: allow H501(a canary-less replica is a rollup gap, not an error)
            return None

    def _probe_tenantz(self, url: str) -> Optional[Dict[str, Any]]:
        """One replica's per-tenant cost-account snapshot, or None
        (replica without the route, unreachable, or malformed — never
        raises)."""
        try:
            with urllib.request.urlopen(
                url + "/tenantz?format=json", timeout=2.0
            ) as resp:
                doc = json.load(resp)
            return doc if isinstance(doc, dict) else None
        except Exception:  # lint: allow H501(a meter-less replica is a rollup gap, not an error)
            return None

    def _probe_decisionz(self, url: str) -> Optional[Dict[str, Any]]:
        """One replica's decision-journal snapshot, or None (replica
        without the route, unreachable, or malformed — never raises)."""
        try:
            with urllib.request.urlopen(
                url + "/decisionz?format=json&limit=64", timeout=2.0
            ) as resp:
                doc = json.load(resp)
            return doc if isinstance(doc, dict) else None
        except Exception:  # lint: allow H501(a journal-less replica is a rollup gap, not an error)
            return None

    # -- routing policy -------------------------------------------------
    def _preference(self, model: str, replicas: List[_Replica]) -> List[_Replica]:
        """Rendezvous-hash preference order of ``replicas`` for
        ``model`` (highest hash first): every router instance computes
        the same order from the same replica set, no shared state."""

        def score(r: _Replica) -> int:
            h = hashlib.blake2b(
                f"{model}|{r.url}".encode("utf-8"), digest_size=8
            ).digest()
            return int.from_bytes(h, "big")

        return sorted(replicas, key=score, reverse=True)

    def _pick(self, model: str, exclude: Optional[set] = None) -> Optional[_Replica]:
        """Choose a replica for one attempt (and count it in-flight), or
        None when no replica can take the model right now.

        Policy: rendezvous order, filtered to ready + not draining +
        hosting the model (unknown model lists count as hosting);
        breaker-open replicas are skipped unless their cooldown expired
        and no probe is out (then ONE half-open probe is admitted);
        bounded load spills past a replica whose in-flight exceeds
        ``load_factor`` x the eligible average + 1."""
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            eligible: List[_Replica] = []
            for r in self._replicas.values():
                if exclude and r.url in exclude:
                    continue
                if not r.ready or r.state == "draining":
                    continue
                if model and (
                    model in r.not_models
                    or (r.models is not None and model not in r.models)
                ):
                    continue
                if r.cb_open:
                    if now >= r.cb_open_until and not r.probing:
                        eligible.append(r)  # half-open probe candidate
                    continue
                eligible.append(r)
            if not eligible:
                return None
            order = self._preference(model, eligible)
            total = sum(r.inflight for r in eligible)
            cap = self.load_factor * (total / len(eligible)) + 1.0
            chosen = next((r for r in order if r.inflight < cap), None)
            if chosen is None:
                chosen = min(order, key=lambda r: r.inflight)
            probe = self._cb_mark_probe(chosen)
            chosen.inflight += 1
        # journal after our lock is released (emit takes its own lock)
        if probe:
            trip = _journal.find_last(actor=ACTOR_ROUTER, action=CB_TRIP)
            _journal.emit(
                ACTOR_ROUTER, CB_HALF_OPEN,
                model=model or None,
                severity="info",
                message=f"half-open probe admitted to {chosen.url}",
                cause=(
                    trip["event_id"]
                    if trip and trip["evidence"].get("replica") == chosen.url
                    else None
                ),
                evidence={"replica": chosen.url,
                          "cooldown_s": self.cb_cooldown_s},
            )
        return chosen

    # -- breaker transitions (registered in analysis/protocols.py:
    # writes live in the lock-held helpers below, the declared journal
    # events are emitted by _pick/_report after the lock is released) --
    def _cb_mark_probe(self, replica: _Replica) -> bool:
        """(caller holds ``self._lock``) Flip an eligible open replica
        into its half-open probe slot; True iff this attempt IS the
        probe (open -> half_open)."""
        if not replica.cb_open:
            return False
        replica.probing = True  # the one admitted half-open probe
        return True

    def _cb_on_success(self, replica: _Replica) -> Optional[str]:
        """(caller holds ``self._lock``) Success-path breaker
        bookkeeping; returns the journal verb to emit after release.

        Only the half-open PROBE's success readmits (half_open ->
        closed).  A success while open with no probe out is a stale
        response from before the trip — readmitting on it would skip
        the probe protocol entirely, so it only clears the failure
        streak."""
        replica.fails = 0
        if replica.cb_open and replica.probing:
            replica.cb_open = False
            replica.probing = False
            _CB_CLOSE_C.inc()
            return CB_READMIT
        return None

    def _cb_on_failure(self, replica: _Replica, now: float) -> Optional[str]:
        """(caller holds ``self._lock``) Failure-path breaker
        bookkeeping; returns the journal verb to emit after release.

        A failed half-open probe re-opens for another cooldown
        (half_open -> open, journaled as ``cb_reopen``); a stale
        failure while open with no probe out is silent bookkeeping; a
        closed replica trips once the consecutive-failure threshold is
        crossed."""
        replica.fails += 1
        if replica.cb_open:
            probe_failed = replica.probing
            replica.probing = False
            replica.cb_open_until = now + self.cb_cooldown_s
            return CB_REOPEN if probe_failed else None
        if replica.fails >= self.cb_failures:
            replica.cb_open = True
            replica.probing = False
            replica.cb_open_until = now + self.cb_cooldown_s
            _CB_OPEN_C.inc()
            return CB_TRIP
        return None

    def _report(self, replica: _Replica, ok: bool) -> None:
        """Account one attempt's outcome into the replica's breaker."""
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            replica.inflight = max(0, replica.inflight - 1)
            if ok:
                transition = self._cb_on_success(replica)
            else:
                transition = self._cb_on_failure(replica, now)
            fails = replica.fails
        if transition == CB_TRIP:
            _journal.emit(
                ACTOR_ROUTER, CB_TRIP,
                severity="warn",
                message=(
                    f"circuit breaker opened for {replica.url} after "
                    f"{fails} consecutive failures"
                ),
                evidence={"replica": replica.url, "consecutive_failures": fails,
                          "threshold": self.cb_failures,
                          "cooldown_s": self.cb_cooldown_s},
            )
        elif transition == CB_READMIT:
            probe = _journal.find_last(actor=ACTOR_ROUTER, action=CB_HALF_OPEN)
            _journal.emit(
                ACTOR_ROUTER, CB_READMIT,
                severity="info",
                message=f"half-open probe succeeded; {replica.url} readmitted",
                cause=(
                    probe["event_id"]
                    if probe and probe["evidence"].get("replica") == replica.url
                    else None
                ),
                evidence={"replica": replica.url},
            )
        elif transition == CB_REOPEN:
            probe = _journal.find_last(actor=ACTOR_ROUTER, action=CB_HALF_OPEN)
            _journal.emit(
                ACTOR_ROUTER, CB_REOPEN,
                severity="warn",
                message=(
                    f"half-open probe failed; {replica.url} re-opened for "
                    f"another {self.cb_cooldown_s}s cooldown"
                ),
                cause=(
                    probe["event_id"]
                    if probe and probe["evidence"].get("replica") == replica.url
                    else None
                ),
                evidence={"replica": replica.url,
                          "cooldown_s": self.cb_cooldown_s},
            )

    # -- proxying -------------------------------------------------------
    def _forward(self, replica: _Replica, method: str, path: str,
                 body: Optional[bytes]):
        """One proxied attempt; returns ``(status, body_bytes, headers)``
        or raises :class:`ReplicaFailure` on a retryable outcome."""
        req = urllib.request.Request(
            replica.url + path, data=body, method=method,
            headers={"Content-Type": "application/json"} if body else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                out = resp.read()
                self._report(replica, True)
                return resp.getcode(), out, dict(resp.headers)
        except urllib.error.HTTPError as e:
            out = e.read()
            if e.code >= 500:
                self._report(replica, False)
                raise ReplicaFailure(
                    f"replica {replica.url} answered {e.code}", url=replica.url
                ) from None
            # 4xx is the replica's considered verdict (bad request, over
            # quota, unknown model): the replica itself is healthy
            self._report(replica, True)
            return e.code, out, dict(e.headers)
        except (urllib.error.URLError, socket.timeout, ConnectionError, OSError) as e:
            self._report(replica, False)
            raise ReplicaFailure(
                f"replica {replica.url} unreachable ({e})", url=replica.url
            ) from None

    def _route(self, model: str, method: str, path: str, body: Optional[bytes]):
        """Failover routing of one idempotent request: each attempt
        picks the best replica excluding the one that just failed, under
        the bounded :class:`RetryPolicy`."""
        _inject("fleet.route", model=model, path=path)
        policy = RetryPolicy(
            max_attempts=max(1, self.retries),
            base_delay=0.02,
            max_delay=0.5,
            retryable=(ReplicaFailure,),
        )
        last_failed: set = set()

        def no_candidate(attempts: int):
            # distinguish "the fleet is down" (typed 503, retryable by
            # the client) from "no ready replica hosts this model at
            # all" (an unknown model: honest 404, retrying is pointless)
            with self._lock:
                _tsan.note_access("fleet.router.replicas", write=False)
                ready = [
                    r for r in self._replicas.values()
                    if r.ready and r.state != "draining"
                ]
                unknown_everywhere = bool(model) and bool(ready) and all(
                    model in r.not_models
                    or (r.models is not None and model not in r.models)
                    for r in ready
                )
            if unknown_everywhere and not last_failed:
                return _ModelNotFound(model)
            return NoReplicaError(
                f"no replica can take model {model!r} "
                f"({len(self.replica_urls())} registered)",
                model=model,
                attempts=attempts,
                retry_after_s=self.health_period_s,
            )

        def attempt():
            tried_here: set = set(last_failed)
            queue_shed = None
            while True:
                replica = self._pick(model, exclude=tried_here)
                if replica is None:
                    if queue_shed is not None:
                        # EVERY candidate is at its local queue bound:
                        # the fleet really is full — pass the shed (and
                        # its drain-rate Retry-After) to the client
                        return queue_shed
                    raise no_candidate(len(last_failed) + 1)
                try:
                    status, out, headers = self._forward(replica, method, path, body)
                except ReplicaFailure:
                    last_failed.add(replica.url)
                    _FAILOVERS_C.inc()
                    raise
                if status == 404 and path == "/v1/predict":
                    # this replica cannot take the model; remember and
                    # try the next in preference order without burning a
                    # retry attempt (the replica is healthy)
                    tried_here.add(replica.url)
                    with self._lock:
                        _tsan.note_access("fleet.router.replicas")
                        replica.not_models.add(model)
                    continue
                if status == 429 and path == "/v1/predict":
                    # replica-LOCAL pressure (bounded admission queue)
                    # spills to the next replica — that is exactly what
                    # a fleet is for; a tenant-quota shed is a policy
                    # verdict and passes through untouched
                    try:
                        cause = json.loads(out).get("cause")
                    except ValueError:
                        cause = None
                    if cause == "queue":
                        tried_here.add(replica.url)
                        queue_shed = (status, out, headers)
                        continue
                return status, out, headers

        return policy.call(attempt)

    # -- the HTTP surface ----------------------------------------------
    def handle(self, method: str, path: str, body: Optional[bytes]):
        """Route one request; returns ``(status, body_str, content_type,
        headers)``.  The in-process entry point the HTTP handlers and
        the tests share."""
        bare = path.split("?", 1)[0]
        if bare.startswith("/fleet/") or bare in ("/metrics", "/fleetz", "/tenantz"):
            query = path.split("?", 1)[1] if "?" in path else ""
            params = dict(kv.split("=", 1) for kv in query.split("&") if "=" in kv)
            return self._handle_local(bare, params)
        if not bare.startswith("/v1/"):
            return 404, json.dumps({"error": f"unknown route {bare!r}"}), "application/json", {}
        t0 = time.perf_counter()
        try:
            if method == "POST" and bare == "/v1/predict":
                status, out, headers = self._predict(body)
            else:
                model = ""
                if bare.startswith("/v1/models/"):
                    model = bare[len("/v1/models/"):].split("/", 1)[0]
                status, out, headers = self._route(model, method, bare, body)
        except OverloadedError as e:
            _SHED_C.inc()
            doc = {"error": str(e), "cause": e.cause, "retry_after_s": e.retry_after_s}
            hdrs = {}
            if e.retry_after_s is not None:
                hdrs["Retry-After"] = f"{max(e.retry_after_s, 0.001):.3f}"
            return 429, json.dumps(doc), "application/json", hdrs
        except NoReplicaError as e:
            _NO_REPLICA_C.inc()
            doc = {
                "error": str(e),
                "cause": "no_replica",
                "model": e.model,
                "attempts": e.attempts,
                "retry_after_s": e.retry_after_s,
            }
            hdrs = {"Retry-After": f"{max(e.retry_after_s or 0.001, 0.001):.3f}"}
            return 503, json.dumps(doc), "application/json", hdrs
        except _ModelNotFound as e:
            return 404, json.dumps({"error": f"unknown model {e.model!r}"}), "application/json", {}
        except ReplicaFailure as e:
            # bounded failover exhausted on real failures: the honest
            # verdict is unavailability, typed like the no-replica case
            _NO_REPLICA_C.inc()
            doc = {
                "error": f"all failover attempts failed (last: {e})",
                "cause": "failover_exhausted",
                "retry_after_s": self.health_period_s,
            }
            return 503, json.dumps(doc), "application/json", {
                "Retry-After": f"{max(self.health_period_s, 0.001):.3f}"
            }
        ms = (time.perf_counter() - t0) * 1e3
        _REQS_C.inc()
        _LATENCY_H.observe(ms)
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            self._latencies.append((time.monotonic(), ms))
        ctype = headers.get("Content-Type", "application/json")
        fwd = {k: v for k, v in headers.items() if k.lower() == "retry-after"}
        return status, out.decode("utf-8", "replace"), ctype, fwd

    def _predict(self, body: Optional[bytes]):
        """The /v1/predict path: global admission, trace-id stamping,
        failover routing."""
        try:
            doc = json.loads(body or b"")
        except ValueError:
            return 400, b'{"error": "request body must be a JSON object"}', {}
        if not isinstance(doc, dict) or "model" not in doc:
            return 400, b'{"error": "predict body needs a \\"model\\" field"}', {}
        model = str(doc["model"])
        inputs = doc.get("inputs")
        rows = len(inputs) if isinstance(inputs, list) and inputs and isinstance(inputs[0], list) else 1
        retry_after = self._bucket.take(max(1, rows))
        if retry_after > 0.0:
            raise OverloadedError(
                f"fleet quota exceeded ({self._bucket.rate:g} rows/s); "
                f"retry in {retry_after:.3f}s",
                cause="quota",
                retry_after_s=retry_after,
            )
        if not doc.get("trace_id"):
            # stamp the routed trace id: the replica adopts it, so the
            # request stitches across processes in /tracez + aggregate
            doc["trace_id"] = _tracing.new_trace_id()
            body = json.dumps(doc).encode("utf-8")
        return self._route(model, "POST", "/v1/predict", body)

    def _handle_local(self, path: str, params: Optional[Dict[str, str]] = None):
        params = params or {}
        if path == "/fleet/healthz":
            n = self._count_ready()
            doc = {"ready_replicas": n, "replicas": len(self.replica_urls())}
            return (200 if n else 503), json.dumps(doc), "application/json", {}
        if path == "/fleet/statusz":
            return 200, json.dumps(self.statusz(), indent=1, default=str), "application/json", {}
        if path == "/fleetz":
            if params.get("format") == "json":
                return 200, json.dumps(self.fleetz_report(), indent=1, default=str), "application/json", {}
            return 200, self.render_fleetz_html(), "text/html", {}
        if path == "/tenantz":
            # the fleet-merged view of every replica's tenant accounts —
            # same route name as the replica surface, so a dashboard
            # pointed at "the service" works against router or replica
            doc = self.fleetz_report()["tenants"]
            if params.get("format") == "json":
                return 200, json.dumps(doc, indent=1, default=str), "application/json", {}
            return 200, self._render_tenants_html(doc), "text/html", {}
        if path == "/metrics":
            from ..telemetry.server import OPENMETRICS_CONTENT_TYPE

            return 200, _tm.expose(), OPENMETRICS_CONTENT_TYPE, {}
        return 404, json.dumps({"error": f"unknown route {path!r}"}), "application/json", {}

    # -- fleet-wide roofline rollup (/fleetz) ---------------------------
    def fleetz_report(self) -> Dict[str, Any]:
        """The fleet-wide observatory rollup: every polled replica's
        watermark + calibration provenance, and each dispatch key's
        per-replica roofline rows merged into one record with the
        slowest replica named and its relative excess scored by the
        PR 6 straggler machinery (``aggregate.straggler_score`` over
        the per-replica fenced means — ``0`` balanced, ``1`` = the
        slowest replica takes 2x the median)."""
        from ..telemetry.aggregate import straggler_score

        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            snaps = {
                r.url: (dict(r.observatory), r.observatory_ts)
                for r in self._replicas.values()
                if r.observatory is not None
            }
            canary_snaps = {
                r.url: dict(r.canary)
                for r in self._replicas.values()
                if r.canary is not None
            }
            tenant_snaps = {
                r.url: dict(r.tenants)
                for r in self._replicas.values()
                if r.tenants is not None
            }
            journal_snaps = {
                r.url: dict(r.journal)
                for r in self._replicas.values()
                if r.journal is not None
            }
        replicas: Dict[str, Any] = {}
        kernels: Dict[str, Dict[str, Any]] = {}
        now = time.time()
        for url in sorted(snaps):
            obs, ts = snaps[url]
            replicas[url] = {
                "watermark": obs.get("watermark"),
                "peaks": obs.get("peaks"),
                "ledger_rows": obs.get("ledger_total", len(obs.get("ledger") or [])),
                "snapshot_age_s": round(now - ts, 3),
            }
            for row in obs.get("ledger") or []:
                key = row.get("key")
                if not key:
                    continue
                kernels.setdefault(key, {"replicas": {}})["replicas"][url] = {
                    "calls": row.get("calls"),
                    "mean_ms": row.get("mean_ms"),
                    "timing": row.get("timing"),
                    "gflops_per_s": row.get("gflops_per_s"),
                    "gbytes_per_s": row.get("gbytes_per_s"),
                    "utilization": row.get("utilization"),
                    "bound": row.get("bound"),
                }
        for key, entry in kernels.items():
            per = entry["replicas"]
            means = [(u, per[u].get("mean_ms")) for u in sorted(per)]
            known = [(u, m) for u, m in means if m is not None]
            entry["slowest"] = max(known, key=lambda um: um[1])[0] if known else None
            entry["straggler_score"] = round(
                straggler_score([m for _u, m in means]), 4
            )
        # fleet-wide canary rollup: each replica runs its own decision
        # plane over its own shadow traffic — a model whose replicas
        # disagree on the canary version or verdict is DIVERGENT, the
        # state an operator must resolve before trusting any promotion
        canary_models: Dict[str, Dict[str, Any]] = {}
        for url in sorted(canary_snaps):
            for name, st in sorted((canary_snaps[url].get("models") or {}).items()):
                e = canary_models.setdefault(
                    name,
                    {"replicas": {}, "verdicts": [], "canary_versions": [],
                     "divergent": False},
                )
                e["replicas"][url] = {
                    "canary_version": st.get("canary_version"),
                    "verdict": st.get("verdict"),
                    "rows": st.get("rows"),
                    "mismatch_pct": st.get("mismatch_pct"),
                    "latency_ratio": st.get("latency_ratio"),
                    "decision": (st.get("decision") or {}).get("action"),
                    "last_trace_id": st.get("last_trace_id"),
                }
                if st.get("verdict") not in e["verdicts"]:
                    e["verdicts"].append(st.get("verdict"))
                if st.get("canary_version") not in e["canary_versions"]:
                    e["canary_versions"].append(st.get("canary_version"))
        for e in canary_models.values():
            e["divergent"] = (
                len(e["verdicts"]) > 1 or len(e["canary_versions"]) > 1
            )
        # fleet-wide per-tenant cost rollup: each replica's /tenantz
        # accounts merged by tenant — totals re-derived from the merged
        # rows, so "accounts sum to the fleet total" survives the merge
        from ..telemetry.aggregate import merge_tenant_accounts

        tenants = merge_tenant_accounts(
            [tenant_snaps[u] for u in sorted(tenant_snaps)]
        )
        # fleet-wide decision timeline: every polled replica's decision
        # journal plus the router's own (breaker trips, probes), merged
        # into one worker-tagged timeline — "what did the fleet decide,
        # in what order" without ssh-ing into N replicas
        decisions = _journal.merge_journal_snapshots(
            [(u, journal_snaps[u]) for u in sorted(journal_snaps)]
            + [("router", _journal.journal_snapshot())]
        )
        return {
            "timestamp": now,
            "ready_replicas": self._count_ready(),
            "replicas": replicas,
            "kernels": dict(sorted(kernels.items())),
            "canary": dict(sorted(canary_models.items())),
            "tenants": tenants,
            "decisions": decisions,
        }

    def render_fleetz_html(self) -> str:
        """The human form of ``/fleetz``: per-replica watermark header +
        the fleet-wide per-kernel utilization table, the slowest replica
        per key highlighted."""
        import html as _html

        doc = self.fleetz_report()
        parts = [
            "<html><head><title>/fleetz</title></head><body>",
            "<h1>/fleetz — fleet roofline rollup</h1>",
            f"<p>{doc['ready_replicas']} ready replica(s), "
            f"{len(doc['replicas'])} with observatory snapshots</p>",
            "<table border=1 cellpadding=3><tr><th>replica</th><th>in use MiB</th>"
            "<th>predicted MiB</th><th>budget MiB</th><th>peaks</th>"
            "<th>ledger rows</th><th>age s</th></tr>",
        ]
        for url, rep in doc["replicas"].items():
            wm = rep.get("watermark") or {}
            peaks = rep.get("peaks")
            peaks_s = (
                f"{float(peaks['flops']) / 1e9:.0f} GF/s · "
                f"{float(peaks['bytes_per_s']) / 1e9:.0f} GB/s ({peaks['source']})"
                if peaks
                else "—"
            )
            parts.append(
                "<tr>"
                f"<td>{_html.escape(url)}</td>"
                f"<td>{float(wm.get('bytes_in_use') or 0) / 2**20:.1f}</td>"
                f"<td>{float(wm.get('predicted_peak_bytes') or 0) / 2**20:.1f}</td>"
                f"<td>{float(wm.get('budget_bytes') or 0) / 2**20:.1f}</td>"
                f"<td>{_html.escape(peaks_s)}</td>"
                f"<td>{rep.get('ledger_rows')}</td>"
                f"<td>{rep.get('snapshot_age_s')}</td>"
                "</tr>"
            )
        parts.append("</table><h2>per-kernel utilization</h2>")
        parts.append(
            "<table border=1 cellpadding=3><tr><th>executable</th><th>replica</th>"
            "<th>calls</th><th>mean ms</th><th>GFLOP/s</th><th>GB/s</th>"
            "<th>util</th><th>bound</th><th>straggler</th></tr>"
        )
        for key, entry in doc["kernels"].items():
            per = entry["replicas"]
            first = True
            for url in sorted(per):
                row = per[url]
                slow = url == entry.get("slowest") and len(per) > 1
                cell = _html.escape(url)
                if slow:
                    cell = f"<b style='color:#b00'>{cell} ⟵ slowest</b>"
                parts.append(
                    "<tr>"
                    + (
                        f"<td rowspan={len(per)}>{_html.escape(str(key))}</td>"
                        if first
                        else ""
                    )
                    + f"<td>{cell}</td>"
                    f"<td>{row.get('calls')}</td><td>{row.get('mean_ms')}</td>"
                    f"<td>{row.get('gflops_per_s') if row.get('gflops_per_s') is not None else '—'}</td>"
                    f"<td>{row.get('gbytes_per_s') if row.get('gbytes_per_s') is not None else '—'}</td>"
                    f"<td>{row.get('utilization') if row.get('utilization') is not None else '—'}</td>"
                    f"<td>{_html.escape(str(row.get('bound')))}</td>"
                    + (
                        f"<td rowspan={len(per)}>{entry.get('straggler_score')}</td>"
                        if first
                        else ""
                    )
                    + "</tr>"
                )
                first = False
        parts.append("</table>")
        if not doc["kernels"]:
            parts.append("<p>no per-kernel snapshots collected yet</p>")
        parts.append("<h2>fleet canary state</h2>")
        canary = doc.get("canary") or {}
        if canary:
            parts.append(
                "<table border=1 cellpadding=3><tr><th>model</th>"
                "<th>replica</th><th>canary</th><th>verdict</th>"
                "<th>rows</th><th>mismatch %</th><th>latency x</th>"
                "<th>decision</th></tr>"
            )
            for name, entry in canary.items():
                per = entry["replicas"]
                first = True
                label = _html.escape(name)
                if entry.get("divergent"):
                    label = (
                        f"<b style='color:#b00'>{label} ⟵ divergent "
                        f"({'/'.join(str(v) for v in entry['verdicts'])})</b>"
                    )
                for url in sorted(per):
                    row = per[url]
                    parts.append(
                        "<tr>"
                        + (f"<td rowspan={len(per)}>{label}</td>" if first else "")
                        + f"<td>{_html.escape(url)}</td>"
                        f"<td>v{_html.escape(str(row.get('canary_version')))}</td>"
                        f"<td>{_html.escape(str(row.get('verdict')))}</td>"
                        f"<td>{row.get('rows')}</td>"
                        f"<td>{row.get('mismatch_pct')}</td>"
                        f"<td>{row.get('latency_ratio')}</td>"
                        f"<td>{_html.escape(str(row.get('decision') or '—'))}</td>"
                        "</tr>"
                    )
                    first = False
            parts.append("</table>")
        else:
            parts.append("<p>no canary snapshots collected yet</p>")
        parts.append("<h2>fleet tenant accounts</h2>")
        parts.append(self._tenants_table_html(doc.get("tenants") or {}))
        parts.append("<h2>fleet decision timeline</h2>")
        decisions = (doc.get("decisions") or {}).get("events") or []
        if decisions:
            parts.append(
                "<table border=1 cellpadding=3><tr><th>time</th><th>worker</th>"
                "<th>actor</th><th>action</th><th>model</th><th>sev</th>"
                "<th>message</th></tr>"
            )
            for e in decisions[-32:]:
                parts.append(
                    "<tr>"
                    f"<td>{time.strftime('%H:%M:%S', time.localtime(e.get('ts', 0)))}</td>"
                    f"<td>{_html.escape(str(e.get('worker', '')))}</td>"
                    f"<td>{_html.escape(str(e.get('actor', '')))}</td>"
                    f"<td>{_html.escape(str(e.get('action', '')))}</td>"
                    f"<td>{_html.escape(str(e.get('model') or '—'))}</td>"
                    f"<td>{_html.escape(str(e.get('severity', '')))}</td>"
                    f"<td>{_html.escape(str(e.get('message', '')))}</td>"
                    "</tr>"
                )
            parts.append("</table>")
        else:
            parts.append("<p>no decision events collected yet</p>")
        parts.append(
            "<p><a href='/tenantz'>full /tenantz</a> · "
            "<a href='/fleetz?format=json'>json</a></p></body></html>"
        )
        return "".join(parts)

    @staticmethod
    def _tenants_table_html(doc: Dict[str, Any]) -> str:
        """The merged-tenant-ledger table fragment (/fleetz + /tenantz)."""
        import html as _html

        rows = doc.get("tenants") or []
        if not rows:
            return "<p>no tenant-account snapshots collected yet</p>"
        t = doc.get("total") or {}
        parts = [
            f"<p>{t.get('tenants', 0)} tenants · {t.get('rows', 0)} rows · "
            f"{float(t.get('flops') or 0.0):.3g} FLOPs · "
            f"{float(t.get('device_ms') or 0.0):.1f} device-ms across "
            f"{doc.get('sources', 0)} replica snapshot(s)</p>",
            "<table border=1 cellpadding=3><tr><th>tenant</th><th>class</th>"
            "<th>requests</th><th>rows</th><th>FLOPs</th><th>bytes</th>"
            "<th>device-ms</th><th>replicas</th><th>models</th></tr>",
        ]
        for r in rows:
            parts.append(
                "<tr>"
                f"<td>{_html.escape(str(r['tenant']))}</td>"
                f"<td>{_html.escape(str(r.get('class')))}</td>"
                f"<td align=right>{r['requests']}</td>"
                f"<td align=right>{r['rows']}</td>"
                f"<td align=right>{float(r['flops']):.3g}</td>"
                f"<td align=right>{float(r['bytes_accessed']):.3g}</td>"
                f"<td align=right>{float(r['device_ms']):.1f}</td>"
                f"<td align=right>{r.get('replicas')}</td>"
                f"<td>{_html.escape(', '.join(r.get('models') or []))}</td>"
                "</tr>"
            )
        parts.append("</table>")
        return "".join(parts)

    def _render_tenants_html(self, doc: Dict[str, Any]) -> str:
        """The human form of the router's merged ``/tenantz``."""
        return (
            "<html><head><title>tenantz (fleet)</title></head><body>"
            "<h1>Fleet per-tenant cost accounts</h1>"
            + self._tenants_table_html(doc)
            + "<p><a href='/tenantz?format=json'>json</a> · merged from the "
            "health poller's per-replica /tenantz snapshots</p>"
            "</body></html>"
        )

    # -- introspection / autoscaler signals ----------------------------
    def statusz(self) -> Dict[str, Any]:
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            replicas = [r.doc() for r in self._replicas.values()]
        return {
            "url": self.url,
            "replicas": replicas,
            "requests": _REQS_C.value,
            "failovers": _FAILOVERS_C.value,
            "shed": _SHED_C.value,
            "no_replica_503": _NO_REPLICA_C.value,
            "cb_ejections": _CB_OPEN_C.value,
            "cb_readmissions": _CB_CLOSE_C.value,
        }

    def stats(self, window_s: float = 30.0) -> Dict[str, Any]:
        """The autoscaler's signal snapshot: ready count, total
        in-flight, shed counter, and the latency p50/p99 over the
        sliding window."""
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("fleet.router.replicas", write=False)
            ready = [r for r in self._replicas.values() if r.ready]
            inflight = sum(r.inflight for r in ready)
            lat = [ms for (t, ms) in self._latencies if now - t <= window_s]
        lat.sort()
        n = len(lat)
        return {
            "replicas": len(self.replica_urls()),
            "ready": len(ready),
            "inflight": inflight,
            "inflight_per_ready": (inflight / len(ready)) if ready else 0.0,
            "shed": _SHED_C.value,
            "no_replica_503": _NO_REPLICA_C.value,
            "window_requests": n,
            "p50_ms": lat[n // 2] if n else 0.0,
            "p99_ms": lat[min(n - 1, int(n * 0.99))] if n else 0.0,
        }

    # -- shutdown -------------------------------------------------------
    def close(self) -> None:
        """Stop the poller and the front door.  Idempotent."""
        with self._lock:
            _tsan.note_access("fleet.router.replicas")
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        httpd = self._httpd
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t = self._serve_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        p = self._poll_thread
        if p is not None and p is not threading.current_thread():
            p.join(timeout=5)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class _ModelNotFound(Exception):
    """Every candidate replica answered 404 for the model: the honest
    client verdict is 404, not 503 (internal control flow only)."""

    def __init__(self, model: str):
        super().__init__(f"unknown model {model!r}")
        self.model = model


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m heat_tpu.fleet.router`` — a standalone router
    process."""
    import argparse

    ap = argparse.ArgumentParser(description="heat_tpu fleet router")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--replica", action="append", default=[],
                    help="replica base URL (repeatable)")
    args = ap.parse_args(argv)
    router = FleetRouter(replicas=tuple(args.replica), port=args.port, host=args.host)
    print(f"fleet router serving on {router.url}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
