"""Kernel roofline observatory tests (ISSUE 14 tentpole).

The contract under test (docs/observability.md):

* ``core/dispatch.py`` notes every cached-executable call into the
  execution ledger — monotonic timing on every call, every Nth call per
  key (``HEAT_TPU_PERF_SYNC_EVERY``) ``block_until_ready``-fenced so the
  sample measures device time;
* the ledger joins measured time with cost-accounting FLOPs/bytes into
  achieved GFLOP/s, GB/s, arithmetic intensity and a compute-vs-
  bandwidth bound verdict against device peaks (env knobs, an atomic+CRC
  calibration cache, or a one-shot matmul/copy micro-calibration);
* live HBM watermark gauges cross-check the measured bytes against the
  static estimator's predicted peak and the armed budget, firing the
  ``hbm:watermark`` alert end to end;
* ``/rooflinez`` serves the per-executable table, ``/profilez``
  starts/stops a bounded single-in-flight jax.profiler capture with
  downloadable artifacts, ``/metrics`` is OpenMetrics-clean
  (content-type + ``# EOF``);
* crash flight-recorder bundles and the ``HEAT_TPU_METRICS_DUMP``
  atexit JSON both carry the ``observatory`` section, rendered by the
  inspect CLI;
* the fleet router's health poller collects each replica's observatory
  snapshot and ``/fleetz`` renders the merged per-kernel table across
  real replica subprocesses, slowest replica per key highlighted.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving, telemetry
from heat_tpu.core import dispatch
from heat_tpu.telemetry import alerts as talerts
from heat_tpu.telemetry import inspect as tinspect
from heat_tpu.telemetry import observatory as obs
from heat_tpu.telemetry import server as tserver

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_observatory():
    prev_enabled = obs.set_enabled(True)
    prev_sync = obs.set_sync_every(4)
    prev_cost = dispatch.cost_accounting_enabled()
    obs.reset()
    obs.set_memory_stats_provider(None)
    yield
    obs.set_enabled(prev_enabled)
    obs.set_sync_every(prev_sync)
    dispatch.set_cost_accounting(prev_cost)
    obs.set_memory_stats_provider(None)
    obs.reset()
    talerts.clear_alerts()


@pytest.fixture
def live_server():
    srv = tserver.start_server(0)
    yield srv
    tserver.stop_server()


def _get(srv, route):
    with urllib.request.urlopen(f"{srv.url}{route}", timeout=10) as r:
        return r.status, r.read().decode("utf-8"), dict(r.headers)


def _post(srv, route):
    req = urllib.request.Request(f"{srv.url}{route}", method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def _dispatch_some(n=8, rows=128):
    """Drive n identical cached dispatches; returns the forced scalar.

    ``rows`` picks the dispatch key (shape enters the key; scalar values
    do not) — tests that must observe a FRESH compile (the cost join
    records on the miss) pass a shape no earlier test used."""
    x = ht.random.randn(rows, 4, split=0).astype(ht.float32)
    out = 0.0
    for _ in range(n):
        out = float((x * 2.0 + 1.0).sum())
    return out


# ----------------------------------------------------------------------
# the execution ledger
# ----------------------------------------------------------------------
class TestLedger:
    def test_records_calls_and_fenced_samples(self):
        obs.set_sync_every(2)
        _dispatch_some(n=9, rows=112)
        rows = obs.ledger_report()
        assert rows, "dispatches must land in the ledger"
        top = rows[0]
        assert top["calls"] >= 8
        assert top["mean_ms"] > 0
        # every 2nd call is block_until_ready-fenced
        assert top["sync_samples"] >= 3
        assert top["timing"] == "fenced"
        assert top["sync_min_ms"] is not None

    def test_sync_every_zero_never_fences(self):
        obs.set_sync_every(0)
        _dispatch_some(n=6, rows=96)
        rows = obs.ledger_report()
        assert rows and all(r["sync_samples"] == 0 for r in rows)
        assert rows[0]["timing"] == "enqueue"

    def test_disarmed_records_nothing(self):
        obs.set_enabled(False)
        _dispatch_some(n=4, rows=80)
        assert obs.ledger_report() == []

    def test_reset_all_clears_ledger(self):
        _dispatch_some(n=4, rows=72)
        assert obs.ledger_report()
        telemetry.reset_all("observatory")
        assert obs.ledger_report() == []

    def test_roofline_join_bandwidth_verdict(self):
        """An elementwise chain is bandwidth-bound against any sane
        peak pair (intensity well under the ridge)."""
        dispatch.set_cost_accounting(True)
        _dispatch_some(n=6, rows=144)
        peaks = {"flops": 1e12, "bytes_per_s": 1e10}  # ridge = 100 FLOP/B
        rows = [r for r in obs.ledger_report(peaks) if r["flops"]]
        assert rows, "cost accounting must join flops onto the ledger"
        top = rows[0]
        assert top["bound"] == "bandwidth"
        assert top["gbytes_per_s"] > 0
        assert top["intensity"] is not None and top["intensity"] < 100
        assert top["utilization"] is not None

    def test_roofline_join_compute_verdict(self):
        """A matmul's intensity sits far above a low ridge -> compute."""
        import jax.numpy as jnp

        dispatch.set_cost_accounting(True)
        a = np.ones((256, 256), np.float32)
        import jax

        buf = jax.device_put(a)
        for _ in range(5):
            dispatch.eager_apply(jnp.matmul, (buf, buf))
        peaks = {"flops": 1e12, "bytes_per_s": 1e11}  # ridge = 10 FLOP/B
        rows = [
            r for r in obs.ledger_report(peaks)
            if "matmul" in r["key"] and r["flops"]
        ]
        assert rows
        # 2*256^3 flops over ~3*256*256*4 bytes ≈ 43 FLOP/B > ridge 10
        assert rows[0]["bound"] == "compute"
        assert rows[0]["intensity"] > 10


# ----------------------------------------------------------------------
# device peaks: knobs -> cache -> calibration
# ----------------------------------------------------------------------
class TestPeaks:
    def test_env_knobs_win(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_PEAK_FLOPS", "2e12")
        monkeypatch.setenv("HEAT_TPU_PEAK_GBPS", "100")
        obs.reset_peaks()
        peaks = obs.device_peaks(calibrate=False)
        assert peaks["source"] == "env"
        assert peaks["flops"] == pytest.approx(2e12)
        assert peaks["bytes_per_s"] == pytest.approx(1e11)
        obs.reset_peaks()

    def test_no_cheap_source_returns_none_without_calibration(self, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_PEAK_FLOPS", raising=False)
        monkeypatch.delenv("HEAT_TPU_PEAK_GBPS", raising=False)
        monkeypatch.delenv("HEAT_TPU_PEAK_CACHE", raising=False)
        obs.reset_peaks()
        assert obs.device_peaks(calibrate=False) is None
        obs.reset_peaks()

    def test_calibration_persists_and_reloads(self, tmp_path, monkeypatch):
        cache = str(tmp_path / "peaks.json")
        monkeypatch.delenv("HEAT_TPU_PEAK_FLOPS", raising=False)
        monkeypatch.delenv("HEAT_TPU_PEAK_GBPS", raising=False)
        monkeypatch.setenv("HEAT_TPU_PEAK_CACHE", cache)
        obs.reset_peaks()
        peaks = obs.device_peaks(calibrate=True)
        assert peaks["source"] == "calibrated"
        assert peaks["flops"] > 0 and peaks["bytes_per_s"] > 0
        # atomic + CRC sidecar, like every other artifact
        assert os.path.exists(cache) and os.path.exists(cache + ".crc32")
        obs.reset_peaks()
        again = obs.device_peaks(calibrate=False)
        assert again["source"] == "cache"
        assert again["flops"] == pytest.approx(peaks["flops"])
        obs.reset_peaks()

    def test_corrupt_cache_recalibrates(self, tmp_path, monkeypatch):
        cache = str(tmp_path / "peaks.json")
        monkeypatch.setenv("HEAT_TPU_PEAK_CACHE", cache)
        with open(cache, "w") as f:
            f.write("{torn")
        obs.reset_peaks()
        peaks = obs.device_peaks(calibrate=True)
        assert peaks["source"] == "calibrated"  # never crashed on the torn file
        obs.reset_peaks()

    def test_fingerprint_mismatch_misses_cache(self, tmp_path, monkeypatch):
        cache = str(tmp_path / "peaks.json")
        monkeypatch.setenv("HEAT_TPU_PEAK_CACHE", cache)
        obs.reset_peaks()
        obs.device_peaks(calibrate=True)
        with open(cache) as f:
            doc = json.load(f)
        assert doc["fingerprint"] == obs._device_fingerprint()
        doc["fingerprint"] = "jax=9.9|backend=tpu|kind=v9|n=4096"
        from heat_tpu.resilience.atomic import atomic_write

        with atomic_write(cache) as tmp:
            with open(tmp, "w") as f:
                json.dump(doc, f)
        obs.reset_peaks()
        assert obs.device_peaks(calibrate=False) is None  # stale artifact refused
        obs.reset_peaks()


# ----------------------------------------------------------------------
# HBM watermarks + the measured-vs-predicted alert
# ----------------------------------------------------------------------
class TestWatermark:
    def test_probe_reports_some_source(self):
        doc = obs.watermark_tick(force=True)
        assert doc is not None
        assert doc["source"] in ("device", "host_rss")
        assert doc["bytes_in_use"] > 0

    def test_budget_alert_fires_and_resolves(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "1024")
        doc = obs.watermark_tick(force=True)
        assert doc["bytes_in_use"] > 1024
        budget_alerts = [
            a for a in talerts.active_alerts()
            if a["name"] == "hbm:watermark" and a["labels"]["cause"] == "budget"
        ]
        assert budget_alerts and budget_alerts[0]["severity"] == "page"
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "0")
        obs.watermark_tick(force=True)
        assert not any(a["name"] == "hbm:watermark" for a in talerts.active_alerts())

    def test_predicted_margin_alert(self, monkeypatch):
        from heat_tpu.analysis import memory_model as mm

        # budget armed (but not exceeded): the predicted cross-check
        # only runs on budget-armed processes — a process-wide in-use
        # number always dwarfs one program's predicted peak, so the
        # check would be pure noise unarmed
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "1000000")
        monkeypatch.setenv("HEAT_TPU_HBM_ALERT_MARGIN", "1.5")
        mm.reset_estimates()
        mm.note_estimate("prog", mm.PeakEstimate(per_device_bytes=1000, peak_bytes=1000))
        assert mm.predicted_peak_bytes() == 1000
        obs.set_memory_stats_provider(lambda: (2000.0, 2000.0, "test"))
        obs.watermark_tick(force=True)  # 2000 > 1000 * 1.5, under budget
        assert any(
            a["name"] == "hbm:watermark" and a["labels"]["cause"] == "predicted"
            for a in talerts.active_alerts()
        )
        obs.set_memory_stats_provider(lambda: (1200.0, 2000.0, "test"))
        obs.watermark_tick(force=True)  # 1200 < 1500: resolved
        assert not any(a["name"] == "hbm:watermark" for a in talerts.active_alerts())
        mm.reset_estimates()

    def test_undersized_budget_alert_end_to_end_on_live_service(
        self, live_server, tmp_path, monkeypatch
    ):
        """The acceptance scenario: a serving process with a deliberately
        undersized HEAT_TPU_HBM_BUDGET_BYTES raises the watermark alert
        through the fenced-dispatch tick and surfaces it on /statusz."""
        monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", "4096")
        obs.set_sync_every(1)  # every predict dispatch fences + cross-checks
        rng = np.random.default_rng(0)
        pts = rng.standard_normal((96, 5)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=4, random_state=0
        ).fit(ht.array(pts, split=0))
        d = str(tmp_path / "m")
        serving.save_model(km, d, version=1, name="km")
        svc = serving.InferenceService(max_delay_ms=1.0, max_batch=8)
        try:
            svc.load("km", d)
            for _ in range(4):
                svc.predict("km", pts[:4], timeout=30)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if talerts.is_firing("hbm:watermark", labels={"cause": "budget"}):
                    break
                obs.watermark_tick(force=True)
                time.sleep(0.05)
            active = {a["name"]: a for a in talerts.active_alerts()}
            assert "hbm:watermark" in active
            status, body, _ = _get(live_server, "/statusz")
            statusz = json.loads(body)
            assert any(
                a["name"] == "hbm:watermark" for a in statusz["alerts"]["active"]
            )
            # the serving process auto-armed the cost join, so the
            # acceptance table has GFLOP/s for the steady-state keys
            status, body, _ = _get(live_server, "/rooflinez?format=json")
            doc = json.loads(body)
            assert doc["ledger"], "a live serving process must show its keys"
            assert any(r["gflops_per_s"] is not None for r in doc["ledger"])
        finally:
            svc.close()


# ----------------------------------------------------------------------
# HTTP surfaces
# ----------------------------------------------------------------------
class TestRooflinezRoute:
    def test_html_and_json_forms(self, live_server):
        dispatch.set_cost_accounting(True)
        _dispatch_some(n=6, rows=176)
        status, body, headers = _get(live_server, "/rooflinez")
        assert status == 200
        assert headers["Content-Type"].startswith("text/html")
        assert "roofline observatory" in body and "<table" in body
        status, body, _ = _get(live_server, "/rooflinez?format=json")
        doc = json.loads(body)
        assert status == 200
        assert doc["ledger"] and doc["ledger"][0]["calls"] >= 1
        for field in ("calls", "mean_ms", "gflops_per_s", "gbytes_per_s", "bound"):
            assert field in doc["ledger"][0]
        assert doc["peaks"] is not None  # json form may calibrate

    def test_limit_param_bounds_the_payload(self, live_server):
        x = ht.random.randn(64, 3, split=0).astype(ht.float32)
        # three distinct keys: the op identity enters the key
        for op in (lambda a: a * 2.0, lambda a: a + 2.0, lambda a: a - 2.0):
            for _ in range(2):
                float(op(x).sum())
        status, body, _ = _get(live_server, "/rooflinez?format=json&limit=1")
        doc = json.loads(body)
        assert len(doc["ledger"]) == 1
        assert doc["ledger_total"] >= 2 and doc["truncated"] is True

    def test_metrics_exposition_hygiene(self, live_server):
        """PR 14 satellite: /metrics must declare OpenMetrics (the
        payload carries exemplar syntax) and terminate with # EOF."""
        _dispatch_some(n=2)
        status, body, headers = _get(live_server, "/metrics")
        assert status == 200
        assert headers["Content-Type"] == (
            "application/openmetrics-text; version=1.0.0; charset=utf-8"
        )
        assert body.rstrip("\n").endswith("# EOF")
        # observatory gauges ride in the same payload
        assert "heat_tpu_observatory_ledger_size" in body

    def test_root_index_lists_new_routes(self, live_server):
        status, body, _ = _get(live_server, "/")
        assert "/rooflinez" in body and "/profilez" in body


class TestProfilez:
    def test_capture_roundtrip_single_inflight_and_download(
        self, live_server, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("HEAT_TPU_PROFILE_DIR", str(tmp_path / "prof"))
        status, body = _post(live_server, "/profilez/start?duration_s=10")
        start_doc = json.loads(body)
        assert status == 200 and start_doc["dir"]
        # single in-flight: a second start is a 409 conflict
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(live_server, "/profilez/start")
        assert ei.value.code == 409
        _dispatch_some(n=3, rows=192)
        status, body = _post(live_server, "/profilez/stop")
        stop_doc = json.loads(body)
        assert status == 200
        assert stop_doc["artifacts"], "a capture must leave artifacts"
        # stopping again: nothing in flight -> 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(live_server, "/profilez/stop")
        assert ei.value.code == 409
        status, body, _ = _get(live_server, "/profilez?format=json")
        st = json.loads(body)
        assert st["active"] is False and len(st["captures"]) >= 1
        name = urllib.parse.quote(stop_doc["artifacts"][0]["name"])
        with urllib.request.urlopen(
            f"{live_server.url}/profilez/artifact?name={name}", timeout=10
        ) as r:
            assert r.status == 200 and len(r.read()) > 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{live_server.url}/profilez/artifact?name=../../../etc/passwd",
                timeout=10,
            )
        assert ei.value.code == 404  # traversal refused

    def test_duration_capped_and_auto_stopped(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_PROFILE_DIR", str(tmp_path / "prof"))
        monkeypatch.setenv("HEAT_TPU_PROFILE_MAX_S", "0.3")
        doc = obs.start_capture(duration_s=9999)
        assert doc["duration_s"] == pytest.approx(0.3)
        # wait for the deadline record itself: stop_capture clears the
        # in-flight flag BEFORE it appends the capture record (the
        # profiler stop runs between the two lock sections), so polling
        # `active` alone can observe the gap
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = obs.capture_status()
            if not st["active"] and st["captures"] and (
                st["captures"][-1]["reason"] == "deadline"
            ):
                break
            time.sleep(0.05)
        st = obs.capture_status()
        assert st["active"] is False
        assert st["captures"][-1]["reason"] == "deadline"


# ----------------------------------------------------------------------
# crash bundles + the atexit metrics dump (PR 14 satellite)
# ----------------------------------------------------------------------
class TestCrashSurfaces:
    def test_bundle_and_metrics_dump_carry_observatory(self, tmp_path):
        """A crashed subprocess leaves BOTH a flight-recorder bundle and
        the HEAT_TPU_METRICS_DUMP atexit JSON carrying the observatory
        section (ledger + watermark + calibration provenance), and the
        inspect CLI renders it."""
        bundles = tmp_path / "bundles"
        dump = tmp_path / "metrics.json"
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import heat_tpu as ht\n"
            "from heat_tpu.core import dispatch\n"
            "from heat_tpu.telemetry import observatory as obs\n"
            "dispatch.set_cost_accounting(True)\n"
            "obs.set_sync_every(2)\n"
            "obs.set_peaks(1e12, 1e10, source='spec-sheet')\n"
            "x = ht.random.randn(64, 4, split=0).astype(ht.float32)\n"
            "for _ in range(6):\n"
            "    float((x * 2.0 + 1.0).sum())\n"
            "obs.watermark_tick(force=True)\n"
            "from heat_tpu.resilience.errors import PermanentFault\n"
            "raise PermanentFault('boom')\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["HEAT_TPU_FLIGHT_RECORDER"] = str(bundles)
        env["HEAT_TPU_METRICS_DUMP"] = str(dump)
        proc = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True,
            cwd=REPO_ROOT, timeout=300,
        )
        assert proc.returncode != 0
        assert b"PermanentFault" in proc.stderr

        paths = sorted(bundles.glob("flight_*.json"))
        assert len(paths) == 1
        doc = tinspect.load_bundle(str(paths[0]))
        section = doc["observatory"]
        assert section is not None
        assert section["ledger"], "the crash bundle must carry the ledger"
        assert section["ledger"][0]["calls"] >= 5
        assert section["ledger"][0]["bound"] in ("bandwidth", "compute")
        assert section["watermark"]["source"] in ("device", "host_rss")
        assert section["peaks"]["source"] == "spec-sheet"

        # the atexit metrics dump carries the same section (CRC-verified)
        from heat_tpu.resilience.atomic import verify_checksum

        verify_checksum(str(dump))
        with open(dump) as f:
            dumped = json.load(f)
        assert dumped["observatory"]["ledger"]
        assert dumped["observatory"]["peaks"]["source"] == "spec-sheet"

        res = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.inspect", str(paths[0])],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, cwd=REPO_ROOT, timeout=300,
        )
        assert res.returncode == 0, res.stderr.decode()[-2000:]
        out = res.stdout.decode()
        assert "observatory" in out
        assert "spec-sheet" in out and "watermark" in out


# ----------------------------------------------------------------------
# fleet rollup: /fleetz across real replica subprocesses
# ----------------------------------------------------------------------
class TestFleetz:
    @pytest.mark.slow
    def test_fleetz_merges_two_real_replicas(self, tmp_path):
        """The acceptance scenario: >= 2 real replica subprocesses, the
        router's poller collects each one's observatory snapshot, and
        /fleetz shows the merged per-kernel table with the slowest
        replica named."""
        from heat_tpu.fleet import FleetRouter, LocalReplicaSet

        rng = np.random.default_rng(5)
        pts = rng.standard_normal((128, 6)).astype(np.float32)
        km = ht.cluster.KMeans(
            n_clusters=3, init="random", max_iter=5, random_state=0
        ).fit(ht.array(pts, split=0))
        mdir = str(tmp_path / "km")
        serving.save_model(km, mdir, version=1, name="km")
        rs = LocalReplicaSet(
            {"km": mdir}, str(tmp_path / "fleet"),
            max_batch=8, max_delay_ms=1.0,
            env=dict(os.environ, HEAT_TPU_PERF_SYNC_EVERY="2"),
        )
        router = FleetRouter(health_period_s=30.0)  # poll explicitly
        try:
            urls = [rs.spawn(), rs.spawn()]
            for url in urls:
                router.add_replica(url)
            # drive steady-state traffic at each replica directly so both
            # ledgers fill with the same (model, bucket) dispatch keys
            body = json.dumps({"model": "km", "inputs": pts[:4].tolist()}).encode()
            for url in urls:
                for _ in range(6):
                    req = urllib.request.Request(
                        url + "/v1/predict", data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=30) as r:
                        assert r.status == 200
            router.poll_health()
            doc = router.fleetz_report()
            assert set(doc["replicas"]) == {u.rstrip("/") for u in urls}
            for rep in doc["replicas"].values():
                assert rep["watermark"]["bytes_in_use"] > 0
            assert doc["kernels"], "steady-state keys must merge into /fleetz"
            merged = [
                e for e in doc["kernels"].values() if len(e["replicas"]) == 2
            ]
            assert merged, "the same dispatch key must appear on both replicas"
            entry = merged[0]
            assert entry["slowest"] in {u.rstrip("/") for u in urls}
            assert entry["straggler_score"] >= 0.0
            # serving replicas auto-arm the cost join -> utilization known
            assert any(
                row["gflops_per_s"] is not None or row["gbytes_per_s"] is not None
                for e in merged for row in e["replicas"].values()
            )
            status, html, ctype, _ = router.handle("GET", "/fleetz", None)
            assert status == 200 and ctype.startswith("text/html")
            assert "per-kernel utilization" in html
            assert "slowest" in html
            status, body2, _, _ = router.handle("GET", "/fleetz?format=json", None)
            assert json.loads(body2)["kernels"]
        finally:
            router.close()
            rs.close()


# ----------------------------------------------------------------------
# hygiene: every new knob is registered (H201-clean by construction)
# ----------------------------------------------------------------------
class TestKnobs:
    def test_new_knobs_registered(self):
        from heat_tpu.core import _env

        for name in (
            "HEAT_TPU_OBSERVATORY",
            "HEAT_TPU_PERF_SYNC_EVERY",
            "HEAT_TPU_PEAK_FLOPS",
            "HEAT_TPU_PEAK_GBPS",
            "HEAT_TPU_PEAK_CACHE",
            "HEAT_TPU_HBM_ALERT_MARGIN",
            "HEAT_TPU_PROFILE_DIR",
            "HEAT_TPU_PROFILE_MAX_S",
        ):
            assert name in _env.KNOBS, name

    def test_new_locks_registered(self):
        from heat_tpu.analysis.concurrency import LOCK_REGISTRY

        assert "telemetry.observatory" in LOCK_REGISTRY
        assert "telemetry.observatory.profiler" in LOCK_REGISTRY
