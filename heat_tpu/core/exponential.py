"""Exponential/logarithmic operations, analog of heat/core/exponential.py."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op

__all__ = [
    "exp",
    "expm1",
    "exp2",
    "log",
    "log2",
    "log10",
    "log1p",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "square",
]


def exp(x, out=None):
    """e**x (exponential.py:15)."""
    return _local_op(jnp.exp, x, out)


def expm1(x, out=None):
    """e**x - 1 (exponential.py:51)."""
    return _local_op(jnp.expm1, x, out)


def exp2(x, out=None):
    """2**x (exponential.py:87)."""
    return _local_op(jnp.exp2, x, out)


def log(x, out=None):
    """Natural logarithm (exponential.py:123)."""
    return _local_op(jnp.log, x, out)


def log2(x, out=None):
    """Base-2 logarithm (exponential.py:161)."""
    return _local_op(jnp.log2, x, out)


def log10(x, out=None):
    """Base-10 logarithm (exponential.py:199)."""
    return _local_op(jnp.log10, x, out)


def log1p(x, out=None):
    """log(1 + x) (exponential.py:237)."""
    return _local_op(jnp.log1p, x, out)


def logaddexp(t1, t2):
    """log(exp(t1) + exp(t2)) (exponential.py:275)."""
    return _binary_op(jnp.logaddexp, t1, t2)


def logaddexp2(t1, t2):
    """log2(2**t1 + 2**t2) (exponential.py:297)."""
    return _binary_op(jnp.logaddexp2, t1, t2)


def sqrt(x, out=None):
    """Square root (exponential.py:318)."""
    return _local_op(jnp.sqrt, x, out)


def square(x, out=None):
    """x*x (exponential.py:282 analog)."""
    return _local_op(jnp.square, x, out, no_cast=True)


def pow_scalar_base(base, exponent):
    """base ** exponent for scalar base (helper for logspace)."""
    from . import arithmetics

    return arithmetics.pow(base, exponent)
