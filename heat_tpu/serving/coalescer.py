"""Request coalescer: many concurrent ``predict()`` calls, one dispatch.

The serving hot path must never pay per-request what the framework
amortizes per batch — Python dispatch, DNDarray wrapping, an XLA
launch.  Each served model gets one :class:`ModelBatcher`: callers
enqueue their rows and block on a per-request event; a dedicated
batcher thread drains the queue into one batch per **tick** (up to
``HEAT_TPU_SERVE_MAX_BATCH`` rows), pads the batch up to a **bucket**
shape (:func:`heat_tpu.core.dispatch.batch_bucket`: next power of
two), runs ONE estimator inference over the padded batch, and scatters
each caller's slice of the result back.

**Deadline-aware ticks (QoS scheduling, docs/serving.md).**  Every
request carries an absolute coalescing **deadline** — an explicit
per-request budget (``deadline_ms`` body field / ``X-Heat-Deadline-Ms``
header) or its QoS class's default (``HEAT_TPU_QOS_DEADLINE_*_MS``) —
and the batcher is earliest-deadline-first end to end:

* the **tick fires** at the earliest ``dispatch_by`` over the queue
  (``min(enqueued_at + max_delay_s, deadline)``), recomputed on every
  wakeup — so an SLO-critical arrival mid-wait *shortens* the window
  and wakes the tick early (``serving.qos.early_wakes``) instead of
  waiting out a best-effort head-of-line delay;
* the **batch is picked by EDF** (:func:`take_edf_batch`): requests
  sorted by (deadline, arrival, queue index) — FIFO among equal
  deadlines — greedily packed to ``max_batch`` rows, skipping
  requests that no longer fit and backfilling with later ones that do;
* the coalesced batch **inherits** its earliest member's deadline
  (:func:`effective_deadline`) — the slack/miss accounting
  (``serving.deadline_slack_ms`` / ``serving.deadline_misses``) judges
  the batch by its most urgent rider, not its average one.

The bucket padding is what keeps the executable-cache key set finite:
request traffic produces arbitrary batch sizes, but the dispatch layer
only ever sees ``log2(max_batch)+1`` distinct leading extents — after
one warmup pass per bucket, steady-state serving triggers **zero new
compiles** whatever the traffic mix (the ``bench_serving`` acceptance
gate).  Pad rows are real zero rows (not mask metadata), so the true
extent baked into cached programs is the bucket itself; pad outputs are
simply dropped by the scatter.

Lock discipline (sanitized by the TSAN lane): the queue is only touched
under the registered ``serving.coalescer`` lock via its Condition; the
inference itself — the blocking part — always runs *outside* the lock,
so enqueues never stall behind XLA.

**Request tracing** (:mod:`heat_tpu.telemetry.tracing`): ``submit()``
captures the caller's trace context into the request; the batch's
``serve.batch``/``serve.pad``/``serve.scatter`` (plus the service's
dispatch/execute) spans run under the *primary* (first traced) request's
context across the thread hop.  Per-request bookkeeping — the
``serve.coalesce_wait`` span for the time in queue, and mirroring the
batch records into co-batched traces — happens on each *woken caller*,
never on the batcher thread: the batcher is the throughput bottleneck
and pays only per-batch tracing work, while callers do their own
accounting in time they would have spent blocked anyway.  One slow
``/v1/predict`` therefore shows its whole pipeline in ``/tracez``
whichever batch slot it rode in.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..analysis import tsan as _tsan
from ..core import dispatch as _dispatch
from ..core._env import env_float
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing
from ..telemetry.spans import clear_notes as _clear_notes
from ..telemetry.spans import flush_notes as _flush_notes
from ..telemetry.spans import stage_note as _stage_note
from .admission import QOS_CLASSES

__all__ = ["ModelBatcher", "effective_deadline", "observe_stage", "take_edf_batch"]

_BATCHES_C = _tm.counter("serving.batches", "coalesced inference dispatches")
_BATCH_ROWS_H = _tm.histogram(
    "serving.batch_rows", "true rows per coalesced inference batch"
)
_PAD_ROWS_C = _tm.counter(
    "serving.pad_rows", "bucket-padding rows dispatched (wasted compute rows)"
)
_EARLY_WAKES_C = _tm.counter(
    "serving.qos.early_wakes",
    "coalescer ticks shortened by an arrival more urgent than the batch in formation",
)
_DEADLINE_SLACK_H = _tm.histogram(
    "serving.deadline_slack_ms",
    "batch effective-deadline slack at dispatch (negative = dispatched late)",
)
_DEADLINE_MISS_C = _tm.counter(
    "serving.deadline_misses", "requests answered after their coalescing deadline"
)

#: per-stage latency decomposition of one served request — the
#: histograms that replace eyeballing a single end-to-end number.
#: Exemplars (most recent trace_id per bucket) connect each bucket to a
#: retained trace in /tracez.
_STAGES = ("admission", "coalesce", "pad", "dispatch", "execute", "scatter")
_STAGE_H = {
    s: _tm.histogram(
        f"serving.stage.{s}_ms",
        f"per-request serving latency decomposition: the {s} stage",
    )
    for s in _STAGES
}


def observe_stage(stage: str, ms: float, trace_id: Optional[str] = None) -> None:
    """Observe one serving-stage duration, exemplared with the given (or
    the ambient) trace id when exemplars are enabled."""
    if trace_id is None:
        trace_id = _tracing.current_trace_id()
    # direct module-flag read: this runs up to 6x per request
    _STAGE_H[stage].observe(
        ms, exemplar=trace_id if (trace_id and _tracing._EXEMPLARS) else None
    )


class _Request:
    __slots__ = ("rows", "n", "event", "result", "error", "enqueued_at",
                 "enqueued_ns", "ctx", "taken_ns", "primary_trace_id",
                 "batch_records", "tenant", "cls", "deadline", "dispatch_by")

    def __init__(self, rows: np.ndarray, tenant: str = "default",
                 cls: str = "standard", deadline: Optional[float] = None):
        self.rows = rows
        self.n = int(rows.shape[0])
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.enqueued_ns = time.perf_counter_ns()  # span clock for coalesce_wait
        self.ctx = _tracing.current_context()  # caller -> batcher handoff
        # stamped by the batcher, consumed by the caller after wake-up:
        # the caller records its own coalesce_wait span and mirrors the
        # batch's raw note batch into its trace, so the batcher thread —
        # the throughput bottleneck — pays no per-request tracing work
        self.taken_ns: Optional[int] = None
        self.primary_trace_id: Optional[str] = None
        self.batch_records: Optional[tuple] = None
        # QoS fields: who is riding (cost metering joins on tenant) and
        # by when (absolute monotonic deadline; dispatch_by additionally
        # caps the wait at the coalescing window — see submit())
        self.tenant = tenant
        self.cls = cls
        self.deadline = self.enqueued_at + 3600.0 if deadline is None else deadline
        self.dispatch_by = self.deadline


def take_edf_batch(queue: List[_Request], max_batch: int) -> List[_Request]:
    """Pop the next batch by earliest-deadline-first (mutates ``queue``).

    Requests are considered in (deadline, arrival, queue index) order —
    FIFO among equal deadlines, so EDF degenerates to the old FIFO pick
    when every deadline is the class default and the classes match —
    and greedily packed until ``max_batch`` rows: a request that no
    longer fits is *skipped* (it keeps its place for the next tick)
    while later, smaller requests may still backfill the remaining
    capacity.  Pure queue surgery (no locking, no clocks) so the EDF
    grid tests can drive it directly."""
    order = sorted(
        range(len(queue)),
        key=lambda i: (queue[i].deadline, queue[i].enqueued_at, i),
    )
    taken = []
    rows = 0
    for i in order:
        if rows + queue[i].n <= max_batch:
            taken.append(i)
            rows += queue[i].n
    batch = [queue[i] for i in taken]
    drop = set(taken)
    queue[:] = [r for i, r in enumerate(queue) if i not in drop]
    return batch


def effective_deadline(batch: List[_Request]) -> float:
    """Deadline inheritance: the coalesced batch is due when its most
    urgent member is — the earliest deadline over the batch."""
    return min(r.deadline for r in batch)


class ModelBatcher:
    """One model's coalescing queue + batcher thread.

    ``infer_fn(batch_rows: np.ndarray) -> np.ndarray`` is the model
    inference over a padded batch (the service wires it to the
    registry's *active* version at every tick, so a promote/rollback
    applies from the next batch with zero downtime).
    """

    def __init__(
        self,
        name: str,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int,
        max_delay_s: float,
        on_batch: Optional[Callable[[np.ndarray], None]] = None,
        on_mirror: Optional[Callable[..., Any]] = None,
        on_account: Optional[Callable[[List[Tuple[str, str, int]], float], None]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.name = name
        self._infer_fn = infer_fn
        #: post-batch hook: called with the TRUE (un-padded) rows after
        #: every waiting caller has been woken — work here (the input
        #: drift sketches) is off every caller's latency path by
        #: construction, the data analogue of the deferred stage notes
        self._on_batch = on_batch
        #: shadow-mirror hook: called with ``(true_rows, true_outputs,
        #: primary_trace_id, infer_ms)`` after the callers are woken —
        #: the canary decision plane's tap into the scatter path, same
        #: off-the-latency-path placement as ``on_batch``
        self._on_mirror = on_mirror
        #: cost-metering hook: called with ``([(tenant, cls, rows), ...],
        #: infer_ms)`` after the callers are woken — the per-tenant
        #: accountant's tap (/tenantz), same off-the-latency-path contract
        self._on_account = on_account
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        #: class-default deadline budgets, read once (the knobs are
        #: process-stable; a per-submit env read would be 3 dict probes
        #: per request on the hot path)
        self._class_budget_s = {
            "latency": env_float("HEAT_TPU_QOS_DEADLINE_LATENCY_MS") / 1e3,
            "standard": env_float("HEAT_TPU_QOS_DEADLINE_STANDARD_MS") / 1e3,
            "batch": env_float("HEAT_TPU_QOS_DEADLINE_BATCH_MS") / 1e3,
        }
        self._queue: List[_Request] = []
        self._queued_rows = 0
        #: the tick the batcher thread is currently sleeping toward
        #: (None while executing); submit() compares arrivals against it
        #: to count early wakes — guarded by the coalescer lock
        self._wait_deadline: Optional[float] = None
        self._open = True
        self.last_batch_ts = 0.0
        self.last_batch_trace_id: Optional[str] = None
        self._lock = _tsan.register_lock("serving.coalescer")
        self._cond = threading.Condition(self._lock)
        self._thread = threading.Thread(
            target=self._run, name=f"heat-tpu-serve-{name}", daemon=True
        )
        self._thread.start()

    # -- caller side ----------------------------------------------------
    def submit(
        self,
        rows: np.ndarray,
        timeout: Optional[float] = None,
        tenant: str = "default",
        cls: str = "standard",
        deadline_s: Optional[float] = None,
    ) -> np.ndarray:
        """Enqueue ``rows`` and block until their predictions return.

        ``tenant``/``cls`` ride along for EDF ordering and cost
        metering; ``deadline_s`` is an explicit coalescing budget in
        seconds from now (default: the class's
        ``HEAT_TPU_QOS_DEADLINE_*_MS`` budget).  Raises the batch's
        inference error if its dispatch failed, ``TimeoutError`` past
        ``timeout``, ``RuntimeError`` after ``close()``."""
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError(f"rows must be 2-D (n, features), got shape {rows.shape}")
        if rows.shape[0] == 0:
            return rows[:0]
        if rows.shape[0] > self.max_batch:
            raise ValueError(
                f"request of {rows.shape[0]} rows exceeds the coalescer's "
                f"max batch {self.max_batch} (HEAT_TPU_SERVE_MAX_BATCH); "
                "split the request"
            )
        budget = deadline_s if deadline_s is not None else self._class_budget_s.get(
            cls, self._class_budget_s["standard"]
        )
        req = _Request(rows, tenant=tenant, cls=cls, deadline=None)
        req.deadline = req.enqueued_at + max(float(budget), 0.0)
        # the tick must fire by the earlier of the coalescing window and
        # the request's own deadline — a tight deadline shortens the
        # wait, it never extends it past max_delay_s
        req.dispatch_by = min(req.enqueued_at + self.max_delay_s, req.deadline)
        with self._cond:
            _tsan.note_access("serving.coalescer.queue")
            if not self._open:
                raise RuntimeError(f"batcher for model {self.name!r} is closed")
            self._queue.append(req)
            self._queued_rows += req.n
            if self._wait_deadline is not None and req.dispatch_by < self._wait_deadline:
                # the batcher is mid-wait toward a later tick: this
                # arrival's urgency moves the tick earlier (the wait
                # loop recomputes it on wake-up)
                _EARLY_WAKES_C.inc()
            self._cond.notify_all()
        if not req.event.wait(timeout):
            # the batcher may still complete it; the caller stops waiting
            raise TimeoutError(
                f"predict on model {self.name!r} timed out after {timeout}s"
            )
        if req.ctx is not None and req.taken_ns is not None:
            # trace bookkeeping runs HERE, on the woken caller (its trace
            # context is still ambient), never on the batcher thread: the
            # caller notes its queue wait (materialized when its request
            # root flushes) and — when it rode another request's batch —
            # mirrors the shared batch records into its own trace
            wait_ns = req.taken_ns - req.enqueued_ns
            _stage_note(
                "serve.coalesce_wait", req.enqueued_ns, wait_ns,
                model=self.name, rows=req.n,
            )
            observe_stage("coalesce", wait_ns / 1e6, req.ctx.trace_id)
            if req.batch_records is not None and req.ctx.trace_id != req.primary_trace_id:
                _tracing.link_batch([req.ctx.trace_id], req.batch_records)
        if req.error is not None:
            raise req.error
        return req.result

    def queued_rows(self) -> int:
        with self._lock:
            _tsan.note_access("serving.coalescer.queue", write=False)
            return self._queued_rows

    def lane_depths(self) -> dict:
        """Per-class queued rows and oldest-waiting-age (seconds) of this
        model's coalescing queue — the per-model healthz's "is latency
        stuck behind batch" diagnostic."""
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("serving.coalescer.queue", write=False)
            out = {
                cls: {"queued_rows": 0, "oldest_wait_s": 0.0} for cls in QOS_CLASSES
            }
            for r in self._queue:
                d = out.setdefault(r.cls, {"queued_rows": 0, "oldest_wait_s": 0.0})
                d["queued_rows"] += r.n
                d["oldest_wait_s"] = round(
                    max(d["oldest_wait_s"], now - r.enqueued_at), 4
                )
            return out

    def alive(self) -> bool:
        """Whether the batcher thread is serving (per-model /healthz)."""
        return self._thread.is_alive() and self._open

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, drain queued requests, join the batcher
        thread.  Idempotent and safe to call concurrently."""
        with self._cond:
            _tsan.note_access("serving.coalescer.queue")
            self._open = False
            self._cond.notify_all()
        t = self._thread
        if t is not threading.current_thread():
            t.join(timeout)

    # -- batcher thread -------------------------------------------------
    def _take_batch(self) -> List[_Request]:
        """Pop the next EDF batch (caller holds the lock)."""
        batch = take_edf_batch(self._queue, self.max_batch)
        self._queued_rows -= sum(r.n for r in batch)
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                _tsan.note_access("serving.coalescer.queue")
                while self._open and not self._queue:
                    self._cond.wait()
                if not self._open and not self._queue:
                    return
                # batching window: wait for more work until the most
                # urgent queued request's dispatch_by elapses or a full
                # batch is ready — recomputed on every wakeup, so an
                # SLO-critical arrival mid-wait (submit notifies) pulls
                # the tick earlier instead of waiting out a best-effort
                # head-of-line delay
                while self._open and self._queued_rows < self.max_batch:
                    deadline = min(r.dispatch_by for r in self._queue)
                    self._wait_deadline = deadline
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                self._wait_deadline = None
                batch = self._take_batch()
            if batch:
                self._execute(batch)  # outside the lock: XLA must not block enqueues

    def _execute(self, batch: List[_Request]) -> None:
        taken_ns = time.perf_counter_ns()
        for r in batch:
            r.taken_ns = taken_ns  # callers derive their queue wait
        # deadline inheritance: the batch is judged by its most urgent
        # member; slack is measured at dispatch (the part the scheduler
        # controls — inference time is the model's)
        _DEADLINE_SLACK_H.observe(
            (effective_deadline(batch) - time.monotonic()) * 1e3
        )
        try:
            _inject("serve.batch", model=self.name)
            n = sum(r.n for r in batch)
            bucket = _dispatch.batch_bucket(n, self.max_batch)
            n_traced = sum(1 for r in batch if r.ctx is not None)
            primary = next((r.ctx for r in batch if r.ctx is not None), None)
            ptid = primary.trace_id if primary is not None else None
            # batch-level stages (pad/dispatch/execute/scatter and the
            # batch envelope) are NOTED under the primary request's
            # context and materialized in one flush; the woken callers
            # mirror the records into their co-batched traces (see
            # submit()), so each retained trace is complete while the
            # batcher thread pays only one buffered append per stage
            with _tracing.use_context(primary):
                tb0 = time.perf_counter_ns()
                rows = np.concatenate([r.rows for r in batch], axis=0)
                if bucket > n:
                    pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
                    rows = np.concatenate([rows, pad], axis=0)
                t1 = time.perf_counter_ns()
                _stage_note("serve.pad", tb0, t1 - tb0, rows=n, bucket=bucket)
                observe_stage("pad", (t1 - tb0) / 1e6, ptid)
                ti0 = time.perf_counter_ns()
                out = np.asarray(self._infer_fn(rows))
                infer_ms = (time.perf_counter_ns() - ti0) / 1e6
                t0 = time.perf_counter_ns()
                off = 0
                for r in batch:
                    r.result = out[off : off + r.n]
                    off += r.n
                t1 = time.perf_counter_ns()
                _stage_note("serve.scatter", t0, t1 - t0, requests=len(batch))
                observe_stage("scatter", (t1 - t0) / 1e6, ptid)
                _stage_note(
                    "serve.batch", tb0, t1 - tb0,
                    model=self.name, rows=n, bucket=bucket, traces=n_traced,
                )
                records = _flush_notes()
            _BATCHES_C.inc()
            _BATCH_ROWS_H.observe(n)
            _PAD_ROWS_C.inc(bucket - n)
            self.last_batch_ts = time.time()
            self.last_batch_trace_id = ptid
            done_at = time.monotonic()
            # wake the callers only after every shared field is in place
            for r in batch:
                r.primary_trace_id = ptid
                r.batch_records = records
                if done_at > r.deadline:
                    _DEADLINE_MISS_C.inc()
                r.event.set()
            if self._on_batch is not None:
                # callers are already awake: the hook's cost lands on
                # the batcher thread between ticks, never on a request
                try:
                    self._on_batch(rows[:n])
                except Exception:  # lint: allow H501(a sketch bug must never fail served requests)
                    pass
            if self._on_mirror is not None:
                # shadow mirroring: the hook only samples + enqueues (a
                # bounded queue another thread drains) — same contract
                try:
                    self._on_mirror(rows[:n], out[:n], ptid, infer_ms)
                except Exception:  # lint: allow H501(a canary bug must never fail served requests)
                    pass
            if self._on_account is not None:
                # per-tenant cost settlement: pure dict arithmetic on
                # the batcher thread between ticks, off every caller's
                # latency path like the other hooks
                try:
                    self._on_account([(r.tenant, r.cls, r.n) for r in batch], infer_ms)
                except Exception:  # lint: allow H501(a metering bug must never fail served requests)
                    pass
        except BaseException as e:  # lint: allow H501(per-request error delivery; the batcher thread must survive)
            _clear_notes()  # a failed batch must not leak notes into the next
            for r in batch:
                if not r.event.is_set():
                    r.error = e
                    r.event.set()
