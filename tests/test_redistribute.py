"""Arbitrary-target redistribution (VERDICT r3 missing #2 / next #5):
port of the reference's redistribute tests
(heat/core/tests/test_dndarray.py:873-935) plus the TPU-native layer's
guarantees — the ragged layout is physically placed (one gather whose
plan follows the target cumsum), the metadata APIs report it, and ragged
``__partitioned__`` sources ingest and round-trip.
"""

import numpy as np
import pytest

import heat_tpu as ht


def test_redistribute_1d():
    st = ht.zeros((50,), split=0)
    size = st.comm.size
    assert size >= 3
    target = np.zeros((size, 1), np.int64)
    target[1] = 30
    target[2] = 20
    st.redistribute_(target_map=target)
    lmap = st.lshape_map
    assert lmap[1, 0] == 30 and lmap[2, 0] == 20
    assert all(lmap[r, 0] == 0 for r in range(size) if r not in (1, 2))
    counts, displs = st.counts_displs()
    assert counts == (0, 30, 20) + (0,) * (size - 3)
    assert displs[1] == 0 and displs[2] == 30
    assert not st.is_balanced()
    # values unharmed
    np.testing.assert_array_equal(st.numpy(), np.zeros(50))


def test_redistribute_2d_split1_values_move():
    data = np.arange(50 * 50, dtype=np.float32).reshape(50, 50)
    st = ht.array(data, split=1)
    size = st.comm.size
    target = np.zeros((size, 2), np.int64)
    target[0, 1] = 13
    target[2, 1] = 50 - 13
    st.redistribute_(target_map=target)
    lmap = st.lshape_map
    assert tuple(lmap[0]) == (50, 13)
    assert tuple(lmap[2]) == (50, 37)
    assert tuple(lmap[1]) == (50, 0)
    # the physical ragged buffer holds each device's target columns
    layout = st._ragged_layout
    assert layout is not None
    tm, buf = layout
    assert buf.shape[1] == size * 37  # slots padded to the largest chunk
    got0 = np.asarray(buf[:, :13])  # device 0's slots: first 13 columns
    np.testing.assert_array_equal(got0, data[:, :13])
    got2 = np.asarray(buf[:, 2 * 37 : 2 * 37 + 37])
    np.testing.assert_array_equal(got2, data[:, 13:])
    # partition interface exports the ragged layout
    parts = st.__partitioned__
    key0 = (0, 0)
    assert parts["partitions"][key0]["shape"] == (50, 13)
    np.testing.assert_array_equal(
        parts["get"](parts["partitions"][key0]["data"]), data[:, :13]
    )
    key2 = (2, 0)
    assert parts["partitions"][key2]["start"] == (0, 13)
    np.testing.assert_array_equal(
        parts["get"](parts["partitions"][key2]["data"]), data[:, 13:]
    )


def test_redistribute_3d_and_split_none():
    st = ht.zeros((10, 11, 12), split=2)
    size = st.comm.size
    target = np.zeros((size, 3), np.int64)
    target[0, 2] = 12
    st.redistribute_(target_map=target)
    assert tuple(st.lshape_map[0]) == (10, 11, 12)
    assert st.lshape_map[1:, 2].sum() == 0
    # split=None: does nothing (reference behavior)
    sn = ht.zeros((8, 8, 8), split=None)
    sn.redistribute_(target_map=np.zeros((size, 3), np.int64))
    assert sn.lshape_map[0, 0] == 8


def test_redistribute_errors():
    st = ht.zeros((50, 81, 67), split=0)
    size = st.comm.size
    with pytest.raises(ValueError):  # counts do not sum to the extent
        st.redistribute_(target_map=np.zeros((size, 3), np.int64))
    with pytest.raises(TypeError):
        st.redistribute_(target_map="sdfibn")
    with pytest.raises(TypeError):
        st.redistribute_(lshape_map="sdfibn")
    with pytest.raises(ValueError):
        st.redistribute_(lshape_map=np.zeros(2, np.int64))
    with pytest.raises(ValueError):
        st.redistribute_(target_map=np.zeros((2, 4), np.int64))
    with pytest.raises(ValueError):  # negative counts
        bad = np.zeros((size, 3), np.int64)
        bad[0, 0], bad[1, 0] = -1, 51
        st.redistribute_(target_map=bad)


def test_balance_and_mutation_reset():
    data = np.arange(40, dtype=np.float32)
    st = ht.array(data, split=0)
    size = st.comm.size
    target = np.zeros((size, 1), np.int64)
    target[0] = 40
    st.redistribute_(target_map=target)
    assert not st.is_balanced()
    st.balance_()
    assert st.is_balanced()
    assert st._ragged_layout is None
    # canonical target is a no-op that clears ragged state
    st.redistribute_(target_map=target)
    st.redistribute_(target_map=st.comm.lshape_map((40,), 0))
    assert st.is_balanced()
    # mutating the array drops the stale ragged layout
    st.redistribute_(target_map=target)
    st.resplit_(None)
    assert st._ragged_layout is None


def test_mutation_invalidates_placed_buffer():
    data = np.arange(40, dtype=np.float32)
    a = ht.array(data, split=0)
    size = a.comm.size
    target = np.zeros((size, 1), np.int64)
    target[0], target[1] = 25, 15
    a.redistribute_(target_map=target)
    _, buf = a._ragged_layout  # materialize the placed buffer
    a[0] = 999.0
    _, buf2 = a._ragged_layout  # rebuilt after the write
    assert float(np.asarray(buf2)[0]) == 999.0
    # the layout itself survives the write (values moved, map did not)
    assert tuple(a.lshape_map[:2, 0]) == (25, 15)


def test_no_target_balances():
    data = np.arange(40, dtype=np.float32)
    a = ht.array(data, split=0)
    size = a.comm.size
    target = np.zeros((size, 1), np.int64)
    target[0] = 40
    a.redistribute_(target_map=target)
    assert not a.is_balanced()
    a.redistribute_()  # reference semantics: no target = balance
    assert a.is_balanced()


def test_ragged_partitioned_roundtrip():
    """from_partitioned of an unbalanced source round-trips (VERDICT #5)."""
    data = np.arange(30 * 4, dtype=np.float64).reshape(30, 4)
    src = ht.array(data, split=0)
    size = src.comm.size
    target = np.zeros((size, 2), np.int64)
    target[0, 0] = 3
    target[1, 0] = 17
    target[-1, 0] = 10
    src.redistribute_(target_map=target)
    rebuilt = ht.from_partitioned(src)
    np.testing.assert_array_equal(rebuilt.numpy(), data)
    assert rebuilt.split == 0
    # re-apply the ragged map on the rebuilt array: full round-trip
    rebuilt.redistribute_(target_map=target)
    np.testing.assert_array_equal(rebuilt.lshape_map, src.lshape_map)
    np.testing.assert_array_equal(rebuilt.numpy(), data)
