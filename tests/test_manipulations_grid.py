"""Deep per-function width for the manipulations family (VERDICT r4 #6
follow-through): the analog of heat/core/tests/test_manipulations.py's
per-op batteries (diag offsets, split-section grids, pad width formats,
reshape target grids, sort/unique/topk option matrices, exception
contracts), table-compressed, against numpy ground truth on the virtual
mesh.  Complements tests/test_manipulations_width.py (structural edges)
and tests/test_reference_sweeps.py (cross-family smoke) with the
reference's per-function case width.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


def _m(shape=(7, 6), dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    if np.issubdtype(dtype, np.floating):
        return rng.standard_normal(shape).astype(dtype)
    return rng.integers(0, 20, shape).astype(dtype)


# ---------------------------------------------------------------- diag(onal)

@pytest.mark.parametrize("split", SPLITS)
def test_diag_offset_grid(split):
    a = _m((6, 9))
    x = ht.array(a, split=split)
    for off in (-5, -2, -1, 0, 1, 3, 8):
        np.testing.assert_allclose(
            ht.diag(x, offset=off).numpy(), np.diag(a, k=off), err_msg=f"k={off}"
        )
    # vector -> matrix direction, offsets both ways
    v = _m((5,), seed=1)
    hv = ht.array(v, split=0 if split == 0 else None)
    for off in (-2, 0, 2):
        np.testing.assert_allclose(ht.diag(hv, offset=off).numpy(), np.diag(v, k=off))


@pytest.mark.parametrize("split", SPLITS)
def test_diagonal_dim_pairs(split):
    a = _m((4, 5, 6), seed=2)
    x = ht.array(a, split=split)
    for off in (-1, 0, 2):
        for d1, d2 in ((0, 1), (0, 2), (1, 2), (2, 0)):
            np.testing.assert_allclose(
                ht.diagonal(x, offset=off, dim1=d1, dim2=d2).numpy(),
                np.diagonal(a, offset=off, axis1=d1, axis2=d2),
                err_msg=f"off={off} dims=({d1},{d2})",
            )


def test_diag_exceptions():
    with pytest.raises((ValueError, TypeError)):
        ht.diag(ht.array(_m((2, 3, 4))))  # >2-D input
    with pytest.raises((ValueError, TypeError)):
        ht.diag(ht.array(5.0))  # 0-D input
    x = ht.array(_m((4, 4)))
    with pytest.raises((ValueError, TypeError)):
        ht.diagonal(x, dim1=0, dim2=0)  # identical dims


# ------------------------------------------------------------- split family

@pytest.mark.parametrize("split", SPLITS)
def test_split_sections_and_indices_grid(split):
    a = _m((8, 12), seed=3)
    x = ht.array(a, split=split)
    # equal sections along both axes
    for axis, sections in ((0, 2), (0, 4), (1, 3), (1, 6)):
        got = ht.split(x, sections, axis=axis)
        want = np.split(a, sections, axis=axis)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g.numpy(), w, err_msg=f"ax{axis} n{sections}")
    # index lists, including empty leading/trailing pieces
    for axis, idx in ((0, [3]), (0, [0, 3, 8]), (1, [2, 5, 11]), (1, [4, 4])):
        got = ht.split(x, idx, axis=axis)
        want = np.split(a, idx, axis=axis)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g.numpy(), w, err_msg=f"ax{axis} idx{idx}")


@pytest.mark.parametrize("split", SPLITS)
def test_hsplit_vsplit_dsplit(split):
    a3 = _m((4, 6, 8), seed=4)
    x3 = ht.array(a3, split=split)
    for fn, nfn, arg in (
        (ht.hsplit, np.hsplit, 3),
        (ht.hsplit, np.hsplit, [2, 4]),
        (ht.vsplit, np.vsplit, 2),
        (ht.vsplit, np.vsplit, [1, 3]),
        (ht.dsplit, np.dsplit, 4),
        (ht.dsplit, np.dsplit, [3, 7]),
    ):
        got = fn(x3, arg)
        want = nfn(a3, arg)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g.numpy(), w, err_msg=f"{nfn.__name__}({arg})")


def test_split_exceptions():
    x = ht.array(_m((6, 6)))
    with pytest.raises(ValueError):
        ht.split(x, 4, axis=0)  # 6 not divisible by 4
    with pytest.raises((ValueError, IndexError)):
        ht.split(x, 2, axis=5)
    with pytest.raises((ValueError, TypeError)):
        ht.vsplit(ht.array(np.arange(4.0)), 2)  # vsplit needs >= 2-D
    with pytest.raises((ValueError, TypeError)):
        ht.dsplit(ht.array(_m((4, 6))), 2)  # dsplit needs >= 3-D


# --------------------------------------------------------------------- pad

@pytest.mark.parametrize("split", SPLITS)
def test_pad_width_format_grid(split):
    a = _m((5, 7), seed=5)
    x = ht.array(a, split=split)
    cases = [
        (1, 1),                      # scalar-per-side shorthand, all axes
        ((2, 1), (0, 3)),            # full per-axis tuple
        ((0, 0), (2, 2)),            # one axis untouched
    ]
    for pw in cases:
        np.testing.assert_allclose(
            ht.pad(x, pw).numpy(), np.pad(a, pw), err_msg=f"pad_width={pw}"
        )
    # constant_values variants
    np.testing.assert_allclose(
        ht.pad(x, ((1, 1), (1, 1)), mode="constant", constant_values=7.5).numpy(),
        np.pad(a, ((1, 1), (1, 1)), constant_values=7.5),
    )


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("mode", ["edge", "wrap", "reflect", "symmetric"])
def test_pad_mode_grid(split, mode):
    a = _m((5, 7), seed=6)
    x = ht.array(a, split=split)
    pw = ((2, 1), (1, 2))
    np.testing.assert_allclose(
        ht.pad(x, pw, mode=mode).numpy(), np.pad(a, pw, mode=mode), err_msg=mode
    )


def test_pad_exceptions():
    x = ht.array(_m((4, 4)))
    with pytest.raises((ValueError, NotImplementedError)):
        ht.pad(x, ((1, 1), (1, 1)), mode="no-such-mode")
    with pytest.raises((ValueError, TypeError)):
        ht.pad(x, ((1, 1), (1, 1), (1, 1)))  # 3 axes of widths for a 2-D array


# ----------------------------------------------------------------- reshape

@pytest.mark.parametrize("split", SPLITS)
def test_reshape_target_grid(split):
    a = _m((6, 8), seed=7)
    x = ht.array(a, split=split)
    for shape in ((48,), (8, 6), (2, 24), (3, 2, 8), (4, 2, 2, 3), (-1, 12), (16, -1)):
        np.testing.assert_allclose(
            ht.reshape(x, shape).numpy(), a.reshape(shape), err_msg=f"-> {shape}"
        )
    # varargs form and new_split landing
    got = ht.reshape(x, 4, 12, new_split=1)
    assert got.split == 1 and got.shape == (4, 12)
    np.testing.assert_allclose(got.numpy(), a.reshape(4, 12))


def test_reshape_exceptions():
    x = ht.array(_m((6, 8)))
    with pytest.raises(ValueError):
        ht.reshape(x, (7, 7))
    with pytest.raises(ValueError):
        ht.reshape(x, (-1, -1))


# ------------------------------------------------------------- sort / topk

@pytest.mark.parametrize("split", SPLITS)
def test_sort_axis_descending_grid(split):
    a = _m((6, 9), seed=8)
    x = ht.array(a, split=split)
    for axis in (0, 1, -1):
        for desc in (False, True):
            vals, idx = ht.sort(x, axis=axis, descending=desc)
            want = np.sort(a, axis=axis)
            if desc:
                want = np.flip(want, axis=axis)
            np.testing.assert_allclose(
                vals.numpy(), want, err_msg=f"axis={axis} desc={desc}"
            )
            # the returned indices must reproduce the values
            np.testing.assert_allclose(
                np.take_along_axis(a, idx.numpy().astype(np.int64), axis=axis), want
            )


@pytest.mark.parametrize("split", [None, 0])
def test_topk_option_grid(split):
    a = _m((5, 11), seed=9)
    x = ht.array(a, split=split)
    for k in (1, 3, 11):
        for largest in (True, False):
            vals, idx = ht.topk(x, k, dim=1, largest=largest, sorted=True)
            want = np.sort(a, axis=1)
            want = want[:, ::-1][:, :k] if largest else want[:, :k]
            np.testing.assert_allclose(
                vals.numpy(), want, err_msg=f"k={k} largest={largest}"
            )
            np.testing.assert_allclose(
                np.take_along_axis(a, idx.numpy().astype(np.int64), axis=1),
                vals.numpy(),
            )
    with pytest.raises((ValueError, RuntimeError)):
        ht.topk(x, 12, dim=1)  # k exceeds the dim


# ------------------------------------------------------------------ unique

@pytest.mark.parametrize("split", [None, 0])
def test_unique_option_grid(split):
    a = np.array([4, 1, 3, 1, 4, 4, 2, 3], np.int32)
    x = ht.array(a, split=split)
    u = ht.unique(x, sorted=True)
    u = u[0] if isinstance(u, tuple) else u
    np.testing.assert_array_equal(np.sort(u.numpy()), np.unique(a))
    vals, inv = ht.unique(x, sorted=True, return_inverse=True)
    np.testing.assert_array_equal(vals.numpy()[inv.numpy()], a)


@pytest.mark.parametrize("split", [None, 0])
def test_unique_axis_rows(split):
    a = np.array([[1, 2], [3, 4], [1, 2], [5, 6], [3, 4]], np.float32)
    x = ht.array(a, split=split)
    u = ht.unique(x, sorted=True, axis=0)
    u = u[0] if isinstance(u, tuple) else u
    got = u.numpy()
    want = np.unique(a, axis=0)
    np.testing.assert_allclose(got[np.lexsort(got.T[::-1])], want)


# -------------------------------------------------- stack / concat variants

@pytest.mark.parametrize("split", SPLITS)
def test_stack_variant_grid(split):
    a, b, c = _m((4, 5), seed=10), _m((4, 5), seed=11), _m((4, 5), seed=12)
    xs = [ht.array(v, split=split) for v in (a, b, c)]
    np.testing.assert_allclose(ht.column_stack(xs).numpy(), np.column_stack((a, b, c)))
    np.testing.assert_allclose(ht.row_stack(xs).numpy(), np.vstack((a, b, c)))
    np.testing.assert_allclose(ht.hstack(xs).numpy(), np.hstack((a, b, c)))
    np.testing.assert_allclose(ht.vstack(xs).numpy(), np.vstack((a, b, c)))
    for ax in (0, 1, 2, -1):
        np.testing.assert_allclose(
            ht.stack(xs, axis=ax).numpy(), np.stack((a, b, c), axis=ax), err_msg=f"ax={ax}"
        )


def test_column_stack_vectors_and_mixed():
    v1, v2 = np.arange(4.0, dtype=np.float32), np.arange(4.0, 8.0, dtype=np.float32)
    m = _m((4, 2), seed=13)
    got = ht.column_stack([ht.array(v1), ht.array(m), ht.array(v2)])
    np.testing.assert_allclose(got.numpy(), np.column_stack((v1, m, v2)))


def test_stack_exceptions():
    with pytest.raises(ValueError):
        ht.stack([ht.array(_m((3, 4))), ht.array(_m((4, 3)))])
    with pytest.raises((ValueError, IndexError)):
        ht.stack([ht.array(_m((3, 4)))] * 2, axis=4)


# ----------------------------------------------------- repeat / tile widths

@pytest.mark.parametrize("split", SPLITS)
def test_repeat_forms(split):
    a = _m((4, 5), seed=14)
    x = ht.array(a, split=split)
    np.testing.assert_allclose(ht.repeat(x, 3).numpy(), np.repeat(a, 3))
    for axis in (0, 1):
        np.testing.assert_allclose(
            ht.repeat(x, 2, axis=axis).numpy(), np.repeat(a, 2, axis=axis)
        )
    # per-element repeats along an axis
    reps = [1, 3, 2, 1]
    np.testing.assert_allclose(
        ht.repeat(x, reps, axis=0).numpy(), np.repeat(a, reps, axis=0)
    )


@pytest.mark.parametrize("split", SPLITS)
def test_tile_reps_grid(split):
    a = _m((3, 4), seed=15)
    x = ht.array(a, split=split)
    for reps in (2, (2,), (2, 3), (2, 1, 2)):
        np.testing.assert_allclose(
            ht.tile(x, reps).numpy(), np.tile(a, reps), err_msg=f"reps={reps}"
        )


# ------------------------------------------------------------ flip / roll

@pytest.mark.parametrize("split", SPLITS)
def test_flip_axis_grid(split):
    a = _m((4, 5, 6), seed=16)
    x = ht.array(a, split=split)
    for ax in (None, 0, 1, 2, (0, 1), (1, 2), (0, 1, 2)):
        np.testing.assert_allclose(
            ht.flip(x, ax).numpy(), np.flip(a, ax), err_msg=f"axis={ax}"
        )
    np.testing.assert_allclose(ht.fliplr(x).numpy(), np.fliplr(a))
    np.testing.assert_allclose(ht.flipud(x).numpy(), np.flipud(a))


@pytest.mark.parametrize("split", SPLITS)
def test_roll_shift_grid(split):
    a = _m((6, 7), seed=17)
    x = ht.array(a, split=split)
    for shift, axis in (
        (0, 0), (3, 0), (-2, 1), (9, 0), (-13, 1),
        ((1, 2), (0, 1)), ((2, -3), (1, 0)),
    ):
        np.testing.assert_allclose(
            ht.roll(x, shift, axis).numpy(), np.roll(a, shift, axis),
            err_msg=f"shift={shift} axis={axis}",
        )
    # flattened roll (axis=None)
    np.testing.assert_allclose(ht.roll(x, 5).numpy(), np.roll(a, 5))


# -------------------------------------------------------- shape bookkeeping

@pytest.mark.parametrize("split", SPLITS)
def test_squeeze_expand_grid(split):
    a = _m((1, 5, 1, 4), seed=18)
    x = ht.array(a, split=split)
    np.testing.assert_allclose(ht.squeeze(x).numpy(), np.squeeze(a))
    for ax in (0, 2):
        np.testing.assert_allclose(ht.squeeze(x, axis=ax).numpy(), np.squeeze(a, ax))
    b = _m((5, 4), seed=19)
    y = ht.array(b, split=split)
    for ax in (0, 1, 2, -1):
        np.testing.assert_allclose(
            ht.expand_dims(y, ax).numpy(), np.expand_dims(b, ax), err_msg=f"ax={ax}"
        )
    with pytest.raises(ValueError):
        ht.squeeze(x, axis=1)  # non-unit axis


@pytest.mark.parametrize("split", SPLITS)
def test_broadcast_to_shapes(split):
    a = _m((1, 6), seed=20)
    x = ht.array(a, split=split)
    for shape in ((4, 6), (2, 3, 1, 6)):
        np.testing.assert_allclose(
            ht.broadcast_to(x, shape).numpy(), np.broadcast_to(a, shape)
        )
    with pytest.raises(ValueError):
        ht.broadcast_to(x, (6, 5))


def test_broadcast_arrays_triple():
    a, b, c = _m((1, 5)), _m((4, 1), seed=21), _m((5,), seed=22)
    got = ht.broadcast_arrays(ht.array(a), ht.array(b), ht.array(c))
    want = np.broadcast_arrays(a, b, c)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g.numpy(), w)


# -------------------------------------------------------------- resplit

@pytest.mark.parametrize("src", SPLITS)
@pytest.mark.parametrize("dst", SPLITS)
def test_resplit_matrix(src, dst):
    a = _m((9, 10), seed=23)  # both extents non-divisible by 8
    x = ht.array(a, split=src)
    y = ht.resplit(x, dst)
    assert y.split == dst
    np.testing.assert_allclose(y.numpy(), a, err_msg=f"{src}->{dst}")
