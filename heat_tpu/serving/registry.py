"""Model registry: named, versioned estimators hot-loaded from checkpoints.

The serving analogue of the elastic layer's resume path: a registry
maps ``name -> {version -> fitted estimator}`` with one **active**
version per name, loaded from a :class:`~heat_tpu.utils.checkpoint.
Checkpointer` directory written by :func:`~heat_tpu.serving.model_io.
save_model`.  Three properties the online path needs:

* **hot load** — :meth:`ModelRegistry.load` decodes and rebuilds the
  estimator *outside* the registry lock, then installs it with one
  locked pointer swap: requests in flight keep reading the old active
  version and never observe a half-loaded model.
  :meth:`~ModelRegistry.load_async` is the PR 3 background-writer
  pattern **inverted**: the restore (checksum verify, decode, device
  upload) runs on a bounded background *loader* thread (at most one in
  flight, back-pressure on overrun) and the atomic swap happens when
  the load completes; loader errors re-raise at the handle's
  ``wait()`` or the next ``load_async``/``close()``, never silently.
* **cross-world restore** — the registry's ``comm`` is handed to
  ``Checkpointer.restore(comm=...)``, so a model fitted at world size P
  re-splits onto the serving world Q (counted in
  ``checkpoint.crossworld_restores``); ``template=`` forwards for
  shape/dtype validation (:class:`~heat_tpu.resilience.errors.
  ReshapeError` on mismatch).
* **zero-downtime promote/rollback** — every version stays resident
  until unloaded; :meth:`~ModelRegistry.promote` swaps the active
  pointer under the lock and pushes the previous active onto a history
  stack :meth:`~ModelRegistry.rollback` pops.  A bad canary rolls back
  with one pointer swap, no filesystem IO.

Fault site ``serve.load`` is evaluated on every (sync or async)
load — a scripted fault plan can fail a hot-load to prove the active
version keeps serving.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis import tsan as _tsan
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing
from ..telemetry.spans import span as _span
from . import model_io as _mio

__all__ = ["ModelRegistry", "PendingLoad"]

_LOADS_C = _tm.counter("serving.loads", "model versions loaded into a registry")
_MODELS_G = _tm.gauge("serving.models", "model names resident in the registry")


class PendingLoad:
    """Handle for one in-flight :meth:`ModelRegistry.load_async`.

    ``wait()`` blocks until the load completes and re-raises the loader
    error if it failed; ``version``/``error`` are readable afterwards.
    """

    def __init__(self, name: str):
        self.name = name
        self.version: Optional[int] = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> int:
        """Block until the load finished; returns the loaded version or
        re-raises the loader's error."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"model load {self.name!r} still in flight")
        if self.error is not None:
            raise self.error
        return self.version

    def done(self) -> bool:
        return self._done.is_set()


class ModelRegistry:
    """Named, versioned, hot-swappable fitted estimators.

    Thread-safe: the version table is only touched under the registered
    ``serving.registry`` lock; estimator objects themselves are
    immutable after load (fitted state only), so serving threads read
    them lock-free once handed out.
    """

    def __init__(self, comm=None):
        self._comm = comm
        # name -> {"versions": {v: record}, "active": v|None, "history": [v]}
        self._models: Dict[str, Dict[str, Any]] = {}
        self._lock = _tsan.register_lock("serving.registry")
        # bounded background loader (<=1 in flight), inverted async-writer
        self._loader: Optional[threading.Thread] = None
        self._load_error: Optional[BaseException] = None

    @property
    def comm(self):
        if self._comm is None:
            from ..parallel import get_comm

            self._comm = get_comm()
        return self._comm

    # -- loading --------------------------------------------------------
    def load(
        self,
        name: str,
        directory: str,
        version: Optional[int] = None,
        template: Any = None,
        comm=None,
        activate: bool = True,
    ) -> int:
        """Hot-load one model version from a checkpoint directory.

        Decodes the latest (or the given) version through the
        cross-world restore path onto the registry's comm, rebuilds the
        estimator, and installs it with one atomic pointer swap.
        ``activate=False`` loads a canary version without promoting it
        (``promote`` later, or serve it explicitly by version).
        Returns the version loaded."""
        from ..utils.checkpoint import Checkpointer

        _inject("serve.load", model=name)
        comm = comm if comm is not None else self.comm
        ck = Checkpointer(directory)
        step = ck.latest_step() if version is None else int(version)
        if step is None:
            raise FileNotFoundError(f"no model versions in {directory}")
        with _span("serve.load", model=name, version=step):
            written_world = ck.world_size(step)
            doc = ck.restore(step, template=template, comm=comm)
            est = _mio.build_estimator(doc, comm=comm)
            meta = ck.metadata(step) or {}
        # precision-policy choke point: refuse a version whose recorded
        # compute dtype — or this process's effective one — violates the
        # policy it was exported under (PrecisionPolicyError).  Raises
        # BEFORE the install below, so a refused canary leaves the
        # registry (and the active version) untouched.
        from ..analysis import precision_policy as _pp

        _pp.check_load(
            doc.get("kind"), meta.get("policy"), meta.get("compute_dtype"),
            label=f"registry.load:{name}@v{step}",
        )
        baseline = None
        bj = doc.get("baseline_json")
        if bj:
            import json as _json

            try:
                baseline = _json.loads(str(bj))
            except ValueError:
                baseline = None  # torn baseline must not fail the load
        record = {
            "estimator": est,
            "kind": doc.get("kind"),
            "version": step,
            "directory": directory,
            "loaded_at": time.time(),
            "world_size_written": written_world,
            "world_size_serving": comm.size,
            "baseline": baseline,
            "policy": meta.get("policy"),
            "meta": meta,
        }
        with self._lock:
            _tsan.note_access("serving.registry.models")
            entry = self._models.setdefault(
                name, {"versions": {}, "active": None, "history": [],
                       "canary": None}
            )
            entry["versions"][step] = record
            if activate or entry["active"] is None:
                if entry["active"] is not None and entry["active"] != step:
                    entry["history"].append(entry["active"])
                entry["active"] = step
                if entry.get("canary") == step:
                    entry["canary"] = None
            else:
                # loaded-but-not-activated IS the canary slot: the
                # decision plane (serving/canary.py) mirrors shadow
                # traffic to this version until a verdict lands
                entry["canary"] = step
            activated = entry["active"] == step
            _MODELS_G.set(len(self._models))
        if baseline is not None and activated:
            # drift-monitor attach OUTSIDE the registry lock (the sketch
            # registry has its own registered lock; no nesting)
            from ..telemetry import sketch as _sketch

            _sketch.SKETCHES.set_baseline(name, baseline)
        _LOADS_C.inc()
        return step

    def load_async(
        self,
        name: str,
        directory: str,
        version: Optional[int] = None,
        template: Any = None,
        comm=None,
        activate: bool = True,
    ) -> PendingLoad:
        """Hot-load on the bounded background loader thread.

        At most one load is in flight; a second ``load_async`` during a
        load back-pressures until the first completes (and re-raises its
        error, if any).  The currently active version keeps serving
        until the loaded one atomically swaps in.  Returns a
        :class:`PendingLoad` handle."""
        self.wait()  # back-pressure (<=1 in flight) + error surface
        handle = PendingLoad(name)
        ctx = _tracing.current_context()  # caller -> loader-thread handoff

        def _run():
            try:
                with _tracing.use_context(ctx):
                    handle.version = self.load(
                        name, directory, version=version, template=template,
                        comm=comm, activate=activate,
                    )
            except BaseException as e:  # lint: allow H501(loader error surfaced at handle.wait/next load/close)
                handle.error = e
                with self._lock:
                    _tsan.note_access("serving.registry.models")
                    self._load_error = e
            finally:
                handle._done.set()

        t = threading.Thread(
            target=_run, name=f"heat-tpu-model-load-{name}", daemon=True
        )
        self._loader = t
        t.start()
        return handle

    def wait(self) -> None:
        """Drain the background loader; re-raise its pending error."""
        t = self._loader
        if t is not None and t is not threading.current_thread():
            t.join()
            self._loader = None
        with self._lock:
            _tsan.note_access("serving.registry.models")
            err, self._load_error = self._load_error, None
        if err is not None:
            raise err

    def close(self) -> None:
        """Drain the loader (idempotent); re-raises a pending error."""
        self.wait()

    # -- version management ---------------------------------------------
    def _entry(self, name: str) -> Dict[str, Any]:
        try:
            return self._models[name]
        except KeyError:
            raise KeyError(
                f"unknown model {name!r}; loaded models: {sorted(self._models)}"
            ) from None

    def promote(self, name: str, version: int) -> None:
        """Make ``version`` the active one (atomic pointer swap); the
        previous active version goes onto the rollback history.  The
        promoted version's persisted input baseline (when it carries
        one) replaces the drift monitor's — each version is scored
        against ITS OWN training distribution."""
        with self._lock:
            _tsan.note_access("serving.registry.models")
            entry = self._entry(name)
            if version not in entry["versions"]:
                raise KeyError(
                    f"model {name!r} has no loaded version {version}; "
                    f"resident: {sorted(entry['versions'])}"
                )
            if entry["active"] is not None and entry["active"] != version:
                entry["history"].append(entry["active"])
            entry["active"] = version
            if entry.get("canary") == version:
                entry["canary"] = None  # the canary went live
            baseline = entry["versions"][version].get("baseline")
        self._attach_baseline(name, baseline)

    def rollback(self, name: str) -> int:
        """Re-activate the previously active version (atomic pointer
        swap); returns the version now active.  Re-attaches that
        version's persisted baseline like :meth:`promote`."""
        with self._lock:
            _tsan.note_access("serving.registry.models")
            entry = self._entry(name)
            prev = None
            while entry["history"]:
                cand = entry["history"].pop()
                if cand in entry["versions"]:
                    entry["active"] = prev = cand
                    break
            if prev is None:
                raise ValueError(f"model {name!r} has no version to roll back to")
            baseline = entry["versions"][prev].get("baseline")
        self._attach_baseline(name, baseline)
        return prev

    def _attach_baseline(self, name: str, baseline) -> None:
        """Swap the drift monitor's baseline for ``name`` (outside the
        registry lock — the sketch registry has its own)."""
        if baseline is None:
            return
        from ..telemetry import sketch as _sketch

        _sketch.SKETCHES.set_baseline(name, baseline)

    def unload(self, name: str, version: Optional[int] = None) -> None:
        """Drop one version (or the whole model when ``version`` is
        None).  Unloading the active version is refused — promote or
        roll back first, so serving never loses its target mid-flight."""
        with self._lock:
            _tsan.note_access("serving.registry.models")
            entry = self._entry(name)
            if version is None:
                del self._models[name]
            else:
                version = int(version)
                if version == entry["active"]:
                    raise ValueError(
                        f"version {version} of {name!r} is active; promote or "
                        "rollback before unloading it"
                    )
                entry["versions"].pop(version, None)
                entry["history"] = [v for v in entry["history"] if v != version]
                if entry.get("canary") == version:
                    entry["canary"] = None
            _MODELS_G.set(len(self._models))

    # -- reading --------------------------------------------------------
    def get(self, name: str, version: Optional[int] = None):
        """The (active, or the given) fitted estimator for ``name``."""
        return self.record(name, version)["estimator"]

    def record(self, name: str, version: Optional[int] = None) -> Dict[str, Any]:
        """The full version record (estimator + load metadata)."""
        with self._lock:
            _tsan.note_access("serving.registry.models", write=False)
            entry = self._entry(name)
            v = entry["active"] if version is None else int(version)
            if v is None or v not in entry["versions"]:
                raise KeyError(f"model {name!r} has no loaded version {v!r}")
            return entry["versions"][v]

    def active_version(self, name: str) -> Optional[int]:
        with self._lock:
            _tsan.note_access("serving.registry.models", write=False)
            return self._entry(name)["active"]

    def canary_version(self, name: str) -> Optional[int]:
        """The resident-but-not-active version under shadow evaluation
        (set by ``load(activate=False)``, cleared by ``promote`` /
        ``unload`` of that version); None when no canary is loaded."""
        with self._lock:
            _tsan.note_access("serving.registry.models", write=False)
            return self._entry(name).get("canary")

    def model_names(self) -> List[str]:
        with self._lock:
            _tsan.note_access("serving.registry.models", write=False)
            return sorted(self._models)

    def models(self) -> Dict[str, Any]:
        """Listing document (the ``/v1/models`` payload): per model, the
        active version, every resident version's kind/load time/world
        sizes, and the rollback history."""
        out: Dict[str, Any] = {}
        with self._lock:
            _tsan.note_access("serving.registry.models", write=False)
            for name, entry in self._models.items():
                out[name] = {
                    "active": entry["active"],
                    "canary": entry.get("canary"),
                    "history": list(entry["history"]),
                    "versions": {
                        str(v): {
                            k: rec[k]
                            for k in (
                                "kind",
                                "version",
                                "directory",
                                "loaded_at",
                                "world_size_written",
                                "world_size_serving",
                            )
                        }
                        for v, rec in entry["versions"].items()
                    },
                }
        return out
