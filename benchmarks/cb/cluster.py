"""Clustering continuous benchmarks (reference: benchmarks/cb/cluster.py)."""

# flake8: noqa
import heat_tpu as ht
from monitor import monitor


@monitor()
def kmeans(data):
    model = ht.cluster.KMeans(n_clusters=4, init="kmeans++")
    model.fit(data)


@monitor()
def kmedians(data):
    model = ht.cluster.KMedians(n_clusters=4, init="kmedians++")
    model.fit(data)


@monitor()
def kmedoids(data):
    model = ht.cluster.KMedoids(n_clusters=4, init="kmedoids++")
    model.fit(data)


@monitor()
def batchparallel_kmeans(data):
    model = ht.cluster.BatchParallelKMeans(n_clusters=4, init="k-means++")
    model.fit(data)


def run_cluster_benchmarks(scale: float = 1.0):
    n = max(int(5000 * scale), 256)
    data = ht.utils.data.spherical.create_spherical_dataset(
        num_samples_cluster=n, radius=1.0, offset=4.0, dtype=ht.float32, random_state=1
    )
    kmeans(data)
    kmedians(data)
    kmedoids(data)
    batchparallel_kmeans(data)
