"""Memory layout helpers, analog of heat/core/memory.py."""

from __future__ import annotations

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x: DNDarray) -> DNDarray:
    """Deep copy (memory.py:13).  jax arrays are immutable; wrapping the same
    buffer in a fresh DNDarray has copy semantics."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
    return DNDarray(x.larray_padded, x.gshape, x.dtype, x.split, x.device, x.comm)


def sanitize_memory_layout(x, order: str = "C"):
    """Memory order normalization (memory.py:43).  XLA owns physical layout;
    'F' order is accepted for API parity and ignored."""
    if order not in ("C", "F"):
        raise ValueError(f"invalid memory layout order, expected 'C' or 'F', got {order!r}")
    return x
