"""Sparse elementwise arithmetic, analog of heat/sparse/arithmetics.py
(add :17, mul :58 via ``__binary_op_csx``, sparse/_operations.py:17-209).

The reference applies local torch sparse ops per chunk and re-syncs nnz;
here the global BCOO op (union for add, intersection for mul) is one XLA
expression.
"""

from __future__ import annotations

from jax.experimental import sparse as jsparse

from ..core.dndarray import DNDarray
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix

__all__ = ["add", "mul", "sum", "matmul"]


def _binary_op_csx(op_name, t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Generic sparse-sparse elementwise op (sparse/_operations.py:17)."""
    if not isinstance(t1, DCSX_matrix) or not isinstance(t2, DCSX_matrix):
        raise TypeError(f"both operands must be sparse matrices, got {type(t1)}, {type(t2)}")
    if type(t1) is not type(t2):
        raise TypeError(f"operands must share the sparse format, got {type(t1).__name__} and {type(t2).__name__}")
    if t1.shape != t2.shape:
        raise ValueError(f"shapes must match, got {t1.shape} and {t2.shape}")
    a, b = t1.larray, t2.larray
    if op_name == "add":
        res = jsparse.bcoo_sum_duplicates(_bcoo_union_add(a, b))
    else:
        res = jsparse.bcoo_sum_duplicates(jsparse.bcoo_sort_indices(jsparse.bcoo_multiply_sparse(a, b)))
    from ..core import types

    dtype = types.canonical_heat_type(res.data.dtype)
    return type(t1)(res, int(res.nse), t1.shape, dtype, t1.split, t1.device, t1.comm)


def _bcoo_union_add(a, b):
    import jax.numpy as jnp

    data = jnp.concatenate([a.data, b.data])
    idx = jnp.concatenate([a.indices, b.indices], axis=0)
    return jsparse.bcoo_sort_indices(jsparse.BCOO((data, idx), shape=a.shape))


def add(t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Element-wise sparse addition (sparse/arithmetics.py:17)."""
    return _binary_op_csx("add", t1, t2)


def mul(t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Element-wise sparse multiplication (sparse/arithmetics.py:58)."""
    return _binary_op_csx("mul", t1, t2)


def sum(t: DCSX_matrix, axis=None) -> "DNDarray":
    """Sparse sum reduction to a dense DNDarray.

    Beyond the reference's sparse surface (its DCSX has no reductions);
    axis=None gives the 0-d total, axis 0/1 a dense vector.  BCOO's
    segment-sum reduction runs on-device; nothing is densified before the
    reduction."""
    import jax.numpy as jnp

    if not isinstance(t, DCSX_matrix):
        raise TypeError(f"expected a sparse matrix, got {type(t)}")
    mat = t.larray
    if axis is None:
        res = jsparse.bcoo_reduce_sum(mat, axes=(0, 1)).todense()
        return DNDarray.from_dense(jnp.asarray(res), None, t.device, t.comm)
    axis = axis if axis >= 0 else axis + 2
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0, 1 or None, got {axis}")
    res = jsparse.bcoo_reduce_sum(mat, axes=(axis,)).todense()
    split = 0 if t.split is not None else None
    return DNDarray.from_dense(res, split, t.device, t.comm)


def matmul(a, b):
    """Sparse matrix product: sparse@sparse -> sparse, sparse@dense and
    dense@sparse -> dense DNDarray.

    Beyond the reference's sparse surface; the products lower to XLA's
    sparse dot (``bcoo_dot_general``), which on TPU feeds the MXU with the
    gathered rows instead of densifying the operand."""
    import jax.numpy as jnp

    a_sp = isinstance(a, DCSX_matrix)
    b_sp = isinstance(b, DCSX_matrix)
    if not a_sp and not b_sp:
        raise TypeError("at least one operand must be a sparse matrix")
    ref = a if a_sp else b
    if a_sp and b_sp:
        res = jsparse.bcoo_sum_duplicates(
            jsparse.bcoo_sort_indices(a.larray @ b.larray)
        )
        from ..core import types

        dtype = types.canonical_heat_type(res.data.dtype)
        out_shape = (a.shape[0], b.shape[1])
        return type(a)(res, int(res.nse), out_shape, dtype, a.split, a.device, a.comm)
    if a_sp:
        dense = b._dense() if isinstance(b, DNDarray) else jnp.asarray(b)
        out = a.larray @ dense
        split = a.split if a.split == 0 else (b.split if isinstance(b, DNDarray) else None)
        return DNDarray.from_dense(out, split if split in (0, 1) else None, a.device, a.comm)
    dense = a._dense() if isinstance(a, DNDarray) else jnp.asarray(a)
    out = dense @ b.larray
    split = a.split if isinstance(a, DNDarray) and a.split == 0 else None
    return DNDarray.from_dense(out, split, b.device, b.comm)
