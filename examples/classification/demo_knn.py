"""Distributed k-nearest-neighbours demo (analog of examples/classification/demo_knn.py).

Loads the bundled iris dataset as a split-0 DNDarray (every rank reads its
own slab of the HDF5 file), then cross-validates a KNeighborsClassifier:
the distance matrix between test and train chunks is a sharded matmul and
the vote is a distributed top-k.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import numpy as np

import heat_tpu as ht
from heat_tpu.classification import KNeighborsClassifier


def fold_indices(n: int, fold: int, n_folds: int) -> tuple:
    """Boolean masks for one verification fold (reference demo's fold split)."""
    test = np.zeros(n, dtype=bool)
    test[fold::n_folds] = True
    return ~test, test


def main() -> None:
    X = ht.load_hdf5(ht.datasets.path("iris.h5"), dataset="data", split=0)
    # iris: 3 classes x 50 consecutive samples
    y = ht.array(np.repeat(np.arange(3), 50), split=0)

    n_folds = 5
    accuracies = []
    xd, yd = X.numpy(), y.numpy()
    for fold in range(n_folds):
        train, test = fold_indices(xd.shape[0], fold, n_folds)
        clf = KNeighborsClassifier(n_neighbors=5)
        clf.fit(ht.array(xd[train], split=0), ht.array(yd[train], split=0))
        pred = clf.predict(ht.array(xd[test], split=0)).numpy().ravel()
        acc = float((pred == yd[test]).mean())
        accuracies.append(acc)
        print(f"fold {fold}: accuracy {acc:.3f}")
    print(f"mean accuracy over {n_folds} folds: {np.mean(accuracies):.3f}")


if __name__ == "__main__":
    main()
