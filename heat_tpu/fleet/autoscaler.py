"""Load-driven elastic autoscaling: serving signals in, replica count out.

The elastic layer (PR 8) reacts to *loss* — a dead worker shrinks the
mesh.  This controller reacts to *load*: it watches the router's
serving signals (sliding-window p99, in-flight per ready replica, shed
and no-replica counters) and drives the replica count between
``HEAT_TPU_FLEET_MIN_REPLICAS`` and ``MAX_REPLICAS`` through the
:class:`~heat_tpu.fleet.replica.LocalReplicaSet` actuator — the
``ProcessSupervisor`` pattern repurposed from surviving failures to
matching capacity.

**Hysteresis**, because thrash is worse than lag: a tick is
*overloaded* when any up-signal breaches (p99 over
``HEAT_TPU_FLEET_P99_UP_MS``, in-flight per ready replica over
``INFLIGHT_UP``, any shed/no-replica delta, or zero ready replicas
below the floor) and *underloaded* only when every down-signal clears
(p99 under ``P99_DOWN_MS``, in-flight under ``INFLIGHT_DOWN``, zero
sheds).  Scale-up needs ``UP_TICKS`` consecutive overloaded ticks,
scale-down ``DOWN_TICKS`` consecutive underloaded ones; any mixed tick
resets both streaks.  One step per decision: spawn one replica (born
warm through the AOT cache + pre-warm manifest, so added capacity is
useful within seconds, not after a compile storm) or drain one (router
first — no new work — then SIGTERM, so scale-down sheds **zero**
requests).

:meth:`FleetAutoscaler.evaluate` is a pure function of the signal
snapshot — the tests drive it with synthetic signals; the tick thread
just feeds it real ones.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..analysis import tsan as _tsan
from ..analysis.protocols import ACTOR_AUTOSCALER
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry import tsdb as _tsdb

__all__ = ["FleetAutoscaler"]

_UPS_C = _tm.counter("fleet.scale_ups", "autoscaler scale-up actions")
_DOWNS_C = _tm.counter("fleet.scale_downs", "autoscaler scale-down actions")


def _env():
    from ..core import _env as envmod

    return envmod


class FleetAutoscaler:
    """Drive ``replica_set`` size from ``router`` signals.

    ``router`` needs ``stats()``, ``add_replica``, ``drain_replica``,
    ``remove_replica`` and ``replica_urls()``; ``replica_set`` needs
    ``spawn()``, ``drain_stop(url)`` and ``urls()`` — the
    :class:`~heat_tpu.fleet.router.FleetRouter` /
    :class:`~heat_tpu.fleet.replica.LocalReplicaSet` surfaces, which
    the tests stub."""

    def __init__(
        self,
        router,
        replica_set,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        tick_s: Optional[float] = None,
        up_ticks: Optional[int] = None,
        down_ticks: Optional[int] = None,
        p99_up_ms: Optional[float] = None,
        p99_down_ms: Optional[float] = None,
        inflight_up: Optional[float] = None,
        inflight_down: Optional[float] = None,
    ):
        env = _env()
        self.router = router
        self.replica_set = replica_set
        self.min_replicas = int(min_replicas) if min_replicas is not None else env.env_int("HEAT_TPU_FLEET_MIN_REPLICAS")
        self.max_replicas = int(max_replicas) if max_replicas is not None else env.env_int("HEAT_TPU_FLEET_MAX_REPLICAS")
        self.tick_s = float(tick_s) if tick_s is not None else env.env_float("HEAT_TPU_FLEET_TICK_S")
        self.up_ticks = int(up_ticks) if up_ticks is not None else env.env_int("HEAT_TPU_FLEET_UP_TICKS")
        self.down_ticks = int(down_ticks) if down_ticks is not None else env.env_int("HEAT_TPU_FLEET_DOWN_TICKS")
        self.p99_up_ms = float(p99_up_ms) if p99_up_ms is not None else env.env_float("HEAT_TPU_FLEET_P99_UP_MS")
        self.p99_down_ms = float(p99_down_ms) if p99_down_ms is not None else env.env_float("HEAT_TPU_FLEET_P99_DOWN_MS")
        self.inflight_up = float(inflight_up) if inflight_up is not None else env.env_float("HEAT_TPU_FLEET_INFLIGHT_UP")
        self.inflight_down = float(inflight_down) if inflight_down is not None else env.env_float("HEAT_TPU_FLEET_INFLIGHT_DOWN")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        self._over_streak = 0
        self._under_streak = 0
        self._last_shed = 0
        self._last_503 = 0
        self._last_decision: Dict[str, Any] = {}
        self._lock = _tsan.register_lock("fleet.autoscaler")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the decision (pure in, action out) -----------------------------
    def evaluate(self, sig: Dict[str, Any]) -> Optional[str]:
        """Fold one signal snapshot into the hysteresis state; returns
        the action this tick calls for: ``"up"``, ``"down"`` or None.
        Pure with respect to the router — tests feed synthetic
        snapshots."""
        with self._lock:
            _tsan.note_access("fleet.autoscaler.state")
            n = int(sig.get("replicas", 0))
            shed_delta = max(0, int(sig.get("shed", 0)) - self._last_shed)
            nr_delta = max(0, int(sig.get("no_replica_503", 0)) - self._last_503)
            self._last_shed = int(sig.get("shed", 0))
            self._last_503 = int(sig.get("no_replica_503", 0))
            p99 = float(sig.get("p99_ms", 0.0))
            per_ready = float(sig.get("inflight_per_ready", 0.0))
            have_traffic = int(sig.get("window_requests", 0)) > 0
            overloaded = (
                (have_traffic and p99 > self.p99_up_ms)
                or per_ready > self.inflight_up
                or shed_delta > 0
                or nr_delta > 0
                or int(sig.get("ready", 0)) < self.min_replicas
            )
            underloaded = (
                not overloaded
                and shed_delta == 0
                and nr_delta == 0
                and per_ready < self.inflight_down
                and (not have_traffic or p99 < self.p99_down_ms)
            )
            if overloaded:
                self._over_streak += 1
                self._under_streak = 0
            elif underloaded:
                self._under_streak += 1
                self._over_streak = 0
            else:
                self._over_streak = 0
                self._under_streak = 0
            action = None
            if self._over_streak >= self.up_ticks and n < self.max_replicas:
                action = "up"
                self._over_streak = 0
            elif self._under_streak >= self.down_ticks and n > self.min_replicas:
                action = "down"
                self._under_streak = 0
            self._last_decision = {
                "time": time.time(),
                "signal": dict(sig),
                "overloaded": overloaded,
                "underloaded": underloaded,
                "over_streak": self._over_streak,
                "under_streak": self._under_streak,
                "action": action,
            }
        # signal history OUTSIDE our lock: tsdb has its own registered
        # lock and the journal evidence resolves against these series
        _tsdb.record("fleet.p99_ms", p99)
        _tsdb.record("fleet.inflight_per_ready", per_ready)
        _tsdb.record("fleet.replicas", float(n))
        return action

    # -- the actuation --------------------------------------------------
    def scale_up(self) -> Optional[str]:
        """Spawn one replica and register it with the router; returns
        its URL (None when the spawn failed — the next tick retries)."""
        try:
            url = self.replica_set.spawn()
        except Exception:  # lint: allow H501(a failed spawn must not kill the tick thread; the next tick retries)
            return None
        self.router.add_replica(url)
        _UPS_C.inc()
        self._journal_scale("spawn", url)
        return url

    def scale_down(self) -> Optional[str]:
        """Drain one replica (newest first — oldest replicas keep their
        warm caches) out of the router, then stop it; returns its URL."""
        urls = self.replica_set.urls()
        if not urls:
            return None
        url = urls[-1]
        self.router.drain_replica(url)
        self.replica_set.drain_stop(url)
        self.router.remove_replica(url)
        _DOWNS_C.inc()
        self._journal_scale("drain", url)
        return url

    def _journal_scale(self, action: str, url: Optional[str]) -> None:
        """One decision-journal entry per actuation, carrying the exact
        signal snapshot that tripped the hysteresis plus the metric
        windows the evidence resolves against (``/queryz``)."""
        decision = self.state()
        sig = decision.get("signal", {})
        evidence: Dict[str, Any] = {
            "replica_url": url,
            "signal": sig,
            "over_streak_needed": self.up_ticks,
            "under_streak_needed": self.down_ticks,
            "series": ["fleet.p99_ms", "fleet.inflight_per_ready",
                       "fleet.replicas"],
        }
        for series in ("fleet.p99_ms", "fleet.inflight_per_ready"):
            stats = _tsdb.window_stats(series, window_s=60.0)
            if stats.get("n"):
                evidence[series] = {k: stats[k] for k in ("n", "min", "max", "mean", "last")}
        _journal.emit(
            ACTOR_AUTOSCALER, action,
            severity="info",
            message=(
                f"scale-{'up' if action == 'spawn' else 'down'}: "
                f"p99={sig.get('p99_ms', 0.0):g}ms "
                f"inflight/ready={sig.get('inflight_per_ready', 0.0):g} "
                f"replicas={sig.get('replicas', 0)}"
            ),
            evidence=evidence,
        )

    def tick(self) -> Optional[str]:
        """One evaluation + actuation cycle (the tick thread's body;
        tests call it directly)."""
        action = self.evaluate(self.router.stats())
        if action == "up":
            self.scale_up()
        elif action == "down":
            self.scale_down()
        return action

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        """Start the tick thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="heat-tpu-fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # lint: allow H501(a tick error must not kill the controller; the next tick retries)
                pass
            self._stop.wait(self.tick_s)

    def close(self) -> None:
        """Stop the tick thread (the replica set is the owner's to
        close).  Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._thread = None

    def state(self) -> Dict[str, Any]:
        """The last decision record (/fleet/statusz, tests)."""
        with self._lock:
            _tsan.note_access("fleet.autoscaler.state", write=False)
            return dict(self._last_decision)

    def __enter__(self) -> "FleetAutoscaler":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
