"""Planar pencil breadth (VERDICT r3 missing #3 / next #4): every
transform kind along a split axis rides the all_to_all pencil — explicit
``n``, Hermitian length changes, and non-divisible partner axes included —
and none of their programs contains an all-gather.

Reference parity: heat/fft/fft.py:66-137 (the pencil covers every kind).
"""

import os
import re as _re

import jax
import numpy as np
import pytest

import importlib

import heat_tpu as ht

fft_mod = importlib.import_module("heat_tpu.fft.fft")


@pytest.fixture(autouse=True)
def planar_mode():
    os.environ["HEAT_TPU_PLANAR"] = "1"
    try:
        yield
    finally:
        del os.environ["HEAT_TPU_PLANAR"]


P = jax.device_count()  # conftest mesh (8 default; CI sweeps 3)
TOL = dict(rtol=2e-4, atol=1e-3)


def _np_op(kind):
    return getattr(np.fft, kind)


@pytest.mark.parametrize("kind", ["fft", "ifft", "rfft", "ihfft"])
@pytest.mark.parametrize("n", [None, 24, 40])  # shrink and grow vs 32
def test_pencil_forward_kinds_split0(kind, n):
    rng = np.random.default_rng(5)
    x = rng.standard_normal((32, 2 * P)).astype(np.float32)
    if kind in ("fft", "ifft"):
        a = ht.array(x, split=0)
        got = getattr(ht.fft, kind)(a, n=n, axis=0)
        assert got._planar is not None and got.split == 0
        np.testing.assert_allclose(got.numpy(), _np_op(kind)(x, n=n, axis=0), **TOL)
    else:
        a = ht.array(x, split=0)
        got = getattr(ht.fft, kind)(a, n=n, axis=0)
        assert got._planar is not None and got.split == 0
        np.testing.assert_allclose(got.numpy(), _np_op(kind)(x, n=n, axis=0), **TOL)


@pytest.mark.parametrize("kind", ["irfft", "hfft"])
@pytest.mark.parametrize("n", [None, 30, 50])
def test_pencil_real_output_kinds_split0(kind, n):
    rng = np.random.default_rng(7)
    z = (rng.standard_normal((17, 2 * P)) + 1j * rng.standard_normal((17, 2 * P))).astype(
        np.complex64
    )
    a = ht.fft.fft(ht.array(z.real.astype(np.float32), split=0), axis=1)  # planar source
    # overwrite with a controlled Hermitian-half input: build from z via planes
    a = ht.array(z, split=0)
    got = getattr(ht.fft, kind)(a, n=n, axis=0)
    want = _np_op(kind)(z, n=n, axis=0)
    assert got.split == 0
    assert got._planar is None  # real output
    np.testing.assert_allclose(got.numpy(), want, **TOL)


def test_pencil_nondivisible_partner():
    """No axis divisible by the mesh: the partner is padded locally, not
    resharded through GSPMD (the r3 fallback this replaces)."""
    rng = np.random.default_rng(9)
    x = rng.standard_normal((3 * P, 13)).astype(np.float32)  # 13 % 8 != 0
    a = ht.array(x, split=0)
    got = ht.fft.fft(a, axis=0)
    assert got._planar is not None and got.split == 0
    np.testing.assert_allclose(got.numpy(), np.fft.fft(x, axis=0), **TOL)
    # rfft with the ragged partner and explicit n
    got2 = ht.fft.rfft(a, n=20, axis=0)
    np.testing.assert_allclose(got2.numpy(), np.fft.rfft(x, n=20, axis=0), **TOL)


def test_pencil_split1_and_rfftn():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((2 * P, 48)).astype(np.float32)
    a = ht.array(x, split=1)
    got = ht.fft.rfft(a, axis=1)
    assert got.split == 1
    np.testing.assert_allclose(got.numpy(), np.fft.rfft(x, axis=1), **TOL)
    # rfftn with the real axis ON the split: real pencil + local complex pass
    got2 = ht.fft.rfftn(ht.array(x, split=1))
    np.testing.assert_allclose(got2.numpy(), np.fft.rfftn(x), **TOL)
    # irfftn back
    back = ht.fft.irfftn(got2, s=x.shape)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=2e-3)


@pytest.mark.parametrize(
    "kind,have_im", [("fft", True), ("ifft", True), ("rfft", False),
                     ("ihfft", False), ("irfft", True), ("hfft", True)]
)
def test_pencil_hlo_no_allgather(kind, have_im):
    """The compiled pencil program for EVERY kind moves data only through
    all-to-alls (VERDICT r3 #4's done-bar)."""
    import jax

    comm = ht.get_comm()
    n_true = 32
    fn = fft_mod._pencil_planar_kind_fn(comm, kind, 0, 1, n_true, None, 2, None, have_im)
    shp = jax.ShapeDtypeStruct((comm.padded_extent(n_true), 2 * P), np.float32)
    args = (shp, shp) if have_im else (shp,)
    txt = fn.lower(*args).compile().as_text()
    assert "all-gather" not in txt, f"{kind} pencil gathered"
    assert "all-to-all" in txt


def test_fftn_split_axis_no_gather_end_to_end():
    rng = np.random.default_rng(13)
    x = rng.standard_normal((2 * P, 12, 10)).astype(np.float32)
    a = ht.array(x, split=0)
    got = ht.fft.fftn(a)
    assert got._planar is not None and got.split == 0
    np.testing.assert_allclose(got.numpy(), np.fft.fftn(x), rtol=1e-3, atol=5e-3)
    back = ht.fft.ifftn(got)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=2e-3)
